"""Shape / layout / indexing manipulation ops.

Parity surface: python/paddle/tensor/manipulation.py. All static-shape
transforms lower to XLA reshape/transpose/gather/scatter; the data-dependent
ones (masked_select, nonzero, unique) work eagerly and document their jit
constraints.
"""
from __future__ import annotations

import builtins

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from .creation import _t
from .dispatch import apply


def cast(x, dtype):
    from ..framework import dtype as dtypes

    npd = dtypes.canonicalize(dtype).np_dtype
    return apply("cast", lambda v: jnp.asarray(v, dtype=npd), _t(x))


def reshape(x, shape, name=None):
    if isinstance(shape, Tensor):
        shape = [int(s) for s in np.asarray(shape._value)]
    shape = tuple(int(s) if not isinstance(s, Tensor) else int(s.item()) for s in shape)
    return apply("reshape", lambda v: jnp.reshape(v, shape), _t(x))


def reshape_(x, shape, name=None):
    return x._adopt(reshape(x, shape))


def view(x, shape_or_dtype, name=None):
    return reshape(x, shape_or_dtype)


def transpose(x, perm, name=None):
    perm = [int(p) for p in perm]
    return apply("transpose", lambda v: jnp.transpose(v, perm), _t(x))


def moveaxis(x, source, destination, name=None):
    return apply("moveaxis", lambda v: jnp.moveaxis(v, source, destination), _t(x))


def swapaxes(x, axis0, axis1, name=None):
    return apply("swapaxes", lambda v: jnp.swapaxes(v, axis0, axis1), _t(x))


def concat(x, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    ts = [_t(e) for e in x]
    return apply("concat", lambda vs: jnp.concatenate(vs, axis=axis), ts)


def stack(x, axis=0, name=None):
    ts = [_t(e) for e in x]
    return apply("stack", lambda vs: jnp.stack(vs, axis=axis), ts)


def split(x, num_or_sections, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())

    def fn(v):
        if isinstance(num_or_sections, int):
            return tuple(jnp.split(v, num_or_sections, axis=axis))
        secs = [int(s) for s in num_or_sections]
        total = v.shape[axis]
        # paddle allows one -1 section
        neg = [i for i, s in enumerate(secs) if s == -1]
        if neg:
            known = sum(s for s in secs if s != -1)
            secs[neg[0]] = total - known
        points = np.cumsum(secs)[:-1].tolist()
        return tuple(jnp.split(v, points, axis=axis))

    return list(apply("split", fn, _t(x)))


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def unbind(x, axis=0, name=None):
    n = x.shape[axis]

    def fn(v):
        return tuple(jnp.squeeze(s, axis=axis) for s in jnp.split(v, n, axis=axis))

    return list(apply("unbind", fn, _t(x)))


def squeeze(x, axis=None, name=None):
    def fn(v):
        if axis is None:
            return jnp.squeeze(v)
        axes = axis if isinstance(axis, (list, tuple)) else [axis]
        axes = tuple(int(a) % v.ndim for a in axes if v.shape[int(a) % v.ndim] == 1)
        return jnp.squeeze(v, axis=axes) if axes else v

    return apply("squeeze", fn, _t(x))


def squeeze_(x, axis=None, name=None):
    return x._adopt(squeeze(x, axis))


def unsqueeze(x, axis, name=None):
    def fn(v):
        axes = axis if isinstance(axis, (list, tuple)) else [axis]
        out = v
        for a in sorted(int(ax) for ax in axes):
            out = jnp.expand_dims(out, a)
        return out

    return apply("unsqueeze", fn, _t(x))


def unsqueeze_(x, axis, name=None):
    return x._adopt(unsqueeze(x, axis))


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    def fn(v):
        nd = v.ndim
        s = start_axis % nd if nd else 0
        e = stop_axis % nd if nd else 0
        new_shape = v.shape[:s] + (-1,) + v.shape[e + 1:]
        return jnp.reshape(v, new_shape)

    return apply("flatten", fn, _t(x))


def flip(x, axis, name=None):
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    return apply("flip", lambda v: jnp.flip(v, axis=tuple(axes)), _t(x))


def rot90(x, k=1, axes=(0, 1), name=None):
    return apply("rot90", lambda v: jnp.rot90(v, k=k, axes=tuple(axes)), _t(x))


def roll(x, shifts, axis=None, name=None):
    return apply("roll", lambda v: jnp.roll(v, shifts, axis=axis), _t(x))


def tile(x, repeat_times, name=None):
    if isinstance(repeat_times, Tensor):
        repeat_times = [int(r) for r in np.asarray(repeat_times._value)]
    return apply("tile", lambda v: jnp.tile(v, tuple(repeat_times)), _t(x))


def expand(x, shape, name=None):
    if isinstance(shape, Tensor):
        shape = [int(s) for s in np.asarray(shape._value)]

    def fn(v):
        tgt = list(shape)
        # -1 keeps the source dim (paddle semantics)
        vshape = (1,) * (len(tgt) - v.ndim) + tuple(v.shape)
        tgt = [vs if t == -1 else t for t, vs in zip(tgt, vshape)]
        return jnp.broadcast_to(v.reshape(vshape), tuple(tgt))

    return apply("expand", fn, _t(x))


def broadcast_to(x, shape, name=None):
    return expand(x, shape)


def expand_as(x, y, name=None):
    return expand(x, y.shape)


def broadcast_tensors(inputs, name=None):
    ts = [_t(e) for e in inputs]
    return list(apply("broadcast_tensors", lambda vs: tuple(jnp.broadcast_arrays(*vs)), ts))


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def gather(x, index, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())

    def fn(v, idx):
        return jnp.take(v, idx.reshape(-1) if idx.ndim > 1 else idx, axis=int(axis))

    return apply("gather", fn, _t(x), _t(index))


def gather_nd(x, index, name=None):
    def fn(v, idx):
        if idx.shape[-1] == 0:
            return jnp.broadcast_to(v, idx.shape[:-1] + v.shape)
        comps = tuple(jnp.moveaxis(idx, -1, 0))
        return v[comps]

    return apply("gather_nd", fn, _t(x), _t(index))


def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    return apply(
        "take_along_axis",
        lambda v, idx: jnp.take_along_axis(v, idx, axis=axis),
        _t(arr), _t(indices),
    )


def put_along_axis(arr, indices, values, axis, reduce="assign", include_self=True,
                   broadcast=True, name=None):
    def fn(v, idx, val):
        val = jnp.broadcast_to(jnp.asarray(val, v.dtype), idx.shape)
        if reduce == "assign":
            return _scatter_along_axis(v, idx, val, axis, "set")
        if reduce in ("add", "sum"):
            return _scatter_along_axis(v, idx, val, axis, "add")
        if reduce in ("mul", "multiply"):
            return _scatter_along_axis(v, idx, val, axis, "mul")
        raise ValueError(f"unsupported reduce: {reduce}")

    vals = values if isinstance(values, Tensor) else jnp.asarray(values)
    return apply("put_along_axis", fn, _t(arr), _t(indices), vals)


def _scatter_along_axis(v, idx, val, axis, mode):
    axis = axis % v.ndim
    idx_full = [jnp.arange(s).reshape([-1 if i == d else 1 for i in range(idx.ndim)])
                for d, s in enumerate(idx.shape)]
    idx_full[axis] = idx
    loc = tuple(jnp.broadcast_arrays(*idx_full))
    ref = v.at[loc]
    return {"set": ref.set, "add": ref.add, "mul": ref.multiply}[mode](val)


def scatter(x, index, updates, overwrite=True, name=None):
    def fn(v, idx, upd):
        idx = idx.reshape(-1)
        if overwrite:
            return v.at[idx].set(upd)
        base = v.at[idx].set(jnp.zeros_like(upd))
        return base.at[idx].add(upd)

    return apply("scatter", fn, _t(x), _t(index), _t(updates))


def scatter_(x, index, updates, overwrite=True, name=None):
    return x._adopt(scatter(x, index, updates, overwrite))


def scatter_nd_add(x, index, updates, name=None):
    def fn(v, idx, upd):
        comps = tuple(jnp.moveaxis(idx, -1, 0))
        return v.at[comps].add(upd)

    return apply("scatter_nd_add", fn, _t(x), _t(index), _t(updates))


def scatter_nd(index, updates, shape, name=None):
    from .creation import zeros

    zero = zeros(shape, dtype=updates.dtype)
    return scatter_nd_add(zero, index, updates)


def index_select(x, index, axis=0, name=None):
    return apply("index_select", lambda v, idx: jnp.take(v, idx, axis=axis), _t(x), _t(index))


def index_sample(x, index):
    return apply(
        "index_sample", lambda v, idx: jnp.take_along_axis(v, idx, axis=1), _t(x), _t(index)
    )


def index_add(x, index, axis, value, name=None):
    def fn(v, idx, val):
        sl = [slice(None)] * v.ndim
        perm_axis = axis % v.ndim
        moved = jnp.moveaxis(v, perm_axis, 0)
        movedv = jnp.moveaxis(val, perm_axis, 0)
        out = moved.at[idx].add(movedv)
        return jnp.moveaxis(out, 0, perm_axis)

    return apply("index_add", fn, _t(x), _t(index), _t(value))


def index_put(x, indices, value, accumulate=False, name=None):
    idx_ts = [_t(i) for i in indices]

    def fn(v, idxs, val):
        key = tuple(idxs)
        return v.at[key].add(val) if accumulate else v.at[key].set(val)

    return apply("index_put", fn, _t(x), idx_ts, _t(value))


def masked_select(x, mask, name=None):
    # data-dependent output shape: eager-only (under jit use where/gather)
    def fn(v, m):
        return v[m]

    return apply("masked_select", fn, _t(x), _t(mask))


def masked_fill(x, mask, value, name=None):
    v = value if isinstance(value, Tensor) else jnp.asarray(value)
    return apply("masked_fill", lambda a, m, val: jnp.where(m, jnp.asarray(val, a.dtype), a),
                 _t(x), _t(mask), v)


def masked_fill_(x, mask, value, name=None):
    return x._adopt(masked_fill(x, mask, value))


def masked_scatter(x, mask, value, name=None):
    def fn(v, m, val):
        flat_m = m.reshape(-1)
        cnt = jnp.cumsum(flat_m) - 1
        src = val.reshape(-1)[jnp.clip(cnt, 0, val.size - 1)]
        return jnp.where(flat_m, src, v.reshape(-1)).reshape(v.shape)

    return apply("masked_scatter", fn, _t(x), _t(mask), _t(value))


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        from .search import nonzero

        return nonzero(condition, as_tuple=True)
    xt = x if isinstance(x, Tensor) else jnp.asarray(x)
    yt = y if isinstance(y, Tensor) else jnp.asarray(y)
    return apply("where", lambda c, a, b: jnp.where(c, a, b), _t(condition), xt, yt)


def slice(x, axes, starts, ends):  # noqa: A001
    def fn(v):
        idx = [builtins_slice(None)] * v.ndim
        for ax, st, en in zip(axes, starts, ends):
            st = int(st.item()) if isinstance(st, Tensor) else int(st)
            en = int(en.item()) if isinstance(en, Tensor) else int(en)
            idx[ax] = builtins_slice(st, en)
        return v[tuple(idx)]

    return apply("slice", fn, _t(x))


builtins_slice = builtins.slice


def strided_slice(x, axes, starts, ends, strides, name=None):
    def fn(v):
        idx = [builtins_slice(None)] * v.ndim
        for ax, st, en, sd in zip(axes, starts, ends, strides):
            idx[ax] = builtins_slice(int(st), int(en), int(sd))
        return v[tuple(idx)]

    return apply("strided_slice", fn, _t(x))


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):  # noqa: A002
    if isinstance(pad, Tensor):
        pad = [int(p) for p in np.asarray(pad._value)]
    pad = [int(p) for p in pad]

    def fn(v):
        nd = v.ndim
        if len(pad) == 2 * nd:
            width = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
        else:
            # paddle nn.functional.pad convention: pad applies to last dims,
            # ordered (last_dim_lo, last_dim_hi, second_last_lo, ...)
            npairs = len(pad) // 2
            width = [(0, 0)] * nd
            if data_format in ("NCHW", "NCL", "NCDHW") and npairs == nd - 2:
                # spatial dims only, reversed pair order
                for i in range(npairs):
                    dim = nd - 1 - i
                    width[dim] = (pad[2 * i], pad[2 * i + 1])
            elif data_format in ("NHWC", "NLC", "NDHWC") and npairs == nd - 2:
                for i in range(npairs):
                    dim = nd - 2 - i
                    width[dim] = (pad[2 * i], pad[2 * i + 1])
            else:
                for i in range(npairs):
                    dim = nd - 1 - i
                    width[dim] = (pad[2 * i], pad[2 * i + 1])
        jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge",
                 "circular": "wrap"}[mode]
        if jmode == "constant":
            return jnp.pad(v, width, mode="constant", constant_values=value)
        return jnp.pad(v, width, mode=jmode)

    return apply("pad", fn, _t(x))


def repeat_interleave(x, repeats, axis=None, name=None):
    def fn(v, *r):
        rep = r[0] if r else repeats
        return jnp.repeat(v, rep, axis=axis)

    if isinstance(repeats, Tensor):
        return apply("repeat_interleave", fn, _t(x), repeats)
    return apply("repeat_interleave", fn, _t(x))


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    # data-dependent shape: eager-only
    vals = np.unique(
        np.asarray(x._value), return_index=return_index,
        return_inverse=return_inverse, return_counts=return_counts, axis=axis,
    )
    if not isinstance(vals, tuple):
        return Tensor(jnp.asarray(vals))
    outs = [Tensor(jnp.asarray(v)) for v in vals]
    return tuple(outs)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None,
                       dtype="int64", name=None):
    arr = np.asarray(x._value)
    if axis is None:
        arr = arr.reshape(-1)
        change = np.concatenate([[True], arr[1:] != arr[:-1]])
    else:
        raise NotImplementedError("unique_consecutive with axis")
    vals = arr[change]
    outs = [Tensor(jnp.asarray(vals))]
    if return_inverse:
        inv = np.cumsum(change) - 1
        outs.append(Tensor(jnp.asarray(inv)))
    if return_counts:
        idx = np.nonzero(change)[0]
        counts = np.diff(np.concatenate([idx, [arr.size]]))
        outs.append(Tensor(jnp.asarray(counts)))
    return outs[0] if len(outs) == 1 else tuple(outs)


def as_real(x, name=None):
    return apply("as_real", lambda v: jnp.stack([jnp.real(v), jnp.imag(v)], -1), _t(x))


def as_complex(x, name=None):
    return apply("as_complex", lambda v: jax.lax.complex(v[..., 0], v[..., 1]), _t(x))


def crop(x, shape=None, offsets=None, name=None):
    def fn(v):
        offs = offsets or [0] * v.ndim
        shp = shape or v.shape
        idx = tuple(builtins_slice(int(o), int(o) + int(s)) for o, s in zip(offs, shp))
        return v[idx]

    return apply("crop", fn, _t(x))


def atleast_1d(*inputs, name=None):
    outs = [apply("atleast_1d", jnp.atleast_1d, _t(i)) for i in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*inputs, name=None):
    outs = [apply("atleast_2d", jnp.atleast_2d, _t(i)) for i in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*inputs, name=None):
    outs = [apply("atleast_3d", jnp.atleast_3d, _t(i)) for i in inputs]
    return outs[0] if len(outs) == 1 else outs


def tensor_split(x, num_or_indices, axis=0, name=None):
    def fn(v):
        return tuple(jnp.array_split(v, num_or_indices, axis=axis))

    return list(apply("tensor_split", fn, _t(x)))


def hsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=1 if x.ndim > 1 else 0)


def vsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=0)


def dsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=2)


def hstack(x, name=None):
    ts = [_t(e) for e in x]
    return apply("hstack", lambda vs: jnp.hstack(vs), ts)


def vstack(x, name=None):
    ts = [_t(e) for e in x]
    return apply("vstack", lambda vs: jnp.vstack(vs), ts)


def dstack(x, name=None):
    ts = [_t(e) for e in x]
    return apply("dstack", lambda vs: jnp.dstack(vs), ts)


def column_stack(x, name=None):
    ts = [_t(e) for e in x]
    return apply("column_stack", lambda vs: jnp.column_stack(vs), ts)


def row_stack(x, name=None):
    return vstack(x)


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):  # noqa: A002
    def fn(v):
        shard_size = (index_num + nshards - 1) // nshards
        lo = shard_id * shard_size
        in_shard = (v >= lo) & (v < lo + shard_size)
        return jnp.where(in_shard, v - lo, ignore_value)

    return apply("shard_index", fn, _t(input))
