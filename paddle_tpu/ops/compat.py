"""Top-level API long tail — ops completing paddle.* surface parity.

Parity targets (reference python/paddle):
  tensor/math.py       — take:6830, combinations:8117, isin:8476,
                         cartesian_prod:8666, sgn:6770, positive:5636,
                         signbit:8188
  tensor/manipulation.py — unflatten:6997, diagonal_scatter:7375,
                         select_scatter:7431, slice_scatter:7539,
                         block_diag:7651
  tensor/linalg.py     — matrix_transpose:191, vecdot:1880,
                         histogram_bin_edges:2610, histogramdd:5448
  tensor/random.py     — standard_gamma:295
  tensor/math.py gammainc/gammaincc — regularized incomplete gamma
"""
from __future__ import annotations

import itertools
import math as _math

import jax
import jax.numpy as jnp
import numpy as np

from .creation import _t
from .dispatch import apply

__all__ = [
    "add_n", "take", "isin", "combinations", "cartesian_prod", "block_diag",
    "unflatten", "select_scatter", "slice_scatter", "diagonal_scatter",
    "vecdot", "matrix_transpose", "histogram_bin_edges", "histogramdd",
    "standard_gamma", "sgn", "positive", "signbit", "less",
    "bitwise_invert", "gammainc", "gammaincc", "reverse", "rank", "shape",
    "tolist", "view_as", "pi", "e", "inf", "nan", "newaxis",
]

# numeric constants (reference: paddle.pi etc. — python/paddle/__init__.py)
pi = _math.pi
e = _math.e
inf = float("inf")
nan = float("nan")
newaxis = None


def add_n(inputs, name=None):
    """parity: paddle.add_n (ops.yaml add_n) — elementwise sum of a list of
    same-shaped tensors."""
    if not isinstance(inputs, (list, tuple)):
        inputs = [inputs]
    ts = [_t(v) for v in inputs]

    def fn(*vs):
        out = vs[0]
        for v in vs[1:]:
            out = out + v
        return out

    return apply("add_n", fn, *ts)


def take(x, index, mode="raise", name=None):
    """Flat-view gather; mode governs out-of-range indices."""
    if mode not in ("raise", "wrap", "clip"):
        raise ValueError(f"take: unknown mode {mode!r}")
    t, idx = _t(x), _t(index)
    n = 1
    for s in t.shape:
        n *= s
    if mode == "raise":
        iv = np.asarray(idx._value)
        if iv.size and (iv.min() < -n or iv.max() >= n):
            raise IndexError(
                f"take(mode='raise'): index out of range for input with "
                f"{n} elements")

    def fn(v, i):
        flat = v.reshape(-1)
        if mode == "wrap":
            i = jnp.mod(i, n)
        elif mode == "clip":
            i = jnp.clip(i, 0, n - 1)
        else:
            i = jnp.where(i < 0, i + n, i)
        return flat[i]

    return apply("take", fn, t, idx)


def isin(x, test_x, assume_unique=False, invert=False, name=None):
    """Membership test against the flattened test set."""
    def fn(v, t):
        hit = jnp.any(v[..., None] == t.reshape(-1), axis=-1)
        return ~hit if invert else hit

    return apply("isin", fn, _t(x), _t(test_x))


def combinations(x, r=2, with_replacement=False, name=None):
    """itertools.combinations(/with_replacement) over a 1-D tensor."""
    t = _t(x)
    if t.ndim != 1:
        raise ValueError("combinations: x must be 1-D")
    n = t.shape[0]
    gen = (itertools.combinations_with_replacement if with_replacement
           else itertools.combinations)
    idx = np.asarray(list(gen(range(n), int(r))), np.int32).reshape(
        -1, int(r))
    return apply("combinations", lambda v: v[jnp.asarray(idx)], t)


def cartesian_prod(x, name=None):
    """Cartesian product of 1-D tensors → [prod(n_i), len(x)]."""
    ts = [_t(v) for v in x]

    def fn(*vs):
        grids = jnp.meshgrid(*vs, indexing="ij")
        return jnp.stack([g.reshape(-1) for g in grids], axis=-1)

    out = apply("cartesian_prod", fn, *ts)
    return out


def block_diag(inputs, name=None):
    """Stack 2-D (or promotable) tensors along the diagonal."""
    ts = [_t(v) for v in inputs]

    def fn(*vs):
        vs = [v.reshape(1, -1) if v.ndim < 2 else v for v in vs]
        R = sum(v.shape[0] for v in vs)
        C = sum(v.shape[1] for v in vs)
        out = jnp.zeros((R, C), vs[0].dtype)
        r = c = 0
        for v in vs:
            out = jax.lax.dynamic_update_slice(out, v.astype(out.dtype),
                                               (r, c))
            r += v.shape[0]
            c += v.shape[1]
        return out

    return apply("block_diag", fn, *ts)


def unflatten(x, axis, shape, name=None):
    """Split one axis into the given shape (one -1 inferred)."""
    t = _t(x)
    ax = axis % t.ndim
    shape = [int(s) for s in shape]
    if shape.count(-1) > 1:
        raise ValueError("unflatten: only one -1 allowed in shape")
    if -1 in shape:
        known = 1
        for s in shape:
            if s != -1:
                known *= s
        shape[shape.index(-1)] = t.shape[ax] // known
    new_shape = tuple(t.shape[:ax]) + tuple(shape) + tuple(t.shape[ax + 1:])
    return apply("unflatten", lambda v: v.reshape(new_shape), t)


def select_scatter(x, values, axis, index, name=None):
    """Embed values at x[..., index, ...] on the given axis."""
    t = _t(x)
    ax = axis % t.ndim
    idx = tuple(slice(None) if i != ax else int(index)
                for i in range(t.ndim))
    return apply("select_scatter",
                 lambda v, val: v.at[idx].set(val.astype(v.dtype)),
                 t, _t(values))


def slice_scatter(x, value, axes, starts, ends, strides, name=None):
    """Embed value into a strided slice of x."""
    t = _t(x)
    sl = [slice(None)] * t.ndim
    for ax, st, en, sr in zip(axes, starts, ends, strides):
        sl[ax % t.ndim] = slice(int(st), int(en), int(sr))
    sl = tuple(sl)
    return apply("slice_scatter",
                 lambda v, val: v.at[sl].set(
                     jnp.broadcast_to(val, v[sl].shape).astype(v.dtype)),
                 t, _t(value))


def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1, name=None):
    """Embed y along the (offset) diagonal of axes (axis1, axis2)."""
    t = _t(x)
    a1, a2 = axis1 % t.ndim, axis2 % t.ndim
    n, m = t.shape[a1], t.shape[a2]
    if offset >= 0:
        L = min(n, m - offset)
        ri = jnp.arange(L)
        ci = jnp.arange(L) + offset
    else:
        L = min(n + offset, m)
        ri = jnp.arange(L) - offset
        ci = jnp.arange(L)

    def fn(v, dv):
        # move diag axes to front, scatter, move back
        perm = [a1, a2] + [i for i in range(v.ndim) if i not in (a1, a2)]
        inv = np.argsort(perm)
        vp = jnp.transpose(v, perm)
        # paddle.diagonal puts the diagonal LAST: dv shape [..., L]
        dvp = jnp.moveaxis(dv, -1, 0) if dv.ndim > 1 else dv
        vp = vp.at[ri, ci].set(dvp.astype(v.dtype))
        return jnp.transpose(vp, inv)

    return apply("diagonal_scatter", fn, t, _t(y))


def vecdot(x, y, axis=-1, name=None):
    """Dot product along an axis (conjugating x for complex)."""
    def fn(a, b):
        a = jnp.conj(a) if jnp.iscomplexobj(a) else a
        return jnp.sum(a * b, axis=axis)

    return apply("vecdot", fn, _t(x), _t(y))


def matrix_transpose(x, name=None):
    return apply("matrix_transpose", lambda v: jnp.swapaxes(v, -2, -1),
                 _t(x))


def histogram_bin_edges(input, bins=100, min=0, max=0, name=None):
    t = _t(input)
    lo, hi = float(min), float(max)
    if lo == 0 and hi == 0:
        v = np.asarray(t._value)
        lo, hi = float(v.min()), float(v.max())
        if lo == hi:
            lo, hi = lo - 0.5, hi + 0.5
    edges = jnp.linspace(lo, hi, int(bins) + 1, dtype=jnp.float32)
    from ..core.tensor import Tensor
    return Tensor(edges)


def histogramdd(x, bins=10, ranges=None, density=False, weights=None,
                name=None):
    """N-D histogram → (hist, list of bin-edge tensors)."""
    t = _t(x)
    w = _t(weights)._value if weights is not None else None
    if isinstance(bins, (list, tuple)) and len(bins) and \
            not isinstance(bins[0], int):
        bins = [np.asarray(_t(b)._value) for b in bins]
    hist, edges = jnp.histogramdd(t._value, bins=bins, range=ranges,
                                  weights=w, density=density)
    from ..core.tensor import Tensor
    return Tensor(hist), [Tensor(ed) for ed in edges]


def standard_gamma(x, name=None):
    """Sample Gamma(alpha=x, scale=1) elementwise (differentiable in
    alpha via JAX's implicit reparameterization)."""
    from ..framework.random import next_key

    key = next_key()
    return apply("standard_gamma",
                 lambda a: jax.random.gamma(key, a.astype(jnp.float32)
                                            ).astype(a.dtype)
                 if jnp.issubdtype(a.dtype, jnp.floating)
                 else jax.random.gamma(key, a.astype(jnp.float32)),
                 _t(x))


def sgn(x, name=None):
    """sign for real; x/|x| (0 → 0) for complex."""
    def fn(v):
        if jnp.iscomplexobj(v):
            mag = jnp.abs(v)
            return jnp.where(mag == 0, 0, v / jnp.where(mag == 0, 1, mag))
        return jnp.sign(v)

    return apply("sgn", fn, _t(x))


def positive(x, name=None):
    t = _t(x)
    if t.dtype == jnp.bool_:
        raise TypeError("positive: bool input not supported")
    return apply("positive", lambda v: +v, t)


def signbit(x, name=None):
    return apply("signbit", lambda v: jnp.signbit(
        v.astype(jnp.float32) if jnp.issubdtype(v.dtype, jnp.integer)
        else v), _t(x))


def less(x, y, name=None):
    """Alias of less_than (reference: paddle.less)."""
    from .logic import less_than
    return less_than(x, y)


def bitwise_invert(x, name=None):
    """Alias of bitwise_not (reference: paddle.bitwise_invert)."""
    from .logic import bitwise_not
    return bitwise_not(x)


def gammainc(x, y, name=None):
    """Regularized lower incomplete gamma P(x, y)."""
    return apply("gammainc",
                 lambda a, b: jax.scipy.special.gammainc(a, b), _t(x), _t(y))


def gammaincc(x, y, name=None):
    """Regularized upper incomplete gamma Q(x, y)."""
    return apply("gammaincc",
                 lambda a, b: jax.scipy.special.gammaincc(a, b), _t(x), _t(y))


def reverse(x, axis, name=None):
    """Legacy alias of flip."""
    from .manipulation import flip
    return flip(x, axis)


def rank(input, name=None):
    from ..core.tensor import Tensor
    return Tensor(jnp.asarray(_t(input).ndim, jnp.int32))


def shape(input, name=None):
    from ..core.tensor import Tensor
    return Tensor(jnp.asarray(_t(input).shape, jnp.int32))


def tolist(x, name=None):
    return np.asarray(_t(x)._value).tolist()


def view_as(x, other, name=None):
    from .manipulation import view
    return view(x, _t(other).shape)
