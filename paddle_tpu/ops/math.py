"""Elementwise & reduction math ops.

Parity surface: python/paddle/tensor/math.py (and ops.yaml entries, reference:
paddle/phi/ops/yaml/ops.yaml). Every op routes through dispatch.apply so
autograd records a node; kernels are jax.numpy/lax and fuse in XLA.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..framework import dtype as dtypes
from .creation import _t, to_tensor
from .dispatch import apply


def _unary(opname, jfn):
    def op(x, name=None):
        return apply(opname, jfn, _t(x))

    op.__name__ = opname
    return op


def _binary(opname, jfn):
    def op(x, y, name=None):
        xt = x if isinstance(x, Tensor) else None
        yt = y if isinstance(y, Tensor) else None
        if xt is None and yt is None:
            return Tensor(jfn(jnp.asarray(x), jnp.asarray(y)))
        a = xt if xt is not None else x
        b = yt if yt is not None else y
        return apply(opname, jfn, a, b)

    op.__name__ = opname
    return op


# -- unary -------------------------------------------------------------------
exp = _unary("exp", jnp.exp)
expm1 = _unary("expm1", jnp.expm1)
log = _unary("log", jnp.log)
log2 = _unary("log2", jnp.log2)
log10 = _unary("log10", jnp.log10)
log1p = _unary("log1p", jnp.log1p)
sqrt = _unary("sqrt", jnp.sqrt)
rsqrt = _unary("rsqrt", jax.lax.rsqrt)
square = _unary("square", jnp.square)
reciprocal = _unary("reciprocal", lambda v: 1.0 / v)
abs = _unary("abs", jnp.abs)  # noqa: A001
sign = _unary("sign", jnp.sign)
negative = _unary("negative", jnp.negative)
neg = negative
sin = _unary("sin", jnp.sin)
cos = _unary("cos", jnp.cos)
tan = _unary("tan", jnp.tan)
asin = _unary("asin", jnp.arcsin)
acos = _unary("acos", jnp.arccos)
atan = _unary("atan", jnp.arctan)
sinh = _unary("sinh", jnp.sinh)
cosh = _unary("cosh", jnp.cosh)
asinh = _unary("asinh", jnp.arcsinh)
acosh = _unary("acosh", jnp.arccosh)
atanh = _unary("atanh", jnp.arctanh)
tanh = _unary("tanh", jnp.tanh)
sigmoid = _unary("sigmoid", jax.nn.sigmoid)
erf = _unary("erf", jax.scipy.special.erf)
erfinv = _unary("erfinv", jax.scipy.special.erfinv)
floor = _unary("floor", jnp.floor)
ceil = _unary("ceil", jnp.ceil)
round = _unary("round", jnp.round)  # noqa: A001
trunc = _unary("trunc", jnp.trunc)
frac = _unary("frac", lambda v: v - jnp.trunc(v))
digamma = _unary("digamma", jax.scipy.special.digamma)
lgamma = _unary("lgamma", jax.scipy.special.gammaln)
gamma = _unary("gamma", lambda v: jnp.exp(jax.scipy.special.gammaln(v)) * jnp.sign(v) ** 0)
i0 = _unary("i0", jax.scipy.special.i0)
i0e = _unary("i0e", jax.scipy.special.i0e)
i1 = _unary("i1", jax.scipy.special.i1)
i1e = _unary("i1e", jax.scipy.special.i1e)
deg2rad = _unary("deg2rad", jnp.deg2rad)
rad2deg = _unary("rad2deg", jnp.rad2deg)
angle = _unary("angle", jnp.angle)
conj = _unary("conj", jnp.conj)
real = _unary("real", jnp.real)
imag = _unary("imag", jnp.imag)
softplus_raw = _unary("softplus", jax.nn.softplus)
logit = _unary("logit", jax.scipy.special.logit)


# -- binary ------------------------------------------------------------------
add = _binary("add", jnp.add)
subtract = _binary("subtract", jnp.subtract)
multiply = _binary("multiply", jnp.multiply)
divide = _binary("divide", jnp.true_divide)
floor_divide = _binary("floor_divide", jnp.floor_divide)
mod = _binary("mod", jnp.mod)
remainder = mod
floor_mod = mod
fmod = _binary("fmod", jnp.fmod)
pow = _binary("pow", jnp.power)  # noqa: A001
maximum = _binary("maximum", jnp.maximum)
minimum = _binary("minimum", jnp.minimum)
fmax = _binary("fmax", jnp.fmax)
fmin = _binary("fmin", jnp.fmin)
atan2 = _binary("atan2", jnp.arctan2)
heaviside = _binary("heaviside", jnp.heaviside)
gcd = _binary("gcd", jnp.gcd)
lcm = _binary("lcm", jnp.lcm)
hypot = _binary("hypot", jnp.hypot)
copysign = _binary("copysign", jnp.copysign)
nextafter = _binary("nextafter", jnp.nextafter)
logaddexp = _binary("logaddexp", jnp.logaddexp)
ldexp = _binary("ldexp", jnp.ldexp)
inner = _binary("inner", jnp.inner)
outer = _binary("outer", jnp.outer)
kron = _binary("kron", jnp.kron)


def divide_no_nan(x, y):
    return apply("divide_no_nan", lambda a, b: jnp.where(b == 0, 0.0, a / jnp.where(b == 0, 1.0, b)), _t(x), _t(y))


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    def fn(v, s):
        if bias_after_scale:
            return v * s + jnp.asarray(bias, _result_float(v))
        return (v + jnp.asarray(bias, _result_float(v))) * s

    s = scale if isinstance(scale, Tensor) else jnp.asarray(scale)
    out = apply("scale", fn, _t(x), s)
    if act is not None:
        from ..nn import functional as F

        out = getattr(F, act)(out)
    return out


def _result_float(v):
    d = np.dtype(v.dtype)
    return d if dtypes.np_is_floating(d) else np.float32


def increment(x, value=1.0, name=None):
    out = apply("increment", lambda v: v + jnp.asarray(value, v.dtype), x)
    x._adopt(out)
    return x


def clip(x, min=None, max=None, name=None):  # noqa: A002
    def fn(v, *mm):
        lo = mm[0] if isinstance(min, Tensor) else min
        hi_idx = 1 if isinstance(min, Tensor) else 0
        hi = mm[hi_idx] if isinstance(max, Tensor) else max
        return jnp.clip(v, lo, hi)

    extra = [m for m in (min, max) if isinstance(m, Tensor)]
    return apply("clip", fn, _t(x), *extra)


def lerp(x, y, weight, name=None):
    if isinstance(weight, Tensor):
        return apply("lerp", lambda a, b, w: a + w * (b - a), _t(x), _t(y), weight)
    return apply("lerp", lambda a, b: a + weight * (b - a), _t(x), _t(y))


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return apply(
        "nan_to_num",
        lambda v: jnp.nan_to_num(v, nan=nan, posinf=posinf, neginf=neginf),
        _t(x),
    )


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return apply("stanh", lambda v: scale_b * jnp.tanh(scale_a * v), _t(x))


def multiplex(inputs, index, name=None):
    return apply(
        "multiplex",
        lambda vs, idx: jnp.stack(vs, 0)[idx.reshape(-1), jnp.arange(vs[0].shape[0])],
        [_t(i) for i in inputs], _t(index),
    )


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):  # noqa: A002
    return apply(
        "addmm", lambda i, a, b: beta * i + alpha * (a @ b), _t(input), _t(x), _t(y)
    )


# -- reductions ---------------------------------------------------------------
def _axes(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        axis = axis.tolist()
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def sum(x, axis=None, dtype=None, keepdim=False, name=None):  # noqa: A001
    d = dtypes.canonicalize(dtype).np_dtype if dtype else None

    def fn(v):
        dd = d
        if dd is None and np.issubdtype(np.dtype(v.dtype), np.bool_):
            dd = dtypes.index_dtype()
        return jnp.sum(v, axis=_axes(axis), keepdims=keepdim, dtype=dd)

    return apply("sum", fn, _t(x))


def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    return apply("nansum", lambda v: jnp.nansum(v, axis=_axes(axis), keepdims=keepdim), _t(x))


def mean(x, axis=None, keepdim=False, name=None):
    return apply("mean", lambda v: jnp.mean(v, axis=_axes(axis), keepdims=keepdim), _t(x))


def nanmean(x, axis=None, keepdim=False, name=None):
    return apply("nanmean", lambda v: jnp.nanmean(v, axis=_axes(axis), keepdims=keepdim), _t(x))


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    return apply("prod", lambda v: jnp.prod(v, axis=_axes(axis), keepdims=keepdim), _t(x))


def max(x, axis=None, keepdim=False, name=None):  # noqa: A001
    return apply("max", lambda v: jnp.max(v, axis=_axes(axis), keepdims=keepdim), _t(x))


def min(x, axis=None, keepdim=False, name=None):  # noqa: A001
    return apply("min", lambda v: jnp.min(v, axis=_axes(axis), keepdims=keepdim), _t(x))


amax = max
amin = min


def logsumexp(x, axis=None, keepdim=False, name=None):
    return apply(
        "logsumexp",
        lambda v: jax.scipy.special.logsumexp(v, axis=_axes(axis), keepdims=keepdim),
        _t(x),
    )


def cumsum(x, axis=None, dtype=None, name=None):
    def fn(v):
        if axis is None:
            return jnp.cumsum(v.reshape(-1))
        return jnp.cumsum(v, axis=int(axis))

    return apply("cumsum", fn, _t(x))


def cumprod(x, dim=None, dtype=None, name=None):
    def fn(v):
        if dim is None:
            return jnp.cumprod(v.reshape(-1))
        return jnp.cumprod(v, axis=int(dim))

    return apply("cumprod", fn, _t(x))


def cummax(x, axis=None, dtype="int64", name=None):
    def full_fn(v):
        ax = 0 if axis is None else int(axis)
        vv = v.reshape(-1) if axis is None else v
        vals = jax.lax.associative_scan(jnp.maximum, vv, axis=ax)
        n = vv.shape[ax]
        ar = jnp.arange(n).reshape([-1 if i == ax % vv.ndim else 1 for i in range(vv.ndim)])
        eq = vv == vals
        idx = jax.lax.associative_scan(jnp.maximum, jnp.where(eq, ar, -1), axis=ax)
        return vals, idx.astype(dtypes.index_dtype())

    vals, idx = apply("cummax", full_fn, _t(x))
    return vals, idx


def cummin(x, axis=None, dtype="int64", name=None):
    def full_fn(v):
        ax = 0 if axis is None else int(axis)
        vv = v.reshape(-1) if axis is None else v
        vals = jax.lax.associative_scan(jnp.minimum, vv, axis=ax)
        n = vv.shape[ax]
        ar = jnp.arange(n).reshape([-1 if i == ax % vv.ndim else 1 for i in range(vv.ndim)])
        eq = vv == vals
        idx = jax.lax.associative_scan(jnp.maximum, jnp.where(eq, ar, -1), axis=ax)
        return vals, idx.astype(dtypes.index_dtype())

    vals, idx = apply("cummin", full_fn, _t(x))
    return vals, idx


def logcumsumexp(x, axis=None, name=None):
    def fn(v):
        vv = v.reshape(-1) if axis is None else v
        ax = 0 if axis is None else int(axis)
        return jax.lax.associative_scan(jnp.logaddexp, vv, axis=ax)

    return apply("logcumsumexp", fn, _t(x))


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply(
        "trace", lambda v: jnp.trace(v, offset=offset, axis1=axis1, axis2=axis2), _t(x)
    )


def all(x, axis=None, keepdim=False, name=None):  # noqa: A001
    return apply("all", lambda v: jnp.all(v, axis=_axes(axis), keepdims=keepdim), _t(x))


def any(x, axis=None, keepdim=False, name=None):  # noqa: A001
    return apply("any", lambda v: jnp.any(v, axis=_axes(axis), keepdims=keepdim), _t(x))


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return apply(
        "count_nonzero",
        lambda v: jnp.count_nonzero(v, axis=_axes(axis), keepdims=keepdim).astype(dtypes.index_dtype()),
        _t(x),
    )
