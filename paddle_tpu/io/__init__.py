"""paddle_tpu.io — datasets & data loading.

Parity: python/paddle/io/ (DataLoader — reader.py:262; samplers, Dataset /
IterableDataset / TensorDataset; multiprocess workers in dataloader/worker.py).
On TPU the loader is host-side; worker parallelism uses threads feeding a
prefetch queue (the device never blocks on Python), which plays the role of
the reference's shared-memory worker transport.
"""
from __future__ import annotations

import itertools
import queue
import threading
import time
from typing import Iterable, List, Optional

import numpy as np

from ..core.tensor import Tensor
from ..observability import goodput as _goodput
from ..observability.catalog import instrument as _instrument

_M_BATCHES = _instrument("dataloader_batches_total")
_M_BATCH_WAIT = _instrument("dataloader_batch_wait_seconds")

__all__ = [
    "Dataset", "IterableDataset", "TensorDataset", "ArrayDataset", "ComposeDataset",
    "ChainDataset", "Subset", "ConcatDataset", "random_split",
    "Sampler", "SequenceSampler", "RandomSampler", "WeightedRandomSampler",
    "BatchSampler", "DistributedBatchSampler", "DataLoader", "default_collate_fn",
    "get_worker_info",
]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset has no __getitem__")

    def __len__(self):
        raise RuntimeError("IterableDataset has no __len__")


class TensorDataset(Dataset):
    def __init__(self, tensors: List[Tensor]):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ArrayDataset(Dataset):
    """Contiguous numpy-backed map-style dataset with NATIVE batch collation:
    DataLoader gathers whole batches through the C++ runtime
    (csrc/ptpu_runtime.cpp ptpu_gather_rows — parallel row memcpy outside the
    GIL), playing the role of the reference's C++ DataFeed/shared-memory
    worker transport (fluid/framework/data_feed.h:1144,
    io/dataloader/worker.py)."""

    def __init__(self, *arrays):
        assert arrays and all(len(a) == len(arrays[0]) for a in arrays)
        self.arrays = [np.ascontiguousarray(a) for a in arrays]

    def __getitem__(self, idx):
        out = tuple(a[idx] for a in self.arrays)
        return out if len(out) > 1 else out[0]

    def __len__(self):
        return len(self.arrays[0])


def _native_gather(arr: np.ndarray, indices, nthreads: int = 4) -> np.ndarray:
    """Batch-gather rows via the native runtime; numpy fallback."""
    import ctypes

    idx = np.ascontiguousarray(indices, np.int64)
    out = np.empty((len(idx),) + arr.shape[1:], arr.dtype)
    try:
        from ..lib import native_lib
        lib = native_lib()
    except RuntimeError:
        np.take(arr, idx, axis=0, out=out)
        return out
    row_bytes = int(arr.dtype.itemsize * np.prod(arr.shape[1:], dtype=np.int64))
    lib.ptpu_gather_rows(
        arr.ctypes.data_as(ctypes.c_char_p),
        idx.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
        len(idx), row_bytes,
        out.ctypes.data_as(ctypes.c_char_p), nthreads)
    return out


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = datasets

    def __getitem__(self, idx):
        out = []
        for ds in self.datasets:
            item = ds[idx]
            out.extend(item if isinstance(item, (list, tuple)) else [item])
        return tuple(out)

    def __len__(self):
        return min(len(d) for d in self.datasets)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = datasets

    def __iter__(self):
        return itertools.chain(*self.datasets)


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cum = np.cumsum([len(d) for d in self.datasets]).tolist()

    def __len__(self):
        return self.cum[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        di = int(np.searchsorted(self.cum, idx, side="right"))
        prev = 0 if di == 0 else self.cum[di - 1]
        return self.datasets[di][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    total = len(dataset)
    lengths = list(lengths)
    if all(isinstance(l, float) for l in lengths):
        lengths = [int(total * l) for l in lengths]
        lengths[-1] = total - sum(lengths[:-1])
    if sum(lengths) != total:
        raise ValueError("sum of lengths must equal dataset size")
    perm = np.random.permutation(total).tolist()
    out, off = [], 0
    for n in lengths:
        out.append(Subset(dataset, perm[off:off + n]))
        off += n
    return out


# -- samplers -----------------------------------------------------------------
class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))

    def __len__(self):
        return len(self.data_source)


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[: self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, dtype=np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        return iter(np.random.choice(len(self.weights), self.num_samples,
                                     replace=self.replacement, p=p).tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1,
                 drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Shards the index space across data-parallel ranks (parity:
    python/paddle/io/dataloader/batch_sampler.py DistributedBatchSampler)."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        if num_replicas is None or rank is None:
            from ..distributed import get_rank, get_world_size

            num_replicas = num_replicas or get_world_size()
            rank = rank if rank is not None else get_rank()
        self.nranks = num_replicas
        self.local_rank = rank
        self.epoch = 0
        self.num_samples = int(np.ceil(len(dataset) / num_replicas))
        self.total_size = self.num_samples * num_replicas

    def set_epoch(self, epoch):
        self.epoch = epoch

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            indices = rng.permutation(n).tolist()
        else:
            indices = list(range(n))
        indices += indices[: self.total_size - n]
        indices = indices[self.local_rank:self.total_size:self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size


# -- collate ------------------------------------------------------------------
def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, Tensor):
        return Tensor(np.stack([np.asarray(s._value) for s in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, np.integer)):
        return Tensor(np.asarray(batch, dtype=np.int64))
    if isinstance(sample, (float, np.floating)):
        return Tensor(np.asarray(batch, dtype=np.float32))
    if isinstance(sample, (str, bytes)):
        return list(batch)
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    if isinstance(sample, (list, tuple)):
        return type(sample)(default_collate_fn(list(items))
                            for items in zip(*batch))
    return batch


class _WorkerInfo:
    def __init__(self, id_, num_workers, dataset):
        self.id = id_
        self.num_workers = num_workers
        self.dataset = dataset


_worker_info = threading.local()


def get_worker_info():
    return getattr(_worker_info, "info", None)


# -- loader -------------------------------------------------------------------
class DataLoader:
    """parity: python/paddle/io/reader.py:262 DataLoader.

    ``num_workers > 0`` spawns real worker PROCESSES with shared-memory
    batch transport (io/mp_loader.py — the analogue of the reference's
    dataloader/worker.py + shared-memory LoDTensor path); workers collate in
    numpy (GIL-free transforms, no forked TPU client) and the parent does
    the single host→device copy. ``in_order=False`` yields batches in
    arrival order instead of sampler order. ``worker_mode="thread"`` keeps
    the in-process prefetch pool (for transforms that must touch device
    tensors)."""

    _default_collate = staticmethod(default_collate_fn)

    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False, drop_last=False,
                 collate_fn=None, num_workers=0, use_buffer_reader=True,
                 prefetch_factor=2, use_shared_memory=True, timeout=0,
                 worker_init_fn=None, persistent_workers=False,
                 in_order=True, worker_mode="process"):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self.timeout = timeout
        self.worker_init_fn = worker_init_fn
        self.use_shared_memory = use_shared_memory
        self.persistent_workers = persistent_workers
        self.in_order = in_order
        if worker_mode not in ("process", "thread"):
            raise ValueError(
                f"worker_mode must be 'process' or 'thread', got "
                f"{worker_mode!r}")
        self.worker_mode = worker_mode
        self._pool = None
        # checkpointable position (distributed.resilience crash-resume):
        # counts batches yielded by the ACTIVE iterator; assumes one live
        # iterator at a time (the training-loop case)
        self._pos_epoch = 0
        self._pos_batch = 0
        self._resume_skip = 0
        # loader-vs-consumer utilization probe, refreshed per epoch:
        # wait_s = time the consumer blocked on the loader; busy_s = time
        # the consumer spent between batches (its own step time)
        self.last_epoch_stats = None
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        elif batch_size is None:
            self.batch_sampler = None
            self.batch_size = None
        else:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size,
                                              drop_last=drop_last)

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no len()")
        if self.batch_sampler is None:
            return len(self.dataset)
        return len(self.batch_sampler)

    def _fetch(self, indices):
        if (isinstance(self.dataset, ArrayDataset)
                and self.collate_fn is default_collate_fn):
            cols = tuple(Tensor(_native_gather(a, indices))
                         for a in self.dataset.arrays)
            return cols if len(cols) > 1 else cols[0]
        samples = [self.dataset[i] for i in indices]
        return self.collate_fn(samples)

    def _iter_sync(self, skip: int = 0):
        if self._iterable_mode:
            n = 0
            batch = []
            for item in self.dataset:
                batch.append(item)
                if len(batch) == self.batch_size:
                    n += 1
                    if n > skip:     # resume: re-stream, drop consumed
                        yield self.collate_fn(batch)
                    batch = []
            if batch and not self.drop_last and n + 1 > skip:
                yield self.collate_fn(batch)
            return
        if self.batch_sampler is None:
            for i in range(skip, len(self.dataset)):
                yield self.dataset[i]
            return
        # map-style resume skip is sampler-level: no sample is fetched for
        # the skipped batches
        for indices in itertools.islice(iter(self.batch_sampler), skip,
                                        None):
            yield self._fetch(indices)

    def _iter_threaded(self, skip: int = 0):
        """Prefetching thread pool: the stand-in for the reference's
        multiprocess worker + shared-memory transport (io/dataloader/worker.py)
        — on TPU hosts the goal is simply to keep the infeed ahead of step
        time."""
        q: "queue.Queue" = queue.Queue(self.prefetch_factor * self.num_workers)
        sentinel = object()
        idx_iter = itertools.islice(iter(self.batch_sampler), skip, None)
        lock = threading.Lock()
        exc = []

        def worker(wid):
            _worker_info.info = _WorkerInfo(wid, self.num_workers, self.dataset)
            if self.worker_init_fn is not None:
                self.worker_init_fn(wid)
            while True:
                with lock:
                    try:
                        indices = next(idx_iter)
                    except StopIteration:
                        break
                try:
                    q.put(self._fetch(indices))
                except Exception as e:  # propagate to consumer
                    exc.append(e)
                    break
            q.put(sentinel)

        threads = [threading.Thread(target=worker, args=(i,), daemon=True)
                   for i in range(self.num_workers)]
        for t in threads:
            t.start()
        done = 0
        while done < self.num_workers:
            item = q.get()
            if item is sentinel:
                done += 1
                continue
            yield item
        if exc:
            raise exc[0]

    def _iter_mp(self, skip: int = 0):
        from .mp_loader import WorkerPool

        pool = self._pool
        if pool is None or not pool.alive or pool.in_use:
            # a second live iterator over the same loader must not share
            # queues with the first (interleaved epochs would cross-deliver
            # batches) — it gets its own pool, torn down at exhaustion
            pool = WorkerPool(self)
            if self._pool is None or not self._pool.alive:
                self._pool = pool
        pool.in_use = True
        if self._iterable_mode:
            gen = pool.run_iterable_epoch(skip=skip)
        else:
            # resume skip happens before submission: skipped batches are
            # never fetched, collated, or shipped through shm
            gen = pool.run_map_epoch(
                itertools.islice(iter(self.batch_sampler), skip, None),
                self.in_order)
        clean = False
        try:
            for batch in gen:
                yield batch
            clean = True
        finally:
            gen.close()
            pool.in_use = False
            if not clean or not self.persistent_workers or pool is not self._pool:
                # an abandoned epoch leaves stale batches in the result
                # queue — a partially-consumed pool cannot be reused
                pool.shutdown()
                if pool is self._pool:
                    self._pool = None

    def _timed(self, gen):
        """Wrap an epoch iterator with the utilization probe."""
        wait_s = 0.0
        busy_s = 0.0
        n = 0
        try:
            while True:
                t0 = time.monotonic()
                try:
                    item = next(gen)
                except StopIteration:
                    # clean exhaustion: the epoch is over for position
                    # tracking (an abandoned iterator does NOT bump it)
                    self._pos_epoch += 1
                    self._pos_batch = 0
                    break
                t1 = time.monotonic()
                wait_s += t1 - t0
                n += 1
                self._pos_batch += 1
                _M_BATCH_WAIT.observe(t1 - t0)   # no-op unless obs enabled
                _M_BATCHES.inc()
                # consumer-blocked time is data_wait badput
                _goodput.account("data_wait", t1 - t0)
                yield item          # consumer runs while suspended here
                busy_s += time.monotonic() - t1
        finally:
            total = wait_s + busy_s
            self.last_epoch_stats = {
                "batches": n, "wait_s": wait_s, "busy_s": busy_s,
                "input_bound_frac": (wait_s / total) if total > 0 else 0.0,
            }

    def __iter__(self):
        skip = self._resume_skip
        self._resume_skip = 0
        self._pos_batch = skip
        if self.num_workers > 0:
            if self.worker_mode == "process" and (
                    self._iterable_mode or self.batch_sampler is not None):
                return self._timed(self._iter_mp(skip))
            if not self._iterable_mode and self.batch_sampler is not None:
                return self._timed(self._iter_threaded(skip))
        return self._timed(self._iter_sync(skip))

    # -- checkpointable position (distributed.resilience) -----------------
    def state_dict(self):
        """Loader position for exact crash-resume: epochs completed and
        batches yielded in the current epoch. Exact only for a
        deterministic sampler (``shuffle=False`` or epoch-seeded)."""
        return {"epoch": self._pos_epoch, "batch": self._pos_batch}

    def load_state_dict(self, sd) -> None:
        """Restore a :meth:`state_dict` position. The NEXT ``__iter__``
        fast-forwards ``sd['batch']`` batches — at the sampler level for
        map-style datasets (skipped batches are never fetched), by
        stream-and-discard for iterables."""
        self._pos_epoch = int(sd.get("epoch", 0))
        self._pos_batch = int(sd.get("batch", 0))
        self._resume_skip = self._pos_batch

    def __del__(self):
        pool = getattr(self, "_pool", None)
        if pool is not None:
            pool.shutdown()

    def __call__(self):
        return self.__iter__()


class SubsetRandomSampler(Sampler):
    """parity: io/sampler.py SubsetRandomSampler — random order over a fixed
    index subset."""

    def __init__(self, indices):
        self.indices = list(indices)
        if len(self.indices) == 0:
            raise ValueError(
                "SubsetRandomSampler: indices must not be empty")

    def __iter__(self):
        import numpy as _np

        order = _np.random.permutation(len(self.indices))
        return iter([self.indices[i] for i in order])

    def __len__(self):
        return len(self.indices)
