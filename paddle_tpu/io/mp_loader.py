"""Multiprocess DataLoader workers with shared-memory batch transport.

Parity: python/paddle/io/reader.py:262 (DataLoader num_workers>0),
python/paddle/io/dataloader/worker.py (_worker_loop: index queue in, data
queue out, worker_init_fn, error propagation) and the reference's
shared-memory LoDTensor transport (core._convert_to_shared_memory /
fluid/framework/data_feed shared-memory path).

TPU-native re-design: the device is fed by the HOST, so the worker contract
is numpy-only — forked workers never touch the JAX/TPU client (forking a
process with a live TPU client risks deadlock on copied XLA mutexes; the
child therefore does decode/augment/collate in numpy, which is also where
the GIL win lives). Batches travel as POSIX shared-memory segments
(multiprocessing.shared_memory): the worker writes the collated arrays,
passes (name, shape, dtype) through the result queue, and the PARENT does
the single host→HBM copy (Tensor() == jnp.asarray → device_put), so arrays
cross process boundaries without pickling and touch the device exactly once.

Reassembly is sequence-tagged: map-style epochs emit batches in sampler
order (a heap-free dict buffer keyed by seq), or in arrival order when
``in_order=False`` — the unordered mode trades determinism for zero
head-of-line blocking when per-batch transform cost is skewed.
"""
from __future__ import annotations

import multiprocessing
import os
import queue as _queue
import time
import traceback
from typing import Any, Callable, List, Optional

import numpy as np

from ..core.tensor import Tensor
from ..observability import state as _obs_state
from ..observability.catalog import instrument as _instrument

_M_RQ_DEPTH = _instrument("dataloader_result_queue_depth")

_SHM_MIN_BYTES = 1 << 14  # below 16 KiB the queue pickle is cheaper than shm


class _ShmRef:
    """Pickled placeholder for an array parked in shared memory."""

    __slots__ = ("name", "shape", "dtype", "as_tensor")

    def __init__(self, name, shape, dtype, as_tensor):
        self.name = name
        self.shape = shape
        self.dtype = dtype
        self.as_tensor = as_tensor


class _ArrLeaf:
    """Small array sent inline through the queue."""

    __slots__ = ("array", "as_tensor")

    def __init__(self, array, as_tensor):
        self.array = array
        self.as_tensor = as_tensor


def _numpy_collate(batch):
    """default_collate_fn with numpy leaves (workers must not build device
    Tensors); the parent converts tagged leaves into Tensors."""
    sample = batch[0]
    if isinstance(sample, Tensor):
        return np.stack([np.asarray(s._value) for s in batch])
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, dtype=np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, dtype=np.float32)
    if isinstance(sample, (str, bytes)):
        return list(batch)
    if isinstance(sample, dict):
        return {k: _numpy_collate([b[k] for b in batch]) for k in sample}
    if isinstance(sample, (list, tuple)):
        return type(sample)(_numpy_collate(list(items))
                            for items in zip(*batch))
    return batch


def _shm_export(a: np.ndarray, use_shm: bool, as_tensor: bool):
    a = np.ascontiguousarray(a)
    if not use_shm or a.nbytes < _SHM_MIN_BYTES:
        return _ArrLeaf(a, as_tensor)
    from multiprocessing import shared_memory
    from multiprocessing.resource_tracker import unregister

    shm = shared_memory.SharedMemory(create=True, size=a.nbytes)
    np.ndarray(a.shape, a.dtype, buffer=shm.buf)[...] = a
    ref = _ShmRef(shm.name, a.shape, str(a.dtype), as_tensor)
    # ownership transfers to the parent (it unlinks after the device copy);
    # without this the worker's resource tracker would destroy the segment
    # when the worker exits
    try:
        unregister(shm._name, "shared_memory")
    except Exception:
        pass
    shm.close()
    return ref


def _pack_tree(obj, use_shm: bool, default_collated: bool):
    if isinstance(obj, Tensor):
        # a custom collate_fn built a device Tensor inside a forked worker —
        # that touches the JAX client the parent already initialized (copied
        # XLA mutex state: deadlock risk). Enforce the numpy-only contract.
        raise RuntimeError(
            "custom collate_fn returned a Tensor inside a DataLoader worker "
            "process; process workers must stay numpy-only (return numpy "
            "arrays — the parent converts them), or use "
            "worker_mode='thread'")
    if isinstance(obj, np.ndarray):
        return _shm_export(obj, use_shm, as_tensor=default_collated)
    if isinstance(obj, dict):
        return {k: _pack_tree(v, use_shm, default_collated)
                for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_pack_tree(v, use_shm, default_collated)
                         for v in obj)
    return obj


def _unpack_tree(obj):
    if isinstance(obj, _ShmRef):
        from multiprocessing import shared_memory

        shm = shared_memory.SharedMemory(name=obj.name)
        try:
            arr = np.ndarray(obj.shape, np.dtype(obj.dtype), buffer=shm.buf)
            if obj.as_tensor:
                import jax

                if jax.default_backend() == "cpu":
                    # CPU jnp.asarray is zero-copy: the device array would
                    # alias the segment we are about to unlink
                    out = Tensor(arr.copy())
                else:
                    # single host→HBM copy; block so the (possibly async)
                    # transfer finishes before the segment is unlinked
                    out = Tensor(arr)
                    out._value.block_until_ready()
            else:
                out = arr.copy()
        finally:
            shm.close()
            try:
                shm.unlink()
            except FileNotFoundError:
                pass
        return out
    if isinstance(obj, _ArrLeaf):
        return Tensor(obj.array) if obj.as_tensor else obj.array
    if isinstance(obj, dict):
        return {k: _unpack_tree(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_unpack_tree(v) for v in obj)
    return obj


def _discard_tree(obj):
    """Unlink shm segments of a batch the consumer abandoned."""
    if isinstance(obj, _ShmRef):
        from multiprocessing import shared_memory

        try:
            shm = shared_memory.SharedMemory(name=obj.name)
            shm.close()
            shm.unlink()
        except FileNotFoundError:
            pass
    elif isinstance(obj, dict):
        for v in obj.values():
            _discard_tree(v)
    elif isinstance(obj, (list, tuple)):
        for v in obj:
            _discard_tree(v)


# ---------------------------------------------------------------------------
# worker process body (module-level: importable under any start method)
# ---------------------------------------------------------------------------
def _worker_loop(dataset, index_q, result_q, collate_fn, wid, num_workers,
                 worker_init_fn, use_shm, iterable_mode, batch_size,
                 drop_last):
    from . import _WorkerInfo, _worker_info, default_collate_fn

    _worker_info.info = _WorkerInfo(wid, num_workers, dataset)
    default_collated = collate_fn is None
    collate = _numpy_collate if collate_fn is None else collate_fn
    try:
        if worker_init_fn is not None:
            worker_init_fn(wid)
        while True:
            task = index_q.get()
            if task is None:
                break
            kind = task[0]
            if kind == "epoch" and iterable_mode:
                # each worker sees the full stream; users shard it with
                # get_worker_info() — reference worker.py IterableDataset
                # contract
                seq = 0
                try:
                    batch: List[Any] = []
                    for item in dataset:
                        batch.append(item)
                        if len(batch) == batch_size:
                            payload = _pack_tree(collate(batch), use_shm,
                                                 default_collated)
                            result_q.put(("batch", (wid, seq), payload))
                            seq += 1
                            batch = []
                    if batch and not drop_last:
                        payload = _pack_tree(collate(batch), use_shm,
                                             default_collated)
                        result_q.put(("batch", (wid, seq), payload))
                except Exception:
                    result_q.put(("error", None, traceback.format_exc()))
                result_q.put(("done", wid, None))
            elif kind == "task":
                _, seq, indices = task
                try:
                    samples = [dataset[i] for i in indices]
                    payload = _pack_tree(collate(samples), use_shm,
                                         default_collated)
                    result_q.put(("batch", seq, payload))
                except Exception:
                    result_q.put(("error", seq, traceback.format_exc()))
            elif kind == "epoch_end":
                result_q.put(("done", wid, None))
    except (KeyboardInterrupt, BrokenPipeError, EOFError):
        pass


class WorkerPool:
    """A set of live worker processes plus the epoch protocol.

    One pool serves many epochs when ``persistent_workers=True`` (workers
    park on the index queue between epochs); otherwise the loader builds a
    pool per epoch and tears it down at exhaustion.
    """

    def __init__(self, loader):
        self._loader = loader
        ctx_name = "fork" if "fork" in multiprocessing.get_all_start_methods() \
            else None
        self._ctx = multiprocessing.get_context(ctx_name)
        n = loader.num_workers
        # one index queue PER worker (the reference's worker protocol):
        # epoch/shutdown signals are addressed, never stolen by a sibling
        self.index_qs = [self._ctx.Queue() for _ in range(n)]
        # bounded: backpressure keeps shm residency O(prefetch), not O(epoch)
        self.result_q = self._ctx.Queue(
            maxsize=max(2, loader.prefetch_factor * n))
        custom_collate = None if loader.collate_fn is loader._default_collate \
            else loader.collate_fn
        self.procs = []
        for wid in range(n):
            p = self._ctx.Process(
                target=_worker_loop,
                args=(loader.dataset, self.index_qs[wid], self.result_q,
                      custom_collate, wid, n, loader.worker_init_fn,
                      loader.use_shared_memory, loader._iterable_mode,
                      loader.batch_size if loader._iterable_mode else 0,
                      loader.drop_last if loader._iterable_mode else False),
                daemon=True)
            p.start()
            self.procs.append(p)
        self.alive = True
        self.in_use = False  # an epoch generator is actively driving it

    # -- epoch drivers ------------------------------------------------------
    def _get(self):
        timeout = self._loader.timeout or None
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            try:
                item = self.result_q.get(timeout=1.0 if timeout is None
                                         else max(0.01, deadline - time.monotonic()))
                if _obs_state.enabled():
                    try:       # qsize is advisory (unimplemented on macOS)
                        _M_RQ_DEPTH.set(self.result_q.qsize())
                    except (NotImplementedError, OSError):
                        pass
                return item
            except _queue.Empty:
                if deadline is not None and time.monotonic() >= deadline:
                    raise RuntimeError(
                        f"DataLoader timed out after {timeout}s waiting on "
                        "workers")
                if not any(p.is_alive() for p in self.procs):
                    raise RuntimeError(
                        "DataLoader workers exited unexpectedly")

    def run_map_epoch(self, batches, in_order: bool):
        n = self._loader.num_workers
        inflight = 0
        seq_out = 0
        pending = {}
        it = iter(enumerate(batches))
        exhausted = False
        rr = 0  # round-robin worker assignment (reference worker protocol)

        def feed():
            nonlocal inflight, exhausted, rr
            budget = max(2, self._loader.prefetch_factor) * n
            while not exhausted and inflight < budget:
                try:
                    seq, indices = next(it)
                except StopIteration:
                    exhausted = True
                    for q in self.index_qs:
                        q.put(("epoch_end",))
                    return
                self.index_qs[rr % n].put(("task", seq, indices))
                rr += 1
                inflight += 1

        feed()
        done = 0
        try:
            while done < n or inflight > 0:
                kind, seq, payload = self._get()
                if kind == "done":
                    done += 1
                    continue
                if kind == "error":
                    raise RuntimeError(
                        f"DataLoader worker failed on batch {seq}:\n{payload}")
                inflight -= 1
                feed()
                if not in_order:
                    yield _unpack_tree(payload)
                    continue
                pending[seq] = payload
                while seq_out in pending:
                    yield _unpack_tree(pending.pop(seq_out))
                    seq_out += 1
        finally:
            for p in pending.values():
                _discard_tree(p)

    def run_iterable_epoch(self, skip: int = 0):
        """``skip``: resume fast-forward — the first ``skip`` arrived
        batches are dropped at the parent (workers re-stream the dataset;
        their shm segments are reclaimed without a device copy)."""
        n = self._loader.num_workers
        for q in self.index_qs:
            q.put(("epoch",))
        done = 0
        while done < n:
            kind, seq, payload = self._get()
            if kind == "done":
                done += 1
            elif kind == "error":
                raise RuntimeError(f"DataLoader worker failed:\n{payload}")
            elif skip > 0:
                skip -= 1
                _discard_tree(payload)
            else:
                yield _unpack_tree(payload)

    # -- teardown -----------------------------------------------------------
    def shutdown(self):
        if not self.alive:
            return
        self.alive = False
        def drain():
            while True:
                try:
                    kind, _, payload = self.result_q.get_nowait()
                    if kind == "batch":
                        _discard_tree(payload)
                except (_queue.Empty, OSError):
                    return

        try:
            for q in self.index_qs:
                q.put(None)
            # drain stragglers so bounded result_q can't deadlock a join,
            # and reclaim their shm segments
            t_end = time.monotonic() + 2.0
            for p in self.procs:
                p.join(timeout=max(0.1, t_end - time.monotonic()))
            drain()
            for p in self.procs:
                if p.is_alive():
                    p.terminate()
            for p in self.procs:
                p.join(timeout=1.0)
            # a worker unblocked by the first drain may have enqueued one
            # more payload before terminate() — reclaim those segments too
            time.sleep(0.05)
            drain()
            for q in self.index_qs:
                q.cancel_join_thread()
                q.close()
            self.result_q.cancel_join_thread()
            self.result_q.close()
        except Exception:
            pass

    def __del__(self):
        self.shutdown()
