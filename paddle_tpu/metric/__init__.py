"""paddle_tpu.metric (parity: python/paddle/metric/metrics.py —
Metric base + Accuracy/Precision/Recall/Auc)."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


def _np(x):
    return np.asarray(x._value) if isinstance(x, Tensor) else np.asarray(x)


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def compute(self, pred, label, *args):
        pred_np = _np(pred)
        label_np = _np(label)
        if label_np.ndim == pred_np.ndim and label_np.shape[-1] == 1:
            label_np = label_np[..., 0]
        top = np.argsort(-pred_np, axis=-1)[..., : self.maxk]
        correct = top == label_np[..., None]
        return Tensor(correct.astype(np.float32))

    def update(self, correct, *args):
        c = _np(correct)
        res = []
        for i, k in enumerate(self.topk):
            num = float(c[..., :k].sum())
            self.total[i] += num
            self.count[i] += c.shape[0] if c.ndim > 1 else 1
            res.append(num / max(c.shape[0] if c.ndim > 1 else 1, 1))
        return res[0] if len(res) == 1 else res

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return self._name
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name=None):
        self._name = name or "precision"
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int32).reshape(-1)
        l = _np(labels).astype(np.int32).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name=None):
        self._name = name or "recall"
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int32).reshape(-1)
        l = _np(labels).astype(np.int32).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name=None):
        self._name = name or "auc"
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        p = _np(preds)
        l = _np(labels).reshape(-1)
        if p.ndim == 2:
            p = p[:, -1]
        idx = np.clip((p * self.num_thresholds).astype(np.int64), 0,
                      self.num_thresholds)
        for i, lab in zip(idx, l):
            if lab:
                self._stat_pos[i] += 1
            else:
                self._stat_neg[i] += 1

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if not tot_pos or not tot_neg:
            return 0.0
        pos = np.cumsum(self._stat_pos[::-1])
        neg = np.cumsum(self._stat_neg[::-1])
        tpr = pos / tot_pos
        fpr = neg / tot_neg
        return float(np.trapezoid(tpr, fpr)) if hasattr(np, "trapezoid") else \
            float(np.trapz(tpr, fpr))

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):  # noqa: A002
    pred = _np(input)
    lab = _np(label).reshape(-1)
    top = np.argsort(-pred, axis=-1)[:, :k]
    ok = (top == lab[:, None]).any(axis=1).mean()
    return Tensor(np.asarray(ok, dtype=np.float32))
