"""Probability transforms (parity: python/paddle/distribution/transform.py —
Transform base with forward/inverse/log_det_jacobian, Affine/Exp/Sigmoid/
Tanh/Power/Abs/Chain/Reshape/Independent transforms, and
TransformedDistribution in distribution space)."""
from __future__ import annotations

import math
from typing import List, Sequence

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..ops.creation import _t
from ..ops.dispatch import apply

__all__ = [
    "Transform", "AffineTransform", "ExpTransform", "SigmoidTransform",
    "TanhTransform", "PowerTransform", "AbsTransform", "ChainTransform",
    "ReshapeTransform", "IndependentTransform", "TransformedDistribution",
]


class Transform:
    """Invertible map with tractable log|det J|."""

    def forward(self, x):
        return apply(f"{type(self).__name__}.fwd", self._forward, _t(x))

    def inverse(self, y):
        return apply(f"{type(self).__name__}.inv", self._inverse, _t(y))

    def forward_log_det_jacobian(self, x):
        return apply(f"{type(self).__name__}.fldj", self._fldj, _t(x))

    def inverse_log_det_jacobian(self, y):
        return apply(f"{type(self).__name__}.ildj",
                     lambda v: -self._fldj(self._inverse(v)), _t(y))

    # subclass hooks over raw jnp values
    def _forward(self, x):
        raise NotImplementedError

    def _inverse(self, y):
        raise NotImplementedError

    def _fldj(self, x):
        raise NotImplementedError


class AffineTransform(Transform):
    def __init__(self, loc, scale):
        self.loc = _t(loc)._value
        self.scale = _t(scale)._value

    def _forward(self, x):
        return self.loc + self.scale * x

    def _inverse(self, y):
        return (y - self.loc) / self.scale

    def _fldj(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale)), x.shape)


class ExpTransform(Transform):
    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _fldj(self, x):
        return x


class SigmoidTransform(Transform):
    def _forward(self, x):
        return 1 / (1 + jnp.exp(-x))

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _fldj(self, x):
        return -jnp.logaddexp(0.0, -x) - jnp.logaddexp(0.0, x)


class TanhTransform(Transform):
    def _forward(self, x):
        return jnp.tanh(x)

    def _inverse(self, y):
        return jnp.arctanh(y)

    def _fldj(self, x):
        return 2.0 * (math.log(2.0) - x - jnp.logaddexp(0.0, -2.0 * x))


class PowerTransform(Transform):
    def __init__(self, power):
        self.power = float(_t(power)._value) if not isinstance(power, float) \
            else power

    def _forward(self, x):
        return jnp.power(x, self.power)

    def _inverse(self, y):
        return jnp.power(y, 1.0 / self.power)

    def _fldj(self, x):
        return jnp.log(jnp.abs(self.power * jnp.power(x, self.power - 1)))


class AbsTransform(Transform):
    """Non-bijective |x| (inverse returns the positive branch)."""

    def _forward(self, x):
        return jnp.abs(x)

    def _inverse(self, y):
        return y


class ChainTransform(Transform):
    def __init__(self, transforms: Sequence[Transform]):
        self.transforms = list(transforms)

    def _forward(self, x):
        for t in self.transforms:
            x = t._forward(x)
        return x

    def _inverse(self, y):
        for t in reversed(self.transforms):
            y = t._inverse(y)
        return y

    def _fldj(self, x):
        total = 0.0
        for t in self.transforms:
            total = total + t._fldj(x)
            x = t._forward(x)
        return total


class ReshapeTransform(Transform):
    def __init__(self, in_event_shape, out_event_shape):
        self.in_shape = tuple(in_event_shape)
        self.out_shape = tuple(out_event_shape)

    def _forward(self, x):
        lead = x.shape[:x.ndim - len(self.in_shape)]
        return x.reshape(lead + self.out_shape)

    def _inverse(self, y):
        lead = y.shape[:y.ndim - len(self.out_shape)]
        return y.reshape(lead + self.in_shape)

    def _fldj(self, x):
        lead = x.shape[:x.ndim - len(self.in_shape)]
        return jnp.zeros(lead)


class IndependentTransform(Transform):
    """Sums the last n event dims out of the log-det."""

    def __init__(self, base: Transform, reinterpreted_batch_rank: int):
        self.base = base
        self.rank = reinterpreted_batch_rank

    def _forward(self, x):
        return self.base._forward(x)

    def _inverse(self, y):
        return self.base._inverse(y)

    def _fldj(self, x):
        ldj = self.base._fldj(x)
        return jnp.sum(ldj, axis=tuple(range(-self.rank, 0)))


class TransformedDistribution:
    """parity: paddle.distribution.TransformedDistribution."""

    def __init__(self, base, transforms):
        self.base = base
        self.transform = (transforms if isinstance(transforms, Transform)
                          else ChainTransform(list(transforms)))

    def sample(self, shape=()):
        x = self.base.sample(shape)
        return self.transform.forward(x)

    def log_prob(self, value):
        x = self.transform.inverse(value)
        base_lp = self.base.log_prob(x)
        ldj = self.transform.forward_log_det_jacobian(x)
        return base_lp - ldj
