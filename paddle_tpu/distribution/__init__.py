"""paddle_tpu.distribution (parity: python/paddle/distribution/ — Normal,
Bernoulli, Categorical, ... + kl_divergence registry), over
jax.scipy/jax.random."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..framework import dtype as _dtypes
import numpy as np

from ..core.tensor import Tensor
from ..framework.random import next_key

__all__ = [
    "Distribution", "Normal", "Uniform", "Bernoulli", "Categorical", "Beta",
    "Dirichlet", "Exponential", "Gamma", "Geometric", "Gumbel", "Laplace",
    "LogNormal", "Multinomial", "Poisson", "StudentT", "kl_divergence",
    "register_kl",
]


def _v(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x, jnp.float32)


class Distribution:
    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        return self.sample(shape)

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return Tensor(jnp.exp(_v(self.log_prob(value))))

    def entropy(self):
        raise NotImplementedError


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _v(loc)
        self.scale = _v(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    @property
    def mean(self):
        return Tensor(jnp.broadcast_to(self.loc, self.batch_shape))

    @property
    def variance(self):
        return Tensor(jnp.broadcast_to(jnp.square(self.scale), self.batch_shape))

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        eps = jax.random.normal(next_key(), shape)
        return Tensor(self.loc + self.scale * eps)

    def log_prob(self, value):
        v = _v(value)
        var = jnp.square(self.scale)
        return Tensor(-jnp.square(v - self.loc) / (2 * var) -
                      jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))

    def entropy(self):
        e = 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale)
        return Tensor(jnp.broadcast_to(e, self.batch_shape))

    def kl_divergence(self, other):
        var_ratio = jnp.square(self.scale / other.scale)
        t1 = jnp.square((self.loc - other.loc) / other.scale)
        return Tensor(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)))


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _v(low)
        self.high = _v(high)
        super().__init__(jnp.broadcast_shapes(self.low.shape, self.high.shape))

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        u = jax.random.uniform(next_key(), shape)
        return Tensor(self.low + (self.high - self.low) * u)

    def log_prob(self, value):
        v = _v(value)
        inside = (v >= self.low) & (v <= self.high)
        return Tensor(jnp.where(inside, -jnp.log(self.high - self.low), -jnp.inf))

    def entropy(self):
        return Tensor(jnp.log(self.high - self.low))


class Bernoulli(Distribution):
    def __init__(self, probs, name=None):
        self.probs = _v(probs)
        super().__init__(self.probs.shape)

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        return Tensor(jax.random.bernoulli(
            next_key(), jnp.broadcast_to(self.probs, shape)).astype(jnp.float32))

    def log_prob(self, value):
        v = _v(value)
        p = jnp.clip(self.probs, 1e-7, 1 - 1e-7)
        return Tensor(v * jnp.log(p) + (1 - v) * jnp.log1p(-p))

    def entropy(self):
        p = jnp.clip(self.probs, 1e-7, 1 - 1e-7)
        return Tensor(-(p * jnp.log(p) + (1 - p) * jnp.log1p(-p)))


class Categorical(Distribution):
    def __init__(self, logits, name=None):
        self.logits = _v(logits)
        super().__init__(self.logits.shape[:-1])

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        return Tensor(jax.random.categorical(next_key(), self.logits,
                                             shape=shape).astype(_dtypes.index_dtype()))

    def log_prob(self, value):
        v = _v(value).astype(jnp.int32)
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        return Tensor(jnp.take_along_axis(logp, v[..., None], axis=-1)[..., 0])

    def probs(self, value):
        return Tensor(jnp.exp(_v(self.log_prob(value))))

    def entropy(self):
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        return Tensor(-jnp.sum(jnp.exp(logp) * logp, axis=-1))


class Beta(Distribution):
    def __init__(self, alpha, beta, name=None):
        self.alpha = _v(alpha)
        self.beta = _v(beta)
        super().__init__(jnp.broadcast_shapes(self.alpha.shape, self.beta.shape))

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        return Tensor(jax.random.beta(next_key(), self.alpha, self.beta, shape))

    def log_prob(self, value):
        v = _v(value)
        lbeta = (jax.scipy.special.gammaln(self.alpha) +
                 jax.scipy.special.gammaln(self.beta) -
                 jax.scipy.special.gammaln(self.alpha + self.beta))
        return Tensor((self.alpha - 1) * jnp.log(v) +
                      (self.beta - 1) * jnp.log1p(-v) - lbeta)

    @property
    def mean(self):
        return Tensor(self.alpha / (self.alpha + self.beta))


class Dirichlet(Distribution):
    def __init__(self, concentration, name=None):
        self.concentration = _v(concentration)
        super().__init__(self.concentration.shape[:-1],
                         self.concentration.shape[-1:])

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        return Tensor(jax.random.dirichlet(next_key(), self.concentration, shape))

    def log_prob(self, value):
        v = _v(value)
        a = self.concentration
        norm = jnp.sum(jax.scipy.special.gammaln(a), -1) - \
            jax.scipy.special.gammaln(jnp.sum(a, -1))
        return Tensor(jnp.sum((a - 1) * jnp.log(v), -1) - norm)


class Exponential(Distribution):
    def __init__(self, rate, name=None):
        self.rate = _v(rate)
        super().__init__(self.rate.shape)

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        return Tensor(jax.random.exponential(next_key(), shape) / self.rate)

    def log_prob(self, value):
        return Tensor(jnp.log(self.rate) - self.rate * _v(value))


class Gamma(Distribution):
    def __init__(self, concentration, rate, name=None):
        self.concentration = _v(concentration)
        self.rate = _v(rate)
        super().__init__(jnp.broadcast_shapes(self.concentration.shape,
                                              self.rate.shape))

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        return Tensor(jax.random.gamma(next_key(), self.concentration, shape) /
                      self.rate)

    def log_prob(self, value):
        v = _v(value)
        a, b = self.concentration, self.rate
        return Tensor(a * jnp.log(b) + (a - 1) * jnp.log(v) - b * v -
                      jax.scipy.special.gammaln(a))


class Geometric(Distribution):
    def __init__(self, probs, name=None):
        self.probs = _v(probs)
        super().__init__(self.probs.shape)

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        u = jax.random.uniform(next_key(), shape)
        return Tensor(jnp.floor(jnp.log1p(-u) / jnp.log1p(-self.probs)))

    def log_prob(self, value):
        v = _v(value)
        return Tensor(v * jnp.log1p(-self.probs) + jnp.log(self.probs))


class Gumbel(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _v(loc)
        self.scale = _v(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        return Tensor(self.loc + self.scale * jax.random.gumbel(next_key(), shape))

    def log_prob(self, value):
        z = (_v(value) - self.loc) / self.scale
        return Tensor(-(z + jnp.exp(-z)) - jnp.log(self.scale))


class Laplace(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _v(loc)
        self.scale = _v(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        return Tensor(self.loc + self.scale * jax.random.laplace(next_key(), shape))

    def log_prob(self, value):
        return Tensor(-jnp.abs(_v(value) - self.loc) / self.scale -
                      jnp.log(2 * self.scale))


class LogNormal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.base = Normal(loc, scale)
        super().__init__(self.base.batch_shape)

    def sample(self, shape=()):
        return Tensor(jnp.exp(_v(self.base.sample(shape))))

    def log_prob(self, value):
        v = _v(value)
        return Tensor(_v(self.base.log_prob(Tensor(jnp.log(v)))) - jnp.log(v))


class Multinomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = total_count
        self.probs = _v(probs)
        super().__init__(self.probs.shape[:-1], self.probs.shape[-1:])

    def sample(self, shape=()):
        n = self.probs.shape[-1]
        draws = jax.random.categorical(
            next_key(), jnp.log(self.probs),
            shape=tuple(shape) + self.batch_shape + (self.total_count,))
        return Tensor(jax.nn.one_hot(draws, n).sum(-2))


class Poisson(Distribution):
    def __init__(self, rate, name=None):
        self.rate = _v(rate)
        super().__init__(self.rate.shape)

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        return Tensor(jax.random.poisson(next_key(), self.rate, shape).astype(
            jnp.float32))

    def log_prob(self, value):
        v = _v(value)
        return Tensor(v * jnp.log(self.rate) - self.rate -
                      jax.scipy.special.gammaln(v + 1))


class StudentT(Distribution):
    def __init__(self, df, loc=0.0, scale=1.0, name=None):
        self.df = _v(df)
        self.loc = _v(loc)
        self.scale = _v(scale)
        super().__init__(jnp.broadcast_shapes(self.df.shape, self.loc.shape,
                                              self.scale.shape))

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        return Tensor(self.loc + self.scale * jax.random.t(next_key(), self.df,
                                                           shape))

    def log_prob(self, value):
        z = (_v(value) - self.loc) / self.scale
        df = self.df
        return Tensor(
            jax.scipy.special.gammaln((df + 1) / 2) -
            jax.scipy.special.gammaln(df / 2) -
            0.5 * jnp.log(df * math.pi) - jnp.log(self.scale) -
            (df + 1) / 2 * jnp.log1p(jnp.square(z) / df))


# -- KL registry ---------------------------------------------------------------
_KL_REGISTRY = {}


def register_kl(p_cls, q_cls):
    def deco(fn):
        _KL_REGISTRY[(p_cls, q_cls)] = fn
        return fn

    return deco


def kl_divergence(p, q):
    fn = _KL_REGISTRY.get((type(p), type(q)))
    if fn is not None:
        return fn(p, q)
    if hasattr(p, "kl_divergence"):
        return p.kl_divergence(q)
    raise NotImplementedError(f"no KL registered for {type(p)} vs {type(q)}")


@register_kl(Normal, Normal)
def _kl_normal(p, q):
    return p.kl_divergence(q)


@register_kl(Categorical, Categorical)
def _kl_cat(p, q):
    logp = jax.nn.log_softmax(p.logits, -1)
    logq = jax.nn.log_softmax(q.logits, -1)
    return Tensor(jnp.sum(jnp.exp(logp) * (logp - logq), -1))

from .transform import (  # noqa: F401,E402
    AbsTransform, AffineTransform, ChainTransform, ExpTransform,
    IndependentTransform, PowerTransform, ReshapeTransform, SigmoidTransform,
    TanhTransform, Transform, TransformedDistribution,
)

from .extra import (  # noqa: E402,F401
    ExponentialFamily, LKJCholesky,
    Binomial, Cauchy, Chi2, ContinuousBernoulli, Independent,
    MultivariateNormal,
)
__all__ += ["ExponentialFamily", "LKJCholesky",
            "Binomial", "Cauchy", "Chi2", "ContinuousBernoulli",
            "Independent", "MultivariateNormal"]
