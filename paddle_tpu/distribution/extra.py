"""Distribution zoo extension (parity: python/paddle/distribution/ —
binomial.py, cauchy.py, chi2.py, continuous_bernoulli.py,
multivariate_normal.py, independent.py), over jax.random /
jax.scipy.special.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..framework.random import next_key
from . import Distribution, Gamma, _v

__all__ = ["Binomial", "Cauchy", "Chi2", "ContinuousBernoulli",
           "MultivariateNormal", "Independent", "ExponentialFamily",
           "LKJCholesky"]


class Binomial(Distribution):
    """parity: distribution/binomial.py."""

    def __init__(self, total_count, probs, name=None):
        self.total_count = jnp.asarray(total_count)
        self.probs = _v(probs)
        super().__init__(jnp.broadcast_shapes(
            jnp.shape(self.total_count), self.probs.shape))

    @property
    def mean(self):
        return Tensor(self.total_count * self.probs)

    @property
    def variance(self):
        return Tensor(self.total_count * self.probs * (1 - self.probs))

    # exact Bernoulli-sum sampling/entropy up to this n; above it the
    # normal approximation is used (O(n) memory otherwise)
    _EXACT_N = 1024

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        n = int(jnp.max(self.total_count))
        if n > self._EXACT_N:
            mean = self.total_count * self.probs
            std = jnp.sqrt(mean * (1 - self.probs))
            g = jax.random.normal(next_key(), shape)
            counts = jnp.clip(jnp.round(mean + std * g), 0,
                              self.total_count)
            return Tensor(counts.astype(jnp.float32))
        u = jax.random.uniform(next_key(), (n,) + shape)
        counts = jnp.sum(
            (u < self.probs)
            & (jnp.arange(n).reshape((n,) + (1,) * len(shape))
               < self.total_count), axis=0)
        return Tensor(counts.astype(jnp.float32))

    def log_prob(self, value):
        k = _v(value)
        n = self.total_count.astype(jnp.float32)
        p = jnp.clip(self.probs, 1e-7, 1 - 1e-7)
        comb = (jax.scipy.special.gammaln(n + 1)
                - jax.scipy.special.gammaln(k + 1)
                - jax.scipy.special.gammaln(n - k + 1))
        return Tensor(comb + k * jnp.log(p) + (n - k) * jnp.log1p(-p))

    def entropy(self):
        n = int(jnp.max(self.total_count))
        if n > self._EXACT_N:
            # Gaussian-limit entropy 0.5*log(2πe·np(1-p))
            var = self.total_count * self.probs * (1 - self.probs)
            return Tensor(0.5 * jnp.log(2 * math.pi * math.e * var))
        # exact finite sum over support
        ks = jnp.arange(n + 1, dtype=jnp.float32)
        ks = ks.reshape((n + 1,) + (1,) * len(self.batch_shape))
        lp = _v(self.log_prob(Tensor(ks)))
        valid = ks <= self.total_count
        return Tensor(-jnp.sum(jnp.where(valid, jnp.exp(lp) * lp, 0.0),
                               axis=0))


class Cauchy(Distribution):
    """parity: distribution/cauchy.py."""

    def __init__(self, loc, scale, name=None):
        self.loc = _v(loc)
        self.scale = _v(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    @property
    def mean(self):
        raise ValueError("Cauchy has no mean")

    @property
    def variance(self):
        raise ValueError("Cauchy has no variance")

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        return Tensor(self.loc + self.scale
                      * jax.random.cauchy(next_key(), shape))

    def log_prob(self, value):
        z = (_v(value) - self.loc) / self.scale
        return Tensor(-jnp.log(math.pi * self.scale * (1 + z * z)))

    def cdf(self, value):
        z = (_v(value) - self.loc) / self.scale
        return Tensor(jnp.arctan(z) / math.pi + 0.5)

    def entropy(self):
        return Tensor(jnp.broadcast_to(
            jnp.log(4 * math.pi * self.scale), self.batch_shape))


class Chi2(Gamma):
    """parity: distribution/chi2.py — Gamma(df/2, 1/2)."""

    def __init__(self, df, name=None):
        self.df = _v(df)
        super().__init__(self.df / 2.0, jnp.ones_like(self.df) / 2.0)


class ContinuousBernoulli(Distribution):
    """parity: distribution/continuous_bernoulli.py (Loaiza-Ganem &
    Cunningham 2019)."""

    def __init__(self, probs, lims=(0.499, 0.501), name=None):
        self.probs = _v(probs)
        self._lims = lims
        super().__init__(self.probs.shape)

    def _outside(self):
        return (self.probs < self._lims[0]) | (self.probs > self._lims[1])

    def _log_norm(self):
        """log C(λ): λ safe-clamped near 1/2, Taylor there."""
        lam = jnp.clip(self.probs, 1e-6, 1 - 1e-6)
        safe = jnp.where(self._outside(), lam, 0.4)
        log_c = jnp.log(
            2 * jnp.abs(jnp.arctanh(1 - 2 * safe))
            / jnp.abs(1 - 2 * safe))
        taylor = math.log(2.0) + 4.0 / 3.0 * (lam - 0.5) ** 2
        return jnp.where(self._outside(), log_c, taylor)

    @property
    def mean(self):
        lam = jnp.clip(self.probs, 1e-6, 1 - 1e-6)
        m = lam / (2 * lam - 1) + 1 / (2 * jnp.arctanh(1 - 2 * lam))
        return Tensor(jnp.where(self._outside(), m, 0.5))

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        u = jax.random.uniform(next_key(), shape, minval=1e-6,
                               maxval=1 - 1e-6)
        lam = jnp.clip(self.probs, 1e-6, 1 - 1e-6)
        s = (jnp.log1p(u * (2 * lam - 1) / (1 - lam))
             / jnp.log(lam / (1 - lam)))
        return Tensor(jnp.where(self._outside(), s, u))

    def log_prob(self, value):
        x = _v(value)
        lam = jnp.clip(self.probs, 1e-6, 1 - 1e-6)
        return Tensor(x * jnp.log(lam) + (1 - x) * jnp.log1p(-lam)
                      + self._log_norm())


class MultivariateNormal(Distribution):
    """parity: distribution/multivariate_normal.py (full covariance)."""

    def __init__(self, loc, covariance_matrix=None, scale_tril=None,
                 precision_matrix=None, name=None):
        self.loc = _v(loc)
        if scale_tril is not None:
            self._tril = _v(scale_tril)
        elif covariance_matrix is not None:
            self._tril = jnp.linalg.cholesky(_v(covariance_matrix))
        elif precision_matrix is not None:
            self._tril = jnp.linalg.cholesky(
                jnp.linalg.inv(_v(precision_matrix)))
        else:
            raise ValueError("one of covariance_matrix / scale_tril / "
                             "precision_matrix is required")
        super().__init__(self.loc.shape[:-1], self.loc.shape[-1:])

    @property
    def mean(self):
        return Tensor(self.loc)

    @property
    def covariance_matrix(self):
        return Tensor(self._tril @ jnp.swapaxes(self._tril, -1, -2))

    @property
    def variance(self):
        return Tensor(jnp.sum(jnp.square(self._tril), axis=-1))

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape + self.event_shape
        eps = jax.random.normal(next_key(), shape)
        return Tensor(self.loc + jnp.einsum("...ij,...j->...i",
                                            self._tril, eps))

    rsample = sample

    def log_prob(self, value):
        d = self.event_shape[0]
        diff = _v(value) - self.loc
        sol = jax.scipy.linalg.solve_triangular(self._tril, diff[..., None],
                                                lower=True)[..., 0]
        half_logdet = jnp.sum(jnp.log(jnp.diagonal(self._tril, axis1=-2,
                                                   axis2=-1)), -1)
        return Tensor(-0.5 * jnp.sum(sol * sol, -1) - half_logdet
                      - 0.5 * d * math.log(2 * math.pi))

    def entropy(self):
        d = self.event_shape[0]
        half_logdet = jnp.sum(jnp.log(jnp.diagonal(self._tril, axis1=-2,
                                                   axis2=-1)), -1)
        e = 0.5 * d * (1 + math.log(2 * math.pi)) + half_logdet
        return Tensor(jnp.broadcast_to(e, self.batch_shape))

    def kl_divergence(self, other):
        d = self.event_shape[0]
        m = jax.scipy.linalg.solve_triangular(
            other._tril, self._tril, lower=True)
        tr = jnp.sum(jnp.square(m), axis=(-2, -1))
        diff = other.loc - self.loc
        sol = jax.scipy.linalg.solve_triangular(other._tril, diff[..., None],
                                                lower=True)[..., 0]
        maha = jnp.sum(sol * sol, -1)
        logdet = (jnp.sum(jnp.log(jnp.diagonal(other._tril, axis1=-2,
                                               axis2=-1)), -1)
                  - jnp.sum(jnp.log(jnp.diagonal(self._tril, axis1=-2,
                                                 axis2=-1)), -1))
        return Tensor(0.5 * (tr + maha - d) + logdet)


class Independent(Distribution):
    """parity: distribution/independent.py — reinterpret batch dims as
    event dims."""

    def __init__(self, base, reinterpreted_batch_rank=1, name=None):
        self.base = base
        self._rank = reinterpreted_batch_rank
        bs = base.batch_shape
        super().__init__(bs[:len(bs) - self._rank],
                         bs[len(bs) - self._rank:] + base.event_shape)

    @property
    def mean(self):
        return self.base.mean

    @property
    def variance(self):
        return self.base.variance

    def sample(self, shape=()):
        return self.base.sample(shape)

    def log_prob(self, value):
        lp = _v(self.base.log_prob(value))
        return Tensor(jnp.sum(lp, axis=tuple(range(-self._rank, 0))))

    def entropy(self):
        e = _v(self.base.entropy())
        return Tensor(jnp.sum(e, axis=tuple(range(-self._rank, 0))))


class ExponentialFamily(Distribution):
    """parity: distribution/exponential_family.py — base class whose entropy
    comes from the Bregman divergence of the log-normalizer (computed here
    with jax autodiff in place of the reference's dygraph grad)."""

    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        raise NotImplementedError

    @property
    def _mean_carrier_measure(self):
        raise NotImplementedError

    def entropy(self):
        nat = [jnp.asarray(p._value if isinstance(p, Tensor) else p)
               for p in self._natural_parameters]
        lg = self._log_normalizer(*nat)
        grads = jax.grad(
            lambda *ps: jnp.sum(self._log_normalizer(*ps)),
            argnums=tuple(range(len(nat))))(*nat)
        ent = -self._mean_carrier_measure + lg
        for p, g in zip(nat, grads):
            ent = ent - p * g
        return Tensor(ent)


class LKJCholesky(Distribution):
    """parity: distribution/lkj_cholesky.py — distribution over Cholesky
    factors of correlation matrices, LKJ(dim, concentration). Sampling via
    the onion method; log_prob matches the standard LKJ-Cholesky density
    Σ_i (dim - i - 1 + 2(η - 1)) log L_ii + log Z(η)."""

    def __init__(self, dim=2, concentration=1.0, sample_method="onion"):
        if dim < 2:
            raise ValueError("LKJCholesky: dim must be >= 2")
        self.dim = int(dim)
        self.concentration = Tensor(jnp.asarray(float(concentration),
                                                jnp.float32))
        self.sample_method = sample_method
        super().__init__(batch_shape=(), event_shape=(dim, dim))

    def sample(self, shape=()):
        shape = tuple(shape)
        n = self.dim
        eta = float(np.asarray(self.concentration._value))
        key = next_key()
        # onion method (LKJ 2009): build rows from Beta marginals + sphere
        k1, k2 = jax.random.split(key)
        L = jnp.zeros(shape + (n, n), jnp.float32)
        L = L.at[..., 0, 0].set(1.0)
        beta_key = k1
        for i in range(1, n):
            beta_key, ku, kn = jax.random.split(beta_key, 3)
            a = eta + (n - 1 - i) / 2.0
            y = jax.random.beta(ku, i / 2.0, a, shape)      # squared radius
            u = jax.random.normal(kn, shape + (i,))
            u = u / jnp.linalg.norm(u, axis=-1, keepdims=True)
            w = jnp.sqrt(y)[..., None] * u
            L = L.at[..., i, :i].set(w)
            L = L.at[..., i, i].set(jnp.sqrt(jnp.maximum(1.0 - y, 1e-12)))
        return Tensor(L)

    def log_prob(self, value):
        L = jnp.asarray(value._value if isinstance(value, Tensor) else value)
        n = self.dim
        eta = jnp.asarray(self.concentration._value)
        diag = jnp.diagonal(L, axis1=-2, axis2=-1)[..., 1:]
        order = jnp.arange(1, n, dtype=jnp.float32)
        # exponents: (n - i - 1) + 2(eta - 1) for row index i = 1..n-1
        expo = (n - order - 1.0) + 2.0 * (eta - 1.0)
        unnorm = jnp.sum(expo * jnp.log(diag), axis=-1)
        # log normalization (standard LKJ-Cholesky constant, the
        # torch/numpyro per-row Beta formulation)
        lognorm = 0.0
        for k in range(1, n):
            alpha_k = eta + (n - 1 - k) / 2.0
            lognorm += (k / 2.0) * jnp.log(jnp.pi) \
                + jax.scipy.special.gammaln(alpha_k) \
                - jax.scipy.special.gammaln(alpha_k + k / 2.0)
        return Tensor(unnorm - lognorm)
