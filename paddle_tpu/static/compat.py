"""Legacy static-graph API compatibility surface.

Parity: python/paddle/static/__init__.py __all__. In this framework "static
mode" IS jit capture (see static/__init__.py), so these entry points map the
reference's Program/Scope machinery onto the capture layer and the eager
parameter store: Scope = named Tensor dict, append_backward/gradients =
eager autograd, serialize_* = pickled state + exported StableHLO.
"""
from __future__ import annotations

import pickle

import numpy as np

__all__ = [
    "BuildStrategy", "CompiledProgram", "ExponentialMovingAverage",
    "IpuCompiledProgram", "IpuStrategy", "Print", "Variable",
    "WeightNormParamAttr", "accuracy", "append_backward", "auc",
    "cpu_places", "create_global_var", "create_parameter",
    "ctr_metric_bundle", "cuda_places", "deserialize_persistables",
    "deserialize_program", "device_guard", "global_scope", "gradients",
    "ipu_shard_guard", "load", "load_from_file", "load_program_state",
    "normalize_program", "py_func", "save", "save_to_file", "scope_guard",
    "serialize_persistables", "serialize_program", "set_ipu_shard",
    "set_program_state", "xpu_places", "Scope",
]


# ---------------------------------------------------------------------------
# scope
# ---------------------------------------------------------------------------
class _Var:
    def __init__(self, name):
        self.name = name
        self._tensor = None

    def get_tensor(self):
        return self._tensor

    def set(self, value, place=None):
        import paddle_tpu as paddle

        self._tensor = value if hasattr(value, "_value") else \
            paddle.to_tensor(np.asarray(value))


class Scope:
    """parity: the C++ Scope (fluid/framework/scope.h) — named variables."""

    def __init__(self):
        self._vars = {}

    def var(self, name):
        return self._vars.setdefault(name, _Var(name))

    def find_var(self, name):
        return self._vars.get(name)

    def local_var_names(self):
        return list(self._vars)


_global_scope = Scope()
_scope_stack = [_global_scope]


def global_scope():
    return _scope_stack[-1]


class scope_guard:
    """parity: static.scope_guard — pushes a Scope for the with-block."""

    def __init__(self, scope):
        self._scope = scope

    def __enter__(self):
        _scope_stack.append(self._scope)
        return self._scope

    def __exit__(self, *exc):
        _scope_stack.pop()
        return False


# ---------------------------------------------------------------------------
# vars / params
# ---------------------------------------------------------------------------
def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    """parity: static.create_global_var — a named filled tensor registered
    in the global scope."""
    import paddle_tpu as paddle

    t = paddle.full(list(shape), value, dtype)
    nm = name or f"global_var_{len(global_scope()._vars)}"
    global_scope().var(nm).set(t)
    t.name = nm
    return t


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    import paddle_tpu as paddle

    return paddle.create_parameter(shape, dtype, name, attr, is_bias,
                                   default_initializer)


def _dataplaceholder():
    from . import _DataPlaceholder

    return _DataPlaceholder


# static.Variable is the declared-input/IR-value type; capture mode uses the
# data() placeholder for that role.
from . import _DataPlaceholder as Variable  # noqa: E402


class WeightNormParamAttr:
    """parity: static.WeightNormParamAttr — ParamAttr requesting
    weight-norm reparameterization along ``dim`` (apply nn.utils.weight_norm
    on the owning layer in this framework)."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        self.dim = dim
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.do_model_average = do_model_average
        self.need_clip = need_clip


# ---------------------------------------------------------------------------
# autograd entry points
# ---------------------------------------------------------------------------
def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None, checkpoints=None):
    """parity: static.append_backward — backward over the eager tape;
    returns [(param, grad)] like the reference."""
    loss.backward()
    params = parameter_list
    if params is None:
        from ..core.tensor import Parameter

        params = [t for t in _live_params() if t.grad is not None]
    return [(p, p.grad) for p in params if p.grad is not None]


def _live_params():
    import gc

    from ..core.tensor import Parameter

    return [o for o in gc.get_objects() if isinstance(o, Parameter)]


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """parity: static.gradients — d(targets)/d(inputs) via eager autograd."""
    import paddle_tpu as paddle

    ts = targets if isinstance(targets, (list, tuple)) else [targets]
    xs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    gs = (target_gradients
          if isinstance(target_gradients, (list, tuple))
          else ([target_gradients] if target_gradients is not None else None))
    return paddle.autograd.grad(ts, xs, grad_outputs=gs, allow_unused=True)


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """parity: static.py_func — eager mode simply calls the function."""
    xs = x if isinstance(x, (list, tuple)) else [x]
    result = func(*xs)
    return result


def Print(input, first_n=-1, message=None, summarize=20,  # noqa: A002
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_layout=True,
          print_tensor_lod=True, print_phase="both"):
    """parity: static.Print — logs the tensor and passes it through."""
    vals = np.asarray(input._value)
    parts = []
    if message:
        parts.append(message)
    if print_tensor_shape:
        parts.append(f"shape={list(vals.shape)}")
    if print_tensor_type:
        parts.append(f"dtype={vals.dtype}")
    flat = vals.reshape(-1)[:summarize if summarize > 0 else None]
    parts.append(f"data={flat.tolist()}")
    print("  ".join(parts))
    return input


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------
def accuracy(input, label, k=1, correct=None, total=None, name=None):  # noqa: A002
    """parity: static.accuracy — top-k accuracy of predictions."""
    import paddle_tpu as paddle

    probs = np.asarray(input._value)
    y = np.asarray(label._value).reshape(-1)
    topk = np.argsort(-probs, axis=-1)[:, :k]
    acc = float(np.mean([(y[i] in topk[i]) for i in range(len(y))]))
    return paddle.to_tensor(np.asarray(acc, np.float32))


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,  # noqa: A002
        slide_steps=1, ins_tag_weight=None):
    """parity: static.auc — ROC-AUC of positive-class scores."""
    import paddle_tpu as paddle

    probs = np.asarray(input._value)
    pos = probs[:, 1] if probs.ndim == 2 and probs.shape[1] == 2 else \
        probs.reshape(-1)
    y = np.asarray(label._value).reshape(-1)
    order = np.argsort(-pos, kind="stable")
    y_sorted = y[order]
    P = y_sorted.sum()
    N = len(y_sorted) - P
    if P == 0 or N == 0:
        val = 0.0
    else:
        tps = np.cumsum(y_sorted)
        fps = np.cumsum(1 - y_sorted)
        tpr = np.concatenate([[0], tps / P])
        fpr = np.concatenate([[0], fps / N])
        val = float(np.trapezoid(tpr, fpr))
    out = paddle.to_tensor(np.asarray(val, np.float32))
    return out, out, [out]


def ctr_metric_bundle(input, label, ins_tag_weight=None):  # noqa: A002
    """parity: static.ctr_metric_bundle — (auc, squared error, abs error,
    prediction count) for click-through-rate models."""
    import paddle_tpu as paddle

    probs = np.asarray(input._value).reshape(-1)
    y = np.asarray(label._value).reshape(-1).astype(np.float64)
    auc_t, _, _ = auc(input, label)
    sqrerr = paddle.to_tensor(np.asarray(((probs - y) ** 2).sum(),
                                         np.float32))
    abserr = paddle.to_tensor(np.asarray(np.abs(probs - y).sum(),
                                         np.float32))
    prob_sum = paddle.to_tensor(np.asarray(probs.sum(), np.float32))
    q = paddle.to_tensor(np.asarray(float(len(probs)), np.float32))
    return auc_t, sqrerr, abserr, prob_sum, q


# ---------------------------------------------------------------------------
# places / device guard
# ---------------------------------------------------------------------------
def cpu_places(device_count=None):
    import os

    import paddle_tpu as paddle

    n = device_count or int(os.environ.get("CPU_NUM", 1))
    return [paddle.CPUPlace(i) for i in range(n)]


def cuda_places(device_ids=None):
    raise RuntimeError(
        "cuda_places: paddle_tpu is not compiled with CUDA; use "
        "tpu devices via paddle.device.get_all_devices()")


def xpu_places(device_ids=None):
    raise RuntimeError("xpu_places: paddle_tpu is not compiled with XPU")


class device_guard:
    """parity: static.device_guard — records the placement request; XLA owns
    actual placement under capture."""

    def __init__(self, device=None):
        self.device = device

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


# ---------------------------------------------------------------------------
# build / compiled program
# ---------------------------------------------------------------------------
class BuildStrategy:
    """parity: static.BuildStrategy — graph-build knobs. XLA performs the
    reference's fusion passes; the attributes are accepted and recorded."""

    class ReduceStrategy:
        AllReduce = 0
        Reduce = 1

    class GradientScaleStrategy:
        CoeffNumDevice = 0
        One = 1
        Customized = 2

    def __init__(self):
        self.fuse_elewise_add_act_ops = False
        self.fuse_bn_act_ops = False
        self.fuse_bn_add_act_ops = True
        self.enable_auto_fusion = False
        self.fuse_relu_depthwise_conv = False
        self.sync_batch_norm = False
        self.fuse_broadcast_ops = False
        self.fuse_all_optimizer_ops = False
        self.enable_inplace = False
        self.enable_addto = False
        self.gradient_scale_strategy = \
            BuildStrategy.GradientScaleStrategy.CoeffNumDevice
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.memory_optimize = None
        self.build_cinn_pass = False


class CompiledProgram:
    """parity: static.CompiledProgram — wraps a program (captured callable)
    with a BuildStrategy; Executor.run accepts it transparently."""

    def __init__(self, program, build_strategy=None):
        self._program = program
        self._build_strategy = build_strategy or BuildStrategy()

    def __getattr__(self, item):
        return getattr(self.__dict__["_program"], item)


class IpuStrategy:
    def __init__(self):
        raise RuntimeError("IpuStrategy: paddle_tpu has no IPU support")


class IpuCompiledProgram:
    def __init__(self, *a, **k):
        raise RuntimeError(
            "IpuCompiledProgram: paddle_tpu has no IPU support")


class ipu_shard_guard:
    def __init__(self, index=-1, stage=-1):
        raise RuntimeError("ipu_shard_guard: paddle_tpu has no IPU support")


def set_ipu_shard(call_func, index=-1, stage=-1):
    raise RuntimeError("set_ipu_shard: paddle_tpu has no IPU support")


# ---------------------------------------------------------------------------
# EMA
# ---------------------------------------------------------------------------
class ExponentialMovingAverage:
    """parity: static.ExponentialMovingAverage — shadow parameters
    ema_t = decay * ema_{t-1} + (1 - decay) * p_t, with apply()/restore()
    swapping. Operates on the eager parameters of the given layer (or all
    live Parameters)."""

    def __init__(self, decay=0.999, thres_steps=None, name=None,
                 layer=None):
        self._decay = float(decay)
        self._thres_steps = thres_steps
        self._layer = layer
        self._shadow = {}
        self._backup = {}
        self._step = 0

    def _params(self):
        if self._layer is not None:
            return list(self._layer.named_parameters())
        return [(str(id(p)), p) for p in _live_params()]

    def update(self):
        self._step += 1
        # reference: the (1+t)/(10+t) warmup only applies with thres_steps
        d = self._decay
        if self._thres_steps is not None:
            d = min(self._decay, (1 + self._step) / (10 + self._step))
        for name, p in self._params():
            cur = np.asarray(p._value, np.float32)
            if name not in self._shadow:
                self._shadow[name] = cur.copy()
            else:
                self._shadow[name] = d * self._shadow[name] + (1 - d) * cur

    def apply(self, executor=None, need_restore=True):
        import contextlib

        @contextlib.contextmanager
        def ctx():
            import jax.numpy as jnp

            for name, p in self._params():
                if name in self._shadow:
                    self._backup[name] = p._value
                    p._replace_value(jnp.asarray(
                        self._shadow[name], p._value.dtype))
            try:
                yield
            finally:
                if need_restore:
                    self.restore()

        return ctx()

    def restore(self, executor=None):
        import jax.numpy as jnp  # noqa: F401

        for name, p in self._params():
            if name in self._backup:
                p._replace_value(self._backup.pop(name))


# ---------------------------------------------------------------------------
# serialization
# ---------------------------------------------------------------------------
def _state_of(program):
    layer = getattr(program, "_layer", None) or getattr(program, "layer",
                                                        None)
    if layer is not None and hasattr(layer, "state_dict"):
        return {k: np.asarray(v._value)
                for k, v in layer.state_dict().items()}
    return {k: np.asarray(v.get_tensor()._value)
            for k, v in global_scope()._vars.items()
            if v.get_tensor() is not None}


def save(program, model_path, protocol=4, **configs):
    """parity: static.save — persists the program state (parameters +
    scope vars) as <path>.pdparams."""
    state = _state_of(program)
    with open(model_path + ".pdparams", "wb") as f:
        pickle.dump(state, f, protocol=protocol)


def load(program, model_path, executor=None, var_list=None):
    """parity: static.load — restores state saved by static.save."""
    with open(model_path + ".pdparams", "rb") as f:
        state = pickle.load(f)
    set_program_state(program, state)
    return state


def load_program_state(model_path, var_list=None):
    with open(model_path + ".pdparams", "rb") as f:
        return pickle.load(f)


def set_program_state(program, state_dict):
    import jax.numpy as jnp

    layer = getattr(program, "_layer", None) or getattr(program, "layer",
                                                        None)
    if layer is not None and hasattr(layer, "set_state_dict"):
        import paddle_tpu as paddle

        layer.set_state_dict({k: paddle.to_tensor(v)
                              for k, v in state_dict.items()})
        return
    for k, v in state_dict.items():
        global_scope().var(k).set(jnp.asarray(v))


def save_to_file(path, content):
    """parity: static.io.save_to_file — raw bytes out."""
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path):
    with open(path, "rb") as f:
        return f.read()


def serialize_program(feed_vars, fetch_vars, program=None, legacy_format=False):
    """parity: static.serialize_program — bytes form of the program
    structure (the capture layer's export: input specs + fetch count)."""
    meta = {
        "feeds": [getattr(v, "name", str(i))
                  for i, v in enumerate(feed_vars or [])],
        "fetches": len(fetch_vars or []),
    }
    return pickle.dumps(meta)


def deserialize_program(data):
    from . import Program

    meta = pickle.loads(data)
    p = Program()
    p._meta = meta
    return p


def serialize_persistables(feed_vars, fetch_vars, program=None, **kwargs):
    return pickle.dumps(_state_of(program))


def deserialize_persistables(program, data, executor=None):
    state = pickle.loads(data)
    set_program_state(program, state)
    return state


def normalize_program(program, feed_vars, fetch_vars, **kwargs):
    """parity: static.normalize_program — prune to the feed→fetch slice;
    capture-based programs are already minimal."""
    return program
