"""Program-graph introspection: an OpDesc/Block/Program view over jaxpr.

Parity: the reference's ProgramDesc object model
(paddle/fluid/framework/program_desc.h; python surface
python/paddle/base/framework.py Program/Block/Operator) — programs are
inspectable op graphs: enumerate ops, read their inputs/outputs/attrs,
list block variables, print the IR, clone for inference.

TPU-native design: the single IR is the jaxpr. ``Program.from_callable``
traces a python function (or a ``to_static`` StaticFunction) once with
abstract values and exposes the closed jaxpr through the reference's
object model — each jaxpr equation is an ``Operator``, each intermediate
an entry in the block's var table. The view is read-only by design:
transformation passes belong to XLA (SURVEY §7's absorption rule), but
inspection, counting, and serialization-for-debugging are first-class.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import jax
import numpy as np

__all__ = ["Operator", "Block", "Program"]


class Operator:
    """One jaxpr equation viewed as the reference's Operator/OpDesc."""

    def __init__(self, eqn, namer):
        self._eqn = eqn
        self.type = eqn.primitive.name
        self.input_names = [namer(v) for v in eqn.invars]
        self.output_names = [namer(v) for v in eqn.outvars]
        # static params = the reference's op attributes
        self._attrs = dict(eqn.params)

    def input_arg_names(self) -> List[str]:
        return list(self.input_names)

    def output_arg_names(self) -> List[str]:
        return list(self.output_names)

    def attr_names(self) -> List[str]:
        return sorted(self._attrs)

    def attr(self, name: str):
        return self._attrs[name]

    @property
    def attrs(self) -> Dict[str, Any]:
        return dict(self._attrs)

    def __repr__(self):
        return (f"{{{', '.join(self.output_names)}}} = {self.type}"
                f"({', '.join(self.input_names)})")


class _VarView:
    __slots__ = ("name", "shape", "dtype", "persistable")

    def __init__(self, name, aval, persistable=False):
        self.name = name
        self.shape = list(getattr(aval, "shape", ()))
        self.dtype = getattr(aval, "dtype", None)
        self.persistable = persistable

    def __repr__(self):
        return f"var {self.name} : {self.dtype}{self.shape}"


class Block:
    """The reference's Block: an op list plus a var table."""

    def __init__(self, idx: int = 0):
        self.idx = idx
        self.ops: List[Operator] = []
        self._vars: Dict[str, _VarView] = {}

    @property
    def vars(self) -> Dict[str, _VarView]:
        return dict(self._vars)

    def var(self, name: str) -> _VarView:
        if name not in self._vars:
            raise ValueError(f"var {name!r} not in block {self.idx}")
        return self._vars[name]

    def all_parameters(self) -> List[_VarView]:
        return [v for v in self._vars.values() if v.persistable]

    def __repr__(self):
        return f"<Block {self.idx}: {len(self.ops)} ops>"


class Program:
    """Inspectable program over a traced jaxpr (see module docstring).

    >>> prog = Program.from_callable(fn, example_x)
    >>> [op.type for op in prog.global_block().ops]
    >>> print(prog)          # reference-style IR listing
    """

    def __init__(self):
        self.blocks: List[Block] = [Block(0)]
        self._jaxpr = None
        self._param_names: List[str] = []
        self._for_test = False

    # -- construction -----------------------------------------------------
    @classmethod
    def from_callable(cls, fn, *example_args,
                      param_names: Optional[Sequence[str]] = None,
                      **example_kwargs) -> "Program":
        """Trace ``fn`` abstractly and build the op-graph view. Example
        args may be arrays, Tensors, or ShapeDtypeStructs."""
        from ..core.tensor import Tensor

        # only tensor-like leaves trace; python scalars/bools/strings stay
        # STATIC, exactly like StaticFunction's guard-key args — an
        # `if flag:` signature must build, not TracerBoolConvert. The
        # pytree flatten covers every registered container (namedtuples,
        # custom nodes), not just list/tuple/dict.
        def is_traced(v):
            return isinstance(v, (Tensor, jax.Array, np.ndarray)) or \
                type(v).__name__ == "ShapeDtypeStruct"

        leaves, treedef = jax.tree_util.tree_flatten(
            (list(example_args), dict(example_kwargs)),
            is_leaf=lambda v: isinstance(v, Tensor))
        traced_idx = [i for i, l in enumerate(leaves) if is_traced(l)]
        vals = [leaves[i]._value if isinstance(leaves[i], Tensor)
                else leaves[i] for i in traced_idx]

        def pure(*tvals):
            from ..autograd import no_grad

            new_leaves = list(leaves)
            for i, v in zip(traced_idx, tvals):
                new_leaves[i] = Tensor(v)
            a, k = jax.tree_util.tree_unflatten(treedef, new_leaves)
            with no_grad():
                out = fn(*a, **k)
            return jax.tree_util.tree_map(
                lambda t: t._value if isinstance(t, Tensor) else t, out,
                is_leaf=lambda v: isinstance(v, Tensor))

        closed = jax.make_jaxpr(pure)(*vals)
        return cls.from_jaxpr(closed, param_names=param_names)

    @classmethod
    def from_jaxpr(cls, closed_jaxpr,
                   param_names: Optional[Sequence[str]] = None) -> "Program":
        prog = cls()
        prog._jaxpr = closed_jaxpr
        prog._param_names = list(param_names or [])
        jaxpr = closed_jaxpr.jaxpr
        blk = prog.blocks[0]
        names: Dict[int, str] = {}
        counter = [0]
        lit_counter = [0]

        def namer(v):
            if type(v).__name__ == "Literal":
                # every literal gets a var-table entry with a unique name
                # (the reference invariant: every op input resolves to a
                # block var); scalars show their value for readability
                if id(v) in names:
                    return names[id(v)]
                if np.ndim(v.val) == 0:
                    n = f"lit_{lit_counter[0]}({v.val!r})"
                else:
                    n = f"lit_{lit_counter[0]}(<array>)"
                lit_counter[0] += 1
                names[id(v)] = n
                aval = getattr(v, "aval", None)
                blk._vars[n] = _VarView(n, aval)   # const, NOT a parameter
                return n
            if id(v) not in names:
                names[id(v)] = f"_t{counter[0]}"
                counter[0] += 1
            return names[id(v)]

        pn = list(param_names or [])
        for i, v in enumerate(jaxpr.invars):
            name = pn[i] if i < len(pn) else f"x{i}"
            names[id(v)] = name
            blk._vars[name] = _VarView(name, v.aval,
                                       persistable=i < len(pn))
        for v in jaxpr.constvars:
            n = namer(v)
            blk._vars[n] = _VarView(n, v.aval, persistable=True)
        for eqn in jaxpr.eqns:
            op = Operator(eqn, namer)
            blk.ops.append(op)
            for v, n in zip(eqn.outvars, op.output_names):
                blk._vars[n] = _VarView(n, v.aval)
        prog._out_names = [namer(v) for v in jaxpr.outvars]
        return prog

    # -- reference API surface --------------------------------------------
    def global_block(self) -> Block:
        return self.blocks[0]

    @property
    def num_blocks(self) -> int:
        return len(self.blocks)

    def all_parameters(self) -> List[_VarView]:
        return self.global_block().all_parameters()

    def clone(self, for_test: bool = False) -> "Program":
        p = (Program.from_jaxpr(self._jaxpr,
                                param_names=self._param_names)
             if self._jaxpr is not None else Program())
        p._for_test = for_test
        return p

    def op_types(self) -> List[str]:
        return [op.type for op in self.global_block().ops]

    def __str__(self):
        blk = self.global_block()
        lines = [f"{{ // block {blk.idx}"]
        for v in blk._vars.values():
            lines.append(f"    {v!r}")
        for op in blk.ops:
            lines.append(f"    {op!r}")
        lines.append(f"    return ({', '.join(self._out_names)})"
                     if getattr(self, '_out_names', None) else "    return ()")
        lines.append("}")
        return "\n".join(lines)

    __repr__ = __str__
