"""paddle.static.nn — legacy functional layer API over the eager/capture
ops (parity: python/paddle/static/nn/__init__.py __all__). Each function is
the reference's static layer expressed against nn.functional; parameters
are created eagerly (capture mode treats them as constants closed over)."""
from __future__ import annotations

import numpy as np

__all__ = [
    "fc", "batch_norm", "bilinear_tensor_product", "embedding", "case",
    "cond", "static_pylayer", "conv2d", "conv2d_transpose", "conv3d",
    "conv3d_transpose", "data_norm", "deform_conv2d", "group_norm",
    "instance_norm", "layer_norm", "nce", "prelu", "py_func", "row_conv",
    "spectral_norm", "switch_case", "while_loop", "sparse_embedding",
    "sequence_conv", "sequence_softmax", "sequence_pool",
    "sequence_first_step", "sequence_last_step", "sequence_expand",
]


def _param(shape, dtype="float32", attr=None, is_bias=False, ones=False):
    import paddle_tpu as paddle

    init = None
    if ones:  # norm scales default to 1 (reference LayerHelper behavior)
        from paddle_tpu.nn import initializer as _I

        init = _I.Constant(1.0)
    return paddle.create_parameter(list(shape), dtype, attr=attr,
                                   is_bias=is_bias,
                                   default_initializer=init)


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    """parity: static.nn.fc — flatten trailing dims, linear, optional act."""
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F

    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = []
    for xi in xs:
        shape = xi.shape
        flat = int(np.prod(shape[num_flatten_dims:]))
        v = paddle.reshape(xi, list(shape[:num_flatten_dims]) + [flat])
        w = _param([flat, size], attr=weight_attr)
        outs.append(paddle.matmul(v, w))
    out = outs[0]
    for o in outs[1:]:
        out = out + o
    if bias_attr is not False:
        out = out + _param([size], attr=bias_attr, is_bias=True)
    if activation:
        out = getattr(F, activation)(out)
    return out


def embedding(input, size, is_sparse=False, is_distributed=False,  # noqa: A002
              padding_idx=None, param_attr=None, dtype="float32"):
    import paddle_tpu.nn.functional as F

    w = _param(list(size), dtype, attr=param_attr)
    return F.embedding(input, w, padding_idx=padding_idx), w


def sparse_embedding(input, size, padding_idx=None, is_test=False,  # noqa: A002
                     entry=None, table_class="MemorySparseTable",
                     param_attr=None, dtype="float32", slot=None):
    """parity: static.nn.sparse_embedding (PS sparse table) — dense
    embedding here; the PS architecture is a documented skip (PARITY D19)."""
    return embedding(input, size, padding_idx=padding_idx,
                     param_attr=param_attr, dtype=dtype)[0]


def batch_norm(input, act=None, is_test=False, momentum=0.9,  # noqa: A002
               epsilon=1e-5, param_attr=None, bias_attr=None,
               data_layout="NCHW", **kwargs):
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F

    C = input.shape[1 if data_layout == "NCHW" else -1]
    layer = nn.BatchNorm(C, momentum=momentum, epsilon=epsilon)
    if is_test:
        layer.eval()
    out = layer(input)
    if act:
        out = getattr(F, act)(out)
    return out


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,  # noqa: A002
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    import paddle_tpu.nn.functional as F

    shape = input.shape[begin_norm_axis:]
    w = _param(shape, attr=param_attr, ones=True) if scale else None
    b = _param(shape, attr=bias_attr, is_bias=True) if shift else None
    out = F.layer_norm(input, shape, w, b, epsilon)
    if act:
        out = getattr(F, act)(out)
    return out


def group_norm(input, groups, epsilon=1e-5, param_attr=None,  # noqa: A002
               bias_attr=None, act=None, data_layout="NCHW", name=None):
    import paddle_tpu.nn.functional as F

    C = input.shape[1 if data_layout == "NCHW" else -1]
    w = _param([C], attr=param_attr, ones=True)
    b = _param([C], attr=bias_attr, is_bias=True)
    out = F.group_norm(input, groups, epsilon, w, b,
                       data_format=data_layout)
    if act:
        out = getattr(F, act)(out)
    return out


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,  # noqa: A002
                  name=None):
    import paddle_tpu.nn.functional as F

    C = input.shape[1]
    w = _param([C], attr=param_attr, ones=True)
    b = _param([C], attr=bias_attr, is_bias=True)
    return F.instance_norm(input, weight=w, bias=b, eps=epsilon)


def data_norm(input, act=None, epsilon=1e-5, param_attr=None,  # noqa: A002
              data_layout="NCHW", **kwargs):
    """parity: static.nn.data_norm — normalization by accumulated batch
    statistics; eager form normalizes with the current batch stats."""
    import paddle_tpu as paddle

    mean = paddle.mean(input, axis=0, keepdim=True)
    var = paddle.var(input, axis=0, keepdim=True)
    return (input - mean) / paddle.sqrt(var + epsilon)


def conv2d(input, num_filters, filter_size, stride=1, padding=0,  # noqa: A002
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           act=None, data_format="NCHW", **kwargs):
    import paddle_tpu.nn.functional as F

    C = input.shape[1 if data_format == "NCHW" else -1]
    ks = filter_size if isinstance(filter_size, (list, tuple)) \
        else (filter_size, filter_size)
    w = _param([num_filters, C // groups, *ks], attr=param_attr)
    b = _param([num_filters], attr=bias_attr, is_bias=True) \
        if bias_attr is not False else None
    out = F.conv2d(input, w, b, stride, padding, dilation, groups,
                   data_format)
    if act:
        out = getattr(F, act)(out)
    return out


def conv3d(input, num_filters, filter_size, stride=1, padding=0,  # noqa: A002
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           act=None, data_format="NCDHW", **kwargs):
    import paddle_tpu.nn.functional as F

    C = input.shape[1 if data_format == "NCDHW" else -1]
    ks = filter_size if isinstance(filter_size, (list, tuple)) \
        else (filter_size,) * 3
    w = _param([num_filters, C // groups, *ks], attr=param_attr)
    b = _param([num_filters], attr=bias_attr, is_bias=True) \
        if bias_attr is not False else None
    out = F.conv3d(input, w, b, stride, padding, dilation, groups,
                   data_format)
    if act:
        out = getattr(F, act)(out)
    return out


def conv2d_transpose(input, num_filters, output_size=None,  # noqa: A002
                     filter_size=None, padding=0, stride=1, dilation=1,
                     groups=1, param_attr=None, bias_attr=None, act=None,
                     data_format="NCHW", **kwargs):
    import paddle_tpu.nn.functional as F

    C = input.shape[1 if data_format == "NCHW" else -1]
    ks = filter_size if isinstance(filter_size, (list, tuple)) \
        else (filter_size, filter_size)
    w = _param([C, num_filters // groups, *ks], attr=param_attr)
    b = _param([num_filters], attr=bias_attr, is_bias=True) \
        if bias_attr is not False else None
    out = F.conv2d_transpose(input, w, b, stride, padding, groups=groups,
                             dilation=dilation, output_size=output_size,
                             data_format=data_format)
    if act:
        out = getattr(F, act)(out)
    return out


def conv3d_transpose(input, num_filters, output_size=None,  # noqa: A002
                     filter_size=None, padding=0, stride=1, dilation=1,
                     groups=1, param_attr=None, bias_attr=None, act=None,
                     data_format="NCDHW", **kwargs):
    import paddle_tpu.nn.functional as F

    C = input.shape[1 if data_format == "NCDHW" else -1]
    ks = filter_size if isinstance(filter_size, (list, tuple)) \
        else (filter_size,) * 3
    w = _param([C, num_filters // groups, *ks], attr=param_attr)
    b = _param([num_filters], attr=bias_attr, is_bias=True) \
        if bias_attr is not False else None
    out = F.conv3d_transpose(input, w, b, stride, padding, groups=groups,
                             dilation=dilation, output_size=output_size,
                             data_format=data_format)
    if act:
        out = getattr(F, act)(out)
    return out


def deform_conv2d(x, offset, mask, num_filters, filter_size, stride=1,
                  padding=0, dilation=1, groups=1, deformable_groups=1,
                  im2col_step=1, param_attr=None, bias_attr=None, name=None):
    from ..vision.ops import deform_conv2d as dc

    C = x.shape[1]
    ks = filter_size if isinstance(filter_size, (list, tuple)) \
        else (filter_size, filter_size)
    w = _param([num_filters, C // groups, *ks], attr=param_attr)
    b = _param([num_filters], attr=bias_attr, is_bias=True) \
        if bias_attr is not False else None
    return dc(x, offset, w, bias=b, stride=stride, padding=padding,
              dilation=dilation, deformable_groups=deformable_groups,
              groups=groups, mask=mask)


def bilinear_tensor_product(x, y, size, act=None, param_attr=None,
                            bias_attr=None, name=None):
    import paddle_tpu.nn.functional as F

    w = _param([size, x.shape[-1], y.shape[-1]], attr=param_attr)
    b = _param([size], attr=bias_attr, is_bias=True) \
        if bias_attr is not False else None
    out = F.bilinear(x, y, w, b)
    if act:
        out = getattr(F, act)(out)
    return out


def prelu(x, mode="all", param_attr=None, data_format="NCHW", name=None):
    import paddle_tpu.nn.functional as F

    n = {"all": 1, "channel": x.shape[1], "element":
         int(np.prod(x.shape[1:]))}[mode]
    w = _param([n], attr=param_attr)
    return F.prelu(x, w, data_format=data_format)


def nce(input, label, num_total_classes, sample_weight=None,  # noqa: A002
        param_attr=None, bias_attr=None, num_neg_samples=None, name=None,
        sampler="uniform", custom_dist=None, seed=0, is_sparse=False):
    """parity: static.nn.nce — noise-contrastive estimation loss over a
    sampled softmax (uniform negative sampling)."""
    import jax

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F

    D = input.shape[-1]
    K = num_neg_samples or 10
    w = _param([num_total_classes, D], attr=param_attr)
    b = _param([num_total_classes], attr=bias_attr, is_bias=True)
    from ..framework.random import next_key

    B = input.shape[0]
    neg = paddle.to_tensor(np.asarray(
        jax.random.randint(next_key(), (B, K), 0, num_total_classes),
        np.int32))
    pos_w = paddle.index_select(w, paddle.reshape(label, [-1]), axis=0)
    pos_b = paddle.index_select(b, paddle.reshape(label, [-1]), axis=0)
    pos_logit = paddle.sum(input * pos_w, axis=-1) + pos_b
    neg_w = paddle.index_select(w, paddle.reshape(neg, [-1]), axis=0)
    neg_b = paddle.index_select(b, paddle.reshape(neg, [-1]), axis=0)
    neg_logit = paddle.sum(
        paddle.reshape(neg_w, [B, K, D]) * paddle.unsqueeze(input, 1),
        axis=-1) + paddle.reshape(neg_b, [B, K])
    pos_loss = F.binary_cross_entropy_with_logits(
        pos_logit, paddle.ones_like(pos_logit), reduction="none")
    neg_loss = F.binary_cross_entropy_with_logits(
        neg_logit, paddle.zeros_like(neg_logit), reduction="none")
    return paddle.unsqueeze(pos_loss + paddle.sum(neg_loss, axis=-1), -1)


def row_conv(input, future_context_size, param_attr=None, act=None):  # noqa: A002
    """parity: static.nn.row_conv — lookahead row convolution over the time
    axis: out[t] = sum_{k=0..D} in[t+k] * w[k]."""
    import paddle_tpu as paddle

    D = future_context_size
    T = input.shape[1]
    w = _param([D + 1, input.shape[-1]], attr=param_attr)
    outs = []
    import paddle_tpu.nn.functional as F  # noqa: F401

    pad = paddle.zeros(list(input.shape[:1]) + [D] + list(input.shape[2:]))
    xp = paddle.concat([input, pad], axis=1)
    out = None
    for k in range(D + 1):
        seg = paddle.slice(xp, [1], [k], [k + T]) * w[k]
        out = seg if out is None else out + seg
    if act:
        out = getattr(F, act)(out)
    return out


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """parity: static.nn.spectral_norm — normalize weight by its largest
    singular value (power iteration)."""
    import paddle_tpu as paddle

    w = paddle.moveaxis(weight, dim, 0)
    mat = paddle.reshape(w, [w.shape[0], -1])
    v = paddle.to_tensor(
        np.random.default_rng(0).normal(size=(mat.shape[1],)
                                        ).astype(np.float32))
    for _ in range(max(1, power_iters)):
        u = paddle.mv(mat, v)
        u = u / (paddle.norm(u) + eps)
        v = paddle.mv(paddle.transpose(mat, [1, 0]), u)
        v = v / (paddle.norm(v) + eps)
    sigma = paddle.dot(u, paddle.mv(mat, v))
    return weight / sigma


# -- control flow (capture-compatible: python control flow over eager) ------
def cond(pred, true_fn=None, false_fn=None, name=None, return_names=None):
    """parity: static.nn.cond — in eager/capture mode the predicate value is
    concrete, so this is python control flow."""
    p = bool(np.asarray(pred._value)) if hasattr(pred, "_value") else \
        bool(pred)
    if p:
        return true_fn() if true_fn else None
    return false_fn() if false_fn else None


def case(pred_fn_pairs, default=None, name=None):
    for pred, fn in pred_fn_pairs:
        p = bool(np.asarray(pred._value)) if hasattr(pred, "_value") else \
            bool(pred)
        if p:
            return fn()
    return default() if default else None


def switch_case(branch_index, branch_fns, default=None, name=None):
    idx = int(np.asarray(branch_index._value)) if hasattr(
        branch_index, "_value") else int(branch_index)
    fns = dict(branch_fns) if not isinstance(branch_fns, dict) else branch_fns
    if idx in fns:
        return fns[idx]()
    return default() if default else None


def while_loop(cond_fn, body, loop_vars, is_test=False, name=None):
    """parity: static.nn.while_loop — host loop in eager; use
    jax.lax.while_loop inside jit-captured code for compiled loops."""
    vals = list(loop_vars)
    while True:
        c = cond_fn(*vals)
        if not bool(np.asarray(c._value) if hasattr(c, "_value") else c):
            break
        out = body(*vals)
        vals = list(out) if isinstance(out, (list, tuple)) else [out]
    return vals


def static_pylayer(forward_fn, inputs, backward_fn=None, name=None):
    """parity: static.nn.static_pylayer — PyLayer in static form."""
    from ..autograd.py_layer import PyLayer

    class _P(PyLayer):
        @staticmethod
        def forward(ctx, *args):
            return forward_fn(*args)

        @staticmethod
        def backward(ctx, *grads):
            if backward_fn is None:
                return grads
            return backward_fn(*grads)

    return _P.apply(*inputs)


# -- sequence ops (LoD sequences become padded [B, T, ...] + lengths) -------
def sequence_conv(input, num_filters, filter_size=3, **kwargs):  # noqa: A002
    """parity: static.nn.sequence_conv — context-window conv over time."""
    import paddle_tpu as paddle

    D = input.shape[-1]
    w = _param([filter_size * D, num_filters])
    T = input.shape[1]
    lo = (filter_size - 1) // 2
    hi = filter_size - 1 - lo  # asymmetric for even filter sizes
    zl = paddle.zeros(list(input.shape[:1]) + [lo] + [D])
    zr = paddle.zeros(list(input.shape[:1]) + [hi] + [D])
    xp = paddle.concat([zl, input, zr], axis=1)
    ctx = paddle.concat([paddle.slice(xp, [1], [k], [k + T])
                         for k in range(filter_size)], axis=-1)
    return paddle.matmul(ctx, w)


def sequence_softmax(input, use_cudnn=False, name=None):  # noqa: A002
    import paddle_tpu.nn.functional as F

    return F.softmax(input, axis=1)


def sequence_pool(input, pool_type, is_test=False, pad_value=0.0):  # noqa: A002
    import paddle_tpu as paddle

    pt = pool_type.lower()
    if pt == "sum":
        return paddle.sum(input, axis=1)
    if pt in ("average", "avg", "mean"):
        return paddle.mean(input, axis=1)
    if pt == "max":
        return paddle.max(input, axis=1)
    if pt == "sqrt":
        import math

        return paddle.sum(input, axis=1) / math.sqrt(input.shape[1])
    if pt == "first":
        return input[:, 0]
    if pt == "last":
        return input[:, -1]
    raise ValueError(f"sequence_pool: unknown pool_type {pool_type}")


def sequence_first_step(input):  # noqa: A002
    return sequence_pool(input, "first")


def sequence_last_step(input):  # noqa: A002
    return sequence_pool(input, "last")


def sequence_expand(x, y, ref_level=-1, name=None):
    """parity: static.nn.sequence_expand — tile x rows to match y's time
    dimension."""
    import paddle_tpu as paddle

    reps = y.shape[1] if y.ndim > 1 else 1
    return paddle.tile(paddle.unsqueeze(x, 1), [1, reps] + [1] * (x.ndim - 1))


from .compat import py_func  # noqa: E402,F401
