"""Plan / Job multi-program orchestration (the "new executor" surface).

Parity: the reference's static executor Plan/Job model —
paddle/fluid/framework/new_executor/interpreter (Plan = ordered Jobs, each
a program with a type and micro_batch_id; built by the pipeline scheduler
passes, run by StandaloneExecutor — python/paddle/base/executor.py:677
_ExecutorCache builds Plan([Job("default")])).

TPU-native re-design: a Job wraps one COMPILED jax program (any callable
over named arrays — jitted on first use) plus the names it consumes and
produces; a Plan is the ordered job list; StandaloneExecutor threads a
scope {name: array} through the jobs. This is the orchestration layer for
schedules that genuinely need several programs with host sequencing
(gradient-merge F-then-apply, eval/predict alternation, pipeline stages as
separate programs) — the single-program hot path stays one pjit.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

import jax

__all__ = ["Job", "Plan", "StandaloneExecutor", "build_gradient_merge_plan"]


class Job:
    """One schedulable program (parity: interpreter Job — type +
    micro_batch_id).

    ``fn`` is called POSITIONALLY with the scope values named by
    ``inputs`` (in order) and must return a tuple/list whose length equals
    ``outputs`` (a single bare return is treated as a 1-tuple).
    ``micro_batch_id`` >= 0 selects the micro-batch slice fed to this job
    for keys listed in ``sliced`` (the scheduler passes' microbatching).
    Keys in ``donate`` are buffer-donated to XLA and removed from the
    scope unless the job re-produces them via ``outputs``.
    """

    def __init__(self, fn: Callable, job_type: str = "default",
                 micro_batch_id: int = -1,
                 inputs: Optional[Sequence[str]] = None,
                 outputs: Optional[Sequence[str]] = None,
                 sliced: Sequence[str] = (), donate: Sequence[str] = ()):
        self._raw_fn = fn
        self.type = job_type
        self.micro_batch_id = micro_batch_id
        self.inputs = list(inputs or [])
        self.outputs = list(outputs or [])
        self.sliced = tuple(sliced)
        self.donate = tuple(donate)
        self._jitted = None

    def set_micro_batch_id(self, mb_id: int):
        self.micro_batch_id = mb_id

    def _compile(self, cache: Optional[dict] = None):
        if self._jitted is None:
            donate = tuple(self.inputs.index(k) for k in self.donate
                           if k in self.inputs)
            key = (self._raw_fn, donate)
            if cache is not None and key in cache:
                # jobs sharing one fn (per-micro-batch clones) share the
                # compiled program — micro_batch_id only changes host-side
                # slicing, not the trace
                self._jitted = cache[key]
            else:
                self._jitted = jax.jit(self._raw_fn, donate_argnums=donate)
                if cache is not None:
                    cache[key] = self._jitted
        return self._jitted


class Plan:
    """Ordered job list (parity: framework Plan(jobs,
    type_to_program))."""

    def __init__(self, jobs: List[Job], num_micro_batches: int = 1):
        self.jobs = list(jobs)
        self.num_micro_batches = num_micro_batches

    def job_types(self):
        return [j.type for j in self.jobs]


class StandaloneExecutor:
    """Threads a scope through the plan's jobs (parity:
    StandaloneExecutor.run — new_executor/standalone_executor.cc; feed by
    name, fetch by name)."""

    def __init__(self, place=None, plan: Optional[Plan] = None):
        self.place = place
        self.plan = plan
        self._jit_cache: dict = {}

    def run(self, feed: Dict[str, object],
            fetch_list: Optional[Sequence[str]] = None):
        scope = dict(feed)
        M = self.plan.num_micro_batches
        for job in self.plan.jobs:
            fn = job._compile(self._jit_cache)
            args = []
            for k in job.inputs:
                v = scope[k]
                if k in job.sliced and job.micro_batch_id >= 0:
                    if job.micro_batch_id >= M:
                        raise ValueError(
                            f"Plan: job micro_batch_id="
                            f"{job.micro_batch_id} out of range for "
                            f"num_micro_batches={M}")
                    B = v.shape[0]
                    if B % M:
                        raise ValueError(
                            f"Plan: sliced input '{k}' batch {B} is not "
                            f"divisible by num_micro_batches={M}")
                    mb = B // M
                    v = v[job.micro_batch_id * mb:
                          (job.micro_batch_id + 1) * mb]
                args.append(v)
            out = fn(*args)
            if not isinstance(out, (tuple, list)):
                out = (out,)
            if len(out) != len(job.outputs):
                raise ValueError(
                    f"Plan: job '{job.type}' returned {len(out)} values "
                    f"but declares outputs {job.outputs}")
            # drop only buffers that were actually donated: the key must be
            # a real input, and a sliced input donates only its slice (the
            # full scope array stays alive for the other micro-batches)
            for k in job.donate:
                if k in job.inputs and k not in job.sliced:
                    scope.pop(k, None)
            scope.update(dict(zip(job.outputs, out)))
        if fetch_list is None:
            return scope
        return [scope[k] for k in fetch_list]


def build_gradient_merge_plan(loss_and_grads_fn: Callable,
                              apply_fn: Callable,
                              num_micro_batches: int) -> Plan:
    """The GradientMergePass schedule as a Plan: one forward+backward job
    per micro-batch accumulating grads, then one optimizer-apply job
    (parity: passes/pipeline_scheduler_pass FThenB + gradient merge).

    loss_and_grads_fn(params, batch) -> (loss, grads);
    apply_fn(params, grads, opt_state) -> (params, opt_state).
    Scope keys: params, batch (sliced), opt_state, grads_acc, loss_acc;
    the optimizer job writes "loss" (merged mean) and resets
    grads_acc/loss_acc so the scope threads directly into the next step.
    Builder jobs do not donate (feeds are caller-owned); pass donate= on
    hand-built Jobs when the scope owns its buffers.
    """
    import jax.numpy as jnp

    def fwd_bwd(params, batch, grads_acc, loss_acc):
        loss, grads = loss_and_grads_fn(params, batch)
        acc = jax.tree_util.tree_map(
            lambda a, g: a + g.astype(a.dtype), grads_acc, grads)
        return acc, loss_acc + loss

    def apply(params, grads_acc, loss_acc, opt_state):
        mean_g = jax.tree_util.tree_map(
            lambda g: g / num_micro_batches, grads_acc)
        new_p, new_state = apply_fn(params, mean_g, opt_state)
        zero = jax.tree_util.tree_map(jnp.zeros_like, grads_acc)
        # report the merged mean loss and reset the accumulator so the
        # scope can thread straight into the next step
        return (new_p, new_state, zero, loss_acc / num_micro_batches,
                jnp.zeros_like(loss_acc))

    jobs = []
    for mb in range(num_micro_batches):
        jobs.append(Job(
            fwd_bwd, job_type="forward_backward", micro_batch_id=mb,
            inputs=["params", "batch", "grads_acc", "loss_acc"],
            outputs=["grads_acc", "loss_acc"], sliced=("batch",)))
    jobs.append(Job(
        apply, job_type="optimizer",
        inputs=["params", "grads_acc", "loss_acc", "opt_state"],
        outputs=["params", "opt_state", "grads_acc", "loss", "loss_acc"]))
    return Plan(jobs, num_micro_batches)
