"""paddle_tpu.static — static-graph-style entry points.

Parity: python/paddle/static/. In the TPU-native design there is no separate
Program IR: "static mode" IS jit capture (paddle_tpu.jit). This module keeps
the static API names working by delegating to the capture layer: InputSpec,
save/load_inference_model over exported StableHLO.
"""
from __future__ import annotations

from ..jit import InputSpec  # noqa: F401
from ..jit import load as _jit_load
from ..jit import save as _jit_save

__all__ = ["InputSpec", "save_inference_model", "load_inference_model",
           "default_main_program", "default_startup_program", "Program",
           "program_guard", "name_scope"]


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    layer = kwargs.get("layer")
    if layer is None:
        raise ValueError(
            "TPU-native save_inference_model exports a Layer: pass layer=... "
            "(or use paddle_tpu.jit.save)")
    _jit_save(layer, path_prefix, input_spec=feed_vars)


def load_inference_model(path_prefix, executor=None, **kwargs):
    return _jit_load(path_prefix)


class Program:
    """Vestigial Program object for API compatibility; capture replaces it."""

    def __init__(self):
        self._ops = []

    def global_block(self):
        return self

    def clone(self, for_test=False):
        return self


_main = Program()
_startup = Program()


def default_main_program():
    return _main


def default_startup_program():
    return _startup


class program_guard:
    def __init__(self, main_program=None, startup_program=None):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class name_scope:
    def __init__(self, prefix=None):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False
