"""paddle_tpu.static — static-graph-style entry points.

Parity: python/paddle/static/. In the TPU-native design there is no separate
Program IR: "static mode" IS jit capture (paddle_tpu.jit). This module keeps
the static API names working by delegating to the capture layer: InputSpec,
save/load_inference_model over exported StableHLO.
"""
from __future__ import annotations

from ..jit import InputSpec  # noqa: F401
from ..jit import load as _jit_load
from ..jit import save as _jit_save

__all__ = ["InputSpec", "save_inference_model", "load_inference_model",
           "default_main_program", "default_startup_program", "Program",
           "program_guard", "name_scope", "data", "Executor"]


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    layer = kwargs.get("layer")
    if layer is None:
        raise ValueError(
            "TPU-native save_inference_model exports a Layer: pass layer=... "
            "(or use paddle_tpu.jit.save)")
    _jit_save(layer, path_prefix, input_spec=feed_vars)


def load_inference_model(path_prefix, executor=None, **kwargs):
    return _jit_load(path_prefix)


from .program import Block, Operator, Program  # noqa: E402,F401


_main = Program()
_startup = Program()


def default_main_program():
    return _main


def default_startup_program():
    return _startup


class program_guard:
    def __init__(self, main_program=None, startup_program=None):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class name_scope:
    def __init__(self, prefix=None):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class _DataPlaceholder:
    """Returned by static.data — a named InputSpec that eager/capture code
    treats as an input slot."""

    def __init__(self, name, shape, dtype):
        self.name = name
        self.spec = InputSpec(shape, dtype or "float32", name)
        self.shape = list(shape)
        self.dtype = self.spec.dtype


def data(name, shape, dtype=None, lod_level=0):
    """parity: paddle.static.data — declares a program input."""
    return _DataPlaceholder(name, shape, dtype)


class Executor:
    """parity: paddle.base.executor.Executor (executor.py:1237) — in the
    TPU-native design a 'program' is a python callable (usually a
    to_static-captured function or a loaded TranslatedLayer); run() feeds a
    dict keyed by static.data names and fetches outputs."""

    def __init__(self, place=None):
        self.place = place

    @staticmethod
    def _input_names(program):
        """Resolve the program's input-argument names: named InputSpecs if
        the capture carries them, else the wrapped function's signature
        (reference Executor matches feeds by name — executor.py _feed_data)."""
        import inspect

        sf = getattr(program, "_static_function", None)
        if sf is None and hasattr(program, "_fn"):  # bare StaticFunction
            sf = program
        specs = getattr(sf, "_input_spec", None)
        if specs and all(getattr(s, "name", None) for s in specs):
            return [s.name for s in specs]
        fn = getattr(sf, "_fn", None) or getattr(program, "forward", program)
        try:
            sig = inspect.signature(fn)
        except (TypeError, ValueError):
            return None
        names = [p.name for p in sig.parameters.values()
                 if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
                 and p.name != "self"]
        return names or None

    def run(self, program=None, feed=None, fetch_list=None, **kwargs):
        import numpy as _np

        from ..core.tensor import Tensor as _T
        from ..ops.creation import to_tensor as _to

        if program is None or isinstance(program, Program):
            return []  # vestigial startup-program run
        feed = dict(feed or {})
        names = self._input_names(program)
        if names is not None and feed:
            unknown = [k for k in feed if k not in names]
            if unknown:
                raise ValueError(
                    f"Executor.run: feed names {unknown} do not match "
                    f"program inputs {names}")
            # bind by keyword: a missing required input raises the
            # program's own clear TypeError instead of mis-binding
            outs = program(**{n: _to(feed[n]) for n in feed})
        else:
            if len(feed) > 1:
                raise ValueError(
                    "Executor.run: cannot resolve feed order by name for "
                    "this program; pass a single feed or a program captured "
                    "with named InputSpecs")
            outs = program(*[_to(v) for v in feed.values()])
        seq = outs if isinstance(outs, (list, tuple)) else [outs]
        return [_np.asarray(o._value) if isinstance(o, _T) else _np.asarray(o)
                for o in seq]

    def close(self):
        pass

from .plan import (  # noqa: E402,F401
    Job, Plan, StandaloneExecutor, build_gradient_merge_plan,
)
__all__ += ["Job", "Plan", "StandaloneExecutor",
            "build_gradient_merge_plan"]


from .compat import *  # noqa: E402,F401,F403
from .compat import __all__ as _compat_all  # noqa: E402
from . import nn  # noqa: E402,F401
from .. import amp  # noqa: E402,F401
__all__ += _compat_all
