"""paddle_tpu.jit — program capture over jax.jit.

Parity surface: python/paddle/jit/ (to_static — api.py:197; SOT bytecode JIT
under jit/sot/; save/load TranslatedLayer). TPU-native re-design: instead of a
CPython bytecode translator building a PIR program, capture IS jax tracing —
``to_static`` wraps a Layer/function into a pure jax function over its
parameter pytree, jit-compiles per input-signature (guard-based retrace =
one cache entry per (shapes, dtypes, static-arg) key, the analogue of SOT's
guard/compile_cache — jit/sot/symbolic/compile_cache.py), and re-enters the
eager autograd tape through one fused GradNode whose vjp is the compiled
backward (so ``loss.backward()`` through a captured program works, the
analogue of the reference's pir_run_program op —
python/paddle/jit/dy2static/pir_partial_program.py:555,630).

Buffer state (BatchNorm running stats) threads through capture: mutations
land on the bound traced values (framework/capture.py), ride out of the
jitted program as extra outputs, and are committed back to the layer's
buffers after each call — so ``to_static(model)`` training matches eager.
"""
from __future__ import annotations

import functools
import time
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .. import observability as _obs
from ..autograd import no_grad
from ..observability import flight_recorder as _flight
from ..observability import goodput as _goodput
from ..observability import trace_span
from ..observability.catalog import instrument as _instrument
from ..core.tensor import Tensor
from ..framework import dtype as dtypes
from ..framework.random import next_key, rng_context
from ..nn.layer.layers import Layer
from ..ops.dispatch import apply
from .branch_capture import GraphBreak as _BranchGraphBreak

__all__ = ["to_static", "InputSpec", "save", "load", "not_to_static",
           "ignore_module", "enable_to_static", "TranslatedLayer",
           "BuildStrategy", "segment_scope", "cache"]

from . import cache  # noqa: E402  (persistent compile-artifact store —
# measured-not-traced products like the MoE gmm tiling winners survive
# the process; see jit/cache.py)

from .segments import segment_scope  # noqa: E402  (public: eager code can
# opt into lazy-segment batching directly — ops defer into cached compiled
# segments, any .item()/numpy() materializes; avoids per-op dispatch and
# compile storms through a remote-attached chip)

_to_static_enabled = True

# compile-path telemetry (no-ops until FLAGS_obs_enabled; names in
# observability.catalog)
_M_JIT_HITS = _instrument("jit_cache_hits_total")
_M_JIT_MISSES = _instrument("jit_cache_misses_total")
_M_JIT_COMPILE = _instrument("jit_compile_seconds")


class BuildStrategy:
    """Capture-behavior knobs (parity surface: paddle.static.BuildStrategy
    as accepted by jit.to_static — api.py:197).

    ``allow_graph_break`` (default True): when tracing fails on
    data-dependent Python control flow (``if tensor.item() > 0:`` — a jax
    ConcretizationTypeError), run that input signature SEGMENT-COMPILED
    (jit/segments.py: ops defer into cached jitted segments, the break
    itself runs eagerly, autograd composes across segments) and cache
    the decision — the reference SOT's compile-prefix/resume-after-break
    fallback (jit/sot/.../eval_frame_callback.py:54). False = re-raise
    (the reference's full_graph=True strictness).
    """

    def __init__(self, allow_graph_break: bool = True):
        self.allow_graph_break = allow_graph_break


def enable_to_static(flag: bool):
    global _to_static_enabled
    _to_static_enabled = flag


class InputSpec:
    """parity: paddle.static.InputSpec."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = list(shape)
        self.dtype = dtypes.convert_dtype(dtype)
        self.name = name
        self.stop_gradient = stop_gradient

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype.name})"

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, tensor.dtype, name)


def _guard_key(args, kwargs):
    parts = []

    def walk(o):
        if isinstance(o, Tensor):
            parts.append(("T", tuple(o._value.shape), str(o._value.dtype)))
        elif isinstance(o, (list, tuple)):
            # the container TYPE is part of the guard: two namedtuple
            # classes (different field orders) with identical tensor
            # layouts must not share a compiled program
            parts.append(("L", type(o), len(o)))
            for e in o:
                walk(e)
        elif isinstance(o, dict):
            parts.append(("D", tuple(sorted(o))))
            for k in sorted(o):
                walk(o[k])
        elif isinstance(o, np.ndarray):
            parts.append(("A", o.tobytes()))
        else:
            parts.append(("S", o))

    walk(args)
    walk(kwargs)
    return tuple(parts)


def _split_tensors(obj, acc):
    """Replace Tensors with index placeholders; return skeleton."""
    if isinstance(obj, Tensor):
        acc.append(obj)
        return ("__tensor__", len(acc) - 1)
    if isinstance(obj, tuple) and hasattr(obj, "_fields"):  # namedtuple
        return type(obj)(*(_split_tensors(e, acc) for e in obj))
    if isinstance(obj, (list, tuple)):
        return type(obj)(_split_tensors(e, acc) for e in obj)
    if isinstance(obj, dict):
        # sorted-key order must match _guard_key, so two calls with the same
        # keys in different insertion order share one compile cache entry
        # with identical tensor slot assignment
        return {k: _split_tensors(obj[k], acc) for k in sorted(obj)}
    return obj


def _rebuild(skel, vals, wrap):
    if isinstance(skel, tuple) and len(skel) == 2 and skel[0] == "__tensor__":
        return wrap(vals[skel[1]])
    if isinstance(skel, tuple) and hasattr(skel, "_fields"):  # namedtuple
        return type(skel)(*(_rebuild(e, vals, wrap) for e in skel))
    if isinstance(skel, (list, tuple)) and not (
        isinstance(skel, tuple) and len(skel) == 2 and skel[0] == "__tensor__"
    ):
        return type(skel)(_rebuild(e, vals, wrap) for e in skel)
    if isinstance(skel, dict):
        return {k: _rebuild(v, vals, wrap) for k, v in skel.items()}
    return skel


_GRAPH_BREAK_ERRORS = tuple(
    e for e in (
        getattr(jax.errors, "ConcretizationTypeError", None),
        getattr(jax.errors, "TracerArrayConversionError", None),
        getattr(jax.errors, "TracerBoolConversionError", None),
        getattr(jax.errors, "TracerIntegerConversionError", None),
    ) if e is not None)


class StaticFunction:
    """Guard-cached jit wrapper around a function or Layer.forward."""

    def __init__(self, function: Callable, layer: Optional[Layer] = None,
                 input_spec=None, full_graph=True, backend=None,
                 build_strategy: Optional[BuildStrategy] = None):
        self._fn = function
        self._layer = layer
        self._input_spec = input_spec
        self._cache = {}
        self._build_strategy = build_strategy or BuildStrategy()
        self._segment_keys = set()  # graph-broke: segment-compiled mode
        self._warned_break = False
        # observability: compiles = traced whole-graph programs;
        # cond_branches = Python ifs converted to lax.cond; eager_calls =
        # uncacheable-signature fallbacks; segment_runs = calls executed
        # in segment-compiled mode; segments = compiled-segment
        # executions; segment_compiles = segments that newly compiled
        self._stats = {"compiles": 0, "cond_branches": 0, "eager_calls": 0,
                       "segment_runs": 0, "segments": 0,
                       "segment_compiles": 0}
        functools.update_wrapper(self, function)

    @property
    def forward(self):
        return self

    def concrete_program(self):
        return [e["jitted"] for e in self._cache.values()]

    def program(self, *example_args, **example_kwargs):
        """Op-graph view of this function traced at the example signature
        (reference ConcreteProgram.main_program): a static.Program whose
        Operators are the jaxpr equations — layer parameters appear as
        persistable consts. Inspection-only (passes belong to XLA)."""
        from ..static.program import Program

        return Program.from_callable(self._fn, *example_args,
                                     **example_kwargs)

    def _build(self, skel_args, skel_kwargs, n_args, out_box):
        from ..framework.capture import capture_buffer_updates
        from .branch_capture import capture_branches, combine_tensor_leaves

        layer = self._layer
        fn = self._fn
        stats = self._stats

        def pure(params, bufs, key_data, *arg_vals):
            key = jax.random.wrap_key_data(key_data)
            wrap = lambda v: Tensor(v, stop_gradient=True)

            def body():
                # re-runnable per branch path: state binding and the RNG
                # stream both reset at entry, so every arm of a captured
                # lax.cond sees identical starting state
                args = _rebuild(skel_args, arg_vals, wrap)
                kwargs = _rebuild(skel_kwargs, arg_vals, wrap)
                new_bufs = {}
                with rng_context(key), no_grad():
                    if layer is not None:
                        # buffer mutations (BN running stats) land on the
                        # bound traced values and ride out as extra outputs,
                        # so to_static(model) trains running stats correctly
                        with layer.bind_state(params, bufs), \
                                capture_buffer_updates():
                            out = fn(*args, **kwargs)
                            new_bufs = {k: b._value
                                        for k, b in layer.named_buffers()}
                    else:
                        out = fn(*args, **kwargs)
                tensors: List[Tensor] = []
                skel_out = _split_tensors(out, tensors)
                return skel_out, [t._value for t in tensors], new_bufs

            (skel_out, vals, new_bufs), n_cond = capture_branches(
                body, combine_tensor_leaves)
            stats["compiles"] += 1
            stats["cond_branches"] += n_cond
            out_box["skel"] = skel_out
            out_box["n_real"] = len(vals)
            out_box["buf_names"] = sorted(new_bufs)
            return tuple(vals) + tuple(
                new_bufs[k] for k in out_box["buf_names"])

        return jax.jit(pure)

    def __call__(self, *args, **kwargs):
        if not _to_static_enabled:
            return self._fn(*args, **kwargs)
        try:
            # training mode is part of the guard: train/eval trace different
            # programs (BN batch-vs-running stats, dropout)
            mode = self._layer.training if self._layer is not None else None
            key = (mode, _guard_key(args, kwargs))
            hash(key)
        except TypeError:
            key = None  # unhashable guard state → uncacheable: run eager
        if key is None:             # unhashable guard state: uncacheable
            self._stats["eager_calls"] += 1
            return self._fn(*args, **kwargs)
        if key in self._segment_keys:
            return self._run_segmented(args, kwargs)
        arg_tensors: List[Tensor] = []
        skel_args = _split_tensors(args, arg_tensors)
        skel_kwargs = _split_tensors(kwargs, arg_tensors)
        entry = self._cache.get(key)
        fresh = entry is None
        if fresh:
            _M_JIT_MISSES.inc()
            out_box = {}
            jitted = self._build(skel_args, skel_kwargs, len(arg_tensors), out_box)
            entry = {"jitted": jitted, "out_box": out_box}
            self._cache[key] = entry
        else:
            _M_JIT_HITS.inc()
        jitted = entry["jitted"]
        out_box = entry["out_box"]

        if self._layer is not None:
            named_p = list(self._layer.named_parameters())
            bufs = {k: b._value for k, b in self._layer.named_buffers()}
            pnames = [k for k, _ in named_p]
            ptensors = [p for _, p in named_p]
        else:
            pnames, ptensors, bufs = [], [], {}

        key_data = jax.random.key_data(next_key())

        def runner(pvals, avals):
            params = dict(zip(pnames, pvals))
            return jitted(params, bufs, key_data, *avals)

        try:
            fn_name = getattr(self._fn, "__name__", "fn")
            if fresh and _obs.enabled():
                # a fresh cache entry's first run traces + compiles: the
                # observed duration IS the compile cost (steady-state runs
                # take the cached-program path below untimed)
                t0 = time.perf_counter()
                with trace_span("jit.compile", fn=fn_name):
                    outs = apply("jit::" + fn_name,
                                 lambda pvals, avals: runner(pvals, avals),
                                 list(ptensors), list(arg_tensors))
                dt = time.perf_counter() - t0
                _M_JIT_COMPILE.observe(dt)
                _goodput.account("compile", dt)
                _flight.record("compile", fn=fn_name,
                               seconds=round(dt, 6))
            else:
                outs = apply("jit::" + fn_name,
                             lambda pvals, avals: runner(pvals, avals),
                             list(ptensors), list(arg_tensors))
        except _GRAPH_BREAK_ERRORS + (_BranchGraphBreak,) as e:
            # data-dependent Python control flow the branch-capture oracle
            # could not convert to lax.cond (int/float/item concretization,
            # mismatched arm structures, tensor while-loops, >MAX depth) —
            # the reference's SOT would break the frame here; we fall back
            # to eager for this signature and cache the decision
            if not self._build_strategy.allow_graph_break:
                raise
            self._cache.pop(key, None)
            self._segment_keys.add(key)
            if not self._warned_break:
                self._warned_break = True
                import warnings
                warnings.warn(
                    f"to_static({getattr(self._fn, '__name__', 'fn')}): "
                    f"graph break ({type(e).__name__}: {e}) — this input "
                    "signature now runs SEGMENT-COMPILED: ops between "
                    "value materializations execute as cached jitted "
                    "segments, the break itself runs eagerly (the SOT "
                    "subgraph fallback). Scalar-tensor ifs with matching "
                    "arms stay whole-graph automatically; "
                    "BuildStrategy(allow_graph_break=False) makes this an "
                    "error.", stacklevel=2)
            return self._run_segmented(args, kwargs)
        if not isinstance(outs, tuple):
            outs = (outs,)
        n_real = out_box.get("n_real", len(outs))
        buf_names = out_box.get("buf_names", [])
        if buf_names and self._layer is not None:
            named_b = dict(self._layer.named_buffers())
            with no_grad():
                for k, t in zip(buf_names, outs[n_real:]):
                    if k in named_b:
                        named_b[k]._replace_value(t._value)
        wrapped = _rebuild(out_box["skel"], list(outs[:n_real]), lambda t: t)
        return wrapped

    def _run_segmented(self, args, kwargs):
        """Graph-broken path: re-execute the python (so value-dependent
        control flow is exact) with every op deferred into cached compiled
        segments — jit/segments.py, the reference SOT's
        compile-prefix/resume-after-break semantics in trace-based form."""
        from .segments import segment_scope

        with segment_scope() as rec:
            out = self._fn(*args, **kwargs)
        self._stats["segment_runs"] += 1
        self._stats["segments"] += rec.flushes
        self._stats["segment_compiles"] += rec.compiles
        return out


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, full_graph=False, **kwargs):
    """paddle.jit.to_static parity (api.py:197). ``full_graph=False`` (the
    reference default — SOT mode) permits graph-break fallback to eager;
    ``full_graph=True`` makes tracing failures raise. An explicit
    ``build_strategy`` overrides."""

    if isinstance(build_strategy, BuildStrategy):
        bs = build_strategy
    else:
        bs = BuildStrategy(allow_graph_break=not full_graph)

    def decorate(obj):
        if isinstance(obj, Layer):
            static_fwd = StaticFunction(obj.forward, layer=obj,
                                        input_spec=input_spec,
                                        build_strategy=bs)
            obj.forward = static_fwd
            obj._static_function = static_fwd
            return obj
        layer = getattr(obj, "__self__", None)
        if isinstance(layer, Layer):
            return StaticFunction(obj, layer=layer, input_spec=input_spec,
                                  build_strategy=bs)
        return StaticFunction(obj, input_spec=input_spec, build_strategy=bs)

    if function is None:
        return decorate
    return decorate(function)


def not_to_static(fn):
    fn._not_to_static = True
    return fn


def ignore_module(modules):
    pass


# ---------------------------------------------------------------------------
# save / load — exported StableHLO + weights (the inference path; parity:
# paddle.jit.save / TranslatedLayer, reference jit/translated_layer.py; the
# serialized artifact is the analogue of the PIR model format,
# fluid/pir/serialize_deserialize)
# ---------------------------------------------------------------------------
def save(layer, path, input_spec=None, **configs):
    if input_spec is None and getattr(layer, "_static_function", None):
        raise ValueError("input_spec is required to export")
    specs = input_spec or []
    example_args = []
    for spec in specs:
        if isinstance(spec, InputSpec):
            shape = [1 if s in (None, -1) else int(s) for s in spec.shape]
            example_args.append(jnp.zeros(shape, spec.dtype.np_dtype))
        elif isinstance(spec, Tensor):
            example_args.append(spec._value)
        else:
            example_args.append(jnp.asarray(spec))

    params, bufs = layer.functional_state() if isinstance(layer, Layer) else ({}, {})

    def pure(params, bufs, *arg_vals):
        wrap = lambda v: Tensor(v, stop_gradient=True)
        args = [wrap(v) for v in arg_vals]
        with no_grad():
            if isinstance(layer, Layer):
                was_training = layer.training
                layer.eval()
                try:
                    with layer.bind_state(params, bufs):
                        fwd = layer.forward
                        if isinstance(fwd, StaticFunction):
                            fwd = fwd._fn
                        out = fwd(*args)
                finally:
                    if was_training:
                        layer.train()
            else:
                out = layer(*args)
        tensors: List[Tensor] = []
        _split_tensors(out, tensors)
        return tuple(t._value for t in tensors)

    jitted = jax.jit(pure)
    exported = jax.export.export(jitted)(params, bufs, *example_args)
    write_artifact(path, exported, params, bufs)


def write_artifact(path: str, exported, params_tree, buffers_tree):
    """THE writer of the ``.pdmodel``/``.pdiparams`` artifact pair —
    shared by :func:`save` and model-level exporters
    (llama.export_for_inference), so the format :func:`load` parses has
    exactly one producer. Param trees may be nested (int8 exports carry
    {"q","s"} leaves)."""
    import pickle

    from ..framework.io import _to_serializable

    with open(path + ".pdmodel", "wb") as f:
        f.write(exported.serialize())
    wrap = lambda v: v if isinstance(v, Tensor) else Tensor(
        v, stop_gradient=True)
    is_leaf = lambda v: isinstance(v, Tensor)   # Tensor is a pytree node
    with open(path + ".pdiparams", "wb") as f:
        pickle.dump(_to_serializable(
            {"params": jax.tree_util.tree_map(wrap, params_tree,
                                              is_leaf=is_leaf),
             "buffers": jax.tree_util.tree_map(wrap, buffers_tree,
                                               is_leaf=is_leaf)}), f)


class TranslatedLayer(Layer):
    """Loaded inference program (parity: jit/translated_layer.py)."""

    def __init__(self, exported, params, buffers):
        super().__init__()
        self._exported = exported
        self._params = params
        self._buffers_vals = buffers

    def forward(self, *args):
        vals = [a._value if isinstance(a, Tensor) else jnp.asarray(a) for a in args]
        outs = self._exported.call(self._params, self._buffers_vals, *vals)
        outs = [Tensor(o) for o in outs]
        return outs[0] if len(outs) == 1 else tuple(outs)


def load(path, **configs) -> TranslatedLayer:
    import pickle

    from ..framework.io import _from_serializable

    with open(path + ".pdmodel", "rb") as f:
        exported = jax.export.deserialize(f.read())
    with open(path + ".pdiparams", "rb") as f:
        state = _from_serializable(pickle.load(f))
    unwrap = lambda tree: jax.tree_util.tree_map(
        lambda v: v._value if isinstance(v, Tensor) else v, tree,
        is_leaf=lambda v: isinstance(v, Tensor))
    # params may be a NESTED tree (llama.export_for_inference int8
    # exports carry {"q","s"} leaves per weight), not just a flat dict
    params = unwrap(state["params"])
    buffers = unwrap(state["buffers"])
    return TranslatedLayer(exported, params, buffers)


# parity: jit/sot debug knobs (python/paddle/jit/__init__.py set_code_level /
# set_verbosity — utils/envs.py). Here they gate the capture layer's logging.
_debug = {"code_level": 0, "verbosity": 0}


def set_code_level(level=100, also_to_stderr=False):
    _debug["code_level"] = int(level)


def set_verbosity(level=0, also_to_stderr=False):
    _debug["verbosity"] = int(level)
