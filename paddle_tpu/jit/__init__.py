"""paddle_tpu.jit — program capture over jax.jit.

Parity surface: python/paddle/jit/ (to_static — api.py:197; SOT bytecode JIT
under jit/sot/; save/load TranslatedLayer). TPU-native re-design: instead of a
CPython bytecode translator building a PIR program, capture IS jax tracing —
``to_static`` wraps a Layer/function into a pure jax function over its
parameter pytree, jit-compiles per input-signature (guard-based retrace =
one cache entry per (shapes, dtypes, static-arg) key, the analogue of SOT's
guard/compile_cache — jit/sot/symbolic/compile_cache.py), and re-enters the
eager autograd tape through one fused GradNode whose vjp is the compiled
backward (so ``loss.backward()`` through a captured program works, the
analogue of the reference's pir_run_program op —
python/paddle/jit/dy2static/pir_partial_program.py:555,630).

Known jit-mode semantic: BatchNorm running-stat updates are skipped under
capture (buffer mutation inside a traced region); use eager mode or the
functional train-step path when running stats must update.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..autograd import no_grad
from ..core.tensor import Tensor
from ..framework import dtype as dtypes
from ..framework.random import next_key, rng_context
from ..nn.layer.layers import Layer
from ..ops.dispatch import apply

__all__ = ["to_static", "InputSpec", "save", "load", "not_to_static",
           "ignore_module", "enable_to_static", "TranslatedLayer"]

_to_static_enabled = True


def enable_to_static(flag: bool):
    global _to_static_enabled
    _to_static_enabled = flag


class InputSpec:
    """parity: paddle.static.InputSpec."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = list(shape)
        self.dtype = dtypes.convert_dtype(dtype)
        self.name = name
        self.stop_gradient = stop_gradient

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype.name})"

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, tensor.dtype, name)


def _guard_key(args, kwargs):
    parts = []

    def walk(o):
        if isinstance(o, Tensor):
            parts.append(("T", tuple(o._value.shape), str(o._value.dtype)))
        elif isinstance(o, (list, tuple)):
            parts.append(("L", len(o)))
            for e in o:
                walk(e)
        elif isinstance(o, dict):
            parts.append(("D", tuple(sorted(o))))
            for k in sorted(o):
                walk(o[k])
        elif isinstance(o, np.ndarray):
            parts.append(("A", o.tobytes()))
        else:
            parts.append(("S", o))

    walk(args)
    walk(kwargs)
    return tuple(parts)


def _split_tensors(obj, acc):
    """Replace Tensors with index placeholders; return skeleton."""
    if isinstance(obj, Tensor):
        acc.append(obj)
        return ("__tensor__", len(acc) - 1)
    if isinstance(obj, (list, tuple)):
        return type(obj)(_split_tensors(e, acc) for e in obj)
    if isinstance(obj, dict):
        # sorted-key order must match _guard_key, so two calls with the same
        # keys in different insertion order share one compile cache entry
        # with identical tensor slot assignment
        return {k: _split_tensors(obj[k], acc) for k in sorted(obj)}
    return obj


def _rebuild(skel, vals, wrap):
    if isinstance(skel, tuple) and len(skel) == 2 and skel[0] == "__tensor__":
        return wrap(vals[skel[1]])
    if isinstance(skel, (list, tuple)) and not (
        isinstance(skel, tuple) and len(skel) == 2 and skel[0] == "__tensor__"
    ):
        return type(skel)(_rebuild(e, vals, wrap) for e in skel)
    if isinstance(skel, dict):
        return {k: _rebuild(v, vals, wrap) for k, v in skel.items()}
    return skel


class StaticFunction:
    """Guard-cached jit wrapper around a function or Layer.forward."""

    def __init__(self, function: Callable, layer: Optional[Layer] = None,
                 input_spec=None, full_graph=True, backend=None):
        self._fn = function
        self._layer = layer
        self._input_spec = input_spec
        self._cache = {}
        functools.update_wrapper(self, function)

    @property
    def forward(self):
        return self

    def concrete_program(self):
        return list(self._cache.values())

    def _build(self, skel_args, skel_kwargs, n_args, out_box):
        layer = self._layer
        fn = self._fn

        def pure(params, bufs, key_data, *arg_vals):
            key = jax.random.wrap_key_data(key_data)
            wrap = lambda v: Tensor(v, stop_gradient=True)
            args = _rebuild(skel_args, arg_vals, wrap)
            kwargs = _rebuild(skel_kwargs, arg_vals, wrap)
            with rng_context(key), no_grad():
                if layer is not None:
                    with layer.bind_state(params, bufs):
                        out = fn(*args, **kwargs)
                else:
                    out = fn(*args, **kwargs)
            tensors: List[Tensor] = []
            skel_out = _split_tensors(out, tensors)
            out_box["skel"] = skel_out
            return tuple(t._value for t in tensors)

        return jax.jit(pure)

    def __call__(self, *args, **kwargs):
        if not _to_static_enabled:
            if self._layer is not None:
                return self._fn(*args, **kwargs)
            return self._fn(*args, **kwargs)
        key = _guard_key(args, kwargs)
        arg_tensors: List[Tensor] = []
        skel_args = _split_tensors(args, arg_tensors)
        skel_kwargs = _split_tensors(kwargs, arg_tensors)
        entry = self._cache.get(key)
        if entry is None:
            out_box = {}
            jitted = self._build(skel_args, skel_kwargs, len(arg_tensors), out_box)
            entry = {"jitted": jitted, "out_box": out_box}
            self._cache[key] = entry
        jitted = entry["jitted"]
        out_box = entry["out_box"]

        if self._layer is not None:
            named_p = list(self._layer.named_parameters())
            bufs = {k: b._value for k, b in self._layer.named_buffers()}
            pnames = [k for k, _ in named_p]
            ptensors = [p for _, p in named_p]
        else:
            pnames, ptensors, bufs = [], [], {}

        key_data = jax.random.key_data(next_key())

        def runner(pvals, avals):
            params = dict(zip(pnames, pvals))
            return jitted(params, bufs, key_data, *avals)

        outs = apply("jit::" + getattr(self._fn, "__name__", "fn"),
                     lambda pvals, avals: runner(pvals, avals),
                     list(ptensors), list(arg_tensors))
        if not isinstance(outs, tuple):
            outs = (outs,)
        wrapped = _rebuild(out_box["skel"], list(outs), lambda t: t)
        return wrapped


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, full_graph=True, **kwargs):
    """paddle.jit.to_static parity (api.py:197)."""

    def decorate(obj):
        if isinstance(obj, Layer):
            static_fwd = StaticFunction(obj.forward, layer=obj,
                                        input_spec=input_spec)
            obj.forward = static_fwd
            obj._static_function = static_fwd
            return obj
        layer = getattr(obj, "__self__", None)
        if isinstance(layer, Layer):
            return StaticFunction(obj, layer=layer, input_spec=input_spec)
        return StaticFunction(obj, input_spec=input_spec)

    if function is None:
        return decorate
    return decorate(function)


def not_to_static(fn):
    fn._not_to_static = True
    return fn


def ignore_module(modules):
    pass


# ---------------------------------------------------------------------------
# save / load — exported StableHLO + weights (the inference path; parity:
# paddle.jit.save / TranslatedLayer, reference jit/translated_layer.py; the
# serialized artifact is the analogue of the PIR model format,
# fluid/pir/serialize_deserialize)
# ---------------------------------------------------------------------------
def save(layer, path, input_spec=None, **configs):
    import pickle

    from ..framework.io import _to_serializable

    if input_spec is None and getattr(layer, "_static_function", None):
        raise ValueError("input_spec is required to export")
    specs = input_spec or []
    example_args = []
    for spec in specs:
        if isinstance(spec, InputSpec):
            shape = [1 if s in (None, -1) else int(s) for s in spec.shape]
            example_args.append(jnp.zeros(shape, spec.dtype.np_dtype))
        elif isinstance(spec, Tensor):
            example_args.append(spec._value)
        else:
            example_args.append(jnp.asarray(spec))

    params, bufs = layer.functional_state() if isinstance(layer, Layer) else ({}, {})

    def pure(params, bufs, *arg_vals):
        wrap = lambda v: Tensor(v, stop_gradient=True)
        args = [wrap(v) for v in arg_vals]
        with no_grad():
            if isinstance(layer, Layer):
                was_training = layer.training
                layer.eval()
                try:
                    with layer.bind_state(params, bufs):
                        fwd = layer.forward
                        if isinstance(fwd, StaticFunction):
                            fwd = fwd._fn
                        out = fwd(*args)
                finally:
                    if was_training:
                        layer.train()
            else:
                out = layer(*args)
        tensors: List[Tensor] = []
        _split_tensors(out, tensors)
        return tuple(t._value for t in tensors)

    jitted = jax.jit(pure)
    exported = jax.export.export(jitted)(params, bufs, *example_args)
    blob = exported.serialize()
    with open(path + ".pdmodel", "wb") as f:
        f.write(blob)
    with open(path + ".pdiparams", "wb") as f:
        pickle.dump(_to_serializable({"params": {k: Tensor(v) for k, v in params.items()},
                                      "buffers": {k: Tensor(v) for k, v in bufs.items()}}),
                    f)


class TranslatedLayer(Layer):
    """Loaded inference program (parity: jit/translated_layer.py)."""

    def __init__(self, exported, params, buffers):
        super().__init__()
        self._exported = exported
        self._params = params
        self._buffers_vals = buffers

    def forward(self, *args):
        vals = [a._value if isinstance(a, Tensor) else jnp.asarray(a) for a in args]
        outs = self._exported.call(self._params, self._buffers_vals, *vals)
        outs = [Tensor(o) for o in outs]
        return outs[0] if len(outs) == 1 else tuple(outs)


def load(path, **configs) -> TranslatedLayer:
    import pickle

    from ..framework.io import _from_serializable

    with open(path + ".pdmodel", "rb") as f:
        exported = jax.export.deserialize(f.read())
    with open(path + ".pdiparams", "rb") as f:
        state = _from_serializable(pickle.load(f))
    params = {k: v._value for k, v in state["params"].items()}
    buffers = {k: v._value for k, v in state["buffers"].items()}
    return TranslatedLayer(exported, params, buffers)
