"""Segment-compiled execution for graph-broken functions (SOT parity).

The reference's SOT compiles the bytecode BEFORE an unconvertible break,
runs the break eagerly, and resumes capture after it
(python/paddle/jit/sot/opcode_translator/eval_frame_callback.py:54,
sot/symbolic/compile_cache.py). This is the trace-based TPU-native
equivalent, shaped like torch/XLA's lazy-tensor core rather than a
bytecode translator:

* the python function RE-EXECUTES every call (so value-dependent control
  flow — ``.item()`` branches, host-side logic — is always correct);
* every registry op it issues is DEFERRED onto a linear tape instead of
  dispatched to the device (ops/dispatch.py hands the call to the active
  ``SegmentRecorder``);
* any value materialization — ``.item()``, ``bool()``, ``numpy()``,
  printing — CUTS a segment: the pending tape compiles into ONE jitted
  program (cached by tape structure, so steady state never retraces) and
  executes through the normal ``apply`` path, which records a single
  GradNode per segment — autograd composes across segments through the
  eager tape, so graph-broken models still train.

Through a remote-attached chip this is also an eager-mode win: an N-op
python region costs ~1 dispatch instead of N. Measured r4 on a 24-layer
MLP: 18× vs a COLD eager pass (per-op compiles included — the compile
storm segments avoid entirely), ~1.5-2× vs warm eager at ~30 ops,
growing with region size.

Anything the recorder cannot defer (data-dependent output shapes, ops
whose abstract eval fails, nested already-compiled programs) flushes the
tape and runs that op eagerly — the mode degrades toward plain eager,
never toward wrong answers.
"""
from __future__ import annotations

import threading
import weakref
from typing import Any, Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["SegmentRecorder", "segment_scope", "current_recorder"]

_tls = threading.local()


def current_recorder() -> Optional["SegmentRecorder"]:
    if getattr(_tls, "flushing", 0):
        return None               # a flush's own apply must not re-record
    return getattr(_tls, "rec", None)


class _Lazy:
    """Placeholder value carried by a Tensor whose op is still on the
    tape. Shape/dtype queries answer from the abstract value; anything
    that needs data forces a flush and then delegates to the real array."""

    __slots__ = ("aval", "rec", "real", "__weakref__")
    _is_segment_lazy = True

    def __init__(self, aval, rec):
        object.__setattr__(self, "aval", aval)
        object.__setattr__(self, "rec", rec)
        object.__setattr__(self, "real", None)

    # -- metadata (no flush) ---------------------------------------------
    @property
    def shape(self):
        return tuple(self.aval.shape)

    @property
    def ndim(self):
        return len(self.aval.shape)

    @property
    def dtype(self):
        return self.aval.dtype

    @property
    def size(self):
        return int(np.prod(self.aval.shape)) if self.aval.shape else 1

    # -- forcing ----------------------------------------------------------
    def _force(self):
        if self.real is None:
            self.rec.flush()
        if self.real is None:
            raise RuntimeError(
                "segment value was dropped before it was bound — a lazy "
                "tensor escaped its recording scope with no live wrapper")
        return self.real

    def item(self, *a):
        return self._force().item(*a)

    def __array__(self, dtype=None, copy=None):
        out = np.asarray(self._force())
        return out.astype(dtype) if dtype is not None else out

    def __bool__(self):
        return bool(self._force())

    def __int__(self):
        return int(self._force())

    def __float__(self):
        return float(self._force())

    def __index__(self):
        return self._force().__index__()

    def __getattr__(self, name):
        # safety net: unknown attribute/method → materialize and delegate
        return getattr(self._force(), name)

    def __repr__(self):
        if self.real is not None:
            return repr(self.real)
        return f"<lazy {self.aval.dtype}{list(self.aval.shape)}>"


class _InSnap:
    """RECORD-TIME snapshot of one op input: the value reference and the
    autograd provenance as they were when the op was issued. In-place ops
    (`_adopt`) may rebind the live tensor before flush — the tape must
    not see that."""

    __slots__ = ("value", "sg", "grad_node", "out_index", "accum")

    def __init__(self, t):
        self.value = t._value
        self.sg = t.stop_gradient
        self.grad_node = t._grad_node
        self.out_index = t._output_index
        self.accum = t._accumulate_node

    def key(self):
        return (id(self.value), self.sg, id(self.grad_node),
                self.out_index, id(self.accum))

    def raw(self):
        v = self.value
        return v.real if isinstance(v, _Lazy) else v


class _Node:
    __slots__ = ("name", "fn", "s_args", "s_kwargs", "in_snaps",
                 "out_lazies", "multi", "grad_on")

    def __init__(self, name, fn, s_args, s_kwargs, in_snaps, out_lazies,
                 multi, grad_on):
        self.name = name
        self.fn = fn
        self.s_args = s_args
        self.s_kwargs = s_kwargs
        self.in_snaps = in_snaps
        self.out_lazies = out_lazies
        self.multi = multi
        self.grad_on = grad_on


# compiled segment programs, keyed by tape structure — shared across
# recorders so repeated calls of a graph-broken function hit the cache
_SEGMENT_CACHE: dict = {}


def note_lazy_ref(lazy, tensor):
    """Called by core.Tensor whenever a tensor starts referencing a lazy
    value (creation, aliasing constructor, in-place `_adopt`): the
    recorder binds the computed value and grad linkage onto every live
    owner at flush."""
    lazy.rec._owners.setdefault(id(lazy), []).append(weakref.ref(tensor))


def _tensor_with_lazy(lazy, stop_gradient):
    """Build a framework Tensor around a _Lazy without the constructor's
    jnp.asarray coercion."""
    from ..core.tensor import Tensor

    t = Tensor.__new__(Tensor)
    t._value = lazy
    t.stop_gradient = stop_gradient
    t._grad = None
    t._grad_node = None
    t._output_index = 0
    t._accumulate_node = None
    t.name = None
    t.persistable = False
    t.is_parameter = False
    t._version = 0
    note_lazy_ref(lazy, t)
    return t


def _shim_tensor(snap: _InSnap):
    """Tensor view of an input snapshot: carries the RECORDED value and
    autograd provenance into the flush's apply call, immune to later
    in-place rebinds of the original tensor."""
    from ..core.tensor import Tensor

    t = Tensor.__new__(Tensor)
    t._value = snap.raw()
    t.stop_gradient = snap.sg
    t._grad = None
    t._grad_node = snap.grad_node
    t._output_index = snap.out_index
    t._accumulate_node = snap.accum
    t.name = None
    t.persistable = False
    t.is_parameter = False
    t._version = 0
    return t


class SegmentRecorder:
    """Records registry-op calls into segments; see module docstring."""

    def __init__(self):
        self.nodes: List[_Node] = []
        self.flushes = 0           # segments executed (compiled or cached)
        self.compiles = 0          # segments that actually compiled
        self._owners: dict = {}    # id(lazy) -> [weakref(Tensor)]

    # -- recording --------------------------------------------------------
    def record(self, name: str, fn: Callable, args, kwargs):
        """Defer one op. Returns (outs tuple, multi) or None if the op
        cannot be deferred (caller runs it eagerly after our flush)."""
        from ..autograd.tape import AccumulateGrad, is_grad_enabled
        from ..framework import dtype as _dtypes
        from ..ops.dispatch import _fill, _scan

        if name.startswith("jit::"):
            # an inner already-compiled StaticFunction: its closure bakes
            # per-call state (rng key data, buffers) no structural key can
            # see — run it as its own dispatch instead of poisoning the
            # segment cache with never-hitting entries
            return None

        tensors: List = []
        s_args = _scan(args, tensors)
        s_kwargs = _scan(kwargs, tensors)
        avals = []
        for t in tensors:
            v = t._value
            if isinstance(v, _Lazy) and v.real is None and v.rec is not self:
                v.rec.flush()      # nested scope: force the OUTER tape
            v = t._value
            if isinstance(v, _Lazy):
                avals.append(v.aval if v.real is None
                             else jax.ShapeDtypeStruct(
                                 tuple(v.real.shape), v.real.dtype))
            else:
                avals.append(jax.ShapeDtypeStruct(tuple(v.shape), v.dtype))
        try:
            out_avals = jax.eval_shape(
                lambda *vs: fn(*_fill(s_args, vs), **_fill(s_kwargs, vs)),
                *avals)
        except Exception:
            self.flush()           # op needs real values → run it eagerly
            return None
        multi = isinstance(out_avals, (tuple, list))
        flat_avals = tuple(out_avals) if multi else (out_avals,)
        if not all(hasattr(a, "shape") and hasattr(a, "dtype")
                   for a in flat_avals):
            self.flush()
            return None

        grad_on = is_grad_enabled()
        any_grad = grad_on and any(
            not t.stop_gradient
            and _dtypes.np_is_floating(np.dtype(a.dtype))
            for t, a in zip(tensors, avals))
        snaps = []
        for t in tensors:
            if (not t.stop_gradient and t._grad_node is None
                    and t._accumulate_node is None):
                # leaf requiring grad: pin its AccumulateGrad to the
                # ORIGINAL tensor now, so the flush-time shim routes
                # cotangents to it
                t._accumulate_node = AccumulateGrad(t)
            snaps.append(_InSnap(t))
        outs, lazies = [], []
        for a in flat_avals:
            lz = _Lazy(jax.ShapeDtypeStruct(tuple(a.shape), a.dtype), self)
            is_float = _dtypes.np_is_floating(np.dtype(a.dtype))
            t = _tensor_with_lazy(lz, stop_gradient=not (is_float
                                                         and any_grad))
            outs.append(t)
            lazies.append(lz)
        self.nodes.append(_Node(name, fn, s_args, s_kwargs, snaps, lazies,
                                multi, grad_on and any_grad))
        return tuple(outs), multi

    # -- flushing ---------------------------------------------------------
    def flush(self):
        """Compile-and-run the pending tape as one program; bind results."""
        if getattr(_tls, "flushing", 0) or not self.nodes:
            return
        nodes, self.nodes = self.nodes, []
        _tls.flushing = getattr(_tls, "flushing", 0) + 1
        try:
            self._run_segment(nodes)
        finally:
            _tls.flushing -= 1

    def _live_owners(self, lz):
        out = []
        for wr in self._owners.get(id(lz), ()):
            t = wr()
            if t is not None and t._value is lz:
                out.append(t)
        return out

    def _run_segment(self, nodes: List[_Node]):
        from ..ops.dispatch import _fill, apply

        # segment inputs: every op input whose snapshot value is real;
        # dedup only on identical (value, grad-provenance) — a tensor and
        # its detach() share a value but must stay separate inputs
        in_snaps: List[_InSnap] = []
        in_index: dict = {}            # snap.key() -> position
        lazy_pos: dict = {}            # id(lazy) -> (node_i, out_j)
        key_parts: List = ["seg"]
        for ni, nd in enumerate(nodes):
            key_parts.append(nd.name)
            # fn identity is part of the key: closures bake per-call
            # constants (scalars, rng keys) invisible to the arg skeleton.
            # _fn_key hashes (code object, closure-cell contents) so the
            # per-call lambdas most ops build still cache-hit when their
            # constants repeat; opaque cells fall back to the fn object
            # (never stale — at worst a recompile).
            key_parts.append(_fn_key(nd.fn))
            key_parts.append(_skel_key(nd.s_args))
            key_parts.append(_skel_key(nd.s_kwargs))
            key_parts.append(nd.grad_on)
            for sn in nd.in_snaps:
                v = sn.value
                if isinstance(v, _Lazy) and v.real is None:
                    key_parts.append(("lz", lazy_pos[id(v)], sn.sg))
                else:
                    k = sn.key()
                    if k not in in_index:
                        in_index[k] = len(in_snaps)
                        in_snaps.append(sn)
                    raw = sn.raw()
                    key_parts.append(
                        ("in", in_index[k], tuple(raw.shape),
                         str(raw.dtype), sn.sg))
            for j, lz in enumerate(nd.out_lazies):
                lazy_pos[id(lz)] = (ni, j)

        # outputs: lazies still referenced by a live Tensor (everything
        # else is a dead intermediate XLA can fuse away)
        out_sel: List[Tuple[int, int]] = []
        for ni, nd in enumerate(nodes):
            for j, lz in enumerate(nd.out_lazies):
                if self._live_owners(lz):
                    out_sel.append((ni, j))
        key_parts.append(tuple(out_sel))
        key = _hashable(key_parts)

        if len(_SEGMENT_CACHE) > 512:     # opaque-keyed entries never hit
            _SEGMENT_CACHE.clear()
        jitted = _SEGMENT_CACHE.get(key)
        if jitted is None:
            # the cached closure must reference ONLY the extracted plan —
            # never nodes/snaps/lazies, which pin the first call's input
            # arrays, results, and GradNode vjp residuals (activations)
            # for the cache entry's lifetime
            plan = []
            for nd in nodes:
                srcs = []
                for sn in nd.in_snaps:
                    v = sn.value
                    if isinstance(v, _Lazy) and v.real is None:
                        # sg at the USE site: a detached view of a lazy
                        # intermediate resolves to the same traced value —
                        # the stop_gradient must wrap this use
                        srcs.append(("env",) + lazy_pos[id(v)] + (sn.sg,))
                    else:
                        srcs.append(("in", in_index[sn.key()]))
                plan.append((nd.fn, nd.s_args, nd.s_kwargs, tuple(srcs),
                             nd.grad_on, len(nd.out_lazies)))

            def seg_fn(*in_vals):
                env: dict = {}
                for ni, (fn, sa, sk, srcs, grad_on, n_out) in enumerate(
                        plan):
                    vals = []
                    for s in srcs:
                        if s[0] == "env":
                            v = env[s[1:3]]
                            vals.append(jax.lax.stop_gradient(v)
                                        if s[3] else v)
                        else:
                            vals.append(in_vals[s[1]])
                    out = fn(*_fill(sa, vals), **_fill(sk, vals))
                    outs = (tuple(out) if isinstance(out, (tuple, list))
                            else (out,))
                    if not grad_on:
                        outs = tuple(jax.lax.stop_gradient(o)
                                     for o in outs)
                    for j, o in enumerate(outs):
                        env[(ni, j)] = o
                return tuple(env[k] for k in out_sel)

            jitted = jax.jit(seg_fn)
            _SEGMENT_CACHE[key] = jitted
            self.compiles += 1
        self.flushes += 1

        # the flush may be triggered from inside no_grad() (loss logging);
        # the segment's grad recording is decided by the tape as RECORDED
        from ..autograd.tape import enable_grad, no_grad
        grad_ctx = (enable_grad() if any(nd.grad_on for nd in nodes)
                    else no_grad())
        seg_inputs = [_shim_tensor(sn) for sn in in_snaps]
        with grad_ctx:
            outs = apply("jit_segment", lambda *vs: jitted(*vs),
                         *seg_inputs)
        if not isinstance(outs, tuple):
            outs = (outs,)
        # bind: real value + grad linkage onto every live owner tensor
        for (ni, j), res in zip(out_sel, outs):
            lz = nodes[ni].out_lazies[j]
            object.__setattr__(lz, "real", res._value)
            for t in self._live_owners(lz):
                t._value = res._value
                if not t.stop_gradient:
                    # owners that detached (detach()/detach_() set
                    # stop_gradient=True while sharing the lazy) keep
                    # their detachment — no grad node reattached
                    t._grad_node = res._grad_node
                    t._output_index = res._output_index
                    t.stop_gradient = res.stop_gradient
        for nd in nodes:
            for lz in nd.out_lazies:
                self._owners.pop(id(lz), None)


def _fn_key(fn):
    """Structural identity for an op's fn: behavior is determined by its
    code object plus closed-over constants, so equal (code, cells) from
    the same definition site may share one compiled segment. Anything
    opaque degrades to object identity (strong-ref'd in the cache key, so
    id() reuse can never alias two different fns)."""
    code = getattr(fn, "__code__", None)
    if code is None:
        return fn
    parts: List[Any] = [code]
    for cell in (getattr(fn, "__closure__", None) or ()):
        v = cell.cell_contents
        key = _const_key(v)
        if key is None:
            return fn
        parts.append(key)
    for v in (getattr(fn, "__defaults__", None) or ()):
        key = _const_key(v)
        if key is None:
            return fn
        parts.append(key)
    return tuple(parts)


def _const_key(v):
    """Hashable content key for a closure constant, or None if opaque."""
    if v is None or v is Ellipsis or v is NotImplemented:
        return ("singleton", repr(v))
    if isinstance(v, (jax.Array, np.ndarray)):
        try:
            if jnp.issubdtype(v.dtype, jax.dtypes.prng_key):
                v = jax.random.key_data(v)
            if v.size <= 64:
                return ("arr", str(v.dtype), tuple(v.shape),
                        tuple(np.asarray(v).ravel().tolist()))
        except Exception:
            pass
        return None
    if callable(v):
        k = _fn_key(v)
        return None if k is v else ("fn",) + tuple(
            k if isinstance(k, tuple) else (k,))
    try:
        hash(v)
    except TypeError:
        return None
    if type(v).__hash__ is object.__hash__:
        return None                     # identity hash: not content-stable
    return v


def _skel_key(obj):
    from ..ops.dispatch import _Ph

    if isinstance(obj, _Ph):
        return ("ph", obj.i)
    if isinstance(obj, (list, tuple)):
        return (type(obj).__name__,) + tuple(_skel_key(o) for o in obj)
    if isinstance(obj, dict):
        return ("d",) + tuple((k, _skel_key(v))
                              for k, v in sorted(obj.items()))
    try:
        hash(obj)
        return obj
    except TypeError:
        return repr(obj)


def _hashable(parts):
    def conv(o):
        if isinstance(o, list):
            return tuple(conv(x) for x in o)
        if isinstance(o, tuple):
            return tuple(conv(x) for x in o)
        return o
    return conv(tuple(parts))


class segment_scope:
    """Context manager activating a SegmentRecorder for the thread."""

    def __init__(self):
        self.rec = SegmentRecorder()

    def __enter__(self):
        self._prev = getattr(_tls, "rec", None)
        _tls.rec = self.rec
        return self.rec

    def __exit__(self, *exc):
        try:
            try:
                # flush even on error: escaped tensors (buffers rebound by
                # in-place ops) must not be left referencing a dropped
                # tape — the recorded ops are valid regardless of why the
                # python after them raised
                self.rec.flush()
            except Exception:
                if exc[0] is None:
                    raise
                self.rec.nodes.clear()   # already unwinding: best effort
        finally:
            _tls.rec = self._prev
        return False
