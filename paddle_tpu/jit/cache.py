"""Persistent compile-artifact cache for the jit layer.

The in-memory guard cache (``StaticFunction._cache``) dies with the
process; artifacts whose recomputation is *measured* rather than traced
— today the MoE grouped-matmul tiling winners
(:mod:`paddle_tpu.kernels.gmm_autotune`) — are worth keeping across
runs. This module is the one place that knows where such artifacts
live and how to write them without torn files:

* ``cache_dir()`` — ``FLAGS_jit_cache_dir`` > ``$PADDLE_TPU_CACHE_DIR``
  > ``$XDG_CACHE_HOME/paddle_tpu`` > ``~/.cache/paddle_tpu``;
* ``load_json(name)`` / ``store_json(name, obj)`` — JSON documents
  committed with the resilience tier's temp+fsync+rename idiom
  (atomic_ckpt.py), so a crash mid-write leaves the previous version,
  never a truncated one. Corrupt/missing files read as ``{}``.

Documents may carry a **schema version**: ``store_json(name, obj,
schema=N)`` stamps the document with ``{"__schema__": N}`` and
``load_json(name, schema=N)`` returns ``{}`` for any document whose
stamp does not match — a process running older code silently starts
from an empty cache instead of misreading entries whose key format
changed (the gmm tiling keys gained dtype/kernel-variant fields this
way). ``schema=None`` (the default) keeps the historical unversioned
behaviour.

Deliberately tiny and stdlib-only: callers treat persistence as
best-effort (a read-only filesystem must never break compilation).
"""
from __future__ import annotations

import json
import os
import tempfile
from typing import Any, Dict

from ..framework.flags import define_flag, get_flag

define_flag("jit_cache_dir", "",
            "directory for persistent compile artifacts (tiling autotune "
            "winners etc.); empty = $PADDLE_TPU_CACHE_DIR or "
            "$XDG_CACHE_HOME/paddle_tpu or ~/.cache/paddle_tpu")

__all__ = ["cache_dir", "cache_path", "load_json", "store_json",
           "SCHEMA_KEY"]

SCHEMA_KEY = "__schema__"


def cache_dir() -> str:
    d = get_flag("jit_cache_dir") or os.environ.get("PADDLE_TPU_CACHE_DIR")
    if not d:
        xdg = os.environ.get("XDG_CACHE_HOME")
        base = xdg if xdg else os.path.join(os.path.expanduser("~"), ".cache")
        d = os.path.join(base, "paddle_tpu")
    return d


def cache_path(name: str) -> str:
    return os.path.join(cache_dir(), name + ".json")


def load_json(name: str, schema: int = None) -> Dict[str, Any]:
    """Read a cached JSON document; missing or corrupt → ``{}``.

    With ``schema=N`` the document must carry ``{"__schema__": N}``
    (written by ``store_json(..., schema=N)``) — any other stamp, or a
    pre-versioning file, reads as ``{}`` so callers re-derive rather
    than misinterpret entries under an old key format. The stamp itself
    is stripped from the returned mapping."""
    try:
        with open(cache_path(name), "r") as f:
            obj = json.load(f)
        if not isinstance(obj, dict):
            return {}
    except (OSError, ValueError):
        return {}
    if schema is not None:
        if obj.get(SCHEMA_KEY) != schema:
            return {}
    obj.pop(SCHEMA_KEY, None)
    return obj


def store_json(name: str, obj: Dict[str, Any], schema: int = None) -> bool:
    """Atomically commit ``obj`` (temp file + fsync + rename). Returns
    False instead of raising on any I/O failure — persistence is an
    optimization, never a requirement. ``schema=N`` stamps the document
    for :func:`load_json` version checking."""
    if schema is not None:
        obj = dict(obj, **{SCHEMA_KEY: schema})
    path = cache_path(name)
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path),
                                   prefix=".tmp-" + name)
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(obj, f, indent=1, sort_keys=True)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)      # the commit point (atomic on POSIX)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return True
    except OSError:
        return False
