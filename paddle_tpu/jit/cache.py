"""Persistent compile-artifact cache for the jit layer.

The in-memory guard cache (``StaticFunction._cache``) dies with the
process; artifacts whose recomputation is *measured* rather than traced
— today the MoE grouped-matmul tiling winners
(:mod:`paddle_tpu.kernels.gmm_autotune`) — are worth keeping across
runs. This module is the one place that knows where such artifacts
live and how to write them without torn files:

* ``cache_dir()`` — ``FLAGS_jit_cache_dir`` > ``$PADDLE_TPU_CACHE_DIR``
  > ``$XDG_CACHE_HOME/paddle_tpu`` > ``~/.cache/paddle_tpu``;
* ``load_json(name)`` / ``store_json(name, obj)`` — JSON documents
  committed with the resilience tier's temp+fsync+rename idiom
  (atomic_ckpt.py), so a crash mid-write leaves the previous version,
  never a truncated one. Corrupt/missing files read as ``{}``.

Deliberately tiny and stdlib-only: callers treat persistence as
best-effort (a read-only filesystem must never break compilation).
"""
from __future__ import annotations

import json
import os
import tempfile
from typing import Any, Dict

from ..framework.flags import define_flag, get_flag

define_flag("jit_cache_dir", "",
            "directory for persistent compile artifacts (tiling autotune "
            "winners etc.); empty = $PADDLE_TPU_CACHE_DIR or "
            "$XDG_CACHE_HOME/paddle_tpu or ~/.cache/paddle_tpu")

__all__ = ["cache_dir", "cache_path", "load_json", "store_json"]


def cache_dir() -> str:
    d = get_flag("jit_cache_dir") or os.environ.get("PADDLE_TPU_CACHE_DIR")
    if not d:
        xdg = os.environ.get("XDG_CACHE_HOME")
        base = xdg if xdg else os.path.join(os.path.expanduser("~"), ".cache")
        d = os.path.join(base, "paddle_tpu")
    return d


def cache_path(name: str) -> str:
    return os.path.join(cache_dir(), name + ".json")


def load_json(name: str) -> Dict[str, Any]:
    """Read a cached JSON document; missing or corrupt → ``{}``."""
    try:
        with open(cache_path(name), "r") as f:
            obj = json.load(f)
        return obj if isinstance(obj, dict) else {}
    except (OSError, ValueError):
        return {}


def store_json(name: str, obj: Dict[str, Any]) -> bool:
    """Atomically commit ``obj`` (temp file + fsync + rename). Returns
    False instead of raising on any I/O failure — persistence is an
    optimization, never a requirement."""
    path = cache_path(name)
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path),
                                   prefix=".tmp-" + name)
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(obj, f, indent=1, sort_keys=True)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)      # the commit point (atomic on POSIX)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return True
    except OSError:
        return False
