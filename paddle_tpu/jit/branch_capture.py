"""Scalar-tensor branch capture: keep the program compiled across
data-dependent Python ``if``s.

Parity surface: the reference's SOT breaks the CPython frame at a
data-dependent branch and keeps compiled segments on both sides
(python/paddle/jit/sot/opcode_translator/eval_frame_callback.py:54), and its
AST dy2static mode rewrites tensor ``if``/``while`` into cond/while ops
(python/paddle/jit/dy2static/convert_operators.py convert_ifelse).

TPU-native re-design: neither a bytecode translator nor an AST rewrite.
During jax tracing, ``Tensor.__bool__`` on a traced scalar consults a
*branch oracle* instead of raising. The oracle enumerates the reachable
decision paths (re-running the traced body with each branch forced), and —
when every sibling pair of arms produces outputs of identical structure,
shape, and dtype — stitches them together with ``lax.cond``. The whole call
stays ONE compiled XLA program; the Python ``if`` becomes a compiled
conditional, which is exactly what dy2static's convert_ifelse produces via
the cond op, done at trace time instead of AST time.

Bounds: path enumeration is exponential in the number of *dynamic* branch
points on a path, so capture is capped at ``MAX_BRANCH_POINTS`` (deeper
nesting, and tensor ``while`` loops, re-raise and take the eager graph-break
fallback in jit/__init__.py). Arms are both traced unconditionally —
``lax.cond`` on TPU typically compiles to a fused select when arms are
cheap, the right trade for scalar guards like loss-scale checks.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, List, Tuple

import jax
import jax.numpy as jnp

MAX_BRANCH_POINTS = 4  # ≤ 2**4 = 16 path traces per capture

_tls = threading.local()


class GraphBreak(Exception):
    """Raised when branch capture cannot keep the program whole (arms
    disagree on structure/shape/dtype, or too many dynamic branches).
    jit.StaticFunction treats it like a ConcretizationTypeError: fall back
    to eager for the signature."""


class _NeedDecision(Exception):
    """Internal: tracing hit a dynamic branch beyond the forced prefix."""

    def __init__(self, cond_value):
        self.cond_value = cond_value


class _Oracle:
    def __init__(self, forced: Tuple[bool, ...]):
        self.forced = forced
        self.idx = 0

    def decide(self, value) -> bool:
        i = self.idx
        self.idx += 1
        if i < len(self.forced):
            return self.forced[i]
        raise _NeedDecision(value)


def _stack() -> List[_Oracle]:
    s = getattr(_tls, "stack", None)
    if s is None:
        s = _tls.stack = []
    return s


def maybe_decide(value):
    """Called from ``Tensor.__bool__``. Returns a concrete bool when a
    branch-capture oracle is active and ``value`` is a traced scalar;
    returns None (caller proceeds normally) otherwise."""
    s = _stack()
    if not s or not isinstance(value, jax.core.Tracer):
        return None
    if value.size != 1:
        # mirror eager semantics: bool() of a multi-element array is an
        # error, not a branch — let the normal path raise it
        return None
    return s[-1].decide(value)


def capture_branches(body: Callable[[], Any], combine_leaves):
    """Run ``body`` under the oracle; at each dynamic branch, trace both
    arms and merge them with ``lax.cond``.

    ``body`` must be re-runnable (idempotent per run: it re-binds all state
    itself). ``combine_leaves(pred, true_leaf, false_leaf)`` merges two leaf
    results into one (raising GraphBreak on mismatch).

    Returns ``(leaf_result, n_branch_points)``.
    """
    n_points = 0

    from ..core import tensor as _tensor_mod

    def eval_path(prefix: Tuple[bool, ...]):
        nonlocal n_points
        oracle = _Oracle(prefix)
        _stack().append(oracle)
        _tensor_mod._branch_oracle_hook.append(maybe_decide)
        try:
            out = body()
            return out
        except _NeedDecision as nd:
            if len(prefix) >= MAX_BRANCH_POINTS:
                raise GraphBreak(
                    f"more than {MAX_BRANCH_POINTS} data-dependent branch "
                    "points on one path; use lax.cond/lax.while_loop "
                    "explicitly or accept the eager fallback")
            n_points += 1
            pred = jnp.reshape(nd.cond_value, ()).astype(jnp.bool_)
            t_out = eval_path(prefix + (True,))
            f_out = eval_path(prefix + (False,))
            return combine_leaves(pred, t_out, f_out)
        finally:
            _stack().pop()
            _tensor_mod._branch_oracle_hook.pop()
    # all decisions trace inside the caller's jit: conds stay traced values
    result = eval_path(())
    return result, n_points


def combine_tensor_leaves(pred, t_leaf, f_leaf):
    """Leaf combiner for jit capture leaves of the form
    ``(skeleton, [jax values], {buffer name: jax value})``."""
    t_skel, t_vals, t_bufs = t_leaf
    f_skel, f_vals, f_bufs = f_leaf
    if t_skel != f_skel:
        raise GraphBreak(
            "branch arms return different structures; cannot merge with "
            "lax.cond — returning the same pytree shape from both arms "
            "keeps the program compiled")
    if sorted(t_bufs) != sorted(f_bufs):
        raise GraphBreak("branch arms update different buffer sets")
    buf_names = sorted(t_bufs)
    t_flat = list(t_vals) + [t_bufs[k] for k in buf_names]
    f_flat = list(f_vals) + [f_bufs[k] for k in buf_names]
    if len(t_flat) != len(f_flat):
        raise GraphBreak("branch arms return different numbers of tensors")
    for a, b in zip(t_flat, f_flat):
        a_ = jnp.asarray(a)
        b_ = jnp.asarray(b)
        if a_.shape != b_.shape or a_.dtype != b_.dtype:
            raise GraphBreak(
                f"branch arm outputs disagree on shape/dtype "
                f"({a_.shape}/{a_.dtype} vs {b_.shape}/{b_.dtype}); "
                "lax.cond requires identical output avals")
    merged = jax.lax.cond(pred,
                          lambda: tuple(jnp.asarray(v) for v in t_flat),
                          lambda: tuple(jnp.asarray(v) for v in f_flat))
    n_vals = len(t_vals)
    vals = list(merged[:n_vals])
    bufs = dict(zip(buf_names, merged[n_vals:]))
    return t_skel, vals, bufs
