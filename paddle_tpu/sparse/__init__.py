"""paddle_tpu.sparse (parity: python/paddle/sparse/ COO/CSR surface).

XLA/TPU has no native sparse kernels; SparseCooTensor keeps (indices, values)
host-side jax arrays and computes via scatter/gather dense lowering — the
capability surface (construction, conversion, elementwise, matmul) is
preserved while heavy compute densifies (documented divergence).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor

__all__ = ["sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
           "SparseCsrTensor", "add", "matmul", "relu"]


class SparseCooTensor:
    def __init__(self, indices, values, shape):
        self.indices = indices if isinstance(indices, Tensor) else Tensor(indices)
        self.values = values if isinstance(values, Tensor) else Tensor(values)
        self.shape = list(shape)

    def to_dense(self) -> Tensor:
        dense = jnp.zeros(tuple(self.shape),
                          self.values._value.dtype)
        idx = tuple(self.indices._value.astype(jnp.int32))
        return Tensor(dense.at[idx].add(self.values._value))

    def to_sparse_csr(self):
        if len(self.shape) != 2:
            raise ValueError("CSR requires 2-D")
        dense = np.asarray(self.to_dense()._value)
        rows, cols = np.nonzero(dense)
        crows = np.zeros(self.shape[0] + 1, np.int64)
        for r in rows:
            crows[r + 1] += 1
        crows = np.cumsum(crows)
        return SparseCsrTensor(crows, cols, dense[rows, cols], self.shape)

    @property
    def nnz(self):
        return self.values.shape[0]

    def __repr__(self):
        return f"SparseCooTensor(shape={self.shape}, nnz={self.nnz})"


class SparseCsrTensor:
    def __init__(self, crows, cols, values, shape):
        self.crows = crows if isinstance(crows, Tensor) else Tensor(np.asarray(crows))
        self.cols = cols if isinstance(cols, Tensor) else Tensor(np.asarray(cols))
        self.values = values if isinstance(values, Tensor) else Tensor(np.asarray(values))
        self.shape = list(shape)

    def to_dense(self) -> Tensor:
        crows = np.asarray(self.crows._value)
        cols = np.asarray(self.cols._value)
        vals = np.asarray(self.values._value)
        dense = np.zeros(tuple(self.shape), vals.dtype)
        for r in range(self.shape[0]):
            for i in range(crows[r], crows[r + 1]):
                dense[r, cols[i]] += vals[i]
        return Tensor(dense)

    def to_sparse_coo(self, sparse_dim=2):
        dense = np.asarray(self.to_dense()._value)
        idx = np.stack(np.nonzero(dense))
        return SparseCooTensor(idx, dense[tuple(idx)], self.shape)


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    ind = np.asarray(indices._value if isinstance(indices, Tensor) else indices)
    val = np.asarray(values._value if isinstance(values, Tensor) else values)
    if shape is None:
        shape = list(ind.max(axis=1) + 1)
    return SparseCooTensor(ind, val, shape)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    return SparseCsrTensor(crows, cols, values, shape)


def add(x, y):
    return sparse_from_dense(x.to_dense() + y.to_dense())


def matmul(x, y):
    xd = x.to_dense() if isinstance(x, (SparseCooTensor, SparseCsrTensor)) else x
    yd = y.to_dense() if isinstance(y, (SparseCooTensor, SparseCsrTensor)) else y
    from ..ops.linalg import matmul as dense_matmul

    return dense_matmul(xd, yd)


def relu(x):
    from ..core.tensor import Tensor as _T

    return SparseCooTensor(x.indices, _T(jnp.maximum(x.values._value, 0)), x.shape)


def sparse_from_dense(dense: Tensor, sparse_dim=None):
    arr = np.asarray(dense._value)
    idx = np.stack(np.nonzero(arr))
    return SparseCooTensor(idx, arr[tuple(idx)], list(arr.shape))
