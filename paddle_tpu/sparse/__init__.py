"""paddle_tpu.sparse — COO/CSR tensors and ops.

Parity: python/paddle/sparse/ (creation.py sparse_coo/csr_tensor; unary.py
zero-preserving elementwise + coalesce/transpose/sum/cast; binary.py
matmul/masked_matmul/mv/add/subtract/multiply/divide/mask_as; nn/ ReLU,
BatchNorm, Conv2D/3D, SubmConv3D — the sparse_ops.yaml kernel set).

TPU-native design: values/indices are jax arrays; zero-preserving unary ops
map over values only (never densify); ``matmul`` lowers through
jax.experimental.sparse BCOO dot_general (XLA's sparse-dense path);
add/subtract stay sparse via concat+coalesce. Ops without a sensible sparse
lowering on TPU (divide by a sparse operand, general conv) compute densely
and re-sparsify — documented per function. Submanifold conv keeps the
reference's defining property: outputs only at active input sites.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor

__all__ = [
    "SparseCooTensor", "SparseCsrTensor", "sparse_coo_tensor",
    "sparse_csr_tensor", "sparse_from_dense", "coalesce", "is_same_shape",
    "add", "subtract", "multiply", "divide", "matmul", "masked_matmul",
    "mv", "mask_as", "transpose", "sum", "cast", "neg",
    "abs", "pow", "sin", "tan", "asin", "atan", "sinh", "asinh", "atanh",
    "tanh", "square", "sqrt", "log1p", "expm1", "rad2deg", "deg2rad",
    "relu", "isnan", "nn",
]


def _val(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


class SparseCooTensor:
    """COO sparse tensor: indices [ndim_sparse, nnz] + values [nnz, ...]."""

    def __init__(self, indices, values, shape, coalesced=False):
        self.indices = indices if isinstance(indices, Tensor) \
            else Tensor(jnp.asarray(_val(indices), jnp.int32))
        self.values = values if isinstance(values, Tensor) \
            else Tensor(_val(values))
        self.shape = list(int(s) for s in shape)
        self._coalesced = coalesced

    # -- conversions ------------------------------------------------------
    def to_dense(self) -> Tensor:
        dense = jnp.zeros(tuple(self.shape), self.values._value.dtype)
        idx = tuple(self.indices._value.astype(jnp.int32))
        return Tensor(dense.at[idx].add(self.values._value))

    def to_sparse_csr(self) -> "SparseCsrTensor":
        if len(self.shape) != 2:
            raise ValueError("CSR requires 2-D")
        c = coalesce(self)
        rows = np.asarray(c.indices._value[0])
        cols = np.asarray(c.indices._value[1])
        crows = np.zeros(self.shape[0] + 1, np.int64)
        np.add.at(crows, rows + 1, 1)
        return SparseCsrTensor(np.cumsum(crows), cols,
                               c.values._value, self.shape)

    # -- surface ----------------------------------------------------------
    @property
    def nnz(self):
        return self.values.shape[0]

    @property
    def dtype(self):
        return self.values.dtype

    def coalesce(self):
        return coalesce(self)

    def transpose(self, perm):
        return transpose(self, perm)

    def matmul(self, other):
        return matmul(self, other)

    def __repr__(self):
        return f"SparseCooTensor(shape={self.shape}, nnz={self.nnz})"


class SparseCsrTensor:
    def __init__(self, crows, cols, values, shape):
        self.crows = crows if isinstance(crows, Tensor) \
            else Tensor(jnp.asarray(np.asarray(crows), jnp.int32))
        self.cols = cols if isinstance(cols, Tensor) \
            else Tensor(jnp.asarray(np.asarray(cols), jnp.int32))
        self.values = values if isinstance(values, Tensor) \
            else Tensor(_val(values))
        self.shape = list(int(s) for s in shape)

    @property
    def nnz(self):
        return self.values.shape[0]

    def to_sparse_coo(self, sparse_dim=2) -> SparseCooTensor:
        crows = np.asarray(self.crows._value)
        rows = np.repeat(np.arange(self.shape[0]), np.diff(crows))
        idx = jnp.stack([jnp.asarray(rows, jnp.int32),
                         self.cols._value.astype(jnp.int32)])
        return SparseCooTensor(Tensor(idx), self.values, self.shape,
                               coalesced=True)

    def to_dense(self) -> Tensor:
        return self.to_sparse_coo().to_dense()

    def __repr__(self):
        return f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz})"


# ---------------------------------------------------------------------------
# creation
# ---------------------------------------------------------------------------

def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    ind = np.asarray(_val(indices))
    val = _val(values)
    if dtype is not None:
        from ..framework import dtype as dtypes
        val = val.astype(dtypes.convert_dtype(dtype).np_dtype)
    if shape is None:
        shape = list(ind.max(axis=1) + 1)
    return SparseCooTensor(ind, val, shape)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    return SparseCsrTensor(crows, cols, values, shape)


def sparse_from_dense(dense, sparse_dim=None):
    arr = np.asarray(_val(dense))
    idx = np.stack(np.nonzero(arr)) if arr.ndim else np.zeros((0, 0))
    return SparseCooTensor(idx, arr[tuple(idx)], list(arr.shape),
                           coalesced=True)


def coalesce(x: SparseCooTensor) -> SparseCooTensor:
    """Sort indices, merge duplicates by summation (unary.py coalesce)."""
    if x._coalesced:
        return x
    ind = np.asarray(x.indices._value)
    vals = x.values._value
    if ind.shape[1] == 0:
        return SparseCooTensor(ind, vals, x.shape, coalesced=True)
    flat = np.ravel_multi_index(ind, tuple(x.shape[:ind.shape[0]]))
    order = np.argsort(flat, kind="stable")
    flat_sorted = flat[order]
    uniq = np.unique(flat_sorted)
    seg = np.searchsorted(uniq, flat_sorted)
    merged = jax.ops.segment_sum(vals[jnp.asarray(order)],
                                 jnp.asarray(seg), num_segments=len(uniq))
    new_ind = np.stack(np.unravel_index(uniq, tuple(x.shape[:ind.shape[0]])))
    return SparseCooTensor(new_ind, Tensor(merged), x.shape, coalesced=True)


def is_same_shape(x, y) -> bool:
    return list(x.shape) == list(y.shape)


# ---------------------------------------------------------------------------
# unary (zero-preserving: map over values, never densify)
# ---------------------------------------------------------------------------

def _unary(name, fn):
    def op(x, *args, **kwargs):
        if isinstance(x, SparseCsrTensor):
            return SparseCsrTensor(x.crows, x.cols,
                                   Tensor(fn(x.values._value, *args)),
                                   x.shape)
        return SparseCooTensor(x.indices, Tensor(fn(x.values._value, *args)),
                               x.shape, coalesced=x._coalesced)

    op.__name__ = name
    return op


sin = _unary("sin", jnp.sin)
tan = _unary("tan", jnp.tan)
asin = _unary("asin", jnp.arcsin)
atan = _unary("atan", jnp.arctan)
sinh = _unary("sinh", jnp.sinh)
asinh = _unary("asinh", jnp.arcsinh)
atanh = _unary("atanh", jnp.arctanh)
tanh = _unary("tanh", jnp.tanh)
square = _unary("square", jnp.square)
sqrt = _unary("sqrt", jnp.sqrt)
log1p = _unary("log1p", jnp.log1p)
expm1 = _unary("expm1", jnp.expm1)
abs = _unary("abs", jnp.abs)  # noqa: A001
neg = _unary("neg", jnp.negative)
rad2deg = _unary("rad2deg", jnp.rad2deg)
deg2rad = _unary("deg2rad", jnp.deg2rad)
relu = _unary("relu", lambda v: jnp.maximum(v, 0))
isnan = _unary("isnan", jnp.isnan)


def pow(x, factor, name=None):  # noqa: A001
    return _unary("pow", lambda v: jnp.power(v, factor))(x)


def cast(x, index_dtype=None, value_dtype=None, name=None):
    from ..framework import dtype as dtypes

    out = x
    if value_dtype is not None:
        out = _unary("cast", lambda v: v.astype(
            dtypes.convert_dtype(value_dtype).np_dtype))(out)
    if index_dtype is not None and isinstance(out, SparseCooTensor):
        out = SparseCooTensor(
            Tensor(out.indices._value.astype(
                dtypes.convert_dtype(index_dtype).np_dtype)),
            out.values, out.shape, coalesced=out._coalesced)
    return out


def transpose(x: SparseCooTensor, perm: Sequence[int], name=None):
    perm = list(perm)
    ind = x.indices._value[jnp.asarray(perm)]
    shape = [x.shape[p] for p in perm]
    return SparseCooTensor(Tensor(ind), x.values, shape)


def sum(x: SparseCooTensor, axis=None, dtype=None, keepdim=False,  # noqa: A001
        name=None):
    """Reduction over sparse dims (unary.py sum); axis reductions return a
    dense Tensor (the reference's sparse-sum also materializes per-axis)."""
    c = coalesce(x)
    if axis is None:
        return Tensor(jnp.sum(c.values._value))
    from ..ops import math as _m
    return _m.sum(c.to_dense(), axis=axis, keepdim=keepdim)


# ---------------------------------------------------------------------------
# binary
# ---------------------------------------------------------------------------

def _coo(x):
    if isinstance(x, SparseCsrTensor):
        return x.to_sparse_coo()
    return x


def add(x, y, name=None):
    """sparse + sparse via index concat + coalesce — stays sparse."""
    was_csr = isinstance(x, SparseCsrTensor)
    x, y = _coo(x), _coo(y)
    assert is_same_shape(x, y), (x.shape, y.shape)
    ind = jnp.concatenate([x.indices._value, y.indices._value], axis=1)
    vals = jnp.concatenate([x.values._value, y.values._value], axis=0)
    out = coalesce(SparseCooTensor(Tensor(ind), Tensor(vals), x.shape))
    return out.to_sparse_csr() if was_csr else out


def subtract(x, y, name=None):
    return add(x, neg(_coo(y)))


def multiply(x, y, name=None):
    """Elementwise product — nonzero only on the index intersection;
    computed densely then re-masked (documented dense lowering)."""
    was_csr = isinstance(x, SparseCsrTensor)
    xc, yc = _coo(x), _coo(y)
    dense = Tensor(xc.to_dense()._value * yc.to_dense()._value)
    out = mask_as(dense, coalesce(xc))
    return out.to_sparse_csr() if was_csr else out


def divide(x, y, name=None):
    was_csr = isinstance(x, SparseCsrTensor)
    xc, yc = _coo(x), _coo(y)
    dense = Tensor(xc.to_dense()._value / yc.to_dense()._value)
    out = mask_as(dense, coalesce(xc))
    return out.to_sparse_csr() if was_csr else out


def mask_as(x, mask, name=None):
    """Dense tensor masked by a sparse pattern → sparse (binary.py
    mask_as)."""
    m = coalesce(_coo(mask))
    idx = tuple(m.indices._value.astype(jnp.int32))
    vals = _val(x)[idx]
    return SparseCooTensor(m.indices, Tensor(vals), list(_val(x).shape),
                           coalesced=True)


def matmul(x, y, name=None):
    """sparse @ dense through jax.experimental.sparse BCOO dot_general (the
    XLA sparse-dense path); dense/csr operands accepted (binary.py
    matmul)."""
    from jax.experimental import sparse as jsparse

    if isinstance(x, (SparseCooTensor, SparseCsrTensor)):
        xc = coalesce(_coo(x))
        bc = jsparse.BCOO((xc.values._value, xc.indices._value.T),
                          shape=tuple(xc.shape))
        yv = y.to_dense()._value if isinstance(
            y, (SparseCooTensor, SparseCsrTensor)) else _val(y)
        return Tensor(bc @ yv)
    from ..ops.linalg import matmul as dense_matmul
    yv = y.to_dense() if isinstance(y, (SparseCooTensor, SparseCsrTensor)) \
        else y
    return dense_matmul(x, yv)


def masked_matmul(x, y, mask, name=None):
    """(x @ y) sampled at mask's sparsity — SDDMM (binary.py
    masked_matmul)."""
    m = coalesce(_coo(mask))
    rows = m.indices._value[0]
    cols = m.indices._value[1]
    xv, yv = _val(x), _val(y)
    vals = jnp.einsum("nk,nk->n", xv[rows], yv[:, cols].T)
    return SparseCooTensor(m.indices, Tensor(vals), m.shape, coalesced=True)


def mv(x, vec, name=None):
    return matmul(x, vec)


from . import nn  # noqa: E402,F401


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):  # noqa: A002
    """parity: sparse/binary.py addmm — beta*input + alpha*(x@y); x sparse
    (COO/CSR), input/y dense."""
    prod = matmul(x, y)
    from ..ops import math as _m

    return _m.add(_m.scale(input, beta), _m.scale(prod, alpha))


def reshape(x, shape, name=None):
    """parity: sparse/unary.py:882 reshape — reshapes the sparse dims by
    re-deriving indices through the flattened linear index (dense semantics
    preserved; supports -1 and 0 placeholders)."""
    old_shape = x.shape
    shape = list(int(s) for s in shape)
    for i, s in enumerate(shape):
        if s == 0:
            shape[i] = old_shape[i]
    if -1 in shape:
        total = int(np.prod(old_shape))
        known = int(np.prod([s for s in shape if s != -1]))
        shape[shape.index(-1)] = total // known
    coo = x.to_sparse_coo() if isinstance(x, SparseCsrTensor) else coalesce(x)
    idx = np.asarray(coo.indices._value).astype(np.int64)
    flat = np.ravel_multi_index(tuple(idx), tuple(old_shape))
    new_idx = np.stack(np.unravel_index(flat, tuple(shape)))
    out = SparseCooTensor(
        Tensor(jnp.asarray(new_idx, jnp.int32)), coo.values, shape,
        coalesced=True)
    if isinstance(x, SparseCsrTensor):
        return out.to_sparse_csr()
    return out


def slice(x, axes, starts, ends, name=None):  # noqa: A001
    """parity: sparse/unary.py:1017 slice — multi-axis slicing of a sparse
    tensor (negative indices wrap)."""
    coo = x.to_sparse_coo() if isinstance(x, SparseCsrTensor) else coalesce(x)
    idx = np.asarray(coo.indices._value).astype(np.int64)
    vals = np.asarray(coo.values._value)
    shape = list(coo.shape)
    keep = np.ones(idx.shape[1], bool)
    new_shape = list(shape)
    offsets = {}
    for ax, st, en in zip(_as_ints(axes), _as_ints(starts), _as_ints(ends)):
        n = shape[ax]
        st = st + n if st < 0 else min(st, n)
        en = en + n if en < 0 else min(en, n)
        keep &= (idx[ax] >= st) & (idx[ax] < en)
        offsets[ax] = st
        new_shape[ax] = max(0, en - st)
    idx = idx[:, keep]
    for ax, st in offsets.items():
        idx[ax] -= st
    out = SparseCooTensor(Tensor(jnp.asarray(idx, jnp.int32)),
                          Tensor(jnp.asarray(vals[keep])), new_shape,
                          coalesced=True)
    if isinstance(x, SparseCsrTensor):
        return out.to_sparse_csr()
    return out


def _as_ints(v):
    if isinstance(v, Tensor):
        return [int(i) for i in np.asarray(v._value).reshape(-1)]
    return [int(i) for i in v]


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """parity: sparse pca_lowrank — densify (randomized PCA needs dense
    matmuls on TPU) and run linalg.pca_lowrank."""
    from ..ops import linalg as _linalg

    dense = x.to_dense() if isinstance(
        x, (SparseCooTensor, SparseCsrTensor)) else x
    return _linalg.pca_lowrank(dense, q=q, center=center, niter=niter)


__all__ += ["addmm", "reshape", "slice", "pca_lowrank"]
