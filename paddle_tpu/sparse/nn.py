"""paddle_tpu.sparse.nn — sparse layers.

Parity: python/paddle/sparse/nn/ (layer/activation.py ReLU/LeakyReLU/
Softmax, layer/norm.py BatchNorm/SyncBatchNorm, layer/conv.py Conv2D/Conv3D/
SubmConv3D/SubmConv2D over the sparse conv kernels).

TPU-native design: sparse activations/norms operate on the COO values array
only (channels-last values [nnz, C] — the reference's layout). Convolutions
compute via the dense MXU path and re-sparsify: ordinary conv takes the
natural output sparsity; submanifold conv masks outputs to the INPUT's
active sites — the property that makes SubmConv3D keep sparsity through
deep nets (Graham et al.), preserved exactly.
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..nn.layer.layers import Layer

__all__ = ["ReLU", "LeakyReLU", "Softmax", "BatchNorm", "SubmConv2D",
           "SubmConv3D", "Conv2D", "Conv3D", "functional"]


class ReLU(Layer):
    def forward(self, x):
        from . import relu
        return relu(x)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01):
        super().__init__()
        self._slope = negative_slope

    def forward(self, x):
        from . import _unary
        return _unary("leaky_relu", lambda v: jnp.where(
            v > 0, v, self._slope * v))(x)


class Softmax(Layer):
    """Softmax over the last dense (values) axis per nonzero row."""

    def __init__(self, axis=-1):
        super().__init__()

    def forward(self, x):
        from . import _unary
        return _unary("softmax", lambda v: jax.nn.softmax(v, axis=-1))(x)


class BatchNorm(Layer):
    """BatchNorm over sparse values [nnz, C] (sparse/nn/layer/norm.py:30):
    statistics across the nonzero sites only, running stats tracked like the
    dense layer."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 use_global_stats=None, name=None):
        super().__init__()
        from ..nn import initializer as I

        self._momentum = momentum
        self._eps = epsilon
        self.weight = self.create_parameter(
            [num_features], default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter(
            [num_features], default_initializer=I.Constant(0.0))
        self.register_buffer("_mean", Tensor(jnp.zeros([num_features])))
        self.register_buffer("_variance", Tensor(jnp.ones([num_features])))

    def forward(self, x):
        from . import SparseCooTensor

        v = x.values._value
        if self.training:
            mean = jnp.mean(v, axis=0)
            var = jnp.var(v, axis=0)
            m = self._momentum
            self._mean._replace_value(m * self._mean._value + (1 - m) * mean)
            self._variance._replace_value(
                m * self._variance._value + (1 - m) * var)
        else:
            mean, var = self._mean._value, self._variance._value
        out = (v - mean) * jax.lax.rsqrt(var + self._eps) \
            * self.weight._value + self.bias._value
        return SparseCooTensor(x.indices, Tensor(out), x.shape,
                               coalesced=x._coalesced)


class _SparseConv(Layer):
    """Shared machinery: densify → lax.conv (MXU) → re-sparsify."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, subm=False,
                 padding_mode="zeros", weight_attr=None, bias_attr=None,
                 data_format="NDHWC", nd=3):
        super().__init__()
        self._nd = nd
        self._subm = subm
        ks = kernel_size if isinstance(kernel_size, (list, tuple)) \
            else (kernel_size,) * nd
        self._ks = tuple(int(k) for k in ks)
        self._stride = stride if isinstance(stride, (list, tuple)) \
            else (stride,) * nd
        self._padding = padding if isinstance(padding, (list, tuple)) \
            else (padding,) * nd
        self._dilation = dilation if isinstance(dilation, (list, tuple)) \
            else (dilation,) * nd
        from ..nn import initializer as I

        self._groups = groups
        fan_in = in_channels * int(np.prod(self._ks))
        bound = 1.0 / math.sqrt(fan_in)
        self.weight = self.create_parameter(
            list(self._ks) + [in_channels // groups, out_channels],
            default_initializer=I.Uniform(-bound, bound))
        if bias_attr is not False:
            self.bias = self.create_parameter(
                [out_channels], default_initializer=I.Constant(0.0))
        else:
            self.bias = None

    def forward(self, x):
        from . import SparseCooTensor

        dense = x.to_dense()._value  # [N, *spatial, C] channels-last
        nd = self._nd
        dn = jax.lax.conv_dimension_numbers(
            dense.shape, self.weight._value.shape,
            ("NDHWC", "DHWIO", "NDHWC") if nd == 3
            else ("NHWC", "HWIO", "NHWC"))
        if self._subm:
            # submanifold: stride 1, SAME padding (asymmetric for even
            # kernels) so output sites line up 1:1 with input sites
            pads = [(((k - 1) * d) // 2, (k - 1) * d - ((k - 1) * d) // 2)
                    for k, d in zip(self._ks, self._dilation)]
            out = jax.lax.conv_general_dilated(
                dense, self.weight._value, (1,) * nd, pads,
                rhs_dilation=self._dilation, dimension_numbers=dn,
                feature_group_count=self._groups)
        else:
            pads = [(p, p) for p in self._padding]
            out = jax.lax.conv_general_dilated(
                dense, self.weight._value, tuple(self._stride), pads,
                rhs_dilation=self._dilation, dimension_numbers=dn,
                feature_group_count=self._groups)
        if self.bias is not None:
            out = out + self.bias._value
        if self._subm:
            # outputs only at the INPUT's active sites (same indices)
            c = x.coalesce()
            site_idx = c.indices._value  # [nd+1, nnz] (batch + spatial)
            vals = out[tuple(site_idx[i]
                             for i in range(site_idx.shape[0]))]
            return SparseCooTensor(c.indices, Tensor(vals),
                                   list(out.shape), coalesced=True)
        # output sparsity is STRUCTURAL (reachable from input sites via the
        # kernel support), not value-based — a bias must not densify, and
        # off-support sites stay zero exactly like the reference kernels
        occ = (jnp.any(dense != 0, axis=-1, keepdims=True)
               .astype(dense.dtype))
        ones_k = jnp.ones(self._ks + (1, 1), dense.dtype)
        reach = jax.lax.conv_general_dilated(
            occ, ones_k, tuple(self._stride),
            [(p, p) for p in self._padding], rhs_dilation=self._dilation,
            dimension_numbers=dn)
        active = np.stack(np.nonzero(np.asarray(reach[..., 0]) > 0))
        out = out * (reach > 0)  # zero off-support sites (incl. bias)
        vals = out[tuple(active[i] for i in range(active.shape[0]))]
        return SparseCooTensor(active, Tensor(vals), list(out.shape),
                               coalesced=True)


class Conv3D(_SparseConv):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NDHWC"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, subm=False,
                         bias_attr=bias_attr, nd=3)


class SubmConv3D(_SparseConv):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 key=None, weight_attr=None, bias_attr=None,
                 data_format="NDHWC"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, subm=True,
                         bias_attr=bias_attr, nd=3)


class Conv2D(_SparseConv):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NHWC"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, subm=False,
                         bias_attr=bias_attr, nd=2)


class SubmConv2D(_SparseConv):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 key=None, weight_attr=None, bias_attr=None,
                 data_format="NHWC"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, subm=True,
                         bias_attr=bias_attr, nd=2)


class functional:  # namespace parity: paddle.sparse.nn.functional
    @staticmethod
    def relu(x):
        from . import relu as _r
        return _r(x)

    @staticmethod
    def softmax(x, axis=-1):
        return Softmax()(x)

    @staticmethod
    def attention(query, key, value, sparse_mask, key_padding_mask=None,
                  attn_mask=None, name=None):
        """Sparse-mask attention (functional/transformer.py): dense QK^T
        sampled at the mask pattern, softmax over present keys, then AV."""
        from . import masked_matmul

        q, k, v = query._value, key._value, value._value
        scale = 1.0 / math.sqrt(q.shape[-1])
        # [B, H, S, D] dense path with mask applied densely (docs note:
        # the sparse pattern is honored via -inf masking)
        scores = jnp.einsum("bhsd,bhtd->bhst", q, k) * scale
        mask_dense = sparse_mask.to_dense()._value
        if mask_dense.ndim == 3:
            # paddle contract: [batch*num_heads, S, S]
            mask_dense = mask_dense.reshape(scores.shape)
        scores = jnp.where(mask_dense != 0, scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum("bhst,bhtd->bhsd", probs, v)
        return Tensor(out)


class ReLU6(Layer):
    """parity: sparse/nn ReLU6 — zero-preserving clip to [0, 6]."""

    def forward(self, x):
        from . import _unary

        return _unary("relu6", lambda v: jnp.clip(v, 0.0, 6.0))(x)


class MaxPool3D(Layer):
    """parity: sparse/nn MaxPool3D — pools the dense form (sparsity after a
    max-pool is data-dependent; output returned sparse)."""

    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NDHWC", name=None):
        super().__init__()
        self._args = (kernel_size, stride, padding)

    def forward(self, x):
        from ..nn import functional as F
        from ..ops.creation import _t as _tt
        from . import sparse_from_dense

        k, s, p = self._args
        dense = x.to_dense()
        # sparse layout is NDHWC; dense max_pool3d expects NCDHW
        v = jnp.moveaxis(dense._value, -1, 1)
        out = F.max_pool3d(Tensor(v), k, s, p)
        out_v = jnp.moveaxis(_tt(out)._value, 1, -1)
        return sparse_from_dense(Tensor(out_v))


class SyncBatchNorm(BatchNorm):
    """parity: sparse/nn SyncBatchNorm — under GSPMD the batch statistics
    psum falls out of sharding; same computation as BatchNorm here."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        return layer


def _unary_apply(x, fn):
    """Zero-preserving elementwise op via the package _unary helper
    (preserves the coalesced flag)."""
    from . import _unary

    return _unary("sparse_unary", fn)(x)


def _sparse_conv_fn(x, weight, bias, stride, padding, dilation, groups,
                    subm, nd):
    """Shared functional conv over the sparse layer machinery."""
    w = weight if hasattr(weight, "_value") else Tensor(weight)
    ks = tuple(int(k) for k in w.shape[:nd])
    in_ch = int(w.shape[nd]) * groups
    out_ch = int(w.shape[nd + 1])
    cls = {2: (SubmConv2D if subm else Conv2D),
           3: (SubmConv3D if subm else Conv3D)}[nd]
    # go through the real constructor (future __init__ attrs stay valid),
    # then install the caller's weight/bias
    layer = cls(in_ch, out_ch, ks, stride=stride, padding=padding,
                dilation=dilation, groups=groups, bias_attr=False)
    layer.weight = w
    layer.bias = (bias if bias is None or hasattr(bias, "_value")
                  else Tensor(bias))
    return layer.forward(x)


def _add_functional():
    F = functional

    def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
               groups=1, data_format="NDHWC", name=None):
        return _sparse_conv_fn(x, weight, bias, stride, padding, dilation,
                               groups, False, 3)

    def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                    groups=1, data_format="NDHWC", key=None, name=None):
        return _sparse_conv_fn(x, weight, bias, stride, padding, dilation,
                               groups, True, 3)

    def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1,
               groups=1, data_format="NHWC", name=None):
        return _sparse_conv_fn(x, weight, bias, stride, padding, dilation,
                               groups, False, 2)

    def subm_conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                    groups=1, data_format="NHWC", key=None, name=None):
        return _sparse_conv_fn(x, weight, bias, stride, padding, dilation,
                               groups, True, 2)

    def relu6(x, name=None):
        return _unary_apply(x, lambda v: jnp.clip(v, 0.0, 6.0))

    def leaky_relu(x, negative_slope=0.01, name=None):
        return _unary_apply(
            x, lambda v: jnp.where(v > 0, v, negative_slope * v))

    def max_pool3d(x, kernel_size, stride=None, padding=0,
                   data_format="NDHWC", name=None):
        return MaxPool3D(kernel_size, stride, padding)(x)

    F.conv2d = staticmethod(conv2d)
    F.conv3d = staticmethod(conv3d)
    F.subm_conv2d = staticmethod(subm_conv2d)
    F.subm_conv3d = staticmethod(subm_conv3d)
    # igemm variants: same math, different GPU kernel strategy in the
    # reference (implicit gemm); one XLA lowering here
    F.subm_conv2d_igemm = staticmethod(subm_conv2d)
    F.subm_conv3d_igemm = staticmethod(subm_conv3d)
    F.relu6 = staticmethod(relu6)
    F.leaky_relu = staticmethod(leaky_relu)
    F.max_pool3d = staticmethod(max_pool3d)


_add_functional()
__all__ += ["ReLU6", "MaxPool3D", "SyncBatchNorm"]
