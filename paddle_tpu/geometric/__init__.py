"""paddle.geometric parity — graph message passing + segment ops.

Reference: python/paddle/geometric/ (send_u_recv / send_ue_recv message
passing, segment_{sum,mean,max,min}, sample_neighbors, reindex_graph).
TPU-native: jax.ops.segment_* (XLA scatter-reduce — no atomics needed on
TPU's deterministic scatter).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..ops.creation import _t
from ..ops.dispatch import apply

__all__ = [
    "segment_sum", "segment_mean", "segment_max", "segment_min",
    "send_u_recv", "send_ue_recv", "send_uv", "reindex_graph",
    "sample_neighbors",
]


def _nseg(segment_ids, num_segments):
    if num_segments is not None:
        return int(num_segments)
    return int(np.asarray(jnp.max(_t(segment_ids)._value)) + 1)


def segment_sum(data, segment_ids, name=None, num_segments=None):
    n = _nseg(segment_ids, num_segments)
    return apply("segment_sum",
                 lambda d, s: jax.ops.segment_sum(d, s, num_segments=n),
                 _t(data), _t(segment_ids))


def segment_mean(data, segment_ids, name=None, num_segments=None):
    n = _nseg(segment_ids, num_segments)

    def fn(d, s):
        tot = jax.ops.segment_sum(d, s, num_segments=n)
        cnt = jax.ops.segment_sum(jnp.ones_like(d), s, num_segments=n)
        return tot / jnp.maximum(cnt, 1)

    return apply("segment_mean", fn, _t(data), _t(segment_ids))


def segment_max(data, segment_ids, name=None, num_segments=None):
    n = _nseg(segment_ids, num_segments)
    return apply("segment_max",
                 lambda d, s: jax.ops.segment_max(d, s, num_segments=n),
                 _t(data), _t(segment_ids))


def segment_min(data, segment_ids, name=None, num_segments=None):
    n = _nseg(segment_ids, num_segments)
    return apply("segment_min",
                 lambda d, s: jax.ops.segment_min(d, s, num_segments=n),
                 _t(data), _t(segment_ids))


_POOLS = {"sum": jax.ops.segment_sum, "mean": None, "max": jax.ops.segment_max,
          "min": jax.ops.segment_min}


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Gather x[src], scatter-reduce onto dst (reference:
    geometric/message_passing/send_recv.py)."""
    n = out_size or int(np.asarray(jnp.max(_t(dst_index)._value)) + 1)

    def fn(xv, si, di):
        msgs = xv[si]
        if reduce_op == "mean":
            tot = jax.ops.segment_sum(msgs, di, num_segments=n)
            cnt = jax.ops.segment_sum(jnp.ones((msgs.shape[0],) + (1,) * (msgs.ndim - 1),
                                               msgs.dtype), di, num_segments=n)
            return tot / jnp.maximum(cnt, 1)
        return _POOLS[reduce_op](msgs, di, num_segments=n)

    return apply("send_u_recv", fn, _t(x), _t(src_index), _t(dst_index))


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """Message = combine(x[src], edge_feature y), then scatter-reduce."""
    n = out_size or int(np.asarray(jnp.max(_t(dst_index)._value)) + 1)

    def fn(xv, yv, si, di):
        m = xv[si]
        if message_op == "add":
            m = m + yv
        elif message_op == "sub":
            m = m - yv
        elif message_op == "mul":
            m = m * yv
        elif message_op == "div":
            m = m / yv
        if reduce_op == "mean":
            tot = jax.ops.segment_sum(m, di, num_segments=n)
            cnt = jax.ops.segment_sum(
                jnp.ones((m.shape[0],) + (1,) * (m.ndim - 1), m.dtype), di,
                num_segments=n)
            return tot / jnp.maximum(cnt, 1)
        return _POOLS[reduce_op](m, di, num_segments=n)

    return apply("send_ue_recv", fn, _t(x), _t(y), _t(src_index), _t(dst_index))


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """Per-edge message from both endpoints (no reduce)."""
    def fn(xv, yv, si, di):
        a, b = xv[si], yv[di]
        return {"add": a + b, "sub": a - b, "mul": a * b,
                "div": a / b}[message_op]

    return apply("send_uv", fn, _t(x), _t(y), _t(src_index), _t(dst_index))


def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None,
                  name=None):
    """Compact global node ids to local ids (host-side — graph prep is not a
    jit path)."""
    xv = np.asarray(_t(x)._value)
    nb = np.asarray(_t(neighbors)._value)
    uniq, inv = np.unique(np.concatenate([xv, nb]), return_inverse=True)
    order = {int(v): i for i, v in enumerate(xv)}
    remap = np.empty(len(uniq), np.int64)
    nxt = len(xv)
    out_nodes = list(xv)
    for u in uniq:
        if int(u) in order:
            remap[np.searchsorted(uniq, u)] = order[int(u)]
        else:
            remap[np.searchsorted(uniq, u)] = nxt
            out_nodes.append(u)
            nxt += 1
    reindexed = remap[inv[len(xv):]]
    return (Tensor(jnp.asarray(reindexed)),
            Tensor(jnp.asarray(np.asarray(out_nodes))),
            Tensor(_t(count)._value))


def sample_neighbors(row, colptr, input_nodes, sample_size=-1,
                     eids=None, return_eids=False, perm_buffer=None,
                     name=None):
    """Uniform neighbor sampling on a CSC graph (host-side)."""
    rng = np.random.default_rng()
    rowv = np.asarray(_t(row)._value)
    cp = np.asarray(_t(colptr)._value)
    nodes = np.asarray(_t(input_nodes)._value)
    out, counts = [], []
    for nmid in nodes:
        lo, hi = int(cp[nmid]), int(cp[nmid + 1])
        nbrs = rowv[lo:hi]
        if 0 <= sample_size < len(nbrs):
            nbrs = rng.choice(nbrs, size=sample_size, replace=False)
        out.append(nbrs)
        counts.append(len(nbrs))
    cat = np.concatenate(out) if out else np.zeros((0,), rowv.dtype)
    return Tensor(jnp.asarray(cat)), Tensor(jnp.asarray(np.asarray(counts)))
