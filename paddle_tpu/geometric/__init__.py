"""paddle.geometric parity — graph message passing + segment ops.

Reference: python/paddle/geometric/ (send_u_recv / send_ue_recv message
passing, segment_{sum,mean,max,min}, sample_neighbors, reindex_graph).
TPU-native: jax.ops.segment_* (XLA scatter-reduce — no atomics needed on
TPU's deterministic scatter).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..ops.creation import _t
from ..ops.dispatch import apply

__all__ = [
    "segment_sum", "segment_mean", "segment_max", "segment_min",
    "send_u_recv", "send_ue_recv", "send_uv", "reindex_graph",
    "reindex_heter_graph", "sample_neighbors", "weighted_sample_neighbors",
]


def _nseg(segment_ids, num_segments):
    if num_segments is not None:
        return int(num_segments)
    return int(np.asarray(jnp.max(_t(segment_ids)._value)) + 1)


def segment_sum(data, segment_ids, name=None, num_segments=None):
    n = _nseg(segment_ids, num_segments)
    return apply("segment_sum",
                 lambda d, s: jax.ops.segment_sum(d, s, num_segments=n),
                 _t(data), _t(segment_ids))


def segment_mean(data, segment_ids, name=None, num_segments=None):
    n = _nseg(segment_ids, num_segments)

    def fn(d, s):
        tot = jax.ops.segment_sum(d, s, num_segments=n)
        cnt = jax.ops.segment_sum(jnp.ones_like(d), s, num_segments=n)
        return tot / jnp.maximum(cnt, 1)

    return apply("segment_mean", fn, _t(data), _t(segment_ids))


def segment_max(data, segment_ids, name=None, num_segments=None):
    n = _nseg(segment_ids, num_segments)
    return apply("segment_max",
                 lambda d, s: jax.ops.segment_max(d, s, num_segments=n),
                 _t(data), _t(segment_ids))


def segment_min(data, segment_ids, name=None, num_segments=None):
    n = _nseg(segment_ids, num_segments)
    return apply("segment_min",
                 lambda d, s: jax.ops.segment_min(d, s, num_segments=n),
                 _t(data), _t(segment_ids))


_POOLS = {"sum": jax.ops.segment_sum, "mean": None, "max": jax.ops.segment_max,
          "min": jax.ops.segment_min}


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Gather x[src], scatter-reduce onto dst (reference:
    geometric/message_passing/send_recv.py)."""
    n = out_size or int(np.asarray(jnp.max(_t(dst_index)._value)) + 1)

    def fn(xv, si, di):
        msgs = xv[si]
        if reduce_op == "mean":
            tot = jax.ops.segment_sum(msgs, di, num_segments=n)
            cnt = jax.ops.segment_sum(jnp.ones((msgs.shape[0],) + (1,) * (msgs.ndim - 1),
                                               msgs.dtype), di, num_segments=n)
            return tot / jnp.maximum(cnt, 1)
        return _POOLS[reduce_op](msgs, di, num_segments=n)

    return apply("send_u_recv", fn, _t(x), _t(src_index), _t(dst_index))


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """Message = combine(x[src], edge_feature y), then scatter-reduce."""
    n = out_size or int(np.asarray(jnp.max(_t(dst_index)._value)) + 1)

    def fn(xv, yv, si, di):
        m = xv[si]
        if message_op == "add":
            m = m + yv
        elif message_op == "sub":
            m = m - yv
        elif message_op == "mul":
            m = m * yv
        elif message_op == "div":
            m = m / yv
        if reduce_op == "mean":
            tot = jax.ops.segment_sum(m, di, num_segments=n)
            cnt = jax.ops.segment_sum(
                jnp.ones((m.shape[0],) + (1,) * (m.ndim - 1), m.dtype), di,
                num_segments=n)
            return tot / jnp.maximum(cnt, 1)
        return _POOLS[reduce_op](m, di, num_segments=n)

    return apply("send_ue_recv", fn, _t(x), _t(y), _t(src_index), _t(dst_index))


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """Per-edge message from both endpoints (no reduce)."""
    def fn(xv, yv, si, di):
        a, b = xv[si], yv[di]
        return {"add": a + b, "sub": a - b, "mul": a * b,
                "div": a / b}[message_op]

    return apply("send_uv", fn, _t(x), _t(y), _t(src_index), _t(dst_index))


def _reindex_impl(xv, nb_cat):
    """Local-id mapping: x first (ids 0..len(x)-1), then neighbor nodes in
    first-seen order — the reference's graph_reindex contract
    (geometric/reindex.py:34 example ordering)."""
    cat = np.concatenate([xv, nb_cat])
    uniq, first_idx, inv = np.unique(cat, return_index=True,
                                     return_inverse=True)
    order = np.argsort(first_idx, kind="stable")
    rank = np.empty(len(order), np.int64)
    rank[order] = np.arange(len(order))
    local = rank[inv]
    out_nodes = cat[np.sort(first_idx)]
    return local[len(xv):], out_nodes


def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None,
                  name=None):
    """parity: geometric/reindex.py:34 reindex_graph → (reindex_src,
    reindex_dst, out_nodes). Host-side — graph prep is not a jit path."""
    xv = np.asarray(_t(x)._value).reshape(-1)
    nb = np.asarray(_t(neighbors)._value).reshape(-1)
    cnt = np.asarray(_t(count)._value).reshape(-1)
    src, out_nodes = _reindex_impl(xv, nb)
    dst = np.repeat(np.arange(len(xv), dtype=np.int64), cnt)
    return (Tensor(jnp.asarray(src)), Tensor(jnp.asarray(dst)),
            Tensor(jnp.asarray(out_nodes)))


def reindex_heter_graph(x, neighbors, count, value_buffer=None,
                        index_buffer=None, name=None):
    """parity: geometric/reindex.py:153 reindex_heter_graph — neighbors /
    count are per-edge-type lists; edges are concatenated in graph order and
    all nodes share one local-id space."""
    xv = np.asarray(_t(x)._value).reshape(-1)
    nbs = [np.asarray(_t(nb)._value).reshape(-1) for nb in neighbors]
    cnts = [np.asarray(_t(c)._value).reshape(-1) for c in count]
    nb_cat = (np.concatenate(nbs) if nbs
              else np.zeros((0,), xv.dtype))
    src, out_nodes = _reindex_impl(xv, nb_cat)
    dst = np.concatenate([
        np.repeat(np.arange(len(xv), dtype=np.int64), c) for c in cnts
    ]) if cnts else np.zeros((0,), np.int64)
    return (Tensor(jnp.asarray(src)), Tensor(jnp.asarray(dst)),
            Tensor(jnp.asarray(out_nodes)))


def _sample_csc(row, colptr, input_nodes, sample_size, eids, return_eids,
                weight=None):
    # seed from the framework RNG stream so paddle.seed() reproduces
    # sampling like every other random op
    from ..framework.random import next_key

    rng = np.random.default_rng(
        np.asarray(jax.random.key_data(next_key())).view(np.uint32))
    rowv = np.asarray(_t(row)._value).reshape(-1)
    cp = np.asarray(_t(colptr)._value).reshape(-1)
    nodes = np.asarray(_t(input_nodes)._value).reshape(-1)
    ev = (np.asarray(_t(eids)._value).reshape(-1) if eids is not None
          else None)
    wv = (np.asarray(_t(weight)._value).reshape(-1) if weight is not None
          else None)
    out, out_eids, counts = [], [], []
    for nmid in nodes:
        lo, hi = int(cp[nmid]), int(cp[nmid + 1])
        pick = np.arange(lo, hi)
        if 0 <= sample_size < hi - lo:
            if wv is not None:
                # Efraimidis–Spirakis: smallest Exp(1)/w keys = weighted
                # sample without replacement; zero-weight edges get +inf
                # keys so they fill remaining slots (random tiebreak)
                # rather than crashing when positives < sample_size.
                w = wv[lo:hi].astype(np.float64)
                keys = np.where(
                    w > 0, rng.exponential(size=hi - lo)
                    / np.where(w > 0, w, 1.0), np.inf)
                order = np.lexsort((rng.random(hi - lo), keys))
                pick = pick[order[:sample_size]]
            else:
                pick = rng.choice(pick, size=sample_size, replace=False)
        out.append(rowv[pick])
        if ev is not None:
            out_eids.append(ev[pick])
        counts.append(len(pick))
    cat = np.concatenate(out) if out else np.zeros((0,), rowv.dtype)
    res = [Tensor(jnp.asarray(cat)),
           Tensor(jnp.asarray(np.asarray(counts, np.int32)))]
    if return_eids:
        if ev is None:
            raise ValueError("return_eids=True requires eids")
        ecat = (np.concatenate(out_eids) if out_eids
                else np.zeros((0,), rowv.dtype))
        res.append(Tensor(jnp.asarray(ecat)))
    return tuple(res)


def sample_neighbors(row, colptr, input_nodes, sample_size=-1,
                     eids=None, return_eids=False, perm_buffer=None,
                     name=None):
    """parity: geometric/sampling/neighbors.py sample_neighbors — uniform
    neighbor sampling on a CSC graph (host-side)."""
    return _sample_csc(row, colptr, input_nodes, sample_size, eids,
                       return_eids)


def weighted_sample_neighbors(row, colptr, edge_weight, input_nodes,
                              sample_size=-1, eids=None, return_eids=False,
                              name=None):
    """parity: geometric/sampling/neighbors.py:256 — selection probability
    proportional to edge weight, sampled without replacement."""
    return _sample_csc(row, colptr, input_nodes, sample_size, eids,
                       return_eids, weight=edge_weight)
