"""Crash-surviving wrapper over :class:`~paddle_tpu.serving.LLMEngine`.

A long-lived serving process meets failures training never sees twice:
a wedged readback, a device reset mid-call, an injected chaos fault.
The engine's own state machine is host-side and always consistent at
step boundaries, so the recovery move is cheap and total: drop the
poisoned in-flight wave (its tokens were never host-visible — the
stream stays exactly-once), requeue every in-flight request from its
traced host state (``prompt + generated + slot_out``: everything already
streamed is preserved and never re-emitted), rebuild the device carry
from scratch, and keep serving. The device pools' contents are suspect
after a crash, so the requeue is always recompute — the KV swap tier is
deliberately bypassed on this path.

    eng = LLMEngine(params, cfg, injector=FaultInjector("readback_fail@4"))
    results = ResilientEngine(eng).run()    # the crash is a blip, not an outage

Pairs with the seeded serving faults in
:mod:`paddle_tpu.distributed.resilience.faults` (``readback_fail`` /
``slow_step`` / ``pool_squeeze``) — ``tools/chaos_run.py --serving``
drives the full menu and asserts finish-or-shed with zero block leaks.
"""
from __future__ import annotations

from typing import Dict, List, Tuple, Type

from ..distributed.resilience.faults import SimulatedCrash
from ..observability import flight_recorder as _flight
from ..observability.catalog import instrument as _instrument

__all__ = ["ResilientEngine"]

_M_RECOVERIES = _instrument("serving_engine_recoveries_total")


class ResilientEngine:
    """Catch a crashed ``step()``, recover the engine, keep serving.

    ``recoverable``: exception types treated as a crashed step (default:
    the injectable :class:`SimulatedCrash`; widen to e.g. your backend's
    runtime-error type in production). Anything else propagates.
    ``max_recoveries`` bounds the crash budget — a deterministically
    crashing engine must surface, not spin.
    """

    def __init__(self, engine,
                 recoverable: Tuple[Type[BaseException], ...]
                 = (SimulatedCrash,),
                 max_recoveries: int = 8):
        self.engine = engine
        self.recoverable = tuple(recoverable)
        self.max_recoveries = int(max_recoveries)
        self.recoveries = 0

    # -- engine surface ---------------------------------------------------
    def add_request(self, prompt, **kw) -> int:
        return self.engine.add_request(prompt, **kw)

    def has_work(self) -> bool:
        return self.engine.has_work()

    @property
    def results(self) -> Dict[int, List[int]]:
        return self.engine.results

    @property
    def finish_reasons(self) -> Dict[int, str]:
        return self.engine.finish_reasons

    # -- the wrapper ------------------------------------------------------
    def step(self):
        """One engine step; on a recoverable crash, drop the poisoned
        wave, requeue its requests, and return the tokens the step had
        already committed before it died. A step can raise AFTER an
        earlier readback in it committed tokens host-side (slot_out /
        generated) — those ride the engine's salvage buffer and are
        delivered here exactly once (the requeue moves them to
        ``generated``, so re-admission never re-emits them); only the
        never-host-visible in-flight wave is dropped."""
        try:
            return self.engine.step()
        except self.recoverable as e:
            self.recoveries += 1
            if self.recoveries > self.max_recoveries:
                raise
            _M_RECOVERIES.inc()
            _flight.record("serving_step_recovered",
                           error=f"{type(e).__name__}: {e}"[:160],
                           recoveries=self.recoveries,
                           salvaged=len(self.engine._step_emitted))
            salvaged = list(self.engine._step_emitted)
            self.engine.recover_crashed_step()
            return salvaged

    def run(self) -> Dict[int, List[int]]:
        while self.engine.has_work():
            self.step()
        if self.engine._inflight is not None:   # defensive, as engine.run
            self.engine._process_inflight()
        self.engine.drain_offload()
        return self.engine.results
