"""Admission control and load shedding for the serving engine.

Under sustained overload an uncontrolled engine queue grows without
bound: every queued request eventually runs, but p95 TTFT collapses for
ALL of them — the failure mode is universal, not marginal. Admission
control converts that into *graceful* degradation: a bounded admission
queue, per-tenant token-bucket rate limits, and a reject-newest shed
policy driven by the engine's own pressure signals (queue depth, KV
block-pool headroom). A shed request fails in microseconds with a typed
error the frontend can turn into HTTP 429/503 + retry-after — the
requests that ARE admitted keep their latency.

Policy order (first breach wins; the stateless checks run BEFORE the
token bucket is charged, so a request shed for queue/pool reasons never
burns its tenant's rate budget):

1. ``queue_full``   — admission queue at ``max_queue`` entries;
2. ``pool_pressure`` — free KV blocks below ``shed_free_frac`` of the
   pool while work is queued: a new admission would only trade
   preemptions with the requests already inside. The engine's
   ``free_frac`` is CACHE-AWARE: refcount-0 prefix-cache blocks are
   reclaimable on demand (spill/drop, serving/prefix_cache.py), so a
   pool that merely looks full of evictable prefixes never sheds;
3. ``rate_limited`` — the request's tenant bucket lacks
   ``prompt + max_new_tokens`` tokens (cost model: every admitted token
   occupies slot time, prefill or decode).

The controller is a pure policy object — the engine owns the queue and
raises :class:`ShedError`; tests drive ``check`` directly with an
injected clock.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, Optional

from ..observability.catalog import instrument as _instrument

__all__ = ["AdmissionConfig", "AdmissionController", "ShedError",
           "TokenBucket"]

_M_SHED = _instrument("serving_shed_total")


class ShedError(RuntimeError):
    """A request rejected by admission control (load shedding).

    ``reason`` is one of ``queue_full`` / ``rate_limited`` /
    ``pool_pressure``; ``req_id`` is the id the engine minted for the
    rejected request (its trace, if observability is on, ends with a
    ``shed`` finish reason).
    """

    def __init__(self, reason: str, req_id=None):
        super().__init__(
            f"request{'' if req_id is None else f' {req_id}'} shed: "
            f"{reason}")
        self.reason = reason
        self.req_id = req_id


@dataclasses.dataclass
class AdmissionConfig:
    """Shed-policy knobs. Zero values disable the corresponding check
    (``max_queue`` excepted: a bounded queue is the point)."""

    max_queue: int = 64          # admission-queue depth bound
    rate_tokens_per_s: float = 0.0   # per-tenant refill rate (0 = off)
    burst_tokens: float = 0.0    # bucket capacity (0 = 2s of rate)
    shed_free_frac: float = 0.0  # shed when free-block fraction < this
    #                              while the queue is non-empty (0 = off)


class TokenBucket:
    """Classic token bucket; ``take`` is O(1) and monotone in ``now``."""

    __slots__ = ("rate", "capacity", "tokens", "t_last")

    def __init__(self, rate: float, capacity: float, now: float):
        self.rate = float(rate)
        self.capacity = float(capacity)
        self.tokens = float(capacity)    # full bucket: bursts admit
        self.t_last = float(now)

    def take(self, cost: float, now: float) -> bool:
        self.tokens = min(self.capacity,
                          self.tokens + (now - self.t_last) * self.rate)
        self.t_last = now
        if self.tokens >= cost:
            self.tokens -= cost
            return True
        return False


class AdmissionController:
    """Stateful shed policy: one token bucket per tenant plus the
    stateless queue/pool checks. ``now_fn`` is injectable so rate-limit
    tests advance a virtual clock."""

    def __init__(self, config: Optional[AdmissionConfig] = None,
                 now_fn: Callable[[], float] = time.monotonic):
        self.config = config or AdmissionConfig()
        self._now = now_fn
        self._buckets: Dict[str, TokenBucket] = {}

    def check(self, req, queue_depth: int,
              free_frac: float = 1.0) -> Optional[str]:
        """Return a shed reason for ``req`` (an ``engine.Request``), or
        ``None`` to admit. Counts every shed under
        ``serving_shed_total{reason}``."""
        c = self.config
        reason = None
        if c.max_queue and queue_depth >= c.max_queue:
            reason = "queue_full"
        elif c.shed_free_frac > 0 and queue_depth > 0 \
                and free_frac < c.shed_free_frac:
            reason = "pool_pressure"
        elif c.rate_tokens_per_s > 0:
            # charged LAST: a request shed above never ran and must not
            # drain its tenant's budget (that would starve the tenant as
            # rate_limited long after the pressure clears)
            now = self._now()
            bucket = self._buckets.get(req.tenant)
            if bucket is None:
                cap = c.burst_tokens or 2.0 * c.rate_tokens_per_s
                bucket = self._buckets[req.tenant] = TokenBucket(
                    c.rate_tokens_per_s, cap, now)
            cost = len(req.prompt) + int(req.max_new_tokens)
            if not bucket.take(cost, now):
                reason = "rate_limited"
        if reason is not None:
            _M_SHED.inc(reason=reason)
        return reason

    def spill_free_frac(self, default: float) -> float:
        """Proactive-spill pressure threshold (r15) derived from this
        controller's own shed signal: background cold-block spilling
        must engage BEFORE ``pool_pressure`` starts shedding, so when a
        ``shed_free_frac`` is configured the spiller arms at twice it
        (never below the engine's flag ``default``). With no pool-shed
        policy the flag stands alone — the two knobs share one
        ``free_frac`` signal, not two definitions of pressure."""
        c = self.config
        if c.shed_free_frac > 0:
            return max(float(default), 2.0 * c.shed_free_frac)
        return float(default)

    def retry_after(self, tenant: str, cost: float) -> float:
        """Seconds until ``tenant``'s bucket could afford ``cost``
        tokens — the HTTP front door's ``Retry-After`` derivation for a
        ``rate_limited`` shed (a 429 that names WHEN to come back beats
        one that invites an immediate, equally doomed retry). 0.0 when
        no rate limit applies or the tenant has no bucket yet; a cost
        beyond the bucket's whole capacity reports the time to fill it
        (the closest honest answer — the request can never afford more)."""
        c = self.config
        if c.rate_tokens_per_s <= 0:
            return 0.0
        bucket = self._buckets.get(tenant)
        if bucket is None:
            return 0.0
        now = self._now()
        tokens = min(bucket.capacity,
                     bucket.tokens
                     + (now - bucket.t_last) * bucket.rate)
        deficit = min(float(cost), bucket.capacity) - tokens
        return max(0.0, deficit / bucket.rate)
