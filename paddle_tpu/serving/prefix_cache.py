"""Refcounted radix/prefix KV cache over the engine's paged block pool.

The engine recomputed every request's full prefill even when requests
share a system prompt or a multi-turn prefix — the cost model Ragged
Paged Attention (PAPERS.md) argues should scale with *new* tokens, not
total tokens. This module is the host-side index that makes cached
prefill KV shareable:

- **Token trie at block granularity.** Each node maps one BLOCK of
  ``block_size`` tokens (keyed by the exact token tuple) to the physical
  pool block holding that block's K/V for *every* layer. A path from the
  root spells a prefix; matching walks the trie block-by-block, so
  ``add_request`` finds the longest cached prefix in O(prompt/bs) dict
  hops. Prefixes anchor at position 0 (RoPE bakes absolute positions
  into K), so equal tokens ⇒ bit-equal cached KV.
- **Refcounts, not copies.** A matched block is *pinned* into the new
  slot's block table (refcount++) — many slots read one physical block.
  Slots never write a pinned block: suffix prefill and decode append
  strictly past the matched region (copy-on-write at the partial tail is
  implicit — the partial tail block is always slot-private, only FULL
  blocks enter the trie).
- **LRU eviction only at refcount 0, spill before drop.** Under pool
  pressure the engine reclaims cached blocks least-recently-matched
  first. With a host pool attached (PR 8's
  :class:`~paddle_tpu.serving.kv_swap.HostKVPool`, ``kind="prefix"``)
  the block's payload spills to pinned host RAM and the trie node stays
  matchable — a later match restores it with one h2d copy instead of a
  re-prefill. Only when the host tier is full (or absent) is the node
  dropped, subtree and all (a dropped interior node would strand its
  descendants: a match must walk a contiguous path).

Accounting contract (``engine.block_accounting``): every device block is
in exactly one of {free, slot-private ("backed"), cache-owned device
node ("cached"), squeezed}; host-spilled nodes hold NO device block and
ride along as ``host_spilled_blocks`` —
``free + backed + cached + squeezed == total`` at every step boundary.

Speculative decoding (r13) widens what one node's physical block HOLDS,
not the trie's structure: with a draft model configured, every pool
block carries BOTH models' KV for its token range (the draft's
``dk``/``dv`` pool entries are indexed by the same block ids), and the
engine commits MULTIPLE tokens per decode wave. Commit granularity > 1
composes because adoption/matching were always block-granular and keyed
off the engine's ``lengths`` — a spec wave advancing ``lengths`` by c
tokens can complete several FULL blocks at once and finish-time
adoption picks them all up in one :meth:`extend` call, while
rejected-suffix positions (>= ``lengths``) sit only in the always-
private partial tail and can never enter the trie. Spill/restore moves
every pool entry verbatim, so a warm hit re-arms the draft too.
"""
from __future__ import annotations

import itertools
from typing import Callable, Dict, List, Optional, Tuple

import numpy as _np

from ..observability.catalog import instrument as _instrument

__all__ = ["PrefixCache"]

_M_HITS = _instrument("serving_prefix_cache_hits_total")
_M_MISSES = _instrument("serving_prefix_cache_misses_total")
_M_EVICTIONS = _instrument("serving_prefix_cache_evictions_total")
_M_SKIPPED = _instrument("serving_prefill_tokens_skipped_total")
_M_BLOCKS = _instrument("serving_prefix_cache_blocks")

_uid = itertools.count()


class _Node:
    """One cached block: ``key`` is the exact token tuple it spells,
    ``block`` the physical pool block id (``None`` while the payload is
    host-resident), ``refcount`` the number of slots pinning it.

    r15 proactive-spill states: ``spilling`` marks an in-flight
    background d2h of this node's payload (the node KEEPS its device
    block — still matchable, still ``cached`` in the ledger);
    ``host_clean`` marks a landed one — the payload now lives in BOTH
    tiers, so a later reclaim frees the device block instantly with
    zero inline d2h (cached blocks are immutable, so the host copy can
    never go stale). ``dead`` marks a dropped node so a spill landing
    after the drop discards its host entry instead of leaking it."""

    __slots__ = ("uid", "key", "parent", "children", "block", "refcount",
                 "stamp", "spilling", "host_clean", "dead")

    def __init__(self, key: Tuple[int, ...], parent: "_Node"):
        self.uid = next(_uid)
        self.key = key
        self.parent = parent
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.block: Optional[int] = None
        self.refcount = 0
        self.stamp = 0
        self.spilling = False
        self.host_clean = False
        self.dead = False


class PrefixCache:
    """Host-side radix index over cached prefill blocks.

    The engine owns the device pools and the free list; the cache owns
    structure, refcounts, LRU order, and (optionally) the host spill
    tier. Device transfers are injected per call (``fetch_fn`` d2h one
    block, ``restore_fn`` h2d one block, ``alloc_fn`` a free device
    block) so the cache itself stays a pure bookkeeping object —
    unit-testable without a device.
    """

    def __init__(self, block_size: int, host_pool=None):
        self.bs = int(block_size)
        self.host = host_pool          # HostKVPool(kind="prefix") or None
        self.root = _Node((), None)
        self._clock = itertools.count(1)
        # host-visible counters (bench evidence without a registry):
        self.hits = 0                  # lookups matching >= 1 block
        self.misses = 0
        self.tokens_skipped = 0        # prefill tokens served from cache
        # incremental population counts — the engine reads these on the
        # per-add_request / per-allocation hot paths (_avail_blocks, the
        # admission pressure check), so they must never be O(trie) walks
        self._n_device = 0             # nodes holding a device block
        self._n_evictable = 0          # device nodes at refcount 0
        self._n_host = 0               # spilled (host-resident) nodes

    # -- refcount transitions (keep the incremental counts exact) ---------
    def _pin(self, nd: _Node) -> None:
        nd.refcount += 1
        if nd.refcount == 1 and nd.block is not None:
            self._n_evictable -= 1

    def _unpin(self, nd: _Node) -> None:
        if nd.refcount > 0:
            nd.refcount -= 1
            if nd.refcount == 0 and nd.block is not None:
                self._n_evictable += 1

    # -- lookup -----------------------------------------------------------
    def match_and_pin(self, tokens: List[int], max_blocks: int,
                      alloc_fn: Callable[[int], List[int]],
                      restore_fn: Callable[[List[int], List], None]
                      ) -> Tuple[List[_Node], List[int]]:
        """Walk the longest cached path for ``tokens`` (at most
        ``max_blocks`` blocks — the engine caps at ``(len(ctx)-1)//bs``
        so at least one suffix token always prefills and provides the
        sampling hidden state), pinning every matched node. Host-resident
        nodes on the path are pinned DURING the walk (a reclaim fired by
        a later restore allocation can neither spill nor drop them) and
        restored afterwards in ONE batched ``restore_fn(blocks,
        entries)`` h2d scatter — never a transfer per block
        (``entries`` are the host pool's ``SwapEntry`` objects, r15:
        the engine reads ``.staged`` prefetch buffers when present,
        ``.data`` payload dicts otherwise). If allocation runs dry
        mid-restore the match truncates at the first unrestorable node
        (the tail is unpinned; already-restored prefix blocks stay
        cached).

        Returns ``(nodes, blocks)``; the caller places ``blocks`` at the
        head of the slot's block table and remembers ``nodes`` for
        :meth:`unpin` at slot free."""
        nodes: List[_Node] = []
        pend: List[Tuple[int, _Node, object]] = []   # host-resident hits
        node = self.root
        for b in range(max_blocks):
            key = tuple(tokens[b * self.bs:(b + 1) * self.bs])
            child = node.children.get(key)
            if child is None:
                break
            if child.block is None:
                ent = (self.host.get(("pfx", child.uid))
                       if self.host is not None else None)
                if ent is None:
                    # lost host entry: the node is unrestorable — drop it
                    # (subtree included) and treat as a miss from here
                    if child.refcount == 0:
                        self._drop_subtree(child)
                    break
                pend.append((len(nodes), child, ent))
            self._pin(child)
            child.stamp = next(self._clock)
            nodes.append(child)
            node = child
        if pend:
            blks = list(alloc_fn(len(pend)))    # bulk: ONE reclaim sweep
            if len(blks) < len(pend):
                # truncate at the first host node we could not back
                cut = pend[len(blks)][0]
                for nd in nodes[cut:]:
                    self._unpin(nd)
                nodes = nodes[:cut]
                pend = pend[:len(blks)]
            if pend:
                # entries (not raw payloads) ride to the engine so a
                # prefetch-staged restore (SwapEntry.staged, r15) can
                # consume device-resident buffers instead of paying h2d
                restore_fn(blks, [ent for _i, _nd, ent in pend])
                for blk, (_i, nd, _ent) in zip(blks, pend):
                    self.host.pop(("pfx", nd.uid))
                    nd.block = blk
                    nd.host_clean = False
                    self._n_host -= 1
                    self._n_device += 1   # pinned: not evictable
        return nodes, [nd.block for nd in nodes]

    def host_path_entries(self, tokens: List[int], max_blocks: int):
        """Read-only prefetch peek (r15): walk the cached path for
        ``tokens`` and yield ``(key, entry)`` for every host-resident
        node on it — the offload engine stages their payloads h2d ahead
        of the admission that will :meth:`match_and_pin` them. Nothing
        is pinned, restored, or restamped."""
        if self.host is None:
            return
        node = self.root
        for b in range(max_blocks):
            child = node.children.get(
                tuple(tokens[b * self.bs:(b + 1) * self.bs]))
            if child is None:
                return
            if child.block is None:
                ent = self.host.get(("pfx", child.uid))
                if ent is None:
                    return
                yield ("pfx", child.uid), ent
            node = child

    def note_lookup(self, cached_tokens: int) -> None:
        """Count one admission-time lookup (hit ⇔ >= 1 block matched)."""
        if cached_tokens > 0:
            self.hits += 1
            self.tokens_skipped += cached_tokens
            _M_HITS.inc()
            _M_SKIPPED.inc(cached_tokens)
        else:
            self.misses += 1
            _M_MISSES.inc()

    # -- insertion --------------------------------------------------------
    def extend(self, tokens: List[int], start_block: int,
               blocks: List[int], pin: bool) -> List[_Node]:
        """Adopt the slot's freshly written FULL blocks into the trie:
        ``blocks[i]`` holds the KV of token block ``start_block + i``
        (BOTH models' KV under speculative decoding — the pool entries
        share block ids). Walks the existing path to ``start_block`` (it
        exists whenever ``start_block > 0`` was matched or previously
        adopted); adoption stops at the first token block another
        request already cached — the trie keeps ONE physical block per
        prefix and the caller keeps (and later frees) its duplicate.
        Multi-token commits (spec waves, multi-step decode) can hand
        several blocks in one call; the loop adopts them in order.
        Returns the adopted nodes, in table order, ``pin=True`` leaving
        each pinned for the caller (prefill-time adoption) and
        ``pin=False`` leaving them at refcount 0 (finish-time adoption
        by a dying slot)."""
        node = self.root
        for b in range(start_block):
            node = node.children.get(
                tuple(tokens[b * self.bs:(b + 1) * self.bs]))
            if node is None:           # path gone (evicted): nothing to do
                return []
        adopted: List[_Node] = []
        for i, blk in enumerate(blocks):
            b = start_block + i
            key = tuple(tokens[b * self.bs:(b + 1) * self.bs])
            if len(key) < self.bs:
                break                  # partial tail never enters the trie
            if key in node.children:
                break                  # someone already cached this block
            child = _Node(key, node)
            child.block = int(blk)
            child.refcount = 1 if pin else 0
            child.stamp = next(self._clock)
            node.children[key] = child
            self._n_device += 1
            if not pin:
                self._n_evictable += 1
            adopted.append(child)
            node = child
        return adopted

    def unpin(self, nodes: List[_Node]) -> None:
        for nd in nodes:
            self._unpin(nd)

    # -- eviction ---------------------------------------------------------
    def spill_candidates(self, n: int) -> List["_Node"]:
        """Pick up to ``n`` coldest refcount-0 device nodes for a
        PROACTIVE background spill (r15) — not yet host-backed and not
        already mid-spill — and mark them ``spilling``. The caller (the
        engine's offload tick, under pool pressure only) dispatches the
        async d2h and reports back via :meth:`finish_spill` /
        :meth:`abort_spill`. The walk is O(trie), same order as one
        reclaim sweep, and runs only while pressure holds."""
        cands = sorted((nd for nd in self._iter_nodes()
                        if nd.block is not None and nd.refcount == 0
                        and not nd.spilling and not nd.host_clean),
                       key=lambda x: x.stamp)[:max(0, n)]
        for nd in cands:
            nd.spilling = True
        return cands

    def finish_spill(self, nd: "_Node", ok: bool) -> None:
        """Landing callback for a proactive spill. ``ok=False`` (the
        transfer was abandoned) just clears the mark. On success the
        node becomes ``host_clean`` — resident in BOTH tiers — unless
        it was dropped (discard the orphaned host entry) or already
        moved host-side by an inline reclaim (nothing to do: the
        commit replaced the entry with identical bytes)."""
        nd.spilling = False
        if not ok:
            return
        if nd.dead:
            if self.host is not None:
                self.host.discard(("pfx", nd.uid))
            return
        if nd.block is not None:
            nd.host_clean = True

    def abort_spill(self, nd: "_Node") -> None:
        """The engine could not dispatch the spill (host tier full):
        unmark, so the node stays an ordinary reclaim candidate."""
        nd.spilling = False

    def reclaim(self, n: int,
                fetch_fn: Optional[Callable[[List[int]], Dict]]
                ) -> List[int]:
        """Free at least ``n`` device blocks (when reclaimable) from
        refcount-0 nodes, least recently matched first: ``host_clean``
        nodes (their payload already landed host-side via a proactive
        background spill) free INSTANTLY — zero inline d2h, the r15
        point — others spill to the host tier when they fit (the node
        stays matchable), else drop the node and its whole subtree (a
        pinned descendant is impossible — pinning pins the full path).
        ONE LRU sweep and ONE batched d2h (``fetch_fn(blocks)``
        returning per-pool arrays stacked on the block axis — one
        transfer per pool entry) per call, however many blocks move:
        callers needing k blocks must ask for k, not call this k times.
        May over-deliver when a drop frees a subtree."""
        freed: List[int] = []
        # one LRU-ordered sweep (stamps are stable during the reclaim;
        # nodes a subtree drop already freed show block=None and skip)
        cands = sorted((nd for nd in self._iter_nodes()
                        if nd.block is not None and nd.refcount == 0),
                       key=lambda x: x.stamp)
        batch, idx = cands[:n], min(n, len(cands))
        fetch = []
        for nd in batch:
            if nd.host_clean:
                # the proactive spill already paid the d2h in the
                # background: complete the eviction for free
                freed.append(nd.block)
                nd.block = None
                nd.host_clean = False
                nd.spilling = False
                self._n_device -= 1
                self._n_evictable -= 1
                self._n_host += 1
                _M_EVICTIONS.inc(kind="spill")
            else:
                fetch.append(nd)
        if self.host is not None and fetch_fn is not None and fetch:
            datas = fetch_fn([nd.block for nd in fetch])
            for i, nd in enumerate(fetch):
                if nd.block is None:   # freed by an earlier subtree drop
                    continue
                # contiguous copy — a numpy view would pin the whole
                # batch array behind the host pool's byte accounting
                data = {name: _np.ascontiguousarray(arr[:, i:i + 1])
                        for name, arr in datas.items()}
                if self.host.put(("pfx", nd.uid), data, n_tokens=self.bs):
                    freed.append(nd.block)
                    nd.block = None
                    self._n_device -= 1
                    self._n_evictable -= 1
                    self._n_host += 1
                    _M_EVICTIONS.inc(kind="spill")
                else:
                    freed.extend(self._drop_subtree(nd))
        else:
            for nd in fetch:
                if len(freed) >= n:
                    break
                if nd.block is None or nd.refcount:
                    continue
                freed.extend(self._drop_subtree(nd))
        for nd in cands[idx:]:
            if len(freed) >= n:
                break
            if nd.block is None or nd.refcount:
                continue
            freed.extend(self._drop_subtree(nd))
        return freed

    def _drop_subtree(self, node: _Node, count: bool = True) -> List[int]:
        """Detach ``node`` and free its whole subtree (device blocks
        returned, host entries discarded). The eviction counter records
        only nodes that actually held a device block, and only when
        ``count`` (pressure-driven drops) — crash-recovery ``clear`` is
        not cache thrash and must not look like it on a dashboard."""
        if node.parent is not None:
            node.parent.children.pop(node.key, None)
        freed: List[int] = []
        stack = [node]
        while stack:
            nd = stack.pop()
            assert nd.refcount == 0, "dropping a pinned cache node"
            nd.dead = True          # a spill landing later must discard
            if nd.block is not None:
                freed.append(nd.block)
                nd.block = None
                self._n_device -= 1
                self._n_evictable -= 1
                if count:
                    _M_EVICTIONS.inc(kind="drop")
                if nd.host_clean and self.host is not None:
                    # dual-resident node: its host copy dies with it
                    self.host.discard(("pfx", nd.uid))
                nd.host_clean = False
            else:
                self._n_host -= 1
                if self.host is not None:
                    self.host.discard(("pfx", nd.uid))
            stack.extend(nd.children.values())
            nd.children = {}
        return freed

    def clear(self) -> List[int]:
        """Drop everything (crash recovery: the pools' contents are
        suspect). Returns all device blocks for the free list."""
        freed: List[int] = []
        for child in list(self.root.children.values()):
            freed.extend(self._drop_subtree(child, count=False))
        return freed

    # -- accounting -------------------------------------------------------
    def _iter_nodes(self):
        stack = list(self.root.children.values())
        while stack:
            nd = stack.pop()
            yield nd
            stack.extend(nd.children.values())

    @property
    def device_blocks(self) -> int:
        return self._n_device

    @property
    def evictable_blocks(self) -> int:
        """Device blocks reclaimable right now (refcount 0)."""
        return self._n_evictable

    @property
    def host_blocks(self) -> int:
        return self._n_host

    @property
    def host_bytes(self) -> int:
        return self.host.bytes_used if self.host is not None else 0

    def update_gauges(self) -> None:
        _M_BLOCKS.set(self.device_blocks)
