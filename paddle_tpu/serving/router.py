"""Replica router: health-checked failover across N serving engines.

One engine process is one blast radius: a crash kills every stream it
owns, and the ROADMAP's "millions of users" needs many engines behind
one door. This module is that door's brain — a :class:`ReplicaRouter`
fronting N engine replicas, each on its own dedicated step thread (the
same single-owner step-loop idiom as :class:`~paddle_tpu.serving.http.
HTTPFrontDoor`; the HTTP front door remains the wire-level shape, the
router is the placement/failover layer behind it). Four pillars:

- **Placement** — prefix-affinity first: prompts are scored against a
  per-replica shadow of the SAME block-granular token keys the radix
  prefix cache indexes (``tuple(prompt[b*bs:(b+1)*bs])`` per block), so
  requests sharing a system prompt stick to the replica whose trie
  already holds it and pay a near-zero suffix prefill instead of a full
  one. With no affinity signal, placement falls back to tenant-aware
  least-loaded balancing using admission's token-cost model (``prompt +
  max_new_tokens`` outstanding per replica, per tenant first, total as
  the tiebreak). The router sheds (:class:`ShedError` → HTTP 503 +
  Retry-After at the front door) only when NO healthy replica admits
  the request — a single replica's bounded queue is not the cluster's.

- **Health** — every replica step thread stamps a step-progress
  heartbeat (and guards each engine step under the installed
  :mod:`~paddle_tpu.distributed.watchdog`, so a wedged device call
  still trips process-level hang detection). Heartbeat age drives a
  typed state machine ``healthy → suspect → dead`` (plus ``draining`` /
  ``drained``): suspect replicas stop receiving new work, dead ones
  trigger failover. Age alone demotes at most one level per
  :meth:`check` tick (healthy → suspect, then suspect → dead on a
  SECOND stale observation), so a clock step or VM pause cannot
  mass-kill replicas whose threads are fine; a dead step thread is
  fatal immediately. The dead state is a circuit breaker: a recovered
  replica re-enters through ``half_open`` — after
  ``FLAGS_router_halfopen_s`` with a fresh heartbeat it receives ONE
  probe request, and only a cleanly finished probe closes the circuit
  back to ``healthy``. No restart of the router required.

- **Failover with exactly-once resume** — the router records every
  stream's delivered-token count. When a replica dies mid-stream, each
  in-flight request re-dispatches to a healthy replica with ``prompt +
  delivered`` as the new prompt and the remaining token budget — on a
  warm replica the prefix cache makes the replay near-free. Late
  emissions from the dead replica (a zombie thread whose heartbeat
  merely stalled) are deduped at the router by ownership: only the
  stream's CURRENT (replica, engine-rid) owner may append tokens, and
  greedy determinism then guarantees the resumed stream is
  token-identical to an uninterrupted run — test-enforced
  (tests/test_router.py), never best-effort. Re-dispatch and replica
  bootstrap go through :func:`~paddle_tpu.distributed.resilience.retry.
  retry_call` (exponential backoff, full jitter).

- **Per-replica drain** — :meth:`ReplicaRouter.begin_drain` steers new
  traffic away from one replica and lets its in-flight streams finish;
  stragglers past ``FLAGS_router_drain_s`` migrate to healthy replicas
  through the SAME resume path (terminal reason ``drained`` on the old
  replica, token-identical continuation on the new one). A drained
  replica's ledger must read ``free + cached == total`` — zero orphaned
  blocks. :meth:`drain_all` composes with the r14 SIGTERM whole-process
  drain: it drains every replica and then runs the watchdog emergency
  hooks, same registry as the front door and the train loop.

Threading model: each replica's step thread OWNS its engine — the
router never touches an engine off its thread. Submissions and
cancellations travel to the step thread through a per-replica op deque
(futures travel back); emitted tokens and terminal reasons route back
to router-owned stream records inside the step thread's loop. The
router's own mutable maps are guarded by one lock. Health transitions
run inside :meth:`check` — called by the optional monitor thread, by
any caller (the chaos driver), or manually with an injected clock in
tests.

Exactly-once semantics, precisely: a stream's tokens are appended only
by its current owner; failover re-dispatches ``prompt + delivered``
so the overlap is replayed as PREFILL (never re-emitted); terminal
bookkeeping happens exactly once per router id, into exactly one of
``{finished, shed, deadline_exceeded, client_disconnected, drained}``.
Resume parity is guaranteed for greedy (temperature=0) streams —
sampled streams resume with a fresh key and may diverge (documented,
like any preemption-recompute path would without the KV swap tier).

Chaos surface: ``tools/chaos_run.py --router`` runs N in-process
replicas under a half-shared-prefix workload, kills one mid-stream
(seeded), and asserts every minted id lands in exactly one terminal
reason, resumed streams are bit-identical to a clean single-engine
greedy run, per-replica block ledgers balance at every step, and
post-kill traffic rebalances onto the survivors.

Disaggregated prefill/decode (r19): replicas built with
``LLMEngine(..., role="prefill", relay=...)`` run admission + chunked
prefill only — after the first sampled token the engine spills the
slot's KV blocks bit-exact into the SHARED host relay pool
(:class:`~paddle_tpu.serving.kv_swap.HostKVPool` with
``kind="relay"``) and retires the stream with engine reason
``"handoff"``. The router treats ``handoff`` as a ROUTING event, never
a client terminal: the stream re-dispatches — same exactly-once resume
path as failover — onto a decode-capable replica with
``relay_key=<prefill engine rid>``, whose admission restores the
relayed blocks with one batched h2d scatter instead of re-prefilling.
Greedy streams stay token-identical to a colocated run
(test-enforced). Degradations are counted, never silent: a full relay
or a vanished entry means the decode replica re-prefills the
handed-off context (``serving_disagg_handoffs_total{outcome=
"relay_full"|"missing"}``); a prefill replica dying mid-handoff fails
over through the normal from-prompt resume and its orphaned relay
entry is discarded; no decode-capable replica left sheds the stream.
"""
from __future__ import annotations

import itertools
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from .. import observability as _obs
from ..distributed import watchdog as _watchdog
from ..distributed.resilience.retry import retry_call
from ..framework.flags import define_flag, get_flag
from ..observability import flight_recorder as _flight
from ..observability import request_trace as _rt
from ..observability.catalog import instrument as _instrument
from .admission import ShedError
from .resilient import ResilientEngine

__all__ = ["ReplicaRouter", "Replica"]

define_flag("router_suspect_s", 2.0,
            "replica heartbeat age after which the router stops placing "
            "new requests on it (healthy -> suspect)")
define_flag("router_dead_s", 6.0,
            "replica heartbeat age after which the router declares it "
            "dead and fails its in-flight streams over (suspect -> "
            "dead; also entered immediately on a crashed step thread)")
define_flag("router_halfopen_s", 2.0,
            "circuit-breaker re-probe delay: seconds after death before "
            "a replica with a fresh heartbeat is offered ONE probe "
            "request (dead -> half_open; a finished probe closes the "
            "circuit back to healthy)")
define_flag("router_drain_s", 15.0,
            "per-replica drain budget: seconds in-flight streams may "
            "keep running on a draining replica before they migrate to "
            "a healthy one via the resume path")

_M_DISPATCH = _instrument("serving_router_dispatch_total")
_M_AFFINITY = _instrument("serving_router_affinity_total")
_M_SHED = _instrument("serving_router_shed_total")
_M_FAILOVERS = _instrument("serving_router_failovers_total")
_M_RESUMED = _instrument("serving_router_resumed_streams_total")
_M_DEDUP = _instrument("serving_router_dedup_drops_total")
_M_TRANSITIONS = _instrument("serving_router_state_transitions_total")
_M_HEALTHY = _instrument("serving_router_healthy_replicas")

# terminal reasons a router stream may land in — same contract as the
# engine's finish_reasons, shed included (router-level or replica-level).
# The engine-level "handoff" reason (disagg prefill replicas) is NOT
# here on purpose: it is a routing event — the stream resumes on a
# decode replica and still ends in exactly one of these.
TERMINAL_REASONS = frozenset(("finished", "shed", "deadline_exceeded",
                              "client_disconnected", "drained"))

# states that may receive NEW placements ("half_open" only via the
# explicit probe slot — see _place)
_PLACEABLE = ("healthy",)


class _Future:
    """Tiny cross-thread future: a replica thread resolves what a
    router-side caller waits on (no asyncio on either side)."""

    __slots__ = ("_ev", "value", "error")

    def __init__(self):
        self._ev = threading.Event()
        self.value = None
        self.error: Optional[BaseException] = None

    def set(self, value=None, error: Optional[BaseException] = None):
        self.value, self.error = value, error
        self._ev.set()

    def wait(self, timeout: Optional[float]):
        if not self._ev.wait(timeout):
            raise TimeoutError("replica op timed out")
        if self.error is not None:
            raise self.error
        return self.value


class _StreamRec:
    """Router-side record of one client stream across replica moves."""

    __slots__ = ("rid", "prompt", "kw", "tenant", "max_new", "delivered",
                 "replica", "engine_rid", "resumes", "migrating",
                 "cancelled", "done", "charged", "relay_key")

    def __init__(self, rid: int, prompt: List[int], kw: Dict):
        self.rid = rid
        self.prompt = list(prompt)
        self.kw = dict(kw)
        self.tenant = str(kw.get("tenant", "default"))
        self.max_new = int(kw.get("max_new_tokens", 64))
        self.delivered: List[int] = []
        self.charged = 0.0   # admission-cost tokens charged at dispatch
        self.replica: Optional[str] = None      # current owner name
        self.engine_rid: Optional[int] = None   # rid on that owner
        self.resumes = 0
        self.migrating = False   # drain: next terminal resumes elsewhere
        self.cancelled = False   # client cancel: never resurrect
        self.relay_key = None    # disagg: relay entry id (prefill erid)
        self.done = threading.Event()


class Replica:
    """One engine replica on its dedicated step thread.

    The thread owns the engine exclusively (the engine's pipelined state
    machine is single-owner per step); everything else reaches it via
    the op deque. ``hb`` is the step-progress heartbeat the router's
    health machine reads — stamped from the ROUTER's clock so tests can
    drive the whole state machine with an injected ``now_fn``.
    """

    def __init__(self, name: str, engine, router: "ReplicaRouter",
                 resilient: bool = True):
        self.name = name
        # crash recovery stays per-replica: a readback crash inside one
        # replica is salvaged there, invisible to the router
        self.raw = (engine.engine if isinstance(engine, ResilientEngine)
                    else engine)
        # disagg (r19): placement honors the engine's role — "prefill"
        # replicas hand every stream off after prefill, "decode"
        # replicas are last-resort prefill targets, "both" (default)
        # serves the whole lifecycle
        self.role = getattr(self.raw, "role", "both")
        self.stepper = (engine if isinstance(engine, ResilientEngine)
                        else ResilientEngine(engine) if resilient
                        else engine)
        self._router = router
        self._ops: List = []            # guarded by _ops_lock
        self._ops_lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = False
        self._killed = False            # chaos: sudden-death switch
        self.hb_frozen = False          # test hook: stall the heartbeat
        self.crashed: Optional[str] = None
        self.state = "healthy"
        self.hb = router._now()
        self.t_dead: Optional[float] = None
        self.probe_pending = False      # half_open: one probe allowed
        self.probe_rid: Optional[int] = None
        # ownership + affinity shadow (guarded by the router lock)
        self.owned: Dict[int, int] = {}        # engine rid -> router rid
        self.ghosts: Set[int] = set()          # abandoned engine rids
        self.prefix_keys: Set[Tuple[int, ...]] = set()
        # tenant -> outstanding admission-cost tokens (prompt + max_new)
        self.load: Dict[str, float] = {}
        self.dispatches = 0
        self.steps = 0
        self._thread: Optional[threading.Thread] = None

    # -- cross-thread ops --------------------------------------------------
    def enqueue(self, op) -> None:
        with self._ops_lock:
            self._ops.append(op)
        self._wake.set()

    def _fail_pending_ops(self, exc: BaseException) -> None:
        with self._ops_lock:
            ops, self._ops = self._ops, []
        for op in ops:
            if op[0] == "submit":
                op[3].set(error=exc)

    def _run_ops(self) -> None:
        while True:
            with self._ops_lock:
                if not self._ops:
                    return
                op = self._ops.pop(0)
            if op[0] == "submit":
                _k, prompt, kw, fut = op
                try:
                    fut.set(self.raw.add_request(prompt, **kw))
                except BaseException as e:
                    fut.set(error=e)
            elif op[0] == "cancel":
                _k, erid, reason = op
                self.raw.cancel_request(erid, reason=reason)

    # -- the step loop -----------------------------------------------------
    def _loop(self) -> None:
        router = self._router
        # r17 fleet scoping: everything this step thread touches — every
        # engine counter/gauge/histogram AND every span — lands under a
        # {replica=<name>} label, so one process registry carries N
        # attributable replicas. Metric mutators still short-circuit on
        # the enabled() check first, so the disabled path is unchanged.
        scope = _obs.get_registry().scoped(replica=self.name)
        scope.activate()
        try:
            while not self._stop:
                if self._killed:
                    raise RuntimeError(
                        f"replica {self.name}: killed (chaos)")
                if not self.hb_frozen:
                    self.hb = router._now()
                self._run_ops()
                if self.raw.has_work():
                    # a wedged device call on THIS replica still trips
                    # the process watchdog (no-op when none installed)
                    with _watchdog.guarded(f"router-{self.name}-step"):
                        emitted = self.stepper.step()
                    self.steps += 1
                    if not self.hb_frozen:   # step progress IS the pulse
                        self.hb = router._now()
                    router._on_emitted(self, emitted)
                    router._on_terminals(self)
                    if router.step_hook is not None:
                        router.step_hook(self.name, self.raw)
                else:
                    router._on_terminals(self)
                    self._wake.wait(router.idle_wait)
                    self._wake.clear()
        except BaseException as e:      # sudden death — the chaos case
            self.crashed = f"{type(e).__name__}: {e}"
            self._fail_pending_ops(
                RuntimeError(f"replica {self.name} died: {self.crashed}"))
            _flight.record("router_replica_died", replica=self.name,
                           error=self.crashed[:160])
            router._note_crash(self)
        finally:
            scope.deactivate()
            self._fail_pending_ops(
                RuntimeError(f"replica {self.name} stopped"))

    def start(self) -> None:
        self._stop = False
        self._killed = False
        self.crashed = None
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"router-replica-{self.name}")
        self._thread.start()

    def alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def kill(self) -> None:
        """Chaos hook: sudden replica death (preemption/OOM stand-in).
        The step thread dies at its next loop boundary; the engine's
        state is abandoned mid-flight until :meth:`ReplicaRouter.
        revive_replica` recovers it."""
        self._killed = True
        self._wake.set()

    def stop(self, timeout: float = 10.0) -> None:
        self._stop = True
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout)


class ReplicaRouter:
    """Health-checked placement/failover layer over N engine replicas.

    ``engines`` are freshly constructed :class:`LLMEngine` instances
    (same params/config; each is wrapped in :class:`ResilientEngine`
    unless ``resilient=False`` or already wrapped). ``now_fn`` is the
    injectable clock every health/drain decision reads — tests drive
    the full state machine without sleeping. ``step_hook(name, engine)``
    runs after every replica step (the chaos harness's per-replica
    ledger assertion point). ``monitor_interval > 0`` starts a
    background thread calling :meth:`check` on a real-time cadence;
    leave 0 to call it yourself.
    """

    def __init__(self, engines: Sequence, names: Optional[Sequence[str]]
                 = None, now_fn: Callable[[], float] = time.monotonic,
                 step_hook: Optional[Callable] = None,
                 idle_wait: float = 0.005, resilient: bool = True,
                 suspect_s: Optional[float] = None,
                 dead_s: Optional[float] = None,
                 halfopen_s: Optional[float] = None,
                 drain_s: Optional[float] = None,
                 monitor_interval: float = 0.0,
                 retry_sleep: Callable[[float], None] = time.sleep,
                 op_timeout: float = 120.0):
        if not engines:
            raise ValueError("ReplicaRouter needs at least one replica")
        self._now = now_fn
        self.step_hook = step_hook
        self.idle_wait = float(idle_wait)
        self.suspect_s = (float(get_flag("router_suspect_s"))
                          if suspect_s is None else float(suspect_s))
        self.dead_s = (float(get_flag("router_dead_s"))
                       if dead_s is None else float(dead_s))
        self.halfopen_s = (float(get_flag("router_halfopen_s"))
                           if halfopen_s is None else float(halfopen_s))
        self.drain_s = (float(get_flag("router_drain_s"))
                        if drain_s is None else float(drain_s))
        self._retry_sleep = retry_sleep
        self._op_timeout = float(op_timeout)
        self._lock = threading.RLock()
        self._streams: Dict[int, _StreamRec] = {}
        self._next_rid = itertools.count()
        self.results: Dict[int, List[int]] = {}
        self.finish_reasons: Dict[int, str] = {}
        self.failovers = 0
        self.resumed_streams = 0
        self.handoff_resumes = 0   # disagg: prefill→decode stream moves
        self.dedup_drops = 0
        self.affinity_hits = 0
        self.affinity_misses = 0
        self.router_sheds = 0
        names = list(names) if names is not None else \
            [f"r{i}" for i in range(len(engines))]
        if len(names) != len(engines):
            raise ValueError("names/engines length mismatch")
        self.replicas: Dict[str, Replica] = {}
        for i, (name, eng) in enumerate(zip(names, engines)):
            rep = Replica(name, eng, self, resilient=resilient)
            # disjoint engine-rid spaces across replicas: request traces
            # land in ONE process-global tracer, and obs_dump's replica
            # column is only meaningful when ids never collide. The base
            # is 1-indexed so no replica shares the 0-based space that
            # standalone engines (reference replays, warmups) mint from —
            # a collision there makes tracer.get() resolve a router
            # stream's rid to the bystander's newer timeline
            rep.raw._next_id += (i + 1) * 1_000_000
            self.replicas[name] = rep
        self._drain_t0: Dict[str, float] = {}
        self._monitor_interval = float(monitor_interval)
        self._monitor: Optional[threading.Thread] = None
        self._stopping = False
        # fleet federation (r17): the aggregator holds us weakly and
        # carves one per-replica snapshot out of the scoped registry for
        # /fleet/* — latest router wins the singleton
        from ..observability import fleet as _fleet
        _fleet.get_aggregator().attach_router(self)

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "ReplicaRouter":
        """Boot every replica step thread. Bootstrap goes through
        retry_call — a replica whose thread fails to come up (transient
        resource pressure) is retried with full-jitter backoff rather
        than failing the whole router."""
        for rep in self.replicas.values():
            retry_call(self._boot_replica, rep, retries=3,
                       base_delay=0.05, exceptions=(RuntimeError,),
                       sleep=self._retry_sleep)
        if self._monitor_interval > 0:
            self._monitor = threading.Thread(
                target=self._monitor_loop, daemon=True,
                name="router-monitor")
            self._monitor.start()
        return self

    def _boot_replica(self, rep: Replica) -> None:
        rep.start()
        if not rep.alive():
            raise RuntimeError(f"replica {rep.name} failed to start")

    def _monitor_loop(self) -> None:
        while not self._stopping:
            time.sleep(self._monitor_interval)
            try:
                self.check()
            except Exception as e:        # pragma: no cover — monitor
                _flight.record("router_monitor_error",  # must not die
                               error=repr(e)[:160])

    def stop(self) -> None:
        self._stopping = True
        for rep in self.replicas.values():
            rep.stop()
        if self._monitor is not None:
            self._monitor.join(5)

    # -- introspection -----------------------------------------------------
    def states(self) -> Dict[str, str]:
        with self._lock:
            return {name: rep.state
                    for name, rep in self.replicas.items()}

    def live_streams(self) -> int:
        with self._lock:
            return sum(1 for rec in self._streams.values()
                       if not rec.done.is_set())

    def has_work(self) -> bool:
        return self.live_streams() > 0

    # -- placement ---------------------------------------------------------
    @staticmethod
    def _block_keys(prompt: List[int], bs: int) -> List[Tuple[int, ...]]:
        """The radix cache's block-granular token keys for ``prompt``
        (full blocks only — identical to PrefixCache's node keys)."""
        return [tuple(prompt[b * bs:(b + 1) * bs])
                for b in range(len(prompt) // bs)]

    def _affinity_score(self, rep: Replica, keys) -> int:
        """Longest run of leading block keys this replica has served —
        the shadow of what its prefix-cache trie holds."""
        n = 0
        for key in keys:
            if key not in rep.prefix_keys:
                break
            n += 1
        return n

    def _note_dispatch(self, rep: Replica, rec: _StreamRec,
                       prompt: List[int], cost: float) -> None:
        bs = rep.raw.bs
        rep.prefix_keys.update(self._block_keys(prompt, bs))
        rec.charged = float(cost)
        rep.load[rec.tenant] = rep.load.get(rec.tenant, 0.0) + cost
        rep.dispatches += 1
        _M_DISPATCH.inc(replica=rep.name)

    def _unload(self, rep: Replica, rec: _StreamRec) -> None:
        left = rep.load.get(rec.tenant, 0.0) - rec.charged
        if left > 1e-9:
            rep.load[rec.tenant] = left
        else:
            rep.load.pop(rec.tenant, None)

    def _place(self, prompt: List[int], tenant: str, exclude: Set[str],
               role_need: str = "prefill"
               ) -> Tuple[List[Replica], Optional[Dict]]:
        """Candidate replicas, best first. Affinity wins when any
        candidate holds >= 1 leading block of the prompt; otherwise a
        pending half-open probe takes the request (the circuit
        breaker's re-probe), then tenant-aware least-loaded order.
        Second return: the placement-audit record (candidate scores,
        loads, decision reason) when observability is on, else None.

        ``role_need`` (disagg, r19): ``"prefill"`` — the stream starts
        with a prefill, which EVERY role can run, but decode-role
        replicas rank last (before affinity: a decode replica's trie
        shadow must not pull fresh prompts onto it); ``"decode"`` — the
        stream resumes from relayed KV, so prefill-role replicas are
        excluded outright (they would hand off again, forever)."""
        with self._lock:
            cands = [rep for rep in self.replicas.values()
                     if rep.state in _PLACEABLE
                     and rep.name not in exclude
                     and not (role_need == "decode"
                              and rep.role == "prefill")]
            probe = next((rep for rep in self.replicas.values()
                          if rep.state == "half_open" and
                          rep.probe_pending and rep.name not in exclude
                          and not (role_need == "decode"
                                   and rep.role == "prefill")),
                         None)
            if not cands and probe is None:
                return [], None
            bs = cands[0].raw.bs if cands else probe.raw.bs
            keys = self._block_keys(prompt, bs)
            scored = sorted(
                cands,
                key=lambda rep: (role_need == "prefill"
                                 and rep.role == "decode",
                                 -self._affinity_score(rep, keys),
                                 rep.load.get(tenant, 0.0),
                                 sum(rep.load.values()),
                                 rep.name))
            best_aff = (self._affinity_score(scored[0], keys)
                        if scored else 0)
            reason = ("affinity" if best_aff > 0
                      else "half_open_probe" if probe is not None
                      else "least_loaded")
            audit = None
            if _obs.enabled():
                audit = {"tenant": tenant, "blocks": len(keys),
                         "reason": reason,
                         "candidates": [
                             {"replica": rep.name,
                              "affinity": self._affinity_score(rep, keys),
                              "tenant_load":
                                  round(rep.load.get(tenant, 0.0), 1),
                              "load": round(sum(rep.load.values()), 1)}
                             for rep in scored]}
            if best_aff > 0:
                self.affinity_hits += 1
                _M_AFFINITY.inc(outcome="hit")
                # the probe still rides along as a fallback candidate
                return (scored + ([probe] if probe is not None else []),
                        audit)
            if keys:
                self.affinity_misses += 1
                _M_AFFINITY.inc(outcome="miss")
            if probe is not None:
                return [probe] + scored, audit
            return scored, audit

    # -- submission --------------------------------------------------------
    def submit(self, prompt: List[int], **kw) -> int:
        """Mint a router request id and dispatch it. Raises
        :class:`ShedError` (with the minted id and the LAST replica's
        shed reason) only when no healthy replica admitted it — the
        router-level 503. Engine-side validation errors propagate."""
        rid = next(self._next_rid)
        rec = _StreamRec(rid, prompt, kw)
        with self._lock:
            self._streams[rid] = rec
        try:
            self._dispatch(rec, list(rec.prompt), rec.kw,
                           exclude=set())
        except ShedError as e:
            with self._lock:
                self._terminal(rec, "shed")
            self.router_sheds += 1
            _M_SHED.inc()
            raise ShedError(e.reason, rid) from None
        return rid

    def _dispatch(self, rec: _StreamRec, prompt: List[int], kw: Dict,
                  exclude: Set[str], role_need: str = "prefill") -> None:
        """Place ``rec`` on the best candidate, walking down the
        preference order when a replica sheds or dies mid-op. Raises
        ShedError when every candidate refused."""
        last: Optional[ShedError] = None
        tried = set(exclude)
        cands, audit = self._place(prompt, rec.tenant, tried, role_need)
        if not cands:
            raise ShedError("no_healthy_replica")
        for rep in cands:
            fut = _Future()
            rep.enqueue(("submit", list(prompt), dict(kw), fut))
            try:
                erid = fut.wait(self._op_timeout)
            except ShedError as e:
                last = e
                tried.add(rep.name)
                continue
            except (RuntimeError, TimeoutError):
                # the replica died (or wedged) under the op — health
                # will catch it; try the next candidate
                tried.add(rep.name)
                continue
            with self._lock:
                rec.replica = rep.name
                rec.engine_rid = erid
                rep.owned[erid] = rec.rid
                if rep.state == "half_open" and rep.probe_pending:
                    rep.probe_pending = False
                    rep.probe_rid = erid
                self._note_dispatch(
                    rep, rec, prompt,
                    len(prompt) + int(kw.get("max_new_tokens",
                                             rec.max_new)))
            if _obs.enabled():
                _rt.get_request_tracer().annotate(erid, replica=rep.name)
                if audit is not None:
                    from ..observability import fleet as _fleet
                    _fleet.get_placement_log().record(
                        rid=rec.rid, chosen=rep.name,
                        skipped=len(tried) - len(exclude),
                        resume=rec.resumes > 0, **audit)
                    _flight.record("router_placement", rid=rec.rid,
                                   chosen=rep.name,
                                   reason=audit["reason"])
            return
        raise last if last is not None else ShedError("no_healthy_replica")

    def cancel(self, rid: int, reason: str = "client_disconnected") -> None:
        """Client-side cancellation of a router stream: forwarded to the
        owning replica; already-terminal ids no-op (the engine's own
        idempotence guard counts the race)."""
        with self._lock:
            rec = self._streams.get(rid)
            if rec is None or rec.done.is_set():
                return
            rec.cancelled = True
            rep = (self.replicas.get(rec.replica)
                   if rec.replica is not None else None)
            erid = rec.engine_rid
        if rep is not None and erid is not None:
            rep.enqueue(("cancel", erid, reason))

    # -- results -----------------------------------------------------------
    def wait(self, rid: int, timeout: Optional[float] = None) -> List[int]:
        """Block until ``rid`` reaches a terminal reason; return its full
        delivered token stream (``results[rid]``)."""
        rec = self._streams.get(rid)
        if rec is None:
            raise KeyError(f"unknown router request {rid}")
        if not rec.done.wait(timeout):
            raise TimeoutError(f"router request {rid} not terminal "
                               f"after {timeout}s")
        return self.results[rid]

    def wait_all(self, timeout: float = 120.0) -> bool:
        deadline = time.monotonic() + timeout
        for rec in list(self._streams.values()):
            if not rec.done.wait(max(0.0, deadline - time.monotonic())):
                return False
        return True

    # -- step-thread callbacks --------------------------------------------
    def _on_emitted(self, rep: Replica, emitted) -> None:
        if not emitted:
            return
        with self._lock:
            for erid, tok in emitted:
                rrid = rep.owned.get(erid)
                if rrid is None:
                    if erid in rep.ghosts:
                        # a zombie replica (stalled, declared dead,
                        # failed over) kept emitting: the stream moved,
                        # these tokens were replayed elsewhere — drop
                        # and count, never double-deliver
                        self.dedup_drops += 1
                        _M_DEDUP.inc()
                    continue
                self._streams[rrid].delivered.append(int(tok))

    def _on_terminals(self, rep: Replica) -> None:
        eng = rep.raw
        resumes: List[_StreamRec] = []
        handoffs: List[Tuple[_StreamRec, int]] = []
        with self._lock:
            for erid in list(rep.owned):
                reason = eng.finish_reasons.get(erid)
                if reason is None:
                    continue
                rrid = rep.owned.pop(erid)
                rec = self._streams[rrid]
                self._unload(rep, rec)
                if erid == rep.probe_rid:
                    rep.probe_rid = None
                    if rep.state == "half_open":
                        if reason in ("finished", "handoff"):
                            # a prefill-role replica never finishes a
                            # stream itself — a clean handoff is its
                            # proof of life
                            self._transition(rep, "healthy")
                        else:
                            # shed/deadline proves nothing either way:
                            # offer another probe
                            rep.probe_pending = True
                if reason == "handoff":
                    # disagg (r19): the prefill leg is done — its KV sits
                    # in the shared relay under this engine rid. The
                    # stream continues on a decode-capable replica; this
                    # is never a client-visible terminal.
                    if rec.cancelled:
                        relay = self._relay()
                        if relay is not None:
                            relay.discard(erid)
                        self._terminal(rec, "client_disconnected")
                    else:
                        handoffs.append((rec, erid))
                    continue
                if rec.migrating and not rec.cancelled \
                        and reason == "drained":
                    rec.migrating = False
                    resumes.append(rec)
                    continue
                self._terminal(rec, reason)
        for rec in resumes:
            self._resume(rec, exclude={rep.name})
        for rec, erid in handoffs:
            self._resume(rec, exclude=set(), relay_key=erid,
                         role_need="decode")

    def _terminal(self, rec: _StreamRec, reason: str) -> None:
        """Exactly-once terminal bookkeeping (caller holds the lock)."""
        if rec.done.is_set():
            return
        self.results[rec.rid] = list(rec.delivered)
        self.finish_reasons[rec.rid] = reason
        rec.done.set()

    # -- health ------------------------------------------------------------
    def _transition(self, rep: Replica, state: str) -> None:
        if rep.state == state:
            return
        _flight.record("router_replica_state", replica=rep.name,
                       prev=rep.state, state=state)
        _M_TRANSITIONS.inc(state=state)
        rep.state = state
        if state == "dead":
            rep.t_dead = self._now()
        if _obs.enabled():
            _M_HEALTHY.set(sum(1 for r in self.replicas.values()
                               if r.state == "healthy"))

    def _note_crash(self, rep: Replica) -> None:
        """Called from a dying replica thread: open the circuit and fail
        its streams over immediately — no need to wait for the
        heartbeat to age out."""
        with self._lock:
            already_dead = rep.state == "dead"
            if not already_dead:
                self._transition(rep, "dead")
        if not already_dead:
            self._failover(rep)

    def check(self) -> Dict[str, str]:
        """One health/drain tick: age heartbeats through the state
        machine, fail dead replicas' streams over, re-probe recovered
        ones (circuit half-open), migrate drain stragglers, finalize
        drains. Returns the post-tick state map. Uses ``now_fn``
        exclusively — inject a clock to drive transitions in tests."""
        now = self._now()
        failover: List[Replica] = []
        migrate: List[Replica] = []
        with self._lock:
            for rep in self.replicas.values():
                age = now - rep.hb
                if rep.state in ("draining", "drained"):
                    if rep.state == "draining":
                        if not rep.alive() or age >= self.dead_s:
                            # died mid-drain: same as any other death
                            self._transition(rep, "dead")
                            if rep.owned:
                                failover.append(rep)
                        elif not rep.owned and not rep.raw.has_work():
                            self._transition(rep, "drained")
                        elif now - self._drain_t0[rep.name] \
                                >= self.drain_s and rep.owned:
                            migrate.append(rep)
                    continue
                if rep.state == "dead":
                    # circuit breaker: a fresh heartbeat (live thread)
                    # after the re-probe delay earns ONE half-open probe
                    if rep.alive() and rep.crashed is None \
                            and age < self.suspect_s \
                            and now - rep.t_dead >= self.halfopen_s:
                        self._transition(rep, "half_open")
                        rep.probe_pending = True
                    continue
                if rep.state == "half_open":
                    if not rep.alive() or age >= self.dead_s:
                        # the probe window failed: re-open
                        rep.probe_pending = False
                        self._transition(rep, "dead")
                        if rep.owned:
                            failover.append(rep)
                    continue
                # healthy / suspect. Thread death is immediately fatal;
                # heartbeat AGE can only demote one level per tick
                # (healthy -> suspect, suspect -> dead): a single stale
                # observation after a clock step or VM pause must not
                # mass-kill replicas whose threads are fine — they get
                # one tick to stamp a fresh pulse and recover
                if not rep.alive():
                    self._transition(rep, "dead")
                    if rep.owned:
                        failover.append(rep)
                elif age >= self.dead_s and rep.state == "suspect":
                    self._transition(rep, "dead")
                    if rep.owned:
                        failover.append(rep)
                elif age >= self.suspect_s:
                    self._transition(rep, "suspect")
                elif rep.state == "suspect":
                    self._transition(rep, "healthy")
            if _obs.enabled():
                # stamp every tick, not only on transitions: a router
                # that boots healthy and never transitions must still
                # export the true pool size, not the gauge's 0 default
                _M_HEALTHY.set(sum(1 for r in self.replicas.values()
                                   if r.state == "healthy"))
        for rep in failover:
            self._failover(rep)
        for rep in migrate:
            self._migrate_stragglers(rep)
        if _obs.enabled():
            self._slo_tick()
        return self.states()

    def _slo_tick(self) -> None:
        """Fleet SLO burn-rate tick (r17, windowed since r20): sample
        the time-series ring (the router tick keeps history flowing
        even when every engine idles), refresh per-replica attainment
        gauges + breach events, and evaluate the anomaly watchers; with
        FLAGS_obs_fleet_slo_advisory on, a replica burning its windowed
        budget OR firing an advisory watcher (e.g. tok/s divergence vs
        the fleet median) is demoted healthy -> suspect — advisory
        only: placement steers away for a tick, the heartbeat machine
        re-promotes it when its latency recovers, and liveness alone
        still decides dead."""
        from ..observability import fleet as _fleet
        from ..observability import timeseries as _ts

        try:
            _ts.step_tick()
            burning = _fleet.check_slo(list(self.replicas))
            burning |= _ts.get_alert_engine().burning_replicas()
        except Exception as e:      # telemetry must never kill a tick
            _flight.record("router_slo_tick_error", error=repr(e)[:120])
            return
        if not burning or not bool(get_flag("obs_fleet_slo_advisory")):
            return
        with self._lock:
            for name in burning:
                rep = self.replicas.get(name)
                if rep is not None and rep.state == "healthy":
                    _flight.record("router_slo_advisory", replica=name)
                    self._transition(rep, "suspect")

    # -- failover / resume -------------------------------------------------
    def _relay(self):
        """The shared disagg relay pool, discovered from whichever
        replica engine carries one (they all share the SAME pool by
        construction); ``None`` on a non-disagg fleet."""
        for rep in self.replicas.values():
            r = getattr(rep.raw, "relay", None)
            if r is not None:
                return r
        return None

    def _failover(self, rep: Replica) -> None:
        """Re-dispatch every stream the dead replica owned: ``prompt +
        delivered`` becomes the new prompt, the remaining budget the new
        ``max_new_tokens``. The dead replica's engine rids become ghosts
        so late emissions dedupe instead of double-delivering."""
        relay = self._relay()
        with self._lock:
            moved = []
            for erid, rrid in list(rep.owned.items()):
                rep.owned.pop(erid)
                rep.ghosts.add(erid)
                rec = self._streams[rrid]
                self._unload(rep, rec)
                moved.append(rec)
                # a prefill replica dying between relay.put and the
                # router observing "handoff" leaves its spilled KV
                # orphaned under this erid — the stream re-dispatches
                # from the prompt, so the entry is dead weight (no-op
                # when nothing was spilled)
                if relay is not None:
                    relay.discard(erid)
            # its trie is unreachable until revive+recovery clears it
            rep.prefix_keys.clear()
            rep.load.clear()
        for rec in moved:
            if rec.done.is_set():
                continue
            self.failovers += 1
            _M_FAILOVERS.inc()
            if rec.cancelled:
                with self._lock:
                    self._terminal(rec, "client_disconnected")
                continue
            self._resume(rec, exclude={rep.name})

    def _resume(self, rec: _StreamRec, exclude: Set[str],
                relay_key: Optional[int] = None,
                role_need: str = "prefill") -> None:
        """Exactly-once stream resume on a healthy replica. Greedy
        determinism + the replayed-as-prefill overlap make the resumed
        stream token-identical to an uninterrupted run.

        Disagg (r19): a handoff resume passes ``relay_key`` (the
        prefill replica's engine rid, the relay entry's key) and
        ``role_need="decode"`` — the kw COPY sent to the decode replica
        carries the key, ``rec.kw`` never does (a later failover must
        re-prefill, not chase a consumed relay entry). A plain resume
        (``relay_key=None``) discards any relay entry still parked
        under the stream's old handoff key."""
        remaining = rec.max_new - len(rec.delivered)
        relay = (self._relay()
                 if relay_key is not None or rec.relay_key is not None
                 else None)
        if relay_key is None and rec.relay_key is not None:
            # re-prefilling from the prompt: a relay entry the decode
            # replica never consumed (it died first) is unreachable now
            if relay is not None:
                relay.discard(rec.relay_key)
            rec.relay_key = None
        if remaining <= 0:
            if relay_key is not None and relay is not None:
                relay.discard(relay_key)
            with self._lock:
                self._terminal(rec, "finished")
            return
        prompt = rec.prompt + rec.delivered
        kw = dict(rec.kw)
        kw["max_new_tokens"] = remaining
        if relay_key is not None:
            kw["relay_key"] = relay_key
            rec.relay_key = relay_key
        # an eos the dead replica already emitted would have finished
        # there; the resumed request keeps the same stopping rule
        rec.resumes += 1
        if relay_key is None:
            self.resumed_streams += 1
            _M_RESUMED.inc()
        else:
            self.handoff_resumes += 1
        prev_replica, prev_erid = rec.replica, rec.engine_rid
        try:
            retry_call(self._dispatch, rec, prompt, kw, exclude,
                       role_need, retries=2, base_delay=0.05,
                       exceptions=(TimeoutError,),
                       sleep=self._retry_sleep)
        except ShedError:
            # nowhere to resume: the stream ends in exactly one terminal
            # reason — shed — with its partial tokens delivered. A
            # disagg fleet with no decode-capable replica left lands
            # here (the documented degradation); its relay entry goes
            # with it.
            if relay_key is not None and relay is not None:
                relay.discard(relay_key)
            with self._lock:
                self._terminal(rec, "shed")
            self.router_sheds += 1
            _M_SHED.inc()
        except (ValueError, RuntimeError) as e:
            # resumed prompt no longer fits (model-len/bucket bound) or
            # every candidate died under the op: terminal, never a hang
            if relay_key is not None and relay is not None:
                relay.discard(relay_key)
            _flight.record("router_resume_failed", rid=rec.rid,
                           error=repr(e)[:120])
            with self._lock:
                self._terminal(rec, "shed")
            self.router_sheds += 1
            _M_SHED.inc()
        else:
            # failover-continuous tracing (r17): graft the old leg's
            # timeline onto the resumed engine rid, so the client's ONE
            # stream stays ONE trace — with a structured failover (or
            # disagg-handoff) hop — through the move. Old-rid lookups
            # alias forward; the dead replica's zombie writes hit an
            # unknown rid and no-op.
            if _obs.enabled() and prev_erid is not None:
                grafted = _rt.get_request_tracer().reassign(
                    prev_erid, rec.engine_rid,
                    **{"from": prev_replica, "to": rec.replica,
                       "delivered": len(rec.delivered)})
                _flight.record(
                    "router_handoff" if relay_key is not None
                    else "router_failover", rid=rec.rid,
                    **{"from": prev_replica, "to": rec.replica,
                       "delivered": len(rec.delivered),
                       "trace_grafted": bool(grafted)})

    # -- chaos / recovery hooks -------------------------------------------
    def kill_replica(self, name: str) -> None:
        """Chaos: sudden death of one replica (its step thread dies at
        the next loop boundary; in-flight streams fail over on the
        crash note or the next :meth:`check`)."""
        self.replicas[name].kill()

    def revive_replica(self, name: str) -> None:
        """Bring a dead replica's engine back to a serving state and
        restart its step thread. The circuit stays OPEN: the replica
        re-enters traffic through the half-open probe on a later
        :meth:`check`. Bootstrap goes through retry_call."""
        rep = self.replicas[name]
        if rep.alive():
            raise RuntimeError(f"replica {name} is still running")
        eng = rep.raw
        # drop the abandoned in-flight wave, requeue the slots, clear
        # the trie — then cancel the orphans the router already moved
        # elsewhere (the engine's idempotence guard counts any race
        # with an already-terminal id)
        eng.recover_crashed_step()
        with self._lock:
            ghosts = set(rep.ghosts)
            rep.prefix_keys.clear()
            rep.load.clear()
        for erid in ghosts:
            eng.cancel_request(erid, reason="client_disconnected")
        retry_call(self._boot_replica, rep, retries=3, base_delay=0.05,
                   exceptions=(RuntimeError,), sleep=self._retry_sleep)

    # -- drain -------------------------------------------------------------
    def begin_drain(self, name: str) -> None:
        """Steer new traffic away from one replica; in-flight streams
        keep running. Stragglers past the drain budget migrate to
        healthy replicas via the resume path on a later :meth:`check`."""
        rep = self.replicas[name]
        with self._lock:
            if rep.state in ("draining", "drained", "dead"):
                # dead is already out of rotation with no owned streams
                # (failover moved them); draining a corpse would wedge
                # on its frozen engine's has_work() forever
                return
            self._drain_t0[name] = self._now()
            self._transition(rep, "draining")
        _flight.record("router_drain_begin", replica=name)

    def _migrate_stragglers(self, rep: Replica) -> None:
        """Drain budget blown: cut every stream still on the draining
        replica (terminal reason ``drained`` there) and mark it for
        resume — _on_terminals re-dispatches with prompt + delivered."""
        with self._lock:
            pairs = [(erid, self._streams[rrid])
                     for erid, rrid in rep.owned.items()]
        for erid, rec in pairs:
            if not rec.cancelled:
                rec.migrating = True
            rep.enqueue(("cancel", erid, "drained"))

    def drain_all(self, timeout: float = 60.0) -> bool:
        """Whole-router drain (the r14 SIGTERM shape, one level up):
        drain every replica, wait for the streams to retire, then run
        the watchdog emergency hooks — same registry the front door and
        the train loop flush through."""
        t0 = time.monotonic()
        for name in self.replicas:
            self.begin_drain(name)
        ok = self.wait_all(timeout)
        self.check()
        _watchdog.run_emergency_hooks("router-drain",
                                      time.monotonic() - t0)
        _flight.maybe_dump("sigterm")
        return ok
