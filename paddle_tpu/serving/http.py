"""HTTP/SSE serving front door: the socket-facing edge of the engine.

Everything engine-side of production serving landed in r8-r13 — typed
``ShedError`` for overload, per-request deadlines, request traces,
admission control, swap preemption, crash recovery — but none of it was
exercised against the failure modes that actually arrive over a socket:
slow readers, mid-stream disconnects, overload bursts, restarts under
load. This module is that last layer: a stdlib-only asyncio HTTP/1.1
server running :class:`~paddle_tpu.serving.LLMEngine` (or its
:class:`~paddle_tpu.serving.ResilientEngine` wrapper) on a dedicated
step-loop thread, with robustness wired end to end:

- **Streaming** — ``POST /v1/generate`` emits one SSE ``data:`` frame
  per generated token plus a terminal frame carrying the finish reason
  and the full token list (``"stream": false`` returns one JSON body
  instead). The token stream is byte-identical to a direct engine run:
  frames are built by :func:`sse_token_frame` / :func:`sse_terminal_frame`
  with canonical JSON, so parity is testable at the byte level.
- **Backpressure** — each connection owns a bounded send queue; a slow
  client stalls only its own stream (the engine thread never blocks on
  a socket). Past ``FLAGS_serve_send_queue_hwm`` queued frames for
  longer than ``FLAGS_serve_client_stall_s``, the request is cancelled
  server-side and the connection aborted — one wedged reader cannot
  pin a slot's KV blocks forever.
- **Disconnect cancellation** — a dropped connection (write failure or
  reader EOF) marks the request via ``LLMEngine.cancel_request``; the
  next engine step evicts it through the deadline-eviction path, so its
  slot and KV blocks free within ONE step and its trace closes with the
  ``client_disconnected`` terminal reason.
- **Typed overload behavior** — ``ShedError{queue_full, rate_limited,
  pool_pressure}`` maps to 503/429/503 with ``Retry-After`` derived
  from the admission token bucket (``AdmissionController.retry_after``);
  the ``X-Tenant`` header feeds the existing per-tenant rate limits. A
  client-supplied ``timeout_s`` maps onto ``Request.deadline_s``, so a
  blown deadline returns a partial-result terminal frame, never a hang.
- **Graceful drain** — SIGTERM/SIGINT (wired by ``tools/serve.py``) or
  :meth:`HTTPFrontDoor.begin_drain` stops admission (new requests get
  503 + ``Connection: close``), lets in-flight streams finish up to
  ``FLAGS_serve_drain_s``, cancels the stragglers with reason
  ``drained``, runs the watchdog emergency hooks + flight-recorder
  post-mortem, and reports ``serving_http_drain_seconds``.
- **Orchestration probes** — ``GET /healthz`` answers 200 while the
  process lives; ``GET /readyz`` answers 200 only while the step loop
  is healthy AND not draining (the load-balancer eviction signal).
- **Telemetry (r17)** — when observability is enabled the door also
  serves ``GET /metrics`` (Prometheus text) / ``/metrics.json`` (JSON
  snapshot) and the fleet federation views ``/fleet/metrics``,
  ``/fleet/replicas.json``, ``/fleet/placements.json`` — a scraper
  needs only the serving port; 503 while obs is off.
- **Recovery visibility** — a :class:`ResilientEngine` recovery during
  an active stream surfaces as an SSE ``: retrying`` comment frame on
  every live stream instead of a silent stall.

Threading model: three owners, no shared mutable engine state. The
asyncio loop thread owns sockets and per-connection coroutines; the
step-loop thread owns the engine (submissions and cancellations travel
to it through a thread-safe op queue; results travel back through
``call_soon_threadsafe``); the caller's thread only starts/stops/drains.
The engine is never touched off the step thread — the same single-owner
contract its pipelined state machine already requires.

    eng = LLMEngine(params, cfg, admission=AdmissionConfig(max_queue=64))
    front = HTTPFrontDoor(ResilientEngine(eng), port=8000)
    front.start()
    ...
    front.begin_drain(); front.wait_drained()

Chaos surface: ``tools/chaos_run.py --http`` drives concurrent stdlib
clients with seeded mid-stream disconnects, stalled readers, a 2x
overload burst and a SIGTERM mid-stream, asserting the engine-side
invariants (one terminal reason per id, balanced block ledger every
step, zero live slots/streams after drain) from the socket inward.
"""
from __future__ import annotations

import asyncio
import collections
import json
import math
import threading
import time
from typing import Dict, List, Optional, Tuple

from .. import observability as _obs
from ..framework.flags import define_flag, get_flag
from ..observability import flight_recorder as _flight
from ..observability.catalog import instrument as _instrument
from .admission import AdmissionController, ShedError
from .resilient import ResilientEngine

__all__ = ["HTTPFrontDoor", "sse_token_frame", "sse_terminal_frame",
           "sse_retry_frame"]

define_flag("serve_client_stall_s", 10.0,
            "seconds a client may leave its SSE send queue above the "
            "high-water mark before the server cancels the request and "
            "aborts the connection (slow-reader protection)")
define_flag("serve_drain_s", 30.0,
            "graceful-drain budget: seconds in-flight streams may keep "
            "running after SIGTERM/begin_drain before they are cut "
            "with terminal reason 'drained'")
define_flag("serve_send_queue_hwm", 32,
            "per-connection send-queue high-water mark (queued frames); "
            "above it the slow-reader stall clock starts")

_M_HTTP_REQS = _instrument("serving_http_requests_total")
_M_ACTIVE_STREAMS = _instrument("serving_http_active_streams")
_M_DISCONNECTS = _instrument("serving_http_client_disconnects_total")
_M_SEND_QUEUE = _instrument("serving_http_send_queue_depth")
_M_DRAIN_SECONDS = _instrument("serving_http_drain_seconds")

_REASONS = {200: "OK", 400: "Bad Request", 404: "Not Found",
            405: "Method Not Allowed", 408: "Request Timeout",
            413: "Payload Too Large", 429: "Too Many Requests",
            500: "Internal Server Error", 503: "Service Unavailable"}

# request bodies and header blocks buffer in memory before validation:
# bound them (every other per-connection resource here is bounded — the
# inputs must be too)
_MAX_BODY_BYTES = 1 << 20
_MAX_HEADER_LINES = 100


class _BodyTooLarge(Exception):
    def __init__(self, what: str, n: int, limit: int):
        super().__init__(f"request {what} of {n} exceeds the "
                         f"{limit} limit")


# ShedError.reason -> HTTP status (the typed-overload contract)
_SHED_STATUS = {"queue_full": 503, "rate_limited": 429,
                "pool_pressure": 503}


# ---------------------------------------------------------------------------
# SSE frame contract (canonical bytes — the parity tests compare these)
# ---------------------------------------------------------------------------
def _canon(obj) -> bytes:
    return json.dumps(obj, separators=(",", ":"), sort_keys=True).encode()


def sse_token_frame(token: int) -> bytes:
    """One generated token: ``data: {"token": N}\\n\\n``."""
    return b'data: {"token": ' + str(int(token)).encode() + b"}\n\n"


def sse_terminal_frame(request_id: int, reason: str,
                       tokens: List[int]) -> bytes:
    """The stream's last frame: finish reason + the FULL token list
    (tokens streamed before a preemption/recovery included), canonical
    JSON so a reference engine run reconstructs the exact bytes."""
    return b"data: " + _canon({"done": True, "reason": str(reason),
                               "request_id": int(request_id),
                               "tokens": [int(t) for t in tokens]}) \
        + b"\n\n"


def sse_retry_frame(recoveries: int) -> bytes:
    """SSE comment emitted when ResilientEngine recovers a crashed step
    while streams are live — comments are ignored by SSE parsers, so
    clients that don't care see nothing, and clients that do see the
    engine retrying instead of a silent stall."""
    return b": retrying engine-step recovery " \
        + str(int(recoveries)).encode() + b"\n\n"


# ---------------------------------------------------------------------------
# per-request stream state (created on the step thread at admission)
# ---------------------------------------------------------------------------
class _Stream:
    __slots__ = ("rid", "queue", "loop", "writer", "stall_t0",
                 "cancelled")

    def __init__(self, rid, queue, loop):
        self.rid = rid
        self.queue = queue          # asyncio.Queue, consumed on the loop
        self.loop = loop
        self.writer = None          # StreamWriter once the handler streams
        self.stall_t0 = None        # when qsize first crossed the HWM
        self.cancelled = False

    def post(self, item) -> None:
        """Thread-safe enqueue from the step thread (put_nowait must run
        on the loop thread — asyncio queues are not thread-safe)."""
        try:
            self.loop.call_soon_threadsafe(self.queue.put_nowait, item)
        except RuntimeError:
            pass                    # loop already closed (late shutdown)

    def abort(self) -> None:
        """Hard-close the connection from the loop thread: a stalled
        reader's writer coroutine is parked in ``drain()`` and can never
        send a terminal frame — aborting the transport unblocks it."""
        w = self.writer
        if w is not None:
            try:
                w.transport.abort()
            except Exception:
                pass


class HTTPFrontDoor:
    """Asyncio HTTP/1.1 + SSE server over a dedicated engine thread.

    ``engine``: an :class:`LLMEngine` or a :class:`ResilientEngine`
    (recoveries then surface as ``: retrying`` SSE comments).
    ``step_hook``: optional ``fn(raw_engine)`` invoked on the step
    thread after every engine step — the chaos harness's per-step
    ledger assertion point. ``port=0`` binds an ephemeral port
    (``.port`` holds the real one after :meth:`start`).
    """

    def __init__(self, engine, host: str = "127.0.0.1", port: int = 0,
                 step_hook=None, idle_wait: float = 0.02):
        if isinstance(engine, ResilientEngine):
            self.resilient: Optional[ResilientEngine] = engine
            self.engine = engine.engine
        else:
            self.resilient = None
            self.engine = engine
        self.host = host
        self.port = int(port)
        self.step_hook = step_hook
        self.idle_wait = float(idle_wait)
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._server = None
        self._streams: Dict[int, _Stream] = {}   # step-thread-owned
        self._ops: collections.deque = collections.deque()
        self._wake = threading.Event()
        self._started = threading.Event()
        self._drained = threading.Event()
        self._drain_t0: Optional[float] = None
        self._drain_budget: Optional[float] = None
        self._drain_cut = False
        self._stopping = False
        self._healthy = True
        self._loop_thread: Optional[threading.Thread] = None
        self._step_thread: Optional[threading.Thread] = None

    # -- lifecycle --------------------------------------------------------
    def start(self) -> Tuple[str, int]:
        """Bind the server and start the loop + step threads; returns
        ``(host, port)`` once the socket is listening."""
        self._loop = asyncio.new_event_loop()
        self._loop_thread = threading.Thread(
            target=self._loop_main, name="serving-http-loop", daemon=True)
        self._loop_thread.start()
        self._started.wait(10)
        if not self._started.is_set():
            raise RuntimeError("HTTP front door failed to start")
        self._step_thread = threading.Thread(
            target=self._step_loop, name="serving-http-step", daemon=True)
        self._step_thread.start()
        return self.host, self.port

    def _loop_main(self) -> None:
        asyncio.set_event_loop(self._loop)

        async def boot():
            self._server = await asyncio.start_server(
                self._handle, self.host, self.port)
            self.port = self._server.sockets[0].getsockname()[1]

        try:
            self._loop.run_until_complete(boot())
        finally:
            self._started.set()
        try:
            self._loop.run_forever()
        finally:
            try:
                if self._server is not None:
                    self._server.close()
                self._loop.run_until_complete(asyncio.sleep(0))
            except Exception:
                pass
            self._loop.close()

    def begin_drain(self, drain_s: Optional[float] = None) -> None:
        """Start graceful drain (idempotent, any thread): admission
        stops, ``/readyz`` flips to 503, in-flight streams run up to
        the budget (``FLAGS_serve_drain_s`` unless overridden), then
        stragglers are cancelled with terminal reason ``drained``."""
        if self._drain_t0 is not None:
            return
        self._drain_budget = (float(get_flag("serve_drain_s"))
                              if drain_s is None else float(drain_s))
        self._drain_t0 = time.monotonic()
        _flight.record("serving_drain_begin",
                       live_streams=len(self._streams),
                       budget_s=self._drain_budget)
        self._wake.set()

    def wait_drained(self, timeout: Optional[float] = None) -> bool:
        return self._drained.wait(timeout)

    def stop(self, drain_s: float = 0.0,
             timeout: float = 30.0) -> None:
        """Drain (default: immediately — tests and Ctrl-C-twice) and
        tear the threads down."""
        self.begin_drain(drain_s=drain_s)
        self._drained.wait(timeout)
        time.sleep(0.25)          # let final terminal frames flush
        self._stopping = True
        self._wake.set()
        if self._loop is not None:
            try:
                self._loop.call_soon_threadsafe(self._loop.stop)
            except RuntimeError:
                pass
        if self._step_thread is not None:
            self._step_thread.join(timeout)
        if self._loop_thread is not None:
            self._loop_thread.join(timeout)

    @property
    def draining(self) -> bool:
        return self._drain_t0 is not None

    @property
    def ready(self) -> bool:
        """The ``/readyz`` condition: step loop alive and healthy, not
        draining."""
        return (self._healthy and not self.draining
                and self._step_thread is not None
                and self._step_thread.is_alive())

    @property
    def active_streams(self) -> int:
        return len(self._streams)

    # -- step-loop thread (the engine's single owner) ---------------------
    def _stepper_step(self):
        return (self.resilient.step() if self.resilient is not None
                else self.engine.step())

    def _step_loop(self) -> None:
        eng = self.engine
        try:
            while not self._stopping:
                self._run_ops()
                if self.draining and not self._drain_cut and \
                        time.monotonic() - self._drain_t0 \
                        > self._drain_budget:
                    # budget blown: cut every straggler — terminal
                    # reason "drained", applied by the next step
                    self._drain_cut = True
                    for rid in list(self._streams):
                        eng.cancel_request(rid, reason="drained")
                if eng.has_work():
                    rec0 = (self.resilient.recoveries
                            if self.resilient is not None else 0)
                    emitted = self._stepper_step()
                    if self.resilient is not None \
                            and self.resilient.recoveries > rec0:
                        # recovery mid-stream: every live client sees a
                        # retrying comment, never a silent stall
                        frame_n = self.resilient.recoveries
                        for st in self._streams.values():
                            st.post(("retry", frame_n))
                    self._route(emitted)
                    self._notify_terminals()
                    self._sweep_stalls()
                    if self.step_hook is not None:
                        self.step_hook(eng)
                else:
                    if eng._inflight is not None:   # defensive, as run()
                        self._route(eng._process_inflight())
                    self._notify_terminals()
                    if self.draining and not self._streams:
                        break
                    self._wake.wait(self.idle_wait)
                    self._wake.clear()
        except Exception as e:                       # pragma: no cover
            # an unrecoverable engine error must not strand clients in
            # a silent hang: fail every live stream and go unready
            self._healthy = False
            _flight.record("serving_http_step_loop_died",
                           error=f"{type(e).__name__}: {e}"[:160])
            for rid, st in list(self._streams.items()):
                st.post(("done", "error",
                         list(eng.results.get(rid, []))))
                self._streams.pop(rid, None)
        finally:
            self._finish_drain()

    def _fail_pending_ops(self) -> None:
        """Resolve any submit op still queued when the step loop is gone
        (the drain-complete break can race a handler's append): its
        client must get the draining 503, not an eternal ``await fut``.
        Safe from either thread — deque pops are atomic and the futures
        resolve on the loop thread, first setter wins."""
        while self._ops:
            try:
                op = self._ops.popleft()
            except IndexError:
                break
            if op[0] != "submit":
                continue
            fut = op[3]

            def _fail(f=fut):
                if not f.done():
                    f.set_exception(ShedError("draining"))
            try:
                self._loop.call_soon_threadsafe(_fail)
            except RuntimeError:
                pass

    def _finish_drain(self) -> None:
        if self._drained.is_set():
            return
        self._fail_pending_ops()
        if self._drain_t0 is not None:
            elapsed = time.monotonic() - self._drain_t0
            _M_DRAIN_SECONDS.observe(elapsed)
            _flight.record("serving_drain_done",
                           elapsed_s=round(elapsed, 3))
            # "checkpoint" analog of the train loop's SIGTERM path: run
            # the registered watchdog emergency hooks (a serving process
            # with a checkpointing hook flushes it here), then the
            # flight-recorder post-mortem when FLAGS_obs_postmortem_dir
            # is set
            from ..distributed.watchdog import run_emergency_hooks
            run_emergency_hooks("serving-drain", elapsed)
            _flight.maybe_dump("sigterm")
        if _obs.enabled():
            _M_ACTIVE_STREAMS.set(0)
        self._drained.set()
        # close the append/flag race: a handler that appended its op
        # before the set() above either got popped by the first
        # _fail_pending_ops or gets popped here; one that appends after
        # the set() sees _drained in _generate and fails its own op
        self._fail_pending_ops()

    def _run_ops(self) -> None:
        """Apply queued submissions/cancellations from the loop thread
        — the only path by which connections touch the engine."""
        while self._ops:
            op = self._ops.popleft()
            if op[0] == "submit":
                _kind, kw, queue, fut = op
                self._op_submit(kw, queue, fut)
            elif op[0] == "cancel":
                _kind, rid, cause = op
                st = self._streams.get(rid)
                if st is not None and not st.cancelled:
                    st.cancelled = True
                    self.engine.cancel_request(
                        rid, reason="client_disconnected")
                    _M_DISCONNECTS.inc()
                    _flight.record("serving_http_client_disconnect",
                                   req_id=rid, cause=cause)
                self._wake.set()

    def _op_submit(self, kw: Dict, queue, fut) -> None:
        loop = self._loop
        try:
            if self.draining:
                raise ShedError("draining")
            rid = self.engine.add_request(kw.pop("prompt"), **kw)
        except BaseException as e:
            err = e

            def _fail():
                if not fut.cancelled():
                    fut.set_exception(err)
            loop.call_soon_threadsafe(_fail)
            return
        st = _Stream(rid, queue, loop)
        self._streams[rid] = st
        if _obs.enabled():
            _M_ACTIVE_STREAMS.set(len(self._streams))

        def _ok():
            if not fut.cancelled():
                fut.set_result((rid, st))
        loop.call_soon_threadsafe(_ok)

    def _route(self, emitted) -> None:
        """Fan one step's (rid, token) pairs out to their streams — one
        cross-thread post per request per step, not per token."""
        if not emitted:
            return
        per: Dict[int, List[int]] = {}
        for rid, tok in emitted:
            per.setdefault(rid, []).append(int(tok))
        for rid, toks in per.items():
            st = self._streams.get(rid)
            if st is not None:
                st.post(("toks", toks))

    def _notify_terminals(self) -> None:
        """Close out every owned stream whose request reached a terminal
        reason this step (finished / deadline_exceeded /
        client_disconnected / drained)."""
        if not self._streams:
            return
        reasons = self.engine.finish_reasons
        done = [rid for rid in self._streams if rid in reasons]
        for rid in done:
            st = self._streams.pop(rid)
            st.post(("done", reasons[rid],
                     list(self.engine.results.get(rid, []))))
        if done and _obs.enabled():
            _M_ACTIVE_STREAMS.set(len(self._streams))

    def _sweep_stalls(self) -> None:
        """Slow-reader protection: a stream whose send queue sits above
        the high-water mark for longer than FLAGS_serve_client_stall_s
        is cancelled server-side and its connection aborted. qsize() is
        a plain deque length — safe to read cross-thread."""
        if not self._streams:
            if _obs.enabled():
                _M_SEND_QUEUE.set(0)
            return
        hwm = int(get_flag("serve_send_queue_hwm"))
        stall_s = float(get_flag("serve_client_stall_s"))
        now = time.monotonic()
        depth_max = 0
        for rid, st in list(self._streams.items()):
            depth = st.queue.qsize()
            depth_max = max(depth_max, depth)
            if depth <= hwm:
                st.stall_t0 = None
                continue
            if st.stall_t0 is None:
                st.stall_t0 = now
            elif now - st.stall_t0 > stall_s and not st.cancelled:
                st.cancelled = True
                self.engine.cancel_request(
                    rid, reason="client_disconnected")
                _M_DISCONNECTS.inc()
                _flight.record("serving_http_client_stalled",
                               req_id=rid, queued_frames=depth,
                               stalled_s=round(now - st.stall_t0, 3))
                # the writer coroutine is parked in drain() and can
                # never deliver a terminal frame — abort the transport
                if self._loop is not None:
                    try:
                        self._loop.call_soon_threadsafe(st.abort)
                    except RuntimeError:
                        pass
        if _obs.enabled():
            _M_SEND_QUEUE.set(depth_max)

    # -- asyncio loop thread (sockets only, never the engine) -------------
    async def _handle(self, reader, writer) -> None:
        t0 = time.perf_counter()
        code = 500
        path = "?"
        method = "?"
        try:
            # modest write buffer: drain() must apply backpressure per
            # frame, not after the kernel swallowed kilobytes of them
            writer.transport.set_write_buffer_limits(high=4096, low=1024)
            req = await asyncio.wait_for(self._read_request(reader), 30)
            if req is None:
                # connect-then-close (a TCP health probe) or a garbage
                # request line: nothing was answered, so nothing counts
                # — a load balancer probing every few seconds must not
                # read as a climbing 500 rate
                code = None
                return
            method, path, headers, body = req
            code = await self._dispatch(method, path, headers, body,
                                        reader, writer)
        except _BodyTooLarge as e:
            try:
                self._respond(writer, 413, {"error": str(e)})
            except Exception:
                pass
            code = 413
        except (ConnectionError, asyncio.IncompleteReadError,
                asyncio.TimeoutError, BrokenPipeError):
            code = 408
        except Exception as e:
            try:
                self._respond(writer, 500,
                              {"error": f"{type(e).__name__}: {e}"})
                code = 500
            except Exception:
                pass
        finally:
            if code is not None:
                _M_HTTP_REQS.inc(code=str(code))
                if _obs.enabled():
                    _obs.get_tracer().record(
                        "serving.http_request", t0, time.perf_counter(),
                        {"method": method, "path": path, "code": code},
                        depth=0)
            try:
                writer.close()
            except Exception:
                pass

    async def _read_request(self, reader):
        line = await reader.readline()
        if not line:
            return None
        parts = line.decode("latin1").split()
        if len(parts) < 2:
            return None
        method, path = parts[0].upper(), parts[1]
        headers = {}
        while True:
            h = await reader.readline()
            if h in (b"\r\n", b"\n", b""):
                break
            if len(headers) >= _MAX_HEADER_LINES:
                # a client streaming endless header lines would grow
                # this dict for the whole request timeout otherwise
                raise _BodyTooLarge("header lines", len(headers) + 1,
                                    _MAX_HEADER_LINES)
            k, _, v = h.decode("latin1").partition(":")
            headers[k.strip().lower()] = v.strip()
        n = int(headers.get("content-length") or 0)
        if n > _MAX_BODY_BYTES:
            # before buffering a single body byte
            raise _BodyTooLarge("body bytes", n, _MAX_BODY_BYTES)
        body = await reader.readexactly(n) if n else b""
        return method, path, headers, body

    def _respond(self, writer, code: int, obj, extra=()) -> None:
        body = _canon(obj) + b"\n"
        head = (f"HTTP/1.1 {code} {_REASONS.get(code, 'Unknown')}\r\n"
                f"Content-Type: application/json\r\n"
                f"Content-Length: {len(body)}\r\n"
                "Connection: close\r\n")
        for k, v in extra:
            head += f"{k}: {v}\r\n"
        writer.write(head.encode("latin1") + b"\r\n" + body)

    def _respond_text(self, writer, code: int, text: str,
                      ctype: str = "text/plain; version=0.0.4; "
                                   "charset=utf-8") -> None:
        body = text.encode()
        head = (f"HTTP/1.1 {code} {_REASONS.get(code, 'Unknown')}\r\n"
                f"Content-Type: {ctype}\r\n"
                f"Content-Length: {len(body)}\r\n"
                "Connection: close\r\n")
        writer.write(head.encode("latin1") + b"\r\n" + body)

    def _telemetry(self, method, path, writer) -> Optional[int]:
        """Serve the observability surface off the front door itself
        (r17): /metrics + /metrics.json (this process's registry) and
        the /fleet/* federation views — a scraper needs only the door's
        port, no separate obs server. None when ``path`` is not a
        telemetry route; all are read-only GETs."""
        if path not in ("/metrics", "/metrics.json", "/fleet/metrics",
                        "/fleet/replicas.json", "/fleet/placements.json",
                        "/alerts.json"):
            return None
        if method != "GET":
            self._respond(writer, 405, {"error": "GET only"})
            return 405
        import paddle_tpu.observability as _obs

        if not _obs.enabled():
            self._respond(writer, 503,
                          {"error": "observability disabled "
                                    "(FLAGS_obs_enabled)"})
            return 503
        from paddle_tpu.observability import fleet as _fleet
        from paddle_tpu.observability.exposition import (
            render_prometheus, snapshot)

        if path == "/metrics":
            self._respond_text(writer, 200, render_prometheus())
        elif path == "/metrics.json":
            self._respond(writer, 200, snapshot())
        elif path == "/fleet/metrics":
            self._respond_text(writer, 200, _fleet.fleet_metrics_text())
        elif path == "/fleet/replicas.json":
            self._respond(writer, 200, _fleet.replicas_payload())
        elif path == "/alerts.json":
            from paddle_tpu.observability import timeseries as _ts

            self._respond(writer, 200, _ts.alerts_payload())
        else:
            self._respond(writer, 200, _fleet.placements_payload())
        return 200

    async def _dispatch(self, method, path, headers, body, reader,
                        writer) -> int:
        path = path.split("?", 1)[0]
        if path == "/healthz":
            if method != "GET":
                self._respond(writer, 405, {"error": "GET only"})
                return 405
            self._respond(writer, 200,
                          {"ok": True, "draining": self.draining})
            return 200
        if path == "/readyz":
            if method != "GET":
                self._respond(writer, 405, {"error": "GET only"})
                return 405
            code = 200 if self.ready else 503
            self._respond(writer, code,
                          {"ready": self.ready,
                           "draining": self.draining})
            return code
        code = self._telemetry(method, path, writer)
        if code is not None:
            return code
        if path != "/v1/generate":
            self._respond(writer, 404, {"error": f"no route {path}"})
            return 404
        if method != "POST":
            self._respond(writer, 405, {"error": "POST only"})
            return 405
        return await self._generate(headers, body, reader, writer)

    # -- /v1/generate -----------------------------------------------------
    def _parse_generate(self, headers, body) -> Tuple[Dict, bool]:
        try:
            doc = json.loads(body.decode() or "{}")
        except (ValueError, UnicodeDecodeError) as e:
            raise ValueError(f"bad JSON body: {e}")
        if not isinstance(doc, dict):
            raise ValueError("body must be a JSON object")
        prompt = doc.get("prompt")
        if not isinstance(prompt, list) or not prompt \
                or not all(isinstance(t, int) for t in prompt):
            raise ValueError(
                "'prompt' must be a non-empty list of token ids (the "
                "engine is tokenizer-free; tokenize client-side)")
        kw: Dict = {"prompt": [int(t) for t in prompt]}
        for key, typ in (("max_new_tokens", int), ("temperature", float),
                         ("top_k", int), ("top_p", float),
                         ("eos_token_id", int)):
            if doc.get(key) is not None:
                try:
                    kw[key] = typ(doc[key])
                except (TypeError, ValueError):
                    raise ValueError(f"'{key}' must be a {typ.__name__}")
        # the client's latency budget becomes the engine's deadline:
        # expiry delivers a partial-result terminal frame, never a hang
        if doc.get("timeout_s") is not None:
            try:
                kw["deadline_s"] = float(doc["timeout_s"])
            except (TypeError, ValueError):
                raise ValueError("'timeout_s' must be a number")
        tenant = headers.get("x-tenant")
        if tenant:
            kw["tenant"] = str(tenant)
        stream = doc.get("stream", True)
        if not isinstance(stream, bool):
            raise ValueError("'stream' must be a boolean")
        return kw, stream

    def _shed_response(self, writer, exc: ShedError, kw: Dict) -> int:
        code = _SHED_STATUS.get(exc.reason, 503)
        retry_after = 1.0
        adm = self.engine.admission
        if exc.reason == "rate_limited" \
                and isinstance(adm, AdmissionController):
            cost = len(kw.get("prompt") or ()) \
                + int(kw.get("max_new_tokens", 64))
            retry_after = max(
                1.0, adm.retry_after(kw.get("tenant", "default"), cost))
        self._respond(
            writer, code,
            {"error": str(exc), "reason": exc.reason,
             "request_id": exc.req_id},
            extra=[("Retry-After", str(int(math.ceil(retry_after))))])
        return code

    async def _generate(self, headers, body, reader, writer) -> int:
        if self.draining:
            # stopped admission: orchestrators see Connection: close +
            # 503 and take the replica out of rotation
            self._respond(writer, 503,
                          {"error": "draining", "reason": "draining"})
            return 503
        try:
            kw, stream = self._parse_generate(headers, body)
        except ValueError as e:
            self._respond(writer, 400, {"error": str(e)})
            return 400
        fut = self._loop.create_future()
        queue: asyncio.Queue = asyncio.Queue()
        self._ops.append(("submit", dict(kw), queue, fut))
        self._wake.set()
        if self._drained.is_set():
            # the step loop may already have taken its final _run_ops
            # pass — resolve the orphan here instead of awaiting forever
            self._fail_pending_ops()
        try:
            rid, st = await fut
        except ShedError as e:
            if e.reason == "draining":
                self._respond(writer, 503,
                              {"error": "draining",
                               "reason": "draining"})
                return 503
            return self._shed_response(writer, e, kw)
        except ValueError as e:
            self._respond(writer, 400, {"error": str(e)})
            return 400
        if stream:
            return await self._stream_sse(rid, st, reader, writer)
        return await self._respond_json(rid, st, reader, writer)

    def _request_cancel(self, rid: int, cause: str) -> None:
        self._ops.append(("cancel", rid, cause))
        self._wake.set()

    async def _drain_bounded(self, writer) -> None:
        """``drain()`` with a hard deadline. The stall sweep only covers
        streams the front door still owns — a client that stops reading
        right as its request reaches a terminal reason leaves the sweep's
        sight (``_notify_terminals`` pops it), so the writer itself must
        never park in ``drain()`` forever holding the socket, the
        coroutine and the queued frames. A blown deadline aborts the
        transport and surfaces as the disconnect path."""
        try:
            await asyncio.wait_for(
                writer.drain(),
                max(1.0, float(get_flag("serve_client_stall_s"))))
        except asyncio.TimeoutError:
            try:
                writer.transport.abort()
            except Exception:
                pass
            raise ConnectionResetError(
                "client write stalled past FLAGS_serve_client_stall_s")

    async def _watch_eof(self, reader) -> None:
        """Resolve when the client's half of the socket closes — the
        mid-stream disconnect signal (clients never send bytes after
        the request, so any read completing means EOF or junk)."""
        while True:
            try:
                data = await reader.read(65536)
            except (ConnectionError, asyncio.CancelledError):
                return
            if not data:
                return

    async def _stream_sse(self, rid, st: _Stream, reader,
                          writer) -> int:
        st.writer = writer
        writer.write(b"HTTP/1.1 200 OK\r\n"
                     b"Content-Type: text/event-stream\r\n"
                     b"Cache-Control: no-store\r\n"
                     b"Connection: close\r\n\r\n")
        eof_task = asyncio.ensure_future(self._watch_eof(reader))
        get_task = None
        try:
            await self._drain_bounded(writer)
            while True:
                get_task = asyncio.ensure_future(st.queue.get())
                done, _pending = await asyncio.wait(
                    {get_task, eof_task},
                    return_when=asyncio.FIRST_COMPLETED)
                if eof_task in done:
                    # client hung up mid-stream (EOF wins even over a
                    # ready frame — the socket is gone): cancel
                    # server-side so the slot + KV blocks free at the
                    # next engine step
                    get_task.cancel()
                    self._request_cancel(rid, "eof")
                    return 200
                item = get_task.result()
                if item[0] == "toks":
                    for tok in item[1]:
                        writer.write(sse_token_frame(tok))
                    await self._drain_bounded(writer)
                elif item[0] == "retry":
                    writer.write(sse_retry_frame(item[1]))
                    await self._drain_bounded(writer)
                elif item[0] == "done":
                    writer.write(sse_terminal_frame(rid, item[1],
                                                    item[2]))
                    await self._drain_bounded(writer)
                    return 200
        except (ConnectionError, BrokenPipeError,
                asyncio.CancelledError):
            self._request_cancel(rid, "write_failed")
            return 200
        finally:
            eof_task.cancel()
            if get_task is not None and not get_task.done():
                get_task.cancel()

    async def _respond_json(self, rid, st: _Stream, reader,
                            writer) -> int:
        """Non-streaming mode: consume the stream queue privately and
        answer with one JSON body at the terminal."""
        eof_task = asyncio.ensure_future(self._watch_eof(reader))
        try:
            while True:
                get_task = asyncio.ensure_future(st.queue.get())
                done, _pending = await asyncio.wait(
                    {get_task, eof_task},
                    return_when=asyncio.FIRST_COMPLETED)
                if eof_task in done:
                    get_task.cancel()
                    self._request_cancel(rid, "eof")
                    return 408
                item = get_task.result()
                if item[0] == "done":
                    self._respond(writer, 200,
                                  {"request_id": int(rid),
                                   "reason": item[1],
                                   "tokens": item[2]})
                    await self._drain_bounded(writer)
                    return 200
        except (ConnectionError, BrokenPipeError,
                asyncio.CancelledError):
            self._request_cancel(rid, "write_failed")
            return 408
        finally:
            eof_task.cancel()
