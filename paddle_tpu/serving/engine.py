"""Continuous-batching paged-KV serving engine for llama-family models.

Parity surface: the reference wires its paged decode kernel into serving via
incubate/nn/functional/block_multihead_attention (block tables + per-seq
lengths updated by an external loop); vLLM-style continuous batching is the
behavioral model its serving stacks build on top.

TPU-native design — everything the chip executes has STATIC shapes:

- ONE compiled decode step over ``max_slots`` sequence slots. A slot is
  a row of the batch; requests come and go, the program never retraces
  on slot churn. Idle slots write their K/V to a reserved trash block
  and are masked out of sampling.
- Ragged paged attention (r12, the TPU default): decode attention runs
  the Pallas true-length block-walk kernel
  (kernels/paged_attention.ragged_decode_partial) — per-slot programs
  read exactly ``ceil(length/bs)`` real blocks with an online softmax,
  lengths ride as a RUNTIME operand and the block table ships at full
  width, so the decode compile cache holds ONE variant per
  (batch, sampling-flags) set and per-step KV reads scale with the
  tokens actually resident. Off-TPU (or forced via
  ``decode_kernel="bucketed"``) the r6 fallback applies instead: the
  dense prefix gather spans the smallest power-of-two BLOCK COUNT
  covering ``max(lengths) + decode_steps`` across the active slots
  (plus the in-flight pipeline lag) — bounded at (log2 buckets) x
  (<= 8 sampling-flag tuples) compiled variants. Either path is
  counted per dispatch in ``serving_decode_kernel_total{path}`` and
  mirrored by the ``serving_decode_prefix_bucket`` /
  ``serving_decode_variants`` gauges and the
  ``serving_decode_recompiles_total`` counter.
- Bucketed prefill: prompts pad to the smallest configured bucket, one
  compiled program per bucket (the guard-cache analogue of the reference's
  shape-bucketed serving graphs). Prefill K/V is scattered straight into
  the slot's pool blocks; blocks past the true length are handed back.
- Host-side block allocator: a free list over a
  ``[L, num_blocks, block_size, Hkv, D]`` pool pair. Admission reserves
  ceil(bucket/bs) blocks; decode allocates one block per slot whenever the
  next token crosses a block boundary; EOS/max-len frees the slot. When the
  pool runs dry mid-decode the newest-admitted request is preempted (blocks
  freed, request re-queued for a fresh prefill) — forward progress for the
  rest, vLLM's recompute-preemption policy.
- int8 everywhere (optional, decode is weight/KV-bandwidth-bound):
  int8 weight-only params (models/llama.quantize_params) feed the matmuls
  UNCONVERTED via kernels/quant_matmul.weight_only_matmul — scales apply
  to the output, no dequantized weight copy per step — including under a
  'tp' mesh (the int8 qweights + scales shard with the same Megatron
  specs as their dense counterparts). ``kv_dtype="int8"`` additionally
  quantizes the K/V pools with per-entry scales dequantized inside the
  bucketed attention contractions: half the decode KV traffic, double the
  effective block-pool capacity at the same HBM (fewer preemptions).
- Per-request sampling knobs (temperature/top-k/top-p) ride as traced
  vectors through the compiled step: varying them never recompiles.
- Pools are donated through both prefill and decode (jax donate_argnums),
  so the multi-GB cache is updated in place, never copied per token.
- Prefix caching + chunked prefill (optional, r10): ``prefix_cache=True``
  indexes full prompt blocks in a refcounted radix trie
  (serving/prefix_cache.py) so admissions sharing a system prompt or
  multi-turn prefix pin the cached blocks and prefill only the suffix;
  ``prefill_chunk=K`` splits long suffixes into K-token chunks fed one
  per step between decode waves, so prefill cost scales with NEW tokens
  and never monopolizes a step.
- Async two-tier KV offload (r15, on whenever a host tier exists):
  preemption swap-outs and prefix-cache spills dispatch non-blocking
  d2h (serving/offload.py; blocks ride a transient ``in_flight``
  ledger term until the step-boundary sweep lands them), queue-head
  restores prefetch h2d into staging buffers ahead of admission
  (prefetch_hit vs counted inline stall), and cold cached blocks
  spill proactively under pool pressure so reclaim never pays d2h
  inline. Greedy streams are bit-identical to the forced-sync tier
  (``kv_offload="sync"`` / FLAGS_serve_kv_offload_sync).
- Draft-model speculative decoding (optional, r13): the engine hosts a
  SECOND, smaller llama (``draft_params``/``draft_config``) whose KV
  pools ride in the same pool dict under ``dk``/``dv`` keys, indexed by
  the SAME physical block ids as the target pools — one block backs
  both models' KV for its token range, so the block ledger, the prefix
  cache's spill/restore, preemption swap and crash recovery all cover
  the draft for free. Per greedy decode wave the draft autoregressively
  proposes ``spec_tokens`` tokens per slot (the existing ``_paged_decode``
  program at draft scale), the target scores all proposals in ONE
  batched prefill-shaped verify call (``_spec_verify``: dense history
  gather + causal in-piece attention, greedy argmax at every position),
  and the host commits the longest agreeing prefix — decode cost per
  committed token approaches draft cost + 1/k of a verify, instead of
  one full target pass per token. Rejected-suffix KV (both pools) rolls
  back by the length invariant: positions >= ``lengths`` are never read
  and the next wave overwrites them. ``spec=False`` or no draft leaves
  the one-token path byte-identical.
"""
from __future__ import annotations

import dataclasses
import functools
import itertools
import math
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import observability as _obs
from ..distributed.resilience.faults import SimulatedCrash
from ..kernels.mega_decode import (mega_decode_loop, mega_decode_step,
                                   mega_supported)
from ..kernels.paged_attention import ragged_decode_partial
from ..kernels.quant_matmul import (attn_pv, attn_qk, quantize_kv,
                                    weight_only_matmul as _wo_mm)
from ..models.llama import (LlamaConfig, _apply_rope, _apply_rope_at,
                            _attention, _rms_norm, _wmat)  # noqa: F401
from ..observability import flight_recorder as _flight
from ..observability import numerics as _nm
from ..observability import perf as _perf
from ..observability import profiling as _profiling
from ..observability import request_trace as _rt
from ..observability import timeseries as _ts
from ..observability import trace_span
from ..observability.catalog import instrument as _instrument
from ..framework.flags import get_flag
from .admission import AdmissionConfig, AdmissionController, ShedError
from .kv_swap import HostKVPool
from .offload import OffloadEngine
from .prefix_cache import PrefixCache

__all__ = ["LLMEngine", "Request"]

# always-on serving telemetry (no-ops until FLAGS_obs_enabled /
# observability.enable(); names documented in observability.catalog)
_M_QUEUE_DEPTH = _instrument("serving_queue_depth")
_M_ACTIVE_SLOTS = _instrument("serving_active_slots")
_M_KV_USED = _instrument("serving_kv_pool_used_blocks")
_M_KV_BLOCKS = _instrument("serving_kv_pool_blocks")
_M_ADMISSIONS = _instrument("serving_admissions_total")
_M_PREEMPTIONS = _instrument("serving_preemptions_total")
_M_FINISHED = _instrument("serving_requests_finished_total")
_M_TOKENS = _instrument("serving_tokens_total")
_M_TTFT = _instrument("serving_ttft_seconds")
_M_TPS = _instrument("serving_tokens_per_second")
_M_STEP_SECONDS = _instrument("serving_step_seconds")
_M_PREFIX_BUCKET = _instrument("serving_decode_prefix_bucket")
_M_DECODE_RECOMPILES = _instrument("serving_decode_recompiles_total")
_M_KV_READ_BYTES = _instrument("serving_decode_kv_read_bytes")
_M_TPOT = _instrument("serving_tpot_seconds")
_M_SERVING_MFU = _instrument("serving_mfu")
_M_DEADLINE = _instrument("serving_deadline_exceeded_total")
_M_SWAP_FALLBACK = _instrument("serving_kv_swap_fallback_total")
_M_DECODE_KERNEL = _instrument("serving_decode_kernel_total")
_M_DECODE_VARIANTS = _instrument("serving_decode_variants")
_M_SPEC_PROPOSED = _instrument("serving_spec_proposed_total")
_M_SPEC_ACCEPTED = _instrument("serving_spec_accepted_total")
_M_SPEC_ACCEPT_RATE = _instrument("serving_spec_acceptance_rate")
_M_SPEC_TOKENS_PER_WAVE = _instrument("serving_spec_tokens_per_wave")
_M_CANCEL_NOOP = _instrument("serving_cancel_noop_total")
_M_MEGA_FALLBACK = _instrument("serving_mega_fallback_total")
_M_DISAGG_HANDOFFS = _instrument("serving_disagg_handoffs_total")
_M_DISAGG_SECONDS = _instrument("serving_disagg_handoff_seconds")


@dataclasses.dataclass
class Request:
    req_id: int
    prompt: List[int]
    max_new_tokens: int = 64
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    eos_token_id: Optional[int] = None
    # latency budget in seconds from add_request; past it the request is
    # evicted (queued or mid-decode), its KV blocks freed, partial tokens
    # delivered with finish reason "deadline_exceeded". None = no deadline.
    deadline_s: Optional[float] = None
    # admission-control tenant for the per-tenant token-bucket rate limit
    tenant: str = "default"
    # absolute perf_counter deadline, stamped by add_request
    t_deadline: Optional[float] = None
    # tokens generated before a preemption; a re-admission prefills
    # prompt+generated so already-streamed tokens are never re-emitted
    # (vLLM recompute semantics)
    generated: List[int] = dataclasses.field(default_factory=list)
    # disaggregated serving (r19): key of a relay-pool KV entry spilled
    # by a prefill replica. Admission restores the entry (batched h2d
    # scatter) instead of prefilling; a missing entry degrades to a full
    # prefill of the same context — streams identical either way.
    relay_key: Optional[int] = None


# ---------------------------------------------------------------------------
# device programs
# ---------------------------------------------------------------------------
def _sample_rows(logits, key, temps, top_ks, top_ps, any_sampled=True,
                 use_top_k=True, use_top_p=True):
    """Vectorized per-row sampling: every knob is a traced [N] vector, so
    one compiled program serves any mix of greedy/sampled requests.
    temps<=0 → greedy; top_k<=0 → disabled; top_p>=1 → disabled.

    The three ``*_`` flags are STATIC: they prune program branches the
    current slot mix provably doesn't need. The full-vocab ``sort`` /
    ``argsort`` behind top-k/top-p cost ~1.5 ms each per step on a v5e —
    as much as an entire 510M decode layer stack — so an all-greedy batch
    (the common serving state) must compile to a bare argmax. The engine
    derives the flags from its active requests and keeps one compiled
    decode variant per flag tuple (≤8)."""
    N, vocab = logits.shape
    greedy = jnp.argmax(logits, axis=-1)
    if not any_sampled:
        return greedy.astype(jnp.int32)
    lg = logits / jnp.maximum(temps, 1e-6)[:, None]
    if use_top_k:
        # top-k: mask below the per-row kth value (disabled rows: k=vocab)
        eff_k = jnp.where(top_ks > 0, top_ks, vocab)
        srt = jnp.sort(lg, axis=-1)                      # ascending
        kth_idx = jnp.clip(vocab - eff_k, 0, vocab - 1).astype(jnp.int32)
        kth = jnp.take_along_axis(srt, kth_idx[:, None], axis=-1)
        lg = jnp.where(lg < kth, -1e30, lg)
    if use_top_p:
        # top-p: drop tokens outside the smallest prefix with mass >= p
        sort_idx = jnp.argsort(-lg, axis=-1)
        sort_p = jnp.take_along_axis(jax.nn.softmax(lg, axis=-1), sort_idx,
                                     axis=-1)
        cum = jnp.cumsum(sort_p, axis=-1)
        eff_p = jnp.where(top_ps < 1.0, top_ps, 1.0)
        drop_sorted = cum - sort_p >= eff_p[:, None]
        drop = jnp.zeros_like(drop_sorted).at[
            jnp.arange(N)[:, None], sort_idx].set(drop_sorted)
        lg = jnp.where(drop, -1e30, lg)
    sampled = jax.random.categorical(key, lg, axis=-1)
    return jnp.where(temps > 0, sampled, greedy).astype(jnp.int32)


@functools.partial(jax.jit, donate_argnums=(0, 1, 2, 3))
def _apply_admissions(c_last, c_len, c_done, c_rem, wave_toks, slot_of_row,
                      lens_new, rems_new, upd_mask):
    """Scatter one admission wave into the decode carry — a SINGLE
    compiled program with shapes fixed at [max_slots], whatever the
    admission count (pad rows carry slot_of_row == N, dropped by the
    out-of-bounds scatter mode). The eager .at[].set chain this replaces
    re-specialized per wave size: on a remote-compile backend each new
    size cost ~1 s of compile inside the serving hot path (measured r4:
    7.2 s on the first full wave)."""
    N = c_last.shape[0]
    scattered = jnp.zeros((N,), c_last.dtype).at[slot_of_row].set(
        wave_toks.astype(c_last.dtype), mode="drop")
    c_last = jnp.where(upd_mask, scattered, c_last)
    c_len = jnp.where(upd_mask, lens_new.astype(c_len.dtype), c_len)
    c_done = jnp.where(upd_mask, False, c_done)
    c_rem = jnp.where(upd_mask, rems_new.astype(c_rem.dtype), c_rem)
    return c_last, c_len, c_done, c_rem


def _paged_prefill(params, tokens, blk_ids, true_len, pools,
                   temps, top_ks, top_ps, key, hist_len=None,
                   ctx_tbl=None, *, config: LlamaConfig,
                   sample_flags=(True, True, True), kv_int8: bool = False,
                   numerics: bool = False, prefix_nbk: int = 0,
                   kv_prefix: str = ""):
    """Prefill a WAVE of admissions in one compiled program: causal
    forward over the padded prompt batch, every layer's K/V written into
    the slots' pool blocks by ONE batched scatter, and each request's
    FIRST generated token sampled in-program.

    tokens: [B, S_bucket]; blk_ids: [B, S_bucket // bs] physical block
    ids (0 = trash block for pad rows / the pad tail); true_len: [B];
    temps/top_ks/top_ps: [B] sampling knobs; pools: the donated pool dict
    ({"k", "v"} [L, NB, bs, Hkv, D] — plus per-entry f32 scale pools
    {"ks", "vs"} [L, NB, bs, Hkv] when ``kv_int8``). Returns
    (first_tokens [B] int32, pools).

    The engine pads every multi-admission wave to ``max_slots`` rows
    (single admissions use a dedicated B=1 variant — steady-state churn
    must not pay max_slots× the prefill FLOPs) and to the largest bucket
    the wave needs, so TWO compiled variants per (bucket, flags) serve
    any admission mix — batch-size-shaped recompiles can never land
    inside a serving burst. Pad rows point all their blocks at the trash
    block and sample a discarded token.

    Sampling lives inside the compiled program because the host loop may
    sit behind a high-latency tunnel: the eager ~15-op sampling pipeline
    plus a blocking int() per admission cost more wall-clock than the
    prefill math itself (measured r3: the serving engine lost ~45% of its
    roofline to exactly this). Pad positions beyond true_len land in the
    trash block, and causality keeps them out of the true-last-token's
    context.

    Suffix/chunked prefill (``prefix_nbk > 0``, r10): the wave prefills
    only a PIECE of each row's context — tokens ``[hist_len[b],
    hist_len[b] + true_len[b])`` — against KV already resident in the
    pools (a matched prefix-cache path and/or this slot's earlier
    chunks). ``ctx_tbl`` [B, prefix_nbk] names the history's physical
    blocks (power-of-two bucketed like the decode table; pad rows point
    at the trash block and mask via ``hist_len``); the history K/V is
    gathered ONCE up front, each piece token attends to
    (masked history) + (causal within the piece), and RoPE offsets by
    ``hist_len`` per row. With ``prefix_nbk == 0`` the program is the
    original full-prompt prefill, bit for bit — cold traffic never pays
    for the feature. The compiled family stays bounded: (prompt bucket)
    x (2 batch forms) x (<= 8 flag tuples) x (log2 history buckets).

    ``kv_prefix`` (r13 speculative decoding) selects which pool entries
    this program reads/writes: ``""`` = the target model's ``k``/``v``
    (plus ``ks``/``vs`` under int8), ``"d"`` = the draft model's
    ``dk``/``dv``. The draft prefill is the SAME program over the draft
    params/config, dispatched right after the target's so both models'
    KV cover every prefilled position (the draft's sampled token is
    discarded — the target samples the stream).
    """
    c = config
    dt = c.dtype
    pk, pv = kv_prefix + "k", kv_prefix + "v"
    pks, pvs = kv_prefix + "ks", kv_prefix + "vs"
    B, S = tokens.shape
    bs = pools[pk].shape[2]
    nb = S // bs
    x = params["embed"].astype(dt)[tokens]
    freq = c.rope_theta ** (-jnp.arange(0, c.head_dim, 2, jnp.float32)
                            / c.head_dim)
    if prefix_nbk:
        Lc, Hkv, D = c.num_layers, c.num_kv_heads, c.head_dim
        G = c.num_heads // c.num_kv_heads
        Pp = prefix_nbk * bs
        scale = 1.0 / math.sqrt(D)
        # per-row absolute positions: row b's piece starts hist_len[b]
        # tokens into its sequence
        pos = (hist_len.astype(jnp.float32)[:, None]
               + jnp.arange(S, dtype=jnp.float32)[None, :])
        ang = pos[:, :, None] * freq[None, None, :]        # [B, S, D/2]
        cos, sin = jnp.cos(ang), jnp.sin(ang)
        # one dense gather of every row's history (the decode hoist,
        # applied to prefill); int8 pools dequantize here — prefill is
        # compute-bound, the simple form wins over fused-scale dots
        kpre = pools[pk][:, ctx_tbl].reshape(Lc, B, Pp, Hkv, D)
        vpre = pools[pv][:, ctx_tbl].reshape(Lc, B, Pp, Hkv, D)
        if kv_int8:
            ksc = pools[pks][:, ctx_tbl].reshape(Lc, B, Pp, Hkv)
            vsc = pools[pvs][:, ctx_tbl].reshape(Lc, B, Pp, Hkv)
            kpre = kpre.astype(dt) * ksc[..., None].astype(dt)
            vpre = vpre.astype(dt) * vsc[..., None].astype(dt)
        # [B,1,1,1,Pp] over scores [B,Hkv,G,S,Pp]
        pre_mask = (jnp.arange(Pp)[None, :]
                    < hist_len[:, None])[:, None, None, None, :]
        in_mask = jnp.tril(jnp.ones((S, S), bool))[None, None, None]
    else:
        pos = jnp.arange(S, dtype=jnp.float32)
        ang = pos[:, None] * freq[None, :]
        cos, sin = jnp.cos(ang), jnp.sin(ang)

    k_all, v_all = [], []
    for l in range(c.num_layers):
        p = jax.tree_util.tree_map(lambda a: a[l], params["layers"])
        hn = _rms_norm(x, p["attn_norm"], c.rms_eps)
        q = _wo_mm(hn, p["wq"], dt).reshape(B, S, c.num_heads, c.head_dim)
        k = _wo_mm(hn, p["wk"], dt).reshape(B, S, c.num_kv_heads,
                                            c.head_dim)
        v = _wo_mm(hn, p["wv"], dt).reshape(B, S, c.num_kv_heads,
                                            c.head_dim)
        if prefix_nbk:
            q = _apply_rope_at(q, cos, sin)
            k = _apply_rope_at(k, cos, sin)
        else:
            q = _apply_rope(q, cos, sin)
            k = _apply_rope(k, cos, sin)
        k_all.append(k)
        v_all.append(v)
        if prefix_nbk:
            # piece attention: softmax over [history ; causal in-piece],
            # the decode program's concat structure at prefill width —
            # masked history positions contribute an exact 0.0
            qg = q.reshape(B, S, Hkv, G, D)
            s_pre = jnp.einsum("bshgd,bphd->bhgsp", qg, kpre[l],
                               preferred_element_type=jnp.float32) * scale
            s_in = jnp.einsum("bshgd,bthd->bhgst", qg, k,
                              preferred_element_type=jnp.float32) * scale
            s_pre = jnp.where(pre_mask, s_pre, -1e30)
            s_in = jnp.where(in_mask, s_in, -1e30)
            probs = jax.nn.softmax(
                jnp.concatenate([s_pre, s_in], axis=-1), axis=-1)
            att = (jnp.einsum("bhgsp,bphd->bshgd",
                              probs[..., :Pp].astype(dt), vpre[l])
                   + jnp.einsum("bhgst,bthd->bshgd",
                                probs[..., Pp:].astype(dt), v))
            att = att.reshape(B, S, c.num_heads * c.head_dim).astype(dt)
        else:
            # plain causal GQA attention — the model's own core
            # (llama._attention)
            att = _attention(q, k, v, c).reshape(B, S,
                                                 c.num_heads * c.head_dim)
        x = x + _wo_mm(att, p["wo"], dt)
        hn = _rms_norm(x, p["mlp_norm"], c.rms_eps)
        gate = jax.nn.silu(_wo_mm(hn, p["w_gate"], dt))
        x = x + _wo_mm(gate * _wo_mm(hn, p["w_up"], dt), p["w_down"], dt)

    # hoisted writeback: all layers' K/V in ONE scatter per pool (the
    # per-layer Pallas/XLA block appends cost ~0.6 ms of launch overhead
    # each — 2L calls/prefill dwarfed the prefill math itself)
    L = c.num_layers
    flat = blk_ids.reshape(B * nb)
    k_stack = jnp.stack(k_all).reshape(L, B * nb, bs, c.num_kv_heads,
                                       c.head_dim)
    v_stack = jnp.stack(v_all).reshape(L, B * nb, bs, c.num_kv_heads,
                                       c.head_dim)
    pools = dict(pools)
    if kv_int8:
        qk, sk = quantize_kv(k_stack)
        qv, sv = quantize_kv(v_stack)
        if numerics:
            # paired pre/post-quant probe for the int8-KV site: one tiny
            # fused reduction over this wave's K/V, shipped async — the
            # numerics_quant_error{site="kv_int8"} error budget
            _nm.record_quant_error("kv_int8", [(k_stack, qk, sk, -1),
                                               (v_stack, qv, sv, -1)])
        pools[pk] = pools[pk].at[:, flat].set(qk)
        pools[pv] = pools[pv].at[:, flat].set(qv)
        pools[pks] = pools[pks].at[:, flat].set(sk)
        pools[pvs] = pools[pvs].at[:, flat].set(sv)
    else:
        pools[pk] = pools[pk].at[:, flat].set(k_stack)
        pools[pv] = pools[pv].at[:, flat].set(v_stack)

    x = _rms_norm(x, params["final_norm"], c.rms_eps)
    last_h = x[jnp.arange(B), jnp.maximum(true_len - 1, 0)]
    if c.tie_embeddings:
        logits = (last_h @ params["embed"].astype(dt).T).astype(jnp.float32)
    else:
        logits = _wo_mm(last_h, params["lm_head"], dt).astype(jnp.float32)
    toks = _sample_rows(logits, key, temps, top_ks, top_ps, *sample_flags)
    return toks, pools


def _paged_decode(params, last_tokens, lengths, done0, budgets, key, active,
                  block_table, pools, temps, top_ks, top_ps,
                  eos_ids, *, config: LlamaConfig, n_steps: int,
                  sample_flags=(True, True, True), kv_int8: bool = False,
                  numerics: bool = False, ragged: bool = False,
                  mega: bool = False, mega_multistep: bool = False,
                  kv_prefix: str = "", mesh=None):
    """``n_steps`` decode iterations in ONE compiled program (multi-step
    scheduling): the host loop syncs once per call instead of once per
    token — through a remote-attached chip the per-step d2h round-trip
    costs ~10x the decode math itself. Slots that hit their eos or budget
    mid-scan flip to done (their ring entries are masked and never written
    back; their emitted entries read -1).

    Hoisted-dense structure (r4; the per-step Pallas paged-append +
    paged-attention variant measured ~0.6 ms of launch overhead per call
    × 24 calls/step — 4-5× the decode math): the slot prefixes are frozen
    for the whole call, so the pools are GATHERED ONCE into dense
    [L, N, P, Hkv, D] arrays up front, the scan body runs pure fused XLA
    (dense GQA attention over prefix + an in-call ring buffer written at
    the uniform step index — no scatter), and the ring is written back to
    the pools in ONE batched scatter at call end. Zero kernel launches
    inside the scan; per-step cost matches the fixed-batch fused loop.

    Ragged prefix bucketing (r6): ``block_table`` arrives SLICED to the
    engine-chosen bucket [N, MB_bucket], so P = MB_bucket * bs covers only
    ``max(lengths) + n_steps`` (rounded to a power-of-two block count) —
    the gather, the scores, and the PV contraction all scale with the
    ACTUAL ragged horizon instead of max_model_len. Exactness: every
    position >= a slot's length was masked to -1e30 before the softmax,
    so dropping it changes nothing (exp underflows to exactly 0.0).

    int8 KV pools (``kv_int8``): the gathered prefix stays int8 through
    the QK/PV contractions with per-entry scales applied to the f32
    scores resp. folded into the probabilities (kernels/quant_matmul) —
    half the gather/attention KV bytes. The in-call ring stays model
    dtype and is quantized once at writeback.

    Ragged Pallas path (``ragged``, r12 — the default on TPU): no dense
    hoist at all. ``block_table`` arrives at FULL width [N, mb] (one
    static shape forever) and ``lengths`` is a runtime operand: each
    step, each layer calls kernels/paged_attention.ragged_decode_partial,
    whose per-slot program walks the slot's block table at its TRUE
    length (blocks past ``ceil(len/bs)`` are never visited — the walk's
    trip count ends there: no DMA, no FLOPs) with an online softmax,
    streaming int8 blocks unconverted
    and dequantizing in-register. The kernel's partial state (acc, m, l)
    merges with the in-call ring's scores via the flash-decoding combine
    — mathematically the same softmax over [prefix ; ring], computed
    blockwise. Consequences: the compile cache loses its prefix-bucket
    axis entirely (ONE variant per sampling-flag set), per-step KV reads
    scale with the tokens actually resident, and inactive / mid-chunk
    slots walk zero blocks (their lengths are zeroed going in). The
    writeback scatter and kv_int8 numerics probes are shared with the
    bucketed path verbatim. Under a tp ``mesh`` the kernel call is
    shard_mapped over the KV heads (r19): every shard walks the same
    tables against its head slice of the pools — bit-identical partials,
    no cross-shard collective inside the walk.

    The (last, lengths, done, budgets, key) quintet is a device-resident
    carry: the engine feeds each call the previous call's outputs
    untouched while the slot composition is unchanged, so steady-state
    decode performs no h2d transfers at all. ``done`` PERSISTS across
    calls — that is what makes it safe for the engine to dispatch call
    k+1 before reading call k's tokens (speculative chaining): a slot
    that finished mid-call-k stays done in call k+1 and emits -1 padding
    instead of garbage. Call k+1's prefix gather reads call k's pool
    writeback through the donated-pool data dependency.

    eos_ids: [N] (-1 = no eos); budgets: [N] tokens each slot may still
    emit. Returns (emitted [n_steps, N] int32 with -1 padding, last,
    lengths, done, budgets, key, pools).

    ``kv_prefix`` (r13): ``"d"`` runs this program as the speculative
    DRAFT proposal loop — draft params/config, greedy flags, the draft's
    ``dk``/``dv`` pool entries — reusing the identical ragged/bucketed
    machinery at draft scale. Target pool entries pass through the
    donated dict untouched.

    Mega path (``mega``, r18): the whole layer stack of each step runs
    as ONE persistent Pallas launch (kernels/mega_decode) — the r12
    block walk, the per-layer ring write and the FFN fused, weights
    streamed in tiles — so a decode step costs one kernel launch instead
    of L, and the hidden state never round-trips HBM between layers. The
    scan, the sampling epilogue and the end-of-call ring->pool scatter
    below are SHARED with the ragged path verbatim: that is the greedy
    stream-parity contract, and it keeps the variant cache at ONE entry
    per sampling-flag set. ``mega_multistep`` (greedy draft waves only)
    additionally hoists the scan itself into the kernel: the draft's k
    sequential steps — lm_head argmax, embed gather, done/budget
    bookkeeping included — become one persistent launch instead of k.
    """
    c = config
    dt = c.dtype
    pk, pv = kv_prefix + "k", kv_prefix + "v"
    pks, pvs = kv_prefix + "ks", kv_prefix + "vs"
    Lc = c.num_layers
    N, MB = block_table.shape
    k_pool, v_pool = pools[pk], pools[pv]
    bs = k_pool.shape[2]
    Hkv, D = k_pool.shape[3], k_pool.shape[4]
    G = c.num_heads // c.num_kv_heads
    P = MB * bs
    S = n_steps
    lens0 = lengths                       # frozen prefix lengths
    scale = 1.0 / math.sqrt(D)

    if ragged or mega:
        # true-length walk: no gather, no mask — the kernel reads only
        # real blocks. Slots outside the decode set (inactive or
        # mid-chunked-prefill) walk zero blocks.
        walk_lens = jnp.where(active, lens0.astype(jnp.int32), 0)
    else:
        # ---- hoist: one dense gather of every slot's (frozen) prefix ----
        # (int8 pools: the dense arrays stay int8 — half the bytes moved)
        kd = k_pool[:, block_table].reshape(Lc, N, P, Hkv, D)
        vd = v_pool[:, block_table].reshape(Lc, N, P, Hkv, D)
        if kv_int8:
            ksc = pools[pks][:, block_table].reshape(Lc, N, P, Hkv)
            vsc = pools[pvs][:, block_table].reshape(Lc, N, P, Hkv)
        pre_mask = (jnp.arange(P)[None, :]
                    < lens0[:, None])[:, None, None, :]   # [N,1,1,P]

    freq = c.rope_theta ** (-jnp.arange(0, c.head_dim, 2, jnp.float32)
                            / c.head_dim)

    def rope1(t, ang):                    # t: [N, H, D]; ang: [N, D/2]
        d2 = t.shape[-1] // 2
        t1, t2 = t[..., :d2], t[..., d2:]
        cc = jnp.cos(ang)[:, None, :].astype(t.dtype)
        ss = jnp.sin(ang)[:, None, :].astype(t.dtype)
        return jnp.concatenate([t1 * cc - t2 * ss, t2 * cc + t1 * ss], -1)

    # hoist the dense head operand (incl. its dtype convert) out of the
    # scan — XLA does not lift the loop-invariant [hidden, vocab] astype
    # out of the body on its own. An int8 weight-only lm_head has nothing
    # to hoist: it contracts unconverted in-body (weight_only_matmul).
    if c.tie_embeddings:
        head_w = params["embed"].astype(dt).T
    elif not isinstance(params["lm_head"], dict):
        head_w = params["lm_head"].astype(dt)
    else:
        head_w = None

    def body(carry, t):
        last, lens, done, rem, rk, rv, k = carry
        k, sub = jax.random.split(k)
        act = active & ~done
        if mega:
            # one persistent launch replaces the whole per-layer loop;
            # the sampling epilogue below stays shared with ragged
            xh, rk, rv = mega_decode_step(
                params, c, x0=params["embed"].astype(dt)[last], t=t,
                block_table=block_table, walk_lens=walk_lens, lens=lens,
                ring_k=rk, ring_v=rv, k_pool=pools[pk], v_pool=pools[pv],
                ks_pool=pools.get(pks), vs_pool=pools.get(pvs))
            x = xh[:, None]
        else:
            x = params["embed"].astype(dt)[last][:, None]   # [N, 1, h]
            ang = lens.astype(jnp.float32)[:, None] * freq[None, :]
            ring_mask = (jnp.arange(S) <= t)[None, None, None, :]
            for l in range(Lc):
                p = jax.tree_util.tree_map(lambda a: a[l],
                                           params["layers"])
                hn = _rms_norm(x, p["attn_norm"], c.rms_eps)
                q = _wo_mm(hn[:, 0], p["wq"], dt).reshape(N, Hkv * G, D)
                kk = _wo_mm(hn[:, 0], p["wk"], dt).reshape(N, Hkv, D)
                vv = _wo_mm(hn[:, 0], p["wv"], dt).reshape(N, Hkv, D)
                q, kk = rope1(q, ang), rope1(kk, ang)
                # uniform step index: dynamic_update_slice, no scatter
                rk = jax.lax.dynamic_update_slice(
                    rk, kk[None, :, None], (l, 0, t, 0, 0))
                rv = jax.lax.dynamic_update_slice(
                    rv, vv[None, :, None], (l, 0, t, 0, 0))
                qg = q.reshape(N, Hkv, G, D)
                s_rng = jnp.einsum(
                    "nhgd,nshd->nhgs", qg, rk[l],
                    preferred_element_type=jnp.float32) * scale
                s_rng = jnp.where(ring_mask, s_rng, -1e30)
                if ragged:
                    # flash-decoding combine: the kernel's online-softmax
                    # partials over the pool prefix merge with the
                    # in-call ring's scores — one softmax over
                    # [prefix ; ring], computed blockwise (exact up to
                    # f32 rounding). The ring always holds >= 1 live
                    # position, so l_tot >= 1.
                    acc_p, m_p, l_p = ragged_decode_partial(
                        q, pools[pk], pools[pv], block_table, walk_lens,
                        layer=l, ks_pool=pools.get(pks),
                        vs_pool=pools.get(pvs), mesh=mesh)
                    m_tot = jnp.maximum(m_p, jnp.max(s_rng, axis=-1))
                    corr = jnp.exp(m_p - m_tot)
                    p_rng = jnp.exp(s_rng - m_tot[..., None])
                    l_tot = l_p * corr + jnp.sum(p_rng, axis=-1)
                    acc_tot = (acc_p * corr[..., None]
                               + jnp.einsum(
                                   "nhgs,nshd->nhgd", p_rng, rv[l],
                                   preferred_element_type=jnp.float32))
                    att = acc_tot / l_tot[..., None]
                else:
                    s_pre = attn_qk(qg, kd[l],
                                    ksc[l] if kv_int8 else None) * scale
                    s_pre = jnp.where(pre_mask, s_pre, -1e30)
                    probs = jax.nn.softmax(
                        jnp.concatenate([s_pre, s_rng], axis=-1), axis=-1)
                    p_rng = probs[..., P:].astype(dt)
                    att = (attn_pv(probs[..., :P], vd[l],
                                   vsc[l] if kv_int8 else None,
                                   out_dtype=dt)
                           + jnp.einsum("nhgs,nshd->nhgd", p_rng, rv[l]))
                att = att.reshape(N, 1, Hkv * G * D).astype(dt)
                x = x + _wo_mm(att, p["wo"], dt)
                hn = _rms_norm(x, p["mlp_norm"], c.rms_eps)
                gate = jax.nn.silu(_wo_mm(hn, p["w_gate"], dt))
                x = x + _wo_mm(gate * _wo_mm(hn, p["w_up"], dt),
                               p["w_down"], dt)

        xf = _rms_norm(x, params["final_norm"], c.rms_eps)
        if head_w is not None:
            logits = (xf[:, 0] @ head_w).astype(jnp.float32)
        else:
            logits = _wo_mm(xf[:, 0], params["lm_head"],
                            dt).astype(jnp.float32)
        nxt = _sample_rows(logits, sub, temps, top_ks, top_ps,
                           *sample_flags)
        emitted = jnp.where(act, nxt, -1)
        lens = lens + act.astype(lens.dtype)
        rem = rem - act.astype(rem.dtype)
        done = done | (act & (eos_ids >= 0) & (nxt == eos_ids)) \
            | (act & (rem <= 0))
        last = jnp.where(act, nxt, last)
        return (last, lens, done, rem, rk, rv, k), emitted

    ring_k = jnp.zeros((Lc, N, S, Hkv, D), dt)
    ring_v = jnp.zeros((Lc, N, S, Hkv, D), dt)
    if mega and mega_multistep:
        # draft fusion: the scan itself lives in the kernel — S greedy
        # steps, argmax + embed gather + bookkeeping included, in ONE
        # persistent launch. ``done0`` must be all-false (the spec
        # wave's contract) and the PRNG key rides through untouched.
        assert sample_flags == (False, False, False), \
            "mega_multistep is greedy-only"
        (emitted, last_tokens, lens_end, done0, budgets, ring_k,
         ring_v) = mega_decode_loop(
            params, c, x0=params["embed"].astype(dt)[last_tokens],
            n_steps=S, block_table=block_table, walk_lens=walk_lens,
            lens=lengths, active=active, last0=last_tokens,
            budgets=budgets, eos_ids=eos_ids, ring_k=ring_k,
            ring_v=ring_v, k_pool=pools[pk], v_pool=pools[pv])
    else:
        init = (last_tokens, lengths, done0, budgets, ring_k, ring_v,
                key)
        (last_tokens, lens_end, done0, budgets, ring_k, ring_v, key), \
            emitted = jax.lax.scan(body, init, jnp.arange(S))

    # ---- writeback: the ring's valid entries → pools, one scatter -------
    cnt = lens_end - lens0                                # [N]
    j = jnp.arange(S)[None, :]
    valid = (j < cnt[:, None]) & active[:, None]          # [N, S]
    pos = jnp.minimum(lens0[:, None] + j, P - 1)
    log_blk = pos // bs
    phys = jnp.take_along_axis(block_table, log_blk, axis=1)
    phys = jnp.where(valid, phys, 0)                      # trash block 0
    off = pos % bs
    pools = dict(pools)
    if kv_int8:
        rq_k, rs_k = quantize_kv(ring_k)
        rq_v, rs_v = quantize_kv(ring_v)
        if numerics:
            # decode-writeback rung of the kv_int8 error budget (the
            # ring is small — the reduction is noise next to the scan)
            _nm.record_quant_error("kv_int8", [(ring_k, rq_k, rs_k, -1),
                                               (ring_v, rq_v, rs_v, -1)])
        pools[pk] = pools[pk].at[:, phys, off].set(rq_k)
        pools[pv] = pools[pv].at[:, phys, off].set(rq_v)
        pools[pks] = pools[pks].at[:, phys, off].set(rs_k)
        pools[pvs] = pools[pvs].at[:, phys, off].set(rs_v)
    else:
        pools[pk] = pools[pk].at[:, phys, off].set(ring_k)
        pools[pv] = pools[pv].at[:, phys, off].set(ring_v)
    return (emitted, last_tokens, lens_end, done0, budgets, key, pools)


def _spec_verify(params, block_table, last, draft_toks, lengths, active,
                 pools, *, config: LlamaConfig, n_spec: int,
                 kv_int8: bool = False, numerics: bool = False,
                 max_model_len: int = 0):
    """Score a speculative wave in ONE target forward: for every slot the
    piece ``[last, d_1 .. d_k]`` (k = ``n_spec``) runs a prefill-shaped
    pass against the slot's resident KV — the chunked-prefill program's
    structure (dense history gather over the power-of-two ``block_table``
    bucket, per-row RoPE offsets at ``lengths``, softmax over
    [masked history ; causal in-piece]) at the fixed piece width k+1 —
    and returns the target's GREEDY token at ALL k+1 positions:
    ``out[b, j]`` is what the target would emit after consuming piece
    token j. The host accepts the longest prefix where the draft agreed
    (MPK's collapse-many-small-launches argument: k draft steps verify
    in one launch whose arithmetic intensity is prefill's, not
    decode's).

    Writeback is decode-shaped, not prefill-shaped: pieces start at
    ``lengths[b]``, which is NOT block-aligned mid-decode, so each
    position scatters individually via its (physical block, offset)
    pair. ALL k+1 positions write — a later host commit of c <= k
    tokens simply leaves positions >= lengths+c stale, which the length
    invariant makes unreadable and the next wave overwrites (that IS
    the rejected-suffix rollback). Inactive rows and positions past
    ``max_model_len`` divert to trash block 0.

    draft_toks: [k, N] (the draft call's emitted grid, fed back without
    a host round-trip); returns (greedy [N, k+1] int32, pools).
    """
    c = config
    dt = c.dtype
    N, nbk = block_table.shape
    S = n_spec + 1
    bs = pools["k"].shape[2]
    Lc, Hkv, D = c.num_layers, c.num_kv_heads, c.head_dim
    G = c.num_heads // c.num_kv_heads
    Pp = nbk * bs
    scale = 1.0 / math.sqrt(D)

    tokens = jnp.concatenate(
        [last[:, None], draft_toks.T.astype(jnp.int32)], axis=1)  # [N, S]
    tokens = jnp.clip(tokens, 0, c.vocab_size - 1)   # -1 pads embed-safe
    hist = jnp.where(active, lengths.astype(jnp.int32), 0)

    x = params["embed"].astype(dt)[tokens]
    freq = c.rope_theta ** (-jnp.arange(0, c.head_dim, 2, jnp.float32)
                            / c.head_dim)
    pos = (hist.astype(jnp.float32)[:, None]
           + jnp.arange(S, dtype=jnp.float32)[None, :])
    ang = pos[:, :, None] * freq[None, None, :]       # [N, S, D/2]
    cos, sin = jnp.cos(ang), jnp.sin(ang)
    pre_mask = (jnp.arange(Pp)[None, :]
                < hist[:, None])[:, None, None, None, :]
    in_mask = jnp.tril(jnp.ones((S, S), bool))[None, None, None]

    k_all, v_all = [], []
    for l in range(Lc):
        p = jax.tree_util.tree_map(lambda a: a[l], params["layers"])
        hn = _rms_norm(x, p["attn_norm"], c.rms_eps)
        q = _wo_mm(hn, p["wq"], dt).reshape(N, S, c.num_heads, D)
        k = _wo_mm(hn, p["wk"], dt).reshape(N, S, Hkv, D)
        v = _wo_mm(hn, p["wv"], dt).reshape(N, S, Hkv, D)
        q = _apply_rope_at(q, cos, sin)
        k = _apply_rope_at(k, cos, sin)
        k_all.append(k)
        v_all.append(v)
        # the prefill piece attention verbatim: int8 history dequantizes
        # up front (verify is prefill-shaped — compute-bound, the simple
        # form wins over fused-scale dots)
        kpre = pools["k"][l][block_table].reshape(N, Pp, Hkv, D)
        vpre = pools["v"][l][block_table].reshape(N, Pp, Hkv, D)
        if kv_int8:
            ksc = pools["ks"][l][block_table].reshape(N, Pp, Hkv)
            vsc = pools["vs"][l][block_table].reshape(N, Pp, Hkv)
            kpre = kpre.astype(dt) * ksc[..., None].astype(dt)
            vpre = vpre.astype(dt) * vsc[..., None].astype(dt)
        qg = q.reshape(N, S, Hkv, G, D)
        s_pre = jnp.einsum("bshgd,bphd->bhgsp", qg, kpre,
                           preferred_element_type=jnp.float32) * scale
        if kv_int8:
            # in-piece K/V BELOW the diagonal must read as the
            # step-wise decode path would read them: from the pool,
            # int8-quantized. Round-trip the piece through quantize_kv
            # (the exact writeback transform) for t < s; the diagonal
            # (each position's own K/V — the decode ring) stays raw.
            # Without this, verify attends unquantized neighbors and
            # the ~1% quant delta can flip near-tie argmaxes vs the
            # non-speculative stream.
            qk_p, sk_p = quantize_kv(k)
            qv_p, sv_p = quantize_kv(v)
            k_rt = qk_p.astype(dt) * sk_p[..., None].astype(dt)
            v_rt = qv_p.astype(dt) * sv_p[..., None].astype(dt)
        else:
            k_rt, v_rt = k, v
        s_in = jnp.einsum("bshgd,bthd->bhgst", qg, k_rt,
                          preferred_element_type=jnp.float32) * scale
        if kv_int8:
            eye = jnp.eye(S, dtype=bool)[None, None, None]
            s_diag = jnp.einsum("bshgd,bshd->bhgs", qg, k,
                                preferred_element_type=jnp.float32) \
                * scale
            s_in = jnp.where(eye, s_diag[..., None], s_in)
        s_pre = jnp.where(pre_mask, s_pre, -1e30)
        s_in = jnp.where(in_mask, s_in, -1e30)
        probs = jax.nn.softmax(
            jnp.concatenate([s_pre, s_in], axis=-1), axis=-1)
        p_in = probs[..., Pp:].astype(dt)
        if kv_int8:
            eye_f = jnp.eye(S, dtype=p_in.dtype)[None, None, None]
            att_in = (jnp.einsum("bhgst,bthd->bshgd",
                                 p_in * (1 - eye_f), v_rt)
                      + jnp.einsum("bhgs,bshd->bshgd",
                                   jnp.sum(p_in * eye_f, -1), v))
        else:
            att_in = jnp.einsum("bhgst,bthd->bshgd", p_in, v)
        att = jnp.einsum("bhgsp,bphd->bshgd",
                         probs[..., :Pp].astype(dt), vpre) + att_in
        att = att.reshape(N, S, c.num_heads * D).astype(dt)
        x = x + _wo_mm(att, p["wo"], dt)
        hn = _rms_norm(x, p["mlp_norm"], c.rms_eps)
        gate = jax.nn.silu(_wo_mm(hn, p["w_gate"], dt))
        x = x + _wo_mm(gate * _wo_mm(hn, p["w_up"], dt), p["w_down"], dt)

    # positional writeback (the decode ring's scatter at piece width):
    # invalid lanes — inactive rows, positions past max_model_len —
    # divert to the trash block
    j = jnp.arange(S)[None, :]
    wpos = hist[:, None] + j                              # [N, S]
    valid = active[:, None] & (wpos < max_model_len)
    wposc = jnp.minimum(wpos, max_model_len - 1)
    log_blk = jnp.minimum(wposc // bs, nbk - 1)
    phys = jnp.take_along_axis(block_table, log_blk, axis=1)
    phys = jnp.where(valid, phys, 0)
    off = wposc % bs
    k_stack = jnp.stack(k_all)                            # [L, N, S, Hkv, D]
    v_stack = jnp.stack(v_all)
    pools = dict(pools)
    if kv_int8:
        qk, sk = quantize_kv(k_stack)
        qv, sv = quantize_kv(v_stack)
        if numerics:
            # verify-writeback rung of the kv_int8 error budget
            _nm.record_quant_error("kv_int8", [(k_stack, qk, sk, -1),
                                               (v_stack, qv, sv, -1)])
        pools["k"] = pools["k"].at[:, phys, off].set(qk)
        pools["v"] = pools["v"].at[:, phys, off].set(qv)
        pools["ks"] = pools["ks"].at[:, phys, off].set(sk)
        pools["vs"] = pools["vs"].at[:, phys, off].set(sv)
    else:
        pools["k"] = pools["k"].at[:, phys, off].set(k_stack)
        pools["v"] = pools["v"].at[:, phys, off].set(v_stack)

    x = _rms_norm(x, params["final_norm"], c.rms_eps)
    if c.tie_embeddings:
        logits = (x @ params["embed"].astype(dt).T).astype(jnp.float32)
    else:
        logits = _wo_mm(x, params["lm_head"], dt).astype(jnp.float32)
    return jnp.argmax(logits, axis=-1).astype(jnp.int32), pools


# ---------------------------------------------------------------------------
# host engine
# ---------------------------------------------------------------------------
class LLMEngine:
    """Continuous-batching serving loop.

    >>> eng = LLMEngine(params, config, max_slots=4)
    >>> eng.add_request([1, 2, 3], max_new_tokens=32)
    >>> outputs = eng.run()          # {req_id: [generated tokens...]}

    ``step()`` advances one decode step (admitting queued requests first)
    and returns the (req_id, token) pairs emitted — the streaming hook.
    """

    def __init__(self, params, config: LlamaConfig, max_slots: int = 4,
                 block_size: int = 16, max_model_len: int = 512,
                 num_blocks: Optional[int] = None,
                 prompt_buckets: Optional[List[int]] = None, seed: int = 0,
                 mesh=None, decode_steps: int = 1, kv_dtype=None,
                 admission=None, kv_swap_bytes: int = 0, injector=None,
                 prefix_cache: bool = False, prefill_chunk: int = 0,
                 prefix_cache_host_bytes: int = 0,
                 decode_kernel: str = "auto",
                 draft_params=None, draft_config: Optional[LlamaConfig]
                 = None, spec_tokens: int = 4, spec: bool = True,
                 kv_offload: str = "auto", role: str = "both",
                 relay: Optional[HostKVPool] = None):
        """``params`` may be dense (bf16/f32) or int8 weight-only
        (llama.quantize_params) — quantized leaves feed the decode/prefill
        matmuls unconverted (kernels/quant_matmul.weight_only_matmul).

        ``mesh``: an optional jax Mesh with a 'tp' axis — weights take
        the model's Megatron shardings (llama.make_serving_shardings;
        int8 qweights + scales shard with the same specs as their dense
        counterparts), the KV pools shard their kv-head dim over 'tp',
        and GSPMD inserts the serving collectives (the reference's
        multi-GPU serving via mp_degree). The ragged decode kernel
        shard_maps its block walk over the sharded KV heads (r19), and
        spec decode composes by running the DRAFT replicated (params
        and dk/dv pools carry P()) while the verify rides the sharded
        prefill-shaped program — greedy streams stay bit-identical to
        the unsharded engine's across every path.

        ``decode_steps``: decode iterations fused into one compiled call
        (multi-step scheduling). 1 = a host sync per token (exact
        admission granularity); 8-16 amortizes the host/tunnel round-trip
        ~an order of magnitude on remote-attached chips — admission and
        slot reclamation then happen every K tokens.

        ``kv_dtype``: ``None`` keeps the pools in the model dtype;
        ``"int8"`` quantizes them with per-entry scales (dequant fused
        into the bucketed attention contractions) — half the decode KV
        traffic and double the effective block capacity at the same HBM.

        ``admission``: an :class:`AdmissionConfig` (or a prebuilt
        :class:`AdmissionController`) enabling load shedding —
        ``add_request`` raises :class:`ShedError` (typed: queue_full /
        rate_limited / pool_pressure) instead of queueing unboundedly
        under sustained overload. ``None`` admits everything.

        ``kv_swap_bytes``: capacity of the pinned host-RAM KV swap tier
        (:mod:`paddle_tpu.serving.kv_swap`). Non-zero turns preemption
        from recompute into swap: the victim's pool blocks move to host
        memory and re-admission restores them bit-exactly with one h2d
        copy instead of a full re-prefill; recompute remains the
        fallback when the host pool is full. 0 keeps pure recompute.

        ``injector``: a resilience ``FaultInjector`` whose serving kinds
        (``readback_fail`` / ``slow_step`` / ``pool_squeeze``, keyed by
        engine step index) fire inside the step loop — the seeded chaos
        surface behind ``tools/chaos_run.py --serving`` and
        :class:`~paddle_tpu.serving.resilient.ResilientEngine`.

        ``prefix_cache``: a refcounted radix index over the block pool
        (:mod:`paddle_tpu.serving.prefix_cache`) — ``add_request``
        matches the longest cached prefix at block granularity, pins
        those blocks into the slot's table, and prefills ONLY the
        suffix. Cached blocks are LRU-evicted at refcount 0 under pool
        pressure, spilling to a pinned host tier of
        ``prefix_cache_host_bytes`` (0 = drop instead of spill) and
        restoring on a later match.

        ``prefill_chunk``: split suffix prefills longer than this many
        tokens into fixed-size chunks (rounded up to a block-size
        multiple), one chunk per engine step, interleaved with the
        decode waves of the other slots — a long prefill stops
        monopolizing a step, so TTFT stays bounded under mixed traffic.
        0 = one-shot suffix prefill (the pre-r10 behavior).

        ``decode_kernel``: which decode attention path serves the slots
        (r12). ``"ragged"`` — the Pallas true-length block-walk kernel
        (kernels/paged_attention.ragged_decode_partial): lengths become
        a runtime operand, the block table ships at full width, and the
        decode compile cache collapses to ONE variant per (batch,
        sampling-flags) set. ``"bucketed"`` — the r6 host-side
        power-of-two prefix buckets over the hoisted dense gather.
        ``"auto"`` (default) picks ragged on a TPU backend — sharded or
        not — and bucketed elsewhere (off-TPU the kernel would run in
        the Pallas interpreter — correct but slow); the choice is
        counted per dispatch in
        ``serving_decode_kernel_total{path}``, never silent. The
        supported mesh matrix (r19): ragged and bucketed both compose
        with a 'tp' mesh (ragged shard_maps the block walk over the KV
        heads; bucketed shards through its plain gathers/dots), spec
        decode runs its draft replicated under the mesh, and ``"mega"``
        alone bows out — a tp mesh falls back counted
        (``serving_mega_fallback_total{reason="mesh"}``) to ragged on
        TPU / bucketed off it, never raising.
        Both paths share admission, writeback, preemption, the prefix
        cache, chunked prefill, swap and the numerics probes; greedy
        token streams are parity-tested identical.

        ``draft_params`` / ``draft_config``: a second, smaller llama —
        the speculative DRAFT (r13). Greedy decode waves then run
        draft-then-verify: the draft proposes ``spec_tokens`` tokens per
        slot (one multi-step draft call), the target verifies all of
        them in one prefill-shaped batched call, and the longest
        agreeing prefix commits — up to ``spec_tokens`` tokens per
        target forward, token streams EXACTLY the non-speculative
        greedy streams. The draft must share the target's vocabulary;
        its KV pools ride in the same pool dict (``dk``/``dv``) over
        the same physical blocks, so the ledger, prefix cache, swap
        tier and crash recovery need no draft-aware changes. Waves with
        any sampled (temperature>0) slot, or slots whose draft KV fell
        behind, fall back to the normal decode path — never wrong,
        at worst unaccelerated. ``spec=False`` disables the machinery
        entirely (no draft pools, byte-identical engine).

        Pipelining caveat: the engine dispatches call k+1 before reading
        call k's tokens only when every in-flight slot is GUARANTEED
        alive through call k (``_spec_safe``) — which requires
        ``eos_token_id`` unset, since an eos can finish a slot at any
        step. Workloads where every request carries an eos run with a
        synchronous readback between decode calls instead;
        ``decode_steps`` remains the amortization lever there.
        Speculative waves are the exception either way: acceptance is a
        host decision, so a spec wave DRAINS the pipeline and syncs
        once per wave — the draft/verify pair replaces multi-step
        chaining as the round-trip amortizer (and, unlike the chained
        path, composes with per-request eos).

        ``kv_offload`` (r15): how the host tiers move their bytes.
        ``"async"`` — swap-outs and prefix-cache spills dispatch
        non-blocking d2h (blocks stay accounted until the transfer
        lands at a step boundary), queued restores prefetch h2d into
        staging buffers ahead of admission, and refcount-0 cached
        blocks spill proactively under pool pressure
        (:mod:`paddle_tpu.serving.offload`). ``"sync"`` — the pre-r15
        inline transfers (the parity-test reference). ``"auto"``
        (default) follows ``FLAGS_serve_kv_offload_sync``. Greedy token
        streams are bit-identical either way (test-enforced, bf16 and
        int8); only the stall profile differs. Ignored when no host
        tier is configured.

        ``role`` / ``relay`` (r19, disaggregated serving): ``role``
        declares which phase of a request this engine serves —
        ``"both"`` (default: the colocated engine), ``"decode"``
        (identical engine behavior; a placement hint for the
        ReplicaRouter, which keeps fresh prefills off it when a
        prefill-capable replica is healthy), or ``"prefill"``: the
        engine runs admission + (chunked) prefill ONLY — as soon as a
        slot's first token is host-visible it spills the slot's pool
        blocks (payload + scales bit-exact, the swap-out d2h path) into
        the shared host ``relay`` pool (``HostKVPool(kind="relay")``)
        keyed by request id, frees the slot, and finishes the request
        with reason ``"handoff"`` — partial result: the first token.
        A decode/both engine admitting a request whose ``relay_key``
        finds a relay entry restores it via the batched h2d scatter
        instead of prefilling (the swap-in path); a missing or
        incomplete entry degrades to a full prefill of the same context
        — greedy streams are bit-identical to a colocated engine's
        either way (test-enforced, bf16 and int8). ``role="prefill"``
        requires a ``relay``."""
        c = config
        assert max_model_len % block_size == 0
        self.params = params
        self.config = config
        self.N = max_slots
        self.bs = block_size
        self.mb = max_model_len // block_size      # logical blocks per slot
        self.max_model_len = max_model_len
        # +1: physical block 0 is the trash block for idle slots
        self.nb = (num_blocks if num_blocks is not None
                   else max_slots * self.mb) + 1
        self.buckets = sorted(prompt_buckets or
                              [b for b in (64, 128, 256, 512)
                               if b <= max_model_len] or [max_model_len])
        if self.buckets[-1] < max_model_len:
            # re-admission after preemption prefills prompt+generated, which
            # can reach max_model_len — it must always have a bucket
            self.buckets.append(max_model_len)
        for b in self.buckets:
            if b % block_size:
                raise ValueError(
                    f"prompt bucket {b} is not a multiple of "
                    f"block_size {block_size}")
        if kv_dtype not in (None, "int8", jnp.int8):
            raise ValueError(
                f"kv_dtype must be None or 'int8', got {kv_dtype!r}")
        self.kv_int8 = kv_dtype is not None
        pool_shape = (c.num_layers, self.nb, block_size, c.num_kv_heads,
                      c.head_dim)
        if self.kv_int8:
            # int8 payload + f32 per-entry scales (~3% overhead at D=128)
            self.pools = {
                "k": jnp.zeros(pool_shape, jnp.int8),
                "v": jnp.zeros(pool_shape, jnp.int8),
                "ks": jnp.zeros(pool_shape[:-1], jnp.float32),
                "vs": jnp.zeros(pool_shape[:-1], jnp.float32),
            }
        else:
            self.pools = {"k": jnp.zeros(pool_shape, c.dtype),
                          "v": jnp.zeros(pool_shape, c.dtype)}
        # -- speculative decoding (r13): the optional draft model --------
        self._spec_on = spec and draft_params is not None
        self.spec_k = int(spec_tokens)
        self.draft_params = draft_params if self._spec_on else None
        self.draft_config = draft_config if self._spec_on else None
        if self._spec_on:
            if draft_config is None:
                raise ValueError(
                    "draft_params requires a draft_config")
            if draft_config.vocab_size != c.vocab_size:
                raise ValueError(
                    f"draft vocab {draft_config.vocab_size} != target "
                    f"vocab {c.vocab_size} — the two models must share "
                    "a tokenizer")
            if self.spec_k < 1:
                raise ValueError(
                    f"spec_tokens must be >= 1, got {spec_tokens}")
            dc = draft_config
            # draft KV pools share the target's physical block grid
            # (same nb/bs, same block ids): one block backs BOTH
            # models' KV for its token range, so block accounting,
            # prefix-cache spill/restore, preemption swap and crash
            # recovery cover the draft with zero new bookkeeping. Draft
            # pools stay in the draft dtype (the draft is small — int8
            # draft WEIGHTS are the bandwidth lever, not its KV).
            dshape = (dc.num_layers, self.nb, block_size,
                      dc.num_kv_heads, dc.head_dim)
            self.pools["dk"] = jnp.zeros(dshape, dc.dtype)
            self.pools["dv"] = jnp.zeros(dshape, dc.dtype)
        self.mesh = mesh
        if mesh is not None:
            # tp serving (r19): target params shard Megatron-style, the
            # KV pools shard over their kv-head axis, and the ragged
            # block-walk runs under a shard_map over 'tp' (each shard
            # walks the same tables against its head slice — see
            # kernels/paged_attention.ragged_decode_partial). The spec
            # DRAFT stays replicated: its params and dk/dv pools carry
            # P() shardings (draft kv heads need not divide tp), while
            # _spec_verify reuses the sharded prefill program via GSPMD.
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            from ..models import llama as _llama

            tp = dict(mesh.shape).get("tp", 1)
            if c.num_kv_heads % max(tp, 1):
                raise ValueError(
                    f"tp={tp} must divide num_kv_heads={c.num_kv_heads}")
            self.params = params = jax.device_put(
                params, _llama.make_serving_shardings(params, c, mesh,
                                                      fsdp=False))
            if self._spec_on:
                self.draft_params = jax.device_put(
                    self.draft_params,
                    _llama.make_replicated_shardings(self.draft_params,
                                                     mesh))
            pool_sh = NamedSharding(mesh, P(None, None, None, "tp", None))
            scale_sh = NamedSharding(mesh, P(None, None, None, "tp"))
            rep_sh = NamedSharding(mesh, P())
            self.pools = {
                k: jax.device_put(v, rep_sh if k.startswith("d")
                                  else pool_sh if v.ndim == 5 else scale_sh)
                for k, v in self.pools.items()}
        self.free_blocks = deque(range(1, self.nb))
        self.table = np.zeros((self.N, self.mb), np.int32)
        self.n_alloc = np.zeros(self.N, np.int64)  # backed logical blocks
        self.lengths = np.zeros(self.N, np.int64)
        self.slot_req: List[Optional[Request]] = [None] * self.N
        self.slot_out: List[List[int]] = [[] for _ in range(self.N)]
        self.admit_order: List[int] = []           # slots, oldest first
        self.queue: deque = deque()
        self.results: Dict[int, List[int]] = {}
        self.cancel_noops = 0   # cancels/finishes that raced a terminal
        self._next_id = 0
        self._key = jax.random.PRNGKey(seed)
        self._prefill = {}
        self.decode_steps = max(1, int(decode_steps))
        if decode_kernel not in ("auto", "ragged", "bucketed", "mega"):
            raise ValueError(
                f"decode_kernel must be 'auto', 'ragged', 'bucketed' or "
                f"'mega', got {decode_kernel!r}")
        self.decode_kernel = decode_kernel
        # decode compile cache. Ragged path (r12): keyed ("ragged",
        # flags) — ONE variant per sampling-flag tuple (≤8 total; an
        # all-greedy slot mix must not pay top-k/top-p's full-vocab
        # sorts), since lengths are a runtime operand and the table
        # ships at full width. Bucketed fallback: keyed (prefix-bucket,
        # flags) — power-of-two block counts (≤ log2(mb)+2 values) × ≤8
        # flag tuples, bounded however the workload mixes lengths.
        self._decode_cache: Dict = {}
        # cumulative host estimate of decode-call KV pool traffic (see
        # _dispatch_decode) — bench evidence, kept whether or not the
        # metrics registry is enabled
        self.kv_read_bytes_total = 0
        # swap-enabled preemptions that fell back to recompute (host
        # evidence for the offload bench row: the async tier's
        # acceptance is ZERO of these under a fitting host pool)
        self.swap_fallbacks = 0
        # disagg handoff host evidence (r19, bench rows): spills this
        # prefill-role engine completed, their d2h+relay bytes/seconds
        self.handoffs = 0
        self.handoff_bytes = 0
        self.handoff_seconds = 0.0
        # device-resident decode carry (last/lengths/done/budgets/key) +
        # static per-slot vectors; the carry chains from call to call and
        # is only rebuilt from host state when the pipeline is drained
        self._carry = None
        self._slot_vecs = None
        self._slots_dirty = True
        self._table_dirty = True
        self._table_dev = {}         # prefix-bucket (blocks) → device table
        # the dispatched-but-unread decode call (pipeline depth 1): its
        # tokens are fetched while the NEXT call occupies the chip
        self._inflight = None
        # admissions whose in-program-sampled first token has not yet been
        # read back; attached to the next dispatch record
        self._pending_adm: List = []
        # observability: add_request wall time per req awaiting its first
        # host-visible token (TTFT); entries die with the request
        self._obs_t_add: Dict[int, float] = {}
        # first-token wall time per req still decoding, for TPOT at
        # finish; survives preemption (the decode clock keeps running)
        self._obs_t_first: Dict[int, float] = {}
        # cost-model FLOPs per compiled decode variant (serving_mfu);
        # None = analysis unavailable on this jax/backend
        self._decode_flops: Dict = {}
        self._last_decode_flops = None
        # -- survivability layer (deadlines / shedding / swap / chaos) ----
        self.admission = (AdmissionController(admission)
                          if isinstance(admission, AdmissionConfig)
                          else admission)
        self.swap_pool = (HostKVPool(kv_swap_bytes) if kv_swap_bytes
                          else None)
        # -- disaggregated prefill/decode (r19) ---------------------------
        if role not in ("prefill", "decode", "both"):
            raise ValueError(
                f"role must be 'prefill', 'decode' or 'both', got "
                f"{role!r}")
        if relay is not None and getattr(relay, "kind", None) != "relay":
            raise ValueError(
                "relay must be a HostKVPool(kind='relay') shared "
                "between the prefill and decode replicas")
        if role == "prefill" and relay is None:
            raise ValueError(
                "role='prefill' requires a relay pool — the handed-off "
                "KV has to live somewhere the decode replica can reach")
        self.role = role
        self.relay = relay
        # -- async two-tier offload (r15): one transfer engine whenever
        # ANY host tier exists. "auto" defers the sync decision to
        # FLAGS_serve_kv_offload_sync (the version-shimmed d2h start
        # degrades by itself off-TPU / on old jax — see offload.py)
        if kv_offload not in ("auto", "async", "sync"):
            raise ValueError(
                f"kv_offload must be 'auto', 'async' or 'sync', got "
                f"{kv_offload!r}")
        self.offload = (OffloadEngine(
            sync=None if kv_offload == "auto" else kv_offload == "sync")
            if (kv_swap_bytes or (prefix_cache and prefix_cache_host_bytes))
            else None)
        # proactive-spill pressure threshold: the flag default, raised
        # to 2x the admission shed threshold when one is configured
        # (spilling must engage before shedding — one free_frac signal)
        self._spill_free_frac = float(
            get_flag("serve_kv_offload_spill_free_frac"))
        if isinstance(self.admission, AdmissionController):
            self._spill_free_frac = self.admission.spill_free_frac(
                self._spill_free_frac)
        self.injector = injector
        # terminal disposition per request id: every id that entered
        # add_request ends in exactly one of finished / shed /
        # deadline_exceeded / client_disconnected / drained (the
        # chaos-suite contract)
        self.finish_reasons: Dict[int, str] = {}
        self._step_idx = 0
        # blocks held hostage by an injected pool_squeeze, with their
        # release step — counted by block_accounting so the free+backed+
        # squeezed invariant holds THROUGH the fault
        self._squeezed: List = []
        # swap-ins whose carry lanes await their host-known state
        # ((slot, req_id); the recompute path uses _pending_adm instead)
        self._pending_swapin: List = []
        # slots (re)admitted via swap since the last dispatch: their
        # rem_start must come from host state, never the previous
        # record's chained countdown (the slot id may be recycled)
        self._fresh_swapins: set = set()
        self._swapin_cache: Dict = {}
        # requests currently carrying a deadline — the per-step expiry
        # sweep is skipped entirely at 0, so deadline-free traffic pays
        # nothing for the feature (no O(queue) scan in the hot loop)
        self._deadline_live = 0
        # rid -> reason marked by cancel_request (the HTTP front door's
        # disconnect/stall/drain hook); applied at the next step boundary
        # through the deadline-eviction machinery, so a dropped client's
        # slot and KV blocks free within one engine step. The lock makes
        # the marker handoff safe from ANY thread (a lock-free dict swap
        # could lose a marker written between the swap's load and store
        # — a disconnect that never cancels pins its KV blocks)
        self._cancels: Dict[int, str] = {}
        self._cancel_lock = threading.Lock()
        # every (rid, tok) pair committed host-side THIS step, in commit
        # order — the crash-salvage buffer: a step that raises after
        # committing tokens must still deliver them exactly once
        # (ResilientEngine returns this on recovery)
        self._step_emitted: List = []
        # -- prefix cache + chunked prefill (r10) -------------------------
        if prefill_chunk:
            # chunks start and (except the final one) end on block
            # boundaries, so cached prefixes and chunk history stay
            # block-aligned — round up rather than reject
            prefill_chunk = -(-int(prefill_chunk) // block_size) \
                * block_size
            if prefill_chunk > self.buckets[-1]:
                raise ValueError(
                    f"prefill_chunk {prefill_chunk} exceeds the largest "
                    f"prompt bucket {self.buckets[-1]}")
        self.prefill_chunk = prefill_chunk
        self.prefix_cache = (
            PrefixCache(block_size,
                        HostKVPool(prefix_cache_host_bytes, kind="prefix")
                        if prefix_cache_host_bytes else None)
            if prefix_cache else None)
        # trie nodes each slot has pinned, in block-table order: the
        # first len(_pinned[slot]) table entries are cache-owned (shared,
        # never freed by the slot — unpinned instead)
        self._pinned: List[List] = [[] for _ in range(self.N)]
        # slots mid-chunked-prefill: slot -> {"ctx", "pos", "rid"};
        # excluded from decode dispatch until the final chunk lands
        self._chunks: Dict[int, Dict] = {}
        # -- speculative decoding state (r13) -----------------------------
        # per-slot draft-KV coverage: the draft participates in a spec
        # wave only while its KV covers exactly [0, lengths) — a slot
        # advanced by the NORMAL decode path (sampled mix in the wave)
        # goes stale (-1) until a re-prefill resets it. Staleness is a
        # throughput concern only: proposals from bad draft KV still
        # verify against the target, they just stop being accepted.
        self._draft_len = np.zeros(self.N, np.int64)
        self._spec_draft_cache: Dict = {}    # ("ragged"|nbk) → draft fn
        self._spec_verify_cache: Dict = {}   # nbk → verify fn
        # host-side spec evidence (kept whether or not the metrics
        # registry is enabled — bench rows read these)
        self.spec_proposed = 0      # draft tokens offered to verify
        self.spec_accepted = 0      # of those, accepted by the target
        self.spec_committed = 0     # tokens committed by spec waves
        self.spec_waves = 0         # draft+verify wave count
        self.spec_draft_steps = 0   # draft decode steps run (waves * k)
        self.spec_verify_calls = 0  # batched target verify calls

    # -- public api ---------------------------------------------------------
    @property
    def k_pool(self):
        return self.pools["k"]

    @property
    def v_pool(self):
        return self.pools["v"]

    def add_request(self, prompt: List[int], **kw) -> int:
        # validate BEFORE minting the id: a rejected request must not
        # consume a rid, or the "every minted id ends in exactly one
        # terminal reason" contract (finish_reasons) breaks for every
        # oversize prompt a client sends — remotely reachable through
        # the HTTP front door's 400 path
        req = Request(req_id=self._next_id, prompt=list(prompt), **kw)
        if len(req.prompt) + req.max_new_tokens > self.max_model_len:
            raise ValueError(
                f"request {req.req_id}: prompt({len(req.prompt)}) + "
                f"max_new_tokens({req.max_new_tokens}) exceeds "
                f"max_model_len({self.max_model_len})")
        if len(req.prompt) > self.buckets[-1]:
            raise ValueError(
                f"request {req.req_id}: prompt length {len(req.prompt)} "
                f"exceeds the largest prompt bucket {self.buckets[-1]}")
        rid = self._next_id
        self._next_id += 1
        if req.deadline_s is not None:
            req.t_deadline = time.perf_counter() + float(req.deadline_s)
        if self.admission is not None:
            # cache-aware pressure: refcount-0 cached blocks are
            # reclaimable (spill/drop), so they count as headroom — a
            # full-looking pool of evictable prefixes must not shed
            reason = self.admission.check(
                req, queue_depth=len(self.queue),
                free_frac=self._avail_blocks() / max(1, self.nb - 1))
            if reason is not None:
                # reject-newest load shedding: fail THIS request in
                # microseconds (typed, maps to HTTP 429/503) so the
                # admitted ones keep their latency
                self.finish_reasons[rid] = "shed"
                _flight.record("request_shed", req_id=rid, reason=reason)
                if _obs.enabled():
                    tracer = _rt.get_request_tracer()
                    tracer.submit(rid, prompt_tokens=len(req.prompt),
                                  max_new_tokens=req.max_new_tokens,
                                  tenant=req.tenant)
                    tracer.finish(rid, tokens=0, reason="shed",
                                  shed_reason=reason)
                raise ShedError(reason, rid)
        self.queue.append(req)
        if req.t_deadline is not None:
            self._deadline_live += 1
        if _obs.enabled():
            self._obs_t_add[rid] = time.perf_counter()
            _M_QUEUE_DEPTH.set(len(self.queue))
            # the request_id minted here IS the distributed-trace id: it
            # follows the request through slots, preemptions and
            # re-admissions (observability.request_trace); the tenant
            # rides the meta into the summary (obs_dump --requests)
            _rt.get_request_tracer().submit(
                rid, prompt_tokens=len(req.prompt),
                max_new_tokens=req.max_new_tokens, tenant=req.tenant)
        return rid

    def has_work(self) -> bool:
        return bool(self.queue) or any(r is not None for r in self.slot_req)

    def run(self) -> Dict[int, List[int]]:
        while self.has_work():
            self.step()
        if self._inflight is not None:      # defensive: step() drains first
            self._process_inflight()
        self.drain_offload()                # land stragglers: in_flight→0
        return self.results

    # -- internals ----------------------------------------------------------
    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(f"prompt length {n} exceeds largest bucket "
                         f"{self.buckets[-1]}")

    def _prefill_fn(self, bucket: int, B: int, flags, prefix_nbk: int = 0,
                    draft: bool = False):
        if draft:
            # draft prefill: greedy flags always (its sampled token is
            # discarded) so the draft never multiplies the flag axis
            flags = (False, False, False)
        # target keys stay the documented 4-tuple; the draft adds a
        # parallel family, one tag deeper
        key = ((bucket, B, flags, prefix_nbk) if not draft
               else (bucket, B, flags, prefix_nbk, "draft"))
        fn = self._prefill.get(key)
        if fn is None:
            # the numerics gate is baked at variant-compile time (the
            # probes are trace-time ops): variants compiled while
            # FLAGS_obs_numerics was off keep their compiled form —
            # flip the flag before the engine serves to instrument
            fn = jax.jit(functools.partial(
                             _paged_prefill,
                             config=(self.draft_config if draft
                                     else self.config),
                             sample_flags=flags,
                             kv_int8=self.kv_int8 and not draft,
                             numerics=(self.kv_int8 and not draft
                                       and _nm.active()),
                             prefix_nbk=prefix_nbk,
                             kv_prefix="d" if draft else ""),
                         donate_argnums=(4,))
            self._prefill[key] = fn
        return fn

    # -- block allocation over the free list + the prefix cache ------------
    def _avail_blocks(self) -> int:
        """Blocks an allocation could obtain right now: the free list
        plus every refcount-0 cached block (reclaimable by spill/drop)."""
        n = len(self.free_blocks)
        if self.prefix_cache is not None:
            n += self.prefix_cache.evictable_blocks
        return n

    def _take_up_to(self, k: int) -> List[int]:
        """Pop up to ``k`` free blocks, reclaiming from the prefix cache
        (LRU spill-then-drop) when the free list runs short — ONE
        reclaim sweep and one batched d2h however many blocks are
        needed, never a sweep per block."""
        if len(self.free_blocks) < k and self.prefix_cache is not None:
            self.free_blocks.extend(self.prefix_cache.reclaim(
                k - len(self.free_blocks), self._fetch_blocks))
        out: List[int] = []
        while self.free_blocks and len(out) < k:
            out.append(self.free_blocks.popleft())
        return out

    def _fetch_blocks(self, blks: List[int]) -> Dict:
        """d2h a batch of blocks from every pool entry in one gather per
        entry (payload AND scales under int8 pools — the spill/restore
        round-trip is bit-exact). Returns arrays stacked on the block
        axis, the layout :meth:`PrefixCache.reclaim` slices per node."""
        idx = np.asarray(blks, np.int32)
        return {name: np.asarray(jax.device_get(pool[:, idx]))
                for name, pool in self.pools.items()}

    def _restore_blocks(self, blks: List[int], ents: List) -> None:
        """h2d a matched path's spilled blocks in ONE batched scatter
        (the kv_swap restore at block count len(blks), pools donated) —
        never a transfer per block on the admission path. Entries the
        offload engine staged ahead of time (``SwapEntry.staged``, r15)
        contribute device-resident buffers (prefetch hits); the rest
        start their h2d here and the observed wait counts as a stall."""
        names = sorted(ents[0].data)
        staged_i = [i for i, e in enumerate(ents) if e.staged is not None]
        fresh_i = [i for i, e in enumerate(ents) if e.staged is None]
        # reorder entries staged-first WITH their blocks (the scatter
        # pairs blks[i] with slice i, so any consistent permutation is
        # exact) — every fresh payload then batches into ONE host-side
        # concat + one h2d per pool entry, the r10 contract, whatever
        # mix of staged/unstaged the path carries
        blks = [blks[i] for i in staged_i + fresh_i]
        t0 = time.perf_counter()
        fresh_up = {}
        if fresh_i:
            fresh_up = {n: jnp.asarray(np.concatenate(
                [np.asarray(ents[i].data[n]) for i in fresh_i], axis=1)
                if len(fresh_i) > 1 else np.asarray(
                    ents[fresh_i[0]].data[n])) for n in names}
        if self.offload is not None and fresh_i:
            if not self.offload.sync:
                # async miss: observe the true inline wait. Sync mode
                # skips the barrier — the pre-r15 behavior let the
                # transfer overlap into the scatter dispatch, and the
                # forced-sync leg is the bench baseline for exactly
                # that behavior (dt then measures the host-side cost)
                jax.block_until_ready(list(fresh_up.values()))
            self.offload.note_stall(time.perf_counter() - t0,
                                    n=len(fresh_i))
        if self.offload is not None and staged_i:
            self.offload.note_hit(len(staged_i))
        stacked = {}
        for n in names:
            parts = [ents[i].staged[n] for i in staged_i]
            if fresh_i:
                parts.append(fresh_up[n])
            stacked[n] = (jnp.concatenate(parts, axis=1)
                          if len(parts) > 1 else parts[0])
        for i in staged_i:
            ents[i].staged = None
        self.pools = self._swapin_fn(len(blks))(
            self.pools, jnp.asarray(np.asarray(blks, np.int32)),
            *[stacked[n] for n in names])

    def _free_slot(self, slot: int, requeue: bool = False,
                   reason: str = "finished", swap: bool = True):
        req = self.slot_req[slot]
        out = self.slot_out[slot]
        swapped, held = False, []
        if requeue and req is not None and swap \
                and self.swap_pool is not None:
            # swap-instead-of-recompute: move the victim's blocks to the
            # host tier BEFORE they are freed (fallback: plain recompute;
            # async mode parks `held` with the in-flight transfer)
            swapped, held = self._swap_out(slot, req, out)
            if not swapped:
                self.swap_fallbacks += 1
        # blocks [0, keep) are cache-owned: shared, unpinned below, never
        # freed here. A finishing request first offers its decode-grown
        # FULL blocks to the trie (multi-turn prefix reuse: the next turn
        # re-sends prompt+answer and matches them) — adopted blocks
        # transfer ownership to the cache instead of the free list.
        keep = len(self._pinned[slot])
        if not requeue and req is not None and reason == "finished" \
                and self.prefix_cache is not None:
            # KV is valid for the first self.lengths positions only (the
            # final emitted token's KV was never written)
            full = int(self.lengths[slot]) // self.bs
            if full > keep:
                ctx_all = req.prompt + req.generated + out
                adopted = self.prefix_cache.extend(
                    ctx_all, keep,
                    [int(self.table[slot, j]) for j in range(keep, full)],
                    pin=False)
                keep += len(adopted)
        held_set = set(held)
        for j in range(keep, int(self.n_alloc[slot])):
            blk = int(self.table[slot, j])
            if blk not in held_set:     # custody: frees when the spill lands
                self.free_blocks.append(blk)
        if self._pinned[slot]:
            self.prefix_cache.unpin(self._pinned[slot])
            self._pinned[slot] = []
        self._chunks.pop(slot, None)
        self.table[slot, :] = 0
        self.n_alloc[slot] = 0
        self.lengths[slot] = 0
        self._draft_len[slot] = 0
        self.slot_req[slot] = None
        if slot in self.admit_order:
            self.admit_order.remove(slot)
        self.slot_out[slot] = []
        self._table_dirty = True
        self._slots_dirty = True
        # an admission whose first token was never read back dies with the
        # slot (recompute semantics: re-admission prefills and re-samples)
        self._pending_adm = [e for e in self._pending_adm if e[0] != slot]
        self._pending_swapin = [e for e in self._pending_swapin
                                if e[0] != slot]
        self._fresh_swapins.discard(slot)
        if requeue and req is not None:
            # preemption: carry generated tokens so re-admission continues
            # from prompt+generated — streamed tokens stay valid and are
            # never re-emitted (swap-in restores their KV; recompute
            # re-prefills it)
            req.generated.extend(out)
            self.queue.appendleft(req)
            _M_PREEMPTIONS.inc()
            _flight.record("preemption", req_id=req.req_id,
                           generated=len(req.generated), swapped=swapped)
            if _obs.enabled():
                _rt.get_request_tracer().record(
                    req.req_id, "preempt", slot=slot,
                    generated=len(req.generated), swapped=swapped)
        elif req is not None:
            self.results[req.req_id] = req.generated + out
            self.finish_reasons[req.req_id] = reason
            if req.t_deadline is not None:
                self._deadline_live = max(0, self._deadline_live - 1)
            if self.swap_pool is not None:
                self.swap_pool.discard(req.req_id)
                if self.offload is not None:
                    # an in-flight spill for a terminal request is moot:
                    # drop it, reclaim its custody blocks now
                    self.free_blocks.extend(
                        self.offload.cancel(req.req_id))
            if reason == "deadline_exceeded":
                _M_DEADLINE.inc()
                _flight.record("deadline_exceeded", req_id=req.req_id,
                               tokens=len(self.results[req.req_id]))
            elif reason != "finished":
                # front-door cancellation (client_disconnected / drained):
                # terminal, partial tokens delivered, but NOT a completed
                # request — the finished counter must not absorb it
                _flight.record(reason, req_id=req.req_id,
                               tokens=len(self.results[req.req_id]))
            else:
                _M_FINISHED.inc()
            now = time.perf_counter()
            t_first = self._obs_t_first.pop(req.req_id, None)
            # a request that finishes in the SAME step its first token
            # became host-visible retires before step()'s TTFT loop runs —
            # its first token is host-visible right now, so observe here.
            # No TPOT for it: first-visibility and finish coincide, so
            # there is no decode cadence to measure (an exact-0
            # observation would drag the SLO gauge optimistically)
            t_add = self._obs_t_add.pop(req.req_id, None)
            tracer = _rt.get_request_tracer() if _obs.enabled() else None
            if t_add is not None and (req.generated or out):
                if tracer is not None:
                    tracer.record(req.req_id, "first_token")
                _rt.observe_with_exemplar(_M_TTFT, now - t_add,
                                          req.req_id)
            elif t_first is not None:
                # TPOT = decode latency after first-token visibility, per
                # subsequent token (the depth-1 pipeline batches
                # readbacks; the histogram tracks steady-state cadence)
                n_out = len(req.generated) + len(out)
                if n_out > 1:
                    _rt.observe_with_exemplar(
                        _M_TPOT, (now - t_first) / (n_out - 1),
                        req.req_id)
            if tracer is not None:
                tracer.finish(req.req_id,
                              tokens=len(self.results[req.req_id]),
                              reason=reason)

    # -- survivability: swap, deadlines, chaos ------------------------------
    def _swap_out(self, slot: int, req: Request,
                  out: List[int]) -> Tuple[bool, List[int]]:
        """Copy the slot's live KV blocks to the host tier. Keeps
        ``len(ctx) - 1`` positions where ``ctx = prompt + generated +
        out``: the context tail is the re-admission's next decode input,
        whose K/V the first restored decode step rewrites — so a slot
        whose sampled-but-unread first token died with it (KV covers ALL
        of ctx) and a mid-decode victim (KV covers ctx[:-1]) restore
        through one invariant.

        Returns ``(swapped, held)``. Async mode (r15) dispatches a
        NON-BLOCKING d2h and parks the victim's private blocks in the
        offload engine's custody (``held`` — the ledger's transient
        ``in_flight`` term; cache-pinned head blocks stay ``cached``,
        the transfer reads them safely by stream order): the step
        thread never waits on the spill, and the blocks return to the
        free list at the step boundary after it lands. Sync mode blocks
        inline and holds nothing. ``swapped=False`` on fallback (host
        pool full / nothing to keep) — the caller then recomputes."""
        n_keep = len(req.prompt) + len(req.generated) + len(out) - 1
        if n_keep <= 0 or self.lengths[slot] < n_keep:
            # every swap-enabled preemption lands in swap_out OR fallback
            # — an uncounted recompute would hide a swap-tier regression
            _M_SWAP_FALLBACK.inc(reason="nothing_to_keep")
            return False, []
        nb_keep = -(-n_keep // self.bs)
        blocks = np.asarray(self.table[slot, :nb_keep], np.int32)
        # both modes route through the offload engine (a swap pool
        # implies one exists): spill_async owns the sync/async decision
        # — async parks `held` in custody, sync completes inline and
        # holds nothing. Payload AND scales move verbatim either way,
        # so the restore is bit-exact (no requantization drift).
        keep = len(self._pinned[slot])
        held = ([] if self.offload.sync else
                [int(b) for b in self.table[slot, keep:nb_keep]])
        ok = self.offload.spill_async(
            req.req_id, self.pools, blocks, n_keep, self.swap_pool,
            hold_blocks=held)
        return ok, (held if ok else [])

    def _swapin_fn(self, nb: int):
        """One compiled restore per block count: scatter every host pool
        entry back into freshly allocated blocks, pools donated (the
        multi-GB pools are patched in place, never copied)."""
        fn = self._swapin_cache.get(nb)
        if fn is None:
            names = sorted(self.pools)

            def restore(pools, blk, *data):
                pools = dict(pools)
                for name, d in zip(names, data):
                    pools[name] = pools[name].at[:, blk].set(d)
                return pools

            fn = self._swapin_cache[nb] = jax.jit(restore,
                                                  donate_argnums=(0,))
        return fn

    def _swap_in(self, slot: int, req: Request, ent) -> None:
        """Re-admit a preempted request from its host-tier KV: allocate
        blocks, restore the payload, and rebuild host bookkeeping — a
        short h2d instead of a full re-prefill."""
        blocks = self._take_up_to(max(1, ent.n_blocks))
        assert len(blocks) == max(1, ent.n_blocks), \
            "swap-in allocated past _avail_blocks"
        self._pinned[slot] = []      # restored KV is slot-private
        self.table[slot, :len(blocks)] = blocks
        self.n_alloc[slot] = len(blocks)
        self.lengths[slot] = ent.n_tokens
        if self._spec_on:
            # the swap moved BOTH models' pool entries verbatim, so the
            # draft's coverage restores with the target's (a slot whose
            # draft was stale at swap-out restores stale draft KV —
            # acceptance-rate noise, never a correctness issue: every
            # proposal is target-verified)
            self._draft_len[slot] = ent.n_tokens
        self.slot_req[slot] = req
        self.admit_order.append(slot)
        self._table_dirty = True
        self._slots_dirty = True
        offload_mode = None
        if ent.n_blocks:
            names = sorted(ent.data)
            blk = jnp.asarray(np.asarray(blocks[:ent.n_blocks], np.int32))
            staged = ent.staged
            if staged is not None:
                # prefetch hit (r15): the offload engine staged this
                # entry's payload h2d ahead of admission — the scatter
                # consumes already-resident buffers, zero inline wait
                datas = [staged[n] for n in names]
                ent.staged = None
                offload_mode = "hit"
                if self.offload is not None:
                    self.offload.note_hit()
            else:
                t0 = time.perf_counter()
                datas = [jnp.asarray(ent.data[n]) for n in names]
                if self.offload is not None:
                    # the inline h2d is the stall the prefetch tier
                    # exists to hide: observe exactly what it cost.
                    # Sync mode skips the barrier — pre-r15 let the
                    # transfer overlap into the scatter dispatch, and
                    # the forced-sync leg must stay that baseline
                    if not self.offload.sync:
                        jax.block_until_ready(datas)
                    self.offload.note_stall(time.perf_counter() - t0)
                    offload_mode = "stall"
            self.pools = self._swapin_fn(ent.n_blocks)(
                self.pools, blk, *datas)
        self._pending_swapin.append((slot, req.req_id))
        self._fresh_swapins.add(slot)
        _M_ADMISSIONS.inc()
        _flight.record("kv_swap_in", req_id=req.req_id,
                       tokens=ent.n_tokens, blocks=ent.n_blocks,
                       offload=offload_mode)
        if _obs.enabled():
            kw = ({"offload": offload_mode} if offload_mode is not None
                  else {})
            _rt.get_request_tracer().admitted(
                req.req_id, slot=slot, context_tokens=ent.n_tokens,
                swapped_in=True, **kw)

    def _handoff(self, slot: int) -> None:
        """Disaggregated handoff (r19): spill the slot's prefilled KV
        blocks into the shared relay pool and finish the stream with
        reason ``"handoff"`` — the prefill replica's terminal. Keeps
        ``lengths[slot]`` positions (every prefilled token; the sampled
        first token's KV is written by the decode replica's first
        restored step — the :meth:`_swap_out` invariant with the first
        token as ``out``), so a decode replica re-admitting ``prompt +
        delivered`` finds a relay entry of exactly ``len(ctx) - 1``
        tokens: the same restore contract as a swap-in, payload +
        scales bit-exact. A capacity refusal still hands the stream off
        — the decode replica then re-prefills the identical context
        (the pool counts outcome="relay_full"; streams match either
        way, only the transfer saving is lost)."""
        req = self.slot_req[slot]
        t0 = time.perf_counter()
        n_keep = int(self.lengths[slot])
        nb_keep = -(-n_keep // self.bs)
        data = self._fetch_blocks(
            [int(self.table[slot, j]) for j in range(nb_keep)])
        ok = self.relay.put(req.req_id, data, n_keep)
        dt = time.perf_counter() - t0
        nbytes = int(sum(a.nbytes for a in data.values()))
        self.handoffs += 1
        self.handoff_bytes += nbytes
        self.handoff_seconds += dt
        if ok:
            _M_DISAGG_HANDOFFS.inc(outcome="ok")
        _M_DISAGG_SECONDS.observe(dt)
        _flight.record("kv_handoff", req_id=req.req_id, tokens=n_keep,
                       blocks=nb_keep, bytes=nbytes, relayed=ok)
        self._free_slot(slot, reason="handoff")

    def _prefill_handoffs(self):
        """The ``role="prefill"`` tail of a step (standing in for the
        decode dispatch): flush pending first tokens (host sync — a
        handoff must not outrun its stream's delivered prefix), then
        spill every slot whose prefill completed. Mid-chunk slots keep
        chunking; a request that finished ON its first token (budget 1
        or eos) already freed its slot in the flush and never relays."""
        emitted = []
        if self._pending_adm:
            adm, self._pending_adm = self._pending_adm, []
            emitted += self._flush_adm(adm)
        for slot in self._decode_slots():
            if self.slot_req[slot] is not None:
                self._handoff(slot)
        return emitted

    def _finish_expired(self, req: Request, out: List[int],
                        queued: bool,
                        reason: str = "deadline_exceeded") -> None:
        """Terminal bookkeeping for a QUEUED request evicted before any
        slot (deadline expiry or a front-door cancellation): partial
        tokens delivered, its trace closes with ``reason``. Idempotent:
        a rid that already reached a terminal reason is a counted
        no-op — never a double-free of its swap/offload state."""
        rid = req.req_id
        if rid in self.finish_reasons:
            self.cancel_noops += 1
            _M_CANCEL_NOOP.inc()
            return
        self.results[rid] = out
        self.finish_reasons[rid] = reason
        if req.t_deadline is not None:
            self._deadline_live = max(0, self._deadline_live - 1)
        if self.swap_pool is not None:
            self.swap_pool.discard(rid)
            if self.offload is not None:
                self.free_blocks.extend(self.offload.cancel(rid))
        if reason == "deadline_exceeded":
            _M_DEADLINE.inc()
        _flight.record(reason, req_id=rid, queued=queued,
                       tokens=len(out))
        self._obs_t_add.pop(rid, None)
        self._obs_t_first.pop(rid, None)
        if _obs.enabled():
            _rt.get_request_tracer().finish(
                rid, tokens=len(out), reason=reason)

    def cancel_request(self, rid: int,
                       reason: str = "client_disconnected") -> None:
        """Mark a live request for cancellation — the HTTP front door's
        hook for a dropped connection, a stalled reader, or a drain
        cutoff. Applied at the NEXT step boundary (the engine's state
        machine is single-owner per step; the marker dict write is
        atomic, so any thread may call this): queued victims finish
        immediately with their partial tokens, in-slot victims ride the
        deadline-eviction path — slot freed, KV blocks returned, the
        unread in-flight wave's lanes skipped at readback via the
        (slot, rid) snapshot check. Already-terminal rids are a
        COUNTED no-op (``cancel_noops`` / ``serving_cancel_noop_total``)
        — the router's failover path races natural finishes by design,
        and the race must never KeyError or double-free."""
        if rid in self.finish_reasons:
            self.cancel_noops += 1
            _M_CANCEL_NOOP.inc()
            return
        with self._cancel_lock:
            self._cancels[rid] = str(reason)

    def _apply_cancels(self) -> None:
        """Evict every request marked by :meth:`cancel_request` —
        queued (cheap) and in-slot (KV blocks freed within this step).
        Free when no cancellation is pending (the unlocked emptiness
        probe is safe: a marker racing past it is applied next step)."""
        if not self._cancels:
            return
        with self._cancel_lock:
            cancels, self._cancels = self._cancels, {}
        live = {req.req_id for req in self.queue} \
            | {r.req_id for r in self.slot_req if r is not None}
        kept_markers = {rid: rsn for rid, rsn in cancels.items()
                        if rid in live}
        dropped = len(cancels) - len(kept_markers)
        if dropped:
            # Markers that raced a natural finish between the write and
            # this step boundary: counted no-ops, same contract as the
            # early return in cancel_request.
            self.cancel_noops += dropped
            _M_CANCEL_NOOP.inc(dropped)
        cancels = kept_markers
        if not cancels:
            return
        if any(req.req_id in cancels for req in self.queue):
            kept = deque()
            for req in self.queue:
                if req.req_id in cancels:
                    self._finish_expired(req, list(req.generated),
                                         queued=True,
                                         reason=cancels[req.req_id])
                else:
                    kept.append(req)
            self.queue = kept
        for slot in self._active_slots():
            req = self.slot_req[slot]
            if req.req_id in cancels:
                self._free_slot(slot, reason=cancels[req.req_id])

    def _expire_deadlines(self) -> None:
        """Evict every request past its deadline — queued (cheap) and
        in-slot (KV blocks freed; the in-flight record's lanes for the
        slot are skipped at readback via the (slot, rid) snapshot
        check). Free when no live request carries a deadline."""
        if not self._deadline_live:
            return
        now = time.perf_counter()
        if any(r.t_deadline is not None and now >= r.t_deadline
               for r in self.queue):
            kept = deque()
            for req in self.queue:
                if req.t_deadline is not None and now >= req.t_deadline:
                    self._finish_expired(req, list(req.generated),
                                         queued=True)
                else:
                    kept.append(req)
            self.queue = kept
        for slot in self._active_slots():
            req = self.slot_req[slot]
            if req.t_deadline is not None and now >= req.t_deadline:
                self._free_slot(slot, reason="deadline_exceeded")

    def _apply_faults(self) -> None:
        """Release expired pool squeezes, then fire this step's injected
        serving faults (slow_step / pool_squeeze here; readback_fail at
        the readback site in :meth:`_process`)."""
        if self._squeezed:
            keep = []
            for release_step, blocks in self._squeezed:
                if self._step_idx >= release_step:
                    self.free_blocks.extend(blocks)
                else:
                    keep.append((release_step, blocks))
            self._squeezed = keep
        inj = self.injector
        if inj is None:
            return
        if inj.fires("slow_step", self._step_idx):
            _flight.record("injected_slow_step", step=self._step_idx)
            time.sleep(0.02)
        if inj.fires("pool_squeeze", self._step_idx):
            n = min(max(1, (self.nb - 1) // 2), len(self.free_blocks))
            taken = [self.free_blocks.popleft() for _ in range(n)]
            if taken:
                self._squeezed.append((self._step_idx + 2, taken))
            _flight.record("injected_pool_squeeze", step=self._step_idx,
                           blocks=len(taken))

    def _offload_tick(self) -> None:
        """The r15 step-boundary offload sweep, in three moves:

        1. **Land** — commit every finished async spill into its host
           pool and return the custody blocks to the free list (this is
           where a swap-out's ``in_flight`` blocks become ``free``).
        2. **Proactive spill** — when the allocatable-block fraction
           drops below the pressure threshold (admission's ``free_frac``
           signal), start background d2h for the coldest refcount-0
           cached blocks, so a later reclaim frees them without paying
           the transfer inline (``_take_up_to`` never runs dry into a
           blocking d2h storm).
        3. **Prefetch** — scan the first ``prefetch_depth`` queued
           requests: a swapped one's host entry, or the host-resident
           trie nodes its prompt would match, start staging h2d NOW so
           the admission-time restore is a ``prefetch_hit``.

        The seeded ``offload_crash`` chaos fault fires here — with
        transfers potentially in flight — to prove the poisoned-wave
        recovery extends to the transfer engine."""
        off = self.offload
        if off is None:
            return
        freed = off.poll()
        if freed:
            self.free_blocks.extend(freed)
        pc = self.prefix_cache
        if not off.sync:
            if pc is not None and pc.host is not None:
                frac = self._avail_blocks() / max(1, self.nb - 1)
                # one arithmetic headroom probe before the O(trie)
                # candidate sweep: a saturated host tier must not be
                # re-asked every step (doomed reserves would spam the
                # drop_host_full cause counter and re-sort the trie)
                blk_bytes = sum(
                    a.shape[0] * int(np.prod(a.shape[2:]))
                    * a.dtype.itemsize for a in self.pools.values())
                room = (pc.host.capacity_bytes - pc.host.bytes_used
                        - pc.host.reserved_bytes)
                # cap the batch by the room that actually exists, so a
                # partially-full tier never dispatches doomed reserves
                # (each would spuriously count a drop_host_full cause
                # with no drop following)
                n_spill = min(off.spill_batch(),
                              room // max(1, blk_bytes))
                if frac < self._spill_free_frac and n_spill > 0:
                    for nd in pc.spill_candidates(n_spill):
                        if not off.spill_async(
                                ("pfx", nd.uid), self.pools, [nd.block],
                                self.bs, pc.host, hold_blocks=[],
                                on_land=functools.partial(
                                    pc.finish_spill, nd),
                                proactive=True):
                            pc.abort_spill(nd)
            depth = off.prefetch_depth()
            if depth:
                for req in itertools.islice(self.queue, depth):
                    if self.swap_pool is not None:
                        ent = self.swap_pool.get(req.req_id)
                        if ent is not None:
                            off.stage(self.swap_pool, req.req_id, ent)
                            continue
                    if pc is not None and pc.host is not None:
                        ctx = req.prompt + req.generated
                        for key, ent in pc.host_path_entries(
                                ctx, (len(ctx) - 1) // self.bs):
                            off.stage(pc.host, key, ent)
        if self.injector is not None and \
                self.injector.fires("offload_crash", self._step_idx):
            _flight.record("injected_offload_crash",
                           step=self._step_idx,
                           in_flight=off.held_blocks,
                           inflight_bytes=off.inflight_bytes)
            raise SimulatedCrash(
                f"injected offload crash at serving step "
                f"{self._step_idx}")

    def drain_offload(self) -> None:
        """Land every in-flight offload transfer NOW (blocking) — the
        run()-exit / quiescence hook, so a drained engine's ledger
        shows ``in_flight == 0`` and the host tiers hold exactly their
        committed entries."""
        if self.offload is not None:
            self.free_blocks.extend(self.offload.poll(block=True))

    def recover_crashed_step(self) -> None:
        """Recovery surface for a crashed ``step()`` (ResilientEngine):
        drop the poisoned in-flight wave — its tokens were never
        host-visible, so the stream stays exactly-once — and requeue
        every in-flight request from its traced host state for a
        recompute re-admission (the pools' contents are suspect, so the
        swap tier is bypassed). The device carry is rebuilt from host
        state at the next dispatch."""
        self._inflight = None
        self._pending_adm = []
        self._pending_swapin = []
        self._fresh_swapins = set()
        self._carry = None
        self._slots_dirty = True
        for slot in self._active_slots():
            self._free_slot(slot, requeue=True, swap=False)
        self._chunks = {}
        if self.offload is not None:
            # the poisoned-wave rule extends to transfers (r15): every
            # in-flight spill is abandoned (host reservations released,
            # nothing half-landed ever commits) and its custody blocks
            # return to the free list; staged prefetch buffers drop too
            # — the queued requests re-stage or recompute
            self.free_blocks.extend(self.offload.abandon())
        if self.prefix_cache is not None:
            # cached KV is as suspect as the rest of the pools: drop the
            # whole trie (host tier included) and recycle its blocks
            self.free_blocks.extend(self.prefix_cache.clear())

    def block_accounting(self) -> Dict[str, int]:
        """Device block-pool ledger: ``free + backed + cached +
        squeezed + in_flight == total`` at every step boundary, whatever
        mix of eviction / shed / preempt-swap / cache-spill /
        crash-requeue ran — the leak-regression invariant. ``backed``
        counts blocks a slot owns PRIVATELY; a cache-owned block counts
        once under ``cached`` however many slots pin it. ``in_flight``
        (r15) counts blocks custody-parked behind an async swap-out d2h
        still moving — a TRANSIENT term that is zero whenever no
        transfer is in flight, collapsing the ledger back to its 4-term
        form (a proactively spilling cache block stays under ``cached``:
        its node keeps it until reclaim). ``host_spilled_blocks``
        (prefix-cache blocks resident only in the host tier) and
        ``swapped_host_blocks`` ride along — those blocks were freed on
        device and are NOT in the sum.

        Speculative decoding (r13) adds NO terms: the draft's ``dk``/
        ``dv`` pools are indexed by the same physical block ids as the
        target's, so every block holding draft KV already IS one of
        free/backed/cached/squeezed — the invariant is
        model-count-independent (the chaos suite asserts it per step
        with spec on)."""
        pc = self.prefix_cache
        return {
            "total": self.nb - 1,
            "free": len(self.free_blocks),
            "backed": int(sum(int(self.n_alloc[i]) - len(self._pinned[i])
                              for i in range(self.N))),
            "cached": pc.device_blocks if pc is not None else 0,
            "squeezed": sum(len(b) for _, b in self._squeezed),
            "in_flight": (self.offload.held_blocks
                          if self.offload is not None else 0),
            "host_spilled_blocks": (pc.host_blocks if pc is not None
                                    else 0),
            "swapped_host_blocks": (self.swap_pool.swapped_blocks
                                    if self.swap_pool is not None else 0),
        }

    def _admit(self):
        """Admit every queued request a free slot and free blocks can
        take, then dispatch ONE batched prefill program for the whole
        wave (padded to max_slots rows and the wave's largest bucket, so
        the compiled-variant set is one per bucket — a serving burst can
        never hit a batch-size-shaped recompile). NO host sync: each
        first generated token is sampled inside the prefill program and
        rides to the host one decode call later (``_pending_adm`` → the
        next dispatch record).

        With the prefix cache on, each admission first matches the
        longest cached prefix at block granularity (capped at
        ``(len(ctx)-1)//bs`` so at least one token always prefills and
        yields the sampling hidden state), pins those blocks into the
        slot's table, and prefills ONLY the suffix. Suffixes longer than
        ``prefill_chunk`` enter chunked mode: the wave carries their
        first chunk and :meth:`_advance_chunks` feeds one chunk per step
        until the final chunk samples the first token."""
        wave = []           # rows: (slot, req, ctx, hist, piece, final)
        while self.queue and len(wave) < self.N:
            slot = next((i for i in range(self.N)
                         if self.slot_req[i] is None), None)
            if slot is None:
                break
            req = self.queue[0]
            ent = (self.swap_pool.get(req.req_id)
                   if self.swap_pool is not None else None)
            if ent is None and self.offload is not None \
                    and self.swap_pool is not None \
                    and self.offload.pending(req.req_id):
                # the request's swap-out is still in flight but its
                # re-admission is due NOW: land it (blocking — counted
                # as a stall) so the swap-in path sees a committed entry
                freed = self.offload.force_land(req.req_id)
                if freed:
                    self.free_blocks.extend(freed)
                ent = self.swap_pool.get(req.req_id)
            if ent is not None:
                # swap-in re-admission: restore the preempted KV blocks
                # from the host tier — no prefill, no sampled first token
                # (the tail of prompt+generated is the next decode input)
                if self._avail_blocks() < max(1, ent.n_blocks):
                    if not any(r is not None for r in self.slot_req) \
                            and not self._squeezed \
                            and not (self.offload is not None
                                     and self.offload.held_blocks):
                        raise RuntimeError(
                            f"request {req.req_id}: swap-in needs "
                            f"{ent.n_blocks} blocks but the pool only has "
                            f"{self.nb - 1} usable")
                    break                    # blocks busy: wait for frees
                self.queue.popleft()
                self._swap_in(slot, req, self.swap_pool.pop(req.req_id))
                continue
            if self.relay is not None and req.relay_key is not None:
                # disagg restore (r19): a prefill replica's relay entry
                # stands in for the whole prefill — the same batched h2d
                # scatter as a swap-in, bit-exact payload + scales. An
                # entry that vanished with its replica, or whose pool
                # names don't match this engine's (asymmetric draft
                # configs), degrades to a full prefill of the identical
                # context — streams match either way.
                rent = self.relay.get(req.relay_key)
                if rent is not None and set(rent.data) == set(self.pools) \
                        and rent.n_tokens == len(req.prompt) \
                        + len(req.generated) - 1:
                    if self._avail_blocks() < max(1, rent.n_blocks):
                        if not any(r is not None for r in self.slot_req) \
                                and not self._squeezed \
                                and not (self.offload is not None
                                         and self.offload.held_blocks):
                            raise RuntimeError(
                                f"request {req.req_id}: relay restore "
                                f"needs {rent.n_blocks} blocks but the "
                                f"pool only has {self.nb - 1} usable")
                        break            # blocks busy: wait for frees
                    self.queue.popleft()
                    self._swap_in(slot, req,
                                  self.relay.pop(req.relay_key))
                    _M_DISAGG_HANDOFFS.inc(outcome="restored")
                    continue
                self.relay.discard(req.relay_key)
                req.relay_key = None
                _M_DISAGG_HANDOFFS.inc(outcome="missing")
            ctx = req.prompt + req.generated   # re-admission continues
            true_len = len(ctx)
            nodes, cached_blocks = [], []
            if self.prefix_cache is not None:
                # longest cached prefix, pinned; host-resident blocks on
                # the path restore through the free list (one h2d each)
                nodes, cached_blocks = self.prefix_cache.match_and_pin(
                    ctx, (true_len - 1) // self.bs,
                    self._take_up_to, self._restore_blocks)
            m = len(nodes)
            hist = m * self.bs
            # only the blocks the true prompt occupies; the bucket's pad
            # tail scatters into the trash block (never read: causality)
            need = max(1, -(-true_len // self.bs)) - m
            if self._avail_blocks() < need:
                if nodes:
                    self.prefix_cache.unpin(nodes)
                if not any(r is not None for r in self.slot_req) \
                        and not self._squeezed \
                        and not (self.offload is not None
                                 and self.offload.held_blocks):
                    # (an injected pool_squeeze releases its hostage
                    # blocks in a step or two — starvation then is
                    # pressure, not an impossible request)
                    raise RuntimeError(
                        f"request {req.req_id}: prefill needs {need} blocks "
                        f"but the pool only has {self.nb - 1} usable — the "
                        "block pool is too small for this request")
                break                        # blocks busy: wait for frees
            self.queue.popleft()
            blocks = cached_blocks + self._take_up_to(need)
            self.table[slot, :len(blocks)] = blocks
            self.n_alloc[slot] = len(blocks)
            self.lengths[slot] = hist        # grows as pieces land
            self.slot_req[slot] = req
            self.admit_order.append(slot)
            self._pinned[slot] = nodes
            self._table_dirty = True
            self._slots_dirty = True
            if self.prefix_cache is not None:
                self.prefix_cache.note_lookup(hist)
            suffix = true_len - hist
            piece = (min(suffix, self.prefill_chunk)
                     if self.prefill_chunk else suffix)
            if _obs.enabled():
                # "admitted" first time, "resumed" after a preemption —
                # the tracer keys on whether this id was admitted before
                _rt.get_request_tracer().admitted(
                    req.req_id, slot=slot, context_tokens=true_len,
                    cached_tokens=hist)
            wave.append((slot, req, ctx, hist, piece,
                         piece == suffix))
        if wave:
            _M_ADMISSIONS.inc(len(wave))
            self._dispatch_prefill(wave)

    def _advance_chunks(self):
        """Feed every mid-prefill slot its next chunk — ONE chunk per
        slot per step, so long prefills interleave with the other slots'
        decode waves instead of monopolizing the step (bounded TTFT
        under mixed traffic). The final chunk samples the request's
        first token and hands the slot to the decode path."""
        if not self._chunks:
            return
        rows = []
        for slot in sorted(self._chunks):
            st = self._chunks[slot]
            req = self.slot_req[slot]
            if req is None or req.req_id != st["rid"]:
                self._chunks.pop(slot)     # freed since (defensive)
                continue
            ctx, pos = st["ctx"], st["pos"]
            piece = min(self.prefill_chunk, len(ctx) - pos)
            rows.append((slot, req, ctx, pos, piece,
                         pos + piece == len(ctx)))
        if rows:
            self._dispatch_prefill(rows)

    def _dispatch_prefill(self, rows):
        """Dispatch one compiled prefill program for a wave of context
        PIECES — full prompts, cache-hit suffixes, and chunk
        continuations mix freely in one call. Rows whose piece completes
        the context (``final``) keep their in-program-sampled first
        token (``_pending_adm``); chunk rows discard it and stay in
        ``_chunks``. The variant key (bucket, batch form, flags, history
        bucket) keeps the compiled family bounded — chunking and the
        cache extend the EXISTING (bucket, flags) cache with one
        log-bounded axis, not a new family."""
        bucket = self._bucket_for(max(piece for *_x, piece, _f in rows))
        # two batch variants only: 1 (steady-state churn admits one slot
        # at a time — full-width padding would pay max_slots× the prefill
        # FLOPs) and max_slots (bursts). Bounded compiles, bounded waste.
        B = 1 if len(rows) == 1 else self.N
        nbp = bucket // self.bs
        hist_blocks = max(hist // self.bs for _s, _r, _c, hist, _p, _f
                          in rows)
        pnbk = ((1 << (hist_blocks - 1).bit_length()) if hist_blocks
                else 0)
        toks = np.zeros((B, bucket), np.int32)
        blk_ids = np.zeros((B, nbp), np.int32)  # pad rows: all trash
        true_lens = np.ones(B, np.int32)
        hist_lens = np.zeros(B, np.int32)
        ctx_tbl = np.zeros((B, pnbk), np.int32) if pnbk else None
        temps = np.zeros(B, np.float32)
        top_ks = np.zeros(B, np.int32)
        top_ps = np.ones(B, np.float32)
        for i, (slot, req, ctx, hist, piece, final) in enumerate(rows):
            b0 = hist // self.bs
            nblk = -(-(hist + piece) // self.bs) - b0
            toks[i, :piece] = ctx[hist:hist + piece]
            blk_ids[i, :nblk] = self.table[slot, b0:b0 + nblk]
            true_lens[i] = piece
            hist_lens[i] = hist
            if pnbk and b0:
                ctx_tbl[i, :b0] = self.table[slot, :b0]
            if final:        # non-final rows sample a discarded argmax
                temps[i] = req.temperature
                top_ks[i] = req.top_k
                top_ps[i] = req.top_p
        finals = [r for _s, r, _c, _h, _p, final in rows if final]
        sampled = any(r.temperature > 0 for r in finals)
        flags = (sampled,
                 sampled and any(r.top_k > 0 for r in finals
                                 if r.temperature > 0),
                 sampled and any(r.top_p < 1.0 for r in finals
                                 if r.temperature > 0))
        self._key, sub = jax.random.split(self._key)
        wave_rids = [r.req_id for _s, r, _c, _h, _p, _f in rows]
        args = [self.params, jnp.asarray(toks), jnp.asarray(blk_ids),
                jnp.asarray(true_lens), self.pools,
                jnp.asarray(temps), jnp.asarray(top_ks),
                jnp.asarray(top_ps), sub]
        if pnbk:
            args += [jnp.asarray(hist_lens), jnp.asarray(ctx_tbl)]
        with trace_span("serving.prefill", bucket=bucket, batch=B,
                        wave=len(rows), prefix_bucket=pnbk * self.bs,
                        request_ids=wave_rids):
            tok_dev, self.pools = self._prefill_fn(
                bucket, B, flags, pnbk)(*args)
        if self._spec_on:
            # the SAME wave through the draft model, right behind the
            # target's call (pools chain through donation): both models'
            # KV now cover every prefilled position, so these slots
            # enter spec waves in sync. The draft's sampled token is
            # discarded — the target owns the stream.
            self._key, dsub = jax.random.split(self._key)
            dargs = [self.draft_params] + args[1:8] + [dsub] + args[9:]
            dargs[4] = self.pools
            with trace_span("serving.prefill", bucket=bucket, batch=B,
                            wave=len(rows), model="draft",
                            request_ids=wave_rids):
                _junk, self.pools = self._prefill_fn(
                    bucket, B, flags, pnbk, draft=True)(*dargs)
        tracer = _rt.get_request_tracer() if _obs.enabled() else None
        for i, (slot, req, ctx, hist, piece, final) in enumerate(rows):
            self.lengths[slot] = hist + piece
            if self._spec_on:
                self._draft_len[slot] = hist + piece
            if final:
                if self._chunks.pop(slot, None) is not None:
                    self._slots_dirty = True   # rejoins the decode mask
                # reference the WHOLE [B] first-token array + row index:
                # the readback then fetches one array per wave, not one
                # tiny transfer per admission (8 tunnel RTTs measured
                # per wave)
                self._pending_adm.append((slot, req.req_id, tok_dev, i))
            else:
                if slot not in self._chunks:
                    self._slots_dirty = True   # leaves the decode mask
                self._chunks[slot] = {"ctx": ctx, "pos": hist + piece,
                                      "rid": req.req_id}
            if tracer is not None:
                tracer.record(req.req_id, "prefill", bucket=bucket,
                              batch=B, chunk_start=hist, chunk=piece)
            if self.prefix_cache is not None \
                    and len(self._pinned[slot]) == hist // self.bs:
                # adopt this piece's FULL blocks into the trie (pinned:
                # the slot itself holds them); adoption stays contiguous
                # with the pinned head — a gap (another request cached
                # the same block first) ends adoption for this slot
                b0 = hist // self.bs
                full = (hist + piece) // self.bs
                if full > b0:
                    self._pinned[slot].extend(self.prefix_cache.extend(
                        ctx, b0,
                        [int(self.table[slot, j]) for j in range(b0, full)],
                        pin=True))

    def _emit(self, slot: int, tok: int) -> bool:
        """Record a generated token; free the slot when the request is done.
        Returns True if the request finished."""
        req = self.slot_req[slot]
        self.slot_out[slot].append(tok)
        n_gen = len(req.generated) + len(self.slot_out[slot])
        done = (req.eos_token_id is not None and tok == req.eos_token_id) \
            or n_gen >= req.max_new_tokens
        if done:
            self._free_slot(slot)
        return done

    def _ensure_backed(self, slot: int, lag: int = 0,
                       steps: Optional[int] = None) -> bool:
        """Back every block this slot's next ``decode_steps`` writes can
        touch (clamped to its remaining token budget — a near-finished slot
        must not reserve blocks it can never write). ``lag``: tokens the
        unread in-flight call may already have appended beyond the host's
        view of the length (pipelined dispatch); the horizon covers them
        too, since under-backing silently diverts K/V to the trash block.
        ``steps`` overrides the per-wave write horizon (a speculative
        wave commits up to ``spec_k`` tokens, not ``decode_steps``).
        Returns False if the pool is exhausted (caller preempts)."""
        req = self.slot_req[slot]
        remaining = req.max_new_tokens - len(req.generated) \
            - len(self.slot_out[slot])
        base = self.decode_steps if steps is None else steps
        steps = max(1, min(base + lag, remaining + lag))
        horizon = int(self.lengths[slot]) + steps - 1
        last_blk = min(horizon, self.max_model_len - 1) // self.bs
        need = last_blk + 1 - int(self.n_alloc[slot])
        if need <= 0:
            return True
        got = self._take_up_to(need)     # one reclaim sweep for the lot
        for blk in got:
            self.table[slot, int(self.n_alloc[slot])] = blk
            self.n_alloc[slot] += 1
            self._table_dirty = True
        return len(got) == need

    def _active_slots(self):
        return [i for i in range(self.N) if self.slot_req[i] is not None]

    def _decode_slots(self):
        """Slots the decode call covers: active and not mid-chunked-
        prefill (a chunking slot joins once its final chunk lands)."""
        return [i for i in range(self.N) if self.slot_req[i] is not None
                and i not in self._chunks]

    def _spec_safe(self) -> bool:
        """True iff dispatching the next decode call BEFORE reading the
        in-flight one cannot waste work: every slot in the in-flight
        snapshot is guaranteed still alive when it ends — no eos token to
        trip on, and budget strictly beyond the call's horizon. Otherwise
        the engine syncs first (cheaper than risking an all-done call or
        starving admission of a freed slot)."""
        rec = self._inflight
        for slot, rid in rec["snapshot"]:
            req = self.slot_req[slot]
            if req is None or req.req_id != rid:
                return False
            if req.eos_token_id is not None:
                return False
            if rec["rem_start"][slot] - self.decode_steps <= 0:
                return False
        return True

    def _back_or_preempt(self, steps: Optional[int] = None):
        """Back upcoming writes for every active slot; preempt the newest
        admissions while the pool is short (vLLM recompute policy). With
        an unread call in flight the host length lags by up to
        decode_steps — if generous backing fails, the pipeline is drained
        so preemption decisions see exact state. ``steps`` overrides the
        write horizon (speculative waves back ``spec_k`` positions and
        run with the pipeline already drained)."""
        emitted = []
        # chunking slots never appear here (_decode_slots excludes them;
        # their whole context was preallocated at admission — nothing to
        # back until they decode)
        for slot in list(self._decode_slots()):
            if self.slot_req[slot] is None:
                continue                      # already preempted as a victim
            while True:
                in_snap = self._inflight is not None and any(
                    s == slot for s, _ in self._inflight["snapshot"])
                if self._ensure_backed(slot,
                                       self.decode_steps if in_snap else 0,
                                       steps=steps):
                    break
                if self._inflight is not None:
                    # exact lengths before evicting anyone
                    emitted += self._process_inflight()
                    if self.slot_req[slot] is None:
                        break
                    continue
                if self.offload is not None \
                        and self.offload.held_blocks:
                    # blocks are custody-parked behind an in-flight
                    # spill: landing them (blocking) beats preempting
                    # ANOTHER victim — a cascade the async tier must
                    # never cause (held > 0 guarantees progress)
                    self.drain_offload()
                    continue
                victim = self.admit_order[-1]
                if victim == slot and len(self.admit_order) == 1 \
                        and not self._squeezed:
                    # alone and starved: nothing else will ever free a
                    # block — preempting ourselves would livelock. (Under
                    # an injected pool_squeeze the hostage blocks return
                    # in a step or two: self-preempt and wait instead.)
                    raise RuntimeError(
                        f"request {self.slot_req[slot].req_id}: the block "
                        f"pool ({self.nb - 1} usable blocks) is too small "
                        "to decode this request any further")
                self._free_slot(victim, requeue=True)
                if victim == slot:
                    break
        return emitted

    def _refresh_carry(self, active_slots):
        """Bring the device carry and per-slot vectors up to date.

        The carry CHAINS on device from call to call; host state is only
        injected where it is exact: a full rebuild when no call is unread
        (carry is None), or a per-slot scatter for freshly admitted slots
        (whose first token exists only on device). Freed slots are simply
        masked out via the active vector — their stale carry lanes are
        never read."""
        if self._carry is None:
            assert self._inflight is None, \
                "carry rebuild requires a drained pipeline"
            last = np.zeros(self.N, np.int32)
            budgets = np.zeros(self.N, np.int32)
            pend = {s for s, _, _, _ in self._pending_adm}
            for i in active_slots:
                req = self.slot_req[i]
                # swap-in slots continue from the context tail (their KV
                # was restored, not re-prefilled); pend slots get a
                # placeholder overwritten by _apply_admissions
                last[i] = self.slot_out[i][-1] if self.slot_out[i] else \
                    (req.generated[-1] if req.generated
                     else req.prompt[-1])
                budgets[i] = req.max_new_tokens - len(req.generated) \
                    - len(self.slot_out[i]) - (1 if i in pend else 0)
            self._key, sub = jax.random.split(self._key)
            self._carry = (jnp.asarray(last),
                           jnp.asarray(self.lengths, jnp.int32),
                           jnp.zeros(self.N, bool),
                           jnp.asarray(budgets), sub)
        if self._pending_adm:
            # one _apply_admissions call per wave array (usually one):
            # every operand shape is pinned to [max_slots], so nothing
            # here can ever compile inside the serving loop
            groups: Dict = {}
            for s, rid, arr, i in self._pending_adm:
                groups.setdefault(id(arr), (arr, []))[1].append((s, i))
            c_last, c_len, c_done, c_rem, c_key = self._carry
            for arr, items in groups.values():
                B = arr.shape[0]
                slot_of_row = np.full(B, self.N, np.int32)  # N → dropped
                upd = np.zeros(self.N, bool)
                lens_new = np.zeros(self.N, np.int32)
                rems_new = np.zeros(self.N, np.int32)
                for s, i in items:
                    slot_of_row[i] = s
                    upd[s] = True
                    lens_new[s] = int(self.lengths[s])
                    req = self.slot_req[s]
                    rems_new[s] = (req.max_new_tokens
                                   - len(req.generated) - 1)
                c_last, c_len, c_done, c_rem = _apply_admissions(
                    c_last, c_len, c_done, c_rem, arr,
                    jnp.asarray(slot_of_row), jnp.asarray(lens_new),
                    jnp.asarray(rems_new), jnp.asarray(upd))
            self._carry = (c_last, c_len, c_done, c_rem, c_key)
        if self._pending_swapin:
            # swap-in lanes: the same [max_slots]-pinned scatter as a
            # prefill wave, but the "wave token" is host-known (the tail
            # of prompt+generated — no prefill sampled a first token).
            # Also exact after a carry-None rebuild (idempotent values).
            c_last, c_len, c_done, c_rem, c_key = self._carry
            slot_of_row = np.full(self.N, self.N, np.int32)  # N → dropped
            upd = np.zeros(self.N, bool)
            toks = np.zeros(self.N, np.int32)
            lens_new = np.zeros(self.N, np.int32)
            rems_new = np.zeros(self.N, np.int32)
            for row, (s, rid) in enumerate(self._pending_swapin):
                req = self.slot_req[s]
                if req is None or req.req_id != rid:
                    continue          # freed again before any dispatch
                slot_of_row[row] = s
                upd[s] = True
                toks[row] = (req.generated[-1] if req.generated
                             else req.prompt[-1])
                lens_new[s] = int(self.lengths[s])
                rems_new[s] = req.max_new_tokens - len(req.generated)
            self._pending_swapin = []
            if upd.any():
                c_last, c_len, c_done, c_rem = _apply_admissions(
                    c_last, c_len, c_done, c_rem, jnp.asarray(toks),
                    jnp.asarray(slot_of_row), jnp.asarray(lens_new),
                    jnp.asarray(rems_new), jnp.asarray(upd))
                self._carry = (c_last, c_len, c_done, c_rem, c_key)
        if self._slots_dirty or self._slot_vecs is None:
            temps = np.zeros(self.N, np.float32)
            top_ks = np.zeros(self.N, np.int32)
            top_ps = np.ones(self.N, np.float32)
            eos_ids = np.full(self.N, -1, np.int32)
            active = np.zeros(self.N, bool)
            for i in active_slots:
                req = self.slot_req[i]
                temps[i] = req.temperature
                top_ks[i] = req.top_k
                top_ps[i] = req.top_p
                if req.eos_token_id is not None:
                    eos_ids[i] = req.eos_token_id
                active[i] = True
            self._slot_vecs = (jnp.asarray(active), jnp.asarray(temps),
                               jnp.asarray(top_ks), jnp.asarray(top_ps),
                               jnp.asarray(eos_ids))
            self._slots_dirty = False

    def _prefix_blocks(self, active_slots) -> int:
        """Pick the decode call's prefix horizon: the smallest
        power-of-two BLOCK COUNT covering ``max(lengths) + decode_steps``
        over the active slots — from the engine's exact host lengths,
        plus the pipeline lag (an unread in-flight call may already have
        appended up to ``decode_steps`` tokens beyond the host's view for
        the slots in its snapshot). Power-of-two rounding keeps the
        compiled-variant set logarithmic in ``mb`` while amortizing
        growth recompiles."""
        prev = self._inflight
        snap = ({s for s, _ in prev["snapshot"]} if prev is not None
                else ())
        hmax = need = 0
        for i in active_slots:
            h = int(self.lengths[i]) + (self.decode_steps if i in snap
                                        else 0)
            hmax = max(hmax, h)
            need = max(need, int(self.n_alloc[i]))
        horizon = min(hmax + self.decode_steps, self.max_model_len)
        need = max(1, need, -(-horizon // self.bs))
        nbk = 1 << (need - 1).bit_length()
        return min(nbk, self.mb)        # mb >= need, so the clamp is safe

    def _use_ragged(self) -> bool:
        """True when decode dispatches the ragged Pallas block-walk
        kernel: forced by ``decode_kernel="ragged"``, or picked by
        ``"auto"`` on a TPU backend — sharded or not (under a 'tp' mesh
        the walk shard_maps over the KV heads, r19). Off-TPU ``auto``
        keeps the bucketed dense-gather path (the kernel would run
        interpreted); the choice is counted per dispatch in
        serving_decode_kernel_total{path}."""
        return self.decode_kernel == "ragged" or (
            self.decode_kernel == "auto"
            and jax.default_backend() == "tpu")

    def _decode_path(self) -> str:
        """Kernel path for the next decode dispatch: ``"mega"`` (the
        r18 persistent fused megakernel — forced, or picked by
        ``"auto"`` on TPU at batch <= 4 where decode is launch-bound),
        ``"ragged"`` (the r12 block-walk kernel) or ``"bucketed"`` (the
        dense-gather fallback; the per-dispatch label refines to
        ``dense`` at the full-width bucket). An ineligible mega pick —
        a 'tp' mesh included (reason="mesh": GSPMD cannot partition the
        fused launch) — falls back to the ragged walk (bucketed
        off-TPU) and is COUNTED in serving_mega_fallback_total{reason}
        — never silent."""
        want_mega = (self.decode_kernel == "mega"
                     or (self.decode_kernel == "auto"
                         and self.mesh is None and self.N <= 4
                         and jax.default_backend() == "tpu"))
        if want_mega:
            ok, reason = mega_supported(
                self.params, self.config, n_slots=self.N,
                n_steps=self.decode_steps, block_size=self.bs,
                kv_int8=self.kv_int8, mesh=self.mesh)
            if ok:
                return "mega"
            _M_MEGA_FALLBACK.inc(reason=reason)
            if self.decode_kernel == "mega":
                return ("ragged" if jax.default_backend() == "tpu"
                        else "bucketed")
        return "ragged" if self._use_ragged() else "bucketed"

    def _pool_block_bytes(self, draft: bool = False) -> int:
        """Bytes one physical block occupies across one MODEL's pool
        entries and layers (int8 pools: payload + scales). The decode
        KV-traffic estimates count the target's entries only — the
        draft's ``dk``/``dv`` share the block ids but are read by the
        draft's own (cheaper) walks."""
        want = ("dk", "dv") if draft else ("k", "v", "ks", "vs")
        return sum(a.shape[0] * int(np.prod(a.shape[2:])) * a.dtype.itemsize
                   for n, a in self.pools.items() if n in want)

    def _dispatch_decode(self, active_slots):
        """Enqueue one multi-step decode call and record it as in-flight.
        rem_start tracks each slot's EXACT remaining budget at the start
        of the call (host bookkeeping lags; this chains from the previous
        record when pipelined)."""
        prev = self._inflight
        pend = {s for s, _, _, _ in self._pending_adm}
        rem_start = {}
        for i in active_slots:
            req = self.slot_req[i]
            if i in pend:
                rem_start[i] = req.max_new_tokens - len(req.generated) - 1
            elif i in self._fresh_swapins:
                # swap-in since the last dispatch: the slot id may be
                # recycled from the previous record — its budget comes
                # from host state, never the stale chained countdown
                rem_start[i] = req.max_new_tokens - len(req.generated)
            elif prev is not None and i in prev["rem_start"]:
                rem_start[i] = prev["rem_start"][i] - self.decode_steps
            else:
                rem_start[i] = req.max_new_tokens - len(req.generated) \
                    - len(self.slot_out[i])
        path = self._decode_path()
        ragged_like = path in ("mega", "ragged")
        # ragged/mega: the table ships at FULL width — one static shape
        # forever, lengths ride as a runtime operand (no bucket axis in
        # the compile key). Bucketed: host-side power-of-two slice.
        nbk = self.mb if ragged_like else self._prefix_blocks(active_slots)
        if self._table_dirty:
            self._table_dev = {}
            self._table_dirty = False
        tbl = self._table_dev.get(nbk)
        if tbl is None:
            # host-side slice: one tiny h2d per (table change, bucket)
            tbl = self._table_dev[nbk] = jnp.asarray(self.table[:, :nbk])
        c_last, c_len, c_done, c_rem, c_key = self._carry
        v_act, v_t, v_k, v_p, v_eos = self._slot_vecs
        reqs = [self.slot_req[i] for i in active_slots]
        sampled = any(r.temperature > 0 for r in reqs)
        flags = (sampled,
                 sampled and any(r.top_k > 0 for r in reqs
                                 if r.temperature > 0),
                 sampled and any(r.top_p < 1.0 for r in reqs
                                 if r.temperature > 0))
        vk = (path, flags) if ragged_like else (nbk, flags)
        decode = self._decode_cache.get(vk)
        if decode is None:
            # numerics gate baked per variant, like _prefill_fn (the key
            # stays ("mega"|"ragged"|bucket, flags): a mid-run flag flip
            # instruments new variants only — docs/observability.md)
            decode = self._decode_cache[vk] = jax.jit(
                functools.partial(_paged_decode, config=self.config,
                                  n_steps=self.decode_steps,
                                  sample_flags=flags,
                                  kv_int8=self.kv_int8,
                                  numerics=self.kv_int8 and _nm.active(),
                                  ragged=(path == "ragged"),
                                  mega=(path == "mega"),
                                  mesh=self.mesh),
                donate_argnums=(8,))
            _M_DECODE_RECOMPILES.inc()
        # path + traffic accounting (host ints — kept whether or not the
        # registry is on, so bench rows can report evidence without
        # perturbing the measured workload with full telemetry)
        if not ragged_like:
            path = "dense" if nbk >= self.mb else "bucketed"
        _M_DECODE_KERNEL.inc(path=path)
        _M_DECODE_VARIANTS.set(len(self._decode_cache))
        pb = self._pool_block_bytes()
        if ragged_like:
            # every scan step re-walks each slot's true-length blocks.
            # The kernel walks the DEVICE carry lengths, which lag the
            # host's view by up to decode_steps for slots chained
            # behind an unread call — add the lag (the _prefix_blocks
            # convention) so the estimate matches the true walk
            snap = ({s for s, _ in prev["snapshot"]}
                    if prev is not None else ())
            lens = {i: int(self.lengths[i])
                    + (self.decode_steps if i in snap else 0)
                    for i in active_slots}
            walk = sum(-(-ln // self.bs) for ln in lens.values())
            kv_call_bytes = walk * pb * self.decode_steps
            step_bytes = walk * pb
            horizon = max(lens.values(), default=0)
            bucket_tokens = -(-horizon // self.bs) * self.bs
        else:
            # one dense gather (pool read + dense write) + one dense
            # read per scan step, all at the bucket ceiling
            step_bytes = pb * self.N * nbk
            kv_call_bytes = step_bytes * (2 + self.decode_steps)
            bucket_tokens = nbk * self.bs
        self.kv_read_bytes_total += kv_call_bytes
        if _obs.enabled():
            _M_PREFIX_BUCKET.set(bucket_tokens)
            _M_KV_READ_BYTES.set(step_bytes)
            # cost-model FLOPs once per compiled variant (lower() is a
            # trace; allow_compile=False so MFU never compiles twice)
            if vk not in self._decode_flops:
                self._decode_flops[vk] = _perf.flops_of(
                    decode, self.params, c_last, c_len, c_done, c_rem,
                    c_key, v_act, tbl, self.pools, v_t, v_k, v_p, v_eos,
                    allow_compile=False)
            flops = self._decode_flops[vk]
            if flops and ragged_like:
                # the cost model can't see inside the Mosaic custom
                # call, and the walk's FLOPs depend on runtime lengths
                # anyway: add the prefix-attention term analytically —
                # QK + PV = 4*Hq*D per walked token, per layer, per
                # scan step (the ring/matmul/MLP terms are plain XLA
                # ops the cost analysis already counted)
                flops += (4 * self.config.num_heads * self.config.head_dim
                          * walk * self.bs * self.config.num_layers
                          * self.decode_steps)
            if flops and path == "mega":
                # the mega launch also swallows the hidden-state
                # matmuls the ragged path left visible to XLA — add
                # them analytically (2 FLOPs per weight element per
                # row per step; L is already in the stacked shapes)
                wels = sum(
                    int(np.prod((m["q"] if isinstance(m, dict)
                                 else m).shape))
                    for m in (self.params["layers"][n]
                              for n in ("wq", "wk", "wv", "wo",
                                        "w_gate", "w_up", "w_down")))
                flops += 2 * wels * self.N * self.decode_steps
            self._last_decode_flops = flops
        with trace_span("serving.decode", slots=len(active_slots),
                        steps=self.decode_steps,
                        # the true dispatched horizon (ragged: max real
                        # length; bucketed: the ceiling) — matches the
                        # serving_decode_prefix_bucket gauge, never the
                        # full-width table shape
                        prefix_bucket=bucket_tokens,
                        request_ids=[r.req_id for r in reqs]):
            (toks, c_last, c_len, c_done, c_rem, c_key,
             self.pools) = decode(
                self.params, c_last, c_len, c_done, c_rem, c_key, v_act,
                tbl, self.pools, v_t, v_k, v_p, v_eos)
        self._carry = (c_last, c_len, c_done, c_rem, c_key)
        self._inflight = {
            "toks": toks,
            "snapshot": [(i, self.slot_req[i].req_id)
                         for i in active_slots],
            "adm": self._pending_adm,
            "rem_start": rem_start,
        }
        self._pending_adm = []
        self._fresh_swapins = set()
        return prev

    # -- speculative decoding (r13): draft-then-verify waves ---------------
    def _spec_eligible(self, active) -> bool:
        """True when the next decode wave can run draft-then-verify:
        a draft is configured, every decode slot is GREEDY (the
        accept-longest-prefix rule is exact for argmax sampling only),
        and every slot's draft KV covers its full context (a slot
        advanced by the normal path while a sampled request shared its
        wave is stale until re-prefilled). Ineligible waves take the
        normal decode path — never wrong, at worst unaccelerated."""
        if not self._spec_on or not active:
            return False
        for i in active:
            req = self.slot_req[i]
            if req.temperature > 0:
                return False
            if self._draft_len[i] != self.lengths[i]:
                return False
        return True

    def _spec_bucket(self, active) -> int:
        """Power-of-two block count covering every wave slot's history
        PLUS the verify piece's k+1 writes — the verify table slice
        (and the draft's, off the ragged path). Same convention as
        :meth:`_prefix_blocks`, horizon ``spec_k + 1``."""
        hmax = need = 0
        for i in active:
            hmax = max(hmax, int(self.lengths[i]))
            need = max(need, int(self.n_alloc[i]))
        horizon = min(hmax + self.spec_k + 1, self.max_model_len)
        need = max(1, need, -(-horizon // self.bs))
        nbk = 1 << (need - 1).bit_length()
        return min(nbk, self.mb)

    def _spec_draft_fn(self, path: str):
        """The draft proposal program: ``_paged_decode`` at draft scale
        — draft config, ``spec_k`` fused steps, greedy flags, the
        ``dk``/``dv`` pool entries. One cached jit per kernel path (the
        bucketed table width re-specializes inside jax's own cache).
        On the mega path the draft is the second fusion target: the k
        sequential tiny steps run as ONE persistent multi-step launch
        (argmax, embed gather and bookkeeping in-kernel) instead of k
        scan iterations of L launches each."""
        key = path if path in ("mega", "ragged") else "bucketed"
        fn = self._spec_draft_cache.get(key)
        if fn is None:
            fn = self._spec_draft_cache[key] = jax.jit(
                functools.partial(
                    _paged_decode, config=self.draft_config,
                    n_steps=self.spec_k,
                    sample_flags=(False, False, False),
                    kv_int8=False, numerics=False,
                    ragged=(key == "ragged"), mega=(key == "mega"),
                    mega_multistep=(key == "mega"),
                    kv_prefix="d"),
                donate_argnums=(8,))
        return fn

    def _spec_verify_fn(self, nbk: int):
        """The batched verify program, one variant per history bucket —
        the log-bounded axis the chunked-prefill family already pays
        for, with no flag axis (verify is always greedy)."""
        fn = self._spec_verify_cache.get(nbk)
        if fn is None:
            fn = self._spec_verify_cache[nbk] = jax.jit(
                functools.partial(
                    _spec_verify, config=self.config,
                    n_spec=self.spec_k, kv_int8=self.kv_int8,
                    numerics=self.kv_int8 and _nm.active(),
                    max_model_len=self.max_model_len),
                donate_argnums=(6,))
        return fn

    def _spec_wave(self, active):
        """One draft-then-verify decode wave: the draft proposes
        ``spec_k`` tokens per slot in one multi-step call, the target
        scores every proposal in one prefill-shaped batched call (the
        draft grid feeds it device-to-device — no host hop between the
        two), and the host commits the longest agreeing prefix per slot
        — atomically into lengths, the block tables' backing, the
        prefix-cache adoption path (via ``_free_slot``/finish) and the
        emit stream. Capping commits at ``spec_k`` (the "bonus" token
        of classic speculative sampling is dropped) keeps the draft's
        KV in exact lockstep with the target's, so the rejected-suffix
        rollback is pure length bookkeeping: positions >=
        ``lengths`` in EITHER pool are unreadable and the next wave
        overwrites them.

        Runs with the pipeline drained — acceptance is a host decision,
        so the wave syncs once (its amortization is the k-for-1 verify,
        not call chaining), which is also why spec waves, unlike the
        chained path, compose with per-request eos."""
        from ..distributed.watchdog import guarded

        emitted = []
        if self._pending_adm:
            adm, self._pending_adm = self._pending_adm, []
            with guarded("serving-spec-readback"), \
                    trace_span("serving.readback"):
                emitted += self._flush_adm(adm)
        # swap-in carry lanes are host-known state; the spec wave reads
        # host state directly and invalidates the chained device carry
        self._pending_swapin = []
        self._fresh_swapins = set()
        self._carry = None
        self._slots_dirty = True
        emitted += self._back_or_preempt(steps=self.spec_k)
        active = self._decode_slots()
        if not active:
            return emitted
        k = self.spec_k
        N = self.N
        path = self._decode_path()
        if path == "mega":
            # the draft's eligibility envelope is its own (draft-sized
            # weights, multi-step epilogue buffers) — screen it
            # separately and count the fallback
            ok, reason = mega_supported(
                self.draft_params, self.draft_config, n_slots=N,
                n_steps=k, block_size=self.bs, kv_int8=False,
                multi_step=True)
            if not ok:
                _M_MEGA_FALLBACK.inc(reason="draft_" + reason)
                path = ("ragged" if jax.default_backend() == "tpu"
                        else "bucketed")
        ragged_like = path in ("mega", "ragged")
        nbk = self._spec_bucket(active)
        if self._table_dirty:
            self._table_dev = {}
            self._table_dirty = False

        def tdev(width):
            t = self._table_dev.get(width)
            if t is None:
                t = self._table_dev[width] = jnp.asarray(
                    self.table[:, :width])
            return t

        tbl_v = tdev(nbk)
        tbl_d = tdev(self.mb) if ragged_like else tbl_v
        last = np.zeros(N, np.int32)
        budgets = np.zeros(N, np.int32)
        act = np.zeros(N, bool)
        for i in active:
            req = self.slot_req[i]
            out = self.slot_out[i]
            last[i] = out[-1] if out else (
                req.generated[-1] if req.generated else req.prompt[-1])
            # the draft stops proposing at the slot's remaining budget:
            # tokens past it could never commit, and their writes would
            # clamp into real blocks near max_model_len
            budgets[i] = req.max_new_tokens - len(req.generated) \
                - len(out)
            act[i] = True
        walk = sum(-(-int(self.lengths[i]) // self.bs) for i in active)
        last_j = jnp.asarray(last)
        lens_j = jnp.asarray(self.lengths, jnp.int32)
        act_j = jnp.asarray(act)
        rids = [self.slot_req[i].req_id for i in active]
        draft_fn = self._spec_draft_fn(path)
        with trace_span("serving.spec_draft", slots=len(active), k=k,
                        request_ids=rids):
            (demitted, _dl, _dn, _dd, _db, _dk, self.pools) = draft_fn(
                self.draft_params, last_j, lens_j, jnp.zeros(N, bool),
                jnp.asarray(budgets), jax.random.PRNGKey(0), act_j,
                tbl_d, self.pools, jnp.zeros(N, jnp.float32),
                jnp.zeros(N, jnp.int32), jnp.ones(N, jnp.float32),
                jnp.full(N, -1, jnp.int32))
        verify_fn = self._spec_verify_fn(nbk)
        with trace_span("serving.spec_verify", slots=len(active), k=k,
                        prefix_bucket=nbk * self.bs, request_ids=rids):
            vtoks, self.pools = verify_fn(
                self.params, tbl_v, last_j, demitted, lens_j, act_j,
                self.pools)
        if self.injector is not None and \
                self.injector.fires("spec_verify_fail", self._step_idx):
            # chaos surface: a crash between the verify dispatch and
            # its readback. NOTHING of this wave is host-visible yet,
            # so recovery (drop + requeue from host state) rolls back
            # to the last committed token with zero stream divergence
            _flight.record("injected_spec_verify_fail",
                           step=self._step_idx)
            raise SimulatedCrash(
                f"injected speculative-verify failure at serving step "
                f"{self._step_idx}")
        with guarded("serving-spec-readback"), \
                trace_span("serving.readback"):
            d_host = np.asarray(jax.device_get(demitted))   # [k, N]
            v_host = np.asarray(jax.device_get(vtoks))      # [N, k+1]
        wave_prop = wave_acc = wave_commit = 0
        for i in active:
            req = self.slot_req[i]
            rid = req.req_id
            rem = req.max_new_tokens - len(req.generated) \
                - len(self.slot_out[i])
            prop = min(k, rem)              # what the draft really ran
            d, g = d_host[:, i], v_host[i]
            a = 0
            while a < prop and d[a] == g[a]:
                a += 1
            # commit the agreeing prefix + the target's one new token,
            # capped at k (the draft-KV lockstep invariant) and at the
            # budget; a == 0 still commits g[0] — a zero-acceptance
            # draft degenerates to one token per wave, never fewer
            c = min(a + 1, k, rem)
            wave_prop += prop
            wave_acc += a
            for j in range(c):
                tok = int(g[j])
                self.lengths[i] += 1        # verify wrote its K/V
                self._draft_len[i] += 1     # the draft wrote its too
                wave_commit += 1
                emitted.append((rid, tok))
                self._step_emitted.append((rid, tok))
                if self._emit(i, tok):
                    break                   # eos/budget mid-wave
        self.spec_waves += 1
        self.spec_verify_calls += 1
        self.spec_draft_steps += k
        self.spec_proposed += wave_prop
        self.spec_accepted += wave_acc
        self.spec_committed += wave_commit
        _M_SPEC_PROPOSED.inc(wave_prop)
        if wave_acc:
            _M_SPEC_ACCEPTED.inc(wave_acc)
        # KV-traffic estimate (host ints, registry-independent): the
        # draft's walks/gathers at draft-pool bytes + the verify's one
        # dense history gather at target-pool bytes
        pb_t, pb_d = self._pool_block_bytes(), \
            self._pool_block_bytes(draft=True)
        if ragged_like:
            self.kv_read_bytes_total += walk * pb_d * k
        else:
            self.kv_read_bytes_total += pb_d * N * nbk * (2 + k)
        self.kv_read_bytes_total += pb_t * N * nbk
        if _obs.enabled():
            _M_SPEC_ACCEPT_RATE.set(
                self.spec_accepted / max(1, self.spec_proposed))
            _M_SPEC_TOKENS_PER_WAVE.set(
                self.spec_committed / max(1, self.spec_verify_calls))
        return emitted

    def _process(self, rec):
        """Read back one decode record (first tokens of its admissions,
        then its emitted grid) and update host bookkeeping. Slots whose
        request changed since dispatch (finished or preempted) are
        skipped — their lanes are -1 padding or discarded speculation.

        The device_get readbacks below are the engine's blocking host
        syncs — the spot a hung collective or wedged device stalls a
        serving process. They run under the process watchdog when one is
        installed (distributed.watchdog.install): a long-lived server
        gets hang detection + emergency-hook checkpointing for free."""
        from ..distributed.watchdog import guarded

        if self.injector is not None and \
                self.injector.fires("readback_fail", self._step_idx):
            # the injectable stand-in for a wedged device / dead tunnel at
            # the engine's one blocking sync; ResilientEngine's recovery
            # contract (drop the wave, requeue from traced state) is
            # proven against exactly this raise
            _flight.record("injected_readback_fail", step=self._step_idx)
            raise SimulatedCrash(
                f"injected readback failure at serving step "
                f"{self._step_idx}")
        with guarded("serving-decode-readback"), \
                trace_span("serving.readback"):
            return self._process_guarded(rec)

    def _flush_adm(self, adm):
        """Read back a list of pending-admission first tokens
        ((slot, rid, wave_array, row) tuples) and commit them host-side
        — one readback per distinct wave array, not per admission."""
        emitted = []
        uniq = {}
        for slot, rid, arr, i in adm:
            uniq.setdefault(id(arr), (arr, []))[1].append(
                (slot, rid, i))
        host = {aid: np.asarray(jax.device_get(arr))
                for aid, (arr, _) in uniq.items()}
        first = [int(host[id(arr)][i]) for _, _, arr, i in adm]
        for (slot, rid, _, _), tok in zip(adm, first):
            req = self.slot_req[slot]
            if req is None or req.req_id != rid:
                continue              # preempted before its call ran
            tok = int(tok)
            emitted.append((rid, tok))
            # commit point: host-visible from here on — mirrored into
            # the step's salvage buffer so a crash later in this SAME
            # step still delivers it (ResilientEngine)
            self._step_emitted.append((rid, tok))
            self._emit(slot, tok)
        return emitted

    def _process_guarded(self, rec):
        emitted = []
        if rec["adm"]:
            emitted += self._flush_adm(rec["adm"])
        toks_host = np.asarray(jax.device_get(rec["toks"]))  # [K, N]
        for slot, rid in rec["snapshot"]:
            req = self.slot_req[slot]
            if req is None or req.req_id != rid:
                continue
            for k in range(toks_host.shape[0]):
                tok = int(toks_host[k, slot])
                if tok < 0:
                    break          # slot went done mid-scan
                self.lengths[slot] += 1     # its K/V was appended
                if self._spec_on:
                    # this slot advanced through the NORMAL decode path
                    # (a sampled slot was in the wave): its draft KV is
                    # now behind and can't catch up without a
                    # re-prefill — mark it out of the spec pool
                    self._draft_len[slot] = -1
                emitted.append((rid, tok))
                self._step_emitted.append((rid, tok))
                if self._emit(slot, tok):
                    break          # freed: later entries are -1 anyway
        return emitted

    def _process_inflight(self):
        rec, self._inflight = self._inflight, None
        return self._process(rec)

    def step(self):
        """Admit queued requests, keep the chip fed, and return the
        (req_id, token) pairs that became host-visible this call.

        Pipelined: decode call k+1 is dispatched BEFORE call k's tokens
        are read whenever no in-flight slot can finish mid-call
        (``_spec_safe``), so the readback latency — the dominant cost on
        a remote-attached chip — overlaps the next call's compute. The
        token stream therefore lags the chip by up to one call
        (decode_steps tokens per slot).

        Observability (FLAGS_obs_enabled): each call lands a
        ``serving.step`` span (prefill/decode/readback nested inside),
        a step-duration + tokens/sec observation, TTFT (with a
        request_id exemplar) for requests whose first token became
        visible, a per-request decode tick on the timeline, and the
        queue/slot/KV-pool gauges. Disabled, this wrapper costs one
        boolean check (plus the idle profiling-tick global read)."""
        # on-demand device-capture window boundary (near-zero when no
        # capture is armed; deliberately OUTSIDE the enabled() gate — a
        # capture is an explicit operator action, not ambient telemetry)
        _profiling.step_tick()
        if not _obs.enabled():
            return self._step_inner()
        t0 = time.perf_counter()
        with trace_span("serving.step"):
            emitted = self._step_inner()
        now = time.perf_counter()
        dt = now - t0
        _M_STEP_SECONDS.observe(dt)
        if emitted:
            _M_TOKENS.inc(len(emitted))
            if dt > 0:
                _M_TPS.observe(len(emitted) / dt)
            tracer = _rt.get_request_tracer()
            step_toks: Dict[int, int] = {}
            for rid, _tok in emitted:
                step_toks[rid] = step_toks.get(rid, 0) + 1
                t_add = self._obs_t_add.pop(rid, None)
                if t_add is not None:
                    tracer.record(rid, "first_token")
                    _rt.observe_with_exemplar(_M_TTFT, now - t_add, rid)
                    self._obs_t_first[rid] = now
            for rid, n in step_toks.items():
                # one decode tick per request per step (finished
                # requests already left the live table — no-op there)
                tracer.record(rid, "decode", tokens=n)
        if self._last_decode_flops:
            m = _perf.mfu(self._last_decode_flops, dt)
            if m is not None:
                _M_SERVING_MFU.set(m)
        _perf.update_serving_slo_gauges(_M_TTFT, _M_TPOT)
        _perf.update_hbm_gauges()
        _M_QUEUE_DEPTH.set(len(self.queue))
        _M_ACTIVE_SLOTS.set(sum(r is not None for r in self.slot_req))
        _M_KV_BLOCKS.set(self.nb - 1)
        _M_KV_USED.set(self.nb - 1 - len(self.free_blocks))
        if self.prefix_cache is not None:
            self.prefix_cache.update_gauges()
        # time-series sampler (r20): throttled by FLAGS_obs_ts_interval_s,
        # contention-free — a concurrent replica already sampling means
        # this step skips instead of waiting
        _ts.step_tick()
        return emitted

    def _step_inner(self):
        emitted = []
        self._step_emitted = []
        self._step_idx += 1
        # chaos + deadlines + front-door cancellations run before
        # admission: an injected squeeze shapes this step's block
        # budget, and an expired or disconnected request must not
        # occupy the slot a live one could take
        self._apply_faults()
        self._expire_deadlines()
        self._apply_cancels()
        # offload sweep AFTER cancellations (a dead request must not be
        # staged) and BEFORE admission (blocks a landed spill just freed
        # are allocatable THIS step; staged payloads meet their restore)
        self._offload_tick()
        # stale FLOPs from an earlier dispatch must not divide a
        # no-decode step's wall time (a bogus MFU spike on idle steps)
        self._last_decode_flops = None
        # one chunk per mid-prefill slot BEFORE admission/decode: the
        # chunk program and this step's decode wave share the step, so a
        # long prefill never monopolizes it (bounded TTFT for the slots
        # already decoding)
        self._advance_chunks()
        self._admit()
        if self.role == "prefill":
            # disagg (r19): no decode ever dispatches here — slots whose
            # prefill (chunked included) just completed hand their KV to
            # the relay and their stream to a decode replica
            return emitted + self._prefill_handoffs()
        if self._spec_on:
            active = self._decode_slots()
            if active and self._spec_eligible(active):
                # a spec wave syncs on its own acceptance decision:
                # drain the depth-1 pipeline first (host state must be
                # exact), re-admit into any slots that freed, then run
                # draft → verify → commit
                if self._inflight is not None:
                    emitted += self._process_inflight()
                    self._admit()
                    active = self._decode_slots()
                if active and self._spec_eligible(active):
                    return emitted + self._spec_wave(active)
        if self._inflight is not None and not self._spec_safe():
            emitted += self._process_inflight()
            self._admit()          # freed slots: refill before dispatching
        active = self._decode_slots()
        if not active:
            if self._inflight is not None:
                emitted += self._process_inflight()
            return emitted
        emitted += self._back_or_preempt()
        active = self._decode_slots()
        if not active:
            return emitted
        self._refresh_carry(active)
        prev = self._dispatch_decode(active)
        if prev is not None:
            emitted += self._process(prev)
        return emitted
