"""paddle_tpu.serving — continuous-batching LLM serving over paged KV.

Parity: the reference's blocked serving surface —
incubate/nn/functional/block_multihead_attention (python) over
phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu, i.e. a paged
KV cache with per-sequence block tables driven by an external serving loop.

TPU-native re-design (engine.py): instead of a fused CUDA kernel called from
user-managed buffers, the engine owns ONE jit-compiled decode step over a
static slot batch (any mix of live requests recompiles nothing), a host-side
block allocator with admission/preemption, and bucketed prefill programs.

Survivability layer (admission.py / kv_swap.py / resilient.py): bounded
admission with per-tenant rate limits and typed load shedding
(ShedError), per-request deadlines, preempt-to-host KV swap instead of
recompute, and a crash-recovering ResilientEngine wrapper — see
docs/serving.md §Degraded modes.

Prefix caching + chunked prefill (prefix_cache.py, r10): a refcounted
radix index over the block pool so shared system prompts and multi-turn
prefixes skip prefill (LRU eviction at refcount 0, host spill/restore),
and fixed-token prefill chunks interleaved with decode waves so TTFT
stays bounded under mixed traffic — see docs/serving.md §Prefix caching.

HTTP/SSE front door (http.py, r14): a stdlib asyncio HTTP/1.1 server
running the engine on a dedicated step-loop thread — SSE token
streaming with per-connection backpressure and slow-reader stall
cancellation, disconnect cancellation that frees a dropped client's KV
blocks within one engine step (terminal reason ``client_disconnected``),
ShedError mapped to 429/503 + Retry-After with per-tenant limits from
the ``X-Tenant`` header, graceful SIGTERM drain, and /healthz //readyz
for orchestrators — see docs/serving.md §Front door.

Draft-model speculative decoding (engine.py, r13): the engine hosts a
second, smaller llama (``draft_params``/``draft_config``) whose KV pools
share the target's physical blocks; greedy decode waves run
draft-then-verify — k draft proposals scored by ONE batched
prefill-shaped target call, longest agreeing prefix committed — for up
to ``spec_tokens`` tokens per target forward with token streams exactly
equal to non-speculative greedy — see docs/serving.md §Speculative
decoding.

Async two-tier KV offload (offload.py, r15): the host tiers stop
blocking the step thread — swap-outs and prefix-cache spills dispatch
non-blocking d2h (blocks accounted under a transient ``in_flight``
ledger term until the transfer lands at a step boundary), queued
restores prefetch h2d into staging buffers ahead of admission
(``prefetch_hit`` vs counted inline ``stall``), and refcount-0 cached
blocks spill proactively under pool pressure so reclaim stops paying
d2h inline — see docs/serving.md §KV offload tier.

Replica router (router.py, r16): a ``ReplicaRouter`` fronts N engine
replicas on dedicated step threads — prefix-affinity placement over the
same block-granular token keys the radix cache uses, tenant-aware
least-loaded fallback, step-progress heartbeats driving a
healthy/suspect/dead state machine with a half-open circuit breaker,
exactly-once failover resume (replay ``prompt + delivered`` on a
survivor, overlap deduped, greedy streams token-identical to an
uninterrupted run), and per-replica drain that migrates stragglers —
see docs/serving.md §Replica router.
"""
from .admission import (AdmissionConfig, AdmissionController, ShedError,
                        TokenBucket)
from .engine import LLMEngine, Request
from .http import HTTPFrontDoor
from .kv_swap import HostKVPool
from .offload import OffloadEngine
from .prefix_cache import PrefixCache
from .resilient import ResilientEngine
from .router import Replica, ReplicaRouter

__all__ = ["LLMEngine", "Request", "ResilientEngine", "AdmissionConfig",
           "AdmissionController", "ShedError", "TokenBucket",
           "HostKVPool", "PrefixCache", "HTTPFrontDoor", "OffloadEngine",
           "Replica", "ReplicaRouter"]
