"""Host-DRAM KV swap tier: preempt → swap out, re-admit → swap in.

The engine's original preemption policy is vLLM's recompute: a squeezed
slot frees its blocks and the request re-prefills ``prompt + generated``
from scratch on re-admission. Correct, but under a sustained pool
squeeze it turns into a preemption *storm* — every preemption throws
away computed KV and re-buys it at full prefill FLOPs, which squeezes
the pool harder (ROADMAP item 5; nncase's heterogeneous-storage LLM
deployment is the same diagnosis one tier down).

The TPU-native fix is a pinned host-RAM tier under HBM: a preempted
slot's pool blocks — the int8 payload AND its per-entry scales, so the
restore is bit-exact — are ``device_get`` into a bounded
:class:`HostKVPool`, and re-admission ``device_put``-scatters them back
into freshly allocated blocks instead of re-prefilling. A swap-in costs
one h2d copy of the blocks; a recompute costs the full prefill forward.
When the host pool is full, preemption falls back to recompute — the
tier degrades, it never breaks.

Accounting contract: swapped KV holds NO device blocks (they were freed
at swap-out) — the engine's device invariant stays
``free + backed + squeezed == pool size`` while the host tier tracks
its own bytes/blocks (``serving_kv_swap_host_bytes``).
"""
from __future__ import annotations

from typing import Dict, Optional

from ..observability.catalog import instrument as _instrument

__all__ = ["HostKVPool", "SwapEntry"]

_M_SWAP_OUT = _instrument("serving_kv_swap_out_total")
_M_SWAP_IN = _instrument("serving_kv_swap_in_total")
_M_SWAP_FALLBACK = _instrument("serving_kv_swap_fallback_total")
_M_SWAP_BYTES = _instrument("serving_kv_swap_host_bytes")
_M_PREFIX_BYTES = _instrument("serving_prefix_cache_host_bytes")


class SwapEntry:
    """One preempted request's KV blocks on the host: a dict of numpy
    arrays (one per engine pool entry — k/v payload plus ks/vs scales
    under int8 pools), each shaped ``[L, n_blocks, block_size, ...]``."""

    __slots__ = ("data", "n_tokens", "n_blocks", "nbytes")

    def __init__(self, data: Dict, n_tokens: int):
        self.data = data
        self.n_tokens = int(n_tokens)
        self.n_blocks = int(next(iter(data.values())).shape[1])
        self.nbytes = int(sum(a.nbytes for a in data.values()))


class HostKVPool:
    """Bounded pinned-host-RAM pool of swapped-out KV, keyed by req_id.

    ``put`` refuses (and counts a recompute fallback) rather than exceed
    ``capacity_bytes`` — the swap tier must never become the OOM.

    ``kind`` selects the metric surface: ``"swap"`` (default) emits the
    preemption-swap counters and ``serving_kv_swap_host_bytes``;
    ``"prefix"`` is the prefix-cache spill tier
    (:mod:`paddle_tpu.serving.prefix_cache`) — it drives only
    ``serving_prefix_cache_host_bytes`` (the cache counts its own
    spills under ``serving_prefix_cache_evictions_total``).
    """

    def __init__(self, capacity_bytes: int, kind: str = "swap"):
        if kind not in ("swap", "prefix"):
            raise ValueError(f"HostKVPool kind must be 'swap' or "
                             f"'prefix', got {kind!r}")
        self.capacity_bytes = int(capacity_bytes)
        self.kind = kind
        self._g_bytes = _M_SWAP_BYTES if kind == "swap" else _M_PREFIX_BYTES
        self._entries: Dict = {}
        self._bytes = 0

    # -- engine-facing ----------------------------------------------------
    def put(self, rid, data: Dict, n_tokens: int) -> bool:
        """Store one request's blocks; ``False`` (+ fallback counter) when
        the pool lacks room. A re-preemption of the same rid replaces its
        previous entry."""
        ent = SwapEntry(data, n_tokens)
        old = self._entries.pop(rid, None)
        if old is not None:
            self._bytes -= old.nbytes
        if self._bytes + ent.nbytes > self.capacity_bytes:
            if self.kind == "swap":
                _M_SWAP_FALLBACK.inc(reason="host_pool_full")
            self._g_bytes.set(self._bytes)
            return False
        self._entries[rid] = ent
        self._bytes += ent.nbytes
        if self.kind == "swap":
            _M_SWAP_OUT.inc()
        self._g_bytes.set(self._bytes)
        return True

    def get(self, rid) -> Optional[SwapEntry]:
        """Peek (no removal): the engine checks block availability before
        committing to the swap-in."""
        return self._entries.get(rid)

    def pop(self, rid) -> Optional[SwapEntry]:
        """Remove and return the entry — the swap-in commit point."""
        ent = self._entries.pop(rid, None)
        if ent is not None:
            self._bytes -= ent.nbytes
            if self.kind == "swap":
                _M_SWAP_IN.inc()
            self._g_bytes.set(self._bytes)
        return ent

    def discard(self, rid) -> None:
        """Drop a request's entry without a swap-in (it finished, shed,
        or expired while queued)."""
        ent = self._entries.pop(rid, None)
        if ent is not None:
            self._bytes -= ent.nbytes
            self._g_bytes.set(self._bytes)

    # -- accounting -------------------------------------------------------
    @property
    def bytes_used(self) -> int:
        return self._bytes

    @property
    def swapped_blocks(self) -> int:
        return sum(e.n_blocks for e in self._entries.values())

    def __len__(self) -> int:
        return len(self._entries)
