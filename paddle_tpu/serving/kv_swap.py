"""Host-DRAM KV swap tier: preempt → swap out, re-admit → swap in.

The engine's original preemption policy is vLLM's recompute: a squeezed
slot frees its blocks and the request re-prefills ``prompt + generated``
from scratch on re-admission. Correct, but under a sustained pool
squeeze it turns into a preemption *storm* — every preemption throws
away computed KV and re-buys it at full prefill FLOPs, which squeezes
the pool harder (ROADMAP item 5; nncase's heterogeneous-storage LLM
deployment is the same diagnosis one tier down).

The TPU-native fix is a pinned host-RAM tier under HBM: a preempted
slot's pool blocks — the int8 payload AND its per-entry scales, so the
restore is bit-exact — move into a bounded :class:`HostKVPool`, and
re-admission scatters them back into freshly allocated blocks instead
of re-prefilling. A swap-in costs one h2d copy of the blocks; a
recompute costs the full prefill forward. When the host pool is full,
preemption falls back to recompute — the tier degrades, it never
breaks.

r15 (serving/offload.py) makes the tier ASYNC: spills dispatch
non-blocking d2h and land at step boundaries, so the pool gained a
reservation protocol (:meth:`HostKVPool.reserve` /
:meth:`HostKVPool.commit` / :meth:`HostKVPool.unreserve`) that
guarantees a dispatched transfer can always commit, and
:class:`SwapEntry` carries an optional ``staged`` dict of
device-resident prefetch buffers the restore consumes without an
inline h2d wait.

Accounting contract: swapped KV holds NO device blocks (they were freed
at swap-out, or parked under the ledger's transient ``in_flight`` term
while the async d2h is still moving) — the engine's device invariant
stays ``free + backed + cached + squeezed (+ in_flight) == pool size``
while the host tier tracks its own bytes/blocks
(``serving_kv_swap_host_bytes``).
"""
from __future__ import annotations

from typing import Dict, Optional

from ..observability.catalog import instrument as _instrument

__all__ = ["HostKVPool", "SwapEntry"]

_M_SWAP_OUT = _instrument("serving_kv_swap_out_total")
_M_SWAP_IN = _instrument("serving_kv_swap_in_total")
_M_SWAP_FALLBACK = _instrument("serving_kv_swap_fallback_total")
_M_SWAP_BYTES = _instrument("serving_kv_swap_host_bytes")
_M_PREFIX_BYTES = _instrument("serving_prefix_cache_host_bytes")
_M_PREFIX_EVICT = _instrument("serving_prefix_cache_evictions_total")
_M_RELAY_BYTES = _instrument("serving_disagg_kv_relay_bytes")
_M_DISAGG_HANDOFFS = _instrument("serving_disagg_handoffs_total")


class SwapEntry:
    """One preempted request's KV blocks on the host: a dict of numpy
    arrays (one per engine pool entry — k/v payload plus ks/vs scales
    under int8 pools), each shaped ``[L, n_blocks, block_size, ...]``.

    ``staged`` (r15 prefetch): device-resident h2d copies of ``data``
    started ahead of admission by the offload engine — a restore that
    finds them consumes them directly (a ``prefetch_hit``) instead of
    paying the transfer inline. ``None`` when nothing is staged."""

    __slots__ = ("data", "n_tokens", "n_blocks", "nbytes", "staged")

    def __init__(self, data: Dict, n_tokens: int):
        self.data = data
        self.n_tokens = int(n_tokens)
        self.n_blocks = int(next(iter(data.values())).shape[1])
        self.nbytes = int(sum(a.nbytes for a in data.values()))
        self.staged = None


class HostKVPool:
    """Bounded pinned-host-RAM pool of swapped-out KV, keyed by req_id.

    ``put`` refuses (and counts a recompute fallback) rather than exceed
    ``capacity_bytes`` — the swap tier must never become the OOM.
    Reservations (:meth:`reserve`) participate in every capacity check,
    so an async spill dispatched against reserved room can never be
    refused at landing time.

    ``kind`` selects the metric surface: ``"swap"`` (default) emits the
    preemption-swap counters and ``serving_kv_swap_host_bytes``;
    ``"prefix"`` is the prefix-cache spill tier
    (:mod:`paddle_tpu.serving.prefix_cache`) — it drives
    ``serving_prefix_cache_host_bytes``, and a capacity refusal counts
    ``serving_prefix_cache_evictions_total{kind="drop_host_full"}`` (the
    CAUSE marker — the caller's subsequent subtree drop still counts its
    ``kind="drop"`` per node), so a saturated prefix host tier is
    visible on a dashboard instead of silently degrading to drops.
    ``"relay"`` (r19) is the disaggregated prefill→decode handoff tier
    SHARED between replicas — it drives
    ``serving_disagg_kv_relay_bytes``, and a capacity refusal counts
    ``serving_disagg_handoffs_total{outcome="relay_full"}`` (the decode
    replica then degrades to a full prefill of the handed-off context —
    streams identical, the transfer saving is lost).
    """

    def __init__(self, capacity_bytes: int, kind: str = "swap"):
        if kind not in ("swap", "prefix", "relay"):
            raise ValueError(f"HostKVPool kind must be 'swap', 'prefix' "
                             f"or 'relay', got {kind!r}")
        self.capacity_bytes = int(capacity_bytes)
        self.kind = kind
        self._g_bytes = (_M_SWAP_BYTES if kind == "swap"
                         else _M_PREFIX_BYTES if kind == "prefix"
                         else _M_RELAY_BYTES)
        self._entries: Dict = {}
        self._bytes = 0
        # incrementally maintained population counts: block_accounting
        # reads swapped_blocks at EVERY step boundary, so it must never
        # be an O(entries) walk (cross-checked against the walk in
        # tests, the PrefixCache incremental-count pattern)
        self._blocks = 0
        # outstanding async-spill reservations (offload engine): counted
        # by every capacity check so a dispatched transfer always fits
        self._resv: Dict = {}
        self._reserved = 0
        # host evidence (bench rows read this without the registry):
        # capacity refusals — swap: recompute fallbacks, prefix: drops
        self.refusals = 0

    def _count_refusal(self) -> None:
        self.refusals += 1
        if self.kind == "swap":
            _M_SWAP_FALLBACK.inc(reason="host_pool_full")
        elif self.kind == "prefix":
            _M_PREFIX_EVICT.inc(kind="drop_host_full")
        else:
            _M_DISAGG_HANDOFFS.inc(outcome="relay_full")

    # -- async-spill reservation protocol (r15) ---------------------------
    def reserve(self, rid, nbytes: int) -> bool:
        """Reserve room for an in-flight spill of ``nbytes`` keyed
        ``rid``; ``False`` (+ the kind's refusal counter) when the pool
        cannot fit it. Re-reserving a key replaces its reservation, and
        an existing entry under the same key counts as replaced."""
        nbytes = int(nbytes)
        self._reserved -= self._resv.pop(rid, 0)
        old = self._entries.get(rid)
        occupied = self._bytes + self._reserved \
            - (old.nbytes if old is not None else 0)
        if occupied + nbytes > self.capacity_bytes:
            self._count_refusal()
            return False
        self._resv[rid] = nbytes
        self._reserved += nbytes
        return True

    def commit(self, rid, data: Dict, n_tokens: int) -> bool:
        """Turn ``rid``'s reservation into a stored entry (the async
        spill's landing point). Fits by construction when the
        reservation was honest; falls through to :meth:`put` either
        way so the accounting stays in one place."""
        self._reserved -= self._resv.pop(rid, 0)
        return self.put(rid, data, n_tokens)

    def unreserve(self, rid) -> None:
        """Release a reservation whose transfer was cancelled or
        abandoned (terminal request, crash recovery)."""
        self._reserved -= self._resv.pop(rid, 0)

    @property
    def reserved_bytes(self) -> int:
        return self._reserved

    # -- engine-facing ----------------------------------------------------
    def put(self, rid, data: Dict, n_tokens: int) -> bool:
        """Store one request's blocks; ``False`` (+ the kind's refusal
        counter) when the pool lacks room. A re-preemption of the same
        rid replaces its previous entry."""
        ent = SwapEntry(data, n_tokens)
        old = self._entries.pop(rid, None)
        if old is not None:
            self._bytes -= old.nbytes
            self._blocks -= old.n_blocks
        # a reservation under THIS key is room held for this very
        # payload (an inline reclaim racing its own in-flight proactive
        # spill) — credit it, or the pool refuses a spill it reserved
        # for and the caller drops a perfectly spillable subtree
        resv_self = self._resv.get(rid, 0)
        if self._bytes + self._reserved - resv_self + ent.nbytes \
                > self.capacity_bytes:
            self._count_refusal()
            self._g_bytes.set(self._bytes)
            return False
        self._entries[rid] = ent
        self._bytes += ent.nbytes
        self._blocks += ent.n_blocks
        if self.kind == "swap":
            _M_SWAP_OUT.inc()
        self._g_bytes.set(self._bytes)
        return True

    def get(self, rid) -> Optional[SwapEntry]:
        """Peek (no removal): the engine checks block availability before
        committing to the swap-in."""
        return self._entries.get(rid)

    def pop(self, rid) -> Optional[SwapEntry]:
        """Remove and return the entry — the swap-in commit point."""
        ent = self._entries.pop(rid, None)
        if ent is not None:
            self._bytes -= ent.nbytes
            self._blocks -= ent.n_blocks
            if self.kind == "swap":
                _M_SWAP_IN.inc()
            self._g_bytes.set(self._bytes)
        return ent

    def discard(self, rid) -> None:
        """Drop a request's entry without a swap-in (it finished, shed,
        or expired while queued)."""
        ent = self._entries.pop(rid, None)
        if ent is not None:
            ent.staged = None
            self._bytes -= ent.nbytes
            self._blocks -= ent.n_blocks
            self._g_bytes.set(self._bytes)

    # -- accounting -------------------------------------------------------
    @property
    def bytes_used(self) -> int:
        return self._bytes

    @property
    def swapped_blocks(self) -> int:
        """Blocks resident in the tier — incrementally maintained (the
        engine ledger reads this per step; tests cross-check it against
        the entry walk)."""
        return self._blocks

    def __len__(self) -> int:
        return len(self._entries)
