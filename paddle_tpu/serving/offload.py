"""Async two-tier KV offload: background spill + prefetch behind decode.

PR 8 / PR 10 built the *synchronous* host-DRAM tier under HBM: every
preemption swap-out is a blocking ``device_get`` on the engine step
thread, and every swap-in / prefix-cache restore is a blocking h2d at
admission time — under sustained pool pressure the engine pays the
transfer latency inline with decode. nncase (PAPERS.md) deploys LLMs
across heterogeneous storage tiers; the TPU analog is pinned host RAM
under HBM, and the overlap idiom that hides collectives behind compute
(kernels/moe_dispatch.py's double-buffered halves) applies to the
memory hierarchy just as well. This module is the transfer engine that
makes the host pool a true SECOND TIER of the paged block pool:

- **Async spill (d2h).** A swap-out or a proactive cold-block spill
  dispatches a non-blocking device→host copy (a ``pinned_host``
  ``device_put`` where the backend has memory kinds — TPU — else
  ``copy_to_host_async``, else nothing: the landing ``np.asarray``
  blocks briefly, the version-shimmed fallback, same spirit as
  moe_dispatch's ``_shard_map`` shim). The spilled blocks stay
  device-resident and ACCOUNTED until the transfer lands: swap-out
  victims park their private blocks in this engine's custody (the
  ledger's transient ``in_flight`` term), proactively spilled cache
  nodes simply keep their block under ``cached``. The step-boundary
  :meth:`poll` sweep commits landed payloads into the
  :class:`~paddle_tpu.serving.kv_swap.HostKVPool` and returns custody
  blocks to the free list — the engine never blocks on a spill.
- **Prefetch-ahead restore (h2d).** When a swapped request nears the
  head of the admission queue, or a queued prompt's prefix walk would
  land on host-resident trie nodes, :meth:`stage` starts the h2d copy
  one or more steps EARLY into staging buffers attached to the host
  entry (``SwapEntry.staged``). A restore that finds its payload staged
  is a ``prefetch_hit`` (zero inline wait); one that must transfer
  inline is a counted ``stall`` with observed stall seconds —
  ``serving_kv_offload_{prefetch_hits,stalls,stall_seconds}_total``.
- **Exactness.** Transfers move every pool entry verbatim (int8
  payload AND per-entry scales), reservations guarantee a dispatched
  spill always fits its pool, and d2h slices are enqueued before any
  subsequent pool write in stream order — async streams are
  bit-identical to the sync path (test-enforced, bf16 and int8).
- **Crash semantics.** ResilientEngine's poisoned-wave rule extends to
  transfers: :meth:`abandon` drops every in-flight spill (host pool
  reservations released, custody blocks returned for the free list,
  staged buffers discarded) — a crashed step can never commit a
  half-landed payload.

``FLAGS_serve_kv_offload_sync`` forces the old inline behavior (the
forced-sync leg of the parity tests and the bench row); the engine's
``kv_offload="auto"|"async"|"sync"`` constructor knob overrides per
instance. See docs/serving.md §KV offload tier.
"""
from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.flags import define_flag, get_flag
from ..observability.catalog import instrument as _instrument

__all__ = ["OffloadEngine"]

define_flag("serve_kv_offload_sync", False,
            "force synchronous KV offload transfers (the pre-r15 inline "
            "d2h/h2d behavior): spills block the step thread, no "
            "prefetch staging, no proactive spill — the parity-test / "
            "bench reference leg")
define_flag("serve_kv_offload_prefetch_depth", 2,
            "how many queued requests from the admission-queue head the "
            "per-step prefetch sweep inspects for host-resident KV "
            "(swap entries / spilled prefix nodes) to stage h2d early; "
            "0 disables prefetch (every restore stalls inline)")
define_flag("serve_kv_offload_staging_bytes", 256 << 20,
            "device-byte budget for prefetch staging buffers (h2d "
            "copies started ahead of admission); staging requests past "
            "the budget wait for earlier stages to be consumed")
define_flag("serve_kv_offload_spill_free_frac", 0.25,
            "proactive-spill pressure threshold: when the allocatable "
            "block fraction falls below this, refcount-0 LRU cached "
            "blocks start background d2h spills so later reclaims free "
            "them without an inline transfer (doubled shed_free_frac "
            "wins when an AdmissionConfig sets one — the spiller must "
            "engage before the shedder)")
define_flag("serve_kv_offload_spill_batch", 4,
            "max proactive cold-block spills dispatched per engine step "
            "(bounds per-step d2h bandwidth spent on background "
            "spilling)")

_M_PREFETCH_HITS = _instrument("serving_kv_offload_prefetch_hits_total")
_M_STALLS = _instrument("serving_kv_offload_stalls_total")
_M_STALL_SECONDS = _instrument("serving_kv_offload_stall_seconds_total")
_M_INFLIGHT = _instrument("serving_kv_offload_inflight_bytes")
_M_PROACTIVE = _instrument("serving_kv_offload_proactive_spills_total")


def _start_d2h(arr):
    """Begin moving one device array to the host without blocking —
    version-shimmed like moe_dispatch's ``_shard_map``: a
    ``pinned_host`` ``device_put`` where the backend exposes memory
    kinds (TPU), else ``copy_to_host_async`` (jax 0.4.x), else nothing
    (the landing ``np.asarray`` then blocks briefly — the sync
    fallback). Returns the array whose readiness marks the landing."""
    try:
        dev = next(iter(arr.devices()))
        out = jax.device_put(arr, dev.memory("pinned_host"))
        return out
    except Exception:
        pass
    try:
        arr.copy_to_host_async()
    except Exception:
        pass
    return arr


def _is_ready(arr) -> bool:
    """Non-blocking landing probe; absent (exotic array types) the
    transfer is treated as landed and ``np.asarray`` pays the wait."""
    try:
        return bool(arr.is_ready())
    except Exception:
        return True


def _nbytes(arr) -> int:
    return int(np.prod(arr.shape)) * arr.dtype.itemsize


class _Spill:
    """One in-flight d2h batch: the device slices being copied, the
    blocks parked in custody until landing, and the host-pool
    reservation that guarantees the commit fits."""

    __slots__ = ("key", "arrays", "blocks", "n_tokens", "nbytes", "pool",
                 "on_land", "proactive")

    def __init__(self, key, arrays, blocks, n_tokens, nbytes, pool,
                 on_land, proactive):
        self.key = key
        self.arrays = arrays            # name -> device array (landing)
        self.blocks = list(blocks)      # custody (ledger in_flight term)
        self.n_tokens = int(n_tokens)
        self.nbytes = int(nbytes)
        self.pool = pool                # HostKVPool holding the reservation
        self.on_land = on_land          # fn(ok) or None
        self.proactive = proactive


class OffloadEngine:
    """Host-side bookkeeping for the async transfer tier. One instance
    per :class:`~paddle_tpu.serving.engine.LLMEngine`; every method runs
    on the engine's step thread (no locking needed — the engine's state
    machine is single-owner per step)."""

    def __init__(self, sync: Optional[bool] = None):
        # the sync decision is per-instance and frozen at construction:
        # flipping the flag mid-serve must not strand in-flight state
        self.sync = (bool(get_flag("serve_kv_offload_sync"))
                     if sync is None else bool(sync))
        self._spills: Dict = {}         # key -> _Spill
        self._staged: Dict = {}         # key -> (host_pool, entry)
        # host evidence counters (kept whether or not the metrics
        # registry is enabled — bench rows read these)
        self.prefetch_hits = 0
        self.stalls = 0
        self.stall_seconds = 0.0
        self.proactive_spills = 0

    # -- knobs (read per call so tests can set_flags mid-run) -------------
    def prefetch_depth(self) -> int:
        return max(0, int(get_flag("serve_kv_offload_prefetch_depth")))

    def spill_batch(self) -> int:
        return max(0, int(get_flag("serve_kv_offload_spill_batch")))

    def staging_budget(self) -> int:
        return max(0, int(get_flag("serve_kv_offload_staging_bytes")))

    # -- accounting --------------------------------------------------------
    @property
    def held_blocks(self) -> int:
        """Device blocks custody-parked behind in-flight d2h spills —
        the block ledger's transient ``in_flight`` term (zero whenever
        no transfer is in flight, collapsing the ledger back to its
        4-term form)."""
        return sum(len(t.blocks) for t in self._spills.values())

    @property
    def inflight_bytes(self) -> int:
        return sum(t.nbytes for t in self._spills.values())

    @property
    def staged_bytes(self) -> int:
        return sum(ent.nbytes for _p, ent in self._staged.values()
                   if ent.staged is not None)

    def _gauge(self) -> None:
        _M_INFLIGHT.set(self.inflight_bytes)

    # -- spill (d2h) -------------------------------------------------------
    def spill_async(self, key, pools: Dict, block_ids, n_tokens: int,
                    host_pool, hold_blocks: List[int],
                    on_land: Optional[Callable] = None,
                    proactive: bool = False) -> bool:
        """Dispatch one non-blocking d2h spill of ``block_ids`` from
        every pool entry (payload AND scales move verbatim — the restore
        is bit-exact). Reserves ``host_pool`` capacity up front so a
        dispatched transfer can always commit; ``False`` (nothing
        started, the pool's refusal counters fired) when it cannot fit.

        ``hold_blocks`` are parked in this engine's custody until the
        transfer lands (the ledger's ``in_flight`` term) — pass ``[]``
        for spills whose source keeps its block (proactive cache
        spills). In sync mode the transfer completes inline (blocking
        d2h + commit) and nothing is ever held."""
        idx = jnp.asarray(np.asarray(block_ids, np.int32))
        arrays = {name: pool[:, idx] for name, pool in pools.items()}
        nbytes = sum(_nbytes(a) for a in arrays.values())
        if not host_pool.reserve(key, nbytes):
            return False
        if proactive:
            self.proactive_spills += 1
            _M_PROACTIVE.inc()
        if self.sync:
            data = {n: np.asarray(jax.device_get(a))
                    for n, a in arrays.items()}
            host_pool.commit(key, data, n_tokens)
            if on_land is not None:
                on_land(True)
            return True
        # keep the array the transfer actually lands in: on the
        # pinned_host path device_put returns a NEW (host-memory) array
        # — np.asarray on it at landing is a cheap view, not a second
        # d2h of the original device slice
        arrays = {n: _start_d2h(a) for n, a in arrays.items()}
        self._spills[key] = _Spill(key, arrays, hold_blocks, n_tokens,
                                   nbytes, host_pool, on_land, proactive)
        self._gauge()
        return True

    def pending(self, key) -> bool:
        return key in self._spills

    def _land(self, t: _Spill) -> List[int]:
        data = {n: np.asarray(a) for n, a in t.arrays.items()}
        t.pool.commit(t.key, data, t.n_tokens)
        if t.on_land is not None:
            t.on_land(True)
        return t.blocks

    def poll(self, block: bool = False) -> List[int]:
        """The step-boundary completion sweep: commit every landed spill
        into its host pool and return the custody blocks the caller must
        append to the free list. ``block=True`` waits for everything
        (the run()-exit / test-quiescence drain). Also prunes staging
        records whose host entry was consumed or discarded."""
        freed: List[int] = []
        for key in list(self._spills):
            t = self._spills[key]
            if block or all(_is_ready(a) for a in t.arrays.values()):
                del self._spills[key]
                freed.extend(self._land(t))
        for key in list(self._staged):
            pool, ent = self._staged[key]
            if ent.staged is None or pool.get(key) is not ent:
                ent.staged = None          # release the device buffers
                del self._staged[key]
        self._gauge()
        return freed

    def force_land(self, key) -> Optional[List[int]]:
        """Land one in-flight spill NOW (blocking) — admission reached a
        request whose swap-out has not landed yet; the payload commits
        into the transfer's own recorded pool. The observed wait counts
        toward stall seconds (the caller's restore counts the one stall
        event). Returns the custody blocks to free, or ``None`` when no
        such transfer exists."""
        t = self._spills.pop(key, None)
        if t is None:
            return None
        t0 = time.perf_counter()
        blocks = self._land(t)
        # seconds only: the caller's swap-in counts the ONE stall event
        # for this admission (its inline h2d) — counting here too would
        # bill a force-landed restore as two stalls
        self.note_stall(time.perf_counter() - t0, n=0)
        self._gauge()
        return blocks

    def cancel(self, key) -> List[int]:
        """Drop one in-flight spill (its request went terminal): the
        host-pool reservation is released and the custody blocks return
        to the caller for the free list."""
        t = self._spills.pop(key, None)
        if t is None:
            return []
        t.pool.unreserve(key)
        if t.on_land is not None:
            t.on_land(False)
        self._gauge()
        return t.blocks

    def abandon(self) -> List[int]:
        """Crash recovery: drop EVERY in-flight spill and staging buffer
        (the poisoned-wave rule extended to transfers — a crashed step
        must not commit a half-landed payload). Returns all custody
        blocks for the free list."""
        freed: List[int] = []
        for t in self._spills.values():
            t.pool.unreserve(t.key)
            if t.on_land is not None:
                t.on_land(False)
            freed.extend(t.blocks)
        self._spills = {}
        for _pool, ent in self._staged.values():
            ent.staged = None
        self._staged = {}
        self._gauge()
        return freed

    # -- prefetch staging (h2d) --------------------------------------------
    def stage(self, host_pool, key, ent) -> bool:
        """Start the h2d copy of one host entry's payload into staging
        buffers attached to the entry (``SwapEntry.staged``) so the
        restore that eventually consumes it finds the data already
        device-resident (a ``prefetch_hit``). No-ops in sync mode, when
        already staged, or past the staging budget."""
        if self.sync or ent.staged is not None:
            return False
        if self.staged_bytes + ent.nbytes > self.staging_budget():
            return False
        # jnp.asarray enqueues the h2d without waiting on it; the
        # consuming scatter orders behind it by data dependency
        ent.staged = {n: jnp.asarray(np.asarray(a))
                      for n, a in ent.data.items()}
        self._staged[key] = (host_pool, ent)
        return True

    # -- restore outcome counters ------------------------------------------
    def note_hit(self, n: int = 1) -> None:
        self.prefetch_hits += n
        _M_PREFETCH_HITS.inc(n)

    def note_stall(self, seconds: float, n: int = 1) -> None:
        self.stalls += n
        self.stall_seconds += float(seconds)
        _M_STALLS.inc(n)
        _M_STALL_SECONDS.inc(float(seconds))
