"""Native runtime loader (C++ pieces, ctypes-bound).

The reference's runtime is C++ end-to-end; on TPU the device path is XLA, and
the host-side pieces that stay native live in csrc/ptpu_runtime.cpp
(TCPStore rendezvous, GIL-free batch collation). Built on first use with g++
and cached next to the source.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO, "csrc", "ptpu_runtime.cpp")
_SO = os.path.join(_REPO, "csrc", "libptpu_runtime.so")

_lock = threading.Lock()
_lib = None
_build_error: str | None = None


def _build() -> None:
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-pthread", _SRC, "-o", _SO]
    subprocess.run(cmd, check=True, capture_output=True, text=True)


def native_lib():
    """Load (building if needed) the native runtime; returns the ctypes CDLL
    or raises RuntimeError with the build error."""
    global _lib, _build_error
    with _lock:
        if _lib is not None:
            return _lib
        if _build_error is not None:
            raise RuntimeError(_build_error)
        try:
            try:
                if (not os.path.exists(_SO)
                        or os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
                    _build()
                lib = ctypes.CDLL(_SO)
            except Exception:
                # a checked-in .so built on another image may refuse to
                # load here (GLIBCXX/ABI skew): rebuild from source once
                # and retry before declaring the runtime unavailable
                _build()
                lib = ctypes.CDLL(_SO)
        except Exception as e:  # keep the framework importable without g++
            _build_error = f"native runtime unavailable: {e}"
            raise RuntimeError(_build_error) from e
        lib.ptpu_store_server_start.restype = ctypes.c_void_p
        lib.ptpu_store_server_start.argtypes = [ctypes.c_int]
        lib.ptpu_store_server_start2.restype = ctypes.c_void_p
        lib.ptpu_store_server_start2.argtypes = [ctypes.c_int, ctypes.c_char_p]
        lib.ptpu_store_server_port.restype = ctypes.c_int
        lib.ptpu_store_server_port.argtypes = [ctypes.c_void_p]
        lib.ptpu_store_server_stop.argtypes = [ctypes.c_void_p]
        lib.ptpu_store_client_connect.restype = ctypes.c_void_p
        lib.ptpu_store_client_connect.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_double]
        lib.ptpu_store_client_close.argtypes = [ctypes.c_void_p]
        lib.ptpu_store_set.restype = ctypes.c_int
        lib.ptpu_store_set.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int]
        lib.ptpu_store_get.restype = ctypes.c_int
        lib.ptpu_store_get.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int]
        lib.ptpu_store_wait.restype = ctypes.c_int
        lib.ptpu_store_wait.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int]
        lib.ptpu_store_add.restype = ctypes.c_longlong
        lib.ptpu_store_add.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_longlong]
        lib.ptpu_gather_rows.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_longlong), ctypes.c_int,
            ctypes.c_longlong, ctypes.c_char_p, ctypes.c_int]
        _lib = lib
        return _lib


def native_available() -> bool:
    try:
        native_lib()
        return True
    except RuntimeError:
        return False
