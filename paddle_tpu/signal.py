"""paddle.signal parity: stft / istft (reference: python/paddle/signal.py)."""
from __future__ import annotations

import jax.numpy as jnp

from .ops.dispatch import apply
from .ops.creation import _t

__all__ = ["stft", "istft", "frame", "overlap_add"]


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Slice into overlapping frames along the last axis → [..., frames, frame_length]."""
    def fn(v):
        n = v.shape[-1]
        num = 1 + (n - frame_length) // hop_length
        idx = (jnp.arange(frame_length)[None, :]
               + hop_length * jnp.arange(num)[:, None])
        return v[..., idx]
    return apply("frame", fn, _t(x))


def overlap_add(x, hop_length, axis=-1, name=None):
    def fn(v):
        *lead, num, fl = v.shape
        n = fl + hop_length * (num - 1)
        out = jnp.zeros(tuple(lead) + (n,), v.dtype)
        for i in range(num):  # static python loop (num is static)
            out = out.at[..., i * hop_length:i * hop_length + fl].add(v[..., i, :])
        return out
    return apply("overlap_add", fn, _t(x))


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False, onesided=True,
         name=None):
    """parity: paddle.signal.stft — returns [..., n_fft//2+1, frames] complex."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft

    def fn(v, w=None):
        if center:
            pad = n_fft // 2
            v = jnp.pad(v, [(0, 0)] * (v.ndim - 1) + [(pad, pad)],
                        mode=pad_mode)
        n = v.shape[-1]
        num = 1 + (n - n_fft) // hop_length
        idx = (jnp.arange(n_fft)[None, :]
               + hop_length * jnp.arange(num)[:, None])
        frames = v[..., idx]                       # [..., frames, n_fft]
        if w is not None:
            if win_length < n_fft:
                lp = (n_fft - win_length) // 2
                w = jnp.pad(w, (lp, n_fft - win_length - lp))
            frames = frames * w
        spec = jnp.fft.rfft(frames, axis=-1) if onesided \
            else jnp.fft.fft(frames, axis=-1)
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        return jnp.swapaxes(spec, -1, -2)          # [..., freq, frames]

    if window is not None:
        return apply("stft", fn, _t(x), _t(window))
    return apply("stft", fn, _t(x))


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft

    def fn(spec, w=None):
        spec = jnp.swapaxes(spec, -1, -2)          # [..., frames, freq]
        if normalized:
            spec = spec * jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        frames = (jnp.fft.irfft(spec, n=n_fft, axis=-1) if onesided
                  else jnp.fft.ifft(spec, axis=-1).real)
        if w is None:
            wv = jnp.ones((n_fft,), frames.dtype)
        else:
            wv = w
            if win_length < n_fft:
                lp = (n_fft - win_length) // 2
                wv = jnp.pad(wv, (lp, n_fft - win_length - lp))
        frames = frames * wv
        *lead, num, fl = frames.shape
        n = fl + hop_length * (num - 1)
        out = jnp.zeros(tuple(lead) + (n,), frames.dtype)
        norm = jnp.zeros((n,), frames.dtype)
        for i in range(num):
            sl = slice(i * hop_length, i * hop_length + fl)
            out = out.at[..., sl].add(frames[..., i, :])
            norm = norm.at[sl].add(wv * wv)
        out = out / jnp.maximum(norm, 1e-10)
        if center:
            pad = n_fft // 2
            out = out[..., pad:out.shape[-1] - pad]
        if length is not None:
            out = out[..., :length]
        return out

    if window is not None:
        return apply("istft", fn, _t(x), _t(window))
    return apply("istft", fn, _t(x))
