"""AMP O1 op lists (parity: python/paddle/amp/amp_lists.py).

White list: matmul/conv-class ops that are numerically safe and fast in
bf16 on the MXU. Black list: reductions/softmax/norm ops kept in fp32.
"""

WHITE_LIST = {
    "matmul", "mm", "bmm", "conv2d", "conv1d", "conv3d", "conv2d_transpose",
    "einsum", "linear", "addmm", "flash_attention",
}

BLACK_LIST = {
    "exp", "log", "log2", "log10", "log1p", "mean", "sum", "softmax",
    "log_softmax", "cross_entropy", "layer_norm", "rms_norm", "batch_norm",
    "group_norm", "norm", "p_norm", "logsumexp", "erf", "erfinv", "pow",
    "square", "reciprocal", "rsqrt", "cos_sim", "softmax_with_cross_entropy",
    "cast",
}
