"""GradScaler — dynamic loss scaling.

Parity: python/paddle/amp/grad_scaler.py:657. With bfloat16 (the TPU-native amp
dtype) scaling is unnecessary and the scaler becomes a transparent pass-through
(enable=False default mirrors that); the fp16 dynamic-scaling math is fully
implemented for API parity.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np


class GradScaler:
    def __init__(
        self,
        enable: bool = True,
        init_loss_scaling: float = 2.0 ** 15,
        incr_ratio: float = 2.0,
        decr_ratio: float = 0.5,
        incr_every_n_steps: int = 1000,
        decr_every_n_nan_or_inf: int = 1,
        use_dynamic_loss_scaling: bool = True,
    ):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n_nan_or_inf = decr_every_n_nan_or_inf
        self._use_dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False

    def scale(self, var):
        if not self._enable:
            return var
        return var * self._scale

    def _grads_of(self, optimizer):
        for p in optimizer._parameter_list:
            if p.grad is not None:
                yield p

    def unscale_(self, optimizer):
        if not self._enable:
            return
        inv = 1.0 / self._scale
        found = False
        for p in self._grads_of(optimizer):
            g = p.grad._value * inv
            if not bool(jnp.all(jnp.isfinite(g))):
                found = True
            from ..core.tensor import Tensor

            p.grad = Tensor(g)
        self._found_inf = found

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self.update()

    def minimize(self, optimizer, loss):
        self.step(optimizer)

    def update(self):
        if not (self._enable and self._use_dynamic):
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every_n_nan_or_inf:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every_n_steps:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._use_dynamic

    def get_loss_scaling(self):
        return self._scale

    def set_init_loss_scaling(self, v):
        self._scale = float(v)

    def state_dict(self):
        return {
            "scale": self._scale,
            "incr_ratio": self._incr_ratio,
            "decr_ratio": self._decr_ratio,
            "incr_every_n_steps": self._incr_every_n_steps,
            "decr_every_n_nan_or_inf": self._decr_every_n_nan_or_inf,
            "good_steps": self._good_steps,
            "bad_steps": self._bad_steps,
        }

    def load_state_dict(self, state):
        self._scale = state["scale"]
        self._good_steps = state.get("good_steps", 0)
        self._bad_steps = state.get("bad_steps", 0)
