"""AMP debugging utilities.

Parity: python/paddle/amp/debugging.py — check_numerics, operator stats
collection (enable/disable_operator_stats_collection, collect_operator_stats)
and the accuracy-compare workflow. TPU-native: hooks ride the op-dispatch
path (ops/dispatch.py) — the same place the reference instruments its
ad_funcs.
"""
from __future__ import annotations

import contextlib
from collections import defaultdict
from enum import Enum
from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..framework import flags as _flags

__all__ = [
    "DebugMode", "check_numerics", "enable_operator_stats_collection",
    "disable_operator_stats_collection", "collect_operator_stats",
    "enable_tensor_checker", "disable_tensor_checker", "TensorCheckerConfig",
]


class DebugMode(Enum):
    CHECK_NAN_INF_AND_ABORT = 0
    CHECK_NAN_INF = 1
    CHECK_ALL = 4


class TensorCheckerConfig:
    def __init__(self, enable=True, debug_mode=DebugMode.CHECK_NAN_INF_AND_ABORT,
                 output_dir=None, checked_op_list=None,
                 skipped_op_list=None, debug_step=None, stack_height_limit=1):
        self.enable = enable
        self.debug_mode = debug_mode
        self.output_dir = output_dir
        self.checked_op_list = checked_op_list
        self.skipped_op_list = skipped_op_list


def check_numerics(tensor, op_type: str = "", var_name: str = "",
                   debug_mode=DebugMode.CHECK_NAN_INF_AND_ABORT):
    """Count nan/inf in a tensor; abort per debug_mode (parity:
    amp/debugging.py check_numerics). Returns (num_nan, num_inf, num_zero)."""
    v = tensor._value if isinstance(tensor, Tensor) else jnp.asarray(tensor)
    from ..framework.dtype import np_is_floating
    vf = v.astype(jnp.float32) if np_is_floating(v.dtype) else None
    if vf is None:
        z = jnp.asarray(0)
        return Tensor(z), Tensor(z), Tensor(z)
    n_nan = jnp.sum(jnp.isnan(vf)).astype(jnp.int32)
    n_inf = jnp.sum(jnp.isinf(vf)).astype(jnp.int32)
    n_zero = jnp.sum(vf == 0).astype(jnp.int32)
    if debug_mode == DebugMode.CHECK_NAN_INF_AND_ABORT and \
            (int(n_nan) or int(n_inf)):
        raise FloatingPointError(
            f"[check_numerics] op={op_type or '?'} var={var_name or '?'}: "
            f"{int(n_nan)} nan, {int(n_inf)} inf")
    return Tensor(n_nan), Tensor(n_inf), Tensor(n_zero)


# -- operator stats ---------------------------------------------------------

_collecting = False
_stats: Dict[str, Dict[str, int]] = defaultdict(lambda: defaultdict(int))


def _record_op(name: str, out_vals) -> None:
    if not _collecting:
        return
    for v in out_vals:
        d = np.dtype(v.dtype)
        _stats[name][d.name] += 1


def enable_operator_stats_collection() -> None:
    """Start counting per-op dtype calls (parity: the reference's low/high
    precision op lists report)."""
    global _collecting
    _stats.clear()
    _collecting = True


def disable_operator_stats_collection() -> None:
    """Stop collecting and print the per-dtype op table."""
    global _collecting
    _collecting = False
    print("<" + "-" * 60 + ">")
    print(f"{'op':<30}{'calls by dtype'}")
    for op, per in sorted(_stats.items()):
        row = ", ".join(f"{k}:{v}" for k, v in sorted(per.items()))
        print(f"{op:<30}{row}")
    print("<" + "-" * 60 + ">")


@contextlib.contextmanager
def collect_operator_stats():
    enable_operator_stats_collection()
    try:
        yield
    finally:
        disable_operator_stats_collection()


def operator_stats() -> Dict[str, Dict[str, int]]:
    return {k: dict(v) for k, v in _stats.items()}


# -- tensor checker (global nan/inf scan switch) ----------------------------

def enable_tensor_checker(config: TensorCheckerConfig) -> None:
    """parity: amp/debugging.py enable_tensor_checker — turns on the
    dispatch-path nan/inf scan (FLAGS_check_nan_inf analogue)."""
    if config.enable:
        _flags.set_flags({"check_nan_inf": True})


def disable_tensor_checker() -> None:
    _flags.set_flags({"check_nan_inf": False})


def check_layer_numerics(func):
    """parity: amp/debugging.py check_layer_numerics — decorator checking a
    Layer.forward's tensor inputs/outputs for nan/inf."""
    import functools

    @functools.wraps(func)
    def wrapper(self, *args, **kwargs):
        from ..core.tensor import Tensor

        for i, a in enumerate(args):
            if isinstance(a, Tensor):
                check_numerics(a, type(self).__name__, f"input{i}")
        out = func(self, *args, **kwargs)
        outs = out if isinstance(out, (list, tuple)) else [out]
        for i, o in enumerate(outs):
            if isinstance(o, Tensor):
                check_numerics(o, type(self).__name__, f"output{i}")
        return out

    return wrapper


def compare_accuracy(dump_path, another_dump_path, output_filename,
                     loss_scale=1, dump_all_tensors=False):
    """parity: amp/debugging.py compare_accuracy — diff two numerics dump
    directories (produced by enable_operator_stats_collection runs) into an
    excel-ish CSV report."""
    import csv
    import os

    def load(path):
        rows = {}
        if os.path.isdir(path):
            files = [os.path.join(path, f) for f in sorted(os.listdir(path))]
        else:
            files = [path]
        for fp in files:
            if not os.path.isfile(fp):
                continue
            with open(fp) as f:
                for line in f:
                    parts = line.strip().split()
                    if parts:
                        rows[parts[0]] = parts[1:]
        return rows

    a, b = load(dump_path), load(another_dump_path)
    with open(output_filename, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["op", "run1", "run2", "match"])
        for k in sorted(set(a) | set(b)):
            w.writerow([k, " ".join(a.get(k, [])), " ".join(b.get(k, [])),
                        a.get(k) == b.get(k)])
    return output_filename


__all__ += ["check_layer_numerics", "compare_accuracy"]
