"""paddle_tpu.amp — automatic mixed precision.

Parity: python/paddle/amp/auto_cast.py:1006 (O1 white/black lists from
amp_lists.py, O2 decorate) and grad_scaler.py:657 GradScaler. On TPU the
natural low-precision dtype is bfloat16 (no loss scaling required), but the
fp16 + dynamic-loss-scaling path is kept for API parity.

The eager hook (`_amp_transform`) is the analogue of the AMP logic the
reference code-generates into every ad_func (eager_gen.py:645): inputs of
white-listed ops are cast to the amp dtype before dispatch.
"""
from __future__ import annotations

import contextlib
import threading

import numpy as np

from .amp_lists import WHITE_LIST, BLACK_LIST

_tls = threading.local()


class _AmpState:
    __slots__ = ("enable", "dtype", "level")

    def __init__(self, enable, dtype, level):
        self.enable = enable
        self.dtype = dtype
        self.level = level


def _amp_state():
    return getattr(_tls, "amp", None)


def _amp_active() -> bool:
    st = _amp_state()
    return st is not None and st.enable


def amp_state():
    return _amp_state()


def _cast_value(v, np_dtype):
    import jax.numpy as jnp

    d = np.dtype(v.dtype)
    if np.issubdtype(d, np.floating) and d != np.dtype(np_dtype) and d.itemsize >= 4:
        return jnp.asarray(v, dtype=np_dtype)
    return v


def _amp_transform(name, args, kwargs):
    """Cast float32 tensor inputs of white-listed ops to the amp dtype."""
    from ..core.tensor import Tensor
    from ..framework import dtype as dtypes

    st = _amp_state()
    base = name.split("::")[-1]
    if st is None or not st.enable:
        return args, kwargs
    if st.level == "O1" and base not in WHITE_LIST:
        return args, kwargs
    if base in BLACK_LIST:
        return args, kwargs
    if base == "cast":  # never re-enter on the cast op itself
        return args, kwargs
    np_dtype = dtypes.convert_dtype(st.dtype).np_dtype
    from .. import ops as _ops

    def cast_rec(obj):
        if isinstance(obj, Tensor):
            d = np.dtype(obj._value.dtype)
            if np.issubdtype(d, np.floating) and d != np_dtype and d.itemsize >= 4:
                # a recorded cast keeps the grad route to the original tensor
                return _ops.cast(obj, st.dtype)
            return obj
        if isinstance(obj, (list, tuple)):
            return type(obj)(cast_rec(o) for o in obj)
        if isinstance(obj, dict):
            return {k: cast_rec(v) for k, v in obj.items()}
        return obj

    return tuple(cast_rec(list(args))), cast_rec(kwargs)


@contextlib.contextmanager
def auto_cast(enable: bool = True, custom_white_list=None, custom_black_list=None,
              level: str = "O1", dtype: str = "bfloat16", use_promote: bool = True):
    """paddle.amp.auto_cast parity (bfloat16 default: TPU-native choice)."""
    global WHITE_LIST, BLACK_LIST
    prev = _amp_state()
    # only ops NOT already in the defaults are added (and later removed):
    # exiting must never delete default-list members like 'matmul'
    added_w = set(custom_white_list or ()) - WHITE_LIST
    added_b = set(custom_black_list or ()) - BLACK_LIST
    WHITE_LIST |= added_w
    BLACK_LIST |= added_b
    _tls.amp = _AmpState(enable, dtype, level)
    try:
        yield
    finally:
        _tls.amp = prev
        WHITE_LIST -= added_w
        BLACK_LIST -= added_b


amp_guard = auto_cast


def decorate(models, optimizers=None, level: str = "O2", dtype: str = "bfloat16",
             master_weight=None, save_dtype=None):
    """paddle.amp.decorate parity: O2 casts all float params to the amp dtype
    (optimizers keep fp32 master weights via their multi_precision path)."""
    from ..core.tensor import Tensor
    from ..framework import dtype as dtypes

    np_dtype = dtypes.convert_dtype(dtype).np_dtype
    single = not isinstance(models, (list, tuple))
    model_list = [models] if single else list(models)
    if level == "O2":
        import jax.numpy as jnp

        for m in model_list:
            for p in m.parameters():
                d = np.dtype(p._value.dtype)
                if np.issubdtype(d, np.floating) and d.itemsize >= 4:
                    p._replace_value(jnp.asarray(p._value, dtype=np_dtype))
    if optimizers is None:
        return models if single else model_list
    return (models if single else model_list), optimizers


from .grad_scaler import GradScaler  # noqa: E402,F401

__all__ = ["auto_cast", "amp_guard", "decorate", "GradScaler"]

from . import debugging  # noqa: F401,E402


def is_float16_supported(device=None):
    """parity: amp.is_float16_supported — TPU MXU computes in bf16; fp16
    tensors are supported via XLA conversion."""
    return True


def is_bfloat16_supported(device=None):
    """parity: amp.is_bfloat16_supported — bf16 is the TPU-native compute
    dtype."""
    return True
