"""paddle.callbacks namespace (parity: python/paddle/hapi/callbacks.py
re-exported as paddle.callbacks)."""
from .hapi.callbacks import (  # noqa: F401
    Callback, CallbackList, EarlyStopping, LRScheduler, ModelCheckpoint,
    ProgBarLogger, ReduceLROnPlateau, VisualDL, WandbCallback,
)

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "VisualDL",
           "LRScheduler", "EarlyStopping", "ReduceLROnPlateau",
           "WandbCallback"]
