"""Concrete optimizers.

Parity: python/paddle/optimizer/ — SGD/Momentum/Adagrad/Adadelta/Adam/AdamW/
Adamax/RMSProp/Rprop/ASGD/NAdam/RAdam/Lamb/LBFGS (reference kernels:
paddle/phi/kernels/*_kernel.h adam/momentum/lamb etc. — here pure jnp update
rules shared by eager and jit paths).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .optimizer import Optimizer


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision)

    def _update(self, p, g, state, lr, param):
        return p - lr * g.astype(p.dtype)


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _update(self, p, g, state, lr, param):
        g = g.astype(p.dtype)
        v = state.get("velocity")
        if v is None:
            v = jnp.zeros_like(p)
        v = self._momentum * v + g
        state["velocity"] = v
        if self._nesterov:
            return p - lr * (g + self._momentum * v)
        return p - lr * v


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _update(self, p, g, state, lr, param):
        g = g.astype(p.dtype)
        acc = state.get("moment")
        if acc is None:
            acc = jnp.full_like(p, self._init_acc)
        acc = acc + jnp.square(g)
        state["moment"] = acc
        return p - lr * g / (jnp.sqrt(acc) + self._epsilon)


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision)
        self._epsilon = epsilon
        self._rho = rho

    def _update(self, p, g, state, lr, param):
        g = g.astype(p.dtype)
        avg_sq = state.get("avg_squared_grad", jnp.zeros_like(p))
        avg_up = state.get("avg_squared_update", jnp.zeros_like(p))
        avg_sq = self._rho * avg_sq + (1 - self._rho) * jnp.square(g)
        update = jnp.sqrt(avg_up + self._epsilon) / jnp.sqrt(avg_sq + self._epsilon) * g
        avg_up = self._rho * avg_up + (1 - self._rho) * jnp.square(update)
        state["avg_squared_grad"] = avg_sq
        state["avg_squared_update"] = avg_up
        return p - lr * update


class _AdamBase(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, use_multi_tensor=False,
                 amsgrad=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._amsgrad = amsgrad

    def _adam_update(self, p, g, state, lr):
        g = g.astype(jnp.float32) if p.dtype == jnp.float32 else g.astype(p.dtype)
        m = state.get("moment1", jnp.zeros_like(p))
        v = state.get("moment2", jnp.zeros_like(p))
        b1p = state.get("beta1_pow", jnp.ones((), p.dtype))
        b2p = state.get("beta2_pow", jnp.ones((), p.dtype))
        b1p = b1p * self._beta1
        b2p = b2p * self._beta2
        m = self._beta1 * m + (1 - self._beta1) * g
        v = self._beta2 * v + (1 - self._beta2) * jnp.square(g)
        state["moment1"], state["moment2"] = m, v
        state["beta1_pow"], state["beta2_pow"] = b1p, b2p
        m_hat = m / (1 - b1p)
        if self._amsgrad:
            vmax = jnp.maximum(state.get("moment2_max", jnp.zeros_like(p)), v)
            state["moment2_max"] = vmax
            v_hat = vmax / (1 - b2p)
        else:
            v_hat = v / (1 - b2p)
        return p - lr * m_hat / (jnp.sqrt(v_hat) + self._epsilon)


class Adam(_AdamBase):
    def _update(self, p, g, state, lr, param):
        return self._adam_update(p, g, state, lr)


class AdamW(_AdamBase):
    """Decoupled weight decay (reference: python/paddle/optimizer/adamw.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=0.01, lr_ratio=None,
                 apply_decay_param_fun=None, grad_clip=None, lazy_mode=False,
                 multi_precision=False, amsgrad=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, lazy_mode, multi_precision,
                         amsgrad=amsgrad)
        self._wd = float(weight_decay) if isinstance(weight_decay, (int, float)) \
            else float(getattr(weight_decay, "_coeff", 0.0))
        self._apply_decay_param_fun = apply_decay_param_fun
        self._lr_ratio = lr_ratio

    def _use_coupled_weight_decay(self):
        return False

    def _update(self, p, g, state, lr, param):
        decay = self._wd
        if self._apply_decay_param_fun is not None and param is not None and \
                not self._apply_decay_param_fun(getattr(param, "name", None) or ""):
            decay = 0.0
        if decay:
            p = p * (1 - lr * decay)
        return self._adam_update(p, g, state, lr)


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _update(self, p, g, state, lr, param):
        g = g.astype(p.dtype)
        m = state.get("moment", jnp.zeros_like(p))
        u = state.get("inf_norm", jnp.zeros_like(p))
        b1p = state.get("beta1_pow", jnp.ones((), p.dtype)) * self._beta1
        m = self._beta1 * m + (1 - self._beta1) * g
        u = jnp.maximum(self._beta2 * u, jnp.abs(g))
        state["moment"], state["inf_norm"], state["beta1_pow"] = m, u, b1p
        return p - lr / (1 - b1p) * m / (u + self._epsilon)


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _update(self, p, g, state, lr, param):
        g = g.astype(p.dtype)
        ms = state.get("mean_square", jnp.zeros_like(p))
        ms = self._rho * ms + (1 - self._rho) * jnp.square(g)
        state["mean_square"] = ms
        if self._centered:
            mg = state.get("mean_grad", jnp.zeros_like(p))
            mg = self._rho * mg + (1 - self._rho) * g
            state["mean_grad"] = mg
            denom = jnp.sqrt(ms - jnp.square(mg) + self._epsilon)
        else:
            denom = jnp.sqrt(ms + self._epsilon)
        mom = state.get("momentum", jnp.zeros_like(p))
        mom = self._momentum * mom + lr * g / denom
        state["momentum"] = mom
        return p - mom


class Rprop(Optimizer):
    def __init__(self, learning_rate=0.001, learning_rate_range=(1e-5, 50.0),
                 parameters=None, etas=(0.5, 1.2), grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip,
                         multi_precision)
        self._lr_range = learning_rate_range
        self._etas = etas

    def _update(self, p, g, state, lr, param):
        g = g.astype(p.dtype)
        prev = state.get("prev_grad", jnp.zeros_like(p))
        lrs = state.get("lrs", jnp.full_like(p, lr))
        sign = jnp.sign(g * prev)
        lrs = jnp.where(sign > 0, jnp.minimum(lrs * self._etas[1], self._lr_range[1]),
                        jnp.where(sign < 0,
                                  jnp.maximum(lrs * self._etas[0], self._lr_range[0]),
                                  lrs))
        g_eff = jnp.where(sign < 0, 0.0, g)
        state["prev_grad"] = g_eff
        state["lrs"] = lrs
        return p - lrs * jnp.sign(g_eff)


class ASGD(Optimizer):
    def __init__(self, learning_rate=0.001, batch_num=1, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision)
        self._batch_num = batch_num

    def _update(self, p, g, state, lr, param):
        g = g.astype(p.dtype)
        d = state.get("d", jnp.zeros_like(p))
        ys = state.get("ys", jnp.zeros((self._batch_num,) + p.shape, p.dtype))
        i = int(state.get("idx", 0))
        y_old = ys[i]
        d = d - y_old + g
        ys = ys.at[i].set(g)
        state["d"], state["ys"] = d, ys
        state["idx"] = (i + 1) % self._batch_num
        return p - lr * d / self._batch_num


class NAdam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 momentum_decay=0.004, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._psi = momentum_decay

    def _update(self, p, g, state, lr, param):
        g = g.astype(p.dtype)
        t = state.get("t", 0) + 1
        mu_t = self._beta1 * (1 - 0.5 * 0.96 ** (t * self._psi))
        mu_t1 = self._beta1 * (1 - 0.5 * 0.96 ** ((t + 1) * self._psi))
        mu_prod = state.get("mu_prod", 1.0) * mu_t
        m = state.get("moment1", jnp.zeros_like(p))
        v = state.get("moment2", jnp.zeros_like(p))
        m = self._beta1 * m + (1 - self._beta1) * g
        v = self._beta2 * v + (1 - self._beta2) * jnp.square(g)
        state.update(t=t, mu_prod=mu_prod, moment1=m, moment2=v)
        m_hat = mu_t1 * m / (1 - mu_prod * mu_t1) + (1 - mu_t) * g / (1 - mu_prod)
        v_hat = v / (1 - self._beta2 ** t)
        return p - lr * m_hat / (jnp.sqrt(v_hat) + self._epsilon)


class RAdam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _update(self, p, g, state, lr, param):
        g = g.astype(p.dtype)
        t = state.get("t", 0) + 1
        m = state.get("moment1", jnp.zeros_like(p))
        v = state.get("moment2", jnp.zeros_like(p))
        m = self._beta1 * m + (1 - self._beta1) * g
        v = self._beta2 * v + (1 - self._beta2) * jnp.square(g)
        state.update(t=t, moment1=m, moment2=v)
        rho_inf = 2.0 / (1 - self._beta2) - 1
        b2t = self._beta2 ** t
        rho_t = rho_inf - 2 * t * b2t / (1 - b2t)
        m_hat = m / (1 - self._beta1 ** t)
        if rho_t > 5:
            r = np.sqrt((rho_t - 4) * (rho_t - 2) * rho_inf /
                        ((rho_inf - 4) * (rho_inf - 2) * rho_t))
            v_hat = jnp.sqrt(v / (1 - b2t))
            return p - lr * r * m_hat / (v_hat + self._epsilon)
        return p - lr * m_hat


class Lamb(Optimizer):
    """Layer-wise adaptive moments (reference:
    python/paddle/optimizer/lamb.py; phi kernel lamb_kernel)."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, None, grad_clip,
                         multi_precision)
        self._wd = lamb_weight_decay
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def _update(self, p, g, state, lr, param):
        g = g.astype(p.dtype)
        m = state.get("moment1", jnp.zeros_like(p))
        v = state.get("moment2", jnp.zeros_like(p))
        b1p = state.get("beta1_pow", jnp.ones((), p.dtype)) * self._beta1
        b2p = state.get("beta2_pow", jnp.ones((), p.dtype)) * self._beta2
        m = self._beta1 * m + (1 - self._beta1) * g
        v = self._beta2 * v + (1 - self._beta2) * jnp.square(g)
        state.update(moment1=m, moment2=v, beta1_pow=b1p, beta2_pow=b2p)
        m_hat = m / (1 - b1p)
        v_hat = v / (1 - b2p)
        wd = self._wd
        if self._exclude_fn is not None and param is not None and \
                self._exclude_fn(param):
            wd = 0.0
        r = m_hat / (jnp.sqrt(v_hat) + self._epsilon) + wd * p
        w_norm = jnp.linalg.norm(p)
        r_norm = jnp.linalg.norm(r)
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return p - lr * trust * r


class LBFGS(Optimizer):
    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-7, tolerance_change=1e-9, history_size=100,
                 line_search_fn=None, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._max_iter = max_iter
        self._history_size = history_size
        self._tol_grad = tolerance_grad
        self._tol_change = tolerance_change
        self._line_search_fn = line_search_fn
        self._hist = {"s": [], "y": []}
        self._prev_flat_grad = None
        self._prev_flat_param = None

    def _flat(self, vals):
        return jnp.concatenate([v.reshape(-1) for v in vals])

    def step(self, closure=None):
        if closure is not None:
            loss = closure()
        params = [p for p in self._parameter_list if p.grad is not None]
        if not params:
            return
        flat_g = self._flat([p.grad._value.astype(jnp.float32) for p in params])
        flat_p = self._flat([p._value.astype(jnp.float32) for p in params])
        if self._prev_flat_grad is not None:
            s = flat_p - self._prev_flat_param
            y = flat_g - self._prev_flat_grad
            if float(jnp.dot(s, y)) > 1e-10:
                self._hist["s"].append(s)
                self._hist["y"].append(y)
                if len(self._hist["s"]) > self._history_size:
                    self._hist["s"].pop(0)
                    self._hist["y"].pop(0)
        # two-loop recursion
        q = flat_g
        alpha = []
        for s, y in zip(reversed(self._hist["s"]), reversed(self._hist["y"])):
            a = jnp.dot(s, q) / jnp.dot(y, s)
            alpha.append(a)
            q = q - a * y
        if self._hist["s"]:
            s, y = self._hist["s"][-1], self._hist["y"][-1]
            q = q * (jnp.dot(s, y) / jnp.dot(y, y))
        for (s, y), a in zip(zip(self._hist["s"], self._hist["y"]),
                             reversed(alpha)):
            b = jnp.dot(y, q) / jnp.dot(y, s)
            q = q + s * (a - b)
        direction = -q
        lr = self.get_lr()
        new_flat = flat_p + lr * direction
        self._prev_flat_grad = flat_g
        self._prev_flat_param = new_flat
        offset = 0
        for p in params:
            n = p.size
            p._replace_value(
                new_flat[offset:offset + n].reshape(tuple(p.shape)).astype(
                    p._value.dtype))
            offset += n
        self._global_step += 1
        return loss if closure is not None else None
