"""Host-offloaded training memory modes: fit a bigger model on one chip.

Parity: the reference's sharding-offload knobs — sharding stage-2/3
``offload`` (distributed/sharding/group_sharded.py: offload=True moves
optimizer state + master weights to CPU) and the fused-LAMB offload path
(incubate/distributed_fused_lamb). Those stream optimizer state over PCIe
around a CUDA update kernel.

TPU-native re-design over XLA memories (jax Device.addressable_memories):

* **Gradient offload** (``make_offload_train_step(offload_grads=True)``):
  the fwd+bwd program writes its gradient outputs to ``pinned_host``
  memory (jit ``out_shardings`` with a host memory kind) and the update
  phase walks the param tree LEAF BY LEAF (each leaf's grad device_put
  back h2d, updated, freed). Measured caveat (r3, v5e): XLA's buffer
  assignment still materializes the full grad tree in HBM before the d2h
  copy, so this mode reduces steady-state residency (grads don't occupy
  HBM between phases) but NOT the backward's peak — it did not fit 4B on
  16 GB alone.

* **Moment offload** (``offload_moments=True``): adamw's mu/nu live in
  pinned_host between steps and stream through the device per leaf inside
  the update. 16 bytes/param of optimizer state stops occupying HBM; the
  PCIe cost amortizes on big-HBM parts (v5p 8B-class) and is the direct
  analogue of the reference's ``offload=True``.

* **Layer-wise optimizer-in-backward**
  (``make_layerwise_train_step`` + ``init_layerwise_train_state``): the
  peak-memory fix that DOES fit ~4B on a 16 GB chip — no grad tree is
  ever formed; each layer's backward and update run in one donated
  program. See its docstring for the measured numbers.

All modes compose with optimizers in optimizer/functional.py; math is
identical to the fused path (tests assert step equivalence).
"""
from __future__ import annotations

import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp

from .functional import (adafactor_update, adamw_update, init_moments)

__all__ = ["host_put", "device_put_leaf", "make_offload_train_step",
           "make_layerwise_train_step", "init_offload_train_state",
           "StreamTrainState", "init_streaming_train_state",
           "make_streaming_train_step", "streaming_state_from_layerwise",
           "layerwise_state_from_streaming",
           "init_streaming_moe_train_state", "make_streaming_moe_train_step",
           "supports_host_memory", "supports_compiled_host_memory"]

_f32 = jnp.float32


def supports_host_memory(dev=None) -> bool:
    dev = dev or jax.devices()[0]
    try:
        return "pinned_host" in {m.kind for m in dev.addressable_memories()}
    except Exception:
        return False


@functools.lru_cache(maxsize=1)
def supports_compiled_host_memory() -> bool:
    """True when COMPILED programs can read/write pinned_host (TPU yes;
    the CPU backend advertises the memory space but lacks the
    annotate_device_placement lowering, so offload degrades to device
    memory there — same two-phase structure, no host staging)."""
    dev = jax.devices()[0]
    if not supports_host_memory(dev):
        return False
    try:
        sh = _kind_sharding(dev, "pinned_host")
        out = jax.jit(lambda: jnp.zeros((2,)), out_shardings=sh)()
        jax.jit(lambda x: jax.device_put(x, _kind_sharding(dev, "device"))
                + 1)(out)
        return True
    except Exception:
        return False


def _kind_sharding(dev, kind: str):
    from jax.sharding import SingleDeviceSharding

    return SingleDeviceSharding(dev, memory_kind=kind)


def host_put(tree, dev=None):
    """Move a pytree to pinned host memory (no-op values stay usable as
    inputs to jitted programs; XLA inserts the h2d streams)."""
    dev = dev or jax.devices()[0]
    sh = _kind_sharding(dev, "pinned_host")
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sh), tree)


def device_put_leaf(x, dev=None):
    dev = dev or jax.devices()[0]
    return jax.device_put(x, _kind_sharding(dev, "device"))


def init_offload_train_state(module, config, key, optimizer: str = "adamw",
                             moment_dtype=jnp.float32,
                             param_dtype=jnp.float32,
                             offload_moments: bool = True):
    """``module.init_train_state`` with the moment trees parked in pinned
    host memory."""
    # jitted init: the f32 master intermediates are freed per-leaf inside
    # the program, so a 4B bf16 init peaks at ~one f32 leaf, not the full
    # f32 tree (which alone would fill a 16 GB chip)
    state = jax.jit(lambda k: module.init_train_state(
        config, k, optimizer=optimizer, moment_dtype=moment_dtype,
        param_dtype=param_dtype))(key)
    if offload_moments and supports_compiled_host_memory():
        state.mu = host_put(state.mu)
        state.nu = host_put(state.nu)
    return state


def make_offload_train_step(module, config, optimizer: str = "adamw",
                            lr=3e-4, beta1=0.9, beta2=0.95, eps=1e-8,
                            wd=0.1, clip_norm=1.0, loss_function=None,
                            offload_grads: bool = True,
                            offload_moments: bool = False,
                            adafactor_clip=1.0):
    """Build a two-phase host-offloaded train step for ``module`` (a model
    module exposing ``loss_fn(params, tokens, config)`` — llama/moe/bert).

    Returns ``step(state, tokens) -> (state, loss)`` semantically identical
    to ``module.train_step`` (same clip + update math), with gradients
    and/or optimizer moments staged through pinned host memory.
    """
    dev = jax.devices()[0]
    have_host = supports_compiled_host_memory()
    use_host = have_host and offload_grads
    host_sh = _kind_sharding(dev, "pinned_host") if have_host else None
    dev_sh = _kind_sharding(dev, "device")
    lf = loss_function or module.loss_fn

    # ---- phase A: fwd+bwd; grads stream out to host ----------------------
    def _grads(params, tokens):
        loss, grads = jax.value_and_grad(lf)(params, tokens, config)
        gsq = sum(jnp.sum(jnp.square(g.astype(_f32)))
                  for g in jax.tree_util.tree_leaves(grads))
        return loss, gsq, grads

    grads_jit = None  # built lazily: out_shardings needs the grad structure

    # ---- phase B: per-leaf update (one compiled fn per leaf shape) -------
    @functools.partial(jax.jit, static_argnames=("ghost", "mhost"),
                       donate_argnums=(0,))
    def _leaf_adamw(p, g, m, n, scale, bc1, bc2, *, ghost, mhost):
        if ghost:
            g = jax.device_put(g, dev_sh)
        if mhost:
            m = jax.device_put(m, dev_sh)
            n = jax.device_put(n, dev_sh)
        return adamw_update(p, g, m, n, lr=lr, beta1=beta1, beta2=beta2,
                            eps=eps, wd=wd, scale=scale, bc1=bc1, bc2=bc2)

    @functools.partial(jax.jit, static_argnames=("ghost",),
                       donate_argnums=(0,))
    def _leaf_adafactor(p, g, nu, scale, beta2t, *, ghost):
        if ghost:
            g = jax.device_put(g, dev_sh)
        return adafactor_update(p, g, nu, lr=lr, beta2t=beta2t, eps1=1e-30,
                                eps2=1e-3, clip=adafactor_clip, wd=wd,
                                scale=scale)

    def _is_host(x) -> bool:
        return getattr(x.sharding, "memory_kind", None) == "pinned_host"

    def step(state, tokens):
        nonlocal grads_jit
        params = state.params
        if grads_jit is None:
            if use_host:
                out_tree = jax.eval_shape(_grads, params, tokens)
                grad_sh = jax.tree_util.tree_map(lambda _: host_sh,
                                                 out_tree[2])
                grads_jit = jax.jit(
                    _grads, out_shardings=(dev_sh, dev_sh, grad_sh))
            else:
                grads_jit = jax.jit(_grads)
        loss, gsq, grads = grads_jit(params, tokens)
        gnorm = jnp.sqrt(gsq)
        scale = jnp.minimum(1.0, clip_norm / (gnorm + 1e-6))

        t = (state.step + 1).astype(_f32)
        treedef = jax.tree_util.tree_structure(params)
        flat_p = jax.tree_util.tree_leaves(params)
        flat_g = jax.tree_util.tree_leaves(grads)

        if optimizer == "adamw":
            bc1 = 1.0 - beta1 ** t
            bc2 = 1.0 - beta2 ** t
            flat_m = jax.tree_util.tree_leaves(state.mu)
            flat_n = jax.tree_util.tree_leaves(state.nu)
            outs = []
            for p, g, m, n in zip(flat_p, flat_g, flat_m, flat_n):
                mhost = _is_host(m)
                np_, nm, nn = _leaf_adamw(p, g, m, n, scale, bc1, bc2,
                                          ghost=_is_host(g), mhost=mhost)
                if mhost:   # moments go back to their home memory
                    nm, nn = host_put(nm, dev), host_put(nn, dev)
                outs.append((np_, nm, nn))
            unflat = lambda i: jax.tree_util.tree_unflatten(
                treedef, [o[i] for o in outs])
            new_state = module.TrainState(unflat(0), unflat(1), unflat(2),
                                          state.step + 1)
            return new_state, loss
        if optimizer == "adafactor":
            beta2t = 1.0 - t ** -0.8
            flat_nu = treedef.flatten_up_to(state.nu)
            new_p, new_nu = [], []
            for p, g, nu in zip(flat_p, flat_g, flat_nu):
                np_, nnu = _leaf_adafactor(p, g, nu, scale, beta2t,
                                           ghost=_is_host(g))
                new_p.append(np_)
                new_nu.append(nnu)
            new_state = module.TrainState(
                jax.tree_util.tree_unflatten(treedef, new_p), state.mu,
                jax.tree_util.tree_unflatten(treedef, new_nu),
                state.step + 1)
            return new_state, loss
        raise ValueError(f"unknown optimizer {optimizer!r}")

    return step


# ---------------------------------------------------------------------------
# layer-wise optimizer-in-backward (the ~4B-on-16GB enabler)
# ---------------------------------------------------------------------------
def _build_head_tail(c, fac):
    """Compiled head-gradient and embed/norm/head-update programs shared by
    the layerwise and streaming steps (identical math in both)."""
    from ..models import llama as _llama

    dt = c.dtype

    def head_loss(x_final, fn_w, head, targets):
        xn = _llama._rms_norm(x_final, fn_w, c.rms_eps)
        B, S, _ = xn.shape
        if c.loss_chunks > 1:
            total = _llama._chunked_ce_sum(xn, targets, head.astype(dt),
                                           c.loss_chunks)
        else:
            logits = (xn @ head.astype(dt)).astype(jnp.float32)
            logz = jax.nn.logsumexp(logits, axis=-1)
            gold = jnp.take_along_axis(logits, targets[..., None],
                                       axis=-1)[..., 0]
            total = jnp.sum(logz - gold)
        return total / (B * S)

    @jax.jit
    def head_grads(x_final, fn_w, head, targets):
        loss, grads = jax.value_and_grad(
            head_loss, argnums=(0, 1, 2))(x_final, fn_w, head, targets)
        return loss, grads          # (dx_final, d_final_norm, d_head)

    @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
    def tail_update(embed, fn_w, head, nu_e, nu_f, nu_h, tokens_in, dx0,
                    dfn, dhead, beta2t):
        d_embed = jnp.zeros(embed.shape, jnp.float32).at[tokens_in].add(
            dx0.astype(jnp.float32))
        new_e, nnu_e = fac(embed, d_embed, nu_e, beta2t)
        new_f, nnu_f = fac(fn_w, dfn, nu_f, beta2t)
        new_h, nnu_h = fac(head, dhead, nu_h, beta2t)
        return new_e, new_f, new_h, nnu_e, nnu_f, nnu_h

    return head_grads, tail_update

def init_layerwise_train_state(config, key, param_dtype=jnp.bfloat16):
    """Train state for :func:`make_layerwise_train_step`.

    The layers subtree's adafactor second moments use PER-LAYER semantics:
    a stacked matmul weight [L, K, N] factors over (K, N) with the stack
    dim kept (identical to the fused path), but a stacked norm weight
    [L, h] keeps a FULL per-layer second moment {"v": [L, h]} — the fused
    path would factor the L×h matrix across layers, which has no per-layer
    meaning when layers update independently."""
    from ..models import llama as _llama

    params = jax.jit(lambda k: jax.tree_util.tree_map(
        lambda p: p.astype(param_dtype),
        _llama.init_params(config, k)))(key)

    def nu_layers_like(p):
        if p.ndim - 1 >= 2:     # [L, K, N, ...]: factor trailing two dims
            return {"vr": jnp.zeros(p.shape[:-1], _f32),
                    "vc": jnp.zeros(p.shape[:-2] + p.shape[-1:], _f32)}
        return {"v": jnp.zeros(p.shape, _f32)}   # [L, h] norms: full

    nu = {k: (jax.tree_util.tree_map(nu_layers_like, v) if k == "layers"
              else jax.tree_util.tree_map(_nu_like_perlayer, v))
          for k, v in params.items()}
    mu = jax.tree_util.tree_map(lambda p: jnp.zeros((), _f32), params)
    return _llama.TrainState(params, mu, nu, jnp.zeros((), jnp.int32))


def make_layerwise_train_step(config, optimizer: str = "adafactor",
                              lr=3e-4, wd=0.1, adafactor_clip=1.0):
    """Optimizer-in-backward at LAYER granularity for llama-family configs.

    The fused train step's peak HBM is params + the FULL gradient tree
    (bf16 4B: 8 GB + 8 GB — measured 17.25 GB on a 15.75 GB v5e, OOM, and
    gradient out_shardings to pinned_host does not help: XLA materializes
    the grad tree on device before the d2h copy). This step never forms
    that tree. It runs forward once (saving each layer's input, ~60 MB per
    layer), takes the loss/head gradients, then walks the layers in
    REVERSE: one compiled program re-runs layer l's forward, takes its vjp,
    applies the adafactor update to that layer's weights in place (donated
    buffers), and passes the input-cotangent down. A layer's gradients
    (~0.3 GB at 4B) exist only inside its own program invocation.

    Device peak: params + per-layer working set + saved inputs ≈ 10-11 GB
    at 4B — the measured difference between OOM and training.

    Parity analogue: the reference's sharding offload / fused-LAMB offload
    free optimizer+grad HBM by staging through CPU; this achieves the same
    residency bound by scheduling (optimizer-in-backward), which on TPU is
    the cheaper currency (no PCIe round-trip at all).

    Global-norm clipping is not available (it needs the full grad tree by
    definition); adafactor's per-tensor update-RMS clip is the stabilizer,
    as in the Adafactor paper. Tied embeddings are not supported.
    Returns ``step(state, tokens) -> (state, loss)``.
    """
    from ..models import llama as _llama

    c = config
    if optimizer != "adafactor":
        raise NotImplementedError(
            "layerwise step supports adafactor (the no-first-moment "
            "optimizer is what makes per-layer in-place updates free)")
    if c.tie_embeddings:
        raise NotImplementedError("layerwise step: untied embeddings only")
    if getattr(c, "pipeline_microbatches", 0):
        raise NotImplementedError("layerwise step is a single-chip memory "
                                  "mode; use pipeline schedules on meshes")
    dt = c.dtype

    @jax.jit
    def fwd_collect(layers, embed, tokens):
        x = embed.astype(dt)[tokens]
        cos, sin = _llama._rope_tables(tokens.shape[1], c.head_dim,
                                       c.rope_theta)

        def scan_fn(carry, lp):
            return _llama._layer_body(carry, lp, cos, sin, c), carry

        x_final, xs = jax.lax.scan(scan_fn, x, layers)
        return x_final, xs          # xs[l] = layer l's INPUT

    def _fac(p, g, nu, beta2t):
        return adafactor_update(p, g, nu, lr=lr, beta2t=beta2t, eps1=1e-30,
                                eps2=1e-3, clip=adafactor_clip, wd=wd,
                                scale=1.0)

    head_grads, tail_update = _build_head_tail(c, _fac)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def layers_backward(layers, nu_layers, xs, cot, beta2t):
        """Reverse layer walk as ONE compiled program (a lax.scan over the
        layer index). A python-loop-of-jits variant has the same residency
        but pays a host dispatch round-trip per layer — ~5 ms each through
        a remote-device tunnel, ~150 ms/step at 28 layers. The scan body
        still materializes only one layer's gradients at a time (donated
        carries update layers/nu in place via dynamic-update-slice)."""
        cos, sin = _llama._rope_tables(xs.shape[2], c.head_dim,
                                       c.rope_theta)

        def body(carry, l):
            layers, nu_layers, dx = carry
            x_in = xs[l]
            lp = jax.tree_util.tree_map(lambda a: a[l], layers)
            nu_l = jax.tree_util.tree_map(lambda a: a[l], nu_layers)

            def run(lp_, xi):
                return _llama._layer_body(xi, lp_, cos, sin, c)

            _, vjp = jax.vjp(run, lp, x_in)
            dlp, dx = vjp(dx)
            new_lp, new_nu = {}, {}
            for k in lp:
                new_lp[k], new_nu[k] = _fac(lp[k], dlp[k], nu_l[k], beta2t)
            layers = jax.tree_util.tree_map(
                lambda big, new: big.at[l].set(new), layers, new_lp)
            nu_layers = jax.tree_util.tree_map(
                lambda big, new: big.at[l].set(new), nu_layers, new_nu)
            return (layers, nu_layers, dx), None

        (layers, nu_layers, dx), _ = jax.lax.scan(
            body, (layers, nu_layers, cot),
            jnp.arange(c.num_layers - 1, -1, -1))
        return layers, nu_layers, dx

    def step(state, tokens):
        params = state.params
        layers = params["layers"]
        nu = state.nu
        nu_layers = nu["layers"]
        t = (state.step + 1).astype(_f32)
        beta2t = 1.0 - t ** -0.8
        inp = tokens[:, :-1]
        tgt = tokens[:, 1:]

        x_final, xs = fwd_collect(layers, params["embed"], inp)
        loss, (dx, dfn, dhead) = head_grads(x_final, params["final_norm"],
                                            params["lm_head"], tgt)
        layers, nu_layers, dx = layers_backward(layers, nu_layers, xs, dx,
                                                beta2t)
        new_e, new_f, new_h, nnu_e, nnu_f, nnu_h = tail_update(
            params["embed"], params["final_norm"], params["lm_head"],
            nu["embed"], nu["final_norm"], nu["lm_head"], inp, dx, dfn,
            dhead, beta2t)
        new_params = {"layers": layers, "embed": new_e,
                      "final_norm": new_f, "lm_head": new_h}
        new_nu = {"layers": nu_layers, "embed": nnu_e,
                  "final_norm": nnu_f, "lm_head": nnu_h}
        from ..models.llama import TrainState
        return TrainState(new_params, state.mu, new_nu,
                          state.step + 1), loss

    return step


# ---------------------------------------------------------------------------
# host-streamed layer-wise step (the 8B-on-16GB enabler)
# ---------------------------------------------------------------------------
class StreamTrainState:
    """Train state for :func:`make_streaming_train_step`.

    ``layers``/``nu_layers`` are *lists* of per-layer pytrees parked in
    ``pinned_host`` memory (device memory on backends without a host
    space); ``embed``/``final_norm``/``lm_head`` and their second moments
    stay in HBM. ``step`` is a host int — the step loop is host-driven, so
    a device scalar would only add dispatches.
    """

    def __init__(self, layers, nu_layers, embed, final_norm, lm_head,
                 nu_embed, nu_fn, nu_head, step: int = 0):
        self.layers = layers
        self.nu_layers = nu_layers
        self.embed = embed
        self.final_norm = final_norm
        self.lm_head = lm_head
        self.nu_embed = nu_embed
        self.nu_fn = nu_fn
        self.nu_head = nu_head
        self.step = int(step)


def _make_fetch_park(dev, to_host):
    """The streaming steps' h2d/d2h movers (shared by the llama and MoE
    variants — one place for transfer-path fixes)."""
    dev_sh = _kind_sharding(dev, "device")

    def fetch(tree):
        if not to_host:
            return tree
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(x, dev_sh), tree)

    def park(tree):
        return host_put(tree, dev) if to_host else tree

    return fetch, park


def _nu_like_perlayer(p):
    """Per-layer adafactor second-moment slot (factored for matrices)."""
    if p.ndim >= 2:
        return {"vr": jnp.zeros(p.shape[:-1], _f32),
                "vc": jnp.zeros(p.shape[:-2] + p.shape[-1:], _f32)}
    return {"v": jnp.zeros(p.shape, _f32)}


def init_streaming_train_state(config, key, param_dtype=jnp.bfloat16):
    """Init an 8B-class model without ever holding the full parameter set
    in HBM: each layer is initialised on device by one (reused) compiled
    program and immediately streamed to pinned host memory."""
    import math

    from ..models import llama as _llama  # noqa: F401  (config family)

    c = config
    h, f, L = c.hidden_size, c.intermediate_size, c.num_layers
    nq, nkv, d = c.num_heads, c.num_kv_heads, c.head_dim
    s = 1.0 / math.sqrt(h)
    dev = jax.devices()[0]
    to_host = supports_compiled_host_memory()

    @jax.jit
    def init_layer(k):
        ks = jax.random.split(k, 7)

        def g(kk, shape, scale):
            return (jax.random.normal(kk, shape, jnp.float32)
                    * scale).astype(param_dtype)

        return {
            "attn_norm": jnp.ones((h,), param_dtype),
            "wq": g(ks[0], (h, nq * d), s),
            "wk": g(ks[1], (h, nkv * d), s),
            "wv": g(ks[2], (h, nkv * d), s),
            "wo": g(ks[3], (nq * d, h), s / math.sqrt(2 * L)),
            "mlp_norm": jnp.ones((h,), param_dtype),
            "w_gate": g(ks[4], (h, f), s),
            "w_up": g(ks[5], (h, f), s),
            "w_down": g(ks[6], (f, h), 1.0 / math.sqrt(f) / math.sqrt(2 * L)),
        }

    keys = jax.random.split(key, L + 2)
    layers, nu_layers = [], []
    for l in range(L):
        lp = init_layer(keys[l])
        nu_layers.append(jax.tree_util.tree_map(_nu_like_perlayer, lp))
        layers.append(host_put(lp, dev) if to_host else lp)

    @jax.jit
    def init_tail(ke, kh):
        embed = (jax.random.normal(ke, (c.vocab_size, h), jnp.float32)
                 * (1.0 / math.sqrt(h))).astype(param_dtype)
        head = (jax.random.normal(kh, (h, c.vocab_size), jnp.float32)
                * s).astype(param_dtype)
        return embed, jnp.ones((h,), param_dtype), head

    if c.tie_embeddings:
        raise NotImplementedError("streaming step: untied embeddings only")
    embed, fn_w, head = init_tail(keys[L], keys[L + 1])
    return StreamTrainState(
        layers, nu_layers, embed, fn_w, head,
        _nu_like_perlayer(embed), _nu_like_perlayer(fn_w),
        _nu_like_perlayer(head), 0)


def streaming_state_from_layerwise(state, to_host: Optional[bool] = None):
    """Slice a stacked layerwise TrainState into a StreamTrainState (used
    by tests for step-equivalence and by checkpoint conversion). Needs the
    stacked tree addressable — fine on CPU/big-HBM hosts."""
    params, nu = state.params, state.nu
    L = params["layers"]["wq"].shape[0]
    to_host = (supports_compiled_host_memory()
               if to_host is None else to_host)
    dev = jax.devices()[0]
    layers, nu_layers = [], []
    for l in range(L):
        lp = jax.tree_util.tree_map(lambda a: a[l], params["layers"])
        nl = jax.tree_util.tree_map(lambda a: a[l], nu["layers"])
        layers.append(host_put(lp, dev) if to_host else lp)
        nu_layers.append(nl)
    return StreamTrainState(
        layers, nu_layers, params["embed"], params["final_norm"],
        params["lm_head"], nu["embed"], nu["final_norm"], nu["lm_head"],
        int(state.step))


def layerwise_state_from_streaming(state):
    """Re-stack a StreamTrainState into the layerwise TrainState layout
    (for checkpoint save via the existing stacked-tree paths)."""
    from ..models.llama import TrainState

    stack = lambda trees: jax.tree_util.tree_map(
        lambda *xs: jnp.stack([device_put_leaf(x) for x in xs]), *trees)
    layers = stack(state.layers)
    nu_layers = stack(state.nu_layers)
    params = {"layers": layers, "embed": state.embed,
              "final_norm": state.final_norm, "lm_head": state.lm_head}
    nu = {"layers": nu_layers, "embed": state.nu_embed,
          "final_norm": state.nu_fn, "lm_head": state.nu_head}
    mu = jax.tree_util.tree_map(lambda p: jnp.zeros((), _f32), params)
    return TrainState(params, mu, nu, jnp.asarray(state.step, jnp.int32))


def make_streaming_train_step(config, optimizer: str = "adafactor",
                              lr=3e-4, wd=0.1, adafactor_clip=1.0):
    """Layer-wise optimizer-in-backward with **host-streamed parameters**:
    trains a model whose parameters alone exceed HBM (Llama-3-8B bf16 =
    16 GB on a 16 GB chip).

    Mechanism — three compiled programs, a host-driven layer loop, and
    double-buffered PCIe transfers:

    * parameters live per-layer in ``pinned_host``; at any moment at most
      two layers (current + prefetched next) occupy HBM (~0.9 GB at 8B);
    * forward: while layer *l*'s (reused) compiled program runs, layer
      *l+1*'s weights are already streaming h2d — ``jax.device_put`` and
      dispatch are async, so the DMA rides under the matmuls. Only each
      layer's *input* (B·S·h bf16) is saved;
    * backward: one compiled program per layer (again reused) re-runs the
      layer forward, takes its vjp, and applies the adafactor update to
      the **donated** weight buffers; updated weights stream back d2h
      while layer *l-1* computes. A layer's gradients exist only inside
      its own program invocation — no gradient tree, ever.

    PCIe traffic is 3× params/step (fwd h2d + bwd h2d + updated d2h,
    ~48 GB at 8B) — amortized under compute at batch·seq ≥ 16k tokens.

    Parity: the reference's stage-3 ``offload=True`` sharding
    (distributed/sharding/group_sharded.py) and fused-LAMB offload stream
    params/optimizer state over PCIe around CUDA update kernels; this is
    the single-chip TPU equivalent, scheduled rather than sharded.
    Global-norm clipping is unavailable by construction (no full grad
    tree); adafactor's update-RMS clip is the stabilizer.
    Returns ``step(state, tokens) -> (state, loss)``.
    """
    from ..models import llama as _llama

    c = config
    if optimizer != "adafactor":
        raise NotImplementedError("streaming step supports adafactor")
    if c.tie_embeddings:
        raise NotImplementedError("streaming step: untied embeddings only")
    if getattr(c, "pipeline_microbatches", 0):
        raise NotImplementedError("streaming step is a single-chip memory "
                                  "mode; use pipeline schedules on meshes")
    dt = c.dtype
    dev = jax.devices()[0]
    to_host = supports_compiled_host_memory()

    def _fac(p, g, nu, beta2t):
        return adafactor_update(p, g, nu, lr=lr, beta2t=beta2t, eps1=1e-30,
                                eps2=1e-3, clip=adafactor_clip, wd=wd,
                                scale=1.0)

    head_grads, tail_update = _build_head_tail(c, _fac)
    _fetch, _park = _make_fetch_park(dev, to_host)

    @jax.jit
    def embed_fwd(embed, tokens):
        return embed.astype(dt)[tokens]

    @jax.jit
    def layer_fwd(x, lp):
        cos, sin = _llama._rope_tables(x.shape[1], c.head_dim, c.rope_theta)
        return _llama._layer_body(x, lp, cos, sin, c)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def layer_bwd_update(lp, nu_l, x_in, dx, beta2t):
        cos, sin = _llama._rope_tables(x_in.shape[1], c.head_dim,
                                       c.rope_theta)

        def run(lp_, xi):
            return _llama._layer_body(xi, lp_, cos, sin, c)

        _, vjp = jax.vjp(run, lp, x_in)
        dlp, dx_prev = vjp(dx)
        new_lp, new_nu = {}, {}
        for k in lp:
            new_lp[k], new_nu[k] = _fac(lp[k], dlp[k], nu_l[k], beta2t)
        return new_lp, new_nu, dx_prev

    def step(state: StreamTrainState, tokens):
        L = c.num_layers
        inp = tokens[:, :-1]
        tgt = tokens[:, 1:]
        beta2t = 1.0 - float(state.step + 1) ** -0.8

        # ---- forward: prefetch l+1 while l computes ---------------------
        xs = [None] * L
        x = embed_fwd(state.embed, inp)
        nxt = _fetch(state.layers[0])
        for l in range(L):
            cur, nxt = nxt, (_fetch(state.layers[l + 1])
                             if l + 1 < L else None)
            xs[l] = x
            x = layer_fwd(x, cur)
            cur = None      # drop the HBM copy as soon as dispatched

        loss, (dx, dfn, dhead) = head_grads(
            x, state.final_norm, state.lm_head, tgt)

        # ---- backward: reverse walk, update in place, stream back -------
        new_layers = list(state.layers)
        new_nu_layers = list(state.nu_layers)
        nxt = _fetch(state.layers[L - 1])
        for l in range(L - 1, -1, -1):
            cur, nxt = nxt, (_fetch(state.layers[l - 1]) if l > 0 else None)
            new_lp, new_nu, dx = layer_bwd_update(
                cur, state.nu_layers[l], xs[l], dx, beta2t)
            new_layers[l] = _park(new_lp)
            new_nu_layers[l] = new_nu
            xs[l] = None    # free the saved input

        new_e, new_f, new_h, nnu_e, nnu_f, nnu_h = tail_update(
            state.embed, state.final_norm, state.lm_head,
            state.nu_embed, state.nu_fn, state.nu_head, inp, dx, dfn,
            dhead, beta2t)
        return StreamTrainState(
            new_layers, new_nu_layers, new_e, new_f, new_h,
            nnu_e, nnu_f, nnu_h, state.step + 1), loss

    return step


# ---------------------------------------------------------------------------
# host-streamed MoE step (DeepSeekMoE-16B — BASELINE config 5 — on one chip)
# ---------------------------------------------------------------------------
def init_streaming_moe_train_state(config, key, param_dtype=jnp.bfloat16):
    """Streaming state for MoE configs: each layer (attention + router +
    stacked experts + shared experts, ~1.2 GB at DeepSeekMoE-16B) is
    initialised on device by one reused compiled program and parked in
    pinned host memory — the full 33 GB parameter set never exists in
    HBM."""
    import math

    c = config
    h, L, E = c.hidden_size, c.num_layers, c.num_experts
    nq, nkv, d = c.num_heads, c.num_kv_heads, c.head_dim
    fm = c.moe_intermediate_size
    fs = c.n_shared_experts * fm
    s = 1.0 / math.sqrt(h)
    o = s / math.sqrt(2 * L)
    dev = jax.devices()[0]
    to_host = supports_compiled_host_memory()

    @functools.partial(jax.jit, static_argnames=("dense",))
    def init_layer(k, *, dense):
        ks = jax.random.split(k, 12)

        def g(kk, shape, scale):
            return (jax.random.normal(kk, shape, jnp.float32)
                    * scale).astype(param_dtype)

        lp = {
            "attn_norm": jnp.ones((h,), param_dtype),
            "wq": g(ks[0], (h, nq * d), s),
            "wk": g(ks[1], (h, nkv * d), s),
            "wv": g(ks[2], (h, nkv * d), s),
            "wo": g(ks[3], (nq * d, h), o),
            "mlp_norm": jnp.ones((h,), param_dtype),
            "s_gate": g(ks[8], (h, fs), s),
            "s_up": g(ks[9], (h, fs), s),
            "s_down": g(ks[10], (fs, h), o),
        }
        if not dense:
            # dense (first_dense_layers) layers never touch the router or
            # experts — per-layer trees may simply omit them, saving their
            # init, pinned-host residency, and per-step PCIe round trip
            # (~2.2 GB/step at DeepSeekMoE-16B)
            lp.update({
                "router": g(ks[4], (h, E), s),
                "e_gate": g(ks[5], (E, h, fm), s),
                "e_up": g(ks[6], (E, h, fm), s),
                "e_down": g(ks[7], (E, fm, h), o / math.sqrt(fm / h)),
            })
        return lp

    keys = jax.random.split(key, L + 2)
    layers, nu_layers = [], []
    for l in range(L):
        lp = init_layer(keys[l], dense=l < c.first_dense_layers)
        nu_layers.append(jax.tree_util.tree_map(_nu_like_perlayer, lp))
        layers.append(host_put(lp, dev) if to_host else lp)

    @jax.jit
    def init_tail(ke, kh):
        embed = (jax.random.normal(ke, (c.vocab_size, h), jnp.float32)
                 * s).astype(param_dtype)
        head = (jax.random.normal(kh, (h, c.vocab_size), jnp.float32)
                * s).astype(param_dtype)
        return embed, jnp.ones((h,), param_dtype), head

    embed, fn_w, head = init_tail(keys[L], keys[L + 1])
    return StreamTrainState(
        layers, nu_layers, embed, fn_w, head,
        _nu_like_perlayer(embed), _nu_like_perlayer(fn_w),
        _nu_like_perlayer(head), 0)


def make_streaming_moe_train_step(config, optimizer: str = "adafactor",
                                  lr=3e-4, wd=0.1, adafactor_clip=1.0):
    """Host-streamed layerwise train step for MoE configs — trains
    DeepSeekMoE-16B (33 GB of bf16 params, BASELINE config 5) on one
    16 GB chip, the MoE twin of :func:`make_streaming_train_step`.

    Same mechanism (pinned_host residency, prefetch-next-layer, per-layer
    vjp + donated adafactor update, stream-back), plus the MoE-specific
    piece: the router aux loss. ``loss = CE + coef · Σ_l aux_l`` and each
    layer's aux contribution is LOCAL to that layer, so its gradient
    enters the per-layer vjp as a constant cotangent ``coef`` on the
    layer's aux output — no cross-layer aux state is ever needed.

    Parity: incubate/distributed/models/moe (the reference's MoE stack)
    has no single-device answer at this scale; the capability here is the
    scheduling trade (PCIe streaming) the reference buys with multi-GPU
    sharding. Returns ``step(state, tokens) -> (state, loss)``.
    """
    from ..models import moe as _moe

    c = config
    if optimizer != "adafactor":
        raise NotImplementedError("streaming step supports adafactor")
    if getattr(c, "context_parallel", False):
        raise NotImplementedError("streaming step is single-chip")
    dt = c.dtype
    dev = jax.devices()[0]
    to_host = supports_compiled_host_memory()
    coef = float(c.router_aux_coef)
    n_dense = c.first_dense_layers

    def _fac(p, g, nu, beta2t):
        return adafactor_update(p, g, nu, lr=lr, beta2t=beta2t, eps1=1e-30,
                                eps2=1e-3, clip=adafactor_clip, wd=wd,
                                scale=1.0)

    head_grads, tail_update = _build_head_tail(c, _fac)
    _fetch, _park = _make_fetch_park(dev, to_host)

    @jax.jit
    def embed_fwd(embed, tokens):
        return embed.astype(dt)[tokens]

    @functools.partial(jax.jit, static_argnames=("dense",))
    def layer_fwd(x, aux_sum, lp, *, dense):
        cos, sin = _moe._rope_tables(x.shape[1], c.head_dim, c.rope_theta)
        (xo, aux) = _moe._layer_body((x, jnp.zeros((), jnp.float32)), lp,
                                     cos, sin, c, 0, dense)
        return xo, aux_sum + aux

    @functools.partial(jax.jit, static_argnames=("dense",),
                       donate_argnums=(0, 1))
    def layer_bwd_update(lp, nu_l, x_in, dx, beta2t, *, dense):
        cos, sin = _moe._rope_tables(x_in.shape[1], c.head_dim,
                                     c.rope_theta)

        def run(lp_, xi):
            xo, aux = _moe._layer_body((xi, jnp.zeros((), jnp.float32)),
                                       lp_, cos, sin, c, 0, dense)
            return xo, aux

        _, vjp = jax.vjp(run, lp, x_in)
        # aux cotangent = coef: d(loss)/d(aux_l) for loss = ce + coef·Σaux
        dlp, dx_prev = vjp((dx, jnp.asarray(coef, jnp.float32)))
        new_lp, new_nu = {}, {}
        for k in lp:
            new_lp[k], new_nu[k] = _fac(lp[k], dlp[k], nu_l[k], beta2t)
        return new_lp, new_nu, dx_prev

    def step(state: StreamTrainState, tokens):
        L = c.num_layers
        inp = tokens[:, :-1]
        tgt = tokens[:, 1:]
        beta2t = 1.0 - float(state.step + 1) ** -0.8

        xs = [None] * L
        x = embed_fwd(state.embed, inp)
        aux_sum = jnp.zeros((), jnp.float32)
        nxt = _fetch(state.layers[0])
        for l in range(L):
            cur, nxt = nxt, (_fetch(state.layers[l + 1])
                             if l + 1 < L else None)
            xs[l] = x
            x, aux_sum = layer_fwd(x, aux_sum, cur, dense=l < n_dense)
            cur = None

        ce, (dx, dfn, dhead) = head_grads(
            x, state.final_norm, state.lm_head, tgt)

        new_layers = list(state.layers)
        new_nu_layers = list(state.nu_layers)
        nxt = _fetch(state.layers[L - 1])
        for l in range(L - 1, -1, -1):
            cur, nxt = nxt, (_fetch(state.layers[l - 1]) if l > 0 else None)
            new_lp, new_nu, dx = layer_bwd_update(
                cur, state.nu_layers[l], xs[l], dx, beta2t,
                dense=l < n_dense)
            new_layers[l] = _park(new_lp)
            new_nu_layers[l] = new_nu
            xs[l] = None

        new_e, new_f, new_h, nnu_e, nnu_f, nnu_h = tail_update(
            state.embed, state.final_norm, state.lm_head,
            state.nu_embed, state.nu_fn, state.nu_head, inp, dx, dfn,
            dhead, beta2t)
        loss = ce + coef * aux_sum
        return StreamTrainState(
            new_layers, new_nu_layers, new_e, new_f, new_h,
            nnu_e, nnu_f, nnu_h, state.step + 1), loss

    return step
