"""paddle_tpu.optimizer (parity: python/paddle/optimizer/__init__.py)."""
from __future__ import annotations

from . import lr  # noqa: F401
from .optimizer import Optimizer  # noqa: F401
from .optimizers import (  # noqa: F401
    ASGD, LBFGS, SGD, Adadelta, Adagrad, Adam, Adamax, AdamW, Lamb, Momentum,
    NAdam, RAdam, RMSProp, Rprop,
)


class L2Decay:
    """paddle.regularizer.L2Decay parity."""

    def __init__(self, coeff=0.0):
        self._coeff = coeff


class L1Decay:
    def __init__(self, coeff=0.0):
        self._coeff = coeff
