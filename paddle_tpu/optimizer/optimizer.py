"""Optimizer base.

Parity: python/paddle/optimizer/optimizer.py — parameter groups, grad clip,
regularization (L2 coupled / decoupled), multi-precision master weights
(reference master-weight path: optimizer multi_precision + fp16 utils).

The per-param update math lives in pure functions (``_update``) over raw jax
arrays so the same rule serves the eager ``step()`` (buffer-swap) and the
functional/jit path (``apply_gradients``).
"""
from __future__ import annotations

from collections import defaultdict
from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from ..autograd import no_grad
from ..framework import dtype as dtypes
from ..core.tensor import Tensor
from .lr import LRScheduler


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        if parameters is None:
            raise ValueError(
                "parameters is required in this framework (dygraph semantics)")
        self._parameter_list = list(parameters)
        self._param_groups = []
        if self._parameter_list and isinstance(self._parameter_list[0], dict):
            groups = self._parameter_list
            self._parameter_list = []
            for g in groups:
                self._param_groups.append(g)
                self._parameter_list += list(g["params"])
        self._learning_rate = learning_rate
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        if isinstance(weight_decay, (float, int)):
            self._coupled_wd = float(weight_decay)
        elif weight_decay is not None and hasattr(weight_decay, "_coeff"):
            self._coupled_wd = float(weight_decay._coeff)
        else:
            self._coupled_wd = 0.0
        # state: id(param) -> dict of accumulators (raw arrays)
        self._state: Dict[int, dict] = defaultdict(dict)
        self._master_weights: Dict[int, object] = {}
        self._global_step = 0

    # -- lr ---------------------------------------------------------------
    def get_lr(self) -> float:
        if isinstance(self._learning_rate, LRScheduler):
            return float(self._learning_rate())
        return float(self._learning_rate)

    def set_lr(self, value):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._learning_rate = float(value)

    def set_lr_scheduler(self, scheduler):
        self._learning_rate = scheduler

    # -- main entry points -------------------------------------------------
    @no_grad()
    def step(self):
        params_grads = [(p, p.grad) for p in self._parameter_list
                        if p.grad is not None and not p.stop_gradient]
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        lr = self.get_lr()
        for p, g in params_grads:
            pid = id(p)
            state = self._state[pid]
            gv = g._value if isinstance(g, Tensor) else g
            pv = p._value
            # multi-precision master weights for low-precision params
            master = None
            if self._multi_precision and np.dtype(pv.dtype).itemsize < 4 and \
                    dtypes.np_is_floating(pv.dtype):
                master = self._master_weights.get(pid)
                if master is None:
                    master = pv.astype(jnp.float32)
                pv_eff = master
                gv = gv.astype(jnp.float32)
            else:
                pv_eff = pv
            if self._coupled_wd and self._use_coupled_weight_decay():
                gv = gv + self._coupled_wd * pv_eff.astype(gv.dtype)
            param_lr = lr * p.optimize_attr.get("learning_rate", 1.0) \
                if hasattr(p, "optimize_attr") else lr
            new_v = self._update(pv_eff, gv, state, param_lr, p)
            if master is not None:
                self._master_weights[pid] = new_v
                p._replace_value(new_v.astype(pv.dtype))
            else:
                p._replace_value(new_v.astype(pv.dtype))
        self._global_step += 1

    def _use_coupled_weight_decay(self) -> bool:
        return True

    def _update(self, p, g, state, lr, param):
        raise NotImplementedError

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        loss.backward()
        self.step()
        return None, None

    def clear_grad(self, set_to_zero: bool = False):
        for p in self._parameter_list:
            p.clear_grad(set_to_zero)

    clear_gradients = clear_grad

    # -- state dict --------------------------------------------------------
    def state_dict(self):
        out = {"global_step": self._global_step}
        for i, p in enumerate(self._parameter_list):
            st = self._state.get(id(p), {})
            for k, v in st.items():
                out[f"param{i}.{k}"] = Tensor(v) if not isinstance(v, Tensor) else v
            if id(p) in self._master_weights:
                out[f"param{i}.master_weight"] = Tensor(self._master_weights[id(p)])
        if isinstance(self._learning_rate, LRScheduler):
            out["LR_Scheduler"] = self._learning_rate.state_dict()
        return out

    def set_state_dict(self, state):
        self._global_step = int(state.get("global_step", 0))
        for i, p in enumerate(self._parameter_list):
            prefix = f"param{i}."
            for k, v in state.items():
                if isinstance(k, str) and k.startswith(prefix):
                    name = k[len(prefix):]
                    val = v._value if isinstance(v, Tensor) else jnp.asarray(v)
                    if name == "master_weight":
                        self._master_weights[id(p)] = val
                    else:
                        self._state[id(p)][name] = val
        if "LR_Scheduler" in state and isinstance(self._learning_rate, LRScheduler):
            self._learning_rate.set_state_dict(state["LR_Scheduler"])

    # -- functional path (jit/pjit training steps) -------------------------
    def apply_gradients_functional(self, params: dict, grads: dict, state: dict,
                                   lr: Optional[float] = None):
        """Pure update: (params, grads, state) pytrees -> (new_params, new_state).

        Used by captured train steps; the same ``_update`` rule runs under
        jit/pjit with state threaded explicitly."""
        lr = self.get_lr() if lr is None else lr
        new_params, new_state = {}, {}
        for k, pv in params.items():
            gv = grads.get(k)
            if gv is None:
                new_params[k] = pv
                new_state[k] = state.get(k, {})
                continue
            st = dict(state.get(k, {}))
            if self._coupled_wd and self._use_coupled_weight_decay():
                gv = gv + self._coupled_wd * pv.astype(gv.dtype)
            new_params[k] = self._update(pv, gv, st, lr, None).astype(pv.dtype)
            new_state[k] = st
        return new_params, new_state

    def init_state_functional(self, params: dict):
        return {k: {} for k in params}

    @property
    def _learning_rate_scheduler(self):
        return self._learning_rate if isinstance(self._learning_rate, LRScheduler) \
            else None
