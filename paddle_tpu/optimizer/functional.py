"""Functional optimizer steps for the jit-compiled training path.

Parity: the reference's fused device optimizers — AdamW
(paddle/phi/kernels/gpu/adamw_kernel.cu, python surface
optimizer/adamw.py:54) plus its memory-saving modes: multi_precision
bf16-param training (adamw.py `_multi_precision`) and the master-weight
scheme. The factored second moment is the Adafactor trade
(memory-efficient-adaptivity; the reference exposes the same trade through
incubate distributed_fused_lamb / sharding offload knobs).

TPU-native design: pure functions over param pytrees — the whole
update fuses into the train step's single XLA program; optimizer
"memory modes" are just dtypes/shapes of the moment pytrees:

  * ``adamw`` + f32 moments: 8 bytes/param of optimizer state.
  * ``adamw`` + bf16 moments: 4 bytes/param (quality cost ~none at scale).
  * ``adafactor``: O(rows+cols) second moment, no first moment —
    ~0 bytes/param; the standard way to fit >2B params on one 16GB chip.

All math runs in f32 regardless of storage dtype; params may themselves be
stored bf16 (pure-bf16 training) — updates are computed f32 and cast back.
"""
from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

__all__ = ["init_moments", "moment_shardings", "optimizer_update",
           "adamw_update", "adafactor_update"]

_f32 = jnp.float32


def _tmap(f, *trees):
    return jax.tree_util.tree_map(f, *trees)


def init_moments(params, optimizer: str = "adamw",
                 moment_dtype=jnp.float32):
    """Return (mu, nu) moment pytrees for ``optimizer``.

    adamw: mu/nu shaped like params in ``moment_dtype``.
    adafactor: mu is per-leaf zeros[()] placeholders (no first moment); nu
    leaves are dicts {"vr": [..., rows], "vc": [..., cols]} for ndim>=2
    (factored over the trailing two dims, leading stack dims kept) or
    {"v": full} for vectors/scalars.
    """
    if optimizer == "adamw":
        zeros = _tmap(lambda p: jnp.zeros(p.shape, moment_dtype), params)
        return zeros, _tmap(lambda p: jnp.zeros(p.shape, moment_dtype),
                            params)
    if optimizer == "adafactor":
        def nu_like(p):
            if p.ndim >= 2:
                return {"vr": jnp.zeros(p.shape[:-1], _f32),
                        "vc": jnp.zeros(p.shape[:-2] + p.shape[-1:], _f32)}
            return {"v": jnp.zeros(p.shape, _f32)}

        mu = _tmap(lambda p: jnp.zeros((), _f32), params)
        return mu, _tmap(nu_like, params)
    raise ValueError(f"unknown optimizer {optimizer!r}")


def moment_shardings(param_shardings, params, optimizer: str = "adamw"):
    """Shardings for the (mu, nu) trees of ``init_moments``.

    adamw moments are param-shaped, so they reuse the param shardings.
    adafactor's mu is scalar placeholders (replicated) and nu is factored
    {"vr","vc"}/{"v"} dicts whose specs are the param spec with the reduced
    dim dropped — device_put'ing those with param shardings is a shape
    mismatch (the memory-mode crash this fixes).
    ``params`` may be real or abstract (only .ndim is read).
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    if optimizer == "adamw":
        return param_shardings, param_shardings
    if optimizer == "adafactor":
        def mu_sh(s, p):
            return NamedSharding(s.mesh, P())

        def nu_sh(s, p):
            spec = tuple(s.spec) + (None,) * (p.ndim - len(s.spec))
            if p.ndim >= 2:
                return {"vr": NamedSharding(s.mesh, P(*spec[:-1])),
                        "vc": NamedSharding(s.mesh,
                                            P(*(spec[:-2] + spec[-1:])))}
            return {"v": NamedSharding(s.mesh, P(*spec))}

        return (_tmap(mu_sh, param_shardings, params),
                _tmap(nu_sh, param_shardings, params))
    raise ValueError(f"unknown optimizer {optimizer!r}")


def adamw_update(p, g, m, n, *, lr, beta1, beta2, eps, wd, scale, bc1, bc2):
    """One AdamW leaf update; moments stored in their own dtype, math f32."""
    g = g.astype(_f32) * scale
    mf = m.astype(_f32)
    nf = n.astype(_f32)
    mf = beta1 * mf + (1 - beta1) * g
    nf = beta2 * nf + (1 - beta2) * g * g
    u = (mf / bc1) / (jnp.sqrt(nf / bc2) + eps)
    new_p = p.astype(_f32) - lr * (u + wd * p.astype(_f32))
    return new_p.astype(p.dtype), mf.astype(m.dtype), nf.astype(n.dtype)


def adafactor_update(p, g, nu, *, lr, beta2t, eps1, eps2, clip, wd, scale):
    """One Adafactor leaf update (Shazeer & Stern 2018): factored second
    moment over the trailing two dims, RMS-clipped update, no first moment."""
    g = g.astype(_f32) * scale
    g2 = g * g + eps1
    if "vr" in nu:
        vr = beta2t * nu["vr"] + (1 - beta2t) * jnp.mean(g2, axis=-1)
        vc = beta2t * nu["vc"] + (1 - beta2t) * jnp.mean(g2, axis=-2)
        # v̂ = vr ⊗ vc / row-sum(vr)  (rank-1 reconstruction)
        denom = jnp.mean(vr, axis=-1, keepdims=True)
        v = (vr / denom)[..., :, None] * vc[..., None, :]
        new_nu = {"vr": vr, "vc": vc}
    else:
        v = beta2t * nu["v"] + (1 - beta2t) * g2
        new_nu = {"v": v}
    u = g * jax.lax.rsqrt(v + eps1)
    # clip update RMS to `clip` (d=1.0 in the paper)
    rms = jnp.sqrt(jnp.mean(u * u) + 1e-30)
    u = u / jnp.maximum(1.0, rms / clip)
    step_size = jnp.maximum(eps2, lr)
    new_p = p.astype(_f32) - step_size * (u + wd * p.astype(_f32))
    return new_p.astype(p.dtype), new_nu


def optimizer_update(params, grads, mu, nu, step, *, optimizer="adamw",
                     lr=3e-4, beta1=0.9, beta2=0.95, eps=1e-8, wd=0.1,
                     scale=1.0, adafactor_clip=1.0):
    """Apply one optimizer step over whole pytrees. Returns
    (params, mu, nu). ``scale`` folds in grad clipping / accumulation."""
    t = (step + 1).astype(_f32)
    if optimizer == "adamw":
        bc1 = 1.0 - beta1 ** t
        bc2 = 1.0 - beta2 ** t
        flat_p, treedef = jax.tree_util.tree_flatten(params)
        outs = [adamw_update(p, g, m, n, lr=lr, beta1=beta1, beta2=beta2,
                             eps=eps, wd=wd, scale=scale, bc1=bc1, bc2=bc2)
                for p, g, m, n in zip(
                    flat_p, jax.tree_util.tree_leaves(grads),
                    jax.tree_util.tree_leaves(mu),
                    jax.tree_util.tree_leaves(nu))]
        unflat = lambda i: jax.tree_util.tree_unflatten(
            treedef, [o[i] for o in outs])
        return unflat(0), unflat(1), unflat(2)
    if optimizer == "adafactor":
        # decaying beta2̂_t = 1 - t^-0.8 (paper §7), lr as relative step
        beta2t = 1.0 - t ** -0.8
        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat_g = jax.tree_util.tree_leaves(grads)
        flat_nu = treedef.flatten_up_to(nu)
        outs = [adafactor_update(p, g, n, lr=lr, beta2t=beta2t, eps1=1e-30,
                                 eps2=1e-3, clip=adafactor_clip, wd=wd,
                                 scale=scale)
                for p, g, n in zip(flat_p, flat_g, flat_nu)]
        new_p = jax.tree_util.tree_unflatten(treedef, [o[0] for o in outs])
        new_nu = jax.tree_util.tree_unflatten(treedef, [o[1] for o in outs])
        return new_p, mu, new_nu
    raise ValueError(f"unknown optimizer {optimizer!r}")
