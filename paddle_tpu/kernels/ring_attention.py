"""Ring attention — context parallelism over the sequence axis.

The reference has NO in-core ring attention (SURVEY.md §5.7: its SEP axis
splits the sequence and leaves full-sequence attention to downstream model
code via alltoall — fleet/meta_parallel/segment_parallel.py:26,
hybrid_parallel_util.py:278-311). This module supplies the long-context
capability TPU-natively: blockwise attention where each device holds one
sequence shard of Q/K/V and K/V blocks rotate around the ring via
``jax.lax.ppermute`` over ICI, with online-softmax (m, l, acc) accumulation —
activation memory O(S_local), full-sequence exact attention.

Used under ``jax.shard_map`` over the mesh axis that shards the sequence
('sp'/'cp'). Causal masking is block-triangular: a device's Q block attends
fully to earlier K/V blocks, causally to its own, not at all to later ones
(those ring steps are masked, not skipped, to keep the loop shape static for
XLA).
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["ring_attention", "ring_attention_sharded"]


def _block_attend(q, k, v, m, l, acc, mask):
    """One online-softmax accumulation step.
    q: [B,Sq,Hq,D]; k,v: [B,Skv,Hkv,D] with Hq % Hkv == 0 (GQA: query head
    h reads kv head h // (Hq//Hkv), grouped in the einsum so K/V are never
    materialized repeated — they are what rides the ring over ICI);
    m,l: [B,Hq,Sq,1]; acc: [B,Hq,Sq,D]; mask: [Sq,Skv] bool or None."""
    B, Sq, Hq, D = q.shape
    Skv, Hkv = k.shape[1], k.shape[2]
    G = Hq // Hkv
    scale = 1.0 / math.sqrt(D)
    if G == 1:
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                       preferred_element_type=jnp.float32) * scale
    else:
        qg = q.reshape(B, Sq, Hkv, G, D)
        s = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k,
                       preferred_element_type=jnp.float32) * scale
        s = s.reshape(B, Hq, Sq, Skv)
    if mask is not None:
        s = jnp.where(mask[None, None], s, -1e30)
    m_cur = jnp.max(s, axis=-1, keepdims=True)            # [B,Hq,Sq,1]
    m_new = jnp.maximum(m, m_cur)
    p = jnp.exp(s - m_new)
    alpha = jnp.exp(m - m_new)
    l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
    if G == 1:
        pv = jnp.einsum("bhqk,bkhd->bhqd", p.astype(v.dtype), v,
                        preferred_element_type=jnp.float32)
    else:
        pg = p.reshape(B, Hkv, G, Sq, Skv).astype(v.dtype)
        pv = jnp.einsum("bhgqk,bkhd->bhgqd", pg, v,
                        preferred_element_type=jnp.float32)
        pv = pv.reshape(B, Hq, Sq, D)
    acc_new = acc * alpha + pv
    return m_new, l_new, acc_new


def ring_attention(q, k, v, axis_name: str, axis_size: int,
                   causal: bool = True):
    """Per-shard body (call inside shard_map). q/k/v: [B, S_local, H, D],
    the sequence axis sharded over ``axis_name`` (static size ``axis_size``).
    Returns [B, S_local, H, D]. Differentiable (lax.scan ring).

    GQA: pass K/V with their own (fewer) heads — the grouped einsum attends
    query head h to kv head h // (Hq//Hkv), and the ring hops move the
    UNREPEATED K/V blocks (ICI traffic / (Hq//Hkv) vs pre-expanding).
    """
    n = axis_size
    my = jax.lax.axis_index(axis_name)
    B, S, H, D = q.shape

    m0 = jnp.full((B, H, S, 1), -1e30, jnp.float32)
    l0 = jnp.zeros((B, H, S, 1), jnp.float32)
    a0 = jnp.zeros((B, H, S, D), jnp.float32)

    row = jax.lax.broadcasted_iota(jnp.int32, (S, S), 0)
    col = jax.lax.broadcasted_iota(jnp.int32, (S, S), 1)
    tri = row >= col

    def step(carry, t):
        k_t, v_t, m, l, acc = carry
        src = (my - t) % n  # which sequence block we hold this step
        if causal:
            # full attend if src < my; causal if src == my; masked out if >
            full = jnp.ones((S, S), bool)
            mask = jnp.where(src == my, tri,
                             jnp.where(src < my, full, jnp.zeros((S, S), bool)))
        else:
            mask = None
        m, l, acc = _block_attend(q, k_t, v_t, m, l, acc, mask)
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_n = jax.lax.ppermute(k_t, axis_name, perm)
        v_n = jax.lax.ppermute(v_t, axis_name, perm)
        return (k_n, v_n, m, l, acc), None

    (k_f, v_f, m, l, acc), _ = jax.lax.scan(
        step, (k, v, m0, l0, a0), jnp.arange(n))
    out = acc / jnp.maximum(l, 1e-30)
    return jnp.einsum("bhqd->bqhd", out).astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh: Mesh, axis_name: str = "sp",
                           causal: bool = True,
                           batch_axis: Optional[str] = "dp"):
    """Convenience wrapper: runs ring_attention under shard_map over ``mesh``.
    q/k/v are GLOBAL [B, S, H, D] arrays (sequence logically sharded over
    axis_name, batch over batch_axis if present)."""
    ba = batch_axis if (batch_axis and batch_axis in mesh.axis_names) else None
    spec = P(ba, axis_name, None, None)
    fn = jax.shard_map(
        functools.partial(ring_attention, axis_name=axis_name,
                          axis_size=mesh.shape[axis_name], causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)
