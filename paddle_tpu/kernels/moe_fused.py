"""Fused dropless-MoE dispatch: the scatter-free grouped-GEMM hot path.

The r05 bisect (docs/moe.md, "r05 regression postmortem") localized the
MoE step's overhead to the dispatch data movement around the grouped
GEMMs: the gather→GEMM→scatter round-trips through HBM that the
FlashFuser line of work (PAPERS.md) argues should be fused across the
dispatch boundary. This module is that fusion, in two layers:

* **Portable XLA rewrite** (`fused_moe_ffn`, every backend): the routed
  FFN is restructured so that *no scatter exists in forward or backward*:

  - the combine weight ``w`` (and the int8 down-projection scales) are
    folded into the elementwise silu chain BEFORE the down GEMM — the
    post-GEMM ``[A, h]`` f32 weighting multiply disappears into an
    elementwise chain XLA already fuses;
  - the gate-weighted combine-scatter becomes a **gather**: token ``t``'s
    ``k`` routed outputs sit at known sorted positions (the inverse of the
    expert-sort permutation), so ``y[t] = Σ_j ys[inv[t, j]]`` — the same
    scatter→gather trade that made the dense-base form's combine 3 ms/layer
    cheaper on v5e, now applied to the grouped-GEMM form;
  - both gathers carry hand-written VJPs whose backward is *also* a pure
    gather (``d_ys[p] = dy[tok[p]]``, ``dx[t] = Σ_j d_xs[inv[t, j]]``),
    instead of the scatter-add ``jnp.take``'s autodiff would emit.

* **Pallas kernel** (`gather_gmm`, TPU): the expert-sort gather is folded
  into the grouped GEMM's lhs load — each row tile is DMA-gathered from
  the token activations in HBM directly into VMEM (no ``[A, h]`` gathered
  copy ever materializes in HBM), and int8 expert weights stream into
  VMEM *unconverted* (half the rhs bytes; dequantized in-register).
  Requires a per-group tile-padded row layout (built host-free in XLA int
  ops; padding rows carry combine weight 0, so they are exact no-ops in
  both directions). Covered by the ``tests_tpu/`` lane; any failure to
  build falls back to the XLA rewrite at trace time.

Expert weights may be plain arrays or int8 dicts ``{"q": int8, "s": f32}``
from :func:`paddle_tpu.kernels.quant_matmul.quantize_grouped` — gate/up
scales ride the gu elementwise chain, down scales ride the combine-weight
chain (:mod:`quant_matmul`'s output-scaling idiom, grouped).

Path taken is visible as ``moe_gmm_fused_dispatch_total{path}`` with
path ∈ {pallas, xla, xla_fallback}.
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..framework.flags import define_flag, get_flag
from ..observability import numerics as _numerics
from ..observability.catalog import instrument as _instrument
from .quant_matmul import is_quantized_weight

define_flag("moe_fused_kernel", True,
            "use the Pallas gather-fused grouped-GEMM kernel for the "
            "fused MoE dispatch on TPU (off = the portable XLA rewrite "
            "everywhere)")

__all__ = ["fused_moe_ffn", "gather_gmm"]

_M_FUSED = _instrument("moe_gmm_fused_dispatch_total")

# m tile of the gather-fused kernel: small keeps the per-group padding
# waste bounded (≤ E*(KTM-1) rows ≈ 6% at the bench shape)
_KTM = 128


# ---------------------------------------------------------------------------
# scatter-free gathers with gather-based VJPs
# ---------------------------------------------------------------------------

def _inverse_permutation(order):
    """inv with inv[order[p]] = p (an int scatter over [A] ids — the only
    scatter-shaped op left in the pipeline, 4 bytes/row)."""
    A = order.shape[0]
    return jnp.zeros((A,), jnp.int32).at[order].set(
        jnp.arange(A, dtype=jnp.int32))


@jax.custom_vjp
def _gather_rows(x, tok, inv2d):
    """xs[p] = x[tok[p]] — the dispatch gather, with a gather-based VJP.

    ``inv2d[t, j]`` is the row of ``xs`` holding token t's j-th
    assignment, so backward is ``dx[t] = Σ_j d_xs[inv2d[t, j]]`` — a
    k-way gathered sum instead of take's scatter-add transpose. Rows of
    ``xs`` not referenced by ``inv2d`` (per-group tile padding) must
    carry zero cotangents, which the combine-weight fold guarantees."""
    return jnp.take(x, tok, axis=0)


def _gather_rows_fwd(x, tok, inv2d):
    return jnp.take(x, tok, axis=0), (inv2d,)


def _gather_rows_bwd(res, d_xs):
    (inv2d,) = res
    T, k = inv2d.shape
    dx = jnp.sum(
        jnp.take(d_xs, inv2d.reshape(-1), axis=0)
        .reshape(T, k, d_xs.shape[1]).astype(jnp.float32), axis=1)
    return dx.astype(d_xs.dtype), None, None


_gather_rows.defvjp(_gather_rows_fwd, _gather_rows_bwd)


@jax.custom_vjp
def _combine_rows(ys, inv2d, tok):
    """y[t] = Σ_j ys[inv2d[t, j]] in f32 — the combine, as a gather.

    The gate weights are already folded into ``ys``'s producer, so both
    directions are coefficient-free gathers: backward is
    ``d_ys[p] = dy[tok[p]]``. For padded layouts the extra rows receive
    the cotangent of token ``tok[p]`` even though they contributed
    nothing — exact anyway, because their folded combine weight is 0, so
    every downstream product vanishes."""
    T, k = inv2d.shape
    return jnp.sum(
        jnp.take(ys, inv2d.reshape(-1), axis=0)
        .reshape(T, k, ys.shape[1]).astype(jnp.float32), axis=1)


def _combine_rows_fwd(ys, inv2d, tok):
    return _combine_rows(ys, inv2d, tok), (jnp.zeros((), ys.dtype), tok)


def _combine_rows_bwd(res, dy):
    proto, tok = res
    return jnp.take(dy, tok, axis=0).astype(proto.dtype), None, None


_combine_rows.defvjp(_combine_rows_fwd, _combine_rows_bwd)


# ---------------------------------------------------------------------------
# expert-weight unpacking (bf16 arrays or int8 {"q", "s"} leaves)
# ---------------------------------------------------------------------------

def _unpack(w):
    """-> (matrix, scales | None); int8 scales are constants
    (stop_gradient), so quantization never leaks into any grad."""
    if is_quantized_weight(w):
        return (jax.lax.stop_gradient(w["q"]),
                jax.lax.stop_gradient(w["s"]).astype(jnp.float32))
    return w, None


def _gate_up(e_gate, e_up, dt):
    """Concatenate gate|up into the single wide grouped GEMM rhs.
    Returns (Wcat [E, h, 2f] in dt or int8, scales [E, 2f] | None)."""
    qg, sg = _unpack(e_gate)
    qu, su = _unpack(e_up)
    if (sg is None) != (su is None):
        raise ValueError("e_gate/e_up must be both quantized or neither")
    cat = jnp.concatenate([qg, qu], axis=-1)
    if sg is None:
        return cat.astype(dt), None
    return cat, jnp.concatenate([sg, su], axis=-1)


def _grouped(xs, w, gs, full_rows):
    """grouped_matmul with inline int8 conversion (the convert fuses into
    the rhs read on the XLA path; the Pallas kernel reads int8 raw)."""
    from .moe_dispatch import grouped_matmul

    if w.dtype == jnp.int8:
        w = w.astype(xs.dtype)
    return grouped_matmul(xs, w, gs, full_rows=full_rows)


# ---------------------------------------------------------------------------
# Pallas gather-fused grouped GEMM (TPU)
# ---------------------------------------------------------------------------

def _kernel_tn(n: int, h: int = 0, rhs_itemsize: int = 2,
               x_itemsize: int = 2) -> Optional[int]:
    """Largest n tile that divides ``n`` AND keeps the kernel's VMEM
    residency inside the same ~15.5 MiB envelope gmm_autotune._fits is
    calibrated to: double-buffered rhs blocks (2*h*tn), the [tm, h] lhs
    gather scratch, and double-buffered [tm, tn] f32-accumulated output
    blocks. The enclosing jit compiles the Mosaic kernel long after
    trace time, where the try/except around the call site can no longer
    catch it — so anything that would blow VMEM must be screened out
    HERE (None = use the XLA rewrite)."""
    for t in (512, 256, 128):
        if n % t:
            continue
        vmem = (2 * h * t * rhs_itemsize        # rhs double-buffered
                + _KTM * h * x_itemsize         # lhs gather scratch
                + 2 * _KTM * t * 4)             # out blocks (f32 acc)
        if vmem <= 15.5 * 2**20:
            return t
    return None


def gather_gmm(x, idx, rhs, gid, *, tm: int = _KTM,
               tn: Optional[int] = None, out_dtype=None,
               interpret: bool = False):
    """``out[i*tm + r] = x[idx[i*tm + r]] @ rhs[gid[i]]`` — a grouped
    matmul whose lhs rows are DMA-gathered from ``x`` (HBM) inside the
    kernel: the expert-sort gather folded into the GEMM lhs load, the
    FlashFuser move. Each m tile belongs to ONE group (``gid`` per tile,
    scalar-prefetched), which the caller guarantees via the per-group
    tile-padded layout. int8 ``rhs`` streams to VMEM unconverted and is
    widened in-register.

    The gather runs once per m tile (at the first n step) into a VMEM
    scratch reused across the n tiles; output stores are plain blocked
    writes — with the combine weight folded into the lhs by the caller,
    the store IS the weighted combine contribution, and no scatter
    follows."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    A_pad = idx.shape[0]
    T, h = x.shape
    E, h2, n = rhs.shape
    assert h2 == h and A_pad % tm == 0
    tn = tn or _kernel_tn(n, h, rhs.dtype.itemsize, x.dtype.itemsize)
    if tn is None or h % 128:
        raise ValueError(f"gather_gmm: unaligned/oversized shape "
                         f"h={h} n={n}")
    out_dtype = out_dtype or x.dtype
    grid = (A_pad // tm, n // tn)

    def kernel(idx_ref, gid_ref, x_hbm, rhs_ref, out_ref, xs_vmem, sem):
        i = pl.program_id(0)
        j = pl.program_id(1)

        @pl.when(j == 0)
        def _gather():                 # once per m tile, reused over n
            def body(r, _):
                row = idx_ref[i * tm + r]
                cp = pltpu.make_async_copy(
                    x_hbm.at[row], xs_vmem.at[r], sem)
                cp.start()
                cp.wait()
                return 0
            jax.lax.fori_loop(0, tm, body, 0)

        lhs = xs_vmem[...]
        blk = rhs_ref[0]
        if blk.dtype != lhs.dtype:     # int8 weights: widen in-register
            blk = blk.astype(lhs.dtype)
        out_ref[...] = jax.lax.dot_general(
            lhs, blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(out_ref.dtype)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.ANY),        # x stays in HBM
            pl.BlockSpec((1, h, tn),
                         lambda i, j, idx_ref, gid_ref: (gid_ref[i], 0, j)),
        ],
        out_specs=pl.BlockSpec((tm, tn), lambda i, j, *_: (i, j)),
        scratch_shapes=[
            pltpu.VMEM((tm, h), x.dtype),
            pltpu.SemaphoreType.DMA(()),
        ],
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((A_pad, n), out_dtype),
        grid_spec=grid_spec,
        compiler_params=pltpu.TPUCompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=interpret,
    )(idx, gid, x, rhs)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def _gather_gmm_op(x, tok_pad, inv2d, rhs, gs_pad, full_rows):
    """Differentiable wrapper: forward is the Pallas kernel; backward is
    the standard megablox dgrad/wgrad over the rematerialized gather
    (padding rows carry zero cotangents — see the combine-weight fold)."""
    gid = _tile_gids(gs_pad, tok_pad.shape[0], _KTM)
    return gather_gmm(x, tok_pad, rhs, gid, tm=_KTM)


def _tile_gids(gs_pad, A_pad, tm):
    """Group id of each m tile of the padded layout (every tile lies
    inside one group by construction; tail tiles clamp to the last)."""
    E = gs_pad.shape[0]
    starts = jnp.arange(A_pad // tm, dtype=jnp.int32) * tm
    gid = jnp.searchsorted(jnp.cumsum(gs_pad), starts, side="right")
    return jnp.minimum(gid, E - 1).astype(jnp.int32)


def _gather_gmm_fwd(x, tok_pad, inv2d, rhs, gs_pad, full_rows):
    out = _gather_gmm_op(x, tok_pad, inv2d, rhs, gs_pad, full_rows)
    return out, (x, tok_pad, inv2d, rhs, gs_pad)


def _gather_gmm_bwd(full_rows, res, g):
    from .gmm_autotune import get_tilings
    from jax.experimental.pallas.ops.tpu.megablox.gmm import (
        gmm as _gmm, tgmm as _tgmm)

    x, tok_pad, inv2d, rhs, gs_pad = res
    T, h = x.shape
    E, _, n = rhs.shape
    m = tok_pad.shape[0]
    dt = x.dtype
    w = rhs.astype(dt) if rhs.dtype == jnp.int8 else rhs
    tri = get_tilings(m, h, n, E, dt, bool(full_rows), variant="fused")
    if tri is None:
        # unaligned for megablox: the ragged_dot transpose handles it
        xs = jnp.take(x, tok_pad, axis=0)
        _, vjp = jax.vjp(
            lambda a, b: jax.lax.ragged_dot(a, b, gs_pad), xs, w)
        d_xs, d_rhs = vjp(g)
    else:
        d_xs = _gmm(g, w, gs_pad, preferred_element_type=dt,
                    tiling=tri[1], transpose_rhs=True)
        xs = jnp.take(x, tok_pad, axis=0)
        d_rhs = _tgmm(xs.swapaxes(0, 1), g, gs_pad,
                      preferred_element_type=jnp.float32, tiling=tri[2],
                      num_actual_groups=E)
    Tk = inv2d.shape
    dx = jnp.sum(
        jnp.take(d_xs, inv2d.reshape(-1), axis=0)
        .reshape(Tk[0], Tk[1], h).astype(jnp.float32), axis=1).astype(dt)
    if rhs.dtype == jnp.int8:
        d_rhs = None                   # int8 experts are frozen
    else:
        d_rhs = d_rhs.astype(rhs.dtype)
    return dx, None, None, d_rhs, None


_gather_gmm_op.defvjp(_gather_gmm_fwd, _gather_gmm_bwd)


# ---------------------------------------------------------------------------
# the fused routed FFN
# ---------------------------------------------------------------------------

def _routing_meta(x, weights, idx, routing):
    from .moe_dispatch import sort_by_expert

    T, k = idx.shape
    if routing is None:
        order, tok, flat_e = sort_by_expert(idx)
        E = None
        gs = None
    else:
        order, tok, flat_e, gs = (routing.order, routing.tok,
                                  routing.flat_e, routing.gs)
    return order, tok, flat_e, gs


def _elementwise_core(gu, s_gu, ws, s_down, esorted, f, dt):
    """silu(g)·u with every per-row coefficient folded in: the combine
    weight, and (int8) the gate/up output scales + down input scales.
    One fused elementwise chain — the coefficients ride for free."""
    if s_gu is not None:
        gu = gu * jnp.take(s_gu, esorted, axis=0).astype(gu.dtype)
    z = jax.nn.silu(gu[..., :f]) * gu[..., f:]
    coef = ws
    zw = z * coef.astype(dt)[:, None]
    if s_down is not None:
        zw = zw * jnp.take(s_down, esorted, axis=0).astype(dt)
    return zw


def fused_moe_ffn(x, weights, idx, e_gate, e_up, e_down,
                  routing=None):
    """Capacity-less routed FFN, fused scatter-free form (single program).

    Semantically identical to :func:`moe_dispatch.dropless_moe_ffn`
    (same grouped GEMMs over the same expert-sorted rows); the data
    movement differs: combine weights fold into the pre-down-GEMM
    elementwise chain, the combine is a k-way gather, and both gathers'
    VJPs are gathers. On TPU (``FLAGS_moe_fused_kernel``) the dispatch
    gather additionally folds into the Pallas grouped-GEMM lhs load via
    the per-group tile-padded layout. Expert weights may be int8 dicts
    (:func:`quant_matmul.quantize_grouped`) — scales fold into the same
    chains, gradients never touch them."""
    T, h = x.shape
    k = idx.shape[1]
    A = T * k
    dt = x.dtype
    qg, _ = _unpack(e_gate)
    E = qg.shape[0]
    f = qg.shape[-1]

    order, tok, flat_e, gs = _routing_meta(x, weights, idx, routing)
    if gs is None:
        gs = jnp.zeros((E,), jnp.int32).at[flat_e].add(1)
    esorted = flat_e[order]
    inv = _inverse_permutation(order)
    inv2d = inv.reshape(T, k)
    ws = weights.reshape(A)[order].astype(jnp.float32)

    Wcat, s_gu = _gate_up(e_gate, e_up, dt)
    Wd, s_down = _unpack(e_down)
    if s_down is None:
        Wd = Wd.astype(dt)

    use_kernel = (jax.default_backend() == "tpu"
                  and get_flag("moe_fused_kernel")
                  and h % 128 == 0
                  and _kernel_tn(2 * f, h, Wcat.dtype.itemsize,
                                 x.dtype.itemsize) is not None
                  and A >= _KTM)
    y = None
    if use_kernel:
        try:
            y = _fused_padded(x, ws, tok, esorted, gs, inv2d, Wcat, s_gu,
                              Wd, s_down, E, f, dt)
            _M_FUSED.labels(path="pallas").inc()
        except Exception:
            _M_FUSED.labels(path="xla_fallback").inc()
    else:
        _M_FUSED.labels(path="xla").inc()

    if y is None:
        xs = _gather_rows(x, tok, inv2d)
        gu = _grouped(xs, Wcat, gs, full_rows=True)
        zw = _elementwise_core(gu, s_gu, ws, s_down, esorted, f, dt)
        ys = _grouped(zw, Wd, gs, full_rows=True)
        y = _combine_rows(ys, inv2d, tok)
    # routed-output health probe (trace-time gated, zero ops off): with
    # int8 experts this is where a blown scale or a saturating expert
    # first becomes visible. Deliberately OUTSIDE the kernel try block:
    # a probe failure must surface, not masquerade as a Pallas fallback.
    # Lands in forward/serving programs and remat'd training bodies;
    # un-checkpointed grad drops in-scan probes (the models' ladder
    # covers training) — see numerics.record_stats.
    _numerics.record_stats("moe.routed_out", y)
    return y.astype(dt)


def _pad_layout(gs, tok, ws, esorted, inv2d, E: int, tm: int = _KTM):
    """Per-group tile-padded row layout for the gather-GMM kernel: each
    expert's segment is rounded up to a multiple of ``tm`` so every m
    tile lies inside ONE group. Padding rows point at token 0 with
    combine weight 0 — finite garbage that is never gathered forward,
    and every backward product through them carries the zero weight.
    Returns (tok_pad, ws_pad, es_pad, inv_pad2d, gs_pad); the padded row
    count is the static bound ``roundup(A + E*(tm-1), tm)``."""
    T, k = inv2d.shape
    A = T * k
    A_pad = -(-(A + E * (tm - 1)) // tm) * tm       # static upper bound

    tiles_per_g = -(-gs // tm)
    gs_pad = (tiles_per_g * tm).astype(jnp.int32)
    pad_off = jnp.cumsum(gs_pad) - gs_pad
    g_start = jnp.cumsum(gs) - gs
    p = jnp.arange(A, dtype=jnp.int32)
    pos_pad = (jnp.take(pad_off, esorted) + p
               - jnp.take(g_start, esorted)).astype(jnp.int32)

    tok_pad = jnp.zeros((A_pad,), jnp.int32).at[pos_pad].set(tok)
    ws_pad = jnp.zeros((A_pad,), jnp.float32).at[pos_pad].set(ws)
    es_pad = jnp.zeros((A_pad,), jnp.int32).at[pos_pad].set(esorted)
    inv_pad2d = jnp.take(pos_pad, inv2d.reshape(-1)).reshape(T, k)
    return tok_pad, ws_pad, es_pad, inv_pad2d, gs_pad


def _fused_padded(x, ws, tok, esorted, gs, inv2d, Wcat, s_gu, Wd, s_down,
                  E, f, dt):
    """The Pallas-kernel pipeline over the per-group tile-padded layout."""
    tok_pad, ws_pad, es_pad, inv_pad2d, gs_pad = _pad_layout(
        gs, tok, ws, esorted, inv2d, E)
    gu = _gather_gmm_op(x, tok_pad, inv_pad2d, Wcat, gs_pad, False)
    zw = _elementwise_core(gu, s_gu, ws_pad, s_down, es_pad, f, dt)
    ys = _grouped(zw, Wd, gs_pad, full_rows=False)
    return _combine_rows(ys, inv_pad2d, tok_pad)
