"""Measured tiling autotuner for the Mosaic grouped matmul.

The dropless-MoE grouped GEMMs (:func:`moe_dispatch.grouped_matmul`)
used to pick their ``(tm, tk, tn)`` tilings from a static heuristic
calibrated on v5e at the bench shapes. The optimum moves with device
generation, expert count, and dtype — so this module *measures*: on the
first encounter of each ``(m, k, n, E, dtype, full_rows)`` key on a TPU
backend it times a small candidate grid for all three passes (forward
gmm, dgrad gmm with ``transpose_rhs``, wgrad tgmm), keeps the winner
in-process, and persists it through the jit compile-cache machinery
(:mod:`paddle_tpu.jit.cache`, ``gmm_tilings.json``) so steady-state
steps — and future processes on the same device kind — pay zero tuning
cost.

Where measurement is impossible (CPU lane, ``FLAGS_moe_gmm_autotune``
off, or a candidate that fails to compile) the static heuristic answers
instead; unmeasured answers are cached in-process only, never
persisted, so the on-disk file holds nothing but measured winners.

Two trust guards (r05 postmortem, docs/moe.md):

* **Never-worse-than-heuristic**: the heuristic seed is always timed as
  candidate 0, and a measured winner is kept only when it beats the
  heuristic by more than the noise margin — otherwise the heuristic is
  served and ``moe_tiling_autotune_rejected_total`` counts the
  rejection. A noisy grid can therefore never regress below the static
  baseline it replaced.
* **Persisted entries are validated, not trusted**: entries whose
  tilings fall outside the Mosaic envelope (``_fits``) or alignment
  rules are dropped at load (counted as rejected) and re-measured on
  next encounter; the file carries a schema version
  (:data:`SCHEMA`) so a key-format change invalidates old documents
  wholesale instead of misreading them.

Tuning cost and cache traffic are visible in the observability catalog:
``moe_tiling_cache_{hits,misses}_total``, ``moe_tiling_autotune_seconds``
and the ``moe.autotune`` / ``moe.gmm`` spans (see docs/moe.md).
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional, Tuple

import numpy as _np

from ..framework.flags import define_flag, get_flag
from ..observability import trace_span
from ..observability.catalog import instrument as _instrument

define_flag("moe_gmm_autotune", True,
            "measure grouped-matmul tilings on first encounter of each "
            "shape (TPU only); off = the static heuristic")

__all__ = [
    "heuristic_tilings", "get_tilings", "candidate_tilings", "clear",
    "entries", "PERSIST_NAME", "SCHEMA",
]

Tiling = Tuple[int, int, int]
TriTiling = Tuple[Tiling, Tiling, Tiling]          # (fwd, dgrad, wgrad)

PERSIST_NAME = "gmm_tilings"
# v2: keys gained the dtype itemsize envelope (int8 expert weights) and
# the kernel-variant field (plain gmm vs the fused gather-GMM kernel).
SCHEMA = 2
_PASSES = ("fwd", "dgrad", "wgrad")

# a non-heuristic winner must beat the heuristic by more than this
# fraction, else measurement noise could swap in a worse tiling
_NOISE_MARGIN = 0.03

_M_HITS = _instrument("moe_tiling_cache_hits_total")
_M_MISSES = _instrument("moe_tiling_cache_misses_total")
_M_TUNE = _instrument("moe_tiling_autotune_seconds")
_M_REJECTED = _instrument("moe_tiling_autotune_rejected_total")

_LOCK = threading.Lock()
_CACHE: Dict[str, dict] = {}
_LOADED = False

_TILES = (1408, 1024, 512, 256, 128)


def _fits(tm: int, tk: int, tn: int, itemsize: int = 2) -> bool:
    """Mosaic compile envelope, calibrated on v5e: double-buffered input
    tiles within scoped VMEM, and the f32 accumulator tile below the
    observed crash line (tm*tn*4 of 4 MiB fails, 2.88 MiB compiles).
    ``itemsize`` is the operand byte width (2 = bf16, 1 = int8 expert
    weights — int8 halves the input-tile footprint, so bigger tiles fit)."""
    return (2 * itemsize * (tm * tk + tk * tn) + 4 * tm * tn
            <= 15.5 * 2**20
            and 4 * tm * tn <= 3 * 2**20)


def _aligned(t) -> bool:
    """Sanity envelope for a (t1, t2, t3) tiling from an untrusted
    source (the persisted file): positive ints, sublane/lane aligned.
    Everything the candidate generator emits passes; hand-poisoned or
    bit-rotted entries do not."""
    try:
        t1, t2, t3 = (int(v) for v in t)
    except (TypeError, ValueError):
        return False
    return (t1 > 0 and t2 > 0 and t3 > 0
            and t1 % 8 == 0 and t2 % 128 == 0 and t3 % 128 == 0)


def heuristic_tilings(m: int, k: int, n: int) -> Optional[TriTiling]:
    """Static per-pass tilings, measured on v5e at the bench shapes
    (m=32768, E=16; % of bf16 peak):

      fwd  [m,2048]@[E,2048,2816]  (512,512,1408)  33.7%  (512-cubed: 22%)
      fwd  [m,1408]@[E,1408,2048]  (256,1408,2048) 20.7%
      dgrad (transpose_rhs)        whole-K, tn=512 ~31%
      wgrad (tgmm)                 (512,512,1408)  29.2%

    The stock megablox ops.gmm shares ONE tiling between forward, dgrad,
    and tgmm — the measured optimum differs per pass (the dgrad/wgrad
    contraction is the forward's n/m), worth ~1.5x on the routed FFN.
    Returns (fwd, dgrad, wgrad) or None for shapes the kernel doesn't
    like (odd alignments → ragged_dot). tgmm's first tile divides the
    contraction (m) — it must use the same m-aligned tm as the others.

    This is the autotuner's seed ordering and its fallback whenever
    measurement is unavailable."""
    if m % 256 or k % 128 or n % 128:
        return None
    tm = 512 if m % 512 == 0 else 256
    tn = next(t for t in _TILES if n % t == 0)
    if k % 512 == 0:
        fwd_cands = [(tm, 512, tn), (tm, 512, 512), (tm, 512, 128)]
    else:
        fwd_cands = [(256, k, n), (256, k, 1024), (256, k, 512)]
    cands = {
        "fwd": fwd_cands,
        "dgrad": [(tm, n, 512), (tm, 512, 512), (tm, 128, 512)],
        "wgrad": [(tm, 512, tn), (tm, 512, 512), (tm, 512, 128)],
    }
    picked = {}
    for pass_, cs in cands.items():
        picked[pass_] = next((c for c in cs if _fits(*c)), None)
        if picked[pass_] is None:
            return None
    return picked["fwd"], picked["dgrad"], picked["wgrad"]


def candidate_tilings(m: int, k: int, n: int,
                      cap: int = 8,
                      itemsize: int = 2) -> Optional[Dict[str, list]]:
    """Per-pass candidate grid, heuristic winner first. Every candidate
    satisfies the :func:`_fits` VMEM envelope at the operand ``itemsize``
    (int8 weights admit bigger tiles); the heuristic's alignment
    preconditions gate the whole shape. ``cap`` bounds measurement cost
    (first-encounter only, but each candidate is a fresh Mosaic compile)."""
    heur = heuristic_tilings(m, k, n)
    if heur is None:
        return None
    tm_opts = [t for t in (512, 256) if m % t == 0]
    k_tiles = [t for t in (1024, 512, 256) if k % t == 0] or [k]
    n_tiles = [t for t in _TILES if n % t == 0]
    grids = {
        # fwd gmm: [m,k] @ [E,k,n] — (m tile, k contraction tile, n tile)
        "fwd": [(tm, tk, tn)
                for tm in tm_opts for tk in k_tiles for tn in n_tiles],
        # dgrad gmm (transpose_rhs): [m,n] @ [E,n,k]^T — contraction is n
        "dgrad": [(tm, t2, t3)
                  for tm in tm_opts
                  for t2 in dict.fromkeys((n, 512, 128))
                  for t3 in (512, 256)],
        # wgrad tgmm: [k,m] x [m,n] — first tile divides the contraction m
        "wgrad": [(tm, t2, t3)
                  for tm in tm_opts for t2 in (512, 256, 128)
                  for t3 in dict.fromkeys((min(n_tiles[0], 1024), 512, 128))],
    }
    out = {}
    for i, pass_ in enumerate(_PASSES):
        seen = [heur[i]]
        for c in grids[pass_]:
            if c not in seen and _fits(*c, itemsize=itemsize):
                seen.append(c)
        out[pass_] = seen[:cap]
    return out


def _key(device: str, m: int, k: int, n: int, E: int, dtype: str,
         full_rows: bool, variant: str = "gmm") -> str:
    return (f"{device}|m={m}|k={k}|n={n}|E={E}|{dtype}"
            f"|full_rows={full_rows}|v={variant}")


def _ensure_loaded() -> None:
    """Merge the persisted winners into the in-process cache (once).

    Entries are *validated*, never trusted: a tiling outside the Mosaic
    alignment/VMEM envelope (hand-edited file, bit rot, or a winner
    measured under a different envelope calibration) is dropped — the
    next encounter of its key is a cache miss that re-measures — and
    counted in ``moe_tiling_autotune_rejected_total``."""
    global _LOADED
    if _LOADED:
        return
    from ..jit import cache as _jcache

    disk = _jcache.load_json(PERSIST_NAME, schema=SCHEMA)
    rejected = 0
    with _LOCK:
        if _LOADED:
            return
        for key, ent in disk.items():
            t = ent.get("tilings") if isinstance(ent, dict) else None
            if not (isinstance(t, dict) and all(p in t for p in _PASSES)):
                rejected += 1
                continue
            # key layout: device|m=..|k=..|n=..|E=..|<dtype>|full_rows=..|v=..
            try:
                itemsize = _np.dtype(key.split("|")[5]).itemsize
            except (IndexError, TypeError):
                itemsize = 2          # unparsable dtype: bf16 envelope
            if not all(_aligned(t[p]) and _fits(*(int(v) for v in t[p]),
                                                itemsize=itemsize)
                       for p in _PASSES):
                rejected += 1          # poisoned/stale: re-measure later
                continue
            if key not in _CACHE:
                _CACHE[key] = {
                    "tilings": {p: tuple(int(v) for v in t[p])
                                for p in _PASSES},
                    "source": ent.get("source", "measured"),
                }
        _LOADED = True
    for _ in range(rejected):
        _M_REJECTED.inc()


def _persist() -> None:
    from ..jit import cache as _jcache

    with _LOCK:
        doc = {key: {"tilings": {p: list(ent["tilings"][p])
                                 for p in _PASSES},
                     "source": ent["source"]}
               for key, ent in _CACHE.items()
               if ent["source"] == "measured"}
    _jcache.store_json(PERSIST_NAME, doc, schema=SCHEMA)


def _as_tri(ent: dict) -> TriTiling:
    t = ent["tilings"]
    return tuple(tuple(t[p]) for p in _PASSES)  # type: ignore[return-value]


def _default_measure(m, k, n, E, dtype, full_rows):
    """Build the on-device timing closure, or None when this backend
    can't run the Mosaic kernel (the CPU lane)."""
    import jax

    if jax.default_backend() != "tpu":
        return None
    import functools

    import jax.numpy as jnp
    from jax.experimental.pallas.ops.tpu.megablox.gmm import gmm, tgmm

    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    lhs = jax.random.normal(ks[0], (m, k), jnp.float32).astype(dtype)
    rhs = jax.random.normal(ks[1], (E, k, n), jnp.float32).astype(dtype)
    grad = jax.random.normal(ks[2], (m, n), jnp.float32).astype(dtype)
    # balanced groups summing to m — the load the aux loss maintains
    gs = jnp.full((E,), m // E, jnp.int32).at[0].add(m - E * (m // E))
    lhs_t = lhs.swapaxes(0, 1)

    def run(pass_: str, tiling: Tiling) -> float:
        if pass_ == "fwd":
            f = jax.jit(functools.partial(
                gmm, preferred_element_type=lhs.dtype, tiling=tiling))
            args = (lhs, rhs, gs)
        elif pass_ == "dgrad":
            f = jax.jit(functools.partial(
                gmm, preferred_element_type=lhs.dtype, tiling=tiling,
                transpose_rhs=True))
            args = (grad, rhs, gs)
        else:
            f = jax.jit(functools.partial(
                tgmm, preferred_element_type=rhs.dtype, tiling=tiling,
                num_actual_groups=E))
            args = (lhs_t, grad, gs)
        f(*args).block_until_ready()          # compile + warm
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            f(*args).block_until_ready()
            best = min(best, time.perf_counter() - t0)
        return best

    return run


def get_tilings(m: int, k: int, n: int, E: int, dtype, full_rows: bool,
                *, measure: Optional[Callable] = None,
                variant: str = "gmm") -> Optional[TriTiling]:
    """(fwd, dgrad, wgrad) tilings for one ``grouped_matmul`` call site.

    Cache hit → the remembered winner (persisted winners count as hits:
    the whole point is that a warmed cache makes every step steady-state).
    Miss → measure the candidate grid when possible, else the heuristic.
    ``measure(pass_, tiling) -> seconds`` is injectable for tests and for
    :mod:`tools.moe_tune`; pass a factory result, not a factory.
    ``variant`` keys the kernel family ("gmm" = stock megablox,
    "fused" = the gather-fused kernel in :mod:`.moe_fused`) — their
    optima differ, so they never share cache entries.

    Never-worse guard: the heuristic is always timed (candidate 0), and
    a different winner is kept only when it beats the heuristic by more
    than the noise margin; rejected winners increment
    ``moe_tiling_autotune_rejected_total``.

    Returns None for shapes the Mosaic kernel doesn't like — the caller
    falls back to ``ragged_dot``."""
    heur = heuristic_tilings(m, k, n)
    if heur is None:
        return None
    if not get_flag("moe_gmm_autotune"):
        return heur
    _ensure_loaded()
    np_dtype = _np.dtype(dtype)
    dtype_s = np_dtype.name
    key = _key(_device_tag(), m, k, n, E, dtype_s, bool(full_rows),
               variant)
    with _LOCK:
        ent = _CACHE.get(key)
    if ent is not None:
        _M_HITS.inc()
        return _as_tri(ent)
    _M_MISSES.inc()

    runner = measure if measure is not None else _default_measure(
        m, k, n, E, dtype, full_rows)
    if runner is None:
        # nothing to time here: serve the heuristic, remember it
        # in-process only (never persisted — the disk file is
        # measured-winners-only)
        with _LOCK:
            _CACHE.setdefault(
                key, {"tilings": dict(zip(_PASSES, heur)),
                      "source": "heuristic"})
        return heur

    cands = candidate_tilings(m, k, n, itemsize=np_dtype.itemsize)
    picked: Dict[str, Tiling] = {}
    all_measured = True
    t_start = time.perf_counter()
    with trace_span("moe.autotune", m=m, k=k, n=n, E=E, dtype=dtype_s):
        for i, pass_ in enumerate(_PASSES):
            best, best_t = heur[i], float("inf")
            heur_t = float("inf")
            for tiling in cands[pass_]:
                try:
                    with trace_span("moe.gmm", pass_=pass_,
                                    tiling=str(tiling)):
                        dt = runner(pass_, tiling)
                except Exception:
                    continue      # candidate fails to compile/run: skip
                if tiling == heur[i]:
                    heur_t = dt
                if dt < best_t:
                    best, best_t = tiling, dt
            if best_t == float("inf"):
                # every candidate failed: the default-win heuristic was
                # never validated — do NOT let it persist as "measured"
                # (a toolchain fix should re-trigger measurement)
                all_measured = False
            elif (tuple(best) != tuple(heur[i])
                    and best_t > heur_t * (1.0 - _NOISE_MARGIN)):
                # winner inside the noise band of the heuristic: the
                # measurement proved nothing — keep the static pick
                best = heur[i]
                _M_REJECTED.inc()
            picked[pass_] = tuple(best)
    _M_TUNE.observe(time.perf_counter() - t_start)
    source = "measured" if all_measured else "heuristic"
    with _LOCK:
        _CACHE.setdefault(key, {"tilings": picked, "source": source})
        ent = _CACHE[key]
    if all_measured:
        _persist()
    return _as_tri(ent)


def _device_tag() -> str:
    """Tilings are device-generation-specific: the cache key leads with
    the accelerator kind so a v5e file never answers for a v6e."""
    import jax

    backend = jax.default_backend()
    if backend != "tpu":
        return backend
    try:
        return jax.devices()[0].device_kind.replace(" ", "_")
    except Exception:
        return "tpu"


def clear(persisted: bool = False) -> None:
    """Drop the in-process cache; ``persisted=True`` also truncates the
    on-disk file (documented escape hatch after a toolchain upgrade)."""
    global _LOADED
    with _LOCK:
        _CACHE.clear()
        _LOADED = False     # next access re-reads the persisted winners
    if persisted:
        from ..jit import cache as _jcache

        _jcache.store_json(PERSIST_NAME, {}, schema=SCHEMA)


def entries():
    """Snapshot of (key, source, {pass: tiling}) — the tools/moe_tune.py
    table."""
    _ensure_loaded()
    with _LOCK:
        return [(key, ent["source"], dict(ent["tilings"]))
                for key, ent in sorted(_CACHE.items())]
