"""Ulysses attention — all-to-all sequence parallelism.

The reference's SEP axis (SURVEY.md §5.7: topology.py:199-260 provides the
groups; the alltoall-based Ulysses attention itself lives in downstream
PaddleNLP model code over communication/all_to_all.py). Here it is in-core:
inside shard_map, an all-to-all swaps the sharded axis from sequence to
heads, each device computes FULL-sequence attention for its head slice, and
a second all-to-all swaps back. Complements kernels/ring_attention:
Ulysses moves activations twice (cheap when heads >= ring size), ring moves
K/V n-1 times (better for very long sequences / few heads).
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["ulysses_attention", "ulysses_attention_sharded"]


def _dense_causal(q, k, v, causal):
    """Full-sequence attention; GQA-aware (k/v may carry fewer heads —
    query head h attends kv head h // (Hq//Hkv))."""
    B, Sq, Hq, D = q.shape
    Skv, Hkv = k.shape[1], k.shape[2]
    G = Hq // Hkv  # grouped path is exact for G == 1 too (reshapes are free)
    scale = 1.0 / math.sqrt(D)
    qg = q.reshape(B, Sq, Hkv, G, D)
    s = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k,
                   preferred_element_type=jnp.float32) * scale
    s = s.reshape(B, Hq, Sq, Skv)
    if causal:
        mask = jnp.tril(jnp.ones((Sq, Skv), bool))
        s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    pg = p.reshape(B, Hkv, G, Sq, Skv)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", pg, v)
    return out.reshape(B, Sq, Hq, D)


def ulysses_attention(q, k, v, axis_name: str, axis_size: int,
                      causal: bool = True):
    """Per-shard body under shard_map. q/k/v: [B, S_local, H, D] with the
    sequence axis sharded over axis_name; axis_size must divide every
    tensor's OWN head count (q's and k/v's separately) — GQA K/V keep
    their fewer heads through the all-to-all (traffic / (Hq/Hkv) vs
    pre-expanding), since an equal split of q heads and kv heads lands
    group-aligned slices on the same device. If Hkv < axis_size, expand
    K/V (jnp.repeat) to a multiple of axis_size before calling.
    all_to_all #1: gather sequence, scatter heads → [B, S_full, H_local, D];
    attention; all_to_all #2: the reverse."""
    B, S, _, D = q.shape
    n = axis_size

    def seq2head(x):
        # [B, S, H, D] -> [B, S, n, h, D]: head groups; all-to-all sends each
        # group to its device while gathering the full sequence
        H = x.shape[2]
        assert H % n == 0, (H, n)
        x = x.reshape(B, S, n, H // n, D)
        out = jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                 tiled=True)
        # tiled all_to_all keeps the split axis (now size 1): [B, S*n, 1, h, D]
        return out.reshape(B, S * n, H // n, D)

    def head2seq(x):
        # inverse: [B, S*n, h, D] -> regroup sequence shards then swap back
        H = x.shape[2] * n
        x = x.reshape(B, n, S, H // n, D)
        out = jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=3,
                                 tiled=True)
        return out.reshape(B, S, H, D)

    qf, kf, vf = seq2head(q), seq2head(k), seq2head(v)
    of = _dense_causal(qf, kf, vf, causal)
    return head2seq(of)


def ulysses_attention_sharded(q, k, v, mesh: Mesh, axis_name: str = "sp",
                              causal: bool = True,
                              batch_axis: Optional[str] = "dp"):
    """Global-array wrapper (q/k/v: [B, S, H, D])."""
    ba = batch_axis if (batch_axis and batch_axis in mesh.axis_names) else None
    spec = P(ba, axis_name, None, None)
    fn = jax.shard_map(
        functools.partial(ulysses_attention, axis_name=axis_name,
                          axis_size=dict(mesh.shape)[axis_name],
                          causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)
