"""Fused int8 weight-only matmuls + int8 KV-pool quantization helpers.

Parity surface: the reference's weight_only_linear path keeps int8 weights
resident and fuses dequantization into the GEMM epilogue (nn/quant/
quantized_linear.py over the cutlass fpA_intB kernels in
phi/kernels/fusion/cutlass_kernels/). TPU-native version: the int8 operand
is fed DIRECTLY to ``lax.dot_general`` (mixed-dtype dot with
``preferred_element_type=f32``) and the per-output-channel scales are
applied to the f32 accumulator — the [K, N] bf16 dequantized weight copy
the naive ``(q * s).astype(bf16)`` epilogue materializes per step never
exists, so a weight-bandwidth-bound decode step reads half the bytes.

The same trick serves the int8 KV pools of the serving engine
(serving/engine.py): K stays int8 through the QK^T contraction with the
per-entry scale folded into the score, and the V scale is folded into the
softmax probabilities BEFORE the PV contraction (the scale depends on the
contracted position axis, so it must ride the probabilities, not the
output).

Older jax releases reject mixed-dtype dots; ``mixed_dot_supported()``
probes once (shape-level, no compile) and every helper falls back to an
inline dequant-then-dot that still skips the per-channel multiply on the
weight (scales stay on the output) — slower, never wrong.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = [
    "weight_only_matmul", "quantize_kv", "dequantize_kv",
    "attn_qk", "attn_pv", "mixed_dot_supported",
    "quantize_grouped", "is_quantized_weight", "dequantize_channels",
]


def dequantize_channels(q, scale, axis: int):
    """f32 reconstruction of a per-channel int8 tensor: ``q *
    expand_dims(scale, axis)`` where ``axis`` is the dim the scale was
    reduced over — the shared inverse of :func:`quantize_grouped`
    (``axis``), :func:`quantize_kv` (``axis=-1``) and
    ``models.llama.quantize_params`` (``axis=-2``). Also the
    reconstruction the numerics observatory's paired quant-error probes
    measure against (observability.numerics.record_quant_error)."""
    return (q.astype(jnp.float32)
            * jnp.expand_dims(scale.astype(jnp.float32), axis))


@functools.lru_cache(maxsize=1)
def mixed_dot_supported() -> bool:
    """True when this jax accepts a bf16 x int8 dot_general (type-level
    probe via eval_shape — no device, no compile)."""
    try:
        jax.eval_shape(
            lambda a, b: jax.lax.dot_general(
                a, b, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32),
            jax.ShapeDtypeStruct((2, 2), jnp.bfloat16),
            jax.ShapeDtypeStruct((2, 2), jnp.int8))
        return True
    except Exception:
        return False


def _is_quantized(w) -> bool:
    return isinstance(w, dict) and "q" in w


def is_quantized_weight(w) -> bool:
    """True for an int8 weight-only leaf ``{"q": int8, "s": f32}`` (the
    quantize_params / quantize_grouped layout)."""
    return _is_quantized(w)


def quantize_grouped(w, axis: int):
    """Symmetric per-channel int8 for stacked per-expert weights.

    ``w``: [E, ...] grouped weights; ``axis`` is the axis the scale is
    *shared over* (reduced by absmax), e.g.:

    - gate/up ``[E, h, f]`` with ``axis=1`` → ``s`` [E, f]: one scale per
      (expert, output channel), applied to the GEMM *output* — the
      weight_only_matmul idiom, grouped;
    - down ``[E, f, h]`` with ``axis=2`` → ``s`` [E, f]: one scale per
      (expert, *input* channel), folded into the GEMM *input* — it rides
      the same elementwise chain as the MoE combine weights
      (``z * w * s``), so the dequantization costs nothing extra.

    Returns ``{"q": int8 (w.shape), "s": f32 (w.shape minus axis)}``.
    Scales are constants at use sites (stop_gradient): quantization never
    leaks into any gradient."""
    wf = w.astype(jnp.float32)
    scale = jnp.max(jnp.abs(wf), axis=axis) / 127.0
    scale = jnp.maximum(scale, 1e-12)
    q = jnp.round(wf / jnp.expand_dims(scale, axis))
    return {"q": jnp.clip(q, -127, 127).astype(jnp.int8), "s": scale}


def dequantize_grouped(w, axis: int, dtype):
    """Materialize the dense weights of a :func:`quantize_grouped` leaf
    (the slow exact fallback — paths that can't keep the int8 operand
    resident, e.g. the shard_map expert-parallel forms)."""
    return dequantize_channels(w["q"], w["s"], axis).astype(dtype)


def weight_only_matmul(x, w, out_dtype):
    """``x @ w`` where ``w`` is a dense [K, N] array OR an int8
    weight-only leaf ``{"q": int8 [K, N], "s": [N]}`` (models/llama.
    quantize_params layout, sliced to one layer).

    Dense leaves reproduce the historical ``x @ w.astype(out_dtype)``
    exactly. int8 leaves contract x against the int8 matrix directly
    (f32 accumulator) and scale the OUTPUT per channel — no dequantized
    weight copy, no [K, N]-sized multiply.
    """
    if not _is_quantized(w):
        return x @ w.astype(out_dtype)
    q, s = w["q"], w["s"]
    dn = (((x.ndim - 1,), (0,)), ((), ()))
    if mixed_dot_supported():
        y = jax.lax.dot_general(x, q, dn,
                                preferred_element_type=jnp.float32)
    else:  # old jax: inline convert (XLA fuses it into the matmul read)
        y = jax.lax.dot_general(x, q.astype(x.dtype), dn,
                                preferred_element_type=jnp.float32)
    return (y * s.astype(jnp.float32)).astype(out_dtype)


# ---------------------------------------------------------------------------
# int8 KV pools: symmetric per-entry absmax over the head dim
# ---------------------------------------------------------------------------
def quantize_kv(x):
    """[..., D] K/V values -> (int8 [..., D], f32 scale [...]).

    One scale per pool entry (token, kv-head) — the fine-grained limit of
    per-block scaling. Coarser per-block scales break under the decode
    writeback, which APPENDS tokens into partially-filled blocks: the
    block's old scale would clip (or force a requantization of) every new
    entry. Per-entry scales make each write self-contained and the
    round-trip error bound exact (<= absmax/254 per element).
    Overhead at D=128: 4 bytes per 128 int8 bytes (~3%).
    """
    xf = x.astype(jnp.float32)
    scale = jnp.max(jnp.abs(xf), axis=-1) / 127.0
    q = jnp.round(xf / jnp.maximum(scale[..., None], 1e-9))
    return jnp.clip(q, -127, 127).astype(jnp.int8), scale


def dequantize_kv(q, scale, dtype):
    return dequantize_channels(q, scale, -1).astype(dtype)


# ---------------------------------------------------------------------------
# GQA decode attention contractions over (possibly int8) gathered prefixes
#   qg: [N, Hkv, G, D]   queries grouped by kv head
#   kd/vd: [N, P, Hkv, D] gathered prefix (model dtype, or int8 + scales)
#   ks/vs: [N, P, Hkv]   f32 per-entry scales (None for dense pools)
# ---------------------------------------------------------------------------
_QK_DN = (((3,), (3,)), ((0, 1), (0, 2)))   # contract D; batch (N, Hkv)
_PV_DN = (((3,), (1,)), ((0, 1), (0, 2)))   # contract P; batch (N, Hkv)


def attn_qk(qg, kd, ks=None):
    """QK^T scores [N, Hkv, G, P] in f32. int8 K contracts directly; the
    per-entry scale multiplies the f32 score (it is constant over the
    contracted D axis, so it commutes out of the dot)."""
    if kd.dtype == jnp.int8 and not mixed_dot_supported():
        kd, ks = dequantize_kv(kd, ks, qg.dtype), None
    s = jax.lax.dot_general(qg, kd, _QK_DN,
                            preferred_element_type=jnp.float32)
    if ks is not None:
        s = s * jnp.transpose(ks, (0, 2, 1))[:, :, None, :]
    return s


def attn_pv(p, vd, vs=None, *, out_dtype):
    """probs @ V -> [N, Hkv, G, D] in ``out_dtype``. ``p``: f32 softmax
    probabilities [N, Hkv, G, P]. The V scale varies along the CONTRACTED
    P axis, so it is folded into the probabilities (a tensor that already
    exists at this size) and the int8 V feeds the dot unconverted."""
    if vd.dtype == jnp.int8 and not mixed_dot_supported():
        vd, vs = dequantize_kv(vd, vs, out_dtype), None
    if vs is not None:
        p = p * jnp.transpose(vs, (0, 2, 1))[:, :, None, :]
        out = jax.lax.dot_general(p, vd, _PV_DN,
                                  preferred_element_type=jnp.float32)
        return out.astype(out_dtype)
    # dense pools: match the historical bf16 einsum numerics exactly
    return jax.lax.dot_general(p.astype(out_dtype), vd, _PV_DN)
