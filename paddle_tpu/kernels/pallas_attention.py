"""FlashAttention-2 as a Pallas TPU kernel (forward + backward).

Replaces the reference's vendored FlashAttention-2 CUDA library
(reference: third_party/flashattn backing
paddle/phi/kernels/gpu/flash_attn_kernel.cu, python surface
python/paddle/nn/functional/flash_attention.py:358).

TPU-native design: online-softmax tiles sized for the MXU (128-multiple
blocks), f32 accumulators in VMEM scratch carried across the innermost
(kv) grid dimension, log-sum-exp saved as the residual so the backward
recomputes probabilities tile-by-tile (two kernels: dQ over kv tiles, dK/dV
over q tiles) — never materializing the [S, S] score matrix in HBM.

Layout contract: q, k, v are [batch, seq, heads, head_dim] (the framework's
public flash_attention layout); kernels run on [batch*heads, seq, head_dim].
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
STATS = 128  # lane width used to store per-row softmax stats


def _interpret() -> bool:
    # off-TPU (CPU tests) the kernels run in the Pallas interpreter
    return jax.default_backend() != "tpu"


def _pick_block(seq: int, want: int) -> int:
    b = min(want, seq)
    while seq % b:
        b //= 2
    return max(b, 128) if seq % max(b, 128) == 0 else b


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref, *, scale, causal, block_q, block_kv):
    i, j = pl.program_id(1), pl.program_id(2)
    nj = pl.num_programs(2)

    @pl.when(j == 0)
    def _():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    run = True
    if causal:
        # tile fully above the diagonal contributes nothing
        run = (j * block_kv) <= (i * block_q + block_q - 1)

    @pl.when(run)
    def _():
        q = q_ref[0]
        k = k_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            row = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) + i * block_q
            col = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + j * block_kv
            s = jnp.where(row >= col, s, jnp.float32(NEG_INF))

        m_prev = m_ref[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = l_ref[:, :1] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_ref[...] = acc_ref[...] * alpha + pv
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == nj - 1)
    def _():
        l = l_ref[:, :1]
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)
        lse_ref[0] = m_ref[:, :1] + jnp.log(l)   # [block_q, 1]


def _fwd(q, k, v, causal, block_q, block_kv, scale, groups):
    """q: [B*Hq, S, D]; k/v: [B*Hkv, S, D] with Hq = Hkv*groups. Flattened
    b-major, q row b reads kv row b // groups (exact: (bb*Hq + h)//G =
    bb*Hkv + h//G — the repeat-interleave GQA convention of
    jnp.repeat(axis=2), so no repeated K/V is ever materialized)."""
    BH, S, D = q.shape
    bq = _pick_block(S, block_q)
    bkv = _pick_block(S, block_kv)
    grid = (BH, S // bq, S // bkv)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, block_q=bq, block_kv=bkv)
    kv_map = lambda b, i, j: (b // groups, j, 0)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bkv, D), kv_map),
            pl.BlockSpec((1, bkv, D), kv_map),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), q.dtype),
            jax.ShapeDtypeStruct((BH, S, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, D), jnp.float32),
            pltpu.VMEM((bq, STATS), jnp.float32),
            pltpu.VMEM((bq, STATS), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v)
    return out, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               acc_ref, *, scale, causal, block_q, block_kv):
    i, j = pl.program_id(1), pl.program_id(2)
    nj = pl.num_programs(2)

    @pl.when(j == 0)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    run = True
    if causal:
        run = (j * block_kv) <= (i * block_q + block_q - 1)

    @pl.when(run)
    def _():
        q = q_ref[0]
        k = k_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            row = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) + i * block_q
            col = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + j * block_kv
            s = jnp.where(row >= col, s, jnp.float32(NEG_INF))
        p = jnp.exp(s - lse_ref[0])
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0]) * scale
        acc_ref[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(j == nj - 1)
    def _():
        dq_ref[0] = acc_ref[...].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_acc, dv_acc,
                *, scale, causal, block_q, block_kv):
    # grid: (B*Hkv, kv tiles, group q-heads, q tiles) — dk/dv accumulate
    # across BOTH the group's query heads (g) and the q tiles (i)
    j, g, i = pl.program_id(1), pl.program_id(2), pl.program_id(3)
    ng, ni = pl.num_programs(2), pl.num_programs(3)

    @pl.when(jnp.logical_and(g == 0, i == 0))
    def _():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    run = True
    if causal:
        run = (j * block_kv) <= (i * block_q + block_q - 1)

    @pl.when(run)
    def _():
        q = q_ref[0]
        k = k_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            row = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) + i * block_q
            col = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + j * block_kv
            s = jnp.where(row >= col, s, jnp.float32(NEG_INF))
        p = jnp.exp(s - lse_ref[0])                              # [bq, bkv]
        do = do_ref[0]
        dv_acc[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0]) * scale                     # [bq, bkv]
        dk_acc[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(jnp.logical_and(g == ng - 1, i == ni - 1))
    def _():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _bwd(causal, block_q, block_kv, scale, groups, res, do):
    q, k, v, out, lse = res
    BH, S, D = q.shape
    BHkv = k.shape[0]
    bq = _pick_block(S, block_q)
    bkv = _pick_block(S, block_kv)
    delta = jnp.sum(out.astype(jnp.float32) * do.astype(jnp.float32),
                    axis=-1, keepdims=True)                      # [BH, S, 1]

    kv_map = lambda b, i, j: (b // groups, j, 0)
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          block_q=bq, block_kv=bkv),
        grid=(BH, S // bq, S // bkv),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bkv, D), kv_map),
            pl.BlockSpec((1, bkv, D), kv_map),
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, S, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)

    # dk/dv: grid dim0 walks KV rows; q-side refs select the group's q head
    # g via row b*groups + g (inverse of the forward's b // groups map)
    q_map = lambda b, j, g, i: (b * groups + g, i, 0)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          block_q=bq, block_kv=bkv),
        grid=(BHkv, S // bkv, groups, S // bq),
        in_specs=[
            pl.BlockSpec((1, bq, D), q_map),
            pl.BlockSpec((1, bkv, D), lambda b, j, g, i: (b, j, 0)),
            pl.BlockSpec((1, bkv, D), lambda b, j, g, i: (b, j, 0)),
            pl.BlockSpec((1, bq, D), q_map),
            pl.BlockSpec((1, bq, 1), q_map),
            pl.BlockSpec((1, bq, 1), q_map),
        ],
        out_specs=[
            pl.BlockSpec((1, bkv, D), lambda b, j, g, i: (b, j, 0)),
            pl.BlockSpec((1, bkv, D), lambda b, j, g, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BHkv, S, D), k.dtype),
            jax.ShapeDtypeStruct((BHkv, S, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bkv, D), jnp.float32),
            pltpu.VMEM((bkv, D), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, block_q, block_kv, scale, groups):
    out, _ = _fwd(q, k, v, causal, block_q, block_kv, scale, groups)
    return out


def _flash_fwd(q, k, v, causal, block_q, block_kv, scale, groups):
    out, lse = _fwd(q, k, v, causal, block_q, block_kv, scale, groups)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, block_q, block_kv, scale, groups, res, do):
    return _bwd(causal, block_q, block_kv, scale, groups, res, do)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention_fwd(q, k, v, causal: bool = False,
                        block_q: int = 1024, block_kv: int = 1024):
    """q: [batch, seq, heads, head_dim]; k/v may carry FEWER heads (GQA) —
    query head h attends kv head h // (Hq//Hkv) inside the kernel, so the
    repeated K/V (and their expanded dK/dV) never touch HBM.
    Differentiable (custom FA2 backward). Default 1024-blocks measured
    fastest on v5e (2.6B train step: 6.89k vs 6.52k tok/s at 512-blocks,
    bench.py runs); _pick_block shrinks them for shorter sequences."""
    B, S, H, D = q.shape
    Hkv = k.shape[2]
    assert H % Hkv == 0, (H, Hkv)
    groups = H // Hkv
    scale = 1.0 / math.sqrt(D)

    def to_bh(x):
        h = x.shape[2]
        return jnp.swapaxes(x, 1, 2).reshape(B * h, S, D)

    out = _flash(to_bh(q), to_bh(k), to_bh(v), causal, block_q, block_kv,
                 scale, groups)
    return jnp.swapaxes(out.reshape(B, H, S, D), 1, 2)
