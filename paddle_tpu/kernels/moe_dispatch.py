"""Dropless (capacity-less) MoE token dispatch.

Capability parity: the reference's capacity-less MoE all-to-all —
`global_scatter`/`global_gather` (incubate/distributed/models/moe/
moe_layer.py:105-188) exchanges a *ragged* number of tokens per expert and
drops nothing; its fused grouped-GEMM path
(phi/kernels/fusion/cutlass_kernels/moe_gemm/) batches the per-expert FFNs
into one kernel.

TPU-native re-design (three strategies, one semantic):

* ``dropless_moe_ffn``     — single-program GSPMD form: stable-sort the
  ``T*k`` (token, slot) assignments by expert, then three
  ``jax.lax.ragged_dot`` grouped GEMMs (the MXU analogue of the cutlass
  grouped GEMM). No capacity buffer exists, so no token is ever dropped.
* ``dropless_moe_ffn_ep``  — explicit expert-parallel form under
  ``jax.shard_map`` (partial-manual over the token + 'ep' axes): every ep
  rank keeps its expert shard, computes the assignments that route to its
  local experts with a local sort + ``ragged_dot``, and the combine is one
  ``psum`` over 'ep'. Token→expert traffic never leaves the rank (the
  tokens are ep-replicated already); the only collective is the [T,h]
  allreduce of the routed outputs — an ICI-friendly trade of the
  reference's two ragged all-to-alls.
* ``dropless_moe_ffn_a2a`` — the literal reference shape: tokens sharded
  over 'ep', exchanged with ``jax.lax.ragged_all_to_all`` (sizes exchanged
  via ``all_gather``), grouped-GEMM'd on the owner, and returned with the
  reverse ragged all-to-all. XLA:CPU has no ragged-all-to-all lowering, so
  this path is for real TPU meshes; the CPU test lane covers the other two.

All three differentiate: ``ragged_dot`` has jvp/transpose rules, the sorts
and scatters transpose to gathers, and the collectives transpose to
themselves (psum) or the reverse exchange.
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

__all__ = [
    "dropless_moe_ffn", "dropless_moe_ffn_ep", "dropless_moe_ffn_a2a",
    "sort_by_expert",
]


def sort_by_expert(idx: jax.Array) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Flatten top-k assignments [T,k] → stable expert-sorted order.

    Returns (order [T*k] assignment permutation, tok [T*k] source token of
    each sorted assignment, flat_e [T*k] unsorted expert ids)."""
    T, k = idx.shape
    flat_e = idx.reshape(T * k)
    order = jnp.argsort(flat_e)           # stable → deterministic combine
    tok = order // k
    return order, tok, flat_e


def _gmm_tiling(m: int, k: int, n: int):
    """Tiling for the Mosaic grouped matmul: whole-K tiles and the largest
    N tile that fits scoped VMEM with the kernel's double buffering
    (measured on v5e: (256, K, N) runs ~2x ragged_dot's utilization at MoE
    shapes; the 512-cubed default loses to N%512 != 0 padding)."""
    tm = 256 if m % 256 == 0 else (128 if m % 128 == 0 else None)
    if tm is None or k % 128 or n % 128:
        return None     # odd shapes: let ragged_dot take them

    def fits(tk, tn):  # double-buffered bf16 inputs + f32 accumulator
        return 2 * 2 * (tm * tk + tk * tn) + 4 * tm * tn \
            <= 11 * 1024 * 1024

    for tn in [t for t in range(n, 127, -128) if n % t == 0]:
        if fits(k, tn):
            return (tm, k, tn)
    return (tm, min(k, 512), min(n, 512))


def grouped_matmul(xs, w, gs):
    """[m, k] @ per-group [E, k, n] over expert-sorted rows. On TPU this is
    the Mosaic block-sparse grouped matmul (MegaBlocks-style: only row
    blocks that exist are computed — the analogue of the reference's
    cutlass moe_gemm); elsewhere jax.lax.ragged_dot."""
    m, k = xs.shape
    n = w.shape[-1]
    if jax.default_backend() == "tpu":
        tiling = _gmm_tiling(m, k, n)
        if tiling is not None:
            from jax.experimental.pallas.ops.tpu.megablox import gmm

            return gmm(xs, w, gs, preferred_element_type=xs.dtype,
                       tiling=tiling)
    return jax.lax.ragged_dot(xs, w, gs)


def _expert_ffn(xs, gs, e_gate, e_up, e_down, dt):
    """Grouped-GEMM SwiGLU over expert-sorted rows (rows ≥ sum(gs) are
    don't-care — the caller masks their combine weight to zero)."""
    gate = jax.nn.silu(grouped_matmul(xs, e_gate.astype(dt), gs))
    up = grouped_matmul(xs, e_up.astype(dt), gs)
    return grouped_matmul(gate * up, e_down.astype(dt), gs)


def dropless_moe_ffn(x, weights, idx, e_gate, e_up, e_down):
    """Capacity-less routed FFN, single-program (GSPMD) form.

    x: [T,h]; weights/idx: [T,k] from the router; experts [E,h,f]/[E,f,h].
    Every assignment is computed — there is no capacity C and nothing to
    drop (reference semantics: moe_layer.py global_scatter with unbounded
    per-expert counts)."""
    T, h = x.shape
    E = e_gate.shape[0]
    dt = x.dtype
    order, tok, flat_e = sort_by_expert(idx)
    xs = jnp.take(x, tok, axis=0)                         # [T*k, h]
    gs = jnp.zeros((E,), jnp.int32).at[flat_e].add(1)
    ys = _expert_ffn(xs, gs, e_gate, e_up, e_down, dt)    # [T*k, h]
    ws = weights.reshape(T * idx.shape[1])[order].astype(jnp.float32)
    y = jnp.zeros((T, h), jnp.float32).at[tok].add(
        ys.astype(jnp.float32) * ws[:, None])
    return y.astype(dt)


def _ep_local(x_l, w_l, idx_l, eg_l, eu_l, ed_l, *, num_experts_local,
              compute_dtype):
    """Per-(data,ep)-rank body: local tokens × local expert shard, psum('ep').

    Assignments routed to foreign experts sort to the tail and get combine
    weight 0; the psum sums each token's k partial expert outputs across the
    ep ranks that own them. Boundary tensors are f32 (see the caller); the
    grouped GEMMs run in ``compute_dtype`` (bf16 on TPU → MXU)."""
    El = num_experts_local
    me = jax.lax.axis_index("ep")
    Tl, k = idx_l.shape
    A = Tl * k

    flat_e = idx_l.reshape(A)
    lid = flat_e - me * El
    mine = (lid >= 0) & (lid < El)
    order = jnp.argsort(jnp.where(mine, lid, El))         # foreign → tail
    tok = order // k
    xs = jnp.take(x_l.astype(compute_dtype), tok, axis=0)
    gs = jnp.zeros((El,), jnp.int32).at[jnp.where(mine, lid, 0)].add(
        mine.astype(jnp.int32))
    ys = _expert_ffn(xs, gs, eg_l, eu_l, ed_l, compute_dtype)
    ws = jnp.where(mine, w_l.reshape(A), 0.0)[order].astype(jnp.float32)
    y = jnp.zeros((Tl, x_l.shape[1]), jnp.float32).at[tok].add(
        ys.astype(jnp.float32) * ws[:, None])
    return jax.lax.psum(y, "ep")


def dropless_moe_ffn_ep(x, weights, idx, e_gate, e_up, e_down, mesh: Mesh,
                        token_axes: Tuple[str, ...] = ("dp",)):
    """Explicit expert-parallel dropless FFN (partial-manual shard_map).

    Token tensors are sharded over ``token_axes`` and replicated over 'ep';
    experts are sharded over 'ep' on their leading axis. Axes not named
    ('tp' fsdp etc.) stay under GSPMD control, so this nests inside a fully
    sharded train step.

    The shard_map boundary is kept f32: differentiating a bf16-carrying
    partial-manual shard_map inside ``lax.scan`` hits an XLA:CPU compiler
    check failure ("Invalid binary instruction opcode copy"); f32 in/out
    with bf16 compute inside the body sidesteps it, costs one fused convert
    on TPU, and makes the k-way combine psum f32-accurate."""
    E = e_gate.shape[0]
    ep = dict(mesh.shape).get("ep", 1)
    if ep <= 1 or E % ep != 0:
        return dropless_moe_ffn(x, weights, idx, e_gate, e_up, e_down)
    dt = x.dtype
    tok_axes = tuple(a for a in token_axes if dict(mesh.shape).get(a, 1) > 1)
    tok_spec = P(tok_axes if tok_axes else None)
    fn = jax.shard_map(
        lambda xl, wl, il, g, u, d: _ep_local(
            xl, wl, il, g, u, d, num_experts_local=E // ep,
            compute_dtype=dt),
        mesh=mesh,
        in_specs=(tok_spec, tok_spec, tok_spec, P("ep"), P("ep"), P("ep")),
        out_specs=tok_spec,
        axis_names=set(tok_axes) | {"ep"},
        check_vma=False)
    return fn(x.astype(jnp.float32), weights, idx,
              e_gate, e_up, e_down).astype(dt)


def _a2a_local(x_l, w_l, idx_l, eg_l, eu_l, ed_l, *, num_experts,
               num_experts_local, ep_size):
    """Per-ep-rank body of the ragged-all-to-all exchange (reference's
    global_scatter → grouped GEMM → global_gather, TPU collectives)."""
    E, El, R = num_experts, num_experts_local, ep_size
    me = jax.lax.axis_index("ep")
    Tl, k = idx_l.shape
    A = Tl * k
    Amax = A * R
    h = x_l.shape[1]
    dt = x_l.dtype

    flat_e = idx_l.reshape(A)
    order = jnp.argsort(flat_e)                    # expert order == rank order
    tok = order // k
    xs = jnp.take(x_l, tok, axis=0)                # [A,h] send buffer
    eid_send = flat_e[order]

    dest = flat_e // El
    send_sizes = jnp.zeros((R,), jnp.int32).at[dest].add(1)
    sizes = jax.lax.all_gather(send_sizes, "ep")   # [sender, dest]
    in_off = jnp.cumsum(send_sizes) - send_sizes
    recv_sizes = sizes[:, me]
    out_off = (jnp.cumsum(sizes, axis=0) - sizes)[me]

    xr = jax.lax.ragged_all_to_all(
        xs, jnp.zeros((Amax, h), dt),
        in_off, send_sizes, out_off, recv_sizes, axis_name="ep")
    er = jax.lax.ragged_all_to_all(
        eid_send, jnp.full((Amax,), E, jnp.int32),
        in_off, send_sizes, out_off, recv_sizes, axis_name="ep")

    lid = jnp.where(er < E, er - me * El, El)      # padding → tail group
    order2 = jnp.argsort(lid)
    xg = jnp.take(xr, order2, axis=0)
    valid = lid < El
    gs = jnp.zeros((El,), jnp.int32).at[jnp.where(valid, lid, 0)].add(
        valid.astype(jnp.int32))
    yg = _expert_ffn(xg, gs, eg_l, eu_l, ed_l, dt)
    yr = jnp.zeros_like(yg).at[order2].set(yg)     # back to receive order

    rev_in_off = jnp.cumsum(recv_sizes) - recv_sizes
    rev_out_off = (jnp.cumsum(sizes, axis=1) - sizes)[:, me]
    ys = jax.lax.ragged_all_to_all(
        yr, jnp.zeros((A, h), dt),
        rev_in_off, recv_sizes, rev_out_off, send_sizes, axis_name="ep")

    ws = w_l.reshape(A)[order].astype(jnp.float32)
    y = jnp.zeros((Tl, h), jnp.float32).at[tok].add(
        ys.astype(jnp.float32) * ws[:, None])
    return y.astype(dt)


def dropless_moe_ffn_a2a(x, weights, idx, e_gate, e_up, e_down, mesh: Mesh,
                         token_axes: Tuple[str, ...] = ("dp", "ep")):
    """Ragged-all-to-all dropless FFN: tokens sharded over ``token_axes``
    (which always includes 'ep'), exchanged to expert owners within each ep
    group and back (the literal global_scatter/global_gather shape — only
    ~T*k/ep assignments are GEMM'd per rank, vs the psum strategy's T*k).
    Requires a backend with a ragged-all-to-all lowering — real TPU;
    XLA:CPU raises UNIMPLEMENTED, so CPU tests use the _ep/psum strategy
    (a lowering-only test pins the wiring)."""
    E = e_gate.shape[0]
    ep = dict(mesh.shape).get("ep", 1)
    T = x.shape[0]
    tok_axes = tuple(dict.fromkeys(
        a for a in (*token_axes, "ep") if dict(mesh.shape).get(a, 1) > 1))
    n_tok_shards = int(np.prod([dict(mesh.shape)[a] for a in tok_axes])) \
        if tok_axes else 1
    if ep <= 1 or E % ep != 0 or T % max(n_tok_shards, 1) != 0:
        return dropless_moe_ffn(x, weights, idx, e_gate, e_up, e_down)
    tok_spec = P(tok_axes)
    fn = jax.shard_map(
        lambda xl, wl, il, g, u, d: _a2a_local(
            xl, wl, il, g, u, d, num_experts=E,
            num_experts_local=E // ep, ep_size=ep),
        mesh=mesh,
        in_specs=(tok_spec, tok_spec, tok_spec, P("ep"), P("ep"), P("ep")),
        out_specs=tok_spec,
        axis_names=set(tok_axes) | {"ep"},
        check_vma=False)
    return fn(x, weights, idx, e_gate, e_up, e_down)
