"""Dropless (capacity-less) MoE token dispatch.

Capability parity: the reference's capacity-less MoE all-to-all —
`global_scatter`/`global_gather` (incubate/distributed/models/moe/
moe_layer.py:105-188) exchanges a *ragged* number of tokens per expert and
drops nothing; its fused grouped-GEMM path
(phi/kernels/fusion/cutlass_kernels/moe_gemm/) batches the per-expert FFNs
into one kernel.

TPU-native re-design (three strategies, one semantic):

* ``dropless_moe_ffn``     — single-program GSPMD form: stable-sort the
  ``T*k`` (token, slot) assignments by expert, then three
  ``jax.lax.ragged_dot`` grouped GEMMs (the MXU analogue of the cutlass
  grouped GEMM). No capacity buffer exists, so no token is ever dropped.
* ``dropless_moe_ffn_ep``  — explicit expert-parallel form under
  ``jax.shard_map`` (partial-manual over the token + 'ep' axes): every ep
  rank keeps its expert shard, computes the assignments that route to its
  local experts with a local sort + ``ragged_dot``, and the combine is one
  ``psum`` over 'ep'. Token→expert traffic never leaves the rank (the
  tokens are ep-replicated already); the only collective is the [T,h]
  allreduce of the routed outputs — an ICI-friendly trade of the
  reference's two ragged all-to-alls.
* ``dropless_moe_ffn_a2a`` — the literal reference shape: tokens sharded
  over 'ep', exchanged with ``jax.lax.ragged_all_to_all`` (sizes exchanged
  via ``all_gather``), grouped-GEMM'd on the owner, and returned with the
  reverse ragged all-to-all. XLA:CPU has no ragged-all-to-all lowering, so
  this path is for real TPU meshes; the CPU test lane covers the other two.

All three differentiate: ``ragged_dot`` has jvp/transpose rules, the sorts
and scatters transpose to gathers, and the collectives transpose to
themselves (psum) or the reverse exchange.
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

__all__ = [
    "dropless_moe_ffn", "dropless_moe_ffn_dense", "dropless_moe_ffn_ep",
    "dropless_moe_ffn_a2a", "sort_by_expert",
]


def sort_by_expert(idx: jax.Array) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Flatten top-k assignments [T,k] → stable expert-sorted order.

    Returns (order [T*k] assignment permutation, tok [T*k] source token of
    each sorted assignment, flat_e [T*k] unsorted expert ids)."""
    T, k = idx.shape
    flat_e = idx.reshape(T * k)
    order = jnp.argsort(flat_e)           # stable → deterministic combine
    tok = order // k
    return order, tok, flat_e


_TILES = (1408, 1024, 512, 256, 128)


def _fits(tm: int, tk: int, tn: int) -> bool:
    """Mosaic compile envelope, calibrated on v5e: double-buffered bf16
    input tiles within scoped VMEM, and the f32 accumulator tile below the
    observed crash line (tm*tn*4 of 4 MiB fails, 2.88 MiB compiles)."""
    return (2 * 2 * (tm * tk + tk * tn) + 4 * tm * tn <= 15.5 * 2**20
            and 4 * tm * tn <= 3 * 2**20)


def _pick_tilings(m: int, k: int, n: int):
    """Per-pass tilings for the Mosaic grouped matmul, measured on v5e at
    the bench shapes (m=32768, E=16; % of bf16 peak):

      fwd  [m,2048]@[E,2048,2816]  (512,512,1408)  33.7%  (512-cubed: 22%)
      fwd  [m,1408]@[E,1408,2048]  (256,1408,2048) 20.7%
      dgrad (transpose_rhs)        whole-K, tn=512 ~31%
      wgrad (tgmm)                 (512,512,1408)  29.2%

    The stock megablox ops.gmm shares ONE tiling between forward, dgrad,
    and tgmm — the measured optimum differs per pass (the dgrad/wgrad
    contraction is the forward's n/m), worth ~1.5x on the routed FFN.
    Returns (fwd, dgrad, wgrad) tilings or None for shapes the kernel
    doesn't like (odd alignments → ragged_dot). tgmm's first tile divides
    the contraction (m) — it must use the same m-aligned tm as the others."""
    if m % 256 or k % 128 or n % 128:
        return None
    tm = 512 if m % 512 == 0 else 256
    tn = next(t for t in _TILES if n % t == 0)
    if k % 512 == 0:
        fwd_cands = [(tm, 512, tn), (tm, 512, 512), (tm, 512, 128)]
    else:
        fwd_cands = [(256, k, n), (256, k, 1024), (256, k, 512)]
    cands = {
        "fwd": fwd_cands,
        "dgrad": [(tm, n, 512), (tm, 512, 512), (tm, 128, 512)],
        "wgrad": [(tm, 512, tn), (tm, 512, 512), (tm, 512, 128)],
    }
    picked = {}
    for pass_, cs in cands.items():
        picked[pass_] = next((c for c in cs if _fits(*c)), None)
        if picked[pass_] is None:
            return None
    return picked["fwd"], picked["dgrad"], picked["wgrad"]


def _zero_tail(out, gs):
    """Zero output rows >= sum(gs). The Mosaic gmm never visits row tiles
    past the last group (make_group_metadata, visit_empty_groups=False), so
    those rows are UNINITIALIZED memory — unlike ragged_dot, which defines
    them as zeros. The EP paths rely on zeroed tails (foreign assignments
    sort to the tail with combine weight 0; garbage NaN * 0 = NaN would
    poison the psum combine, and the take-vjp scatter-add would mix garbage
    into real token grads in backward)."""
    valid = jax.lax.broadcasted_iota(jnp.int32, (out.shape[0], 1), 0) \
        < jnp.sum(gs)
    return jnp.where(valid, out, 0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _gmm_tuned(lhs, rhs, gs, tilings, full_rows):
    from jax.experimental.pallas.ops.tpu.megablox.gmm import gmm as _gmm
    out = _gmm(lhs, rhs, gs, preferred_element_type=lhs.dtype,
               tiling=tilings[0])
    return out if full_rows else _zero_tail(out, gs)


def _gmm_tuned_fwd(lhs, rhs, gs, tilings, full_rows):
    return _gmm_tuned(lhs, rhs, gs, tilings, full_rows), (lhs, rhs, gs)


def _gmm_tuned_bwd(tilings, full_rows, res, grad):
    from jax.experimental.pallas.ops.tpu.megablox.gmm import (
        gmm as _gmm, tgmm as _tgmm)
    lhs, rhs, gs = res
    dlhs = _gmm(grad, rhs, gs, preferred_element_type=lhs.dtype,
                tiling=tilings[1], transpose_rhs=True)
    if not full_rows:
        dlhs = _zero_tail(dlhs, gs)
    drhs = _tgmm(lhs.swapaxes(0, 1), grad, gs,
                 preferred_element_type=rhs.dtype, tiling=tilings[2],
                 num_actual_groups=rhs.shape[0])
    return dlhs, drhs, None


_gmm_tuned.defvjp(_gmm_tuned_fwd, _gmm_tuned_bwd)


def grouped_matmul(xs, w, gs, full_rows: bool = False):
    """[m, k] @ per-group [E, k, n] over expert-sorted rows. On TPU this is
    the Mosaic block-sparse grouped matmul (MegaBlocks-style: only row
    blocks that exist are computed — the analogue of the reference's
    cutlass moe_gemm), with per-pass measured tilings (``_pick_tilings``);
    elsewhere jax.lax.ragged_dot.

    ``full_rows=True`` asserts sum(gs) == m statically (every row belongs
    to a group), skipping the tail-zeroing pass (``_zero_tail``).

    Note: the TPU path is reverse-mode only (custom_vjp) — forward-mode
    jvp/linearize of a dropless MoE falls back to the CPU/ragged_dot form.
    """
    m, k = xs.shape
    n = w.shape[-1]
    if jax.default_backend() == "tpu":
        tilings = _pick_tilings(m, k, n)
        if tilings is not None:
            return _gmm_tuned(xs, w, gs, tilings, full_rows)
    return jax.lax.ragged_dot(xs, w, gs)


def _expert_ffn(xs, gs, e_gate, e_up, e_down, dt, full_rows=False):
    """Grouped-GEMM SwiGLU over expert-sorted rows (rows ≥ sum(gs) are
    zeroed — the caller additionally masks their combine weight to zero).

    gate and up ride ONE grouped GEMM over a width-2f concat of the weights
    (the reference's cutlass moe_gemm batches them the same way): one pass
    over xs instead of two, and the wider N keeps the MXU fed — measured
    +60% utilization on the first GEMM at the bench shapes."""
    f = e_gate.shape[-1]
    gu = grouped_matmul(
        xs, jnp.concatenate([e_gate, e_up], axis=-1).astype(dt), gs,
        full_rows=full_rows)
    return grouped_matmul(
        jax.nn.silu(gu[..., :f]) * gu[..., f:], e_down.astype(dt), gs,
        full_rows=full_rows)


def _round_up(v: int, m: int) -> int:
    return -(-v // m) * m


def _dense_meta(idx, E: int, Q: int):
    """Branch-free routing metadata for the dense-base dispatch.

    Returns (r [A] slot id per flat assignment, src_tok [E*Q] source token
    per slot (0 for empty), w_sel [E*Q] assignment id per slot (A for
    empty), ok scalar bool: every expert's load fits Q).

    No sort: each assignment's rank within its expert is the exclusive
    prefix count of its expert's one-hot column — dense vector math the
    VPU chews through, vs. the bitonic argsort of the gmm path."""
    T, k = idx.shape
    A = T * k
    flat_e = idx.reshape(A)
    onehot = (flat_e[:, None] == jnp.arange(E, dtype=flat_e.dtype)[None, :]
              ).astype(jnp.int32)
    pos = jnp.take_along_axis(
        jnp.cumsum(onehot, axis=0) - onehot, flat_e[:, None], axis=1)[:, 0]
    gs = onehot.sum(axis=0)
    r = flat_e * Q + pos                       # slot per assignment
    ok = jnp.max(gs) <= Q
    # Overflow (pos >= Q, only when !ok) is clamped to E*Q so it truly
    # drops out of the scatter below — without the clamp an overflowing
    # assignment of expert e < E-1 would land inside expert e+1's slot
    # range and overwrite a valid slot. The cond still takes the gmm
    # branch when !ok; the clamp just keeps the metadata well-formed.
    r = jnp.where(pos < Q, r, E * Q)
    # slot -> flat assignment id (A = empty)
    w_sel = jnp.full((E * Q,), A, jnp.int32).at[r].set(
        jnp.arange(A, dtype=jnp.int32), mode="drop")
    src_tok = jnp.where(w_sel < A, w_sel // k, 0)
    return r, src_tok, w_sel, ok


@functools.partial(jax.custom_vjp, nondiff_argnums=(8,))
def _dense_base_ffn(x, weights, e_gate, e_up, e_down, r, src_tok, w_sel, k):
    y, _ = _dense_base_fwd_impl(x, weights, e_gate, e_up, e_down, r,
                                src_tok, w_sel, k)
    return y


def _dense_base_fwd_impl(x, weights, e_gate, e_up, e_down, r, src_tok,
                         w_sel, k):
    """Routed SwiGLU over a dense [E*Q, h] base buffer; gathers only.

    Every data-movement op here — and in the hand-written vjp below — is a
    gather: the combine uses the fact that slots r[t*k:(t+1)*k] enumerate
    exactly token t's assignments, so both y (fwd) and dx (bwd) are k-way
    gathered sums instead of the scatter-add the autodiff of jnp.take
    would emit (measured 3 ms/layer on v5e — the single hottest op of the
    r3 MoE step)."""
    T, h = x.shape
    E, _, f = e_gate.shape
    dt = x.dtype
    xb = jnp.take(x, src_tok, axis=0)                    # [E*Q, h]
    gu = jnp.einsum("eqh,ehf->eqf", xb.reshape(E, -1, h),
                    jnp.concatenate([e_gate, e_up], axis=-1).astype(dt),
                    preferred_element_type=dt)
    z = jax.nn.silu(gu[..., :f]) * gu[..., f:]
    yb = jnp.einsum("eqf,efh->eqh", z, e_down.astype(dt),
                    preferred_element_type=dt)
    ycat = yb.reshape(-1, h)
    yg = jnp.take(ycat, r, axis=0).reshape(T, k, h).astype(jnp.float32)
    w = weights.reshape(T, k).astype(jnp.float32)
    y = jnp.sum(yg * w[..., None], axis=1).astype(dt)
    return y, (x, weights, e_gate, e_up, e_down, r, src_tok, w_sel, xb,
               gu, z, ycat)


def _dense_base_fwd(x, weights, e_gate, e_up, e_down, r, src_tok, w_sel, k):
    return _dense_base_fwd_impl(x, weights, e_gate, e_up, e_down, r,
                                src_tok, w_sel, k)


def _dense_base_bwd(k, res, dy):
    (x, weights, e_gate, e_up, e_down, r, src_tok, w_sel, xb, gu, z,
     ycat) = res
    T, h = x.shape
    E, _, f = e_gate.shape
    dt = x.dtype
    A = T * k
    w = weights.reshape(A).astype(jnp.float32)

    # router-weight grad: d_w[a] = <dy[tok(a)], ycat[r[a]]>
    yg = jnp.take(ycat, r, axis=0).reshape(T, k, h).astype(jnp.float32)
    d_w = jnp.einsum("th,tkh->tk", dy.astype(jnp.float32), yg)

    # d_ycat: per-slot weight via the slot->assignment map from the
    # residuals (0 for empty slots), dy row via src_tok — gathers, not
    # the take-vjp scatter.
    w_slot = jnp.where(w_sel < A, jnp.take(w, jnp.minimum(w_sel, A - 1)),
                       0.0)
    d_yb = (jnp.take(dy, src_tok, axis=0).astype(jnp.float32)
            * w_slot[:, None]).astype(dt).reshape(E, -1, h)

    dz = jnp.einsum("eqh,efh->eqf", d_yb, e_down.astype(dt),
                    preferred_element_type=dt)
    d_down = jnp.einsum("eqf,eqh->efh", z, d_yb,
                        preferred_element_type=jnp.float32)
    g, u = gu[..., :f], gu[..., f:]
    sg = jax.nn.sigmoid(g.astype(jnp.float32)).astype(dt)
    silu_g = g * sg
    d_u = dz * silu_g
    d_g = dz * u * (sg + silu_g * (1 - sg)).astype(dt)
    dgu = jnp.concatenate([d_g, d_u], axis=-1)
    xbr = xb.reshape(E, -1, h)
    d_w1 = jnp.einsum("eqh,eqf->ehf", xbr, dgu,
                      preferred_element_type=jnp.float32)
    d_gate, d_up = d_w1[..., :f], d_w1[..., f:]
    d_xb = jnp.einsum("eqf,ehf->eqh",
                      dgu, jnp.concatenate([e_gate, e_up],
                                           axis=-1).astype(dt),
                      preferred_element_type=dt).reshape(-1, h)
    # dx[t] = sum_j d_xb[slot of assignment (t, j)] — gather by r again
    dx = jnp.sum(jnp.take(d_xb, r, axis=0).reshape(T, k, h)
                 .astype(jnp.float32), axis=1).astype(dt)
    return (dx, d_w.reshape(weights.shape),
            d_gate.astype(e_gate.dtype), d_up.astype(e_up.dtype),
            d_down.astype(e_down.dtype), None, None, None)


_dense_base_ffn.defvjp(_dense_base_fwd, _dense_base_bwd)


def dropless_moe_ffn_dense(x, weights, idx, e_gate, e_up, e_down,
                           slack: float = 0.125):
    """Capacity-less routed FFN, dense-base form (single program).

    The TPU-first reshape of the reference's unbounded global_scatter
    (moe_layer.py:105-188): instead of ragged grouped GEMMs over
    expert-sorted rows, scatter-free gathers stage each expert's tokens
    into a static [E, Q, h] buffer (Q = A/E rounded up with ``slack``
    headroom) and the expert FFN runs as *dense batched einsums* — 92% MXU
    on v5e vs 63% for the best-tiled Mosaic grouped matmul at the bench
    shapes, because XLA tiles a fixed-shape batched dot far better than
    any ragged kernel. Nothing is dropped: a lax.cond falls back to the
    sort+gmm path (`dropless_moe_ffn`) for the rare batch whose expert
    load exceeds Q, so the fast path's capacity is a *performance* bound,
    never a semantic one (vs. the reference's GShard capacity which
    silently drops — see MoEConfig.routing="capacity").

    Cost of the headroom: Q/(A/E)-1 wasted dense FLOPs (12.5% default) on
    empty slots whose outputs are never gathered; with balanced routing
    (what the aux loss maintains) the fallback fires with probability
    ~Phi(-5 sigma) per step."""
    T, h = x.shape
    E = e_gate.shape[0]
    k = idx.shape[1]
    A = T * k
    Q = min(_round_up(max(int(A / E * (1 + slack)), 1), 128), A)
    if E * Q > 4 * A:
        # tiny/test shapes: the base buffer would dwarf the real work
        return dropless_moe_ffn(x, weights, idx, e_gate, e_up, e_down)
    r, src_tok, w_sel, ok = _dense_meta(idx, E, Q)
    return jax.lax.cond(
        ok,
        lambda x, w, i: _dense_base_ffn(x, w, e_gate, e_up, e_down, r,
                                        src_tok, w_sel, k),
        lambda x, w, i: dropless_moe_ffn(x, w, i, e_gate, e_up, e_down),
        x, weights, idx)


def dropless_moe_ffn(x, weights, idx, e_gate, e_up, e_down):
    """Capacity-less routed FFN, single-program (GSPMD) form.

    x: [T,h]; weights/idx: [T,k] from the router; experts [E,h,f]/[E,f,h].
    Every assignment is computed — there is no capacity C and nothing to
    drop (reference semantics: moe_layer.py global_scatter with unbounded
    per-expert counts)."""
    T, h = x.shape
    E = e_gate.shape[0]
    dt = x.dtype
    order, tok, flat_e = sort_by_expert(idx)
    xs = jnp.take(x, tok, axis=0)                         # [T*k, h]
    gs = jnp.zeros((E,), jnp.int32).at[flat_e].add(1)
    # every assignment belongs to a real expert → sum(gs) == T*k
    ys = _expert_ffn(xs, gs, e_gate, e_up, e_down, dt, full_rows=True)
    ws = weights.reshape(T * idx.shape[1])[order].astype(jnp.float32)
    y = jnp.zeros((T, h), jnp.float32).at[tok].add(
        ys.astype(jnp.float32) * ws[:, None])
    return y.astype(dt)


def _ep_local(x_l, w_l, idx_l, eg_l, eu_l, ed_l, *, num_experts_local,
              compute_dtype):
    """Per-(data,ep)-rank body: local tokens × local expert shard, psum('ep').

    Assignments routed to foreign experts sort to the tail and get combine
    weight 0; the psum sums each token's k partial expert outputs across the
    ep ranks that own them. Boundary tensors are f32 (see the caller); the
    grouped GEMMs run in ``compute_dtype`` (bf16 on TPU → MXU)."""
    El = num_experts_local
    me = jax.lax.axis_index("ep")
    Tl, k = idx_l.shape
    A = Tl * k

    flat_e = idx_l.reshape(A)
    lid = flat_e - me * El
    mine = (lid >= 0) & (lid < El)
    order = jnp.argsort(jnp.where(mine, lid, El))         # foreign → tail
    tok = order // k
    xs = jnp.take(x_l.astype(compute_dtype), tok, axis=0)
    gs = jnp.zeros((El,), jnp.int32).at[jnp.where(mine, lid, 0)].add(
        mine.astype(jnp.int32))
    ys = _expert_ffn(xs, gs, eg_l, eu_l, ed_l, compute_dtype)
    ws = jnp.where(mine, w_l.reshape(A), 0.0)[order].astype(jnp.float32)
    y = jnp.zeros((Tl, x_l.shape[1]), jnp.float32).at[tok].add(
        ys.astype(jnp.float32) * ws[:, None])
    return jax.lax.psum(y, "ep")


def dropless_moe_ffn_ep(x, weights, idx, e_gate, e_up, e_down, mesh: Mesh,
                        token_axes: Tuple[str, ...] = ("dp",)):
    """Explicit expert-parallel dropless FFN (partial-manual shard_map).

    Token tensors are sharded over ``token_axes`` and replicated over 'ep';
    experts are sharded over 'ep' on their leading axis. Axes not named
    ('tp' fsdp etc.) stay under GSPMD control, so this nests inside a fully
    sharded train step.

    The shard_map boundary is kept f32: differentiating a bf16-carrying
    partial-manual shard_map inside ``lax.scan`` hits an XLA:CPU compiler
    check failure ("Invalid binary instruction opcode copy"); f32 in/out
    with bf16 compute inside the body sidesteps it, costs one fused convert
    on TPU, and makes the k-way combine psum f32-accurate."""
    E = e_gate.shape[0]
    ep = dict(mesh.shape).get("ep", 1)
    if ep <= 1 or E % ep != 0:
        return dropless_moe_ffn(x, weights, idx, e_gate, e_up, e_down)
    dt = x.dtype
    tok_axes = tuple(a for a in token_axes if dict(mesh.shape).get(a, 1) > 1)
    tok_spec = P(tok_axes if tok_axes else None)
    fn = jax.shard_map(
        lambda xl, wl, il, g, u, d: _ep_local(
            xl, wl, il, g, u, d, num_experts_local=E // ep,
            compute_dtype=dt),
        mesh=mesh,
        in_specs=(tok_spec, tok_spec, tok_spec, P("ep"), P("ep"), P("ep")),
        out_specs=tok_spec,
        axis_names=set(tok_axes) | {"ep"},
        check_vma=False)
    return fn(x.astype(jnp.float32), weights, idx,
              e_gate, e_up, e_down).astype(dt)


def _a2a_local(x_l, w_l, idx_l, eg_l, eu_l, ed_l, *, num_experts,
               num_experts_local, ep_size):
    """Per-ep-rank body of the ragged-all-to-all exchange (reference's
    global_scatter → grouped GEMM → global_gather, TPU collectives)."""
    E, El, R = num_experts, num_experts_local, ep_size
    me = jax.lax.axis_index("ep")
    Tl, k = idx_l.shape
    A = Tl * k
    Amax = A * R
    h = x_l.shape[1]
    dt = x_l.dtype

    flat_e = idx_l.reshape(A)
    order = jnp.argsort(flat_e)                    # expert order == rank order
    tok = order // k
    xs = jnp.take(x_l, tok, axis=0)                # [A,h] send buffer
    eid_send = flat_e[order]

    dest = flat_e // El
    send_sizes = jnp.zeros((R,), jnp.int32).at[dest].add(1)
    sizes = jax.lax.all_gather(send_sizes, "ep")   # [sender, dest]
    in_off = jnp.cumsum(send_sizes) - send_sizes
    recv_sizes = sizes[:, me]
    out_off = (jnp.cumsum(sizes, axis=0) - sizes)[me]

    xr = jax.lax.ragged_all_to_all(
        xs, jnp.zeros((Amax, h), dt),
        in_off, send_sizes, out_off, recv_sizes, axis_name="ep")
    er = jax.lax.ragged_all_to_all(
        eid_send, jnp.full((Amax,), E, jnp.int32),
        in_off, send_sizes, out_off, recv_sizes, axis_name="ep")

    lid = jnp.where(er < E, er - me * El, El)      # padding → tail group
    order2 = jnp.argsort(lid)
    xg = jnp.take(xr, order2, axis=0)
    valid = lid < El
    gs = jnp.zeros((El,), jnp.int32).at[jnp.where(valid, lid, 0)].add(
        valid.astype(jnp.int32))
    yg = _expert_ffn(xg, gs, eg_l, eu_l, ed_l, dt)
    yr = jnp.zeros_like(yg).at[order2].set(yg)     # back to receive order

    rev_in_off = jnp.cumsum(recv_sizes) - recv_sizes
    rev_out_off = (jnp.cumsum(sizes, axis=1) - sizes)[:, me]
    ys = jax.lax.ragged_all_to_all(
        yr, jnp.zeros((A, h), dt),
        rev_in_off, recv_sizes, rev_out_off, send_sizes, axis_name="ep")

    ws = w_l.reshape(A)[order].astype(jnp.float32)
    y = jnp.zeros((Tl, h), jnp.float32).at[tok].add(
        ys.astype(jnp.float32) * ws[:, None])
    return y.astype(dt)


def dropless_moe_ffn_a2a(x, weights, idx, e_gate, e_up, e_down, mesh: Mesh,
                         token_axes: Tuple[str, ...] = ("dp", "ep")):
    """Ragged-all-to-all dropless FFN: tokens sharded over ``token_axes``
    (which always includes 'ep'), exchanged to expert owners within each ep
    group and back (the literal global_scatter/global_gather shape — only
    ~T*k/ep assignments are GEMM'd per rank, vs the psum strategy's T*k).
    Requires a backend with a ragged-all-to-all lowering — real TPU;
    XLA:CPU raises UNIMPLEMENTED, so CPU tests use the _ep/psum strategy
    (a lowering-only test pins the wiring)."""
    E = e_gate.shape[0]
    ep = dict(mesh.shape).get("ep", 1)
    T = x.shape[0]
    tok_axes = tuple(dict.fromkeys(
        a for a in (*token_axes, "ep") if dict(mesh.shape).get(a, 1) > 1))
    n_tok_shards = int(np.prod([dict(mesh.shape)[a] for a in tok_axes])) \
        if tok_axes else 1
    if ep <= 1 or E % ep != 0 or T % max(n_tok_shards, 1) != 0:
        return dropless_moe_ffn(x, weights, idx, e_gate, e_up, e_down)
    tok_spec = P(tok_axes)
    fn = jax.shard_map(
        lambda xl, wl, il, g, u, d: _a2a_local(
            xl, wl, il, g, u, d, num_experts=E,
            num_experts_local=E // ep, ep_size=ep),
        mesh=mesh,
        in_specs=(tok_spec, tok_spec, tok_spec, P("ep"), P("ep"), P("ep")),
        out_specs=tok_spec,
        axis_names=set(tok_axes) | {"ep"},
        check_vma=False)
    return fn(x, weights, idx, e_gate, e_up, e_down)
