"""Dropless (capacity-less) MoE token dispatch.

Capability parity: the reference's capacity-less MoE all-to-all —
`global_scatter`/`global_gather` (incubate/distributed/models/moe/
moe_layer.py:105-188) exchanges a *ragged* number of tokens per expert and
drops nothing; its fused grouped-GEMM path
(phi/kernels/fusion/cutlass_kernels/moe_gemm/) batches the per-expert FFNs
into one kernel.

TPU-native re-design (three strategies, one semantic):

* ``dropless_moe_ffn``     — single-program GSPMD form: stable-sort the
  ``T*k`` (token, slot) assignments by expert, then three
  ``jax.lax.ragged_dot`` grouped GEMMs (the MXU analogue of the cutlass
  grouped GEMM). No capacity buffer exists, so no token is ever dropped.
* ``dropless_moe_ffn_ep``  — explicit expert-parallel form under
  ``jax.shard_map`` (partial-manual over the token + 'ep' axes): every ep
  rank keeps its expert shard, computes the assignments that route to its
  local experts with a local sort + ``ragged_dot``, and the combine is one
  ``psum`` over 'ep'. Token→expert traffic never leaves the rank (the
  tokens are ep-replicated already); the only collective is the [T,h]
  allreduce of the routed outputs — an ICI-friendly trade of the
  reference's two ragged all-to-alls.
* ``dropless_moe_ffn_a2a`` — the literal reference shape: tokens sharded
  over 'ep', exchanged with ``jax.lax.ragged_all_to_all`` (sizes exchanged
  via ``all_gather``), grouped-GEMM'd on the owner, and returned with the
  reverse ragged all-to-all. XLA:CPU has no ragged-all-to-all lowering, so
  this path is for real TPU meshes; the CPU test lane covers the other two.

All three differentiate: ``ragged_dot`` has jvp/transpose rules, the sorts
and scatters transpose to gathers, and the collectives transpose to
themselves (psum) or the reverse exchange.

Hot-path structure (see docs/moe.md):

* :func:`fused_routing` is the dispatch *prologue*: the fp32 router
  matmul, top-k gating, aux loss, AND the expert-sort scatter metadata
  come out of one shared one-hot/argsort — the router never round-trips
  through separate computations, and every dispatch form below accepts
  the precomputed ``routing=`` so nothing is derived twice.
* :func:`plan_dispatch` memoizes the shape-derived plan (slot count Q,
  dense-vs-gmm decision) per routing shape — every MoE layer of a model
  shares one plan, visible in ``moe_plan_cache_{hits,misses}_total``.
* ``grouped_matmul`` tilings come from the *measured* autotuner
  (:mod:`.gmm_autotune`) with the v5e heuristic as seed and fallback.
* The expert-parallel forms overlap their collectives with the shared-
  expert FFN: pass ``shared=(s_gate, s_up, s_down)`` and the token batch
  is processed as double-buffered halves, each half's collective hiding
  behind the other half's grouped GEMM and the shared-expert compute.
"""
from __future__ import annotations

import functools
import threading
import time
from typing import Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..framework.flags import define_flag, get_flag
from ..observability import numerics as _numerics
from ..observability import trace_span
from ..observability.catalog import instrument as _instrument
from .gmm_autotune import (  # noqa: F401  (re-exported for back-compat)
    _fits, get_tilings, heuristic_tilings, heuristic_tilings as
    _pick_tilings,
)

define_flag("moe_dispatch_autotune", True,
            "measure dense vs gmm vs fused dispatch once per routing "
            "shape on TPU and use the winner (never worse than the "
            "static default); off = the static choice")
define_flag("moe_overlap_min_tokens", 1024,
            "expert-parallel double-buffered overlap is bypassed below "
            "this per-rank token count (halving overhead beats the "
            "collective hiding on small slices; see docs/moe.md)")

__all__ = [
    "dropless_moe_ffn", "dropless_moe_ffn_dense", "dropless_moe_ffn_ep",
    "dropless_moe_ffn_a2a", "dropless_moe_ffn_fused", "sort_by_expert",
    "fused_routing", "Routing", "plan_dispatch", "DispatchPlan",
    "clear_plan_cache", "pick_dispatch_form", "clear_form_cache",
    "make_moe_operands", "time_best",
]

_M_PLAN_HITS = _instrument("moe_plan_cache_hits_total")
_M_PLAN_MISSES = _instrument("moe_plan_cache_misses_total")
_M_FALLBACKS = _instrument("moe_dispatch_fallbacks_total")
_M_OVERLAP_BYPASS = _instrument("moe_overlap_bypass_total")


def _shard_map(f, mesh, in_specs, out_specs, axis_names, check_vma=False):
    """jax.shard_map across jax versions: the public API (axis_names/
    check_vma) when present, else jax.experimental.shard_map (0.4.x —
    partial-manual is spelled ``auto`` = the complement of axis_names,
    replication checking is ``check_rep``)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, axis_names=axis_names,
                             check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_vma,
               auto=frozenset(mesh.axis_names) - set(axis_names))


def sort_by_expert(idx: jax.Array) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Flatten top-k assignments [T,k] → stable expert-sorted order.

    Returns (order [T*k] assignment permutation, tok [T*k] source token of
    each sorted assignment, flat_e [T*k] unsorted expert ids)."""
    T, k = idx.shape
    flat_e = idx.reshape(T * k)
    order = jnp.argsort(flat_e)           # stable → deterministic combine
    tok = order // k
    return order, tok, flat_e


# ---------------------------------------------------------------------------
# fused routing prologue — router matmul + gating + aux loss + sort metadata
# from ONE shared one-hot/argsort (the reference computes these as separate
# gate / scatter-prep passes; here they are one XLA computation feeding
# every dispatch strategy below)
# ---------------------------------------------------------------------------

class Routing(NamedTuple):
    """Everything the router run produces, computed once per MoE layer.

    ``weights``/``idx``/``aux`` match :func:`models.moe.top_k_gating`
    bit-for-bit at fp32; ``order``/``tok``/``flat_e``/``gs`` are the
    expert-sort scatter metadata the single-program dispatch forms would
    otherwise re-derive."""

    weights: jax.Array   # [T,k] f32, renormalized top-k gate weights
    idx: jax.Array       # [T,k] int32 expert ids
    aux: jax.Array       # scalar f32 load-balance aux loss (GShard eq. 4)
    order: jax.Array     # [T*k] expert-sorted assignment permutation
    tok: jax.Array       # [T*k] source token of each sorted assignment
    flat_e: jax.Array    # [T*k] unsorted expert ids
    gs: jax.Array        # [E] int32 per-expert assignment counts


def routing_from_logits(logits: jax.Array, top_k: int) -> Routing:
    """Gating + metadata from precomputed router logits (fp32)."""
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)   # [T,E]
    weights, idx = jax.lax.top_k(probs, top_k)                    # [T,k]
    weights = weights / jnp.sum(weights, -1, keepdims=True)
    T, E = logits.shape
    A = T * top_k
    flat_e = idx.reshape(A)
    # ONE one-hot feeds the group sizes AND the aux-loss expert fractions
    onehot = (flat_e[:, None] == jnp.arange(E, dtype=flat_e.dtype)[None, :]
              ).astype(jnp.int32)                                 # [A,E]
    gs = onehot.sum(axis=0)
    me = jnp.mean(probs, axis=0)                                  # [E]
    # rows 0, k, 2k, ... of the flat one-hot are the top-1 assignments
    ce = jnp.mean(
        onehot.reshape(T, top_k, E)[:, 0].astype(jnp.float32), axis=0)
    aux = E * jnp.sum(me * ce)
    order = jnp.argsort(flat_e)           # stable → deterministic combine
    tok = order // top_k
    return Routing(weights, idx, aux, order, tok, flat_e, gs)


def fused_routing(x: jax.Array, router_w: jax.Array,
                  top_k: int) -> Routing:
    """The dispatch prologue: fp32 router matmul → :class:`Routing`.

    Numerically identical to ``top_k_gating(x.astype(f32) @
    router_w.astype(f32), top_k)`` (same op sequence), plus the sort
    metadata every single-program dispatch form consumes via
    ``routing=`` — so the router, the aux loss, and the scatter prep
    are one fused computation instead of three."""
    logits = x.astype(jnp.float32) @ router_w.astype(jnp.float32)
    # numerics probe on the router logits (trace-time gated, zero ops
    # when off): a diverging router is the classic MoE blowup source,
    # and its NaNs surface HERE before they smear across every expert.
    # Visibility contract: this site sits inside the scanned layer
    # body, so it lands in forward/serving programs and in remat'd
    # training bodies (the recompute re-runs it) — an un-checkpointed
    # grad drops it (see numerics.record_stats); the per-layer ladder
    # in models/ covers training regardless.
    _numerics.record_stats("moe.router_logits", logits)
    return routing_from_logits(logits, top_k)


# ---------------------------------------------------------------------------
# dispatch plan — shape-derived constants, one per routing shape
# ---------------------------------------------------------------------------

class DispatchPlan(NamedTuple):
    """Static dispatch decisions for one routing shape (T, k, E, h).

    Everything here is derivable from shapes alone — it is *host-side*
    metadata (slot count Q, dense-base eligibility), computed once and
    shared by every MoE layer and every step with the same shape instead
    of being re-derived per layer."""

    T: int
    k: int
    E: int
    h: int
    Q: int               # dense-base slots per expert (A/E + slack, /128)
    use_dense: bool      # dense [E,Q,h] staging beats the gmm sort here


_PLAN_CACHE: Dict[tuple, DispatchPlan] = {}
_PLAN_LOCK = threading.Lock()


def _round_up(v: int, m: int) -> int:
    return -(-v // m) * m


def plan_dispatch(T: int, k: int, E: int, h: int,
                  slack: float = 0.125,
                  dense_base: bool = True) -> DispatchPlan:
    """The memoized plan for one routing shape (hit = every MoE layer
    after the first, and every later step)."""
    key = (T, k, E, h, float(slack), bool(dense_base))
    with _PLAN_LOCK:
        plan = _PLAN_CACHE.get(key)
    if plan is not None:
        _M_PLAN_HITS.inc()
        return plan
    _M_PLAN_MISSES.inc()
    A = T * k
    Q = min(_round_up(max(int(A / E * (1 + slack)), 1), 128), A)
    use_dense = bool(dense_base) and E * Q <= 4 * A
    if dense_base and not use_dense:
        # tiny/test shapes: the base buffer would dwarf the real work
        _M_FALLBACKS.labels(reason="dense_buffer_too_big").inc()
    plan = DispatchPlan(T, k, E, h, Q, use_dense)
    with _PLAN_LOCK:
        _PLAN_CACHE.setdefault(key, plan)
    return plan


def clear_plan_cache() -> None:
    with _PLAN_LOCK:
        _PLAN_CACHE.clear()


# ---------------------------------------------------------------------------
# measured dispatch-form selection — the r05 regression fix
#
# r04 made the dense-base staging form the static default on the strength
# of a forward-only MXU measurement; under the full train step it lost
# ~7% to the grouped-GEMM form at the bench shape (BENCH_r05 0.925x,
# docs/moe.md postmortem). Shape heuristics keep getting this wrong, so
# the form is now MEASURED once per routing shape on TPU — fwd+bwd, the
# quantity the bench actually pays — and the winner is persisted through
# the jit artifact cache. The static default ("fused") is always among
# the candidates, so the pick is never worse than the fallback.
# ---------------------------------------------------------------------------

_FORM_PERSIST = "moe_dispatch_forms"
# v2: keys gained the dense_ok candidate-set field — an entry measured
# with the dense form admitted must never answer for a caller that
# excluded it (dense staging can OOM where fused/gmm cannot)
_FORM_SCHEMA = 2
_FORM_STATIC = "fused"
_FORM_CACHE: Dict[str, dict] = {}
_FORM_LOADED = False


def _forms_ensure_loaded() -> None:
    global _FORM_LOADED
    if _FORM_LOADED:
        return
    from ..jit import cache as _jcache

    disk = _jcache.load_json(_FORM_PERSIST, schema=_FORM_SCHEMA)
    with _PLAN_LOCK:
        if _FORM_LOADED:
            return
        for key, ent in disk.items():
            if (isinstance(ent, dict)
                    and ent.get("winner") in ("fused", "gmm", "dense")
                    and key not in _FORM_CACHE):
                _FORM_CACHE[key] = ent
        _FORM_LOADED = True


def _forms_persist() -> None:
    from ..jit import cache as _jcache

    with _PLAN_LOCK:
        doc = {k: dict(e) for k, e in _FORM_CACHE.items()
               if e.get("source") == "measured"}
    _jcache.store_json(_FORM_PERSIST, doc, schema=_FORM_SCHEMA)


def clear_form_cache() -> None:
    global _FORM_LOADED
    with _PLAN_LOCK:
        _FORM_CACHE.clear()
        _FORM_LOADED = False


def make_moe_operands(T: int, h: int, E: int, f: int, dtype, seed: int = 0):
    """The shared synthetic routed-FFN operand recipe: ``(x [T,h],
    router_w [h,E] f32, e_gate [E,h,f], e_up [E,h,f], e_down [E,f,h])``
    with weights scaled 0.1. Every measurement/parity surface (the
    dispatch-form autotuner here, ``bench.moe_phase_breakdown``, the
    ``tests_tpu/`` lane) builds operands through THIS function so they
    time and compare the same problem."""
    ks = jax.random.split(jax.random.PRNGKey(seed), 5)
    x = jax.random.normal(ks[0], (T, h), jnp.float32).astype(dtype)
    rw = jax.random.normal(ks[1], (h, E), jnp.float32) * 0.1
    eg = (jax.random.normal(ks[2], (E, h, f), jnp.float32) * 0.1
          ).astype(dtype)
    eu = (jax.random.normal(ks[3], (E, h, f), jnp.float32) * 0.1
          ).astype(dtype)
    ed = (jax.random.normal(ks[4], (E, f, h), jnp.float32) * 0.1
          ).astype(dtype)
    return x, rw, eg, eu, ed


def time_best(fn, *args, n: int = 3) -> float:
    """Best-of-``n`` wall-clock seconds of ``jax.jit(fn)(*args)`` after a
    compile+warm call — the shared timing discipline of the dispatch-form
    and phase-breakdown measurements."""
    f_jit = jax.jit(fn)
    jax.block_until_ready(f_jit(*args))             # compile + warm
    best = float("inf")
    for _ in range(n):
        t0 = time.perf_counter()
        jax.block_until_ready(f_jit(*args))
        best = min(best, time.perf_counter() - t0)
    return best


def _default_form_measure(T: int, k: int, E: int, h: int, f: int, dtype
                          ) -> Optional[Callable]:
    """fwd+bwd timing closure for one dispatch form at the real routing
    shape, or None off-TPU (the static default answers there)."""
    if jax.default_backend() != "tpu":
        return None

    def run(form: str) -> float:
        from . import moe_fused as _mf

        fns = {"fused": _mf.fused_moe_ffn,
               "gmm": dropless_moe_ffn,
               "dense": dropless_moe_ffn_dense}
        fn = fns[form]
        x, rw, eg, eu, ed = make_moe_operands(T, h, E, f, dtype)

        def loss(x, eg, eu, ed):
            r = fused_routing(x, rw, k)
            y = fn(x, r.weights, r.idx, eg, eu, ed, routing=r)
            return jnp.sum(jnp.square(y.astype(jnp.float32)))

        return time_best(jax.grad(loss, argnums=(0, 1, 2, 3)),
                         x, eg, eu, ed)

    return run


def pick_dispatch_form(T: int, k: int, E: int, h: int, f: int, dtype,
                       *, dense_ok: bool = False,
                       measure: Optional[Callable] = None) -> str:
    """'fused' | 'gmm' | 'dense' for one single-program routing shape.

    TPU: first encounter measures fwd+bwd of each candidate form at the
    real shape, keeps the winner (never worse than the static default —
    the default is always a candidate, and a winner inside the noise
    band of the default is rejected in its favor), and persists it.
    Elsewhere, or with ``FLAGS_moe_dispatch_autotune`` off: the static
    default. ``measure(form) -> seconds`` is injectable for tests."""
    static = _FORM_STATIC
    if not get_flag("moe_dispatch_autotune"):
        return static
    runner = measure if measure is not None else _default_form_measure(
        T, k, E, h, f, dtype)
    if runner is None:
        return static
    from .gmm_autotune import _device_tag

    cands = ["fused", "gmm"] + (["dense"] if dense_ok else [])
    _forms_ensure_loaded()
    key = (f"{_device_tag()}|T={T}|k={k}|E={E}|h={h}|f={f}|"
           f"{np.dtype(dtype).name}|dense_ok={bool(dense_ok)}")
    with _PLAN_LOCK:
        ent = _FORM_CACHE.get(key)
    if ent is not None and ent["winner"] in cands:
        return ent["winner"]
    times: Dict[str, float] = {}
    with trace_span("moe.autotune", kind="dispatch_form", T=T, E=E):
        for form in cands:
            try:
                times[form] = runner(form)
            except Exception:
                continue              # a form that fails to build loses
    if static not in times:
        return static
    winner = min(times, key=times.get)
    if winner != static and times[winner] > times[static] * 0.98:
        winner = static               # within noise: keep the default
    ent = {"winner": winner,
           "ms": {fm: round(v * 1e3, 3) for fm, v in times.items()},
           "source": "measured"}
    with _PLAN_LOCK:
        # a concurrent measurement may have raced us — keep the existing
        # entry only if its winner is admissible HERE, else overwrite (a
        # stale record must never answer with an excluded form)
        existing = _FORM_CACHE.get(key)
        if existing is not None and existing.get("winner") in cands:
            ent = existing
        else:
            _FORM_CACHE[key] = ent
    _forms_persist()
    return ent["winner"]


def _zero_tail(out, gs):
    """Zero output rows >= sum(gs). The Mosaic gmm never visits row tiles
    past the last group (make_group_metadata, visit_empty_groups=False), so
    those rows are UNINITIALIZED memory — unlike ragged_dot, which defines
    them as zeros. The EP paths rely on zeroed tails (foreign assignments
    sort to the tail with combine weight 0; garbage NaN * 0 = NaN would
    poison the psum combine, and the take-vjp scatter-add would mix garbage
    into real token grads in backward)."""
    valid = jax.lax.broadcasted_iota(jnp.int32, (out.shape[0], 1), 0) \
        < jnp.sum(gs)
    return jnp.where(valid, out, 0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _gmm_tuned(lhs, rhs, gs, tilings, full_rows):
    from jax.experimental.pallas.ops.tpu.megablox.gmm import gmm as _gmm
    out = _gmm(lhs, rhs, gs, preferred_element_type=lhs.dtype,
               tiling=tilings[0])
    return out if full_rows else _zero_tail(out, gs)


def _gmm_tuned_fwd(lhs, rhs, gs, tilings, full_rows):
    return _gmm_tuned(lhs, rhs, gs, tilings, full_rows), (lhs, rhs, gs)


def _gmm_tuned_bwd(tilings, full_rows, res, grad):
    from jax.experimental.pallas.ops.tpu.megablox.gmm import (
        gmm as _gmm, tgmm as _tgmm)
    lhs, rhs, gs = res
    dlhs = _gmm(grad, rhs, gs, preferred_element_type=lhs.dtype,
                tiling=tilings[1], transpose_rhs=True)
    if not full_rows:
        dlhs = _zero_tail(dlhs, gs)
    drhs = _tgmm(lhs.swapaxes(0, 1), grad, gs,
                 preferred_element_type=rhs.dtype, tiling=tilings[2],
                 num_actual_groups=rhs.shape[0])
    return dlhs, drhs, None


_gmm_tuned.defvjp(_gmm_tuned_fwd, _gmm_tuned_bwd)


def grouped_matmul(xs, w, gs, full_rows: bool = False):
    """[m, k] @ per-group [E, k, n] over expert-sorted rows. On TPU this is
    the Mosaic block-sparse grouped matmul (MegaBlocks-style: only row
    blocks that exist are computed — the analogue of the reference's
    cutlass moe_gemm), with per-pass tilings from the measured autotuner
    (:func:`gmm_autotune.get_tilings`: first encounter of each
    ``(m, k, n, E, dtype, full_rows)`` key times a candidate grid, the
    winner is cached in-process and persisted); elsewhere
    jax.lax.ragged_dot.

    ``full_rows=True`` asserts sum(gs) == m statically (every row belongs
    to a group), skipping the tail-zeroing pass (``_zero_tail``).

    Note: the TPU path is reverse-mode only (custom_vjp) — forward-mode
    jvp/linearize of a dropless MoE falls back to the CPU/ragged_dot form.
    """
    m, k = xs.shape
    n = w.shape[-1]
    if jax.default_backend() == "tpu":
        tilings = get_tilings(m, k, n, w.shape[0], xs.dtype, full_rows)
        if tilings is not None:
            return _gmm_tuned(xs, w, gs, tilings, full_rows)
        _M_FALLBACKS.labels(reason="shape_unaligned").inc()
    return jax.lax.ragged_dot(xs, w, gs)


def _expert_ffn(xs, gs, e_gate, e_up, e_down, dt, full_rows=False):
    """Grouped-GEMM SwiGLU over expert-sorted rows (rows ≥ sum(gs) are
    zeroed — the caller additionally masks their combine weight to zero).

    gate and up ride ONE grouped GEMM over a width-2f concat of the weights
    (the reference's cutlass moe_gemm batches them the same way): one pass
    over xs instead of two, and the wider N keeps the MXU fed — measured
    +60% utilization on the first GEMM at the bench shapes."""
    f = e_gate.shape[-1]
    gu = grouped_matmul(
        xs, jnp.concatenate([e_gate, e_up], axis=-1).astype(dt), gs,
        full_rows=full_rows)
    return grouped_matmul(
        jax.nn.silu(gu[..., :f]) * gu[..., f:], e_down.astype(dt), gs,
        full_rows=full_rows)


def _shared_swiglu(x, s_gate, s_up, s_down, dt):
    """The always-on shared-expert FFN — computed inside the expert-
    parallel dispatch bodies so its MXU work hides the collectives."""
    xc = x.astype(dt)
    g = jax.nn.silu(xc @ s_gate.astype(dt))
    return (g * (xc @ s_up.astype(dt))) @ s_down.astype(dt)


def _dense_meta(idx, E: int, Q: int):
    """Branch-free routing metadata for the dense-base dispatch.

    Returns (r [A] slot id per flat assignment, src_tok [E*Q] source token
    per slot (0 for empty), w_sel [E*Q] assignment id per slot (A for
    empty), ok scalar bool: every expert's load fits Q).

    No sort: each assignment's rank within its expert is the exclusive
    prefix count of its expert's one-hot column — dense vector math the
    VPU chews through, vs. the bitonic argsort of the gmm path."""
    T, k = idx.shape
    A = T * k
    flat_e = idx.reshape(A)
    onehot = (flat_e[:, None] == jnp.arange(E, dtype=flat_e.dtype)[None, :]
              ).astype(jnp.int32)
    pos = jnp.take_along_axis(
        jnp.cumsum(onehot, axis=0) - onehot, flat_e[:, None], axis=1)[:, 0]
    gs = onehot.sum(axis=0)
    r = flat_e * Q + pos                       # slot per assignment
    ok = jnp.max(gs) <= Q
    # Overflow (pos >= Q, only when !ok) is clamped to E*Q so it truly
    # drops out of the scatter below — without the clamp an overflowing
    # assignment of expert e < E-1 would land inside expert e+1's slot
    # range and overwrite a valid slot. The cond still takes the gmm
    # branch when !ok; the clamp just keeps the metadata well-formed.
    r = jnp.where(pos < Q, r, E * Q)
    # slot -> flat assignment id (A = empty)
    w_sel = jnp.full((E * Q,), A, jnp.int32).at[r].set(
        jnp.arange(A, dtype=jnp.int32), mode="drop")
    src_tok = jnp.where(w_sel < A, w_sel // k, 0)
    return r, src_tok, w_sel, ok


@functools.partial(jax.custom_vjp, nondiff_argnums=(8,))
def _dense_base_ffn(x, weights, e_gate, e_up, e_down, r, src_tok, w_sel, k):
    y, _ = _dense_base_fwd_impl(x, weights, e_gate, e_up, e_down, r,
                                src_tok, w_sel, k)
    return y


def _dense_base_fwd_impl(x, weights, e_gate, e_up, e_down, r, src_tok,
                         w_sel, k):
    """Routed SwiGLU over a dense [E*Q, h] base buffer; gathers only.

    Every data-movement op here — and in the hand-written vjp below — is a
    gather: the combine uses the fact that slots r[t*k:(t+1)*k] enumerate
    exactly token t's assignments, so both y (fwd) and dx (bwd) are k-way
    gathered sums instead of the scatter-add the autodiff of jnp.take
    would emit (measured 3 ms/layer on v5e — the single hottest op of the
    r3 MoE step)."""
    T, h = x.shape
    E, _, f = e_gate.shape
    dt = x.dtype
    xb = jnp.take(x, src_tok, axis=0)                    # [E*Q, h]
    gu = jnp.einsum("eqh,ehf->eqf", xb.reshape(E, -1, h),
                    jnp.concatenate([e_gate, e_up], axis=-1).astype(dt),
                    preferred_element_type=dt)
    z = jax.nn.silu(gu[..., :f]) * gu[..., f:]
    yb = jnp.einsum("eqf,efh->eqh", z, e_down.astype(dt),
                    preferred_element_type=dt)
    ycat = yb.reshape(-1, h)
    yg = jnp.take(ycat, r, axis=0).reshape(T, k, h).astype(jnp.float32)
    w = weights.reshape(T, k).astype(jnp.float32)
    y = jnp.sum(yg * w[..., None], axis=1).astype(dt)
    return y, (x, weights, e_gate, e_up, e_down, r, src_tok, w_sel, xb,
               gu, z, ycat)


def _dense_base_fwd(x, weights, e_gate, e_up, e_down, r, src_tok, w_sel, k):
    return _dense_base_fwd_impl(x, weights, e_gate, e_up, e_down, r,
                                src_tok, w_sel, k)


def _dense_base_bwd(k, res, dy):
    (x, weights, e_gate, e_up, e_down, r, src_tok, w_sel, xb, gu, z,
     ycat) = res
    T, h = x.shape
    E, _, f = e_gate.shape
    dt = x.dtype
    A = T * k
    w = weights.reshape(A).astype(jnp.float32)

    # router-weight grad: d_w[a] = <dy[tok(a)], ycat[r[a]]>
    yg = jnp.take(ycat, r, axis=0).reshape(T, k, h).astype(jnp.float32)
    d_w = jnp.einsum("th,tkh->tk", dy.astype(jnp.float32), yg)

    # d_ycat: per-slot weight via the slot->assignment map from the
    # residuals (0 for empty slots), dy row via src_tok — gathers, not
    # the take-vjp scatter.
    w_slot = jnp.where(w_sel < A, jnp.take(w, jnp.minimum(w_sel, A - 1)),
                       0.0)
    d_yb = (jnp.take(dy, src_tok, axis=0).astype(jnp.float32)
            * w_slot[:, None]).astype(dt).reshape(E, -1, h)

    dz = jnp.einsum("eqh,efh->eqf", d_yb, e_down.astype(dt),
                    preferred_element_type=dt)
    d_down = jnp.einsum("eqf,eqh->efh", z, d_yb,
                        preferred_element_type=jnp.float32)
    g, u = gu[..., :f], gu[..., f:]
    sg = jax.nn.sigmoid(g.astype(jnp.float32)).astype(dt)
    silu_g = g * sg
    d_u = dz * silu_g
    d_g = dz * u * (sg + silu_g * (1 - sg)).astype(dt)
    dgu = jnp.concatenate([d_g, d_u], axis=-1)
    xbr = xb.reshape(E, -1, h)
    d_w1 = jnp.einsum("eqh,eqf->ehf", xbr, dgu,
                      preferred_element_type=jnp.float32)
    d_gate, d_up = d_w1[..., :f], d_w1[..., f:]
    d_xb = jnp.einsum("eqf,ehf->eqh",
                      dgu, jnp.concatenate([e_gate, e_up],
                                           axis=-1).astype(dt),
                      preferred_element_type=dt).reshape(-1, h)
    # dx[t] = sum_j d_xb[slot of assignment (t, j)] — gather by r again
    dx = jnp.sum(jnp.take(d_xb, r, axis=0).reshape(T, k, h)
                 .astype(jnp.float32), axis=1).astype(dt)
    return (dx, d_w.reshape(weights.shape),
            d_gate.astype(e_gate.dtype), d_up.astype(e_up.dtype),
            d_down.astype(e_down.dtype), None, None, None)


_dense_base_ffn.defvjp(_dense_base_fwd, _dense_base_bwd)


def dropless_moe_ffn_dense(x, weights, idx, e_gate, e_up, e_down,
                           slack: float = 0.125,
                           routing: Optional[Routing] = None,
                           plan: Optional[DispatchPlan] = None):
    """Capacity-less routed FFN, dense-base form (single program).

    The TPU-first reshape of the reference's unbounded global_scatter
    (moe_layer.py:105-188): instead of ragged grouped GEMMs over
    expert-sorted rows, scatter-free gathers stage each expert's tokens
    into a static [E, Q, h] buffer (Q = A/E rounded up with ``slack``
    headroom) and the expert FFN runs as *dense batched einsums* — 92% MXU
    on v5e vs 63% for the best-tiled Mosaic grouped matmul at the bench
    shapes, because XLA tiles a fixed-shape batched dot far better than
    any ragged kernel. Nothing is dropped: a lax.cond falls back to the
    sort+gmm path (`dropless_moe_ffn`) for the rare batch whose expert
    load exceeds Q, so the fast path's capacity is a *performance* bound,
    never a semantic one (vs. the reference's GShard capacity which
    silently drops — see MoEConfig.routing="capacity").

    Cost of the headroom: Q/(A/E)-1 wasted dense FLOPs (12.5% default) on
    empty slots whose outputs are never gathered; with balanced routing
    (what the aux loss maintains) the fallback fires with probability
    ~Phi(-5 sigma) per step.

    ``routing`` (from :func:`fused_routing`) is reused when this shape
    skips the dense base entirely; ``plan`` skips re-deriving Q when the
    caller already holds the shared :class:`DispatchPlan`."""
    T, h = x.shape
    E = e_gate.shape[0]
    k = idx.shape[1]
    if plan is None:
        plan = plan_dispatch(T, k, E, h, slack=slack)
    Q = plan.Q
    if not plan.use_dense:
        return dropless_moe_ffn(x, weights, idx, e_gate, e_up, e_down,
                                routing=routing)
    r, src_tok, w_sel, ok = _dense_meta(idx, E, Q)
    # the overflow fallback must NOT capture routing.order/tok: cond
    # operands are computed unconditionally every step, while work inside
    # the untaken branch is not — re-deriving the sort in the ~never-taken
    # branch keeps the argsort off the steady-state dense path (the
    # prologue's sort metadata is DCE'd when nothing else consumes it)
    return jax.lax.cond(
        ok,
        lambda x, w, i: _dense_base_ffn(x, w, e_gate, e_up, e_down, r,
                                        src_tok, w_sel, k),
        lambda x, w, i: dropless_moe_ffn(x, w, i, e_gate, e_up, e_down),
        x, weights, idx)


def dropless_moe_ffn(x, weights, idx, e_gate, e_up, e_down,
                     routing: Optional[Routing] = None):
    """Capacity-less routed FFN, single-program (GSPMD) form.

    x: [T,h]; weights/idx: [T,k] from the router; experts [E,h,f]/[E,f,h].
    Every assignment is computed — there is no capacity C and nothing to
    drop (reference semantics: moe_layer.py global_scatter with unbounded
    per-expert counts).

    With ``routing`` (the :func:`fused_routing` prologue) the sort
    permutation and group sizes are reused instead of re-derived."""
    T, h = x.shape
    E = e_gate.shape[0]
    dt = x.dtype
    if routing is None:
        order, tok, flat_e = sort_by_expert(idx)
        gs = jnp.zeros((E,), jnp.int32).at[flat_e].add(1)
    else:
        order, tok, gs = routing.order, routing.tok, routing.gs
    xs = jnp.take(x, tok, axis=0)                         # [T*k, h]
    # every assignment belongs to a real expert → sum(gs) == T*k
    ys = _expert_ffn(xs, gs, e_gate, e_up, e_down, dt, full_rows=True)
    ws = weights.reshape(T * idx.shape[1])[order].astype(jnp.float32)
    y = jnp.zeros((T, h), jnp.float32).at[tok].add(
        ys.astype(jnp.float32) * ws[:, None])
    return y.astype(dt)


def dropless_moe_ffn_fused(x, weights, idx, e_gate, e_up, e_down,
                           routing: Optional[Routing] = None):
    """Capacity-less routed FFN, fused scatter-free form — see
    :func:`paddle_tpu.kernels.moe_fused.fused_moe_ffn` (same grouped
    GEMMs as :func:`dropless_moe_ffn`, gather-only data movement in both
    directions, Pallas gather-GMM kernel on TPU, int8 expert dicts)."""
    from .moe_fused import fused_moe_ffn

    return fused_moe_ffn(x, weights, idx, e_gate, e_up, e_down,
                         routing=routing)


def _ep_partial(x_l, w_l, idx_l, eg_l, eu_l, ed_l, *, El, me, dt):
    """Routed partial sums for one token slice: local tokens × local
    expert shard, pre-psum [T_slice, h] f32.

    Assignments routed to foreign experts sort to the tail and get combine
    weight 0; the caller's psum sums each token's k partial expert outputs
    across the ep ranks that own them."""
    Tl, k = idx_l.shape
    A = Tl * k

    flat_e = idx_l.reshape(A)
    lid = flat_e - me * El
    mine = (lid >= 0) & (lid < El)
    order = jnp.argsort(jnp.where(mine, lid, El))         # foreign → tail
    tok = order // k
    xs = jnp.take(x_l.astype(dt), tok, axis=0)
    gs = jnp.zeros((El,), jnp.int32).at[jnp.where(mine, lid, 0)].add(
        mine.astype(jnp.int32))
    ys = _expert_ffn(xs, gs, eg_l, eu_l, ed_l, dt)
    ws = jnp.where(mine, w_l.reshape(A), 0.0)[order].astype(jnp.float32)
    return jnp.zeros((Tl, x_l.shape[1]), jnp.float32).at[tok].add(
        ys.astype(jnp.float32) * ws[:, None])


def _overlap_bypassed(shared_w, Tl: int) -> bool:
    """True when the double-buffered-halves overlap should not run for a
    per-rank token slice of ``Tl``: no shared-expert FFN to hide behind,
    an un-halvable slice, or a slice below ``FLAGS_moe_overlap_min_tokens``
    — on small slices the halved grouped GEMMs lose more MXU efficiency
    than the collective hiding buys (the r05 bisect lever), so single
    buffering wins. Threshold bypasses are counted per traced call site
    in ``moe_overlap_bypass_total``."""
    if shared_w is None or Tl < 2 or Tl % 2:
        return True
    if Tl < int(get_flag("moe_overlap_min_tokens")):
        _M_OVERLAP_BYPASS.inc()
        return True
    return False


def _ep_local(x_l, w_l, idx_l, eg_l, eu_l, ed_l, shared_w=None, *,
              num_experts_local, compute_dtype):
    """Per-(data,ep)-rank body of the psum strategy. Boundary tensors are
    f32 (see the caller); the grouped GEMMs run in ``compute_dtype``
    (bf16 on TPU → MXU).

    With ``shared_w`` the token slice is processed as double-buffered
    halves: half 0's combine psum is issued while half 1's grouped GEMMs
    run, and the shared-expert FFN fills the remaining collective
    shadow — the psum never sits on the critical path alone."""
    El = num_experts_local
    me = jax.lax.axis_index("ep")
    dt = compute_dtype
    Tl = x_l.shape[0]
    part = functools.partial(_ep_partial, eg_l=eg_l, eu_l=eu_l, ed_l=ed_l,
                             El=El, me=me, dt=dt)
    if _overlap_bypassed(shared_w, Tl):
        y = jax.lax.psum(part(x_l, w_l, idx_l), "ep")
        if shared_w is not None:
            y = y + _shared_swiglu(x_l, *shared_w, dt).astype(jnp.float32)
        return y
    H = Tl // 2
    y0 = part(x_l[:H], w_l[:H], idx_l[:H])
    p0 = jax.lax.psum(y0, "ep")           # in flight while half 1 computes
    y1 = part(x_l[H:], w_l[H:], idx_l[H:])
    p1 = jax.lax.psum(y1, "ep")           # hidden by the shared FFN below
    s = _shared_swiglu(x_l, *shared_w, dt).astype(jnp.float32)
    return jnp.concatenate([p0, p1], axis=0) + s


def dropless_moe_ffn_ep(x, weights, idx, e_gate, e_up, e_down, mesh: Mesh,
                        token_axes: Tuple[str, ...] = ("dp",),
                        shared: Optional[Tuple] = None):
    """Explicit expert-parallel dropless FFN (partial-manual shard_map).

    Token tensors are sharded over ``token_axes`` and replicated over 'ep';
    experts are sharded over 'ep' on their leading axis. Axes not named
    ('tp' fsdp etc.) stay under GSPMD control, so this nests inside a fully
    sharded train step.

    ``shared=(s_gate, s_up, s_down)`` moves the always-on shared-expert
    FFN *inside* the shard_map body so its compute overlaps the combine
    psum (double-buffered halves, see :func:`_ep_local`); the return
    value is then routed + shared.

    The shard_map boundary is kept f32: differentiating a bf16-carrying
    partial-manual shard_map inside ``lax.scan`` hits an XLA:CPU compiler
    check failure ("Invalid binary instruction opcode copy"); f32 in/out
    with bf16 compute inside the body sidesteps it, costs one fused convert
    on TPU, and makes the k-way combine psum f32-accurate."""
    E = e_gate.shape[0]
    ep = dict(mesh.shape).get("ep", 1)
    if ep <= 1 or E % ep != 0:
        _M_FALLBACKS.labels(reason="ep_shape_mismatch").inc()
        y = dropless_moe_ffn(x, weights, idx, e_gate, e_up, e_down)
        if shared is not None:
            y = y + _shared_swiglu(x, *shared, x.dtype)
        return y
    dt = x.dtype
    tok_axes = tuple(a for a in token_axes if dict(mesh.shape).get(a, 1) > 1)
    tok_spec = P(tok_axes if tok_axes else None)
    body = functools.partial(_ep_local, num_experts_local=E // ep,
                             compute_dtype=dt)
    if shared is None:
        fn = _shard_map(
            lambda xl, wl, il, g, u, d: body(xl, wl, il, g, u, d),
            mesh=mesh,
            in_specs=(tok_spec, tok_spec, tok_spec, P("ep"), P("ep"),
                      P("ep")),
            out_specs=tok_spec,
            axis_names=set(tok_axes) | {"ep"},
            check_vma=False)
        return fn(x.astype(jnp.float32), weights, idx,
                  e_gate, e_up, e_down).astype(dt)
    fn = _shard_map(
        lambda xl, wl, il, g, u, d, sg, su, sd: body(
            xl, wl, il, g, u, d, (sg, su, sd)),
        mesh=mesh,
        in_specs=(tok_spec, tok_spec, tok_spec, P("ep"), P("ep"), P("ep"),
                  P(None), P(None), P(None)),
        out_specs=tok_spec,
        axis_names=set(tok_axes) | {"ep"},
        check_vma=False)
    return fn(x.astype(jnp.float32), weights, idx, e_gate, e_up, e_down,
              *shared).astype(dt)


def _a2a_exchange(x_h, w_h, idx_h, *, E, El, R):
    """Stage 1 of the ragged exchange for one token slice: expert-sort,
    size all_gather, and the payload + expert-id ragged all-to-alls
    (both in flight when this returns — consume late)."""
    me = jax.lax.axis_index("ep")
    Tl, k = idx_h.shape
    A = Tl * k
    Amax = A * R
    h = x_h.shape[1]
    dt = x_h.dtype

    flat_e = idx_h.reshape(A)
    order = jnp.argsort(flat_e)                    # expert order == rank order
    tok = order // k
    xs = jnp.take(x_h, tok, axis=0)                # [A,h] send buffer
    eid_send = flat_e[order]

    dest = flat_e // El
    send_sizes = jnp.zeros((R,), jnp.int32).at[dest].add(1)
    sizes = jax.lax.all_gather(send_sizes, "ep")   # [sender, dest]
    in_off = jnp.cumsum(send_sizes) - send_sizes
    recv_sizes = sizes[:, me]
    out_off = (jnp.cumsum(sizes, axis=0) - sizes)[me]

    xr = jax.lax.ragged_all_to_all(
        xs, jnp.zeros((Amax, h), dt),
        in_off, send_sizes, out_off, recv_sizes, axis_name="ep")
    er = jax.lax.ragged_all_to_all(
        eid_send, jnp.full((Amax,), E, jnp.int32),
        in_off, send_sizes, out_off, recv_sizes, axis_name="ep")
    state = (order, tok, w_h, sizes, send_sizes, recv_sizes)
    return xr, er, state


def _a2a_ffn(xr, er, eg_l, eu_l, ed_l, *, E, El):
    """Stage 2: group the received rows by local expert and run the
    grouped-GEMM SwiGLU (padding rows sort to a zero-weight tail)."""
    dt = xr.dtype
    me = jax.lax.axis_index("ep")
    lid = jnp.where(er < E, er - me * El, El)      # padding → tail group
    order2 = jnp.argsort(lid)
    xg = jnp.take(xr, order2, axis=0)
    valid = lid < El
    gs = jnp.zeros((El,), jnp.int32).at[jnp.where(valid, lid, 0)].add(
        valid.astype(jnp.int32))
    yg = _expert_ffn(xg, gs, eg_l, eu_l, ed_l, dt)
    return jnp.zeros_like(yg).at[order2].set(yg)   # back to receive order


def _a2a_combine(yr, state, *, h):
    """Stage 3: reverse ragged all-to-all + gate-weighted combine for one
    token slice. Returns [T_slice, h] f32."""
    me = jax.lax.axis_index("ep")
    order, tok, w_h, sizes, send_sizes, recv_sizes = state
    Tl, k = w_h.shape
    A = Tl * k
    dt = yr.dtype
    rev_in_off = jnp.cumsum(recv_sizes) - recv_sizes
    rev_out_off = (jnp.cumsum(sizes, axis=1) - sizes)[:, me]
    ys = jax.lax.ragged_all_to_all(
        yr, jnp.zeros((A, h), dt),
        rev_in_off, recv_sizes, rev_out_off, send_sizes, axis_name="ep")
    ws = w_h.reshape(A)[order].astype(jnp.float32)
    return jnp.zeros((Tl, h), jnp.float32).at[tok].add(
        ys.astype(jnp.float32) * ws[:, None])


def _a2a_local(x_l, w_l, idx_l, eg_l, eu_l, ed_l, shared_w=None, *,
               num_experts, num_experts_local, ep_size):
    """Per-ep-rank body of the ragged-all-to-all exchange (reference's
    global_scatter → grouped GEMM → global_gather, TPU collectives).

    With ``shared_w`` the slice is processed as double-buffered halves:
    both halves' forward exchanges are issued back to back, the shared-
    expert FFN computes in their shadow, and half 0's reverse exchange
    hides behind half 1's grouped GEMMs."""
    E, El, R = num_experts, num_experts_local, ep_size
    Tl = x_l.shape[0]
    h = x_l.shape[1]
    dt = x_l.dtype

    def one(x_h, w_h, idx_h):
        xr, er, st = _a2a_exchange(x_h, w_h, idx_h, E=E, El=El, R=R)
        yr = _a2a_ffn(xr, er, eg_l, eu_l, ed_l, E=E, El=El)
        return _a2a_combine(yr, st, h=h)

    if _overlap_bypassed(shared_w, Tl):
        y = one(x_l, w_l, idx_l)
        if shared_w is not None:
            y = y + _shared_swiglu(x_l, *shared_w, dt).astype(jnp.float32)
        return y.astype(dt)
    H = Tl // 2
    xr0, er0, st0 = _a2a_exchange(x_l[:H], w_l[:H], idx_l[:H],
                                  E=E, El=El, R=R)
    xr1, er1, st1 = _a2a_exchange(x_l[H:], w_l[H:], idx_l[H:],
                                  E=E, El=El, R=R)
    s = _shared_swiglu(x_l, *shared_w, dt)         # hides both exchanges
    yr0 = _a2a_ffn(xr0, er0, eg_l, eu_l, ed_l, E=E, El=El)
    y0 = _a2a_combine(yr0, st0, h=h)               # reverse a2a of half 0…
    yr1 = _a2a_ffn(xr1, er1, eg_l, eu_l, ed_l, E=E, El=El)  # …hides here
    y1 = _a2a_combine(yr1, st1, h=h)
    y = jnp.concatenate([y0, y1], axis=0) + s.astype(jnp.float32)
    return y.astype(dt)


def dropless_moe_ffn_a2a(x, weights, idx, e_gate, e_up, e_down, mesh: Mesh,
                         token_axes: Tuple[str, ...] = ("dp", "ep"),
                         shared: Optional[Tuple] = None):
    """Ragged-all-to-all dropless FFN: tokens sharded over ``token_axes``
    (which always includes 'ep'), exchanged to expert owners within each ep
    group and back (the literal global_scatter/global_gather shape — only
    ~T*k/ep assignments are GEMM'd per rank, vs the psum strategy's T*k).
    Requires a backend with a ragged-all-to-all lowering — real TPU;
    XLA:CPU raises UNIMPLEMENTED, so CPU tests use the _ep/psum strategy
    (a lowering-only test pins the wiring).

    ``shared=(s_gate, s_up, s_down)`` fuses the shared-expert FFN into the
    body so the exchanges hide behind it (see :func:`_a2a_local`)."""
    E = e_gate.shape[0]
    ep = dict(mesh.shape).get("ep", 1)
    T = x.shape[0]
    tok_axes = tuple(dict.fromkeys(
        a for a in (*token_axes, "ep") if dict(mesh.shape).get(a, 1) > 1))
    n_tok_shards = int(np.prod([dict(mesh.shape)[a] for a in tok_axes])) \
        if tok_axes else 1
    if ep <= 1 or E % ep != 0 or T % max(n_tok_shards, 1) != 0:
        _M_FALLBACKS.labels(reason="ep_shape_mismatch").inc()
        y = dropless_moe_ffn(x, weights, idx, e_gate, e_up, e_down)
        if shared is not None:
            y = y + _shared_swiglu(x, *shared, x.dtype)
        return y
    tok_spec = P(tok_axes)
    body = functools.partial(_a2a_local, num_experts=E,
                             num_experts_local=E // ep, ep_size=ep)
    if shared is None:
        fn = _shard_map(
            lambda xl, wl, il, g, u, d: body(xl, wl, il, g, u, d),
            mesh=mesh,
            in_specs=(tok_spec, tok_spec, tok_spec, P("ep"), P("ep"),
                      P("ep")),
            out_specs=tok_spec,
            axis_names=set(tok_axes) | {"ep"},
            check_vma=False)
        return fn(x, weights, idx, e_gate, e_up, e_down)
    fn = _shard_map(
        lambda xl, wl, il, g, u, d, sg, su, sd: body(
            xl, wl, il, g, u, d, (sg, su, sd)),
        mesh=mesh,
        in_specs=(tok_spec, tok_spec, tok_spec, P("ep"), P("ep"), P("ep"),
                  P(None), P(None), P(None)),
        out_specs=tok_spec,
        axis_names=set(tok_axes) | {"ep"},
        check_vma=False)
    return fn(x, weights, idx, e_gate, e_up, e_down, *shared)
