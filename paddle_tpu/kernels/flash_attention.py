"""Flash attention kernel entry.

Replaces the reference's FlashAttention-2 third_party dependency
(reference: paddle/phi/kernels/gpu/flash_attn_kernel.cu +
python/paddle/nn/functional/flash_attention.py:358).

The Pallas TPU kernel lives in pallas_attention.py; this module picks the best
implementation for the current backend (Pallas on TPU, fused-XLA reference
math elsewhere) behind one API: inputs [batch, seq, heads, head_dim].
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..ops.dispatch import apply


def _reference_attention(q, k, v, causal):
    if k.shape[2] != q.shape[2]:  # GQA: expand K/V for the dense fallback
        g = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, g, axis=2)
        v = jnp.repeat(v, g, axis=2)
    scale = 1.0 / math.sqrt(q.shape[-1])
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    scores = jnp.einsum("bhsd,bhtd->bhst", qt, kt) * scale
    if causal:
        s, t = scores.shape[-2], scores.shape[-1]
        mask = jnp.tril(jnp.ones((s, t), bool))
        scores = jnp.where(mask, scores, jnp.finfo(scores.dtype).min)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    out = jnp.einsum("bhst,bhtd->bhsd", probs, vt)
    return jnp.swapaxes(out, 1, 2)


def _use_pallas(q_val) -> bool:
    try:
        dev = list(q_val.devices())[0] if hasattr(q_val, "devices") else None
        plat = dev.platform.lower() if dev else jax.default_backend()
    except Exception:
        plat = jax.default_backend()
    if plat not in ("tpu", "axon"):
        return False
    # pallas kernel wants MXU-friendly shapes
    return q_val.shape[1] >= 128 and q_val.shape[-1] % 128 == 0


def flash_attention(query, key, value, causal: bool = False):
    def fn(q, k, v):
        if _use_pallas(q):
            try:
                from .pallas_attention import flash_attention_fwd

                return flash_attention_fwd(q, k, v, causal=causal)
            except Exception:
                pass
        return _reference_attention(q, k, v, causal)

    return apply("flash_attention", fn,
                 query if isinstance(query, Tensor) else Tensor(query),
                 key if isinstance(key, Tensor) else Tensor(key),
                 value if isinstance(value, Tensor) else Tensor(value))
