"""TPU kernel library: attention (flash/ring/ulysses/paged), MoE dispatch,
grouped-matmul autotuning, and int8 weight-only / KV quantized matmuls."""
from .quant_matmul import (attn_pv, attn_qk, dequantize_kv,  # noqa: F401
                           mixed_dot_supported, quantize_kv,
                           weight_only_matmul)

__all__ = [
    "weight_only_matmul", "quantize_kv", "dequantize_kv",
    "attn_qk", "attn_pv", "mixed_dot_supported",
]
