"""TPU kernel library: attention (flash/ring/ulysses/paged), the
persistent fused decode megakernel, MoE dispatch + fused FFN,
grouped-matmul autotuning, and int8 weight-only / KV quantized matmuls.

This is the package's public surface — serving, bench and the chip
lanes import kernel entry points from here; module paths stay available
for the internals (partial-state kernels, autotune caches) that tests
reach into directly.
"""
from .flash_attention import flash_attention  # noqa: F401
from .gmm_autotune import (candidate_tilings, get_tilings,  # noqa: F401
                           heuristic_tilings)
from .mega_decode import (mega_decode_loop, mega_decode_step,  # noqa: F401
                          mega_supported)
from .moe_fused import fused_moe_ffn, gather_gmm  # noqa: F401
from .paged_attention import (PagedKVCache, paged_append,  # noqa: F401
                              paged_append_blocks, paged_append_token,
                              paged_attention, paged_cache_init,
                              paged_decode_attention,
                              ragged_decode_partial, ragged_paged_decode)
from .quant_matmul import (attn_pv, attn_qk, dequantize_kv,  # noqa: F401
                           mixed_dot_supported, quantize_kv,
                           weight_only_matmul)

__all__ = [
    # fused decode megakernel (r18)
    "mega_decode_step", "mega_decode_loop", "mega_supported",
    # paged / ragged decode attention (r4/r12)
    "PagedKVCache", "paged_cache_init", "paged_append",
    "paged_attention", "paged_append_token", "paged_append_blocks",
    "paged_decode_attention", "ragged_decode_partial",
    "ragged_paged_decode",
    # flash attention
    "flash_attention",
    # MoE fused FFN + grouped matmul autotuning
    "fused_moe_ffn", "gather_gmm",
    "heuristic_tilings", "get_tilings", "candidate_tilings",
    # int8 weight-only / KV quantized matmuls
    "weight_only_matmul", "quantize_kv", "dequantize_kv",
    "attn_qk", "attn_pv", "mixed_dot_supported",
]
