"""Paged KV-cache attention (block tables) — the serving decode path.

Parity: the reference's blocked decode kernel
(phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu, python surface
incubate/nn/functional/block_multihead_attention) whose cache is paged:
physical blocks of block_size tokens + per-sequence block tables. The
ragged kernel below is the "Ragged Paged Attention" direction (PAPERS.md
lead paper, arXiv 2604.15464) done natively.

TPU-native: the cache is one [num_blocks, block_size, H, D] pool per k/v;
a block_table [B, max_blocks] maps logical sequence positions to pool
blocks. Two decode strategies live here, with different compile/variant
stories:

- XLA gather path (:func:`paged_attention` / the engine's hoisted-dense
  program): each sequence's blocks are gathered into a dense buffer of a
  STATIC width and positions past the true length are softmax-masked.
  Exact, but the static width must come from somewhere — the serving
  engine picks a power-of-two prefix bucket host-side, so attention cost
  scales with ``max(lengths)`` rounded up to the bucket ceiling and the
  compile cache carries one variant per (bucket, sampling-flags) pair
  (bounded at ``log2(max_blocks)+1 × 8``, but a recompile family all the
  same). This is the off-TPU / interpret fallback.
- Ragged Pallas path (:func:`ragged_paged_decode` /
  :func:`ragged_decode_partial`): one program per slot walks the slot's
  block table at its TRUE length — blocks past ``ceil(len/bs)`` are
  never visited (the walk's trip count ends there: no DMA, no FLOPs),
  the tail inside the last block is masked, and the softmax runs online
  across the walk, so nothing is
  ever gathered to a static horizon. Lengths are a runtime operand, not
  a shape: ONE compiled variant serves any batch composition, and the
  per-step KV read scales with the actual tokens resident, not any
  bucket ceiling. int8 pools stream unconverted and dequantize
  in-register via their per-entry scales (the quant_matmul scale-folding
  math).
"""
from __future__ import annotations

import functools
import math
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["PagedKVCache", "paged_cache_init", "paged_append",
           "paged_attention", "paged_append_token", "paged_append_blocks",
           "paged_decode_attention", "ragged_decode_partial",
           "ragged_paged_decode"]


def _interpret() -> bool:
    # off-TPU (CPU tests) the kernels run in the Pallas interpreter
    return jax.default_backend() != "tpu"


class PagedKVCache(NamedTuple):
    """Pool layout is TOKEN-MAJOR — [num_blocks, block_size, H, D]. Mosaic
    tiles only the trailing two dims of a memref, so keeping (H, D) there
    (both tile-aligned constants) leaves the token dim freely sliceable —
    which is what lets the Pallas append kernel DMA a single token row to
    an arbitrary (block, offset) without violating tiling. (A head-major
    layout would put block_size in the tiled pair and forbid exactly that
    slice.)"""
    k_pool: jax.Array          # [num_blocks, block_size, H, D]
    v_pool: jax.Array          # [num_blocks, block_size, H, D]
    block_table: jax.Array     # [B, max_blocks] int32 (pool indices)
    lengths: jax.Array         # [B] int32 current token counts


def paged_cache_init(batch: int, num_blocks: int, block_size: int,
                     num_heads: int, head_dim: int, max_blocks: int,
                     dtype=jnp.bfloat16) -> PagedKVCache:
    """Pre-partitioned allocation: sequence b owns blocks
    b*max_blocks..(b+1)*max_blocks-1 by default (callers doing real paging
    can overwrite block_table with any pool mapping)."""
    assert num_blocks >= batch * max_blocks
    table = (jnp.arange(batch * max_blocks, dtype=jnp.int32)
             .reshape(batch, max_blocks))
    return PagedKVCache(
        jnp.zeros((num_blocks, block_size, num_heads, head_dim), dtype),
        jnp.zeros((num_blocks, block_size, num_heads, head_dim), dtype),
        table, jnp.zeros((batch,), jnp.int32))


def paged_append(cache: PagedKVCache, k_new, v_new) -> PagedKVCache:
    """Append ONE token per sequence (XLA reference path — the Pallas
    fast path is :func:`paged_append_token`). k_new/v_new: [B, H, D]."""
    bs = cache.k_pool.shape[1]
    pos = cache.lengths                               # [B]
    blk_logical = pos // bs
    offset = pos % bs
    blk_physical = jnp.take_along_axis(
        cache.block_table, blk_logical[:, None], axis=1)[:, 0]
    k_pool = cache.k_pool.at[blk_physical, offset].set(
        k_new.astype(cache.k_pool.dtype))
    v_pool = cache.v_pool.at[blk_physical, offset].set(
        v_new.astype(cache.v_pool.dtype))
    return PagedKVCache(k_pool, v_pool, cache.block_table, pos + 1)


# ---------------------------------------------------------------------------
# Pallas TPU kernels — the serving hot path.
#
# XLA lowers the pool updates/reads below to generic scatter/gather because
# every slot indexes a DIFFERENT physical block (vector indices): measured
# ~0.5 ms PER LAYER each on a v5e — 2x the cost of the whole dense decode
# step at 510M. These kernels replace them with block-table-driven DMAs:
# appends are one grid step per row/block, and the decode attention streams
# exactly the blocks each slot's true length covers (the reference's paged
# serving kernel, block_multi_head_attention_kernel.cu, done the TPU way —
# also the "Ragged Paged Attention" direction in PAPERS.md).
# ---------------------------------------------------------------------------


def _as5d(pool):
    """View a [NB, BS, H, D] pool as [1, NB, BS, H, D] (bitcast — XLA
    aliases the reshape, so in-place semantics survive the wrapper)."""
    return pool if pool.ndim == 5 else pool[None]


def _append_token_kernel(layer_ref, blk_ref, off_ref, k_new_ref, v_new_ref,
                         k_in_ref, v_in_ref, k_out_ref, v_out_ref, sem):
    """Grid (N,): store slot n's new K/V rows at (layer, blk[n], off[n]).
    Integer indexing squeezes the layer/block/token dims on the
    destination and the slot dim on the source, so the DMA moves one
    tile-aligned [Hkv, D] row — only untiled dims are ever sliced."""
    n = pl.program_id(0)
    lyr = layer_ref[0]
    blk, off = blk_ref[n], off_ref[n]
    cp_k = pltpu.make_async_copy(
        k_new_ref.at[n], k_out_ref.at[lyr, blk, off], sem)
    cp_k.start()
    cp_k.wait()
    cp_v = pltpu.make_async_copy(
        v_new_ref.at[n], v_out_ref.at[lyr, blk, off], sem)
    cp_v.start()
    cp_v.wait()


def paged_append_token(k_pool, v_pool, k_new, v_new, blk_phys, offset,
                       layer=0):
    """Append ONE token per slot in place: k_pool[layer, blk_phys[n],
    offset[n]] = k_new[n]. k_pool/v_pool: [L, NB, BS, Hkv, D] or
    [NB, BS, Hkv, D] (aliased — the returned pools reuse the input
    buffers; a 4D pool comes back 4D); k_new/v_new: [N, Hkv, D];
    blk_phys/offset: [N] int32; ``layer`` selects the pool's layer plane
    (traced — the serving engine passes its static layer loop index).
    Slots meant to be inactive should point at the trash block."""
    was4d = k_pool.ndim == 4
    kp, vp = _as5d(k_pool), _as5d(v_pool)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(k_new.shape[0],),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),   # pools stay in HBM
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[pl.BlockSpec(memory_space=pl.ANY),
                   pl.BlockSpec(memory_space=pl.ANY)],
        scratch_shapes=[pltpu.SemaphoreType.DMA(())],
    )
    ko, vo = pl.pallas_call(
        _append_token_kernel,
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct(kp.shape, kp.dtype),
                   jax.ShapeDtypeStruct(vp.shape, vp.dtype)],
        input_output_aliases={5: 0, 6: 1},
        interpret=_interpret(),
    )(jnp.asarray(layer, jnp.int32)[None], blk_phys, offset,
      k_new.astype(kp.dtype), v_new.astype(vp.dtype), kp, vp)
    return (ko[0], vo[0]) if was4d else (ko, vo)


def _append_blocks_kernel(layer_ref, blk_ids_ref, k_blk_ref, v_blk_ref,
                          k_in_ref, v_in_ref, k_out_ref, v_out_ref, sem):
    """Grid (nblk,): store prefill block b at pool block blk_ids[b]
    (HBM-to-HBM DMA of one whole [BS, Hkv, D] block each)."""
    b = pl.program_id(0)
    lyr = layer_ref[0]
    dst = blk_ids_ref[b]
    cp_k = pltpu.make_async_copy(
        k_blk_ref.at[b], k_out_ref.at[lyr, dst], sem)
    cp_k.start()
    cp_k.wait()
    cp_v = pltpu.make_async_copy(
        v_blk_ref.at[b], v_out_ref.at[lyr, dst], sem)
    cp_v.start()
    cp_v.wait()


def paged_append_blocks(k_pool, v_pool, k_blocks, v_blocks, blk_ids,
                        layer=0):
    """Scatter whole prefill blocks into the pool in place (the prefill-side
    analogue of paged_append_token). k_blocks/v_blocks: [nblk, BS, Hkv, D];
    blk_ids: [nblk] int32 destinations (duplicates allowed only for the
    trash block — pad blocks may all point at 0); pools/layer as in
    :func:`paged_append_token`."""
    was4d = k_pool.ndim == 4
    kp, vp = _as5d(k_pool), _as5d(v_pool)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(blk_ids.shape[0],),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[pl.BlockSpec(memory_space=pl.ANY),
                   pl.BlockSpec(memory_space=pl.ANY)],
        scratch_shapes=[pltpu.SemaphoreType.DMA(())],
    )
    ko, vo = pl.pallas_call(
        _append_blocks_kernel,
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct(kp.shape, kp.dtype),
                   jax.ShapeDtypeStruct(vp.shape, vp.dtype)],
        input_output_aliases={4: 0, 5: 1},
        interpret=_interpret(),
    )(jnp.asarray(layer, jnp.int32)[None], blk_ids,
      k_blocks.astype(kp.dtype), v_blocks.astype(vp.dtype), kp, vp)
    return (ko[0], vo[0]) if was4d else (ko, vo)


def _decode_attn_kernel(layer_ref, table_ref, lens_ref, q_ref, k_pool_ref,
                        v_pool_ref, o_ref, kbuf, vbuf, sems, *, block_size,
                        n_kv, max_blocks):
    """Grid (N,): ONE program per slot. All the slot's valid pool blocks
    are DMA'd into VMEM in parallel (start everything, then wait), then
    attention runs single-shot per kv head over the contiguous buffer.
    Few large programs + bulk DMA keep the kernel bandwidth-bound instead
    of program-overhead-bound (a (slot, head, block) grid measured 2 us of
    overhead per tiny program — 20x the DMA time it hid)."""
    n = pl.program_id(0)
    lyr = layer_ref[0]
    ln = lens_ref[n]
    copies = []
    for b in range(max_blocks):
        valid = b * block_size < ln
        blk = table_ref[n, b]

        @pl.when(valid)
        def _(b=b, blk=blk):
            cp_k = pltpu.make_async_copy(
                k_pool_ref.at[lyr, blk],
                kbuf.at[pl.ds(b * block_size, block_size)],
                sems.at[0, b])
            cp_k.start()
            cp_v = pltpu.make_async_copy(
                v_pool_ref.at[lyr, blk],
                vbuf.at[pl.ds(b * block_size, block_size)],
                sems.at[1, b])
            cp_v.start()

        copies.append((valid, blk, b))
    for valid, blk, b in copies:
        @pl.when(valid)
        def _(b=b, blk=blk):
            pltpu.make_async_copy(
                k_pool_ref.at[lyr, blk],
                kbuf.at[pl.ds(b * block_size, block_size)],
                sems.at[0, b]).wait()
            pltpu.make_async_copy(
                v_pool_ref.at[lyr, blk],
                vbuf.at[pl.ds(b * block_size, block_size)],
                sems.at[1, b]).wait()

        # never-copied V blocks hold scratch garbage; the ~0 softmax
        # weights of masked columns still NaN-poison the p@V contraction
        # unless the values are finite, so zero them (VPU-only, no HBM
        # traffic). K needs no fill: masked score columns are rewritten
        # by the -1e30 where() regardless of what the dot produced.
        @pl.when(jnp.logical_not(valid))
        def _(b=b):
            vbuf[b * block_size:(b + 1) * block_size] = jnp.zeros(
                (block_size,) + vbuf.shape[1:], vbuf.dtype)

    S = max_blocks * block_size
    col = jax.lax.broadcasted_iota(jnp.int32, (1, S), 1)
    for h in range(n_kv):                      # static unroll over kv heads
        q = q_ref[0, h]                        # [G, D]
        k = kbuf[:, h]                         # [S, D] (relayout from VMEM)
        v = vbuf[:, h]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)     # [G, S]
        s = s / math.sqrt(q.shape[-1])
        s = jnp.where(col < ln, s, jnp.float32(-1e30))
        m = jnp.max(s, axis=-1, keepdims=True)
        p = jnp.exp(s - m)
        l = jnp.sum(p, axis=-1, keepdims=True)
        o = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)     # [G, D]
        o_ref[0, h] = (o / l).astype(o_ref.dtype)


def paged_decode_attention(q, cache: PagedKVCache, layer=0) -> jax.Array:
    """Pallas decode attention: q [N, Hq, D] -> [N, Hq, D], attending each
    slot's first ``cache.lengths[n]`` pool positions of pool plane
    ``layer`` (pools may be [L, NB, BS, Hkv, D] or 4D). Same contract as
    :func:`paged_attention` (which stays as the XLA reference path and the
    numerics oracle in tests); unlike it, nothing is gathered into a dense
    [N, mb*bs, ...] HBM copy — each slot's blocks stream straight into a
    VMEM buffer, and blocks past the true length are never read."""
    N, Hq, D = q.shape
    kp, vp = _as5d(cache.k_pool), _as5d(cache.v_pool)
    bs, Hkv = kp.shape[2], kp.shape[3]
    mb = cache.block_table.shape[1]
    assert Hq % Hkv == 0, (Hq, Hkv)
    G = Hq // Hkv
    # the two VMEM staging buffers hold the slot's whole context; past
    # ~12 MiB they can't coexist with the rest of the working set in the
    # ~16 MiB VMEM, so long-context pools take the XLA gather path instead
    # of failing with an opaque Mosaic allocation error at serving time
    scratch_bytes = 2 * mb * bs * Hkv * D * kp.dtype.itemsize
    if scratch_bytes > 12 * 1024 * 1024:
        return paged_attention(q, PagedKVCache(
            kp[layer], vp[layer], cache.block_table, cache.lengths))
    qg = q.reshape(N, Hkv, G, D)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(N,),
        in_specs=[
            pl.BlockSpec((1, Hkv, G, D), lambda n, l, t, ln: (n, 0, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),   # pools stay in HBM
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((1, Hkv, G, D),
                               lambda n, l, t, ln: (n, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((mb * bs, Hkv, D), kp.dtype),
            pltpu.VMEM((mb * bs, Hkv, D), vp.dtype),
            pltpu.SemaphoreType.DMA((2, mb)),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_decode_attn_kernel, block_size=bs, n_kv=Hkv,
                          max_blocks=mb),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((N, Hkv, G, D), q.dtype),
        interpret=_interpret(),
    )(jnp.asarray(layer, jnp.int32)[None], cache.block_table,
      cache.lengths, qg, kp, vp)
    return out.reshape(N, Hq, D)


# ---------------------------------------------------------------------------
# Ragged paged attention — the true-length block walk (arXiv 2604.15464).
#
# Grid: one program per slot; the program's kv-head groups are walked
# in-register inside the block loop rather than as a grid axis, because
# the pool layout keeps (Hkv, D) as the Mosaic-tiled pair — a per-head
# DMA would slice the tiled Hkv dim (illegal), and a per-(slot, head)
# grid re-DMAing whole [bs, Hkv, D] blocks would multiply the KV read
# bytes by Hkv on a bandwidth-bound path. Each real block is DMA'd
# exactly once (double-buffered: block b+1 streams while b computes) and
# every kv head consumes it while it is VMEM-resident.
# ---------------------------------------------------------------------------


def _ragged_decode_kernel(layer_ref, table_ref, lens_ref, q_ref,
                          k_pool_ref, v_pool_ref, *rest, block_size,
                          n_kv, max_blocks, kv_int8):
    """Grid (N,): walk slot n's block table up to ``ceil(lens[n]/bs)``
    REAL blocks with an online softmax. Blocks past the length are never
    visited — the fori_loop trip count ends the walk there (program size
    stays O(1) in the table width) and the ``pl.when`` prefetch guard
    stops the DMA stream at the last real block — so a slot's cost
    scales with its true length whatever the table width. The tail
    inside the last block is masked to -1e30 before the running max, so
    its exp is exactly 0.0 (bucketed-path exactness argument, applied
    per block). int8 pools: the [bs, Hkv, D] payload
    blocks and [bs, Hkv] per-entry scale blocks stream as stored; the
    payload widens in-register (int8 -> q dtype is exact) and the K
    scale multiplies the f32 scores / the V scale folds into the
    probabilities — attn_qk / attn_pv's scale-folding math, inlined.

    Emits the online-softmax PARTIAL state per (slot, kv head, q-in-
    group): unnormalized ``acc`` (f32 [N, Hkv, G, D]), running max ``m``
    and sum ``l`` (f32 [N, Hkv, G]) — the flash-decoding combine
    contract, so a caller can merge in-flight tokens (the engine's
    in-call ring) before normalizing. A slot with length 0 emits
    (acc=0, m=-1e30, l=0), the identity of the combine."""
    if kv_int8:
        (ks_pool_ref, vs_pool_ref, acc_ref, m_ref, l_ref,
         kbuf, vbuf, ksbuf, vsbuf, accs, ms, ls, sems) = rest
    else:
        (acc_ref, m_ref, l_ref, kbuf, vbuf, accs, ms, ls, sems) = rest
    n = pl.program_id(0)
    lyr = layer_ref[0]
    ln = lens_ref[n]
    sm_scale = 1.0 / math.sqrt(q_ref.shape[-1])
    ms[:] = jnp.full(ms.shape, -1e30, jnp.float32)
    ls[:] = jnp.zeros(ls.shape, jnp.float32)
    accs[:] = jnp.zeros(accs.shape, jnp.float32)

    def copies(b, slot):
        blk = table_ref[n, b]
        cps = [pltpu.make_async_copy(k_pool_ref.at[lyr, blk],
                                     kbuf.at[slot], sems.at[0, slot]),
               pltpu.make_async_copy(v_pool_ref.at[lyr, blk],
                                     vbuf.at[slot], sems.at[1, slot])]
        if kv_int8:
            cps += [pltpu.make_async_copy(ks_pool_ref.at[lyr, blk],
                                          ksbuf.at[slot], sems.at[2, slot]),
                    pltpu.make_async_copy(vs_pool_ref.at[lyr, blk],
                                          vsbuf.at[slot], sems.at[3, slot])]
        return cps

    # the walk's trip count IS the skip mechanism: blocks past the
    # length are never visited, so program size stays O(1) in the table
    # width (a python unroll over max_blocks would emit mb x Hkv copies
    # of the DMA+MXU body — a compile cliff at long max_model_len)
    nblk = jnp.minimum((ln + block_size - 1) // block_size, max_blocks)

    @pl.when(nblk > 0)
    def _():
        for cp in copies(0, 0):
            cp.start()

    def walk(b, _):
        sl = jax.lax.rem(b, 2)
        # prefetch block b+1 into the other slot while b computes (the
        # standard two-slot pipeline; pl.when ends the stream exactly at
        # the slot's last real block)
        @pl.when(b + 1 < nblk)
        def _():
            for cp in copies(b + 1, 1 - sl):
                cp.start()

        for cp in copies(b, sl):
            cp.wait()
        col = (jax.lax.broadcasted_iota(jnp.int32, (1, block_size), 1)
               + b * block_size)
        live = col < ln                                      # [1, bs]
        for h in range(n_kv):                    # static kv-head groups
            qh = q_ref[0, h]                                 # [G, D]
            kh = kbuf[sl][:, h]                              # [bs, D]
            if kv_int8:
                kh = kh.astype(qh.dtype)         # int8 widen: exact
            s = jax.lax.dot_general(
                qh, kh, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * sm_scale
            if kv_int8:
                s = s * ksbuf[sl][:, h][None, :]
            s = jnp.where(live, s, jnp.float32(-1e30))       # [G, bs]
            m_prev = ms[h]                                   # [G]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
            alpha = jnp.exp(m_prev - m_new)
            p = jnp.exp(s - m_new[:, None])
            ls[h] = ls[h] * alpha + jnp.sum(p, axis=-1)
            vh = vbuf[sl][:, h]
            if kv_int8:
                # V scale rides the probabilities (it varies along the
                # contracted axis) and int8 V widens in-register
                p = p * vsbuf[sl][:, h][None, :]
                vh = vh.astype(jnp.float32)
            else:
                p = p.astype(vh.dtype)
            pv = jax.lax.dot_general(
                p, vh, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)          # [G, D]
            accs[h] = accs[h] * alpha[:, None] + pv
            ms[h] = m_new
        return 0

    jax.lax.fori_loop(0, nblk, walk, 0)

    acc_ref[0] = accs[:]
    m_ref[0] = ms[:]
    l_ref[0] = ls[:]


def ragged_decode_partial(q, k_pool, v_pool, block_table, lengths, *,
                          layer=0, ks_pool=None, vs_pool=None, mesh=None):
    """Ragged block-walk decode attention over each slot's TRUE length —
    partial (flash-decoding) form. q: [N, Hq, D]; pools:
    [L, NB, BS, Hkv, D] or 4D (bf16/f32, or int8 with per-entry f32
    scale pools ks/vs [L, NB, BS, Hkv] or 3D); block_table: [N, MB]
    int32; lengths: [N] runtime operand — NOT a shape. Returns the
    online-softmax partials ``(acc [N, Hkv, G, D] f32, m [N, Hkv, G]
    f32, l [N, Hkv, G] f32)`` so callers can merge extra keys (the
    serving engine's in-call ring) before normalizing; use
    :func:`ragged_paged_decode` for the normalized one-shot form.

    One compiled variant serves ANY length mix: the table width MB is
    the only shape, and slots read exactly ``ceil(lengths[n]/BS)``
    blocks of it. VMEM use is two double-buffered blocks + the [Hkv, G,
    D] accumulators, independent of context length — no long-context
    staging-buffer cliff like :func:`paged_decode_attention`'s.

    With ``mesh`` (a Mesh carrying a 'tp' axis of size > 1) the call is
    wrapped in a shard_map over 'tp': KV heads shard naturally — every
    shard walks the SAME block tables and lengths (replicated scalars)
    against its Hkv/tp head slice of q and the pools (the engine's
    ``P(None,None,None,"tp",None)`` pool shardings). Per-kv-head online
    softmax is independent, so the sharded partials are bit-identical
    to the unsharded ones. Hkv must divide by the tp size."""
    if mesh is not None:
        tp = dict(mesh.shape).get("tp", 1)
        if tp > 1:
            from jax.sharding import PartitionSpec as P
            from .moe_dispatch import _shard_map
            Hkv_g = _as5d(k_pool).shape[3]
            assert Hkv_g % tp == 0, (Hkv_g, tp)
            pool_s = P(None, None, None, "tp", None) \
                if k_pool.ndim == 5 else P(None, None, "tp", None)
            scale_s = None
            if ks_pool is not None:
                scale_s = P(None, None, None, "tp") \
                    if ks_pool.ndim == 4 else P(None, None, "tp")
            inner = functools.partial(ragged_decode_partial, layer=layer)
            if ks_pool is not None:
                inner = lambda q_, k_, v_, t_, l_, ks_, vs_: \
                    ragged_decode_partial(q_, k_, v_, t_, l_, layer=layer,
                                          ks_pool=ks_, vs_pool=vs_)
            fn = _shard_map(
                inner, mesh,
                in_specs=(P(None, "tp", None), pool_s, pool_s, P(), P())
                + ((scale_s, scale_s) if ks_pool is not None else ()),
                out_specs=(P(None, "tp", None, None), P(None, "tp", None),
                           P(None, "tp", None)),
                axis_names=("tp",))
            args = (q, k_pool, v_pool, block_table, lengths)
            if ks_pool is not None:
                args += (ks_pool, vs_pool)
            return fn(*args)
    N, Hq, D = q.shape
    kp, vp = _as5d(k_pool), _as5d(v_pool)
    bs, Hkv = kp.shape[2], kp.shape[3]
    mb = block_table.shape[1]
    assert Hq % Hkv == 0, (Hq, Hkv)
    G = Hq // Hkv
    kv_int8 = kp.dtype == jnp.int8
    if kv_int8 and (ks_pool is None or vs_pool is None):
        raise ValueError("int8 pools require ks_pool/vs_pool scales")
    qg = q.reshape(N, Hkv, G, D)

    in_specs = [
        pl.BlockSpec((1, Hkv, G, D), lambda n, l, t, ln: (n, 0, 0, 0)),
        pl.BlockSpec(memory_space=pl.ANY),     # pools stay in HBM
        pl.BlockSpec(memory_space=pl.ANY),
    ]
    inputs = [qg, kp, vp]
    scratch = [pltpu.VMEM((2, bs, Hkv, D), kp.dtype),
               pltpu.VMEM((2, bs, Hkv, D), vp.dtype)]
    if kv_int8:
        ksp = ks_pool if ks_pool.ndim == 4 else ks_pool[None]
        vsp = vs_pool if vs_pool.ndim == 4 else vs_pool[None]
        in_specs += [pl.BlockSpec(memory_space=pl.ANY),
                     pl.BlockSpec(memory_space=pl.ANY)]
        inputs += [ksp.astype(jnp.float32), vsp.astype(jnp.float32)]
        scratch += [pltpu.VMEM((2, bs, Hkv), jnp.float32),
                    pltpu.VMEM((2, bs, Hkv), jnp.float32)]
    scratch += [pltpu.VMEM((Hkv, G, D), jnp.float32),
                pltpu.VMEM((Hkv, G), jnp.float32),
                pltpu.VMEM((Hkv, G), jnp.float32),
                pltpu.SemaphoreType.DMA((4 if kv_int8 else 2, 2))]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(N,),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, Hkv, G, D), lambda n, l, t, ln: (n, 0, 0, 0)),
            pl.BlockSpec((1, Hkv, G), lambda n, l, t, ln: (n, 0, 0)),
            pl.BlockSpec((1, Hkv, G), lambda n, l, t, ln: (n, 0, 0)),
        ],
        scratch_shapes=scratch,
    )
    acc, m, l = pl.pallas_call(
        functools.partial(_ragged_decode_kernel, block_size=bs, n_kv=Hkv,
                          max_blocks=mb, kv_int8=kv_int8),
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct((N, Hkv, G, D), jnp.float32),
                   jax.ShapeDtypeStruct((N, Hkv, G), jnp.float32),
                   jax.ShapeDtypeStruct((N, Hkv, G), jnp.float32)],
        interpret=_interpret(),
    )(jnp.asarray(layer, jnp.int32)[None], block_table.astype(jnp.int32),
      lengths.astype(jnp.int32), *inputs)
    return acc, m, l


def ragged_paged_decode(q, cache: PagedKVCache, layer=0, ks_pool=None,
                        vs_pool=None, mesh=None) -> jax.Array:
    """Normalized ragged decode attention: q [N, Hq, D] -> [N, Hq, D],
    attending each slot's first ``cache.lengths[n]`` pool positions via
    the true-length block walk (:func:`ragged_decode_partial`). Same
    contract as :func:`paged_attention` — which remains the XLA gather
    reference and the numerics oracle in tests — but lengths are a
    runtime operand: one compiled program serves any length mix, reads
    no block past any slot's length, and holds only two blocks in VMEM
    however long the context. Zero-length slots return 0. ``mesh``
    shards the walk over the 'tp' axis (see
    :func:`ragged_decode_partial`)."""
    N, Hq, D = q.shape
    acc, m, l = ragged_decode_partial(
        q, cache.k_pool, cache.v_pool, cache.block_table, cache.lengths,
        layer=layer, ks_pool=ks_pool, vs_pool=vs_pool, mesh=mesh)
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    out = jnp.where((l > 0)[..., None], out, 0.0)
    return out.reshape(N, Hq, D).astype(q.dtype)


def paged_attention(q, cache: PagedKVCache) -> jax.Array:
    """Decode attention for one query token per sequence.
    q: [B, Hq, D] → [B, Hq, D]. Keys beyond each sequence's length are
    masked. GQA-native: Hq may be G * Hkv (pool heads); query heads are
    grouped against their kv head in the einsum, so the paged pool is never
    materialized repeated (decode is KV-bandwidth-bound — same design as
    models/llama._cached_attention)."""
    B, Hq, D = q.shape
    nb, bs, Hkv = cache.k_pool.shape[0], cache.k_pool.shape[1], \
        cache.k_pool.shape[2]
    mb = cache.block_table.shape[1]
    assert Hq % Hkv == 0, (Hq, Hkv)
    G = Hq // Hkv

    # gather each sequence's blocks: [B, mb, bs, Hkv, D] → [B, mb*bs, Hkv, D]
    k = cache.k_pool[cache.block_table].reshape(B, mb * bs, Hkv, D)
    v = cache.v_pool[cache.block_table].reshape(B, mb * bs, Hkv, D)

    qg = q.reshape(B, Hkv, G, D)
    s = jnp.einsum("bhgd,bkhd->bhgk", qg, k,
                   preferred_element_type=jnp.float32) / math.sqrt(D)
    valid = jnp.arange(mb * bs)[None, :] < cache.lengths[:, None]  # [B, K]
    s = jnp.where(valid[:, None, None, :], s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
    out = jnp.einsum("bhgk,bkhd->bhgd", p, v)
    return out.reshape(B, Hq, D)
