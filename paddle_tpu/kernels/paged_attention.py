"""Paged KV-cache attention (block tables) — the serving decode path.

Parity: the reference's blocked decode kernel
(phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu, python surface
incubate/nn/functional/block_multihead_attention) whose cache is paged:
physical blocks of block_size tokens + per-sequence block tables. Also the
direction of "Ragged Paged Attention" (PAPERS.md) — TPU-friendly paged decode.

TPU-native: the cache is one [num_blocks, block_size, H, D] pool per k/v;
a block_table [B, max_blocks] maps logical sequence positions to pool
blocks. A decode step gathers each sequence's blocks (static max_blocks →
static shapes), masks beyond the true length, and computes the attention in
f32 — everything jit-able with zero dynamic shapes, so one compiled step
serves any batch composition.
"""
from __future__ import annotations

import functools
import math
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["PagedKVCache", "paged_cache_init", "paged_append",
           "paged_attention", "paged_append_token", "paged_append_blocks",
           "paged_decode_attention"]


def _interpret() -> bool:
    # off-TPU (CPU tests) the kernels run in the Pallas interpreter
    return jax.default_backend() != "tpu"


class PagedKVCache(NamedTuple):
    """Pool layout is TOKEN-MAJOR — [num_blocks, block_size, H, D]. Mosaic
    tiles only the trailing two dims of a memref, so keeping (H, D) there
    (both tile-aligned constants) leaves the token dim freely sliceable —
    which is what lets the Pallas append kernel DMA a single token row to
    an arbitrary (block, offset) without violating tiling. (A head-major
    layout would put block_size in the tiled pair and forbid exactly that
    slice.)"""
    k_pool: jax.Array          # [num_blocks, block_size, H, D]
    v_pool: jax.Array          # [num_blocks, block_size, H, D]
    block_table: jax.Array     # [B, max_blocks] int32 (pool indices)
    lengths: jax.Array         # [B] int32 current token counts


def paged_cache_init(batch: int, num_blocks: int, block_size: int,
                     num_heads: int, head_dim: int, max_blocks: int,
                     dtype=jnp.bfloat16) -> PagedKVCache:
    """Pre-partitioned allocation: sequence b owns blocks
    b*max_blocks..(b+1)*max_blocks-1 by default (callers doing real paging
    can overwrite block_table with any pool mapping)."""
    assert num_blocks >= batch * max_blocks
    table = (jnp.arange(batch * max_blocks, dtype=jnp.int32)
             .reshape(batch, max_blocks))
    return PagedKVCache(
        jnp.zeros((num_blocks, block_size, num_heads, head_dim), dtype),
        jnp.zeros((num_blocks, block_size, num_heads, head_dim), dtype),
        table, jnp.zeros((batch,), jnp.int32))


def paged_append(cache: PagedKVCache, k_new, v_new) -> PagedKVCache:
    """Append ONE token per sequence (XLA reference path — the Pallas
    fast path is :func:`paged_append_token`). k_new/v_new: [B, H, D]."""
    bs = cache.k_pool.shape[1]
    pos = cache.lengths                               # [B]
    blk_logical = pos // bs
    offset = pos % bs
    blk_physical = jnp.take_along_axis(
        cache.block_table, blk_logical[:, None], axis=1)[:, 0]
    k_pool = cache.k_pool.at[blk_physical, offset].set(
        k_new.astype(cache.k_pool.dtype))
    v_pool = cache.v_pool.at[blk_physical, offset].set(
        v_new.astype(cache.v_pool.dtype))
    return PagedKVCache(k_pool, v_pool, cache.block_table, pos + 1)


# ---------------------------------------------------------------------------
# Pallas TPU kernels — the serving hot path.
#
# XLA lowers the pool updates/reads below to generic scatter/gather because
# every slot indexes a DIFFERENT physical block (vector indices): measured
# ~0.5 ms PER LAYER each on a v5e — 2x the cost of the whole dense decode
# step at 510M. These kernels replace them with block-table-driven DMAs:
# appends are one grid step per row/block, and the decode attention streams
# exactly the blocks each slot's true length covers (the reference's paged
# serving kernel, block_multi_head_attention_kernel.cu, done the TPU way —
# also the "Ragged Paged Attention" direction in PAPERS.md).
# ---------------------------------------------------------------------------


def _as5d(pool):
    """View a [NB, BS, H, D] pool as [1, NB, BS, H, D] (bitcast — XLA
    aliases the reshape, so in-place semantics survive the wrapper)."""
    return pool if pool.ndim == 5 else pool[None]


def _append_token_kernel(layer_ref, blk_ref, off_ref, k_new_ref, v_new_ref,
                         k_in_ref, v_in_ref, k_out_ref, v_out_ref, sem):
    """Grid (N,): store slot n's new K/V rows at (layer, blk[n], off[n]).
    Integer indexing squeezes the layer/block/token dims on the
    destination and the slot dim on the source, so the DMA moves one
    tile-aligned [Hkv, D] row — only untiled dims are ever sliced."""
    n = pl.program_id(0)
    lyr = layer_ref[0]
    blk, off = blk_ref[n], off_ref[n]
    cp_k = pltpu.make_async_copy(
        k_new_ref.at[n], k_out_ref.at[lyr, blk, off], sem)
    cp_k.start()
    cp_k.wait()
    cp_v = pltpu.make_async_copy(
        v_new_ref.at[n], v_out_ref.at[lyr, blk, off], sem)
    cp_v.start()
    cp_v.wait()


def paged_append_token(k_pool, v_pool, k_new, v_new, blk_phys, offset,
                       layer=0):
    """Append ONE token per slot in place: k_pool[layer, blk_phys[n],
    offset[n]] = k_new[n]. k_pool/v_pool: [L, NB, BS, Hkv, D] or
    [NB, BS, Hkv, D] (aliased — the returned pools reuse the input
    buffers; a 4D pool comes back 4D); k_new/v_new: [N, Hkv, D];
    blk_phys/offset: [N] int32; ``layer`` selects the pool's layer plane
    (traced — the serving engine passes its static layer loop index).
    Slots meant to be inactive should point at the trash block."""
    was4d = k_pool.ndim == 4
    kp, vp = _as5d(k_pool), _as5d(v_pool)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(k_new.shape[0],),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),   # pools stay in HBM
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[pl.BlockSpec(memory_space=pl.ANY),
                   pl.BlockSpec(memory_space=pl.ANY)],
        scratch_shapes=[pltpu.SemaphoreType.DMA(())],
    )
    ko, vo = pl.pallas_call(
        _append_token_kernel,
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct(kp.shape, kp.dtype),
                   jax.ShapeDtypeStruct(vp.shape, vp.dtype)],
        input_output_aliases={5: 0, 6: 1},
        interpret=_interpret(),
    )(jnp.asarray(layer, jnp.int32)[None], blk_phys, offset,
      k_new.astype(kp.dtype), v_new.astype(vp.dtype), kp, vp)
    return (ko[0], vo[0]) if was4d else (ko, vo)


def _append_blocks_kernel(layer_ref, blk_ids_ref, k_blk_ref, v_blk_ref,
                          k_in_ref, v_in_ref, k_out_ref, v_out_ref, sem):
    """Grid (nblk,): store prefill block b at pool block blk_ids[b]
    (HBM-to-HBM DMA of one whole [BS, Hkv, D] block each)."""
    b = pl.program_id(0)
    lyr = layer_ref[0]
    dst = blk_ids_ref[b]
    cp_k = pltpu.make_async_copy(
        k_blk_ref.at[b], k_out_ref.at[lyr, dst], sem)
    cp_k.start()
    cp_k.wait()
    cp_v = pltpu.make_async_copy(
        v_blk_ref.at[b], v_out_ref.at[lyr, dst], sem)
    cp_v.start()
    cp_v.wait()


def paged_append_blocks(k_pool, v_pool, k_blocks, v_blocks, blk_ids,
                        layer=0):
    """Scatter whole prefill blocks into the pool in place (the prefill-side
    analogue of paged_append_token). k_blocks/v_blocks: [nblk, BS, Hkv, D];
    blk_ids: [nblk] int32 destinations (duplicates allowed only for the
    trash block — pad blocks may all point at 0); pools/layer as in
    :func:`paged_append_token`."""
    was4d = k_pool.ndim == 4
    kp, vp = _as5d(k_pool), _as5d(v_pool)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(blk_ids.shape[0],),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[pl.BlockSpec(memory_space=pl.ANY),
                   pl.BlockSpec(memory_space=pl.ANY)],
        scratch_shapes=[pltpu.SemaphoreType.DMA(())],
    )
    ko, vo = pl.pallas_call(
        _append_blocks_kernel,
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct(kp.shape, kp.dtype),
                   jax.ShapeDtypeStruct(vp.shape, vp.dtype)],
        input_output_aliases={4: 0, 5: 1},
        interpret=_interpret(),
    )(jnp.asarray(layer, jnp.int32)[None], blk_ids,
      k_blocks.astype(kp.dtype), v_blocks.astype(vp.dtype), kp, vp)
    return (ko[0], vo[0]) if was4d else (ko, vo)


def _decode_attn_kernel(layer_ref, table_ref, lens_ref, q_ref, k_pool_ref,
                        v_pool_ref, o_ref, kbuf, vbuf, sems, *, block_size,
                        n_kv, max_blocks):
    """Grid (N,): ONE program per slot. All the slot's valid pool blocks
    are DMA'd into VMEM in parallel (start everything, then wait), then
    attention runs single-shot per kv head over the contiguous buffer.
    Few large programs + bulk DMA keep the kernel bandwidth-bound instead
    of program-overhead-bound (a (slot, head, block) grid measured 2 us of
    overhead per tiny program — 20x the DMA time it hid)."""
    n = pl.program_id(0)
    lyr = layer_ref[0]
    ln = lens_ref[n]
    copies = []
    for b in range(max_blocks):
        valid = b * block_size < ln
        blk = table_ref[n, b]

        @pl.when(valid)
        def _(b=b, blk=blk):
            cp_k = pltpu.make_async_copy(
                k_pool_ref.at[lyr, blk],
                kbuf.at[pl.ds(b * block_size, block_size)],
                sems.at[0, b])
            cp_k.start()
            cp_v = pltpu.make_async_copy(
                v_pool_ref.at[lyr, blk],
                vbuf.at[pl.ds(b * block_size, block_size)],
                sems.at[1, b])
            cp_v.start()

        copies.append((valid, blk, b))
    for valid, blk, b in copies:
        @pl.when(valid)
        def _(b=b, blk=blk):
            pltpu.make_async_copy(
                k_pool_ref.at[lyr, blk],
                kbuf.at[pl.ds(b * block_size, block_size)],
                sems.at[0, b]).wait()
            pltpu.make_async_copy(
                v_pool_ref.at[lyr, blk],
                vbuf.at[pl.ds(b * block_size, block_size)],
                sems.at[1, b]).wait()

        # never-copied V blocks hold scratch garbage; the ~0 softmax
        # weights of masked columns still NaN-poison the p@V contraction
        # unless the values are finite, so zero them (VPU-only, no HBM
        # traffic). K needs no fill: masked score columns are rewritten
        # by the -1e30 where() regardless of what the dot produced.
        @pl.when(jnp.logical_not(valid))
        def _(b=b):
            vbuf[b * block_size:(b + 1) * block_size] = jnp.zeros(
                (block_size,) + vbuf.shape[1:], vbuf.dtype)

    S = max_blocks * block_size
    col = jax.lax.broadcasted_iota(jnp.int32, (1, S), 1)
    for h in range(n_kv):                      # static unroll over kv heads
        q = q_ref[0, h]                        # [G, D]
        k = kbuf[:, h]                         # [S, D] (relayout from VMEM)
        v = vbuf[:, h]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)     # [G, S]
        s = s / math.sqrt(q.shape[-1])
        s = jnp.where(col < ln, s, jnp.float32(-1e30))
        m = jnp.max(s, axis=-1, keepdims=True)
        p = jnp.exp(s - m)
        l = jnp.sum(p, axis=-1, keepdims=True)
        o = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)     # [G, D]
        o_ref[0, h] = (o / l).astype(o_ref.dtype)


def paged_decode_attention(q, cache: PagedKVCache, layer=0) -> jax.Array:
    """Pallas decode attention: q [N, Hq, D] -> [N, Hq, D], attending each
    slot's first ``cache.lengths[n]`` pool positions of pool plane
    ``layer`` (pools may be [L, NB, BS, Hkv, D] or 4D). Same contract as
    :func:`paged_attention` (which stays as the XLA reference path and the
    numerics oracle in tests); unlike it, nothing is gathered into a dense
    [N, mb*bs, ...] HBM copy — each slot's blocks stream straight into a
    VMEM buffer, and blocks past the true length are never read."""
    N, Hq, D = q.shape
    kp, vp = _as5d(cache.k_pool), _as5d(cache.v_pool)
    bs, Hkv = kp.shape[2], kp.shape[3]
    mb = cache.block_table.shape[1]
    assert Hq % Hkv == 0, (Hq, Hkv)
    G = Hq // Hkv
    # the two VMEM staging buffers hold the slot's whole context; past
    # ~12 MiB they can't coexist with the rest of the working set in the
    # ~16 MiB VMEM, so long-context pools take the XLA gather path instead
    # of failing with an opaque Mosaic allocation error at serving time
    scratch_bytes = 2 * mb * bs * Hkv * D * kp.dtype.itemsize
    if scratch_bytes > 12 * 1024 * 1024:
        return paged_attention(q, PagedKVCache(
            kp[layer], vp[layer], cache.block_table, cache.lengths))
    qg = q.reshape(N, Hkv, G, D)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(N,),
        in_specs=[
            pl.BlockSpec((1, Hkv, G, D), lambda n, l, t, ln: (n, 0, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),   # pools stay in HBM
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((1, Hkv, G, D),
                               lambda n, l, t, ln: (n, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((mb * bs, Hkv, D), kp.dtype),
            pltpu.VMEM((mb * bs, Hkv, D), vp.dtype),
            pltpu.SemaphoreType.DMA((2, mb)),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_decode_attn_kernel, block_size=bs, n_kv=Hkv,
                          max_blocks=mb),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((N, Hkv, G, D), q.dtype),
        interpret=_interpret(),
    )(jnp.asarray(layer, jnp.int32)[None], cache.block_table,
      cache.lengths, qg, kp, vp)
    return out.reshape(N, Hq, D)


def paged_attention(q, cache: PagedKVCache) -> jax.Array:
    """Decode attention for one query token per sequence.
    q: [B, Hq, D] → [B, Hq, D]. Keys beyond each sequence's length are
    masked. GQA-native: Hq may be G * Hkv (pool heads); query heads are
    grouped against their kv head in the einsum, so the paged pool is never
    materialized repeated (decode is KV-bandwidth-bound — same design as
    models/llama._cached_attention)."""
    B, Hq, D = q.shape
    nb, bs, Hkv = cache.k_pool.shape[0], cache.k_pool.shape[1], \
        cache.k_pool.shape[2]
    mb = cache.block_table.shape[1]
    assert Hq % Hkv == 0, (Hq, Hkv)
    G = Hq // Hkv

    # gather each sequence's blocks: [B, mb, bs, Hkv, D] → [B, mb*bs, Hkv, D]
    k = cache.k_pool[cache.block_table].reshape(B, mb * bs, Hkv, D)
    v = cache.v_pool[cache.block_table].reshape(B, mb * bs, Hkv, D)

    qg = q.reshape(B, Hkv, G, D)
    s = jnp.einsum("bhgd,bkhd->bhgk", qg, k,
                   preferred_element_type=jnp.float32) / math.sqrt(D)
    valid = jnp.arange(mb * bs)[None, :] < cache.lengths[:, None]  # [B, K]
    s = jnp.where(valid[:, None, None, :], s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
    out = jnp.einsum("bhgk,bkhd->bhgd", p, v)
    return out.reshape(B, Hq, D)
