"""Paged KV-cache attention (block tables) — the serving decode path.

Parity: the reference's blocked decode kernel
(phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu, python surface
incubate/nn/functional/block_multihead_attention) whose cache is paged:
physical blocks of block_size tokens + per-sequence block tables. Also the
direction of "Ragged Paged Attention" (PAPERS.md) — TPU-friendly paged decode.

TPU-native: the cache is one [num_blocks, block_size, H, D] pool per k/v;
a block_table [B, max_blocks] maps logical sequence positions to pool
blocks. A decode step gathers each sequence's blocks (static max_blocks →
static shapes), masks beyond the true length, and computes the attention in
f32 — everything jit-able with zero dynamic shapes, so one compiled step
serves any batch composition.
"""
from __future__ import annotations

import math
from typing import NamedTuple

import jax
import jax.numpy as jnp

__all__ = ["PagedKVCache", "paged_cache_init", "paged_append",
           "paged_attention"]


class PagedKVCache(NamedTuple):
    k_pool: jax.Array          # [num_blocks, block_size, H, D]
    v_pool: jax.Array          # [num_blocks, block_size, H, D]
    block_table: jax.Array     # [B, max_blocks] int32 (pool indices)
    lengths: jax.Array         # [B] int32 current token counts


def paged_cache_init(batch: int, num_blocks: int, block_size: int,
                     num_heads: int, head_dim: int, max_blocks: int,
                     dtype=jnp.bfloat16) -> PagedKVCache:
    """Pre-partitioned allocation: sequence b owns blocks
    b*max_blocks..(b+1)*max_blocks-1 by default (callers doing real paging
    can overwrite block_table with any pool mapping)."""
    assert num_blocks >= batch * max_blocks
    table = (jnp.arange(batch * max_blocks, dtype=jnp.int32)
             .reshape(batch, max_blocks))
    return PagedKVCache(
        jnp.zeros((num_blocks, block_size, num_heads, head_dim), dtype),
        jnp.zeros((num_blocks, block_size, num_heads, head_dim), dtype),
        table, jnp.zeros((batch,), jnp.int32))


def paged_append(cache: PagedKVCache, k_new, v_new) -> PagedKVCache:
    """Append ONE token per sequence. k_new/v_new: [B, H, D]."""
    B = k_new.shape[0]
    bs = cache.k_pool.shape[1]
    pos = cache.lengths                               # [B]
    blk_logical = pos // bs
    offset = pos % bs
    blk_physical = jnp.take_along_axis(
        cache.block_table, blk_logical[:, None], axis=1)[:, 0]
    k_pool = cache.k_pool.at[blk_physical, offset].set(
        k_new.astype(cache.k_pool.dtype))
    v_pool = cache.v_pool.at[blk_physical, offset].set(
        v_new.astype(cache.v_pool.dtype))
    return PagedKVCache(k_pool, v_pool, cache.block_table, pos + 1)


def paged_attention(q, cache: PagedKVCache) -> jax.Array:
    """Decode attention for one query token per sequence.
    q: [B, Hq, D] → [B, Hq, D]. Keys beyond each sequence's length are
    masked. GQA-native: Hq may be G * Hkv (pool heads); query heads are
    grouped against their kv head in the einsum, so the paged pool is never
    materialized repeated (decode is KV-bandwidth-bound — same design as
    models/llama._cached_attention)."""
    B, Hq, D = q.shape
    nb, bs, Hkv = cache.k_pool.shape[0], cache.k_pool.shape[1], \
        cache.k_pool.shape[2]
    mb = cache.block_table.shape[1]
    assert Hq % Hkv == 0, (Hq, Hkv)
    G = Hq // Hkv

    # gather each sequence's blocks: [B, mb, bs, Hkv, D] → [B, mb*bs, Hkv, D]
    k = cache.k_pool[cache.block_table].reshape(B, mb * bs, Hkv, D)
    v = cache.v_pool[cache.block_table].reshape(B, mb * bs, Hkv, D)

    qg = q.reshape(B, Hkv, G, D)
    s = jnp.einsum("bhgd,bkhd->bhgk", qg, k,
                   preferred_element_type=jnp.float32) / math.sqrt(D)
    valid = jnp.arange(mb * bs)[None, :] < cache.lengths[:, None]  # [B, K]
    s = jnp.where(valid[:, None, None, :], s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
    out = jnp.einsum("bhgk,bkhd->bhgd", p, v)
    return out.reshape(B, Hq, D)
