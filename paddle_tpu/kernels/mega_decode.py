"""Persistent fused decode megakernel (arXiv 2512.22219 / 2512.12949).

One ``pallas_call`` covers an ENTIRE decode step: the grid iterates the
layer axis (sequential on TPU, so VMEM scratch carries the hidden state
across layers) and each grid step fuses, for its layer,

- the r12 ragged paged-attention block walk (``kernels/paged_attention``'s
  online-softmax / flash-partial machinery, inlined — per-slot true-length
  walks, double-buffered block DMA, int8 KV streamed unconverted with the
  scale folding of ``attn_qk``/``attn_pv``),
- the in-call KV ring write (the decode step's per-layer KV writeback:
  the fresh K/V row is appended to the HBM ring at the step index via the
  ``paged_append_token`` DMA idiom — the ring rides the call as an
  aliased in/out operand, and the end-of-call ring→pool scatter stays the
  XLA code shared verbatim with the ragged/bucketed paths, where the
  valid-count depends on post-sampling ``done`` evolution),
- the full FFN (gate/up/down) plus both RMS norms and RoPE, with every
  weight matrix STREAMED from HBM in double-buffered column tiles — int8
  weights feed the MXU unconverted and their per-output-channel scales
  multiply the f32 accumulator (the ``quant_matmul`` idiom, tiled), so
  VMEM residency is bounded by the tile budget, not the model size.

The ragged path launches ``n_steps × L`` attention kernels per decode
call and round-trips the hidden state through HBM at every layer's XLA
FFN boundary; the mega path launches ``n_steps`` kernels and the hidden
state never leaves VMEM — at batch ≤ 4 decode is launch/latency-bound and
this is the r18 win (serving/engine.py wires it as
``decode_kernel="mega"``, ragged kept as the counted fallback).

Second fusion target (``mega_decode_loop``): the speculative DRAFT wave's
``k`` sequential tiny steps run as ONE persistent launch — the grid grows
a leading step axis, and the greedy epilogue (final norm, a streamed
lm_head with a running tile argmax, the embedding-row DMA for the next
step's input, and the lens/done/budget bookkeeping mirrored from
``serving.engine._paged_decode``) runs in-kernel at the last layer of
each step. Greedy only: the target path keeps sampling (temperature /
top-k / top-p, PRNG) in the XLA epilogue, which is also what keeps the
compile-variant contract at ONE variant per sampling-flag set.

Parity contract (test-enforced): greedy token streams through the mega
path match the ragged path bit-for-bit on decisive-argmax workloads —
the math mirrors ``_paged_decode`` op for op (f32 norm statistics, dtype
cast points, the flash combine over [pool prefix ; raw-dtype ring]), but
matmul tilings differ, so the contract is stream identity, not logit
bitwise equality.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .paged_attention import _interpret
from .quant_matmul import is_quantized_weight, mixed_dot_supported

__all__ = ["mega_decode_step", "mega_decode_loop", "mega_supported",
           "MEGA_VMEM_BUDGET"]

_MATS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")

# same screening precedent as paged_decode_attention's staging-buffer
# gate: past ~12 MiB the working set can't coexist in the ~16 MiB VMEM,
# so the engine counts the fallback instead of hitting an opaque Mosaic
# allocation error at serving time
MEGA_VMEM_BUDGET = 12 * 1024 * 1024
_WTILE_BYTES = 4 * 1024 * 1024          # double-buffered weight tiles
_HTILE_BYTES = 2 * 1024 * 1024          # double-buffered lm_head tiles


def _tile_cols(k: int, itemsize: int, budget: int) -> int:
    """Column-tile width for streaming a [K, M] weight through a
    (2, K, tile) VMEM buffer within ``budget`` bytes: a lane-aligned
    multiple of 128, floored at one lane tile."""
    t = budget // max(1, 2 * k * itemsize)
    return min(2048, max(128, (t // 128) * 128))


def _head_mode(params, config) -> str:
    if getattr(config, "tie_embeddings", False):
        return "tied"
    return "int8" if isinstance(params.get("lm_head"), dict) else "dense"


def mega_supported(params, config, *, n_slots: int, n_steps: int,
                   block_size: int, kv_int8: bool,
                   multi_step: bool = False, mesh=None):
    """(ok, reason) eligibility screen for the mega decode kernel — the
    engine's counted-fallback gate (serving_mega_fallback_total{reason}).
    Estimates the kernel's VMEM scratch envelope (weight tiles, ring
    buffers, walk blocks, hidden-state carry) against the ~12 MiB budget
    the paged_decode_attention screening established. A tp mesh bows
    out with reason "mesh": GSPMD cannot partition the fused single
    launch (the ragged path shard_maps instead), so the engine falls
    back counted rather than raising."""
    if mesh is not None and dict(getattr(mesh, "shape", {})).get("tp", 1) > 1:
        return False, "mesh"
    lay = params["layers"]
    mats = [lay[k] for k in _MATS]
    quant = [is_quantized_weight(m) for m in mats]
    if any(quant) and not all(quant):
        return False, "mixed_weights"
    w_int8 = quant[0]
    wk0 = mats[0]["q"] if w_int8 else mats[0]
    dt = jnp.dtype(config.dtype)
    h = wk0.shape[1]
    Hkv, D = config.num_kv_heads, config.head_dim
    kmax = max((m["q"] if w_int8 else m).shape[1] for m in mats)
    wsize = 1 if w_int8 else dt.itemsize
    tw = _tile_cols(kmax, wsize, _WTILE_BYTES)
    psize = 1 if kv_int8 else dt.itemsize
    bytes_ = 2 * kmax * tw * wsize                       # wbuf
    bytes_ += 2 * n_slots * n_steps * Hkv * D * dt.itemsize   # ring bufs
    bytes_ += 2 * 2 * block_size * Hkv * D * psize       # walk blocks
    if kv_int8:
        bytes_ += 2 * 2 * block_size * Hkv * 4           # walk scales
    bytes_ += 2 * n_slots * h * dt.itemsize              # xs + staging
    if multi_step:
        emb = params["embed"]
        bytes_ += n_slots * h * jnp.dtype(emb.dtype).itemsize   # ebuf
        mode = _head_mode(params, config)
        hsize = (jnp.dtype(emb.dtype).itemsize if mode == "tied"
                 else 1 if mode == "int8"
                 else jnp.dtype(params["lm_head"].dtype).itemsize)
        tv = _tile_cols(h, hsize, _HTILE_BYTES)
        bytes_ += 2 * h * tv * hsize                     # hbuf
    if bytes_ > MEGA_VMEM_BUDGET:
        return False, "vmem"
    return True, "ok"


# ---------------------------------------------------------------------------
# kernel body
# ---------------------------------------------------------------------------
def _mega_kernel(*refs, meta):
    """Grid (S, L) — sequential on TPU, so the VMEM scratch ``xs``
    (hidden state) and the draft bookkeeping persist across grid steps.
    ``meta`` (dict of static shapes/flags) fixes the *refs layout; see
    the builder below for the exact operand order."""
    (n_kv, G, D, bs, MB, S, N, h, L, TW, eps, sm_scale, dt, kv_int8,
     w_int8, multi, head_mode, TV, V, mixed_dot) = (
        meta["n_kv"], meta["G"], meta["D"], meta["bs"], meta["MB"],
        meta["S"], meta["N"], meta["h"], meta["L"], meta["TW"],
        meta["eps"], meta["sm_scale"], meta["dt"], meta["kv_int8"],
        meta["w_int8"], meta["multi"], meta["head_mode"], meta["TV"],
        meta["V"], meta["mixed_dot"])

    it = iter(refs)

    def take(k=1):
        out = [next(it) for _ in range(k)]
        return out[0] if k == 1 else out

    # scalar prefetch (SMEM)
    (t0_ref, table_ref, wl_ref, lens_ref, act_ref, last_ref, rem_ref,
     eos_ref) = take(8)
    # inputs
    x0_ref, freq_ref, an_ref, mn_ref = take(4)
    w_refs = take(7)
    s_refs = take(7) if w_int8 else [None] * 7
    if multi:
        fn_ref = take()
        emb_ref = take()
        head_ref = emb_ref if head_mode == "tied" else take()
        hs_ref = take() if head_mode == "int8" else None
    ring_k_ref, ring_v_ref, k_pool_ref, v_pool_ref = take(4)
    ks_pool_ref, vs_pool_ref = take(2) if kv_int8 else (None, None)
    # outputs
    if multi:
        emit_ref, state_out_ref = take(2)
    else:
        x_out_ref = take()
    # the ring rides the call as aliased in/out ANY operands; ALL
    # in-kernel traffic goes through the OUTPUT refs (on TPU the pair is
    # one buffer; in interpret mode the output copy is seeded from the
    # input and carries this call's earlier writes — the input copy
    # would not)
    rko_ref, rvo_ref = take(2)
    # scratch
    xs, rkb, rvb, kbuf, vbuf = take(5)
    ksbuf, vsbuf = take(2) if kv_int8 else (None, None)
    wbuf = take()
    ring_sem, rout_sem, walk_sem, w_sem = take(4)
    if multi:
        state, ebuf, hbuf, h_sem, e_sem = take(5)

    s_idx = pl.program_id(0)
    lyr = pl.program_id(1)
    t = t0_ref[0] + s_idx

    # -- per-call init: hidden state + (draft) bookkeeping ---------------
    @pl.when((s_idx == 0) & (lyr == 0))
    def _():
        xs[...] = x0_ref[...]
        if multi:
            for c, ref in enumerate((last_ref, lens_ref, None, rem_ref)):
                col = (jnp.zeros((N,), jnp.int32) if ref is None else
                       jnp.stack([ref[i] for i in range(N)]))
                state[:, c:c + 1] = col.reshape(N, 1)

    # the in-call ring plane streams in while the QKV matmuls run
    rin = [pltpu.make_async_copy(rko_ref.at[lyr], rkb, ring_sem.at[0]),
           pltpu.make_async_copy(rvo_ref.at[lyr], rvb, ring_sem.at[1])]
    for cp in rin:
        cp.start()

    x = xs[...]                                          # [N, h] dt

    def rms(xv, w_row):
        xf = xv.astype(jnp.float32)
        var = jnp.mean(xf * xf, axis=-1, keepdims=True)
        return ((xf * jax.lax.rsqrt(var + eps)).astype(xv.dtype)
                * w_row.astype(xv.dtype))

    def stream_mm(xv, w_ref, s_ref):
        """xv [N, K] @ w_ref[lyr] ([K, M], HBM) via double-buffered
        column tiles -> [N, M] f32 (int8: per-output-channel scale
        already applied — the weight_only_matmul idiom, tiled)."""
        K, M = w_ref.shape[1], w_ref.shape[2]
        nt = -(-M // TW)

        def cp(ti):
            a, tw = ti * TW, min(TW, M - ti * TW)
            return pltpu.make_async_copy(
                w_ref.at[lyr, :, a:a + tw],
                wbuf.at[ti % 2, 0:K, 0:tw], w_sem.at[ti % 2])

        cp(0).start()
        outs = []
        for ti in range(nt):
            if ti + 1 < nt:
                cp(ti + 1).start()
            cp(ti).wait()
            a, tw = ti * TW, min(TW, M - ti * TW)
            wt = wbuf[ti % 2, 0:K, 0:tw]
            if w_int8 and not mixed_dot:
                wt = wt.astype(xv.dtype)     # old jax: widen (exact)
            acc = jax.lax.dot_general(
                xv, wt, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            if s_ref is not None:
                acc = acc * s_ref[0, a:a + tw].astype(jnp.float32)[None]
            outs.append(acc)
        return outs[0] if nt == 1 else jnp.concatenate(outs, -1)

    # rope angles from the CURRENT lengths (the draft advances them
    # in-kernel; the target feeds each step's carry via scalar prefetch)
    if multi:
        lens_col = state[:, 1:2].astype(jnp.float32)
    else:
        lens_col = jnp.stack(
            [lens_ref[i] for i in range(N)]).reshape(N, 1) \
            .astype(jnp.float32)
    ang = lens_col * freq_ref[...].reshape(1, D // 2)    # [N, D/2]

    def rope(tv):                                        # [N, H, D]
        d2 = tv.shape[-1] // 2
        t1, t2 = tv[..., :d2], tv[..., d2:]
        cc = jnp.cos(ang)[:, None, :].astype(tv.dtype)
        ss = jnp.sin(ang)[:, None, :].astype(tv.dtype)
        return jnp.concatenate([t1 * cc - t2 * ss, t2 * cc + t1 * ss],
                               -1)

    # -- attention ------------------------------------------------------
    h1 = rms(x, an_ref[0])
    q = stream_mm(h1, w_refs[0], s_refs[0]).astype(dt) \
        .reshape(N, n_kv * G, D)
    kk = stream_mm(h1, w_refs[1], s_refs[1]).astype(dt) \
        .reshape(N, n_kv, D)
    vv = stream_mm(h1, w_refs[2], s_refs[2]).astype(dt) \
        .reshape(N, n_kv, D)
    q, kk = rope(q), rope(kk)
    qg = q.reshape(N, n_kv, G, D)

    # ring write (the per-layer KV writeback): the fresh row lands in
    # the VMEM plane, then DMA-appends to the aliased HBM ring at t —
    # earlier entries (j < t) were already resident for the scores
    for cp in rin:
        cp.wait()
    rkb[:, pl.ds(t, 1)] = kk[:, None]
    rvb[:, pl.ds(t, 1)] = vv[:, None]
    rout = [pltpu.make_async_copy(rkb.at[:, pl.ds(t, 1)],
                                  rko_ref.at[lyr, :, pl.ds(t, 1)],
                                  rout_sem.at[0]),
            pltpu.make_async_copy(rvb.at[:, pl.ds(t, 1)],
                                  rvo_ref.at[lyr, :, pl.ds(t, 1)],
                                  rout_sem.at[1])]
    for cp in rout:
        cp.start()

    # true-length block walk over the pool prefix — the r12 kernel's
    # per-slot program, inlined with fori-carried partials
    def copies(n, b, slot):
        blk = table_ref[n, b]
        cps = [pltpu.make_async_copy(k_pool_ref.at[lyr, blk],
                                     kbuf.at[slot], walk_sem.at[0, slot]),
               pltpu.make_async_copy(v_pool_ref.at[lyr, blk],
                                     vbuf.at[slot], walk_sem.at[1, slot])]
        if kv_int8:
            cps += [pltpu.make_async_copy(
                        ks_pool_ref.at[lyr, blk], ksbuf.at[slot],
                        walk_sem.at[2, slot]),
                    pltpu.make_async_copy(
                        vs_pool_ref.at[lyr, blk], vsbuf.at[slot],
                        walk_sem.at[3, slot])]
        return cps

    m_ps, l_ps, a_ps = [], [], []
    for n in range(N):                        # static slot unroll
        ln = wl_ref[n]
        nblk = jnp.minimum((ln + bs - 1) // bs, MB)
        qn = qg[n]                                       # [Hkv, G, D]

        @pl.when(nblk > 0)
        def _(n=n):
            for cp in copies(n, 0, 0):
                cp.start()

        def walk(b, carry, n=n, ln=ln, nblk=nblk, qn=qn):
            ms_c, ls_c, acc_c = carry
            sl = jax.lax.rem(b, 2)

            @pl.when(b + 1 < nblk)
            def _():
                for cp in copies(n, b + 1, 1 - sl):
                    cp.start()

            for cp in copies(n, b, sl):
                cp.wait()
            col = (jax.lax.broadcasted_iota(jnp.int32, (1, bs), 1)
                   + b * bs)
            live = col < ln
            for kh_i in range(n_kv):
                qh = qn[kh_i]                            # [G, D]
                kh = kbuf[sl][:, kh_i]                   # [bs, D]
                if kv_int8:
                    kh = kh.astype(qh.dtype)
                sc = jax.lax.dot_general(
                    qh, kh, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32) * sm_scale
                if kv_int8:
                    sc = sc * ksbuf[sl][:, kh_i][None, :]
                sc = jnp.where(live, sc, jnp.float32(-1e30))
                m_prev = ms_c[kh_i]
                m_new = jnp.maximum(m_prev, jnp.max(sc, axis=-1))
                alpha = jnp.exp(m_prev - m_new)
                p = jnp.exp(sc - m_new[:, None])
                ls_c = ls_c.at[kh_i].set(
                    ls_c[kh_i] * alpha + jnp.sum(p, axis=-1))
                vh = vbuf[sl][:, kh_i]
                if kv_int8:
                    p = p * vsbuf[sl][:, kh_i][None, :]
                    vh = vh.astype(jnp.float32)
                else:
                    p = p.astype(vh.dtype)
                pv = jax.lax.dot_general(
                    p, vh, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
                acc_c = acc_c.at[kh_i].set(
                    acc_c[kh_i] * alpha[:, None] + pv)
                ms_c = ms_c.at[kh_i].set(m_new)
            return ms_c, ls_c, acc_c

        init = (jnp.full((n_kv, G), -1e30, jnp.float32),
                jnp.zeros((n_kv, G), jnp.float32),
                jnp.zeros((n_kv, G, D), jnp.float32))
        ms_n, ls_n, acc_n = jax.lax.fori_loop(0, nblk, walk, init)
        m_ps.append(ms_n)
        l_ps.append(ls_n)
        a_ps.append(acc_n)
    m_p = jnp.stack(m_ps)                                # [N, Hkv, G]
    l_p = jnp.stack(l_ps)
    acc_p = jnp.stack(a_ps)                              # [N, Hkv, G, D]

    # flash-decoding combine with the raw-dtype ring (j <= t live) —
    # _paged_decode's merge, verbatim
    s_rng = jnp.einsum("nhgd,nshd->nhgs", qg, rkb[...],
                       preferred_element_type=jnp.float32) * sm_scale
    scol = jax.lax.broadcasted_iota(jnp.int32, (1, 1, 1, S), 3)
    s_rng = jnp.where(scol <= t, s_rng, jnp.float32(-1e30))
    m_tot = jnp.maximum(m_p, jnp.max(s_rng, axis=-1))
    corr = jnp.exp(m_p - m_tot)
    p_rng = jnp.exp(s_rng - m_tot[..., None])
    l_tot = l_p * corr + jnp.sum(p_rng, axis=-1)
    acc_tot = (acc_p * corr[..., None]
               + jnp.einsum("nhgs,nshd->nhgd", p_rng, rvb[...],
                            preferred_element_type=jnp.float32))
    att = (acc_tot / l_tot[..., None]).reshape(N, n_kv * G * D) \
        .astype(dt)

    x = x + stream_mm(att, w_refs[3], s_refs[3]).astype(dt)

    # -- FFN ------------------------------------------------------------
    hn = rms(x, mn_ref[0])
    gate = jax.nn.silu(stream_mm(hn, w_refs[4], s_refs[4]).astype(dt))
    up = stream_mm(hn, w_refs[5], s_refs[5]).astype(dt)
    x = x + stream_mm(gate * up, w_refs[6], s_refs[6]).astype(dt)
    xs[...] = x
    if not multi:
        x_out_ref[...] = x

    # -- draft epilogue: greedy argmax + embed DMA + bookkeeping ---------
    if multi:
        @pl.when(lyr == L - 1)
        def _():
            xf = rms(xs[...], fn_ref[0])                 # [N, h]
            nt = -(-V // TV)
            best = jnp.full((N, 1), -jnp.inf, jnp.float32)
            bidx = jnp.zeros((N, 1), jnp.int32)

            def hcp(ti):
                a, tv = ti * TV, min(TV, V - ti * TV)
                if head_mode == "tied":                  # [tv, h] rows
                    return pltpu.make_async_copy(
                        head_ref.at[a:a + tv, :],
                        hbuf.at[ti % 2, 0:tv, :], h_sem.at[ti % 2])
                return pltpu.make_async_copy(            # [h, tv] cols
                    head_ref.at[:, a:a + tv],
                    hbuf.at[ti % 2, :, 0:tv], h_sem.at[ti % 2])

            hcp(0).start()
            for ti in range(nt):
                if ti + 1 < nt:
                    hcp(ti + 1).start()
                hcp(ti).wait()
                a, tv = ti * TV, min(TV, V - ti * TV)
                if head_mode == "tied":
                    wt = hbuf[ti % 2, 0:tv, :].astype(dt)
                    lg = jax.lax.dot_general(
                        xf, wt, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32)
                else:
                    wt = hbuf[ti % 2, :, 0:tv]
                    if head_mode == "int8" and not mixed_dot:
                        wt = wt.astype(dt)
                    lg = jax.lax.dot_general(
                        xf, wt, (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32)
                    if head_mode == "int8":
                        lg = lg * hs_ref[0, a:a + tv] \
                            .astype(jnp.float32)[None]
                # the XLA head matmul rounds through the model dtype
                # before the f32 argmax — mirror for tie exactness
                lg = lg.astype(dt).astype(jnp.float32)
                tmax = jnp.max(lg, axis=-1, keepdims=True)
                tcol = jax.lax.broadcasted_iota(jnp.int32, (N, tv), 1)
                targ = jnp.min(jnp.where(lg >= tmax, tcol, V),
                               axis=-1, keepdims=True) + a
                take_t = tmax > best
                best = jnp.where(take_t, tmax, best)
                bidx = jnp.where(take_t, targ, bidx)

            nxt = bidx                                   # [N, 1] i32
            act_col = jnp.stack(
                [act_ref[i] for i in range(N)]).reshape(N, 1)
            eos_col = jnp.stack(
                [eos_ref[i] for i in range(N)]).reshape(N, 1)
            act = (act_col != 0) & (state[:, 2:3] == 0)
            emit_ref[...] = jnp.where(act, nxt, -1)
            lens2 = state[:, 1:2] + act.astype(jnp.int32)
            rem2 = state[:, 3:4] - act.astype(jnp.int32)
            done2 = ((state[:, 2:3] != 0)
                     | (act & (eos_col >= 0) & (nxt == eos_col))
                     | (act & (rem2 <= 0))).astype(jnp.int32)
            last2 = jnp.where(act, nxt, state[:, 0:1])
            state[:, 0:1] = last2
            state[:, 1:2] = lens2
            state[:, 2:3] = done2
            state[:, 3:4] = rem2
            state_out_ref[...] = jnp.concatenate(
                [last2, lens2, done2, rem2], axis=1)

            # next step's input row: embed[last] — astype(dt) after the
            # gather matches astype-then-gather (same elements)
            def ecp(n):
                return pltpu.make_async_copy(
                    emb_ref.at[last2[n, 0]], ebuf.at[n], e_sem.at[n])
            for n in range(N):
                ecp(n).start()
            for n in range(N):
                ecp(n).wait()
            xs[...] = ebuf[...].astype(dt)

    for cp in rout:
        cp.wait()


# ---------------------------------------------------------------------------
# call builder
# ---------------------------------------------------------------------------
def _mega_call(params, config, *, x0, t0, block_table, walk_lens, lens,
               active, last0, budgets, eos_ids, ring_k, ring_v, k_pool,
               v_pool, ks_pool=None, vs_pool=None, multi_step, n_steps):
    lay = params["layers"]
    mats = [lay[k] for k in _MATS]
    w_int8 = is_quantized_weight(mats[0])
    kv_int8 = k_pool.dtype == jnp.int8
    dt = jnp.dtype(config.dtype)
    N, h = x0.shape
    L = config.num_layers
    Hkv, D = k_pool.shape[3], k_pool.shape[4]
    G = config.num_heads // config.num_kv_heads
    bs = k_pool.shape[2]
    MB = block_table.shape[1]
    S = ring_k.shape[2]
    wdt = jnp.dtype(jnp.int8) if w_int8 else jnp.dtype(mats[0].dtype)
    kmax = max((m["q"] if w_int8 else m).shape[1] for m in mats)
    TW = _tile_cols(kmax, wdt.itemsize, _WTILE_BYTES)
    head_mode = _head_mode(params, config) if multi_step else "none"

    ci = [0]

    def nxt_idx(k=1):
        ci[0] += k
        return ci[0] - k

    nxt_idx(8)                               # scalar prefetch operands
    freq = (config.rope_theta
            ** (-jnp.arange(0, D, 2, jnp.float32) / D)).reshape(1, -1)
    inputs = [x0, freq, lay["attn_norm"], lay["mlp_norm"]]
    in_specs = [
        pl.BlockSpec((N, h), lambda s, l, *_: (0, 0)),
        pl.BlockSpec((1, D // 2), lambda s, l, *_: (0, 0)),
        pl.BlockSpec((1, h), lambda s, l, *_: (l, 0)),
        pl.BlockSpec((1, h), lambda s, l, *_: (l, 0)),
    ]
    nxt_idx(4)
    for m in mats:
        inputs.append(m["q"] if w_int8 else m)
        in_specs.append(pl.BlockSpec(memory_space=pl.ANY))
    nxt_idx(7)
    if w_int8:
        for m in mats:
            mdim = m["q"].shape[2]
            inputs.append(m["s"])
            in_specs.append(pl.BlockSpec(
                (1, mdim), lambda s, l, *_: (l, 0)))
        nxt_idx(7)
    V = TV = 0
    if multi_step:
        emb = params["embed"]
        V = emb.shape[0]
        inputs += [params["final_norm"].reshape(1, h), emb]
        in_specs += [pl.BlockSpec((1, h), lambda s, l, *_: (0, 0)),
                     pl.BlockSpec(memory_space=pl.ANY)]
        nxt_idx(2)
        if head_mode == "tied":
            hdt, TV = jnp.dtype(emb.dtype), _tile_cols(
                h, jnp.dtype(emb.dtype).itemsize, _HTILE_BYTES)
        elif head_mode == "int8":
            hq = params["lm_head"]["q"]
            hdt, TV = jnp.dtype(jnp.int8), _tile_cols(
                h, 1, _HTILE_BYTES)
            inputs.append(hq)
            in_specs.append(pl.BlockSpec(memory_space=pl.ANY))
            nxt_idx()
            inputs.append(params["lm_head"]["s"].reshape(1, V))
            in_specs.append(pl.BlockSpec(
                (1, V), lambda s, l, *_: (0, 0)))
            nxt_idx()
        else:
            hw = params["lm_head"]
            hdt, TV = jnp.dtype(hw.dtype), _tile_cols(
                h, jnp.dtype(hw.dtype).itemsize, _HTILE_BYTES)
            inputs.append(hw)
            in_specs.append(pl.BlockSpec(memory_space=pl.ANY))
            nxt_idx()
    ring_pos = nxt_idx(2)
    inputs += [ring_k, ring_v]
    in_specs += [pl.BlockSpec(memory_space=pl.ANY)] * 2
    inputs += [k_pool, v_pool]
    in_specs += [pl.BlockSpec(memory_space=pl.ANY)] * 2
    nxt_idx(2)
    if kv_int8:
        inputs += [ks_pool.astype(jnp.float32),
                   vs_pool.astype(jnp.float32)]
        in_specs += [pl.BlockSpec(memory_space=pl.ANY)] * 2
        nxt_idx(2)

    if multi_step:
        out_shape = [jax.ShapeDtypeStruct((N, n_steps), jnp.int32),
                     jax.ShapeDtypeStruct((N, 4), jnp.int32),
                     jax.ShapeDtypeStruct(ring_k.shape, ring_k.dtype),
                     jax.ShapeDtypeStruct(ring_v.shape, ring_v.dtype)]
        out_specs = [
            pl.BlockSpec((N, 1), lambda s, l, *_: (0, s)),
            pl.BlockSpec((N, 4), lambda s, l, *_: (0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY)]
        aliases = {ring_pos: 2, ring_pos + 1: 3}
    else:
        out_shape = [jax.ShapeDtypeStruct((N, h), dt),
                     jax.ShapeDtypeStruct(ring_k.shape, ring_k.dtype),
                     jax.ShapeDtypeStruct(ring_v.shape, ring_v.dtype)]
        out_specs = [pl.BlockSpec((N, h), lambda s, l, *_: (0, 0)),
                     pl.BlockSpec(memory_space=pl.ANY),
                     pl.BlockSpec(memory_space=pl.ANY)]
        aliases = {ring_pos: 1, ring_pos + 1: 2}

    scratch = [pltpu.VMEM((N, h), dt),                     # xs
               pltpu.VMEM((N, S, Hkv, D), ring_k.dtype),   # rkb
               pltpu.VMEM((N, S, Hkv, D), ring_v.dtype),   # rvb
               pltpu.VMEM((2, bs, Hkv, D), k_pool.dtype),  # kbuf
               pltpu.VMEM((2, bs, Hkv, D), v_pool.dtype)]  # vbuf
    if kv_int8:
        scratch += [pltpu.VMEM((2, bs, Hkv), jnp.float32),
                    pltpu.VMEM((2, bs, Hkv), jnp.float32)]
    scratch += [pltpu.VMEM((2, kmax, TW), wdt),            # wbuf
                pltpu.SemaphoreType.DMA((2,)),             # ring_sem
                pltpu.SemaphoreType.DMA((2,)),             # rout_sem
                pltpu.SemaphoreType.DMA((4 if kv_int8 else 2, 2)),
                pltpu.SemaphoreType.DMA((2,))]             # w_sem
    if multi_step:
        hshape = (2, TV, h) if head_mode == "tied" else (2, h, TV)
        scratch += [pltpu.VMEM((N, 4), jnp.int32),         # state
                    pltpu.VMEM((N, h), params["embed"].dtype),
                    pltpu.VMEM(hshape, hdt),               # hbuf
                    pltpu.SemaphoreType.DMA((2,)),         # h_sem
                    pltpu.SemaphoreType.DMA((N,))]         # e_sem

    meta = dict(n_kv=Hkv, G=G, D=D, bs=bs, MB=MB, S=S, N=N, h=h, L=L,
                TW=TW, eps=config.rms_eps,
                sm_scale=1.0 / math.sqrt(D), dt=dt, kv_int8=kv_int8,
                w_int8=w_int8, multi=multi_step, head_mode=head_mode,
                TV=TV, V=V, mixed_dot=mixed_dot_supported())
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=8,
        grid=(n_steps if multi_step else 1, L),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=scratch,
    )
    z = jnp.zeros((N,), jnp.int32)
    scalars = [jnp.asarray(t0, jnp.int32).reshape(1),
               block_table.astype(jnp.int32),
               walk_lens.astype(jnp.int32),
               lens.astype(jnp.int32),
               (active.astype(jnp.int32) if active is not None else z),
               (last0.astype(jnp.int32) if last0 is not None else z),
               (budgets.astype(jnp.int32) if budgets is not None else z),
               (eos_ids.astype(jnp.int32) if eos_ids is not None else z)]
    return pl.pallas_call(
        functools.partial(_mega_kernel, meta=meta),
        grid_spec=grid_spec,
        out_shape=out_shape,
        input_output_aliases=aliases,
        interpret=_interpret(),
    )(*scalars, *inputs)


def mega_decode_step(params, config, *, x0, t, block_table, walk_lens,
                     lens, ring_k, ring_v, k_pool, v_pool, ks_pool=None,
                     vs_pool=None):
    """ONE fused decode-step launch (all layers): hidden state x0
    [N, hidden] -> post-layer-stack hidden state [N, hidden], with the
    step's K/V rows appended to the aliased in-call rings at index ``t``.
    The caller owns the epilogue (final norm, lm_head, sampling) and the
    end-of-call ring->pool writeback — shared verbatim with the ragged
    path, which is what the greedy stream-parity tests pin."""
    x, rk, rv = _mega_call(
        params, config, x0=x0, t0=t, block_table=block_table,
        walk_lens=walk_lens, lens=lens, active=None, last0=None,
        budgets=None, eos_ids=None, ring_k=ring_k, ring_v=ring_v,
        k_pool=k_pool, v_pool=v_pool, ks_pool=ks_pool, vs_pool=vs_pool,
        multi_step=False, n_steps=1)
    return x, rk, rv


def mega_decode_loop(params, config, *, x0, n_steps, block_table,
                     walk_lens, lens, active, last0, budgets, eos_ids,
                     ring_k, ring_v, k_pool, v_pool):
    """The speculative-draft fusion target: ``n_steps`` greedy decode
    steps in ONE persistent launch (grid (k, L)) instead of k — the
    greedy epilogue (streamed lm_head + running argmax, embedding-row
    DMA, lens/done/budget updates mirroring ``_paged_decode``'s scan
    body) runs in-kernel at each step's last layer. ``x0`` is
    ``embed[last0]``; ``done0`` must be all-false (the spec wave's
    contract). Returns (emitted [k, N] i32 with -1 padding, last, lens,
    done, budgets, ring_k, ring_v); the caller runs the shared ring ->
    pool writeback."""
    emitted, state, rk, rv = _mega_call(
        params, config, x0=x0, t0=0, block_table=block_table,
        walk_lens=walk_lens, lens=lens, active=active, last0=last0,
        budgets=budgets, eos_ids=eos_ids, ring_k=ring_k, ring_v=ring_v,
        k_pool=k_pool, v_pool=v_pool, multi_step=True, n_steps=n_steps)
    return (emitted.T, state[:, 0], state[:, 1],
            state[:, 2].astype(bool), state[:, 3], rk, rv)
