"""paddle.hub (parity: python/paddle/hub.py) — load models from a local
hubconf.py directory. This environment has no network egress, so only the
local-dir source works; github/gitee sources raise."""
from __future__ import annotations

import importlib.util
import os
import sys

__all__ = ["list", "help", "load", "load_state_dict_from_url"]

_ENTRY = "hubconf.py"


def _load_hubconf(repo_dir):
    path = os.path.join(repo_dir, _ENTRY)
    if not os.path.exists(path):
        raise FileNotFoundError(f"hub: no {_ENTRY} in {repo_dir}")
    spec = importlib.util.spec_from_file_location("paddle_tpu_hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["paddle_tpu_hubconf"] = mod
    spec.loader.exec_module(mod)
    return mod


def _check_source(source):
    if source not in ("local",):
        raise ValueError(
            f"hub source {source!r} unavailable: no network egress in this "
            "environment; clone the repo and use source='local'")


def list(repo_dir, source="local", force_reload=False):  # noqa: A001
    """Entrypoints exposed by the repo's hubconf.py."""
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    return [k for k, v in vars(mod).items()
            if callable(v) and not k.startswith("_")]


def help(repo_dir, model, source="local", force_reload=False):  # noqa: A001
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    if not hasattr(mod, model):
        raise RuntimeError(f"hub: no entrypoint {model!r} in {repo_dir}")
    return getattr(mod, model).__doc__


def load(repo_dir, model, source="local", force_reload=False, **kwargs):
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    if not hasattr(mod, model):
        raise RuntimeError(f"hub: no entrypoint {model!r} in {repo_dir}")
    return getattr(mod, model)(**kwargs)


def load_state_dict_from_url(url, model_dir=None, check_hash=False):
    raise RuntimeError(
        "hub.load_state_dict_from_url: no network egress in this "
        "environment; download the file out-of-band and use paddle.load")
