"""paddle.incubate.autograd parity — forward-mode AD + functional transforms.

Reference: python/paddle/incubate/autograd/ (primapi — jvp/forward_grad,
transpose rules; functional jvp/vjp). TPU-native: jax.jvp/jax.linearize ARE
the forward-mode engine; these wrappers keep the Tensor API surface.
"""
from __future__ import annotations

from typing import Callable, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor

__all__ = ["jvp", "vjp", "forward_grad", "enable_prim", "disable_prim",
           "prim_enabled"]

_prim = False


def enable_prim():
    """parity: paddle.incubate.autograd.enable_prim — in the reference this
    switches autodiff to composite primitives; here jax always differentiates
    through primitives, so this is a recorded no-op."""
    global _prim
    _prim = True


def disable_prim():
    global _prim
    _prim = False


def prim_enabled() -> bool:
    return _prim


def _to_vals(xs):
    seq = xs if isinstance(xs, (list, tuple)) else [xs]
    return [x._value if isinstance(x, Tensor) else jnp.asarray(x) for x in seq]


def _wrap(fn: Callable):
    def pure(*vals):
        outs = fn(*[Tensor(v, stop_gradient=False) for v in vals])
        seq = outs if isinstance(outs, (list, tuple)) else [outs]
        return tuple(o._value if isinstance(o, Tensor) else o for o in seq)
    return pure


def jvp(func: Callable, xs, v=None):
    """Forward-mode: returns (outputs, jvp-products)
    (parity: incubate/autograd/functional.py jvp)."""
    vals = _to_vals(xs)
    tangents = (_to_vals(v) if v is not None
                else [jnp.ones_like(x) for x in vals])
    outs, tangent_out = jax.jvp(_wrap(func), tuple(vals), tuple(tangents))
    mk = lambda t: tuple(Tensor(o) for o in t) if len(t) > 1 else Tensor(t[0])
    return mk(outs), mk(tangent_out)


def vjp(func: Callable, xs, v=None):
    """Reverse-mode pullback (parity: functional.py vjp)."""
    vals = _to_vals(xs)
    outs, pullback = jax.vjp(_wrap(func), *vals)
    cots = (_to_vals(v) if v is not None
            else [jnp.ones_like(o) for o in outs])
    grads = pullback(tuple(cots))
    mk = lambda t: tuple(Tensor(o) for o in t) if len(t) > 1 else Tensor(t[0])
    return mk(outs), mk(grads)


def forward_grad(func: Callable, xs, v=None):
    """Alias of jvp's tangent output (parity: primapi.forward_grad)."""
    _, tang = jvp(func, xs, v)
    return tang


# parity: incubate/autograd functional aliases (Jacobian/Hessian/grad)
from ...autograd import grad  # noqa: E402,F401
from ...autograd import hessian as Hessian  # noqa: E402,F401,N812
from ...autograd import jacobian as Jacobian  # noqa: E402,F401,N812
