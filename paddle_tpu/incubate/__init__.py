"""paddle_tpu.incubate (parity: python/paddle/incubate/)."""
from . import nn  # noqa: F401
