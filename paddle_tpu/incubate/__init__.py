"""paddle_tpu.incubate (parity: python/paddle/incubate/)."""
from . import nn  # noqa: F401

from . import asp  # noqa: F401,E402
from . import autograd  # noqa: F401,E402
from . import optimizer  # noqa: F401,E402
from . import distributed  # noqa: F401,E402

# parity: python/paddle/incubate/__init__.py __all__ — stabilized segment /
# graph ops re-exported from their graduated homes, plus incubate-only ops
from .optimizer import LookAhead, ModelAverage  # noqa: E402,F401
from ..geometric import (  # noqa: E402,F401
    segment_max, segment_mean, segment_min, segment_sum,
)
from ..geometric import (  # noqa: E402
    reindex_graph as graph_reindex,
    sample_neighbors as graph_sample_neighbors,
    send_u_recv as graph_send_recv,
)
from .. import inference  # noqa: E402,F401


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False, name=None):
    """parity: incubate.graph_khop_sampler — multi-hop neighbor sampling:
    one sample_neighbors pass per hop, frontier = previous hop's nodes."""
    import numpy as np

    from ..core.tensor import Tensor
    from ..geometric import sample_neighbors

    frontier = input_nodes
    all_edges = []
    all_counts = []
    for sz in sample_sizes:
        nbrs, cnts = sample_neighbors(row, colptr, frontier, sample_size=sz)
        all_edges.append(nbrs)
        all_counts.append(cnts)
        frontier = nbrs
    import jax.numpy as jnp

    cat = jnp.concatenate([e._value for e in all_edges]) if all_edges else \
        jnp.zeros((0,), jnp.int64)
    cnt = jnp.concatenate([c._value for c in all_counts]) if all_counts \
        else jnp.zeros((0,), jnp.int32)
    return Tensor(cat), Tensor(cnt)


def identity_loss(x, reduction="none"):
    """parity: incubate.identity_loss — marks x as a loss; reduces it."""
    from ..ops import math as _m

    if reduction in (0, "sum"):
        return _m.sum(x)
    if reduction in (1, "mean"):
        return _m.mean(x)
    return x


def softmax_mask_fuse(x, mask, name=None):
    """parity: incubate.softmax_mask_fuse — softmax(x + mask) fused by XLA."""
    import jax

    from ..ops.creation import _t
    from ..ops.dispatch import apply

    return apply("softmax_mask_fuse",
                 lambda v, m: jax.nn.softmax(v + m, axis=-1), _t(x), _t(mask))


def softmax_mask_fuse_upper_triangle(x):
    """parity: incubate.softmax_mask_fuse_upper_triangle — causal-masked
    softmax (upper triangle masked out), fused by XLA."""
    import jax
    import jax.numpy as jnp

    from ..ops.creation import _t
    from ..ops.dispatch import apply

    def fn(v):
        S = v.shape[-1]
        mask = jnp.tril(jnp.ones((S, S), bool))
        return jax.nn.softmax(jnp.where(mask, v, -1e30), axis=-1)

    return apply("softmax_mask_fuse_upper_triangle", fn, _t(x))
