"""paddle_tpu.incubate (parity: python/paddle/incubate/)."""
from . import nn  # noqa: F401

from . import asp  # noqa: F401,E402
from . import autograd  # noqa: F401,E402
from . import optimizer  # noqa: F401,E402
from . import distributed  # noqa: F401,E402
