"""Eager MoE layer API.

Parity: python/paddle/incubate/distributed/models/moe/moe_layer.py:261
MoELayer (+ gates under moe/gate/: NaiveGate, SwitchGate, GShardGate) with
global_scatter/global_gather all-to-all dispatch (:105-188).

TPU-native: the layer wraps the functional GShard einsum dispatch
(models/moe.moe_ffn) — the expert axis carries an 'ep' sharding when a
global mesh provides one, and GSPMD emits the all-to-alls the reference's
global_scatter/global_gather issue explicitly.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ....core.tensor import Tensor
from ....models import moe as _fmoe
from ....nn.layer.layers import Layer
from ....ops.creation import _t
from ....ops.dispatch import apply

__all__ = ["MoELayer", "NaiveGate", "SwitchGate", "GShardGate"]


class _GateBase(Layer):
    def __init__(self, d_model, num_experts, top_k):
        super().__init__()
        self.top_k = top_k
        self.weight = self.create_parameter([d_model, num_experts])

    def forward(self, x):
        logits = x @ Tensor(self.weight._value)
        return logits


class NaiveGate(_GateBase):
    pass


class SwitchGate(_GateBase):
    def __init__(self, d_model, num_experts, top_k=1):
        super().__init__(d_model, num_experts, 1)


class GShardGate(_GateBase):
    def __init__(self, d_model, num_experts, top_k=2):
        super().__init__(d_model, num_experts, 2)


class MoELayer(Layer):
    """parity: MoELayer(gate, experts, ...) — experts is a list of Layers
    with identical structure; their weights are stacked onto a leading
    expert axis for the einsum dispatch."""

    def __init__(self, d_model, d_hidden, num_experts, top_k=2, gate=None,
                 capacity_factor=1.25, group=None, recompute_interval=0,
                 name=None):
        super().__init__()
        self.num_experts = num_experts
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.gate = gate or NaiveGate(d_model, num_experts, top_k)
        self.e_gate = self.create_parameter([num_experts, d_model, d_hidden])
        self.e_up = self.create_parameter([num_experts, d_model, d_hidden])
        self.e_down = self.create_parameter([num_experts, d_hidden, d_model])
        self._cfg = _fmoe.MoEConfig(
            num_experts=num_experts, top_k=top_k, hidden_size=d_model,
            moe_intermediate_size=d_hidden, capacity_factor=capacity_factor)
        self.aux_loss = None

    def forward(self, x):
        shape = x.shape
        d = shape[-1]

        def fn(xv, rw, g, u, dn):
            flat = xv.reshape(-1, d)
            y, aux = _fmoe.moe_ffn(flat, rw, g, u, dn, self._cfg)
            return y.reshape(xv.shape), aux

        out, aux = apply("moe_layer", fn, _t(x), self.gate.weight,
                         self.e_gate, self.e_up, self.e_down)
        self.aux_loss = aux
        return out
