"""Automatic SParsity (ASP) — n:m structured sparsity.

Reference: python/paddle/incubate/asp/ — calculate_density, create_mask
(n:m best-magnitude patterns — utils.py get_mask_1d/2d), prune_model,
decorate (mask-preserving optimizer wrap).

TPU-native note: 2:4 hardware sparse MXU is not a TPU feature; masks here
deliver the *model* capability (train-with-mask, export sparse) with dense
execution — masked weights stay exactly zero through optimizer steps.
"""
from __future__ import annotations

from typing import Dict, List

import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor
from ...nn.layer.layers import Layer

__all__ = ["calculate_density", "create_mask", "prune_model", "decorate",
           "reset_excluded_layers", "set_excluded_layers"]

_excluded: List[str] = []
_masks: Dict[int, np.ndarray] = {}


def calculate_density(x) -> float:
    arr = np.asarray(x._value if isinstance(x, Tensor) else x)
    return float(np.count_nonzero(arr)) / arr.size


def _mask_1d(vec: np.ndarray, n: int, m: int) -> np.ndarray:
    """Keep the n largest-magnitude entries of every m-block."""
    pad = (-len(vec)) % m
    v = np.pad(vec, (0, pad))
    blocks = np.abs(v).reshape(-1, m)
    keep = np.argsort(-blocks, axis=1)[:, :n]
    mask = np.zeros_like(blocks, dtype=bool)
    np.put_along_axis(mask, keep, True, axis=1)
    return mask.reshape(-1)[:len(vec)]


def create_mask(tensor, func_name: str = "mask_1d", n: int = 2, m: int = 4):
    """n:m mask along the last axis (parity: asp/utils.py create_mask)."""
    arr = np.asarray(tensor._value if isinstance(tensor, Tensor) else tensor)
    flat = arr.reshape(-1, arr.shape[-1])
    mask = np.stack([_mask_1d(row, n, m) for row in flat])
    return mask.reshape(arr.shape)


def set_excluded_layers(param_names, main_program=None):
    _excluded.extend(param_names)


def reset_excluded_layers(main_program=None):
    _excluded.clear()


def prune_model(model: Layer, n: int = 2, m: int = 4,
                mask_algo: str = "mask_1d", with_mask: bool = True):
    """Apply n:m masks to every >=2D parameter (conv/linear weights).
    Layers registered via add_supported_layer with a custom pruning_func
    use that function (supported_layer_list.py semantics)."""
    # map param name prefix -> owning layer type name (for the registry)
    owner = {}
    for lname, sub in model.named_sublayers(include_self=True):
        for pname, _ in sub.named_parameters(include_sublayers=False):
            full = f"{lname}.{pname}" if lname else pname
            owner[full] = type(sub).__name__
    pruned = {}
    for name, p in model.named_parameters():
        if p is None or len(p.shape) < 2 or name in _excluded:
            continue
        custom = _custom_pruning.get(owner.get(name, ""))
        if custom is not None:
            import numpy as _np

            mask, new_w = custom(_np.asarray(p._value), n, m, mask_algo,
                                 name)
            p._replace_value(jnp.asarray(new_w, p._value.dtype))
        else:
            mask = create_mask(p, mask_algo, n, m)
            p._replace_value(p._value * jnp.asarray(mask, p._value.dtype))
        if with_mask:
            _masks[id(p)] = mask
        pruned[name] = mask
    return pruned


class _ASPOptimizer:
    """Mask-preserving optimizer wrapper (parity: asp decorate) — re-applies
    masks after every step so pruned weights stay zero."""

    def __init__(self, inner):
        self._inner = inner

    def __getattr__(self, k):
        return getattr(self._inner, k)

    def step(self):
        self._inner.step()
        for p in getattr(self._inner, "_parameter_list", []):
            mask = _masks.get(id(p))
            if mask is not None:
                p._replace_value(p._value * jnp.asarray(mask, p._value.dtype))


def decorate(optimizer):
    return _ASPOptimizer(optimizer)


_custom_pruning = {}


def add_supported_layer(layer, pruning_func=None):
    """parity: asp/supported_layer_list.py:96 add_supported_layer —
    register a layer type (or name) whose weights prune_model should
    sparsify, optionally with a custom pruning function
    fn(weight_np, n, m, mask_algo, param_name) -> (mask, pruned)."""
    key = layer if isinstance(layer, str) else getattr(
        layer, "__name__", type(layer).__name__)
    _custom_pruning[key] = pruning_func


__all__ += ["add_supported_layer"]
