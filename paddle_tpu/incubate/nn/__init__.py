from . import functional  # noqa: F401

from .layer_extras import *  # noqa: E402,F401,F403
