"""paddle.incubate.nn fused Layer classes (parity:
python/paddle/incubate/nn/__init__.py) — stateful wrappers over
incubate.nn.functional; XLA fuses each block."""
from __future__ import annotations

import numpy as np

from ...nn.layer.layers import Layer

__all__ = [
    "FusedLinear", "FusedFeedForward", "FusedMultiHeadAttention",
    "FusedMultiTransformer", "FusedTransformerEncoderLayer",
    "FusedBiasDropoutResidualLayerNorm", "FusedDropoutAdd",
]


class FusedLinear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, transpose_weight=False, name=None):
        super().__init__()
        self._transpose = transpose_weight
        shape = ([out_features, in_features] if transpose_weight
                 else [in_features, out_features])
        self.weight = self.create_parameter(shape, attr=weight_attr)
        self.bias = (self.create_parameter([out_features], attr=bias_attr,
                                           is_bias=True)
                     if bias_attr is not False else None)

    def forward(self, x):
        from . import functional as IF

        return IF.fused_linear(x, self.weight, self.bias,
                               transpose_weight=self._transpose)


class FusedDropoutAdd(Layer):
    def __init__(self, p=0.5, mode="upscale_in_train", name=None):
        super().__init__()
        self._p, self._mode = p, mode

    def forward(self, x, y):
        from . import functional as IF

        return IF.fused_dropout_add(x, y, p=self._p,
                                    training=self.training, mode=self._mode)


class FusedBiasDropoutResidualLayerNorm(Layer):
    def __init__(self, embed_dim, dropout_rate=0.5, weight_attr=None,
                 bias_attr=None, epsilon=1e-5, name=None):
        super().__init__()
        self._p = dropout_rate
        self._eps = epsilon
        self.linear_bias = self.create_parameter([embed_dim], is_bias=True)
        self.ln_scale = self.create_parameter(
            [embed_dim], default_initializer=_ones())
        self.ln_bias = self.create_parameter([embed_dim], is_bias=True)

    def forward(self, x, residual):
        from . import functional as IF

        return IF.fused_bias_dropout_residual_layer_norm(
            x, residual, bias=self.linear_bias, ln_scale=self.ln_scale,
            ln_bias=self.ln_bias, dropout_rate=self._p,
            ln_epsilon=self._eps, training=self.training)


def _ones():
    from ...nn import initializer as I

    return I.Constant(1.0)


class FusedFeedForward(Layer):
    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None,
                 ln2_bias_attr=None, nranks=1, ring_id=-1, name=None):
        super().__init__()
        self._act = activation
        self._p = dropout_rate
        self._act_p = (act_dropout_rate if act_dropout_rate is not None
                       else dropout_rate)
        self._pre = normalize_before
        self._eps = epsilon
        self.linear1_weight = self.create_parameter(
            [d_model, dim_feedforward], attr=linear1_weight_attr)
        self.linear1_bias = self.create_parameter(
            [dim_feedforward], attr=linear1_bias_attr, is_bias=True)
        self.linear2_weight = self.create_parameter(
            [dim_feedforward, d_model], attr=linear2_weight_attr)
        self.linear2_bias = self.create_parameter(
            [d_model], attr=linear2_bias_attr, is_bias=True)
        self.ln1_scale = self.create_parameter(
            [d_model], attr=ln1_scale_attr, default_initializer=_ones())
        self.ln1_bias = self.create_parameter([d_model], attr=ln1_bias_attr,
                                              is_bias=True)
        self.ln2_scale = self.create_parameter(
            [d_model], attr=ln2_scale_attr, default_initializer=_ones())
        self.ln2_bias = self.create_parameter([d_model], attr=ln2_bias_attr,
                                              is_bias=True)

    def forward(self, src):
        from . import functional as IF

        return IF.fused_feedforward(
            src, self.linear1_weight, self.linear2_weight,
            linear1_bias=self.linear1_bias, linear2_bias=self.linear2_bias,
            ln1_scale=self.ln1_scale, ln1_bias=self.ln1_bias,
            ln2_scale=self.ln2_scale, ln2_bias=self.ln2_bias,
            dropout1_rate=self._act_p, dropout2_rate=self._p,
            activation=self._act, ln1_epsilon=self._eps,
            ln2_epsilon=self._eps, pre_layer_norm=self._pre,
            training=self.training)


class FusedMultiHeadAttention(Layer):
    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False,
                 qkv_weight_attr=None, qkv_bias_attr=None,
                 linear_weight_attr=None, linear_bias_attr=None,
                 pre_ln_scale_attr=None, pre_ln_bias_attr=None,
                 ln_scale_attr=None, ln_bias_attr=None, epsilon=1e-5,
                 nranks=1, ring_id=-1, name=None):
        super().__init__()
        self._heads = num_heads
        self._p = dropout_rate
        self._attn_p = attn_dropout_rate
        self._pre = normalize_before
        self._eps = epsilon
        head_dim = embed_dim // num_heads
        self.qkv_weight = self.create_parameter(
            [3, num_heads, head_dim, embed_dim], attr=qkv_weight_attr)
        self.qkv_bias = self.create_parameter(
            [3 * embed_dim], attr=qkv_bias_attr, is_bias=True)
        self.linear_weight = self.create_parameter(
            [embed_dim, embed_dim], attr=linear_weight_attr)
        self.linear_bias = self.create_parameter(
            [embed_dim], attr=linear_bias_attr, is_bias=True)
        self.pre_ln_scale = self.create_parameter(
            [embed_dim], attr=pre_ln_scale_attr, default_initializer=_ones())
        self.pre_ln_bias = self.create_parameter(
            [embed_dim], attr=pre_ln_bias_attr, is_bias=True)
        self.ln_scale = self.create_parameter(
            [embed_dim], attr=ln_scale_attr, default_initializer=_ones())
        self.ln_bias = self.create_parameter([embed_dim], attr=ln_bias_attr,
                                             is_bias=True)

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        from . import functional as IF

        return IF.fused_multi_head_attention(
            query, self.qkv_weight, self.linear_weight,
            pre_layer_norm=self._pre, pre_ln_scale=self.pre_ln_scale,
            pre_ln_bias=self.pre_ln_bias, ln_scale=self.ln_scale,
            ln_bias=self.ln_bias, pre_ln_epsilon=self._eps,
            qkv_bias=self.qkv_bias, linear_bias=self.linear_bias,
            attn_mask=attn_mask, dropout_rate=self._p,
            attn_dropout_rate=self._attn_p, ln_epsilon=self._eps,
            training=self.training)


class FusedTransformerEncoderLayer(Layer):
    """parity: incubate FusedTransformerEncoderLayer — fused attention +
    fused FFN."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate=dropout_rate,
            attn_dropout_rate=(attn_dropout_rate
                               if attn_dropout_rate is not None
                               else dropout_rate),
            normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        out = self.fused_attn(src, attn_mask=src_mask)
        return self.ffn(out)


class FusedMultiTransformer(Layer):
    """parity: incubate FusedMultiTransformer — the serving decoder stack
    over fused_multi_transformer."""

    def __init__(self, embed_dim, num_heads, dim_feedforward,
                 dropout_rate=0.0, activation="gelu",
                 normalize_before=True, num_layers=1, nranks=1, ring_id=-1,
                 name=None, **kwargs):
        super().__init__()
        self._pre = normalize_before
        self._act = activation
        self._p = dropout_rate
        head_dim = embed_dim // num_heads
        mk = self.create_parameter
        self.ln_scales = [mk([embed_dim], default_initializer=_ones())
                          for _ in range(num_layers)]
        self.ln_biases = [mk([embed_dim], is_bias=True)
                          for _ in range(num_layers)]
        self.qkv_weights = [mk([3, num_heads, head_dim, embed_dim])
                            for _ in range(num_layers)]
        self.qkv_biases = [mk([3 * embed_dim], is_bias=True)
                           for _ in range(num_layers)]
        self.linear_weights = [mk([embed_dim, embed_dim])
                               for _ in range(num_layers)]
        self.linear_biases = [mk([embed_dim], is_bias=True)
                              for _ in range(num_layers)]
        self.ffn_ln_scales = [mk([embed_dim], default_initializer=_ones())
                              for _ in range(num_layers)]
        self.ffn_ln_biases = [mk([embed_dim], is_bias=True)
                              for _ in range(num_layers)]
        self.ffn1_weights = [mk([embed_dim, dim_feedforward])
                             for _ in range(num_layers)]
        self.ffn1_biases = [mk([dim_feedforward], is_bias=True)
                            for _ in range(num_layers)]
        self.ffn2_weights = [mk([dim_feedforward, embed_dim])
                             for _ in range(num_layers)]
        self.ffn2_biases = [mk([embed_dim], is_bias=True)
                            for _ in range(num_layers)]
        for group in ("ln_scales", "ln_biases", "qkv_weights", "qkv_biases",
                      "linear_weights", "linear_biases", "ffn_ln_scales",
                      "ffn_ln_biases", "ffn1_weights", "ffn1_biases",
                      "ffn2_weights", "ffn2_biases"):
            for i, p in enumerate(getattr(self, group)):
                self.add_parameter(f"{group}_{i}", p)

    def forward(self, src, attn_mask=None, caches=None, time_step=None,
                **kwargs):
        from . import functional as IF

        return IF.fused_multi_transformer(
            src, self.ln_scales, self.ln_biases, self.qkv_weights,
            self.qkv_biases, self.linear_weights, self.linear_biases,
            self.ffn_ln_scales, self.ffn_ln_biases, self.ffn1_weights,
            self.ffn1_biases, self.ffn2_weights, self.ffn2_biases,
            pre_layer_norm=self._pre, attn_mask=attn_mask,
            dropout_rate=self._p, activation=self._act,
            training=self.training)
