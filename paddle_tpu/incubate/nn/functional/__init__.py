"""Fused op surface (parity: python/paddle/incubate/nn/functional/ —
fused_rms_norm, fused_rotary_position_embedding, swiglu, fused_matmul_bias,
fused_moe, masked/block multihead attention).

On TPU "fused" means XLA fusion or a Pallas kernel — the API contract is what
matters; implementations route to the ops/kernels layers.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ....ops.creation import _t
from ....ops.dispatch import apply


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, bias=None, residual=None,
                   quant_scale=-1, **kw):
    from ....nn import functional as F

    def fn(v, w, *rest):
        i = 0
        res = None
        b = None
        if residual is not None:
            res = rest[i]
            i += 1
        if bias is not None:
            b = rest[i]
        if b is not None:
            v = v + b
        if res is not None:
            v = v + res
        var = jnp.mean(jnp.square(v.astype(jnp.float32)), axis=-1, keepdims=True)
        out = (v.astype(jnp.float32) * jax.lax.rsqrt(var + epsilon)).astype(v.dtype)
        out = out * w
        if norm_bias is not None:
            out = out + norm_bias._value
        return out

    args = [_t(x), _t(norm_weight)]
    if residual is not None:
        args.append(_t(residual))
    if bias is not None:
        args.append(_t(bias))
    return apply("fused_rms_norm", fn, *args)


def fused_layer_norm(x, norm_weight, norm_bias, epsilon=1e-5,
                     begin_norm_axis=-1, **kw):
    from ....nn import functional as F

    return F.layer_norm(x, [x.shape[-1]], norm_weight, norm_bias, epsilon)


def swiglu(x, y=None, name=None):
    """parity: incubate/nn/functional/swiglu — silu(x) * y (or split x)."""
    if y is None:
        def fn(v):
            a, b = jnp.split(v, 2, axis=-1)
            return jax.nn.silu(a) * b

        return apply("swiglu", fn, _t(x))
    return apply("swiglu", lambda a, b: jax.nn.silu(a) * b, _t(x), _t(y))


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style=True,
                                    time_major=False, rotary_emb_base=10000.0):
    """parity: incubate/nn/functional/fused_rotary_position_embedding.
    Inputs [batch, seq, heads, head_dim]."""

    def rope_one(x_val, sin_val, cos_val):
        if use_neox_rotary_style:
            x1, x2 = jnp.split(x_val, 2, axis=-1)
            rotated = jnp.concatenate([-x2, x1], axis=-1)
            return x_val * cos_val + rotated * sin_val
        x1 = x_val[..., 0::2]
        x2 = x_val[..., 1::2]
        rot = jnp.stack([-x2, x1], axis=-1).reshape(x_val.shape)
        return x_val * cos_val + rot * sin_val

    def make_sincos(x_val):
        seq = x_val.shape[1]
        dim = x_val.shape[-1]
        inv = 1.0 / (rotary_emb_base ** (jnp.arange(0, dim, 2,
                                                    dtype=jnp.float32) / dim))
        t = jnp.arange(seq, dtype=jnp.float32)
        freqs = jnp.outer(t, inv)
        emb = jnp.concatenate([freqs, freqs], axis=-1)
        return (jnp.sin(emb)[None, :, None, :].astype(x_val.dtype),
                jnp.cos(emb)[None, :, None, :].astype(x_val.dtype))

    outs = []
    for t_in in (q, k, v):
        if t_in is None:
            outs.append(None)
            continue
        if sin is not None and cos is not None:
            def fn(v_, s_, c_):
                s_ = s_.reshape(1, s_.shape[-2], 1, s_.shape[-1]) if s_.ndim != 4 else s_
                c_ = c_.reshape(1, c_.shape[-2], 1, c_.shape[-1]) if c_.ndim != 4 else c_
                return rope_one(v_, s_.astype(v_.dtype), c_.astype(v_.dtype))

            outs.append(apply("fused_rope", fn, _t(t_in), _t(sin), _t(cos)))
        else:
            def fn(v_):
                s_, c_ = make_sincos(v_)
                return rope_one(v_, s_, c_)

            outs.append(apply("fused_rope", fn, _t(t_in)))
    return tuple(outs)


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False,
                      name=None):
    from ....ops.linalg import matmul

    out = matmul(x, y, transpose_x, transpose_y)
    if bias is not None:
        out = out + bias
    return out


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    return fused_matmul_bias(x, weight, bias, transpose_y=transpose_weight)


def fused_linear_activation(x, y, bias=None, trans_x=False, trans_y=False,
                            activation="gelu"):
    from ....nn import functional as F

    out = fused_matmul_bias(x, y, bias, trans_x, trans_y)
    return getattr(F, activation)(out)


def fused_bias_dropout_residual_layer_norm(x, residual, bias=None,
                                           ln_scale=None, ln_bias=None,
                                           dropout_rate=0.0, ln_epsilon=1e-5,
                                           training=True, **kw):
    from ....nn import functional as F

    out = x if bias is None else x + bias
    if dropout_rate:
        out = F.dropout(out, dropout_rate, training=training)
    out = out + residual
    return F.layer_norm(out, [out.shape[-1]], ln_scale, ln_bias, ln_epsilon)


def fused_dropout_add(x, y, p=0.0, training=True, mode="upscale_in_train",
                      name=None):
    from ....nn import functional as F

    return F.dropout(x, p, training=training, mode=mode) + y
