"""Fused op surface (parity: python/paddle/incubate/nn/functional/ —
fused_rms_norm, fused_rotary_position_embedding, swiglu, fused_matmul_bias,
fused_moe, masked/block multihead attention).

On TPU "fused" means XLA fusion or a Pallas kernel — the API contract is what
matters; implementations route to the ops/kernels layers.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ....ops.creation import _t
from ....ops.dispatch import apply


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, bias=None, residual=None,
                   quant_scale=-1, **kw):
    from ....nn import functional as F

    def fn(v, w, *rest):
        i = 0
        res = None
        b = None
        if residual is not None:
            res = rest[i]
            i += 1
        if bias is not None:
            b = rest[i]
        if b is not None:
            v = v + b
        if res is not None:
            v = v + res
        var = jnp.mean(jnp.square(v.astype(jnp.float32)), axis=-1, keepdims=True)
        out = (v.astype(jnp.float32) * jax.lax.rsqrt(var + epsilon)).astype(v.dtype)
        out = out * w
        if norm_bias is not None:
            out = out + norm_bias._value
        return out

    args = [_t(x), _t(norm_weight)]
    if residual is not None:
        args.append(_t(residual))
    if bias is not None:
        args.append(_t(bias))
    return apply("fused_rms_norm", fn, *args)


def fused_layer_norm(x, norm_weight, norm_bias, epsilon=1e-5,
                     begin_norm_axis=-1, **kw):
    from ....nn import functional as F

    return F.layer_norm(x, [x.shape[-1]], norm_weight, norm_bias, epsilon)


def swiglu(x, y=None, name=None):
    """parity: incubate/nn/functional/swiglu — silu(x) * y (or split x)."""
    if y is None:
        def fn(v):
            a, b = jnp.split(v, 2, axis=-1)
            return jax.nn.silu(a) * b

        return apply("swiglu", fn, _t(x))
    return apply("swiglu", lambda a, b: jax.nn.silu(a) * b, _t(x), _t(y))


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style=True,
                                    time_major=False, rotary_emb_base=10000.0):
    """parity: incubate/nn/functional/fused_rotary_position_embedding.
    Inputs [batch, seq, heads, head_dim]."""

    def rope_one(x_val, sin_val, cos_val):
        if use_neox_rotary_style:
            x1, x2 = jnp.split(x_val, 2, axis=-1)
            rotated = jnp.concatenate([-x2, x1], axis=-1)
            return x_val * cos_val + rotated * sin_val
        x1 = x_val[..., 0::2]
        x2 = x_val[..., 1::2]
        rot = jnp.stack([-x2, x1], axis=-1).reshape(x_val.shape)
        return x_val * cos_val + rot * sin_val

    def make_sincos(x_val):
        seq = x_val.shape[1]
        dim = x_val.shape[-1]
        inv = 1.0 / (rotary_emb_base ** (jnp.arange(0, dim, 2,
                                                    dtype=jnp.float32) / dim))
        t = jnp.arange(seq, dtype=jnp.float32)
        freqs = jnp.outer(t, inv)
        emb = jnp.concatenate([freqs, freqs], axis=-1)
        return (jnp.sin(emb)[None, :, None, :].astype(x_val.dtype),
                jnp.cos(emb)[None, :, None, :].astype(x_val.dtype))

    outs = []
    for t_in in (q, k, v):
        if t_in is None:
            outs.append(None)
            continue
        if sin is not None and cos is not None:
            def fn(v_, s_, c_):
                s_ = s_.reshape(1, s_.shape[-2], 1, s_.shape[-1]) if s_.ndim != 4 else s_
                c_ = c_.reshape(1, c_.shape[-2], 1, c_.shape[-1]) if c_.ndim != 4 else c_
                return rope_one(v_, s_.astype(v_.dtype), c_.astype(v_.dtype))

            outs.append(apply("fused_rope", fn, _t(t_in), _t(sin), _t(cos)))
        else:
            def fn(v_):
                s_, c_ = make_sincos(v_)
                return rope_one(v_, s_, c_)

            outs.append(apply("fused_rope", fn, _t(t_in)))
    return tuple(outs)


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False,
                      name=None):
    from ....ops.linalg import matmul

    out = matmul(x, y, transpose_x, transpose_y)
    if bias is not None:
        out = out + bias
    return out


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    return fused_matmul_bias(x, weight, bias, transpose_y=transpose_weight)


def fused_linear_activation(x, y, bias=None, trans_x=False, trans_y=False,
                            activation="gelu"):
    from ....nn import functional as F

    out = fused_matmul_bias(x, y, bias, trans_x, trans_y)
    return getattr(F, activation)(out)


def fused_bias_dropout_residual_layer_norm(x, residual, bias=None,
                                           ln_scale=None, ln_bias=None,
                                           dropout_rate=0.0, ln_epsilon=1e-5,
                                           training=True, **kw):
    from ....nn import functional as F

    out = x if bias is None else x + bias
    if dropout_rate:
        out = F.dropout(out, dropout_rate, training=training)
    out = out + residual
    return F.layer_norm(out, [out.shape[-1]], ln_scale, ln_bias, ln_epsilon)


def fused_dropout_add(x, y, p=0.0, training=True, mode="upscale_in_train",
                      name=None):
    from ....nn import functional as F

    return F.dropout(x, p, training=training, mode=mode) + y


def masked_multihead_attention(x, cache_kv=None, bias=None, src_mask=None,
                               sequence_lengths=None, rotary_tensor=None,
                               beam_cache_offset=None, qkv_out_scale=None,
                               out_shift=None, out_smooth=None, seq_len=1,
                               rotary_emb_dims=0, use_neox_rotary_style=False,
                               compute_dtype="default", **kw):
    """Single-token decode attention over a KV cache (parity:
    incubate/nn/functional/masked_multihead_attention — the reference's
    fused decode kernel). x: [B, 3*H*D] packed qkv for ONE step;
    cache_kv: [2, B, H, max_len, D]; sequence_lengths: [B] current lengths.
    Returns (out [B, H*D], updated cache_kv)."""
    import jax
    import jax.numpy as jnp
    import math as _math

    from ....core.tensor import Tensor
    from ....ops.creation import _t
    from ....ops.dispatch import apply

    def fn(xv, cache, seqlens):
        B = xv.shape[0]
        _, _, H, max_len, D = cache.shape
        qkv = xv.reshape(B, 3, H, D)
        q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]
        pos = seqlens.astype(jnp.int32)                      # [B]
        bidx = jnp.arange(B)
        kc = cache[0].at[bidx, :, pos].set(k)                # [B,H,max,D]
        vc = cache[1].at[bidx, :, pos].set(v)
        s = jnp.einsum("bhd,bhkd->bhk", q, kc,
                       preferred_element_type=jnp.float32)
        s = s / _math.sqrt(D)
        mask = jnp.arange(max_len)[None, None, :] <= pos[:, None, None]
        s = jnp.where(mask, s, -1e30)
        p = jax.nn.softmax(s, -1).astype(vc.dtype)
        out = jnp.einsum("bhk,bhkd->bhd", p, vc)
        return out.reshape(B, H * D), jnp.stack([kc, vc])

    seqlens = sequence_lengths if sequence_lengths is not None else None
    out, new_cache = apply("masked_multihead_attention", fn, _t(x),
                           _t(cache_kv), _t(seqlens))
    return out, new_cache


def block_multihead_attention(qkv, key_cache, value_cache, seq_lens_encoder,
                              seq_lens_decoder, seq_lens_this_time,
                              padding_offsets=None, cum_offsets=None,
                              cu_seqlens_q=None, cu_seqlens_k=None,
                              block_tables=None, max_seq_len=None, **kw):
    """Blocked KV-cache attention for batched decode (parity:
    incubate/nn/functional/block_multihead_attention — the reference's paged
    decode kernel over cutlass). Simplified contract: qkv [B, 3, H, D] one
    step per sequence; caches [B, H, max_len, D]; seq_lens_decoder [B]."""
    import jax
    import jax.numpy as jnp
    import math as _math

    from ....ops.creation import _t
    from ....ops.dispatch import apply

    def fn(qkvv, kc, vc, lens):
        B, _, H, D = qkvv.shape
        q, k, v = qkvv[:, 0], qkvv[:, 1], qkvv[:, 2]
        pos = lens.astype(jnp.int32)
        bidx = jnp.arange(B)
        kc = kc.at[bidx, :, pos].set(k)
        vc = vc.at[bidx, :, pos].set(v)
        s = jnp.einsum("bhd,bhkd->bhk", q, kc,
                       preferred_element_type=jnp.float32) / _math.sqrt(D)
        mask = jnp.arange(kc.shape[2])[None, None, :] <= pos[:, None, None]
        s = jnp.where(mask, s, -1e30)
        p = jax.nn.softmax(s, -1).astype(vc.dtype)
        out = jnp.einsum("bhk,bhkd->bhd", p, vc)
        return out, kc, vc

    return apply("block_multihead_attention", fn, _t(qkv), _t(key_cache),
                 _t(value_cache), _t(seq_lens_decoder))


def fused_moe(x, gate_weight, ffn1_weight, ffn2_weight, ffn1_bias=None,
              ffn2_bias=None, quant_method="None", moe_topk=2, norm_topk_prob=True,
              **kw):
    """Fused MoE FFN (parity: incubate/nn/functional/fused_moe.py:75 over the
    cutlass grouped-GEMM kernels). x: [T, h]; gate_weight [h, E];
    ffn1_weight [E, h, 2f] (gate+up packed) or [E, h, f]; ffn2 [E, f, h]."""
    import jax
    import jax.numpy as jnp

    from ....core.tensor import Tensor
    from ....models.moe import MoEConfig, moe_ffn
    from ....ops.creation import _t
    from ....ops.dispatch import apply

    def fn(xv, gw, w1, w2):
        E = gw.shape[-1]
        f2 = w1.shape[-1]
        if f2 % 2 == 0:
            gate_w, up_w = w1[..., :f2 // 2], w1[..., f2 // 2:]
        else:
            gate_w = up_w = w1
        cfg = MoEConfig(num_experts=E, top_k=moe_topk,
                        hidden_size=xv.shape[-1],
                        moe_intermediate_size=w2.shape[1],
                        capacity_factor=float(E))
        y, _aux = moe_ffn(xv, gw, gate_w, up_w, w2, cfg)
        return y

    return apply("fused_moe", fn, _t(x), _t(gate_weight), _t(ffn1_weight),
                 _t(ffn2_weight))
